"""Analyzer core: findings, suppression parsing, baseline handling and
the run driver. Stdlib-only (ast/json/re) so tools/contract_check.py
stays runnable anywhere the package imports.

Suppression grammar (one per line, same line as the finding or a
comment line directly above it)::

    # contract: ok <rule-id> — <why>

The justification is REQUIRED: an empty one still suppresses the base
finding but raises a ``suppression-empty`` finding of its own, so CI
fails until the why is written (ISSUE 12: reviewer vigilance becomes a
machine check, including on the escape hatch).

Baseline file (tools/contract_baseline.json): accepted pre-existing
findings, fingerprinted WITHOUT line numbers so ordinary edits don't
churn it::

    {"version": 1,
     "findings": {"<rule>::<file>::<scope>::<key>":
                  {"count": 2, "why": "..."}}}

Every entry carries a justification too (``baseline-invalid`` fires on
an empty one), and a stale entry — a fingerprint the analyzer no longer
produces — is reported so fixes SHRINK the file instead of leaving
dead weight.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*contract:\s*ok\s+([A-Za-z0-9_.-]+)\s*(?:[—–-]+\s*(.*?))?\s*$")

#: the why a `--baseline write` stamps on entries it adds — the tier-1
#: baseline lint rejects it, so an auto-written baseline cannot land
#: without a human justification per entry
UNREVIEWED_WHY = "UNREVIEWED — justify before commit"


class Finding:
    """One rule violation. ``fingerprint`` excludes the line number on
    purpose: baselines must survive unrelated edits to the file."""

    __slots__ = ("rule", "path", "line", "scope", "key", "message")

    def __init__(self, rule: str, path: str, line: int, scope: str,
                 key: str, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.scope = scope
        self.key = key
        self.message = message

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.scope}::{self.key}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "scope": self.scope, "key": self.key,
                "message": self.message,
                "fingerprint": self.fingerprint}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.scope}] {self.message}")

    def __repr__(self) -> str:  # debugging/pytest output
        return f"<Finding {self.render()}>"


class ModuleInfo:
    """One parsed source file plus its suppression table."""

    def __init__(self, abspath: Path, relpath: str):
        self.abspath = abspath
        self.path = relpath  # repo-relative posix path — the id rules use
        self.source = abspath.read_text()
        self.tree = ast.parse(self.source, filename=str(abspath))
        self.lines = self.source.splitlines()
        #: lineno -> list of (rule_id, why) suppressions on that line
        self.suppressions: Dict[int, List[Tuple[str, str]]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressions.setdefault(i, []).append(
                    (m.group(1), (m.group(2) or "").strip()))

    def _comment_only(self, lineno: int) -> bool:
        if not (1 <= lineno <= len(self.lines)):
            return False
        return self.lines[lineno - 1].lstrip().startswith("#")

    def suppression_for(self, rule: str, line: int
                        ) -> Optional[Tuple[str, str, int]]:
        """The (rule, why, lineno) suppression covering a finding of
        `rule` anchored at `line`: same line, or a contiguous block of
        comment lines directly above the statement."""
        candidates = [line]
        up = line - 1
        while self._comment_only(up):
            candidates.append(up)
            up -= 1
        for ln in candidates:
            for rid, why in self.suppressions.get(ln, ()):
                if rid == rule:
                    return (rid, why, ln)
        return None


class AnalysisReport:
    """Everything one run produced, pre-baseline."""

    def __init__(self, root: Path):
        self.root = root
        self.findings: List[Finding] = []
        self.suppressed: List[Tuple[Finding, str, int]] = []  # (f, why, line)
        self.files_scanned = 0

    def sorted_findings(self) -> List[Finding]:
        return sorted(self.findings,
                      key=lambda f: (f.path, f.line, f.rule, f.key))


def _meta_suppression_findings(module: ModuleInfo,
                               known_rules: Iterable[str]) -> List[Finding]:
    """`suppression-empty` for justification-less suppressions and for
    suppressions naming a rule that does not exist (a typo'd id would
    otherwise silently fail to suppress AND never be noticed)."""
    out = []
    known = set(known_rules)
    for lineno, entries in sorted(module.suppressions.items()):
        for rid, why in entries:
            if not why:
                out.append(Finding(
                    "suppression-empty", module.path, lineno,
                    "<suppression>", rid,
                    f"suppression for {rid!r} has no justification — "
                    "write the why after the dash"))
            elif rid not in known:
                out.append(Finding(
                    "suppression-empty", module.path, lineno,
                    "<suppression>", rid,
                    f"suppression names unknown rule {rid!r} "
                    "(typo? it will never match a finding)"))
    return out


def analyze_paths(paths: Iterable[Path], root: Path,
                  registry=None, rules: Optional[Iterable[str]] = None
                  ) -> AnalysisReport:
    """Run every (selected) rule over `paths`. `root` anchors the
    repo-relative paths used in fingerprints; `registry` defaults to
    the engine's DEFAULT_REGISTRY."""
    from . import registry as reg_mod
    from .callgraph import ModuleGraph
    reg = registry if registry is not None else reg_mod.DEFAULT_REGISTRY
    selected = set(rules) if rules is not None else None
    report = AnalysisReport(root)
    modules: List[ModuleInfo] = []
    for p in paths:
        p = Path(p)
        try:
            rel = p.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = p.name
        modules.append(ModuleInfo(p, rel))
    report.files_scanned = len(modules)

    for module in modules:
        graph = ModuleGraph(module.tree)
        raw: List[Finding] = []
        for rule_id, meta in reg_mod.RULES.items():
            if meta.checker is None:
                continue  # meta rules (suppression/baseline lints)
            if selected is not None and rule_id not in selected:
                continue
            raw.extend(meta.checker(module, graph, reg))
        raw.extend(_meta_suppression_findings(module, reg_mod.RULES))
        for f in raw:
            if f.rule == "suppression-empty":
                report.findings.append(f)  # never suppressible
                continue
            sup = module.suppression_for(f.rule, f.line)
            if sup is not None:
                report.suppressed.append((f, sup[1], sup[2]))
            else:
                report.findings.append(f)
    return report


# -- baseline ----------------------------------------------------------------

def load_baseline(path: Path) -> Dict[str, Dict[str, object]]:
    if not Path(path).exists():
        return {}
    text = Path(path).read_text()
    if not text.strip():  # /dev/null or a truncated file = no baseline
        return {}
    data = json.loads(text)
    return dict(data.get("findings", {}))


def write_baseline(path: Path, findings: Iterable[Finding],
                   previous: Optional[Dict[str, Dict[str, object]]] = None
                   ) -> Dict[str, Dict[str, object]]:
    """`--baseline write`: accept the current findings. Existing
    justifications are preserved; NEW entries get the UNREVIEWED stamp
    the baseline lint rejects, so a human must justify each before it
    can land."""
    prev = previous if previous is not None else {}
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    entries = {}
    for fp in sorted(counts):
        why = prev.get(fp, {}).get("why", UNREVIEWED_WHY)
        entries[fp] = {"count": counts[fp], "why": why}
    Path(path).write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=1, sort_keys=True)
        + "\n")
    return entries


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, Dict[str, object]]
                   ) -> Tuple[List[Finding], List[str], List[Finding]]:
    """Returns (new_findings, stale_fingerprints, baseline_lint).

    Per fingerprint, up to `count` occurrences are absorbed; the rest
    are new. Baseline slots the run did not consume are stale — an
    entry whose findings were (even partially) fixed must shrink its
    count or disappear. Entries with a missing/empty/UNREVIEWED why or
    a non-positive count come back as `baseline-invalid` findings."""
    remaining = {fp: int(e.get("count", 0)) for fp, e in baseline.items()}
    new: List[Finding] = []
    for f in findings:
        if remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
        else:
            new.append(f)
    # ANY unconsumed slot is stale — a count=2 entry with one of its
    # findings fixed must shrink to 1, or the leftover slot would
    # silently absorb a future regression of the same fingerprint
    stale = sorted(fp for fp, n in remaining.items() if n > 0)
    lint: List[Finding] = []
    for fp, entry in sorted(baseline.items()):
        why = str(entry.get("why", "")).strip()
        if not why or why == UNREVIEWED_WHY:
            lint.append(Finding(
                "baseline-invalid", "tools/contract_baseline.json", 1,
                "<baseline>", fp,
                f"baseline entry {fp} lacks a justification"))
        if int(entry.get("count", 0)) < 1:
            lint.append(Finding(
                "baseline-invalid", "tools/contract_baseline.json", 1,
                "<baseline>", fp,
                f"baseline entry {fp} has a non-positive count"))
    return new, stale, lint
