"""Per-module call-graph resolution shared by the contract rules.

The rules are deliberately MODULE-level (the ISSUE 12 scope): a walk
follows calls to functions and methods defined in the same file —
`self.foo()`, bare `foo()`, nested defs — which is exactly where the
engine's lock-hold regions and producer-thread entry points live.
Cross-module effects (e.g. `upload_leaves` doing a device transfer) are
declared data in the registry instead of being chased interprocedurally.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

FuncKey = Tuple[Optional[str], str]  # (class name or None, function name)


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — diagnostics only
        return "<expr>"


class ModuleGraph:
    """Index of every function/method in one module plus call
    resolution. Nested defs are indexed by bare name as a fallback so
    `pool.submit(worker, ...)` can resolve a closure target."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.functions: Dict[FuncKey, ast.FunctionDef] = {}
        self.by_name: Dict[str, ast.FunctionDef] = {}
        self.jnp_aliases = _numpy_jax_aliases(tree)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[(None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.functions[(node.name, sub.name)] = sub
        # bare-name fallback index (includes nested defs)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.by_name.setdefault(node.name, node)

    def resolve_call(self, call: ast.Call, current_class: Optional[str]
                     ) -> Optional[Tuple[FuncKey, ast.FunctionDef]]:
        """Resolve a call to a module-local target, or None."""
        func = call.func
        if isinstance(func, ast.Name):
            return self.resolve_name(func.id, current_class)
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id in ("self", "cls") and current_class:
            key = (current_class, func.attr)
            if key in self.functions:
                return key, self.functions[key]
        return None

    def resolve_name(self, name: str, current_class: Optional[str]
                     ) -> Optional[Tuple[FuncKey, ast.FunctionDef]]:
        if (None, name) in self.functions:
            return (None, name), self.functions[(None, name)]
        if current_class and (current_class, name) in self.functions:
            return (current_class, name), self.functions[(current_class,
                                                          name)]
        fn = self.by_name.get(name)
        if fn is not None:
            return (current_class, name), fn
        return None

    def scopes(self) -> Iterator[Tuple[str, Optional[str],
                                       ast.FunctionDef]]:
        """(qualname, class name, node) for every indexed function."""
        for (cls, name), node in self.functions.items():
            qual = f"{cls}.{name}" if cls else name
            yield qual, cls, node


def qualname(key: FuncKey) -> str:
    cls, name = key
    return f"{cls}.{name}" if cls else name


def _numpy_jax_aliases(tree: ast.Module) -> List[str]:
    """Names `jax.numpy` is imported under in this module (usually
    ['jnp']) — the trace-purity rules match against these."""
    aliases = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    aliases.append(a.asname or "jax.numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(
                    a.name == "numpy" for a in node.names):
                for a in node.names:
                    if a.name == "numpy":
                        aliases.append(a.asname or "numpy")
    return aliases


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def attr_root(node: ast.AST) -> Optional[str]:
    """The leftmost Name of an attribute chain (`jnp.lax.foo` -> 'jnp')."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None
