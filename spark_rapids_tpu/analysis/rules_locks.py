"""Lock-discipline rules (ISSUE 12 tentpole rule family 1).

One shared walk per module: every `with <registered-lock>:` region is
entered, module-local calls are followed (the engine's `_locked` helper
convention lives in-file), and three findings fall out:

* ``lock-blocking-call`` — a blocking call (IO, wait/join/sleep, queue
  get/put, event emit, device transfer, budget reserve-with-drain)
  reachable while the lock is held;
* ``lock-reacquire``     — re-acquisition of a non-reentrant lock along
  the path (the PR 5 heartbeat deadlock class);
* ``lock-order``         — acquiring a lock that sorts EARLIER in the
  registry's declared outermost-first order than one already held.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from . import registry as reg_mod
from .callgraph import ModuleGraph, unparse
from .core import Finding, ModuleInfo

_MAX_DEPTH = 8


def _match_lock(expr: ast.AST, cls: Optional[str], specs) -> Optional[
        reg_mod.LockSpec]:
    text = unparse(expr)
    for spec in specs:
        if spec.expr != text:
            continue
        if spec.cls is None or spec.cls == cls or cls is None:
            # cls None at the call site happens when a module function
            # handles an instance — accept, the expr text is specific
            return spec
    return None


def _blocking_reason(call: ast.Call, reg, held) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in reg_mod.BLOCKING_NAMES:
            return f"file IO `{func.id}(...)`"
        if func.id in reg.extra_blocking_calls:
            return (f"`{func.id}(...)` — "
                    f"{reg.extra_blocking_calls[func.id]}")
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = unparse(func.value)
    if attr == "wait" and any(s.expr == recv for s in held):
        # waiting on the HELD lock's own condition variable releases it
        # atomically — the canonical CV pattern, not a blocked hold
        return None
    if attr == "join" and ("path" in recv or
                           isinstance(func.value, ast.Constant)):
        return None  # os.path.join / ", ".join — not a thread join
    if attr == "reserve" and "budget" in recv:
        for kw in call.keywords:
            if kw.arg == "wait_for_writeback" and isinstance(
                    kw.value, ast.Constant) and kw.value.value is False:
                return None  # the documented lock-safe form
        return "budget reserve (may drain spill writebacks)"
    if attr in reg_mod.BLOCKING_ATTRS:
        return f"blocking `{recv}.{attr}(...)`"
    if attr in reg_mod.QUEUE_BLOCKING_ATTRS and \
            reg_mod.QUEUE_RECEIVER_RE.search(recv):
        return f"queue `{recv}.{attr}(...)`"
    if attr == "emit" and any(h in recv for h in
                              reg_mod.EMIT_RECEIVER_HINTS):
        return f"event emit `{recv}.emit(...)` (bus lock + file write)"
    if attr == "acquire":
        return f"acquire of unregistered lock `{recv}`"
    if attr in reg.extra_blocking_calls:
        return f"`{recv}.{attr}(...)` — {reg.extra_blocking_calls[attr]}"
    return None


class _Walker:
    def __init__(self, module: ModuleInfo, graph: ModuleGraph, reg):
        self.module = module
        self.graph = graph
        self.reg = reg
        self.specs = reg.locks_for(module.path)
        self.blocking: List[Finding] = []
        self.reacquire: List[Finding] = []
        self.order: List[Finding] = []
        self._visited = set()

    def run(self) -> None:
        if not self.specs:
            return
        for qual, cls, fnode in self.graph.scopes():
            for stmt in fnode.body:
                self._scan(stmt, (), cls, qual, (qual,), 0)

    # -- events ------------------------------------------------------------
    def _on_acquire(self, spec, node, held, scope, path) -> Tuple:
        held_names = [s.name for s in held]
        if spec.name in held_names and not spec.reentrant:
            self.reacquire.append(Finding(
                "lock-reacquire", self.module.path, node.lineno, scope,
                spec.name,
                f"non-reentrant lock `{spec.name}` ({spec.expr}) "
                f"re-acquired along {' -> '.join(path)}"))
        order = self.reg.lock_order
        if spec.name in order:
            for h in held:
                if h.name in order and h.name != spec.name and \
                        order.index(spec.name) < order.index(h.name):
                    self.order.append(Finding(
                        "lock-order", self.module.path, node.lineno,
                        scope, f"{h.name}->{spec.name}",
                        f"lock `{spec.name}` acquired while holding "
                        f"`{h.name}` — declared order says "
                        f"`{spec.name}` is the outer lock"))
        if spec.name in held_names:
            return held
        return held + (spec,)

    def _on_call(self, call: ast.Call, held, cls, scope, path,
                 depth) -> None:
        # registered-lock .acquire() without a with-scope
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            spec = _match_lock(func.value, cls, self.specs)
            if spec is not None:
                self._on_acquire(spec, call, held, scope, path)
                return
        if held:
            reason = _blocking_reason(call, self.reg, held)
            if reason is not None:
                inner = held[-1].name
                via = (f" (via {' -> '.join(path)})"
                       if len(path) > 1 else "")
                self.blocking.append(Finding(
                    "lock-blocking-call", self.module.path, call.lineno,
                    scope, f"{inner}::{_call_key(call)}",
                    f"{reason} while holding lock `{inner}`{via}"))
        # follow module-local targets with the held set
        resolved = self.graph.resolve_call(call, cls)
        if resolved is not None and depth < _MAX_DEPTH:
            (tcls, tname), tnode = resolved
            tqual = f"{tcls}.{tname}" if tcls else tname
            vkey = (tqual, tuple(sorted(s.name for s in held)))
            if tqual not in path and vkey not in self._visited:
                self._visited.add(vkey)
                for stmt in tnode.body:
                    self._scan(stmt, held, tcls if tcls else cls, tqual,
                               path + (tqual,), depth + 1)

    # -- recursion ---------------------------------------------------------
    def _scan(self, node, held, cls, scope, path, depth) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested defs run later, not under this hold
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                spec = _match_lock(item.context_expr, cls, self.specs)
                if spec is not None:
                    new_held = self._on_acquire(spec, node, new_held,
                                                scope, path)
                else:
                    self._scan(item.context_expr, held, cls, scope,
                               path, depth)
            for b in node.body:
                self._scan(b, new_held, cls, scope, path, depth)
            return
        if isinstance(node, ast.Call):
            self._on_call(node, held, cls, scope, path, depth)
        for child in ast.iter_child_nodes(node):
            self._scan(child, held, cls, scope, path, depth)


def _call_key(call: ast.Call) -> str:
    return unparse(call.func)


def _walk(module: ModuleInfo, graph: ModuleGraph, reg) -> _Walker:
    cached = getattr(graph, "_lock_walk", None)
    if cached is not None:
        return cached
    w = _Walker(module, graph, reg)
    w.run()
    graph._lock_walk = w
    return w


def check_blocking(module, graph, reg):
    return list(_walk(module, graph, reg).blocking)


def check_reacquire(module, graph, reg):
    return list(_walk(module, graph, reg).reacquire)


def check_order(module, graph, reg):
    return list(_walk(module, graph, reg).order)
