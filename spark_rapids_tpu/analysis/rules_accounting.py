"""Accounting-symmetry rule (ISSUE 12 rule family 5).

Registry-declared paired calls — budget ``reserve``/``release``, quota
``charge``/``discharge`` — must stay balanced on every exception edge:
PRs 3/4/6 each shipped review fixes for counters left asymmetric on a
failure branch (a failed writeback keeping freed bytes counted, a
quota charge surviving its entry's death). Two shapes are flagged:

* **one-sided** — a function opens (reserves/charges) but contains no
  close at all, and is not a registry-declared escrow function (one
  whose obligation transfers to an object by design);
* **exception-edge** — opens and closes exist, but no close sits in a
  ``finally``/``except`` and calls that may raise run between the open
  and the close, so an unwind leaks the obligation.
"""

from __future__ import annotations

import ast
from typing import List

from .callgraph import ModuleGraph, unparse
from .core import Finding, ModuleInfo


def _match(call: ast.Call, attr: str, hint: str) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == attr:
        return hint in unparse(func.value)
    return False


def _guarded(fnode: ast.FunctionDef, pair) -> bool:
    """A close inside any finally/except body of the function."""
    for node in ast.walk(fnode):
        if not isinstance(node, ast.Try):
            continue
        guard_stmts: List[ast.stmt] = list(node.finalbody)
        for h in node.handlers:
            guard_stmts.extend(h.body)
        for stmt in guard_stmts:
            for call in ast.walk(stmt):
                if isinstance(call, ast.Call) and _match(
                        call, pair.close_attr, pair.receiver_hint):
                    return True
    return False


def check(module: ModuleInfo, graph: ModuleGraph, reg):
    pairs = reg.pairs_for(module.path)
    if not pairs:
        return []
    out = []
    for qual, cls, fnode in graph.scopes():
        for pair in pairs:
            if qual in pair.escrow:
                continue
            opens = []
            closes = []
            for node in ast.walk(fnode):
                if isinstance(node, ast.Call):
                    if _match(node, pair.open_attr, pair.receiver_hint):
                        opens.append(node)
                    elif _match(node, pair.close_attr,
                                pair.receiver_hint):
                        closes.append(node)
            if not opens:
                continue
            if not closes:
                out.append(Finding(
                    "accounting-symmetry", module.path, opens[0].lineno,
                    qual, f"{pair.name}:one-sided",
                    f"`{pair.open_attr}` ({pair.name}) with no "
                    f"`{pair.close_attr}` on any path of `{qual}` — "
                    "declare the escrow in the registry if ownership "
                    "transfers, else close on every edge"))
                continue
            if _guarded(fnode, pair):
                continue
            open_line = min(o.lineno for o in opens)
            close_line = max(c.lineno for c in closes)
            risky = False
            skip = {id(n) for n in opens} | {id(n) for n in closes}
            for node in ast.walk(fnode):
                if isinstance(node, (ast.Call, ast.Raise)) and \
                        id(node) not in skip and \
                        open_line < getattr(node, "lineno", 0) < \
                        close_line:
                    risky = True
                    break
            if risky:
                out.append(Finding(
                    "accounting-symmetry", module.path, open_line, qual,
                    f"{pair.name}:exception-edge",
                    f"`{pair.open_attr}`/`{pair.close_attr}` "
                    f"({pair.name}) in `{qual}` balance only on the "
                    "straight-line path — calls between them can "
                    "raise and leak the obligation; close in a "
                    "finally/except"))
    return out
