"""Thread-local propagation rule (ISSUE 12 rule family 2).

Every `threading.Thread(target=...)` and pool `submit`/`map` in the
package spawns work on a thread with EMPTY thread-locals: active conf,
event-log query id, speculation scope, task attempt, lifecycle context
and breaker engagement are all gone unless the target routes through
the capture/adopt helpers (the PR 3/4/5 discipline). The rule resolves
the spawn target module-locally and requires an adopt-helper call
somewhere in its reachable body — or an explicit justified suppression
at the spawn site (e.g. a process-wide daemon that carries no per-query
context by design).
"""

from __future__ import annotations

import ast
from typing import Optional

from .callgraph import ModuleGraph, unparse
from .core import Finding, ModuleInfo

_MAX_DEPTH = 8


def _spawn_target(call: ast.Call) -> Optional[ast.AST]:
    """The callable a spawn site runs, or None if not a spawn."""
    func = call.func
    if isinstance(func, (ast.Name, ast.Attribute)) and \
            unparse(func).endswith("Thread"):
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if isinstance(func, ast.Attribute) and func.attr == "submit" and \
            call.args:
        return call.args[0]
    if isinstance(func, ast.Attribute) and func.attr == "map" and \
            "pool" in unparse(func.value) and call.args:
        return call.args[0]
    return None


def _target_adopts(graph: ModuleGraph, target: ast.AST,
                   cls: Optional[str], reg) -> Optional[bool]:
    """True/False when the target resolves module-locally; None when it
    cannot be resolved (cross-module / lambda / partial)."""
    if isinstance(target, ast.Call):  # functools.partial(fn, ...)
        if target.args:
            return _target_adopts(graph, target.args[0], cls, reg)
        return None
    if isinstance(target, ast.Lambda):
        # a lambda wrapper adopts if its body routes through a helper
        # (e.g. lambda p: obs_events.with_query_id(qid, fn, p))
        for node in ast.walk(target.body):
            if isinstance(node, ast.Call):
                fn = node.func
                cname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if cname in reg.adopt_helpers:
                    return True
        return None
    # the target IS an adopt helper (obs_events.with_query_id wrapper)
    terminal = target.id if isinstance(target, ast.Name) else (
        target.attr if isinstance(target, ast.Attribute) else None)
    if terminal in reg.adopt_helpers:
        return True
    name = None
    if isinstance(target, ast.Name):
        name = target.id
    elif isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name) and target.value.id in ("self", "cls"):
        name = target.attr
    if name is None:
        return None
    resolved = graph.resolve_name(name, cls)
    if resolved is None:
        return None
    seen = set()

    def reach(fnode, fcls, depth) -> bool:
        for node in ast.walk(fnode):
            if isinstance(node, ast.Call):
                fn = node.func
                cname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if cname in reg.adopt_helpers:
                    return True
                if depth < _MAX_DEPTH:
                    sub = graph.resolve_call(node, fcls)
                    if sub is not None and sub[0] not in seen:
                        seen.add(sub[0])
                        (scls, _), snode = sub
                        if reach(snode, scls or fcls, depth + 1):
                            return True
        return False

    (tcls, _), tnode = resolved
    return reach(tnode, tcls or cls, 0)


def check(module: ModuleInfo, graph: ModuleGraph, reg):
    if reg.scope_prefix not in module.path:
        return []
    out = []
    for qual, cls, fnode in graph.scopes():
        for node in ast.walk(fnode):
            if not isinstance(node, ast.Call):
                continue
            target = _spawn_target(node)
            if target is None:
                continue
            adopts = _target_adopts(graph, target, cls, reg)
            if adopts:
                continue
            tdesc = unparse(target)
            how = ("never calls a capture/adopt helper"
                   if adopts is False else
                   "is not module-locally resolvable (adoption cannot "
                   "be verified)")
            out.append(Finding(
                "thread-adopt", module.path, node.lineno, qual, tdesc,
                f"spawn target `{tdesc}` {how} — thread-locals (conf, "
                "query id, attempt, speculation, engagement) will not "
                "propagate; adopt them or suppress with the why"))
    return out
