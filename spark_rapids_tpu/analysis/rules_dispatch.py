"""Dispatch-ledger chokepoint rule (ISSUE 13 satellite).

``dispatch-ledger``: every ``jax.jit(...)`` and ``pl.pallas_call(...)``
site in the package must route through the dispatch-ledger chokepoint
(``obs.dispatch.instrument``) or carry a justified suppression. A bare
jit site is a program the observability plane cannot see — its
dispatches, compiles and recompile storms vanish from
``QueryProfile.dispatch_summary()``, the bench ``{"dispatch"}`` deltas
and the storm detector, which is exactly the silent-throughput-loss
channel the plane exists to close.

Accepted suppressions by construction: Pallas ``pallas_call`` bodies
traced inline into an instrumented enclosing program (they are part of
the outer program, not a separate device dispatch). The chokepoint
module itself (``obs/dispatch.py``) owns the one real ``jax.jit`` call.
"""

from __future__ import annotations

import ast

from .callgraph import ModuleGraph, attr_root
from .core import Finding, ModuleInfo


def check(module: ModuleInfo, graph: ModuleGraph, reg):
    if reg.scope_prefix not in module.path:
        return []  # tools/bench scripts may drive jax directly
    if module.path.endswith("obs/dispatch.py"):
        return []  # THE chokepoint: the one sanctioned jax.jit call
    out = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Attribute):
            continue
        if node.attr == "jit" and attr_root(node) == "jax":
            out.append(Finding(
                "dispatch-ledger", module.path, node.lineno,
                "<module>", "jax.jit",
                "bare `jax.jit` — route this program through "
                "obs.dispatch.instrument(label=...) so its dispatches/"
                "compiles reach the ledger, or suppress with the why"))
        elif node.attr == "pallas_call":
            out.append(Finding(
                "dispatch-ledger", module.path, node.lineno,
                "<module>", "pallas_call",
                "bare `pallas_call` — either instrument the enclosing "
                "jit entry point and suppress here (traced inline), or "
                "route the call through the ledger chokepoint"))
    return out
