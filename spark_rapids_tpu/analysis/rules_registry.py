"""Registry-drift rules (ISSUE 12 satellite: the test_docs_lint AST
walks folded into the analyzer, so there is ONE rule registry).

``conf-key-registered``: every full ``spark.rapids.*`` string literal
must resolve in the config registry (dynamic prefixes exempt) — an
unregistered key is a typo or a missing ConfEntry.

``event-kind-registered``: every ``emit("<literal kind>", ...)`` must
be in obs.events.EVENT_LEVELS — an unregistered kind silently defaults
to MODERATE and never reaches the docs schema table.

Both lazily import their registries (config.py and obs/events.py are
stdlib-only), so the CLI stays runnable without jax.
"""

from __future__ import annotations

import ast

from .callgraph import ModuleGraph, unparse
from .core import Finding, ModuleInfo
from .scan import conf_key_literals


def _conf_registry():
    from ..config import RapidsConf, _REGISTRY
    return _REGISTRY, RapidsConf._DYNAMIC_PREFIXES


def check_conf_keys(module: ModuleInfo, graph: ModuleGraph, reg):
    out = []
    registry = prefixes = None
    for key, lineno in conf_key_literals(module.tree):
        if registry is None:
            registry, prefixes = _conf_registry()
        if key in registry or key.startswith(prefixes):
            continue
        out.append(Finding(
            "conf-key-registered", module.path, lineno, "<module>", key,
            f"conf key {key!r} is not in the config registry — add a "
            "ConfEntry (and run tools/gen_docs.py) or fix the typo"))
    return out


def check_event_kinds(module: ModuleInfo, graph: ModuleGraph, reg):
    if module.path.endswith("obs/events.py"):
        return []  # the registry module itself emits via variables
    out = []
    levels = None
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else None)
        if name != "emit":
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and
                isinstance(arg.value, str)):
            continue
        if levels is None:
            from ..obs.events import EVENT_LEVELS
            levels = EVENT_LEVELS
        if arg.value not in levels:
            out.append(Finding(
                "event-kind-registered", module.path, node.lineno,
                "<module>", arg.value,
                f"event kind {arg.value!r} is not registered in "
                "obs.events.EVENT_LEVELS — it would silently default "
                "to MODERATE and miss the docs schema table"))
    return out
