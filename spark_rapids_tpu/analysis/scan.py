"""Source discovery + literal scanning shared by the analyzer and
tests/test_docs_lint.py (ISSUE 12 satellite: ONE registry walk — the
docs lint delegates its AST scanning here and keeps only the doc-table
assertions)."""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator, List, Tuple, Union

_KEY_RE = re.compile(r"spark\.rapids\.[A-Za-z0-9_.]+$")


def repo_root(start: Path = None) -> Path:
    """The repo root: the directory holding spark_rapids_tpu/."""
    here = Path(start) if start is not None else Path(__file__)
    return here.resolve().parents[2]


def default_source_files(root: Path = None) -> List[Path]:
    """The analyzer's (and docs lint's) default scan set: the package,
    tools/ and bench.py — tests and fixtures stay out."""
    root = Path(root) if root is not None else repo_root()
    files = sorted((root / "spark_rapids_tpu").rglob("*.py"))
    files += sorted((root / "tools").glob("*.py"))
    bench = root / "bench.py"
    if bench.exists():
        files.append(bench)
    return files


def conf_key_literals(source: Union[Path, ast.Module]
                      ) -> Iterator[Tuple[str, int]]:
    """String literals that ARE a conf key (the whole literal matches),
    with their line — f-strings/doc prose don't count. Moved verbatim
    from tests/test_docs_lint.py (ISSUE 12)."""
    tree = source if isinstance(source, ast.Module) \
        else ast.parse(Path(source).read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                _KEY_RE.fullmatch(node.value.strip()):
            yield node.value.strip(), node.lineno
