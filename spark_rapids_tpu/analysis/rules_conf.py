"""Conf-provenance rule (ISSUE 12 rule family 4).

The PR 6 review rounds found the same bug three times in one PR: state
shared across queries (admission slots, quota fractions, breaker
consults) was parameterized from the CALLING thread's `active_conf()`,
which on a cross-query path belongs to an unrelated query (or to no
query at all — a bench lane, the spill writer). The registry declares
the engine's cross-query/producer entry points; any `active_conf()`
call on a module-local path from one of them is flagged — the value
must ride a captured conf, the admitting Ticket, or a job argument.
"""

from __future__ import annotations

import ast

from .callgraph import ModuleGraph, unparse
from .core import Finding, ModuleInfo

_MAX_DEPTH = 8


def _is_active_conf(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id == "active_conf"
    if isinstance(func, ast.Attribute):
        return func.attr == "active_conf"
    return False


def check(module: ModuleInfo, graph: ModuleGraph, reg):
    entries = reg.entries_for(module.path)
    if not entries:
        return []
    out = []
    seen_findings = set()
    for entry in entries:
        resolved = None
        if (entry.cls, entry.func) in graph.functions:
            resolved = ((entry.cls, entry.func),
                        graph.functions[(entry.cls, entry.func)])
        else:
            resolved = graph.resolve_name(entry.func, entry.cls)
        if resolved is None:
            continue
        visited = set()

        def walk(fnode, fcls, qual, path, depth):
            for node in ast.walk(fnode):
                if not isinstance(node, ast.Call):
                    continue
                if _is_active_conf(node):
                    fkey = (qual, node.lineno)
                    if fkey in seen_findings:
                        continue
                    seen_findings.add(fkey)
                    via = (f" via {' -> '.join(path)}"
                           if len(path) > 1 else "")
                    out.append(Finding(
                        "conf-provenance", module.path, node.lineno,
                        qual, "active_conf",
                        "active_conf() read on a cross-query path "
                        f"(entry `{path[0]}`: {entry.note}){via} — the "
                        "executing thread's conf may belong to an "
                        "unrelated query; pass a captured conf/Ticket"))
                elif depth < _MAX_DEPTH:
                    sub = graph.resolve_call(node, fcls)
                    if sub is not None and sub[0] not in visited:
                        visited.add(sub[0])
                        (scls, sname), snode = sub
                        squal = f"{scls}.{sname}" if scls else sname
                        walk(snode, scls or fcls, squal,
                             path + (squal,), depth + 1)

        (ecls, ename), enode = resolved
        equal = f"{ecls}.{ename}" if ecls else ename
        visited.add((ecls, ename))
        walk(enode, ecls, equal, (equal,), 0)
    return out
