"""Bounded-wait rule (ISSUE 20 satellite, lock-discipline family).

Every blocking rendezvous in the engine must carry a timeout: the
straggler shield can only mitigate stalls it can OBSERVE, and a bare
`Event.wait()` / `Condition.wait()` / `Queue.get()` / `future.result()`
parks its thread beyond the reach of every watchdog, deadline and
cancellation poll the engine has (the PR 6/11 cooperative-cancel
contract polls BETWEEN bounded waits). The rule flags attribute calls
named ``wait`` / ``get`` / ``result`` / ``sleep`` that are provably
unbounded: zero positional arguments AND no ``timeout=`` keyword.

That predicate is deliberately shaped so the common non-blocking forms
pass without receiver modeling:

* ``d.get(key)`` / ``conf.get(KEY)`` — positional args (a zero-arg
  ``dict.get()`` is a TypeError, so a zero-arg ``.get()`` can only be a
  queue-like receiver);
* ``ev.wait(5)`` / ``fut.result(timeout=bound)`` — bounded;
* ``time.sleep(x)`` — the duration IS positional (a zero-arg sleep is
  a TypeError; the name stays in the family so a suppression naming it
  reads naturally).

A call through ``*args`` / ``**kwargs`` is skipped — the bound may ride
the splat, and an unprovable site must not force a suppression. Sites
that are unbounded BY DESIGN (a worker parked on its feed queue, a
result future whose producer owns the deadline) carry the standard
justified ``# contract: ok bounded-wait — <why>`` suppression or a
baseline entry.
"""

from __future__ import annotations

import ast
from typing import List

from .callgraph import ModuleGraph, unparse
from .core import Finding, ModuleInfo

#: the blocking-rendezvous method family (registry.BLOCKING_ATTRS is
#: wider — it also holds IO like fsync; this rule is about WAITS)
WAIT_ATTRS = frozenset({"wait", "get", "result", "sleep"})


def _unbounded(call: ast.Call) -> bool:
    """Provably no timeout: zero positionals, no `timeout=` kwarg, and
    no splat that could carry either."""
    if call.args:
        return False
    for kw in call.keywords:
        if kw.arg is None or kw.arg == "timeout":
            return False
    return True


def check(module: ModuleInfo, graph: ModuleGraph, reg) -> List[Finding]:
    if reg.scope_prefix not in module.path:
        return []
    out: List[Finding] = []
    for qual, _cls, fnode in graph.scopes():
        for node in ast.walk(fnode):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            if attr not in WAIT_ATTRS or not _unbounded(node):
                continue
            recv = unparse(node.func.value)
            out.append(Finding(
                "bounded-wait", module.path, node.lineno, qual,
                f"{recv}.{attr}",
                f"unbounded `{recv}.{attr}()` — no timeout: the thread "
                "parks beyond every watchdog/cancellation poll; pass "
                "timeout= (poll-loop if needed) or suppress with the "
                "why"))
    return out
