"""Engine contract analyzer (ISSUE 12 tentpole).

Ten PRs of review rounds fixed the same bug classes by hand — missed
thread-local adoption at producer-thread spawns, conf reads from the
calling thread instead of the admitting ticket, event emission and
blocking calls while holding engine locks, module-level ``jnp``
constants capturing tracers, and budget counters left asymmetric on
failure branches. This package turns those review findings into an
AST-based static-analysis pass that runs in tier-1
(tests/test_contract_check.py) and as a CLI (tools/contract_check.py).

Structure:

* ``core``        — findings, suppressions, baseline, the run driver
* ``registry``    — THE rule registry: rule metadata plus the engine
                    contract data (named locks + partial order, adopt
                    helpers, cross-query conf entries, accounting pairs)
* ``callgraph``   — per-module call-graph resolution shared by rules
* ``scan``        — source-file discovery + conf-key literal scanning
                    (tests/test_docs_lint.py delegates here)
* ``rules_*``     — one module per rule family

Findings support ``# contract: ok <rule> — <why>`` suppressions
(justification required — an empty one is itself a finding) and a
checked-in baseline (tools/contract_baseline.json) whose every entry
carries a justification.
"""

from .core import (AnalysisReport, Finding, analyze_paths, apply_baseline,
                   load_baseline, write_baseline)
from .registry import DEFAULT_REGISTRY, RULES, ContractRegistry
from .scan import conf_key_literals, default_source_files

__all__ = [
    "AnalysisReport", "Finding", "analyze_paths", "apply_baseline",
    "load_baseline", "write_baseline", "DEFAULT_REGISTRY", "RULES",
    "ContractRegistry", "conf_key_literals", "default_source_files",
]
