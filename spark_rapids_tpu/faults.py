"""Cross-layer fault subsystem (ISSUE 4 tentpole): one registry of named
fault points, one seeded deterministic injection surface, and one error
taxonomy for everything that can die at runtime besides OOM.

The reference engine spreads this across RmmSpark fault injection
(RmmSparkRetrySuiteBase.scala forceRetryOOM/forceSplitAndRetryOOM and the
JNI error-state machine), Spark's task re-execution, and the shuffle
commit protocol. Rebuilt here for a single-process multi-thread engine:

* **Fault points** (`FAULT_POINTS`) name every async/IO seam the engine
  crosses: spill byte movement, shuffle fetch/decode, multi-file reads,
  guarded device dispatch, pipeline producers. Each real call site runs
  `apply(point)` / `apply(point, data)`; with injection off that is ONE
  module-global pointer check (`_PLAN is None`).

* **Injection** is driven by one conf
  (`spark.rapids.tpu.test.faults = "<point>:prob=P,seed=S,kind=K[,max=N][,ms=N][;...]"`,
  kind in io|device|corrupt|delay) and keyed on (task_id, work-item key,
  per-sequence call index): the decision is a pure hash of
  (seed, point, task, key, index) — no wall clock, no RNG state. Sites
  evaluated on pool/producer threads pass their work-item identity as
  the key (chunk index, map-file:partition, stage label, and — ISSUE 7
  — the spill catalog entry's registration ordinal for every
  spill.{d2h_copy,disk_write,disk_read} site): injection PLACEMENT,
  not just count, no longer moves with which THREAD runs a spill
  (writer vs sync, any processing order). The ordinal itself is
  assigned in catalog.add order, so placement is fully run-to-run
  exact when entry registration is deterministic (a single driven
  query); concurrent lanes racing catalog.add still replay counts
  exactly but may map ordinals onto different lanes' entries.

* **Taxonomy**: `TpuRetryOOM`/`TpuSplitAndRetryOOM` (memory/retry.py)
  stay the OOM lane. Everything else transient becomes
  `TpuTaskRetryError` — injected device faults, XLA runtime errors that
  are not RESOURCE_EXHAUSTED, integrity failures (checksum mismatch =
  the data is gone; recompute is the only recovery). `classify()` maps
  an arbitrary exception into "oom" | "task" | "fatal";
  exec/task_retry.py re-executes "task" failures with bounded attempts.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, Optional

#: the closed registry: point name -> (site, what an injected fault means).
#: docs/robustness.md documents this table and tests/test_docs_lint.py
#: asserts the two never drift.
FAULT_POINTS: Dict[str, str] = {
    "spill.d2h_copy": "device->host copy of a spilling buffer "
                      "(memory/catalog.py, sync + async writeback)",
    "spill.disk_write": "host->disk spill file write "
                        "(memory/catalog.py _write_npz)",
    "spill.disk_read": "disk->host spill file read "
                       "(memory/catalog.py _read_npz)",
    "shuffle.fetch": "shuffle block segment fetch "
                     "(shuffle/manager.py HostShuffleReader)",
    "shuffle.decode": "shuffle frame decode "
                      "(shuffle/manager.py read_partition)",
    "io.multifile_read": "multi-file decode task "
                         "(io/multifile.py threaded_chunks)",
    "device.dispatch": "guarded device section "
                       "(memory/retry.py oom_guard)",
    "pipeline.produce": "pipeline producer step "
                        "(exec/pipeline.py PipelinedIterator)",
    "shuffle.ici_exchange": "ICI collective exchange round dispatch "
                            "(exec/exchange.py _ici_exchange_round)",
    "shuffle.skew_split": "adaptive skew-split sub-read frame "
                          "(shuffle/manager.py read_partition_maps)",
}

KINDS = ("io", "device", "corrupt", "delay")


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------

class TpuTaskRetryError(RuntimeError):
    """Transient non-OOM failure: the current task attempt is lost but a
    re-execution from the sources is expected to succeed (the engine
    analog of a Spark task-attempt failure)."""

    #: recovery provenance (ISSUE 6): a dict naming what was lost —
    #: {"kind": "shuffle_block", "shuffle_id", "partition", "map_path"}
    #: or {"kind": "spill_file", "handle"} — or None when unknown. A
    #: shuffle block with captured lineage recovers on the
    #: partition-granular lane (shuffle/manager.py); everything else is
    #: ambiguous and takes the whole-plan lane (exec/task_retry.py).
    provenance = None


class IntegrityError(TpuTaskRetryError):
    """Checksum mismatch on a spill file or shuffle block: the bytes are
    quarantined, the only recovery is recomputation (task retry)."""


class QueryStalledError(TpuTaskRetryError):
    """The progress watchdog (exec/speculation_shield.py) declared this
    attempt's driving seam stalled under `stall.action=retry-seam`:
    the attempt is abandoned at its next cancellation checkpoint and
    re-executed on the bounded task-retry lane."""


class DispatchTimeoutError(TpuTaskRetryError):
    """A dispatched device program was not ready inside
    `dispatch.timeoutMs` (exec/speculation_shield.timed_call): the
    wedged call is abandoned on its watchdog thread and the attempt
    re-executes — the engine analog of a task killed on a hung
    device."""


class InjectedIOError(OSError):
    """Injected `kind=io` fault (a transient OSError look-alike)."""

    def __init__(self, point: str):
        import errno
        super().__init__(errno.EIO, f"injected io fault at {point}")
        self.fault_point = point


class InjectedDeviceError(RuntimeError):
    """Injected `kind=device` fault (an XLA runtime error look-alike)."""

    def __init__(self, point: str):
        super().__init__(f"injected device fault at {point}")
        self.fault_point = point


def is_oom_error(exc: BaseException) -> bool:
    """XLA surfaces allocator exhaustion as a runtime error whose status
    is RESOURCE_EXHAUSTED; map it onto the engine's OOM-retry lane
    (reference: RMM's async OOM callback feeding RmmRapidsRetryIterator)."""
    return type(exc).__name__ == "XlaRuntimeError" \
        and "RESOURCE_EXHAUSTED" in str(exc)


def is_task_transient(exc: BaseException) -> bool:
    """Errors a task re-execution is expected to clear: injected faults
    (device look-alikes, and io look-alikes that escaped a site with no
    io-retry lane of its own, e.g. pipeline.produce), integrity
    failures, and XLA runtime errors that are not resource exhaustion
    (device resets, interconnect hiccups, preempted programs —
    UNAVAILABLE/INTERNAL/ABORTED/DATA_LOSS and friends). A REAL OSError
    stays fatal at this level: it either already exhausted the bounded
    IO retry (persistently unreadable bytes re-read the same way on a
    fresh attempt) or names a non-transient condition."""
    if isinstance(exc, (TpuTaskRetryError, InjectedDeviceError,
                        InjectedIOError)):
        return True
    return type(exc).__name__ == "XlaRuntimeError" \
        and "RESOURCE_EXHAUSTED" not in str(exc)


def classify(exc: BaseException) -> str:
    """"oom" | "task" | "fatal" — the one classification both the
    OOM-retry loop (memory/retry.py) and the task-attempt layer
    (exec/task_retry.py) consult."""
    from .memory.retry import TpuOOMError
    if isinstance(exc, TpuOOMError) or is_oom_error(exc):
        return "oom"
    if is_task_transient(exc):
        return "task"
    return "fatal"


# ---------------------------------------------------------------------------
# injection plan
# ---------------------------------------------------------------------------

class _PointSpec:
    __slots__ = ("point", "prob", "seed", "kind", "max_injections",
                 "delay_ms")

    def __init__(self, point: str, prob: float, seed: int, kind: str,
                 max_injections: Optional[int], delay_ms: int = 0):
        self.point = point
        self.prob = prob
        self.seed = seed
        self.kind = kind
        self.max_injections = max_injections
        #: kind=delay only: injected latency per firing (ms)
        self.delay_ms = delay_ms


class FaultPlan:
    """Parsed injection plan. Decisions are pure in (seed, point,
    task_id, call_index); the per-(point, task) call counters live here
    so the k-th guarded call of a task draws the same verdict on every
    replay."""

    def __init__(self, specs: Dict[str, _PointSpec], spec_string: str = ""):
        self.specs = specs
        #: the normalized conf string this plan was parsed from —
        #: configure() uses it to keep ONE plan alive across task
        #: re-executions of the same chaos run
        self.spec_string = spec_string
        self._lock = threading.Lock()
        self._calls: Dict[tuple, int] = {}
        #: injections actually fired, per point (bench chaos record)
        self.injected: Dict[str, int] = {}

    def _task_id(self) -> int:
        from .memory.retry import current_task_id
        tid = current_task_id()
        return 0 if tid is None else int(tid)

    def decide(self, point: str, corruptible: bool = True,
               key: Optional[str] = None) -> Optional[str]:
        """The armed kind if this call injects, else None. Always
        consumes one call index for (point, task, key) — the decision
        sequence stays aligned across replay — but an armed `corrupt`
        kind at a call with no bytes flowing (`corruptible=False`) is
        NOT fired: it would perturb nothing, so it must not consume the
        max-injection budget, count in stats() or emit fault_inject.

        `key` is the work-item identity for sites evaluated on POOL or
        PRODUCER threads (a chunk index, a map-file:partition pair, a
        stage label): it gives each work item its own call-index
        sequence, so OS thread scheduling cannot permute which item
        draws which verdict and a seeded chaos failure replays on the
        same item. Keyless multi-threaded sites replay the injection
        COUNT deterministically (the draw is a pure hash) but may place
        injections on different calls across runs."""
        spec = self.specs.get(point)
        if spec is None:
            return None
        task = self._task_id()
        with self._lock:
            ckey = (point, task, key)
            idx = self._calls.get(ckey, 0)
            self._calls[ckey] = idx + 1
            if spec.kind == "corrupt" and not corruptible:
                return None
            fired = self.injected.get(point, 0)
            if spec.max_injections is not None \
                    and fired >= spec.max_injections:
                return None
            draw = zlib.crc32(
                f"{spec.seed}:{point}:{task}:{key or ''}:{idx}"
                .encode()) / 2 ** 32
            if draw >= spec.prob:
                return None
            self.injected[point] = fired + 1
        from .obs import events as obs_events
        obs_events.emit("fault_inject", point=point, fault_kind=spec.kind,
                        task_id=task, call_index=idx, seed=spec.seed,
                        key=key)
        return spec.kind

    def apply(self, point: str, data: Optional[bytes] = None,
              key: Optional[str] = None) -> Optional[bytes]:
        kind = self.decide(
            point, corruptible=data is not None and len(data) > 0,
            key=key)
        if kind is None:
            return data
        if kind == "io":
            raise InjectedIOError(point)
        if kind == "device":
            raise InjectedDeviceError(point)
        if kind == "delay":
            # a deterministic straggler, not a failure: the call blocks
            # for the armed ms and proceeds with its data untouched —
            # the reproducible slow participant every watchdog /
            # speculation test needs (budget, stats and the
            # fault_inject event were accounted by decide() above)
            import time
            time.sleep(self.specs[point].delay_ms / 1000.0)
            return data
        pos = zlib.crc32(f"pos:{point}:{len(data)}".encode()) % len(data)
        out = bytearray(data)
        out[pos] ^= 0xFF
        return bytes(out)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.injected)


def parse_faults(spec: str) -> Optional[FaultPlan]:
    """Parse the conf grammar:
    `<point>:prob=P,seed=S,kind=io|device|corrupt|delay[,max=N][,ms=N]
    [;<point>:...]`. Unknown points or kinds fail loudly — a typo'd
    chaos spec silently injecting nothing is worse than an error."""
    spec = (spec or "").strip()
    if not spec:
        return None
    specs: Dict[str, _PointSpec] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, kvs = part.partition(":")
        point = point.strip()
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}; known: "
                             f"{sorted(FAULT_POINTS)}")
        prob, seed, kind, max_inj, delay_ms = 1.0, 0, "io", None, 0
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k, v = k.strip(), v.strip()
            if k == "prob":
                prob = float(v)
            elif k == "seed":
                seed = int(v)
            elif k == "kind":
                if v not in KINDS:
                    raise ValueError(f"unknown fault kind {v!r} for "
                                     f"{point}; known: {KINDS}")
                kind = v
            elif k == "max":
                max_inj = int(v)
            elif k == "ms":
                delay_ms = int(v)
            else:
                raise ValueError(f"unknown fault option {k!r} for {point}")
        if kind == "delay" and delay_ms <= 0:
            raise ValueError(f"kind=delay for {point} requires ms=N > 0")
        specs[point] = _PointSpec(point, prob, seed, kind, max_inj,
                                  delay_ms=delay_ms)
    return FaultPlan(specs, spec) if specs else None


# ---------------------------------------------------------------------------
# process-wide activation (the one-pointer-check fast path)
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_plan_lock = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def install(spec: Optional[str]) -> Optional[FaultPlan]:
    """Install (or with None/empty, clear) the process fault plan from a
    spec string (test/bench entry)."""
    global _PLAN
    plan = parse_faults(spec) if spec else None
    with _plan_lock:
        _PLAN = plan
    return plan


def configure(conf=None) -> Optional[FaultPlan]:
    """(Re)configure injection from a RapidsConf — the session/collect
    hook, mirroring obs.events.configure. A conf that does not mention
    spark.rapids.tpu.test.faults leaves the current plan alone (a
    default-conf session must not disarm another session's chaos run);
    an explicit empty value clears it."""
    from .config import TEST_FAULTS, active_conf
    conf = conf if conf is not None else active_conf()
    if TEST_FAULTS.key not in conf._settings:
        return _PLAN
    spec = (conf.get(TEST_FAULTS) or "").strip()
    cur = _PLAN
    if cur is not None and cur.spec_string == spec:
        # same chaos run: keep the armed plan. Re-installing would reset
        # the per-(point, task) call counters and max-injection budgets,
        # so every task RE-EXECUTION (which reconfigures on its way back
        # through _exec) would replay exactly the faults that killed the
        # previous attempt — recovery could never converge.
        return cur
    return install(spec)


def apply(point: str, data: Optional[bytes] = None,
          key: Optional[str] = None) -> Optional[bytes]:
    """The one call every fault-point site makes. Injection off =
    exactly this pointer check. Sites that run on pool/producer threads
    pass `key` (their work-item identity) so replay is per-item exact —
    see FaultPlan.decide."""
    plan = _PLAN
    if plan is None:
        return data
    return plan.apply(point, data, key=key)


def check(point: str, key: Optional[str] = None) -> None:
    """apply() for data-free sites."""
    plan = _PLAN
    if plan is not None:
        plan.apply(point, key=key)


def stats() -> Dict[str, int]:
    """Per-point injection counts of the active plan ({} when off)."""
    plan = _PLAN
    return plan.stats() if plan is not None else {}


def backoff_s(attempt: int, base_ms: int, cap_ms: int,
              jitter_key: str) -> float:
    """Capped exponential backoff with deterministic jitter, shared by
    all three retry lanes (io/retrying.py, memory/retry.py,
    exec/task_retry.py): min(base * 2^(attempt-1), cap) plus up to 25%
    jitter that is a pure hash of `jitter_key` — a seeded chaos run
    replays with identical timing decisions."""
    ms = min(base_ms * (1 << (attempt - 1)), cap_ms)
    frac = zlib.crc32(jitter_key.encode()) / 2 ** 32
    return ms * (1.0 + 0.25 * frac) / 1000.0


def uniform_spec(prob: float, seed: int, points=None) -> str:
    """A spec string arming every (or the given) fault point at one
    probability with sensible per-point kinds — the bench.py
    --fault-rate entry. Corruption goes where checksums guard the read
    path; device faults where XLA dispatches; io everywhere else."""
    default_kind = {
        "device.dispatch": "device",
        "spill.d2h_copy": "device",
        "pipeline.produce": "io",
        "spill.disk_read": "io",
        "spill.disk_write": "corrupt",
        "shuffle.decode": "corrupt",
        "shuffle.fetch": "io",
        "shuffle.ici_exchange": "device",
        "shuffle.skew_split": "corrupt",
        "io.multifile_read": "io",
    }
    parts = []
    for point in (points or sorted(FAULT_POINTS)):
        parts.append(f"{point}:prob={prob},seed={seed},"
                     f"kind={default_kind.get(point, 'io')}")
    return ";".join(parts)
