"""Plugin lifecycle shell — the reference's Plugin.scala
(RapidsDriverPlugin :412 / RapidsExecutorPlugin :484): startup validation,
device + memory runtime initialization, heartbeat wiring, and the
fatal-error → exit policy (:640-662: a fatal CUDA error logs diagnostics
and kills the executor so the cluster manager reschedules).

Standalone shape: there is no Spark JVM to plug into, so the lifecycle is
an explicit object the embedding application (or TpuSession) drives:
`TpuExecutorPlugin(conf).init()` … `.shutdown()`. The checks mirror the
reference's init order (SURVEY §3.1): environment validation → device
acquisition → memory runtime → shuffle/heartbeats → admission semaphore.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, List, Optional

log = logging.getLogger("spark_rapids_tpu.plugin")


class FatalDeviceError(Exception):
    """Unrecoverable device/runtime failure (the reference's
    CudaFatalException classification)."""


class TpuDriverPlugin:
    """Driver side (reference RapidsDriverPlugin.init :412): conf fixups
    + heartbeat manager for executor peer discovery."""

    def __init__(self, conf=None):
        from .config import RapidsConf, active_conf
        self.conf: RapidsConf = conf or active_conf()
        self.heartbeat_manager = None

    def init(self) -> "TpuDriverPlugin":
        from .parallel.heartbeat import HeartbeatManager
        self.heartbeat_manager = HeartbeatManager()
        log.info("TpuDriverPlugin initialized (heartbeat manager up)")
        return self

    def shutdown(self) -> None:
        self.heartbeat_manager = None


class TpuExecutorPlugin:
    """Executor side (reference RapidsExecutorPlugin.init :484)."""

    def __init__(self, conf=None, executor_id: str = "exec-0",
                 driver: Optional[TpuDriverPlugin] = None,
                 exit_fn: Callable[[int], None] = None):
        from .config import RapidsConf, active_conf
        self.conf: RapidsConf = conf or active_conf()
        self.executor_id = executor_id
        self.driver = driver
        self.heartbeat_endpoint = None
        self.peers: List[str] = []
        #: test seam: production exits the process like Plugin.scala:655
        self._exit = exit_fn or (lambda code: os._exit(code))
        self._initialized = False

    # -- init sequence (reference order, SURVEY §3.1) ----------------------
    def init(self) -> "TpuExecutorPlugin":
        self._validate_environment()
        self._init_device_and_memory()
        self._init_heartbeats()
        self._init_semaphore()
        self._initialized = True
        log.info("TpuExecutorPlugin %s initialized", self.executor_id)
        return self

    def _validate_environment(self) -> None:
        """Platform checks (reference validateGpuArchitecture +
        checkCudfVersion + driver/executor timezone equality)."""
        import jax
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
        if (major, minor) < (0, 4):
            raise FatalDeviceError(
                f"jax {jax.__version__} too old (need >= 0.4, the XLA "
                "runtime contract this engine compiles against)")
        if not jax.devices():
            raise FatalDeviceError("no XLA devices visible")
        # the engine's kernels assume UTC session timezone (non-UTC goes
        # through the timezone DB); reject a mismatched TZ env like the
        # reference rejects driver/executor timezone mismatches
        tz = os.environ.get("TZ")
        if tz not in (None, "", "UTC", "Etc/UTC"):
            log.warning(
                "process TZ=%s; the engine computes in UTC and applies "
                "zone rules via the timezone DB (reference requires "
                "matching driver/executor timezones)", tz)

    def _init_device_and_memory(self) -> None:
        from .memory.device_manager import device_manager
        try:
            device_manager().initialize()
        except Exception as e:  # noqa: BLE001 — classified below
            self.on_fatal_error(e)
            raise

    def _init_heartbeats(self) -> None:
        if self.driver is None or self.driver.heartbeat_manager is None:
            return
        from .parallel.heartbeat import HeartbeatEndpoint
        self.heartbeat_endpoint = HeartbeatEndpoint(
            self.driver.heartbeat_manager, self.executor_id,
            on_new_peer=lambda p: self.peers.append(p.executor_id))
        self.heartbeat_endpoint.start()

    def _init_semaphore(self) -> None:
        from .memory.semaphore import tpu_semaphore
        tpu_semaphore()

    # -- failure policy ----------------------------------------------------
    def on_fatal_error(self, exc: BaseException) -> None:
        """Reference Plugin.scala:640-662: log device diagnostics, then
        exit the executor so the scheduler replaces it (task retry IS the
        recovery model — SURVEY §5)."""
        log.error("FATAL device error: %s", exc, exc_info=exc)
        try:
            import jax
            for d in jax.devices():
                stats = getattr(d, "memory_stats", lambda: None)()
                log.error("device %s: %s", d, stats)
        except Exception:  # noqa: BLE001 — diagnostics are best-effort
            pass
        if self._classify_fatal(exc):
            log.error("executor %s exiting for reschedule",
                      self.executor_id)
            self._exit(1)

    @staticmethod
    def _classify_fatal(exc: BaseException) -> bool:
        """Which failures kill the executor (reference: CudaFatalException
        yes, retryable OOM no)."""
        from .memory.retry import TpuRetryOOM, TpuSplitAndRetryOOM
        if isinstance(exc, (TpuRetryOOM, TpuSplitAndRetryOOM)):
            return False
        if isinstance(exc, FatalDeviceError):
            return True
        name = type(exc).__name__
        return "XlaRuntimeError" in name or "RuntimeError" in name

    def on_task_failed(self, exc: BaseException) -> None:
        """Reference onTaskFailed: inspect for fatal classification."""
        if self._classify_fatal(exc):
            self.on_fatal_error(exc)

    def shutdown(self) -> None:
        if self.heartbeat_endpoint is not None:
            self.heartbeat_endpoint.stop()
        from .memory.device_manager import device_manager
        device_manager().shutdown()
        self._initialized = False
