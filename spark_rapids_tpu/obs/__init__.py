"""Structured observability — the engine's analog of the reference's
GpuMetric/GpuTaskMetrics/NVTX stack joined into one subsystem (ISSUE 2):

  * `events` — process-wide JSONL event bus (query begin/end, operator
    spans, semaphore waits, spills, OOM retries, Pallas tier decisions,
    plan fallbacks, exchange volumes), gated by the
    spark.rapids.tpu.eventLog.{enabled,dir,level} confs and costing one
    pointer check per batch when disabled.
  * `span` — op_span(): the NvtxWithMetrics analog — one context manager
    that emits the xprof TraceAnnotation, bumps a TpuMetric, and appends
    an event record.
  * `profile` — QueryProfile: the executed plan tree annotated with
    per-operator metrics, with text (explain-with-metrics) and JSON
    renderers plus `.statistics()`; surfaced as
    TpuSession.last_query_profile().
  * `stats` — runtime statistics collection (ISSUE 11): per-exchange
    map-output/partition row+byte distributions as log2 histograms,
    exact per-partition totals and skew summaries, carried per query
    on the governing QueryContext (`stats.current()`) — the data plane
    the AQE loop (ROADMAP 4) replans from.
  * `telemetry` — live metrics registry + sampler (ISSUE 11): per-owner
    HBM attribution, link bytes, queue/semaphore/breaker/spill gauges
    in bounded ring-buffer series, flushed as telemetry_sample events;
    gated by spark.rapids.tpu.telemetry.{enabled,intervalMs,historySize}.
  * `dispatch` — the jit dispatch ledger (ISSUE 13): every engine
    program dispatch routes through `dispatch.instrument`, recording
    per stable program key (label x arg-shape bucket x platform) the
    dispatch count, first-trace vs cache-hit split, trace/compile cost
    and donated/retained bytes; emits `program_compile` per fresh trace
    and `recompile_storm` on shape-bucket churn. The whole-stage-
    compilation baseline (ROADMAP 2) reads
    QueryProfile.dispatch_summary() on top of it.

Render an event-log file with tools/profile_report.py (`--format json`
for the machine-readable summary) and telemetry samples with
tools/telemetry_export.py (Prometheus text format).
"""

from . import events  # noqa: F401
from .profile import QueryProfile  # noqa: F401
from .span import op_span  # noqa: F401
