"""Structured observability — the engine's analog of the reference's
GpuMetric/GpuTaskMetrics/NVTX stack joined into one subsystem (ISSUE 2):

  * `events` — process-wide JSONL event bus (query begin/end, operator
    spans, semaphore waits, spills, OOM retries, Pallas tier decisions,
    plan fallbacks, exchange volumes), gated by the
    spark.rapids.tpu.eventLog.{enabled,dir,level} confs and costing one
    pointer check per batch when disabled.
  * `span` — op_span(): the NvtxWithMetrics analog — one context manager
    that emits the xprof TraceAnnotation, bumps a TpuMetric, and appends
    an event record.
  * `profile` — QueryProfile: the executed plan tree annotated with
    per-operator metrics, with text (explain-with-metrics) and JSON
    renderers; surfaced as TpuSession.last_query_profile().

Render an event-log file with tools/profile_report.py.
"""

from . import events  # noqa: F401
from .profile import QueryProfile  # noqa: F401
from .span import op_span  # noqa: F401
