"""Wall-clock phase attribution (ISSUE 17 tentpole piece 1): partition
each governed query's total wall-clock into a CLOSED set of named
phases, with the invariant `sum(phases) == wall_ns` exactly.

The reference's profiling tool reads Spark's task metrics (semaphore
wait, spill time, shuffle write/read time, ...) and attributes stage
wall-clock to them; standalone we rebuild that from the hooks the obs
plane already has — the dispatch ledger times every device call and
knows which ones traced (compile), the shuffle write path splits
pack/serialize/io, the ICI lane times its collective, the semaphore and
workload governor time their waits, the pipelined iterator times its
stalls, the retry layers time their backoffs.

Two accounting surfaces, both fed by the same `add`/`span` calls:

* **Process-global cumulative counters** (`counters()`), always on —
  the obs/stats.py `_global_*` precedent. bench.py deltas them per
  record even for lanes that drive `plan.execute()` directly without a
  governed query (q1_lane), where no ledger exists.
* **Per-query PhaseLedger**, attached to the governed QueryContext by
  `DataFrame.collect()` when `spark.rapids.tpu.phases.enabled` (default
  on; off = the ledger is None and every site's ledger branch is one
  pointer check). `snapshot()` closes the books: `other` is the derived
  remainder, never negative.

Exactness rules:

* Accruals on the query's DRIVING thread are sequential and exclusive —
  `span()` keeps a thread-local stack and subtracts child-notified time
  from the enclosing frame, so nesting (a dispatch inside the ICI
  collective; a spill wait inside the shuffle write) never
  double-counts. Their sum can therefore never exceed wall.
* Accruals from OTHER threads (pipeline producers, adopted via the
  lifecycle adopt_context pattern) land in a separate `folded` map.
  Producer work overlaps consumer work; the only consumer wall-clock it
  can explain is the time the consumer spent *waiting on the producer*
  — the pipeline-stall budget. `snapshot()` re-attributes folded time
  into that budget (scaled down proportionally when producers report
  more time than the consumer stalled), shrinking pipeline-stall by the
  attributed amount, so the total never grows past wall.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

#: the closed phase set — docs/observability.md's phase table is
#: lint-checked against this tuple (tests/test_docs_lint.py), like the
#: event-kind and fault-point tables. `other` is always derived
#: (wall minus the sum of the measured phases), never accrued directly.
PHASES = (
    "admission-wait",      # workload-governor queue (exec/workload.py)
    "compile",             # traced dispatches (obs/dispatch.py)
    "device-compute",      # cached-program dispatches outside any span
    "host-pack-serialize", # shuffle write pack/serialize (exec/exchange.py)
    "shuffle-io",          # shuffle file write/read io_ns
    "ici-collective",      # device all-to-all rounds (ICI lane)
    "spill-wait",          # catalog writeback waits + synchronous spill
    "semaphore-wait",      # device admission (memory/semaphore.py)
    "pipeline-stall",      # consumer blocked on producer (exec/pipeline.py)
    "retry-backoff",       # task-retry + OOM-retry backoff sleeps
    "spec-wait",           # post-bound straggler wait the speculation
                           # shield raced against (exec/speculation_shield)
    "other",               # derived remainder — never negative
)

#: phases a site may accrue into (everything but the derived remainder)
ACCRUABLE = PHASES[:-1]


# ---------------------------------------------------------------------------
# process-global counters (bench.py {"phases": ...} deltas)
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global_ns: Dict[str, int] = {p: 0 for p in ACCRUABLE}


def counters() -> Dict[str, int]:
    """Snapshot of the process-cumulative per-phase nanoseconds — one
    dict so bench.py can delta it per record (chaos-delta pattern)."""
    with _global_lock:
        return dict(_global_ns)


def reset_phase_counters() -> None:
    """Test isolation (conftest tripwire companion)."""
    with _global_lock:
        for k in _global_ns:
            _global_ns[k] = 0


# ---------------------------------------------------------------------------
# thread-local span stack (exclusive accounting)
# ---------------------------------------------------------------------------

_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "spans", None)
    if s is None:
        s = _tls.spans = []
    return s


def in_span() -> bool:
    """Is this thread inside an attribution span? The dispatch hook
    uses this to leave un-traced dispatch time to the enclosing span's
    phase (the ICI all-to-all is ici-collective, not device-compute)."""
    s = getattr(_tls, "spans", None)
    return bool(s)


def _ledger() -> Optional["PhaseLedger"]:
    from ..exec import lifecycle
    ctx = lifecycle.current_context()
    return getattr(ctx, "phase_ledger", None) if ctx is not None else None


def add(phase: str, ns: int) -> None:
    """Accrue `ns` of wall-clock to `phase`: process-global counters
    always; this thread's governed query's ledger when one is attached
    (one pointer check otherwise); and notify the enclosing span frame
    so the parent phase excludes this time."""
    if ns <= 0:
        return
    ns = int(ns)
    with _global_lock:
        _global_ns[phase] += ns
    s = getattr(_tls, "spans", None)
    if s:
        s[-1][1] += ns
    led = _ledger()
    if led is not None:
        led.add(phase, ns)


@contextlib.contextmanager
def span(phase: str) -> Iterator[None]:
    """Attribute this block's EXCLUSIVE elapsed time to `phase`: time
    any nested add()/span() reports is subtracted, and the block's full
    elapsed is notified upward — so arbitrarily nested attribution
    still sums to the outermost block's wall-clock, once."""
    t0 = time.perf_counter_ns()
    frame = [phase, 0]
    stack = _stack()
    stack.append(frame)
    try:
        yield
    finally:
        stack.pop()
        elapsed = time.perf_counter_ns() - t0
        exclusive = elapsed - frame[1]
        if exclusive > 0:
            with _global_lock:
                _global_ns[phase] += exclusive
            led = _ledger()
            if led is not None:
                led.add(phase, exclusive)
        if stack and elapsed > 0:
            stack[-1][1] += elapsed


def note_dispatch(wall_ns: int, traced: bool) -> None:
    """Per-dispatch hook (obs/dispatch.DispatchLedger._account, outside
    the ledger lock). Traced dispatches are compile time wherever they
    happen; cached dispatches are device-compute ONLY outside a span —
    inside one (ICI collective, shuffle pack) the enclosing phase keeps
    the time, matching how the site already reports it."""
    if traced:
        add("compile", wall_ns)
    elif not in_span():
        add("device-compute", wall_ns)


# ---------------------------------------------------------------------------
# per-query ledger
# ---------------------------------------------------------------------------

class PhaseLedger:
    """Per-governed-query phase books. Created on the driving thread by
    DataFrame.collect; accruals from that thread land in `_direct`
    (sequential, exclusive — their sum cannot exceed wall), accruals
    from adopted producer threads land in `_folded` (overlapped —
    snapshot() folds them into the pipeline-stall budget)."""

    __slots__ = ("_t0", "_thread", "_direct", "_folded", "_lock", "_wall")

    def __init__(self):
        self._t0 = time.perf_counter_ns()
        self._thread = threading.get_ident()
        self._direct: Dict[str, int] = {}
        self._folded: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._wall: Optional[int] = None

    def add(self, phase: str, ns: int) -> None:
        direct = threading.get_ident() == self._thread
        with self._lock:
            tgt = self._direct if direct else self._folded
            tgt[phase] = tgt.get(phase, 0) + ns

    def finish(self) -> int:
        """Close the measurement window (idempotent); returns wall_ns."""
        if self._wall is None:
            self._wall = time.perf_counter_ns() - self._t0
        return self._wall

    def dominant_phase(self) -> Optional[str]:
        """The largest phase accrued SO FAR, read mid-flight without
        closing the measurement window (the stall watchdog's `query
        stuck in <phase>` attribution — snapshot() would freeze wall).
        None when nothing has accrued yet."""
        with self._lock:
            merged = dict(self._direct)
            for p, v in self._folded.items():
                merged[p] = merged.get(p, 0) + v
        if not merged:
            return None
        return max(merged, key=merged.get)

    @property
    def wall_ns(self) -> int:
        return self.finish()

    def snapshot(self) -> Dict[str, int]:
        """The closed phase dict: every name in PHASES present,
        `sum(values) == wall_ns` exactly, nothing negative. Folded
        producer time re-attributes pipeline-stall budget: the consumer
        stalled exactly while producers worked, so folded accruals
        displace stall ns one-for-one, scaled down when producers
        report more than the consumer stalled (deeper overlap — that
        surplus genuinely did not cost the query wall-clock)."""
        wall = self.finish()
        with self._lock:
            direct = dict(self._direct)
            folded = dict(self._folded)
        out: Dict[str, int] = {p: 0 for p in PHASES}
        for p, v in direct.items():
            out[p] += v
        folded_total = sum(folded.values())
        if folded_total > 0:
            budget = out["pipeline-stall"]
            attributed = 0
            for p, v in folded.items():
                share = v if folded_total <= budget \
                    else v * budget // folded_total
                out[p] += share
                attributed += share
            out["pipeline-stall"] = budget - attributed
        total = sum(out.values())
        if total > wall:
            # defensive: direct spans are exclusive on one thread and
            # folded time never exceeds the stall budget, so this
            # should be unreachable — but the invariant is load-bearing
            # (tier-1 asserts it), so trim largest-first rather than
            # ever reporting sum > wall
            excess = total - wall
            for p in sorted(out, key=out.__getitem__, reverse=True):
                take = min(out[p], excess)
                out[p] -= take
                excess -= take
                if excess <= 0:
                    break
        out["other"] = wall - sum(v for k, v in out.items()
                                  if k != "other")
        return out


def attach(ctx) -> PhaseLedger:
    """Attach a fresh ledger to a governed QueryContext (the collect
    wrapper, conf-gated by spark.rapids.tpu.phases.enabled)."""
    led = PhaseLedger()
    ctx.phase_ledger = led
    return led
