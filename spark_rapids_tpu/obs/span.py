"""op_span — the NvtxWithMetrics analog (reference NvtxWithMetrics.scala:
one object that IS both the NVTX range and the metric scope).

One context manager:
  * opens a jax.profiler.TraceAnnotation so xprof timelines show the
    engine-level name over the XLA ops it launched,
  * times the body with perf_counter_ns and adds the elapsed ns to an
    optional TpuMetric,
  * appends a `span` event record (DEBUG level) to the event bus when
    logging is enabled.

Timing and metric accumulation happen even when the body raises — a
failed span's time is exactly what an operator debugging it wants
attributed (same try/finally discipline as TpuMetric.ns_timer).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator, Optional

from ..utils.tracing import annotate_op
from . import events


@contextlib.contextmanager
def op_span(name: str, metric=None, kind: str = "span",
            **fields: Any) -> Iterator[None]:
    bus = events.active_bus()
    t0 = time.perf_counter_ns()
    ok = True
    try:
        with annotate_op(name):
            yield
    except BaseException:
        ok = False
        raise
    finally:
        dt = time.perf_counter_ns() - t0
        if metric is not None:
            metric.add(dt)
        if bus is not None:
            bus.emit(kind, op=name, wall_ns=dt, ok=ok, **fields)
