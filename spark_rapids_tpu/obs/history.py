"""Query history store (ISSUE 17 tentpole part 2) — the engine's Spark
history-server analog, one process-local JSONL capsule per finished
governed query instead of a replayable UI event stream.

Behind `spark.rapids.tpu.history.{enabled,dir,maxBytes}` (default OFF —
one module pointer check per collect, the PR 2 event-bus discipline),
`DataFrame.collect`'s governed wrap appends exactly ONE record per
query:

    {"ts_ms": ..., "query": <id>, "fingerprint": <plan fp or null>,
     "ok": ..., "priority": ..., "attempts": ...,
     "wall_ns": ..., "phases": {...},          # closed ledger, sum==wall
     "rows": ..., "batches": ...,              # essential metrics
     "skew": {...},                            # worst exchange skew
     "dispatch": {...}, "shuffle": {...},      # per-query counter deltas
     "ici": {...}, "upload": {...}, "workload": {...},
     "encoded": {...}, "speculation": {...}}

The capsule joins across runs on `fingerprint`
(exec/base.TpuExec.plan_fingerprint — canonical plan identity,
ISSUE 14), which is what makes `tools/history_report.py`'s per-plan
aggregation, `--diff` regression ranking and the profiling advisor
possible without ever re-reading a plan.

Files follow the event-bus rotated-set pattern: per-process
`history-<pid>-<seq>.jsonl`, rotating to `<base>.<n>.jsonl` past
history.maxBytes; creation is lazy, a write failure warns once and
self-uninstalls the store so a full disk never fails a query.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

DEFAULT_DIR = "/tmp/spark_rapids_tpu_history"


class HistoryStore:
    """Append-only JSONL capsule sink (one line per finished query)."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, directory: str, max_bytes: int = 0):
        self.directory = directory or DEFAULT_DIR
        #: rotation threshold (history.maxBytes, the eventLog.maxBytes
        #: pattern): 0 = unbounded
        self.max_bytes = max(0, int(max_bytes))
        with HistoryStore._seq_lock:
            HistoryStore._seq += 1
            seq = HistoryStore._seq
        self._base = os.path.join(self.directory,
                                  f"history-{os.getpid()}-{seq}")
        self._rot = 0
        self._written = 0
        self.path = f"{self._base}.jsonl"
        self._lock = threading.Lock()
        self._file = None
        self._closed = False
        #: capsules appended (tests / bench surface)
        self.records = 0

    def _rotate_locked(self) -> None:
        """Caller holds self._lock (the event-bus rotation contract)."""
        if self._file is not None:
            self._file.close()
            self._file = None
        self._rot += 1
        self._written = 0
        self.path = f"{self._base}.{self._rot}.jsonl"

    def append(self, capsule: Dict[str, Any]) -> None:
        """Write one capsule. Runs inside collect's finally chain, so it
        must NEVER raise: a failure warns once and uninstalls the
        store."""
        if self._closed:
            return
        try:
            line = json.dumps(capsule, separators=(",", ":"), default=str)
            with self._lock:
                if self._closed:
                    return
                if self._file is None:
                    os.makedirs(self.directory, exist_ok=True)
                    # contract: ok lock-blocking-call — the store lock
                    # is the declared LEAF lock and exists precisely to
                    # serialize this lazy open + append; nothing is ever
                    # acquired under it
                    self._file = open(self.path, "a")
                self._file.write(line + "\n")
                self._file.flush()
                self._written += len(line) + 1
                self.records += 1
                if self.max_bytes and self._written >= self.max_bytes:
                    self._rotate_locked()
        except Exception as e:  # noqa: BLE001 — never fail a query
            import logging
            logging.getLogger("spark_rapids_tpu.obs").warning(
                "query history disabled: cannot write %s (%s: %s)",
                self.path, type(e).__name__, e)
            self.close()
            _deactivate(self)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None


_store: Optional[HistoryStore] = None
_store_lock = threading.Lock()


def active_store() -> Optional[HistoryStore]:
    """The configured store, or None — the single pointer check every
    collect pays in disabled mode."""
    return _store


def _deactivate(store: HistoryStore) -> None:
    """Uninstall `store` if still active (write-failure self-removal)."""
    global _store
    with _store_lock:
        if _store is store:
            _store = None


def configure(conf=None) -> Optional[HistoryStore]:
    """(Re)configure from a RapidsConf — process-wide, the event-bus
    semantics: unset history.enabled keeps another session's store; an
    EXPLICIT enabled=false tears it down; enabled with unchanged
    dir+maxBytes keeps the current file open."""
    global _store
    from ..config import (HISTORY_DIR, HISTORY_ENABLED, HISTORY_MAX_BYTES,
                          active_conf)
    conf = conf if conf is not None else active_conf()
    enabled = conf.get(HISTORY_ENABLED)
    with _store_lock:
        if not enabled:
            if HISTORY_ENABLED.key in conf._settings \
                    and _store is not None:
                _store.close()
                _store = None
            return _store
        directory = conf.get(HISTORY_DIR) or DEFAULT_DIR
        max_bytes = max(0, conf.get(HISTORY_MAX_BYTES))
        if _store is not None and _store.directory == directory \
                and _store.max_bytes == max_bytes:
            return _store
        if _store is not None:
            _store.close()
        _store = HistoryStore(directory, max_bytes=max_bytes)
        return _store


def enable(directory: str, max_bytes: int = 0) -> HistoryStore:
    """Conf-free switch-on (bench / tooling entry)."""
    global _store
    with _store_lock:
        if _store is not None:
            _store.close()
        _store = HistoryStore(directory, max_bytes=max_bytes)
        return _store


def reset_history() -> None:
    """Tear down the store (test isolation)."""
    global _store
    with _store_lock:
        if _store is not None:
            _store.close()
        _store = None


# -- capsule assembly --------------------------------------------------------

#: process-counter families snapshotted before a capsule-bound query and
#: diffed after — the per-query shares of the engine's cumulative
#: counters. Keys are capsule field names.
def process_counters() -> Dict[str, Dict[str, int]]:
    """One flat snapshot of every counter family the capsule diffs.
    Read only when a store is active (collect checks active_store()
    first), so disabled-mode collects never pay these imports."""
    from ..columnar import encoded, upload
    from ..exec import adaptive, speculation_shield, workload
    from ..obs import dispatch as obs_dispatch
    from ..shuffle import manager as shuffle_manager
    return {
        "shuffle": shuffle_manager.counters(),
        "ici": shuffle_manager.ici_counters(),
        "upload": upload.counters(),
        "dispatch": obs_dispatch.counters(),
        "workload": workload.counters(),
        "encoded": encoded.counters(),
        "adaptive": adaptive.counters(),
        "speculation": speculation_shield.counters(),
    }


def counters_delta(before: Dict[str, Dict[str, int]],
                   after: Dict[str, Dict[str, int]],
                   ) -> Dict[str, Dict[str, int]]:
    """Per-family {key: after-before}, int keys only (nested/derived
    values in a family snapshot are skipped)."""
    out: Dict[str, Dict[str, int]] = {}
    for fam, cur in after.items():
        base = before.get(fam, {})
        d = {}
        for k, v in cur.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                d[k] = v - base.get(k, 0)
        out[fam] = d
    return out


def worst_skew(stats) -> Optional[Dict[str, Any]]:
    """The worst (highest-ratio) exchange skew summary of a query's
    RuntimeStats, tagged with its op — the advisor's partition-skew
    evidence. None when the query ran no exchange."""
    worst = None
    if stats is None:
        return None
    for st in stats.exchanges():
        sk = st.skew()
        if worst is None or sk["ratio"] > worst["ratio"]:
            worst = dict(sk)
            worst["op"] = f"{st.op}#{st.op_id}"
            worst["partitions"] = st.partitions
    return worst


def build_capsule(*, query_id, fingerprint, ok, priority, attempts,
                  wall_ns, phases, stats, summary, deltas,
                  mesh_devices: int = 1) -> Dict[str, Any]:
    """Assemble the one-line history record. Every field is plain JSON;
    `phases` is the closed ledger dict (sum == wall_ns) or None when
    phase attribution was off."""
    summary = summary or {}
    capsule: Dict[str, Any] = {
        "ts_ms": int(time.time() * 1000),
        "query": query_id,
        "fingerprint": fingerprint,
        "ok": bool(ok),
        "priority": priority,
        "attempts": attempts,
        "wall_ns": int(wall_ns),
        "mesh_devices": int(mesh_devices),
        "phases": phases,
        "rows": summary.get("total.numOutputRows", 0),
        "batches": summary.get("total.numOutputBatches", 0),
        "sem_wait_ns": summary.get("semWaitTimeNs", 0),
        "spill_bytes": (summary.get("spilledDeviceBytes", 0)
                        + summary.get("spilledHostBytes", 0)),
        "skew": worst_skew(stats),
    }
    capsule.update(deltas)
    return capsule
