"""Runtime statistics collection (ISSUE 11 tentpole part 1) — the data
the AQE control loop (ROADMAP item 4) will replan from.

The reference records exactly this class of data: map-output sizes feed
GpuTransitionOverrides/AQE exchange replanning (SURVEY L2), and per-task
GpuTaskMetrics roll up cardinalities. Standalone, every shuffle exchange
records per-map-output and per-partition row/byte distributions into a
per-query `RuntimeStats` object that is reachable DURING execution (from
the governing `QueryContext` — `stats.current()`) and after it via
`QueryProfile.statistics()`.

Cost discipline: nothing here touches the per-row path. Distributions
are built from counts the engine already computes — the PR 9
partition-split program's per-partition count table, the shuffle
writer's partition byte offsets — as fixed-bucket log2 histograms
(`Log2Hist`): O(1) per sample, O(64) per percentile read, no per-row
work and no device syncs. Per-partition byte sums are EXACT (they are
the serializer's own offset table), so `sum(per_partition_bytes) ==
bytes_written` holds to the byte; only the percentile estimates are
bucket-quantized (an upper bound within 2x, tier-1 asserted against
numpy oracles).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

#: fixed bucket count: bucket b holds values v with v.bit_length() == b,
#: i.e. [2^(b-1), 2^b) for b >= 1 and {0} for b == 0 — enough for any
#: int64 byte/row count
N_BUCKETS = 64


class Log2Hist:
    """Fixed-bucket log2 histogram of non-negative integers: O(1) add,
    exact count/sum/min/max, bucket-quantized percentiles. The
    percentile estimate is the UPPER edge of the bucket holding the
    rank-q sample (clamped to the observed max), so for any true
    percentile t >= 1 the estimate lies in [t, 2t) — a one-sided bound
    an AQE consumer can size buffers against safely."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * N_BUCKETS
        self.count = 0
        self.sum = 0
        self.min: Optional[int] = None
        self.max: Optional[int] = None

    def add(self, v: int, n: int = 1) -> None:
        v = int(v)
        if v < 0 or n <= 0:
            return
        self.counts[min(v.bit_length(), N_BUCKETS - 1)] += n
        self.count += n
        self.sum += v * n
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def percentile(self, q: float) -> int:
        """Upper-bound estimate of the q-th percentile (q in [0, 100])
        at bucket resolution; 0 for an empty histogram."""
        if self.count == 0:
            return 0
        rank = max(1, -(-int(q * self.count) // 100))  # ceil(q/100 * n)
        seen = 0
        for b, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                upper = 0 if b == 0 else (1 << b) - 1
                return max(self.min, min(upper, self.max))
        return self.max  # unreachable with count > 0

    def merge(self, other: "Log2Hist") -> None:
        for b in range(N_BUCKETS):
            self.counts[b] += other.counts[b]
        self.count += other.count
        self.sum += other.sum
        if other.min is not None:
            self.min = other.min if self.min is None else min(self.min, other.min)
        if other.max is not None:
            self.max = other.max if self.max is None else max(self.max, other.max)

    def summary(self) -> Dict[str, int]:
        return {"count": self.count, "sum": self.sum,
                "min": self.min or 0, "max": self.max or 0,
                "p50": self.percentile(50), "p95": self.percentile(95)}


def _median(values: Sequence[int]) -> float:
    s = sorted(values)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class ExchangeStats:
    """One exchange execution's runtime statistics: per-map-output and
    per-partition row/byte distributions plus exact per-partition
    totals. Thread-safe (a second exchange in the same plan may record
    from a different pipeline thread)."""

    __slots__ = ("op", "op_id", "partitions", "maps", "rows", "bytes",
                 "map_bytes", "part_rows", "part_bytes",
                 "per_partition_rows", "per_partition_bytes", "_lock")

    def __init__(self, op: str, op_id: Optional[int], partitions: int):
        self.op = op
        self.op_id = op_id
        self.partitions = partitions
        self.maps = 0
        self.rows = 0
        self.bytes = 0
        #: one sample per map output (total serialized bytes)
        self.map_bytes = Log2Hist()
        #: one sample per (map output, partition) — incl. empty
        #: partitions: a skewed key set SHOWS as a mass of zeros plus a
        #: heavy tail, which is the signal AQE splits on
        self.part_rows = Log2Hist()
        self.part_bytes = Log2Hist()
        #: exact cumulative totals across maps (the skew surface)
        self.per_partition_rows = [0] * partitions
        self.per_partition_bytes = [0] * partitions
        self._lock = threading.Lock()

    def record_map(self, rows_per_part: Optional[Sequence[int]],
                   bytes_per_part: Optional[Sequence[int]],
                   total_bytes: int = 0) -> None:
        with self._lock:
            self.maps += 1
            self.bytes += int(total_bytes)
            if total_bytes:
                self.map_bytes.add(int(total_bytes))
            if rows_per_part is not None:
                for p, r in enumerate(rows_per_part):
                    r = int(r)
                    self.rows += r
                    self.per_partition_rows[p] += r
                    self.part_rows.add(r)
            if bytes_per_part is not None:
                for p, b in enumerate(bytes_per_part):
                    b = int(b)
                    self.per_partition_bytes[p] += b
                    self.part_bytes.add(b)

    def skew(self) -> Dict[str, Any]:
        """max/median partition ratio over the exact per-partition
        totals — bytes when the exchange measured them, rows otherwise.
        A zero median (most partitions empty) falls back to the median
        of the NON-empty partitions, so the ratio stays finite and the
        all-in-one-partition case still reads as extreme skew."""
        with self._lock:
            totals = self.per_partition_bytes \
                if any(self.per_partition_bytes) else self.per_partition_rows
            basis = "bytes" if any(self.per_partition_bytes) else "rows"
            totals = list(totals)
        mx = max(totals, default=0)
        med = _median(totals)
        if med == 0:
            med = _median([t for t in totals if t])
        ratio = round(mx / med, 4) if med else 0.0
        return {"basis": basis, "max": mx, "median": med, "ratio": ratio}

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "op": self.op, "op_id": self.op_id,
                "partitions": self.partitions, "maps": self.maps,
                "rows": self.rows, "bytes": self.bytes,
                "map_output_bytes": self.map_bytes.summary(),
                "partition_rows": self.part_rows.summary(),
                "partition_bytes": self.part_bytes.summary(),
                "per_partition_rows": list(self.per_partition_rows),
                "per_partition_bytes": list(self.per_partition_bytes),
            }
        out["skew"] = self.skew()
        return out


class RuntimeStats:
    """Per-query statistics container, created per task attempt by
    `DataFrame._collect_once` and carried on the governing
    `QueryContext` (producer threads adopt the context, so exchange
    writes running behind a pipeline boundary record into the same
    object). Reachable mid-flight via `stats.current()`; snapshotted
    into `QueryProfile.statistics()` at query end."""

    def __init__(self):
        self._lock = threading.Lock()
        self._exchanges: Dict[Any, ExchangeStats] = {}

    def exchange(self, op: str, op_id: Optional[int],
                 partitions: int) -> ExchangeStats:
        key = (op, op_id)
        with self._lock:
            st = self._exchanges.get(key)
            if st is None:
                st = self._exchanges[key] = ExchangeStats(op, op_id,
                                                          partitions)
            return st

    def exchanges(self) -> List[ExchangeStats]:
        with self._lock:
            return list(self._exchanges.values())

    def to_dict(self) -> Dict[str, Any]:
        return {"exchanges": {f"{st.op}#{st.op_id}": st.summary()
                              for st in self.exchanges()}}


def current() -> Optional[RuntimeStats]:
    """The RuntimeStats of this thread's governed query (None outside
    one — a single pointer check, the obs cost discipline)."""
    from ..exec import lifecycle
    ctx = lifecycle.current_context()
    if ctx is None:
        return None
    return ctx.runtime_stats


# ---------------------------------------------------------------------------
# process-wide collector (bench {"statistics": ...} block + TPU rounds)
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global_map_bytes = Log2Hist()
_global_maps = 0
_global_last_skew = 0.0
#: ring of the most recent exchanges' skew summaries — the
#: TpuSession.health() "stats" surface (ISSUE 19): operators see skew
#: pressure per exchange without reading the event log
_RECENT_MAX = 8
_global_recent: List[Dict[str, Any]] = []


class ExchangeRecorder:
    """The write-path hook the exchanges call once per map task: fans
    each record into the per-query RuntimeStats (when a governed query
    is running on this thread) AND the process-wide collector that
    bench.py deltas. `finish()` returns the exchange's summary (for the
    `exchange_stats` event) and publishes the skew ratio."""

    __slots__ = ("_per_query", "_local")

    def __init__(self, op: str, op_id: Optional[int], partitions: int):
        rs = current()
        self._per_query = rs.exchange(op, op_id, partitions) \
            if rs is not None else None
        self._local = ExchangeStats(op, op_id, partitions)

    def record_map(self, rows_per_part, bytes_per_part,
                   total_bytes: int = 0) -> None:
        global _global_maps
        self._local.record_map(rows_per_part, bytes_per_part, total_bytes)
        if self._per_query is not None:
            self._per_query.record_map(rows_per_part, bytes_per_part,
                                       total_bytes)
        with _global_lock:
            _global_maps += 1
            if total_bytes:
                _global_map_bytes.add(int(total_bytes))

    def partition_bytes(self) -> Optional[List[int]]:
        """EXACT per-partition byte totals measured so far (the
        serializer's own offset sums), or None when no map recorded
        bytes — the adaptive replanner's evidence (ISSUE 19). A copy:
        safe to consult while a hybrid drain still records."""
        st = self._local
        with st._lock:
            if st.maps == 0 or not any(st.per_partition_bytes):
                return None
            return list(st.per_partition_bytes)

    def total_bytes(self) -> int:
        """Total serialized bytes measured so far."""
        with self._local._lock:
            return self._local.bytes

    def finish(self) -> Optional[Dict[str, Any]]:
        global _global_last_skew
        if self._local.maps == 0:
            return None
        out = self._local.summary()
        with _global_lock:
            _global_last_skew = out["skew"]["ratio"]
            sk = out["skew"]
            _global_recent.append({
                "op": f"{out['op']}#{out['op_id']}",
                "partitions": out["partitions"], "maps": out["maps"],
                "bytes": out["bytes"], "basis": sk["basis"],
                "max": sk["max"], "median": sk["median"],
                "ratio": sk["ratio"]})
            del _global_recent[:-_RECENT_MAX]
        return out

    def finish_and_emit(self) -> Optional[Dict[str, Any]]:
        """finish() plus THE one `exchange_stats` event — both exchange
        lanes emit through here, so the record schema cannot drift
        between them."""
        out = self.finish()
        if out is not None:
            from . import events as obs_events
            sk = out["skew"]
            obs_events.emit(
                "exchange_stats", exec=out["op"], op_id=out["op_id"],
                partitions=out["partitions"], maps=out["maps"],
                rows=out["rows"], bytes=out["bytes"],
                skew_ratio=sk["ratio"], skew_basis=sk["basis"],
                max_partition=sk["max"], median_partition=sk["median"],
                p95_partition_bytes=out["partition_bytes"]["p95"],
                p95_map_output_bytes=out["map_output_bytes"]["p95"])
        return out


def counters() -> Dict[str, int]:
    """Flat process-cumulative statistics counters (the chaos-delta
    pattern: bench.py reports per-record deltas of `maps`/`bytes`;
    `p95_map_output_bytes` and `skew_ratio_x1000` are point-in-time
    reads a round interprets directly, not deltas)."""
    with _global_lock:
        return {
            "maps": _global_maps,
            "bytes": _global_map_bytes.sum,
            "p95_map_output_bytes": _global_map_bytes.percentile(95),
            "skew_ratio_x1000": int(_global_last_skew * 1000),
        }


def health_section() -> Dict[str, Any]:
    """The TpuSession.health() "stats" block (ISSUE 19 satellite):
    recent per-exchange max/median skew plus the adaptive decisions
    taken — the skew-pressure surface operators read instead of the
    event log."""
    from ..exec import adaptive
    with _global_lock:
        recent = [dict(r) for r in _global_recent]
        last = _global_last_skew
    return {"recent_exchanges": recent,
            "last_skew_ratio": last,
            "adaptive": adaptive.counters()}


def reset_stats() -> None:
    """Test isolation for the process-wide collector."""
    global _global_map_bytes, _global_maps, _global_last_skew
    with _global_lock:
        _global_map_bytes = Log2Hist()
        _global_maps = 0
        _global_last_skew = 0.0
        del _global_recent[:]
