"""Process-wide dispatch ledger (ISSUE 13 tentpole part 1): THE
chokepoint every engine jit entry point routes through.

The engine dispatches many small jitted programs per batch — exactly the
per-operator interpretation overhead whole-stage compilation (ROADMAP
open item 2) must collapse — yet until this plane existed nothing
recorded how many programs run, what tracing/compiling them costs, or
why a program re-traces. `instrument()` replaces bare `jax.jit(...)` at
every entry point (exec operators, exchange split, upload unpack,
transfer pack, the Pallas kernel families) and records, per compiled
program:

  * a stable program key — (owning exec/family label, arg-shape
    bucket, backend platform) — the log2 bucket discipline of
    ops/pallas_tier.shape_bucket, so one key covers every batch that
    compiles to the same program shape;
  * dispatch count, first-trace vs cache-hit discriminated;
  * trace-ns (the Python tracing of the body, measured inside the
    traced function — it only runs when jax actually traces) and
    compile-ns (wall-clock of the compiling dispatch, inclusive of
    trace + lowering + compilation);
  * donated vs retained argument bytes (from the tracer avals at trace
    time, against the site's `donate_argnums`).

Per-exec attribution mirrors the GatherTracker pattern: a site built
with `owner=<exec>` adds to that exec's `numDispatches` /
`compileTimeNs` canonical metrics on every call — dispatches are
counted at CALL time, so jit cache hits never zero the counts and
repeated collects replay identical per-stage dispatches/batch.
Module-level program sites (upload unpack, coalesce concat) attribute
through the thread-local `metric_scope` sink instead.

Each fresh trace emits a `program_compile` event (MODERATE), and the
recompile-storm detector emits `recompile_storm` (ESSENTIAL) when one
program key traces more than `spark.rapids.tpu.dispatch.storm.traces`
times inside `spark.rapids.tpu.dispatch.storm.windowMs` — the
shape-bucket-churn failure mode that silently destroys TPU throughput
(every batch a new exact shape, every dispatch a fresh XLA compile).

Cost discipline: `spark.rapids.tpu.dispatch.ledger.enabled` defaults
ON (the ledger is host-side bookkeeping, ~one dict update per program
dispatch — noise against jit dispatch overhead); explicitly false =
`active_ledger()` None and every instrumented site pays exactly one
pointer check before calling straight into its jitted function.
Results are byte-identical either way — the wrapper never touches the
computation.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DispatchLedger", "InstrumentedJit", "instrument", "active_ledger",
    "configure", "reset_dispatch_ledger", "counters", "programs",
    "health_section", "metric_scope", "site_cache_counters",
    "reset_site_cache",
]

#: canonical per-exec metric names (exec/base.py re-exports them into
#: CANONICAL_METRICS; literals here so obs/ never imports exec/)
NUM_DISPATCHES = "numDispatches"
COMPILE_TIME = "compileTimeNs"

_tls = threading.local()

#: backend platform, resolved once (it cannot change in-process)
_platform_cache: Optional[str] = None


def _platform() -> str:
    global _platform_cache
    if _platform_cache is None:
        import jax
        _platform_cache = jax.default_backend()
    return _platform_cache


def _shape_bucket(shape) -> Tuple[int, ...]:
    from ..ops.pallas_tier import shape_bucket
    return shape_bucket(shape)


def _args_bucket(args, kwargs) -> Tuple:
    """Stable arg-shape bucket: log2-bucketed dims + dtype per array
    leaf, hashable statics verbatim. Long static pytrees (the upload
    unpack's nested column specs) fold into one hash so keys stay
    small."""
    from jax.tree_util import tree_leaves
    parts: List[Any] = []
    for leaf in tree_leaves((args, kwargs)):
        shp = getattr(leaf, "shape", None)
        if shp is not None:
            dt = getattr(leaf, "dtype", None)
            parts.append((_shape_bucket(shp),
                          dt.name if dt is not None else None))
        elif isinstance(leaf, (int, float, bool, str, bytes,
                               type(None))):
            parts.append(leaf)
        else:
            try:
                parts.append(hash(leaf) & 0xFFFFFFFF)
            except TypeError:
                parts.append(type(leaf).__name__)
    if len(parts) > 12:
        parts = parts[:8] + [hash(tuple(parts[8:])) & 0xFFFFFFFF]
    return tuple(parts)


class _Pending:
    """Per-call trace capture: the traced function body sets these when
    jax actually traces (on a cache hit it never runs)."""

    __slots__ = ("traced", "trace_ns", "donated", "retained", "depth")

    def __init__(self):
        self.traced = False
        self.trace_ns = 0
        self.donated = 0
        self.retained = 0
        #: nesting depth of instrumented bodies under this call — only
        #: the outermost frame records time/bytes (an inner instrumented
        #: program inlined into the outer trace is part of it)
        self.depth = 0


class ProgramStats:
    """Cumulative ledger record of one compiled program key."""

    # counters accumulate; donated/retained_bytes hold the LATEST
    # trace's aval sizes (a shape property, not a running total)
    __slots__ = ("label", "bucket", "platform", "dispatches", "traces",
                 "cache_hits", "compile_ns", "trace_ns", "donated_bytes",
                 "retained_bytes", "trace_times", "storms",
                 "storm_open_until")

    def __init__(self, label: str, bucket, platform: str):
        self.label = label
        self.bucket = bucket
        self.platform = platform
        self.dispatches = 0
        self.traces = 0
        self.cache_hits = 0
        self.compile_ns = 0
        self.trace_ns = 0
        self.donated_bytes = 0
        self.retained_bytes = 0
        #: recent trace timestamps (ns) for the storm window
        self.trace_times: deque = deque()
        self.storms = 0
        #: suppress repeat storm events until the window rolls past
        self.storm_open_until = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"label": self.label, "bucket": list(self.bucket),
                "platform": self.platform,
                "dispatches": self.dispatches, "traces": self.traces,
                "cache_hits": self.cache_hits,
                "compile_ns": self.compile_ns,
                "trace_ns": self.trace_ns,
                "donated_bytes": self.donated_bytes,
                "retained_bytes": self.retained_bytes,
                "storms": self.storms}


class DispatchLedger:
    """Process-wide program registry. All mutation happens under one
    leaf lock; events are buffered and emitted after it drops (the
    lock-blocking-call contract)."""

    def __init__(self, storm_traces: int = 8,
                 storm_window_ms: int = 10_000, timeout_ms: int = 0):
        self.storm_traces = max(1, int(storm_traces))
        self.storm_window_ms = max(1, int(storm_window_ms))
        #: dispatch hang bound (ISSUE 20): > 0 routes every dispatch
        #: through a watchdog-timed helper thread that also blocks
        #: until the program's outputs are ready — a wedged device
        #: program becomes a transient DispatchTimeoutError instead of
        #: hanging the process. 0 (the default) = the plain inline path.
        self.timeout_ms = max(0, int(timeout_ms))
        self._lock = threading.Lock()
        self._programs: Dict[Tuple, ProgramStats] = {}
        self._dispatches = 0
        self._traces = 0
        self._cache_hits = 0
        self._compile_ns = 0
        self._trace_ns = 0
        self._storms = 0

    # -- the per-call accounting (InstrumentedJit.__call__ fast path) --
    def dispatch(self, site: "InstrumentedJit", args, kwargs):
        bucket = _args_bucket(args, kwargs)
        key = (site.label, bucket, _platform())
        # a bucket THIS site never traced before is a NEW program, not
        # churn: ledger keys aggregate per label family, so distinct
        # program sites (ExpandExec's per-projection jits, a fresh exec
        # instance per collect) legitimately share a key — only a
        # re-trace within ONE site's own jit cache is the shape-churn
        # signal the storm detector (and the event's `first` flag)
        # discriminate on
        site_first = bucket not in site._seen_buckets
        pend = _Pending()
        t0 = time.perf_counter_ns()
        try:
            if self.timeout_ms > 0:
                return _timed_dispatch(site, args, kwargs, pend,
                                       self.timeout_ms)
            _tls.pending = pend
            try:
                return site._jit(*args, **kwargs)
            finally:
                _tls.pending = None
        finally:
            if pend.traced and site_first:
                site._seen_buckets.add(bucket)
            self._account(site, key, pend, site_first,
                          time.perf_counter_ns() - t0)

    def _account(self, site, key, pend: _Pending, site_first: bool,
                 wall_ns: int) -> None:
        out_events = []
        with self._lock:
            prog = self._programs.get(key)
            if prog is None:
                prog = self._programs[key] = ProgramStats(*key)
            prog.dispatches += 1
            self._dispatches += 1
            if pend.traced:
                prog.traces += 1
                prog.compile_ns += wall_ns
                prog.trace_ns += pend.trace_ns
                # arg bytes are a per-program-shape PROPERTY, not a
                # counter: the latest trace's aval sizes (re-traces
                # inside one bucket differ only marginally)
                prog.donated_bytes = pend.donated
                prog.retained_bytes = pend.retained
                self._traces += 1
                self._compile_ns += wall_ns
                self._trace_ns += pend.trace_ns
                out_events.append((
                    "program_compile",
                    dict(label=prog.label, bucket=list(prog.bucket),
                         platform=prog.platform,
                         compile_ns=wall_ns, trace_ns=pend.trace_ns,
                         first=site_first, traces=prog.traces,
                         donated_bytes=pend.donated,
                         retained_bytes=pend.retained)))
                if not site_first:
                    storm = self._note_trace_locked(prog)
                    if storm is not None:
                        out_events.append(storm)
            else:
                prog.cache_hits += 1
                self._cache_hits += 1
        # metric attribution outside the lock: TpuMetric.add is a plain
        # int accumulate on the dispatching thread
        from . import phase as obs_phase
        obs_phase.note_dispatch(wall_ns, pend.traced)
        metrics = site._owner.metrics if site._owner is not None else None
        if metrics is not None:
            m = metrics.get(NUM_DISPATCHES)
            if m is not None:
                m.add(1)
                if pend.traced:
                    tm = metrics.get(COMPILE_TIME)
                    if tm is not None:
                        tm.add(wall_ns)
        else:
            sink = getattr(_tls, "sink", None)
            if sink is not None:
                sink[0].add(1)
                if pend.traced and sink[1] is not None:
                    sink[1].add(wall_ns)
        if out_events:
            from . import events as obs_events
            if obs_events.active_bus() is not None:
                for kind, fields in out_events:
                    obs_events.emit(kind, **fields)

    def _note_trace_locked(self, prog: ProgramStats):
        """Caller holds self._lock. Slide the storm window; past the
        conf'd trace count one `recompile_storm` fires and the key goes
        quiet until the window rolls past (a storm is one incident, not
        one event per churning batch)."""
        now = time.monotonic_ns()
        window_ns = self.storm_window_ms * 1_000_000
        prog.trace_times.append(now)
        while prog.trace_times and prog.trace_times[0] < now - window_ns:
            prog.trace_times.popleft()
        if len(prog.trace_times) < self.storm_traces \
                or now < prog.storm_open_until:
            return None
        prog.storms += 1
        self._storms += 1
        prog.storm_open_until = now + window_ns
        return ("recompile_storm",
                dict(label=prog.label, bucket=list(prog.bucket),
                     platform=prog.platform,
                     traces_in_window=len(prog.trace_times),
                     window_ms=self.storm_window_ms,
                     threshold=self.storm_traces,
                     compile_ns=prog.compile_ns))

    # -- read surfaces ------------------------------------------------
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"programs": len(self._programs),
                    "dispatches": self._dispatches,
                    "traces": self._traces,
                    "cache_hits": self._cache_hits,
                    "compile_ns": self._compile_ns,
                    "trace_ns": self._trace_ns,
                    "storms": self._storms}

    def programs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [p.to_dict() for p in self._programs.values()]


def _timed_dispatch(site: "InstrumentedJit", args, kwargs,
                    pend: _Pending, timeout_ms: int):
    """Hang-bounded dispatch (ISSUE 20): the program runs — and is
    blocked until ready, so a wedged device execution cannot hide
    behind async dispatch — on a watchdog-timed helper thread. The
    helper adopts the caller's pending frame (jax traces on the calling
    thread, which is the helper here); the breaker domain comes from
    the thread-local override so the ICI collective seam books its
    timeouts against `ici_exchange` (exec/speculation_shield)."""
    from ..exec import speculation_shield
    domain = speculation_shield.current_dispatch_domain()

    def run():
        _tls.pending = pend
        try:
            out = site._jit(*args, **kwargs)
            import jax
            jax.block_until_ready(out)
            return out
        finally:
            _tls.pending = None

    return speculation_shield.timed_call(run, timeout_ms, domain,
                                         site.label)


_ledger: Optional[DispatchLedger] = DispatchLedger()
_ledger_lock = threading.Lock()


def active_ledger() -> Optional[DispatchLedger]:
    """The process ledger, or None when disabled — instrumented sites
    check this pointer once per dispatch (the entire off-mode cost)."""
    return _ledger


def configure(conf=None) -> Optional[DispatchLedger]:
    """(Re)configure from a RapidsConf (None = the thread's active
    conf). Like the event bus the ledger is PROCESS-wide; unlike it the
    conf defaults ON, so a default session (re)creates the ledger and
    only an explicit dispatch.ledger.enabled=false tears it down.
    Storm thresholds are re-read here — never per dispatch."""
    global _ledger
    from ..config import (DISPATCH_LEDGER_ENABLED, DISPATCH_STORM_TRACES,
                          DISPATCH_STORM_WINDOW_MS, DISPATCH_TIMEOUT_MS,
                          active_conf)
    conf = conf if conf is not None else active_conf()
    enabled = conf.get(DISPATCH_LEDGER_ENABLED)
    traces = conf.get(DISPATCH_STORM_TRACES)
    window = conf.get(DISPATCH_STORM_WINDOW_MS)
    timeout = conf.get(DISPATCH_TIMEOUT_MS)
    with _ledger_lock:
        if not enabled:
            _ledger = None
            return None
        if _ledger is None:
            _ledger = DispatchLedger(traces, window, timeout)
        else:
            _ledger.storm_traces = max(1, int(traces))
            _ledger.storm_window_ms = max(1, int(window))
            _ledger.timeout_ms = max(0, int(timeout))
        return _ledger


def reset_dispatch_ledger() -> None:
    """Fresh default-enabled ledger (test isolation). The program-site
    cache resets with it: the two surfaces are one plane — a test that
    wants fresh-trace accounting (program_compile events, trace
    counters) must not inherit another test's already-traced sites."""
    global _ledger
    with _ledger_lock:
        _ledger = DispatchLedger()
    reset_site_cache()


def counters() -> Dict[str, int]:
    led = _ledger
    if led is None:
        return {"programs": 0, "dispatches": 0, "traces": 0,
                "cache_hits": 0, "compile_ns": 0, "trace_ns": 0,
                "storms": 0}
    return led.counters()


def programs() -> List[Dict[str, Any]]:
    led = _ledger
    return led.programs() if led is not None else []


def health_section() -> Dict[str, Any]:
    """`TpuSession.health()["dispatch"]`: enabled flag + the cumulative
    counters + the worst compile-cost programs."""
    led = _ledger
    out: Dict[str, Any] = {"enabled": led is not None}
    out.update(counters())
    if led is not None:
        progs = led.programs()
        progs.sort(key=lambda p: -p["compile_ns"])
        out["top_programs"] = progs[:5]
    return out


@contextmanager
def metric_scope(num_metric, time_metric=None):
    """Attribute module-level program dispatches inside the with-block
    to an exec's (numDispatches, compileTimeNs) metric pair — the
    upload/coalesce sites have no owning exec instance at definition
    time (the upload.metric_sink shape). Owner-bound sites ignore the
    sink."""
    prev = getattr(_tls, "sink", None)
    _tls.sink = (num_metric, time_metric)
    try:
        yield
    finally:
        _tls.sink = prev


# ---------------------------------------------------------------------------
# plan-fingerprint program-site cache (ISSUE 14): every DataFrame.
# collect() rebuilds its exec tree, so per-instance jit wrappers used to
# recompile the WHOLE plan per collect (the PR 13 finding: ~1.9s/collect
# on the scaled q1 CPU lane). Sites built with a `cache_key` — the
# owning exec's canonical plan-subtree fingerprint — are process-cached
# per (label, cache_key): a semantically identical exec instance reuses
# the SAME InstrumentedJit, so its dispatches ride the existing jax jit
# cache (the ledger records them as cache hits, zero fresh traces). The
# fingerprint must capture everything the trace depends on (expression
# semantics, schemas, trace-affecting conf values, platform) — that
# contract lives in exec/stage_compiler.plan_fingerprint.
# ---------------------------------------------------------------------------

_site_cache_lock = threading.Lock()
#: (label, cache_key) -> InstrumentedJit, LRU-ordered (dict order)
_site_cache: "Dict[Tuple[str, Any], InstrumentedJit]" = {}
_site_cache_hits = 0
_site_cache_misses = 0


def _site_cache_max() -> int:
    try:
        from ..config import STAGE_PROGRAM_CACHE_ENTRIES, active_conf
        return max(0, int(active_conf().get(STAGE_PROGRAM_CACHE_ENTRIES)))
    except Exception:  # noqa: BLE001 — conf unavailable early
        return 512


def _cached_site(fn, label: str, owner, cache_key, jit_kwargs):
    global _site_cache_hits, _site_cache_misses
    limit = _site_cache_max()
    if limit <= 0:
        return InstrumentedJit(fn, label, owner=owner, **jit_kwargs)
    key = (label, cache_key)
    with _site_cache_lock:
        site = _site_cache.pop(key, None)
        if site is not None:
            _site_cache[key] = site  # re-append: most recently used
            _site_cache_hits += 1
    if site is not None:
        site.rebind(owner)
        return site
    site = InstrumentedJit(fn, label, owner=owner, **jit_kwargs)
    with _site_cache_lock:
        _site_cache_misses += 1
        _site_cache[key] = site
        while len(_site_cache) > limit:
            _site_cache.pop(next(iter(_site_cache)))
    return site


def site_cache_counters() -> Dict[str, int]:
    """bench `{"stage"}` block + tests: program-site cache activity."""
    with _site_cache_lock:
        return {"sites": len(_site_cache), "hits": _site_cache_hits,
                "misses": _site_cache_misses}


def reset_site_cache() -> None:
    """Drop every cached program site (test isolation; already-built
    exec trees keep the sites they hold — only NEW lookups re-trace)."""
    global _site_cache_hits, _site_cache_misses
    with _site_cache_lock:
        _site_cache.clear()
        _site_cache_hits = 0
        _site_cache_misses = 0


def _trace_state_clean() -> bool:
    """Resolved once — the per-dispatch path must not pay import
    machinery (jax is necessarily imported before any site is built)."""
    global _trace_state_clean
    import jax.core
    _trace_state_clean = jax.core.trace_state_clean
    return _trace_state_clean()


class InstrumentedJit:
    """`jax.jit` plus ledger accounting — the chokepoint wrapper.

    Call-time behavior: with the ledger off, one pointer check then the
    bare jitted call. Nested calls — an instrumented program traced
    inline into another program's trace (the murmur3 kernels inside an
    exec's update kernel), or an abstract evaluation like
    `jax.eval_shape` — pass straight through: they are not device
    dispatches, and counting them would double-book the outer trace."""

    # __weakref__: jax.eval_shape weakly caches the callable it is
    # given — an un-weakref-able wrapper would reject abstract eval
    __slots__ = ("label", "_owner", "_jit", "_donate", "_seen_buckets",
                 "__weakref__")

    def __init__(self, fn, label: str, owner=None, **jit_kwargs):
        import jax
        self.label = label
        #: owning exec instance (per-exec metric attribution + the
        #: QueryProfile dispatch summary walk); None for module sites
        self._owner = owner
        donate = jit_kwargs.get("donate_argnums", ()) or ()
        self._donate = tuple(donate) if isinstance(
            donate, (tuple, list)) else (donate,)
        #: arg-shape buckets THIS site has traced: discriminates a new
        #: program (first trace of a bucket here) from shape churn (a
        #: re-trace the site's own jit cache rejected)
        self._seen_buckets = set()

        @functools.wraps(fn)
        def _traced(*a, **k):
            pend = getattr(_tls, "pending", None)
            if pend is None:
                return fn(*a, **k)
            pend.traced = True
            pend.depth += 1
            t0 = time.perf_counter_ns()
            try:
                out = fn(*a, **k)
            finally:
                pend.depth -= 1
            if pend.depth == 0:
                pend.trace_ns += time.perf_counter_ns() - t0
                pend.donated, pend.retained = self._arg_bytes(a, k)
            return out

        self._jit = jax.jit(_traced, **jit_kwargs)
        if owner is not None:
            # per-exec site registry: QueryProfile._node records these
            # labels so dispatch_summary() joins ledger programs to
            # plan stages by EXACT label (subclass-safe)
            owner.__dict__.setdefault("_dispatch_sites", []).append(self)

    def rebind(self, owner) -> None:
        """Re-point metric attribution at a new owning exec — the
        program-site cache hands one compiled site to every
        semantically identical exec instance (one per collect), and
        each execution's numDispatches/compileTimeNs must land on the
        CURRENTLY executing exec, not the instance that first traced
        the program. Concurrent identical plans (bench --concurrency)
        share the site: their per-exec metric split follows the latest
        rebind — the process ledger stays exact either way."""
        if owner is None or owner is self._owner:
            return
        self._owner = owner
        sites = owner.__dict__.setdefault("_dispatch_sites", [])
        if self not in sites:
            sites.append(self)

    def _arg_bytes(self, args, kwargs) -> Tuple[int, int]:
        """Donated vs retained bytes from the trace-time avals (shapes
        are concrete there; no device data is touched)."""
        from jax.tree_util import tree_leaves
        donated = retained = 0
        for i, a in enumerate(args):
            total = 0
            for leaf in tree_leaves(a):
                shp = getattr(leaf, "shape", None)
                dt = getattr(leaf, "dtype", None)
                if shp is None or dt is None:
                    continue
                n = 1
                for d in shp:
                    n *= int(d)
                total += n * dt.itemsize
            if i in self._donate:
                donated += total
            else:
                retained += total
        for a in kwargs.values():
            for leaf in tree_leaves(a):
                shp = getattr(leaf, "shape", None)
                dt = getattr(leaf, "dtype", None)
                if shp is not None and dt is not None:
                    n = 1
                    for d in shp:
                        n *= int(d)
                    retained += n * dt.itemsize
        return donated, retained

    def __call__(self, *args, **kwargs):
        led = _ledger
        if led is None:
            return self._jit(*args, **kwargs)
        if getattr(_tls, "pending", None) is not None:
            # nested under another instrumented dispatch's trace
            return self._jit(*args, **kwargs)
        if not _trace_state_clean():
            # traced inline into an un-instrumented outer program, or
            # abstractly evaluated (eval_shape) — not a device dispatch
            return self._jit(*args, **kwargs)
        return led.dispatch(self, args, kwargs)


def instrument(fn=None, *, label: str, owner=None, cache_key=None,
               **jit_kwargs):
    """THE jit entry point: `instrument(fn, label=...)` replaces
    `jax.jit(fn)` everywhere the engine compiles a program (the
    dispatch-ledger contract rule holds every `jax.jit`/`pallas_call`
    site in the package to this chokepoint or a justified suppression).
    Usable as a decorator factory: `@instrument(label=...)`.

    `cache_key` (ISSUE 14): a hashable canonical plan-subtree
    fingerprint. When given, the site is served from the process-wide
    program cache — a semantically identical exec built by a later
    collect() reuses the SAME compiled programs (ledger cache hits,
    zero fresh traces) with metric attribution rebound to the new
    owner. The caller owns the soundness contract: equal fingerprints
    MUST imply byte-identical traces."""
    if fn is None:
        if cache_key is not None:
            return lambda f: _cached_site(f, label, owner, cache_key,
                                          jit_kwargs)
        return lambda f: InstrumentedJit(f, label, owner=owner,
                                         **jit_kwargs)
    if cache_key is not None:
        return _cached_site(fn, label, owner, cache_key, jit_kwargs)
    return InstrumentedJit(fn, label, owner=owner, **jit_kwargs)
