"""Per-query resource-attribution telemetry (ISSUE 11 tentpole part 2):
a process-wide metrics registry — push counters, sampled gauges, and
bounded ring-buffer time series — behind
`spark.rapids.tpu.telemetry.{enabled,intervalMs,historySize}`.

Sampling is PULL-based: the engine's existing process counters (catalog
tiers + per-owner HBM attribution, upload/transfer link bytes,
semaphore wait, workload queue, breaker states, spill volumes) are read
by a periodic sampler thread (named `telemetry-sampler`, covered by the
zero-leaked-threads assertions), so instrumented code pays nothing new.
Push counters (`telemetry.add`) exist for seams with no process counter
of their own; disabled (the default) they cost exactly one module
pointer check per update site — the PR 2 event-bus discipline.

Each sample lands in every series' ring buffer and, when the event bus
is up, flushes as one `telemetry_sample` JSONL record — the periodic
exporter. `tools/telemetry_export.py` renders a log's samples as
Prometheus text format for scrape-based monitoring of long soaks.

The series name registry (`SERIES`) is lint-checked against the
docs/observability.md telemetry table (tests/test_docs_lint.py), the
EVENT_LEVELS/CANONICAL_METRICS pattern.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

#: sampled series name -> meaning. Every key appears in each sample and
#: in docs/observability.md's telemetry table (lint-asserted). Per-owner
#: HBM attribution rides the sample as the structured `hbm_by_owner`
#: field (a map, not a scalar series).
SERIES: Dict[str, str] = {
    "hbm.device_bytes": "catalog bytes resident on the DEVICE tier",
    "hbm.host_bytes": "catalog bytes resident on the HOST tier",
    "budget.used_bytes": "HBM budget manager's reserved bytes",
    "link.h2d_bytes": "cumulative host->device upload bytes",
    "link.d2h_bytes": "cumulative device->host packed-fetch bytes",
    "spill.device_bytes": "cumulative bytes spilled off the device tier",
    "spill.host_bytes": "cumulative bytes spilled host->disk",
    "sem.wait_ns": "cumulative admission-semaphore wait",
    "workload.queue_depth": "queries waiting in the admission queue",
    "workload.admitted": "queries currently admitted",
    "queries.active": "registered (governed) query contexts",
    "breakers.open": "circuit-breaker domains not closed",
    "ici.rounds": "cumulative ICI all-to-all exchange rounds",
    "ici.bytes": "cumulative bytes moved over the ICI shuffle lane",
    "ici.fallbacks": "ICI exchanges degraded to the host shuffle lane",
}

#: per-priority-class latency ring depth (queries kept for the SLO
#: percentile surface). Bounded so a soak's registry stays O(1).
_SLO_RING = 512

#: percentile points health()["slo"] reports, in order
_SLO_PCTS = (50, 95, 99)


def _percentile(sorted_ns, pct: int) -> int:
    """Nearest-rank percentile over an already-sorted list (exact for
    the bounded ring sizes we keep — no interpolation surprises in
    golden tests)."""
    n = len(sorted_ns)
    if n == 0:
        return 0
    rank = max(1, -(-pct * n // 100))  # ceil(pct/100 * n), min 1
    return sorted_ns[min(n, rank) - 1]


class TelemetryRegistry:
    """Counters + ring-buffer series + the sampler thread. One instance
    per enabled process (module singleton, `active_registry()`)."""

    def __init__(self, interval_ms: int, history: int):
        self.interval_ms = max(10, int(interval_ms))
        self.history = max(1, int(history))
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._series: Dict[str, deque] = {
            name: deque(maxlen=self.history) for name in SERIES}
        #: per-priority-class query wall-clock ring (ISSUE 17): the
        #: health()["slo"] percentile surface. Keys are priority-class
        #: names ("interactive"/"batch"), values bounded deques of ns.
        self._latency: Dict[str, deque] = {}
        self._queries_seen: Dict[str, int] = {}
        self.samples_taken = 0
        self.writes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- push counters -----------------------------------------------------
    def add(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta
            self.writes += 1

    def counter_values(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # -- SLO latency ring (ISSUE 17) ---------------------------------------
    def note_query_latency(self, priority: str, wall_ns: int) -> None:
        """Record one finished governed query's wall-clock under its
        priority class. Bounded ring per class; percentiles are computed
        lazily on read (slo_snapshot), so the per-query cost is one
        append under the registry lock."""
        with self._lock:
            ring = self._latency.get(priority)
            if ring is None:
                ring = self._latency[priority] = deque(maxlen=_SLO_RING)
            ring.append(int(wall_ns))
            self._queries_seen[priority] = \
                self._queries_seen.get(priority, 0) + 1
            self.writes += 1

    def slo_snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-priority-class p50/p95/p99 wall-clock (ns) over the last
        <= _SLO_RING finished queries, plus the all-time count. Empty
        dict when no governed query has finished yet."""
        with self._lock:
            rings = {p: sorted(r) for p, r in self._latency.items()}
            seen = dict(self._queries_seen)
        out: Dict[str, Dict[str, int]] = {}
        for p, xs in rings.items():
            row = {f"p{q}_ns": _percentile(xs, q) for q in _SLO_PCTS}
            row["window"] = len(xs)
            row["queries"] = seen.get(p, 0)
            out[p] = row
        return out

    # -- sampling ----------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        """Take one snapshot of every gauge source, append it to the
        ring buffers, and flush it to the event bus (when one is up) as
        a `telemetry_sample` record. Also the on-demand entry for
        health()/tests — the sampler thread just calls this on a
        timer."""
        snap = collect_sample()
        with self._lock:
            self.samples_taken += 1
            self.writes += 1
            for name in SERIES:
                self._series[name].append((snap["ts_ms"], snap[name]))
            snap["counters"] = dict(self._counters)
        from . import events as obs_events
        obs_events.emit("telemetry_sample", **snap)
        return snap

    def series(self, name: str) -> list:
        with self._lock:
            return list(self._series[name])

    def last_sample(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not self._series["hbm.device_bytes"]:
                return None
            return {name: self._series[name][-1][1] for name in SERIES}

    # -- sampler thread ----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        # contract: ok thread-adopt — process-wide sampler: it reads
        # global gauges and emits unattributed telemetry_sample records
        # by design; there is no per-query context to adopt
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-sampler", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — a sampling failure must
                pass           # never kill the exporter (or the engine)

    def shutdown(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


def collect_sample() -> Dict[str, Any]:
    """One pull over every gauge source. All reads are lock-light
    snapshots the owning modules already expose; the per-owner HBM
    attribution and the tier totals come from ONE catalog lock pass
    (memory/catalog.bytes_by_owner), so `sum(hbm_by_owner.device) ==
    hbm.device_bytes` holds exactly at every tick."""
    from ..columnar import transfer, upload
    from ..exec import lifecycle, workload
    from ..memory.budget import memory_budget
    from ..memory.catalog import buffer_catalog
    from ..memory.semaphore import tpu_semaphore

    from ..shuffle import manager as shuffle_manager

    cat = buffer_catalog()
    dev_by_owner, host_by_owner, dev_total, host_total = \
        cat.bytes_by_owner()
    up = upload.counters()
    d2h = transfer.counters()
    wl = workload.snapshot()
    ici = shuffle_manager.ici_counters()
    return {
        "ts_ms": int(time.time() * 1000),
        "hbm.device_bytes": dev_total,
        "hbm.host_bytes": host_total,
        "budget.used_bytes": memory_budget().used,
        "link.h2d_bytes": up["bytes"],
        "link.d2h_bytes": d2h["d2h_bytes"],
        "spill.device_bytes": cat.spilled_device_bytes,
        "spill.host_bytes": cat.spilled_host_bytes,
        "sem.wait_ns": tpu_semaphore().total_wait_ns,
        "workload.queue_depth": wl["queue_depth"],
        "workload.admitted": wl["admitted"],
        "queries.active": len(lifecycle.active_query_ids()),
        "breakers.open": len(lifecycle.open_breakers()),
        "ici.rounds": ici["rounds"],
        "ici.bytes": ici["bytes"],
        "ici.fallbacks": ici["fallbacks"],
        "hbm_by_owner": {"device": dev_by_owner, "host": host_by_owner},
    }


# ---------------------------------------------------------------------------
# module singleton (the events.py active-bus pattern)
# ---------------------------------------------------------------------------

_registry: Optional[TelemetryRegistry] = None
_registry_lock = threading.Lock()


def active_registry() -> Optional[TelemetryRegistry]:
    """The enabled registry, or None — the single pointer check every
    push site pays in disabled mode."""
    return _registry


def add(name: str, delta: int = 1) -> None:
    """Push-counter update (cold paths / seams without their own
    process counter). One pointer check when telemetry is off."""
    r = _registry
    if r is not None:
        r.add(name, delta)


def note_query_latency(priority: str, wall_ns: int) -> None:
    """Per-query SLO accounting entry (api/session.collect). One
    pointer check when telemetry is off."""
    r = _registry
    if r is not None:
        r.note_query_latency(priority, wall_ns)


def slo_section() -> Dict[str, Any]:
    """The `slo` section of TpuSession.health(): per-priority-class
    wall-clock percentiles over the latency ring."""
    r = _registry
    if r is None:
        return {"enabled": False}
    return {"enabled": True, "classes": r.slo_snapshot()}


def configure(conf=None) -> Optional[TelemetryRegistry]:
    """(Re)configure from a RapidsConf — process-wide, the event-bus
    semantics: an unset telemetry.enabled keeps another session's
    registry running; an EXPLICIT enabled=false tears it down; an
    enabled conf with unchanged interval/history keeps the current
    registry (and its ring-buffer history) alive."""
    global _registry
    from ..config import (TELEMETRY_ENABLED, TELEMETRY_HISTORY_SIZE,
                          TELEMETRY_INTERVAL_MS, active_conf)
    conf = conf if conf is not None else active_conf()
    enabled = conf.get(TELEMETRY_ENABLED)
    # the replaced registry is detached under the lock but its sampler
    # is JOINED outside it (ISSUE 12 lock-blocking-call fix: shutdown()
    # joins up to 5s — holding `telemetry-config` across that stalled
    # every concurrent configure/enable/reset). The detached sampler
    # may take one last sample while the successor starts: harmless,
    # each writes only its own registry object.
    to_stop = None
    try:
        with _registry_lock:
            if not enabled:
                if TELEMETRY_ENABLED.key in conf._settings \
                        and _registry is not None:
                    to_stop, _registry = _registry, None
                return _registry
            interval = conf.get(TELEMETRY_INTERVAL_MS)
            history = conf.get(TELEMETRY_HISTORY_SIZE)
            if _registry is not None \
                    and _registry.interval_ms == max(10, interval) \
                    and _registry.history == max(1, history):
                return _registry
            to_stop = _registry
            _registry = TelemetryRegistry(interval, history)
            _registry.start()
            return _registry
    finally:
        if to_stop is not None:
            to_stop.shutdown()


def enable(interval_ms: int = 1000,
           history: int = 120) -> TelemetryRegistry:
    """Conf-free switch-on (bench / tooling entry)."""
    global _registry
    with _registry_lock:
        to_stop = _registry
        _registry = TelemetryRegistry(interval_ms, history)
        _registry.start()
        out = _registry
    if to_stop is not None:
        to_stop.shutdown()  # join outside the config lock (ISSUE 12)
    return out


def reset_telemetry() -> None:
    """Tear down the registry + sampler thread (test isolation; the
    conftest tripwire asserts no `telemetry-*` thread survives it)."""
    global _registry
    with _registry_lock:
        to_stop, _registry = _registry, None
    if to_stop is not None:
        to_stop.shutdown()  # join outside the config lock (ISSUE 12)


def counters() -> Dict[str, int]:
    """Flat cumulative counters for bench's {"telemetry": ...} deltas:
    registry activity plus every push counter. All zeros when telemetry
    is off — the block stays present so a round can assert the plane
    actually engaged."""
    r = _registry
    out = {"samples": 0, "registry_writes": 0}
    if r is not None:
        out["samples"] = r.samples_taken
        out["registry_writes"] = r.writes
        for k, v in r.counter_values().items():
            out[k.replace(".", "_")] = v
    return out


def health_section() -> Dict[str, Any]:
    """The `telemetry` section of TpuSession.health()."""
    r = _registry
    if r is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "interval_ms": r.interval_ms,
        "history_size": r.history,
        "samples": r.samples_taken,
        "last": r.last_sample(),
    }
