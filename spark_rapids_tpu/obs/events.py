"""Process-wide structured event bus (ISSUE 2 tentpole part 1).

Reference analog: the GpuMetric stream merged into the Spark SQL UI plus
the NVTX range timeline — here, one JSON-lines file per configured bus,
each line a self-describing record:

    {"ts_ns": ..., "kind": ..., "query": <id or null>, ...fields}

Event kinds and their levels (spark.rapids.tpu.eventLog.level):

  ESSENTIAL  query_start, query_end, query_cancelled, query_shed,
             recompile_storm, query_phases, adaptive_demote,
             query_stalled
  MODERATE   op_close, semaphore_acquire, spill, oom_retry,
             pallas_tier, plan_fallback, plan_not_on_tpu, exchange,
             pipeline_wait, pipeline_full, op_error, fault_inject,
             io_retry, task_retry, integrity_fail, pipeline_stuck,
             spill_error, spill_writer_dead, task_retry_settle_error,
             partition_recompute, breaker_open, breaker_half_open,
             breaker_close, peer_dead, query_queued, query_admitted,
             quota_spill, ici_exchange, adaptive_replan
  DEBUG      op_open, op_batch, span

Cost discipline: `active_bus()` returns None when logging is disabled —
every producer guards with one pointer check, so the steady-state batch
loop pays nothing (acceptance: per-batch overhead not measurable in the
kern/bench timings). When enabled, writes are line-buffered behind a
lock and flushed per record so a crashed query still leaves a parseable
log.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVEL_NAMES = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

#: event kind -> minimum eventLog.level at which it is written
EVENT_LEVELS: Dict[str, int] = {
    "query_start": ESSENTIAL,
    "query_end": ESSENTIAL,
    "op_close": MODERATE,
    "op_error": MODERATE,
    "semaphore_acquire": MODERATE,
    "spill": MODERATE,
    "oom_retry": MODERATE,
    "pallas_tier": MODERATE,
    "plan_fallback": MODERATE,
    "plan_not_on_tpu": MODERATE,
    "exchange": MODERATE,
    # shuffle-write breakdown (ISSUE 9): one record per map task with
    # the lane (device|host), frame/byte totals and the write-time
    # split (pack = device partition + packed D2H, serialize, file IO)
    "shuffle_write": MODERATE,
    # ICI device-resident shuffle lane (ISSUE 16): one record per
    # collective round with bytes moved over the mesh axis, the
    # negotiated slot_cap, the send-grid fill ratio and the collective
    # wall time
    "ici_exchange": MODERATE,
    "pipeline_wait": MODERATE,
    "pipeline_full": MODERATE,
    # robustness events (ISSUE 4): injected faults, retries at every
    # level (IO -> OOM -> task), integrity quarantines and watchdog
    # trips — the failure-story records a production operator reads
    "fault_inject": MODERATE,
    "io_retry": MODERATE,
    "task_retry": MODERATE,
    "integrity_fail": MODERATE,
    "pipeline_stuck": MODERATE,
    "spill_error": MODERATE,
    "spill_writer_dead": MODERATE,
    # lifecycle-governor events (ISSUE 6): cancellations are headline
    # (ESSENTIAL, like query begin/end); breaker transitions, the
    # partition-granular recovery lane, settle failures between task
    # attempts and heartbeat liveness transitions are MODERATE
    "query_cancelled": ESSENTIAL,
    "task_retry_settle_error": MODERATE,
    "partition_recompute": MODERATE,
    "breaker_open": MODERATE,
    "breaker_half_open": MODERATE,
    "breaker_close": MODERATE,
    "peer_dead": MODERATE,
    # workload-governor events (ISSUE 7): a shed query is headline (the
    # caller got an error, like a cancellation); queue/admission
    # transitions and quota-triggered self-spills are MODERATE
    "query_queued": MODERATE,
    "query_admitted": MODERATE,
    "query_shed": ESSENTIAL,
    "quota_spill": MODERATE,
    # packed upload engine (ISSUE 10): one record per host->device batch
    # upload with the lane (packed = one transfer | per-buffer), the
    # ingest seam (scan / shuffle / unspill) and the pack+transfer time
    "upload": MODERATE,
    # gather engine (ISSUE 8): one record per wired-exec execution with
    # its materializing-gather totals (count/packed/pallas/bytes) —
    # reconciles with the numGathers metric and op_close batch counts
    "gather_stats": MODERATE,
    # runtime statistics plane (ISSUE 11): one record per exchange
    # execution with its map-output/partition distributions and skew
    # summary (obs/stats.py), and one per telemetry sampler tick with
    # the registry snapshot (obs/telemetry.py) — the JSONL half of the
    # periodic exporter
    "exchange_stats": MODERATE,
    "telemetry_sample": MODERATE,
    # dispatch/compile observability plane (ISSUE 13): one record per
    # fresh program trace with its trace/compile cost and donated vs
    # retained argument bytes (obs/dispatch.py); one per wired-exec
    # execution with its dispatch/compile deltas (exec/base.py, the
    # gather_stats shape); recompile_storm is headline — shape-bucket
    # churn silently destroys TPU throughput
    "program_compile": MODERATE,
    "dispatch_stats": MODERATE,
    "recompile_storm": ESSENTIAL,
    # wall-clock phase attribution (ISSUE 17): one record per governed
    # query at query end with the closed phase ledger (obs/phase.py,
    # sum(phases) == wall_ns exactly), outcome, priority and attempt
    # count — headline, like query_end (it IS the query's cost story)
    "query_phases": ESSENTIAL,
    # whole-stage compilation (ISSUE 14): one record per fused-stage
    # execution — kind (map | agg | join_agg), the absorbed-op label,
    # ops absorbed, input batches, program dispatches this execution
    # issued, and the donated carried-state bytes (the in-place HBM
    # reuse the donate_argnums contract buys on real hardware)
    "stage_fused": MODERATE,
    # dictionary-encoded execution (ISSUE 18): one encoded_scan record
    # per scan batch that kept columns encoded (code/dict byte split
    # and the eager-decode bytes avoided), and one encoded_materialize
    # per late decode through the gather engine with the seam that
    # forced it (boundary | concat | output | spill)
    "encoded_scan": MODERATE,
    "encoded_materialize": MODERATE,
    # adaptive runtime replanning (ISSUE 19): one adaptive_replan
    # record per applied decision (skew_split / single_build_convert /
    # partition_coalesce / batch_right_size) with its measured-bytes
    # evidence and chosen action; adaptive_demote is headline — a
    # planned strategy measured unaffordable (broadcast_demote) or the
    # replan lane itself stood down (breaker_open / error)
    "adaptive_replan": MODERATE,
    "adaptive_demote": ESSENTIAL,
    # straggler & stall shield (ISSUE 20): a stalled governed query is
    # headline (its SLO is already lost — the event names the frozen
    # seam and the phase the time went into); speculative sub-read
    # resolutions, dispatch hang-bound trips and dead-peer map-output
    # invalidations are MODERATE, like the other recovery-lane records
    "query_stalled": ESSENTIAL,
    "speculative_fetch": MODERATE,
    "dispatch_timeout": MODERATE,
    "map_output_invalidated": MODERATE,
    "op_open": DEBUG,
    "op_batch": DEBUG,
    "span": DEBUG,
}

DEFAULT_DIR = "/tmp/spark_rapids_tpu_events"


def parse_level(name: str, default: int = MODERATE) -> int:
    return _LEVEL_NAMES.get(str(name).strip().upper(), default)


class EventBus:
    """Append-only JSONL sink. The file is created lazily on the first
    record, so an enabled-but-silent process leaves no empty files."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, directory: str, level: int = MODERATE,
                 max_bytes: int = 0):
        self.directory = directory or DEFAULT_DIR
        self.level = level
        #: rotation threshold (spark.rapids.tpu.eventLog.maxBytes,
        #: ISSUE 11 satellite): past it the current file closes and
        #: writing continues in <base>.<n>.jsonl — a soak/bench storm
        #: never grows one file without bound. 0 = unbounded.
        self.max_bytes = max(0, int(max_bytes))
        with EventBus._seq_lock:
            EventBus._seq += 1
            seq = EventBus._seq
        self._base = os.path.join(self.directory,
                                  f"events-{os.getpid()}-{seq}")
        self._rot = 0
        self._written = 0
        self.path = f"{self._base}.jsonl"
        self._lock = threading.Lock()
        self._file = None
        self._closed = False

    def _rotate_locked(self) -> None:
        """Caller holds self._lock. Close the full file and point the
        bus at the next member of the rotated set; the new file is
        created lazily by the next record, like the first one."""
        if self._file is not None:
            self._file.close()
            self._file = None
        self._rot += 1
        self._written = 0
        self.path = f"{self._base}.{self._rot}.jsonl"

    def emit(self, kind: str, **fields: Any) -> None:
        if self._closed or EVENT_LEVELS.get(kind, MODERATE) > self.level:
            return
        # `thread` (ISSUE 13 satellite): the emitting thread's name, so
        # tools/trace_export.py assigns timeline tracks (consumer vs
        # pipeline-* producers vs spill-writer vs decode-pool workers)
        # without heuristics. Read only once the record is known kept —
        # a disabled bus or filtered level pays nothing.
        rec = {"ts_ns": time.time_ns(), "kind": kind,
               "query": current_query_id(),
               "thread": threading.current_thread().name}
        rec.update(fields)
        try:
            line = json.dumps(rec, separators=(",", ":"), default=str)
            with self._lock:
                if self._closed:
                    return
                if self._file is None:
                    os.makedirs(self.directory, exist_ok=True)
                    # contract: ok lock-blocking-call — the bus lock is
                    # the declared LEAF lock and exists precisely to
                    # serialize this lazy open + append; nothing is ever
                    # acquired under it
                    self._file = open(self.path, "a")
                self._file.write(line + "\n")
                self._file.flush()
                self._written += len(line) + 1
                if self.max_bytes and self._written >= self.max_bytes:
                    self._rotate_locked()
        except Exception as e:  # noqa: BLE001 — emit runs inside
            # operator/collect finally blocks: an unwritable event log
            # must never fail a query or mask its real exception. One
            # warning, then the bus stays down.
            import logging
            logging.getLogger("spark_rapids_tpu.obs").warning(
                "event log disabled: cannot write %s (%s: %s)",
                self.path, type(e).__name__, e)
            self.close()
            _deactivate(self)  # producers drop back to the fast path

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None


_bus: Optional[EventBus] = None
_bus_lock = threading.Lock()


def active_bus() -> Optional[EventBus]:
    """The configured bus, or None when event logging is disabled. Hot
    paths call this once and guard on None — the entire disabled-mode
    cost."""
    return _bus


def emit(kind: str, **fields: Any) -> None:
    """Emit one event if logging is enabled (cold-path convenience)."""
    b = _bus
    if b is not None:
        b.emit(kind, **fields)


def _deactivate(bus: EventBus) -> None:
    """Uninstall `bus` if it is still the active one (write-failure
    self-removal: a dead bus must not keep producers instrumented)."""
    global _bus
    with _bus_lock:
        if _bus is bus:
            _bus = None


def configure(conf=None) -> Optional[EventBus]:
    """(Re)configure the process bus from a RapidsConf (None = the
    thread's active conf). The bus is PROCESS-wide, like a Spark event
    log: a conf that leaves eventLog.enabled unset keeps whatever bus
    another session enabled (a default-conf session must not fragment
    someone else's log); an EXPLICIT enabled=false tears it down. An
    enabled conf with unchanged dir+level keeps the current file open
    rather than starting a new one per query."""
    global _bus
    from ..config import (EVENT_LOG_DIR, EVENT_LOG_ENABLED,
                          EVENT_LOG_LEVEL, EVENT_LOG_MAX_BYTES,
                          active_conf)
    conf = conf if conf is not None else active_conf()
    enabled = conf.get(EVENT_LOG_ENABLED)
    with _bus_lock:
        if not enabled:
            if EVENT_LOG_ENABLED.key in conf._settings \
                    and _bus is not None:
                _bus.close()
                _bus = None
            return _bus
        directory = conf.get(EVENT_LOG_DIR) or DEFAULT_DIR
        level = parse_level(conf.get(EVENT_LOG_LEVEL))
        max_bytes = max(0, conf.get(EVENT_LOG_MAX_BYTES))
        if _bus is not None and _bus.directory == directory \
                and _bus.level == level and _bus.max_bytes == max_bytes:
            return _bus
        if _bus is not None:
            _bus.close()
        _bus = EventBus(directory, level, max_bytes=max_bytes)
        return _bus


def enable(directory: str, level: str = "MODERATE",
           max_bytes: int = 0) -> EventBus:
    """Conf-free switch-on (bench / tooling entry)."""
    global _bus
    with _bus_lock:
        if _bus is not None:
            _bus.close()
        _bus = EventBus(directory, parse_level(level),
                        max_bytes=max_bytes)
        return _bus


def reset_event_bus() -> None:
    """Tear down the bus (test isolation)."""
    global _bus
    with _bus_lock:
        if _bus is not None:
            _bus.close()
        _bus = None


# -- query attribution ------------------------------------------------------

_qlocal = threading.local()
_query_counter = 0
_query_counter_lock = threading.Lock()


def current_query_id() -> Optional[int]:
    return getattr(_qlocal, "qid", None)


def adopt_query_id(qid: Optional[int]) -> None:
    """Attribute this thread's events to an existing query id — used by
    pipeline producer threads (exec/pipeline.py) so events emitted
    behind a stage boundary carry their consumer's query."""
    _qlocal.qid = qid


def with_query_id(qid: Optional[int], fn, *args, **kwargs):
    """Run `fn` with this thread's events attributed to `qid`,
    restoring the previous attribution after (ISSUE 12): the shared
    decode/serialize pools and the spill writer serve MANY queries from
    one long-lived thread, so per-job adoption — the submitter captures
    current_query_id() and wraps the work item — is the only
    granularity that keeps io_retry/spill events attributed. Accepted
    by the thread-adopt contract rule as a spawn target."""
    prev = current_query_id()
    adopt_query_id(qid)
    try:
        return fn(*args, **kwargs)
    finally:
        adopt_query_id(prev)


@contextlib.contextmanager
def query_scope(qid: Optional[int] = None) -> Iterator[int]:
    """Attribute every event emitted by this thread inside the body to
    one query id (fresh monotonic id when not given). Nests: an inner
    scope shadows and restores."""
    global _query_counter
    if qid is None:
        with _query_counter_lock:
            _query_counter += 1
            qid = _query_counter
    prev = getattr(_qlocal, "qid", None)
    _qlocal.qid = qid
    try:
        yield qid
    finally:
        _qlocal.qid = prev
