"""Per-query profile surface (ISSUE 2 tentpole part 3) — the executed
TpuExec tree annotated with its metric registries, the standalone analog
of the reference's Spark-SQL-UI plan graph with GpuMetrics merged in.

`QueryProfile` is built by `DataFrame.collect()` (session surface:
`TpuSession.last_query_profile()`) from the executed plan root plus the
task-metrics summary. Metric visibility honors
spark.rapids.sql.metrics.level exactly like `TpuExec.all_metrics()`
(reference GpuExec.scala:36-47): DEBUG metrics only appear when asked
for.

Reading a metric value materializes its pending device scalars (one
stacked d2h transfer per operator) — profiles are built at query end,
never in the batch loop.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def metrics_level(conf=None) -> int:
    """spark.rapids.sql.metrics.level as an int level (the one
    implementation lives at exec.base.metrics_level_from_conf)."""
    from ..exec.base import metrics_level_from_conf
    return metrics_level_from_conf(conf)


def _fmt_ns(ns: int) -> str:
    if ns < 1_000:
        return f"{ns}ns"
    if ns < 1_000_000:
        return f"{ns / 1_000:.1f}us"
    if ns < 1_000_000_000:
        return f"{ns / 1_000_000:.1f}ms"
    return f"{ns / 1_000_000_000:.2f}s"


def _fmt_bytes(b: int) -> str:
    if b < (1 << 10):
        return f"{b}B"
    if b < (1 << 20):
        return f"{b / (1 << 10):.1f}KB"
    if b < (1 << 30):
        return f"{b / (1 << 20):.1f}MB"
    return f"{b / (1 << 30):.2f}GB"


def _fmt_metric(name: str, value: int) -> str:
    if name.endswith(("Time", "Ns")):
        return _fmt_ns(value)
    if name.endswith(("Bytes", "Size")) or name == "dataSize":
        return _fmt_bytes(value)
    return str(value)


def _node(op, level: int) -> Dict[str, Any]:
    node = {
        "op": type(op).__name__,
        "op_id": getattr(op, "_op_id", None),
        "desc": op.node_description(),
        "metrics": {name: m.value for name, m in op.metrics.items()
                    if m.level <= level},
        "children": [_node(c, level) for c in op.children],
    }
    # program labels of this exec's owner-bound dispatch sites (ISSUE
    # 13): dispatch_summary() joins the ledger by EXACT label, so a
    # subclass inheriting its parent's sites (TopNExec builds
    # "SortExec.sort") still claims its programs
    sites = getattr(op, "_dispatch_sites", None)
    if sites:
        node["dispatch_labels"] = sorted({s.label for s in sites})
    return node


class QueryProfile:
    """Executed-plan profile: `.tree` (nested dict), `.summary` (the
    per-query task-metrics roll-up), `.text()` (explain-with-metrics)
    and `.to_json()` renderers."""

    def __init__(self, root, summary: Optional[Dict[str, int]] = None,
                 level: Optional[int] = None, statistics=None,
                 phases=None):
        level = metrics_level() if level is None else level
        self.tree = _node(root, level)
        self.summary = dict(summary or {})
        #: per-query RuntimeStats (obs/stats.py), captured by
        #: DataFrame._collect_once from the governing QueryContext
        self._runtime_stats = statistics
        #: wall-clock phase ledger (obs/phase.PhaseLedger) of the
        #: governed query, or None when phases.enabled is off / the
        #: collect ran ungoverned
        self._phase_ledger = phases
        #: canonical plan fingerprint of the executed root (ISSUE 14 /
        #: the history capsule join key); None when the plan opted out
        self.fingerprint = root.plan_fingerprint() \
            if hasattr(root, "plan_fingerprint") else None

    def phases(self) -> Optional[Dict[str, int]]:
        """The query's closed wall-clock phase dict (obs/phase.PHASES
        keys, sum == wall_ns exactly, `other` the derived remainder) —
        None when no ledger was attached. Pair with `phases_wall_ns()`
        for the denominator."""
        if self._phase_ledger is None:
            return None
        return self._phase_ledger.snapshot()

    def phases_wall_ns(self) -> Optional[int]:
        """Wall-clock the phase dict partitions (ns), or None."""
        if self._phase_ledger is None:
            return None
        return self._phase_ledger.wall_ns

    def to_dict(self) -> Dict[str, Any]:
        out = {"summary": self.summary, "plan": self.tree}
        ph = self.phases()
        if ph is not None:
            out["phases"] = ph
            out["phases_wall_ns"] = self.phases_wall_ns()
        return out

    def statistics(self) -> Dict[str, Any]:
        """Runtime statistics of this query (ISSUE 11): per-exchange
        map-output/partition row+byte distributions (log2-bucket
        histograms with exact count/sum/min/max), exact per-partition
        totals, and a skew summary (max/median partition ratio) — plus
        per-operator cardinality/selectivity derived from the metric
        tree (rows-out over rows-in, the data a broadcast/skew AQE
        decision consumes). Exchange entries exist only for queries
        that shuffled; `operators` is always populated."""
        out: Dict[str, Any] = {"exchanges": {}, "operators": []}
        if self._runtime_stats is not None:
            out["exchanges"] = \
                self._runtime_stats.to_dict()["exchanges"]

        def walk(node):
            rows_out = node["metrics"].get("numOutputRows", 0)
            rows_in = sum(c["metrics"].get("numOutputRows", 0)
                          for c in node["children"]) \
                if node["children"] else None
            row = {"op": node["op"], "op_id": node["op_id"],
                   "rows_out": rows_out, "rows_in": rows_in,
                   "selectivity": (round(rows_out / rows_in, 6)
                                   if rows_in else None)}
            out["operators"].append(row)
            for c in node["children"]:
                walk(c)

        walk(self.tree)
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, default=str)

    def text(self) -> str:
        """Spark-SQL-UI-style explain with metrics inlined per node."""
        lines: List[str] = ["== TPU Query Profile =="]
        task_keys = [k for k in ("semWaitTimeNs", "retryCount",
                                 "splitAndRetryCount", "spilledDeviceBytes",
                                 "spilledHostBytes") if k in self.summary]
        if task_keys:
            parts = []
            for k in task_keys:
                v = self.summary[k]
                parts.append(f"{k}={_fmt_ns(v)}" if k.endswith("Ns")
                             else f"{k}={_fmt_bytes(v)}" if
                             k.endswith("Bytes") else f"{k}={v}")
            lines.append("task: " + " ".join(parts))

        def walk(node: Dict[str, Any], indent: int):
            lines.append("  " * indent + node["desc"])
            if node["metrics"]:
                body = ", ".join(
                    f"{n}: {_fmt_metric(n, v)}"
                    for n, v in sorted(node["metrics"].items()))
                lines.append("  " * indent + f"  + {body}")
            for c in node["children"]:
                walk(c, indent + 1)

        walk(self.tree, 0)
        return "\n".join(lines)

    def dispatch_summary(self) -> Dict[str, Any]:
        """THE whole-stage-compilation baseline table (ISSUE 13 /
        ROADMAP 2): per plan stage, how many device programs exist, how
        many dispatches the stage issued, and dispatches per output
        batch — the per-operator interpretation overhead a stage
        compiler must collapse to ~1/batch. Rows come from the wired
        execs' numDispatches/compileTimeNs metrics (counted at call
        time, so jit cache hits replay identical counts across
        repeated collects); `programs` joins the process dispatch
        ledger by the exec's own site labels. The `stages` rows are
        per-query; `engine` rows (module-level program families the
        plan tree cannot own: upload unpack, transfer pack, coalesce
        concat) and `counters` are PROCESS-lifetime ledger totals —
        the query-scoped share of those dispatches already lands in
        the stage rows via dispatch.metric_scope, so never sum stages
        with engine."""
        from . import dispatch as obs_dispatch
        by_label: Dict[str, List[Dict[str, Any]]] = {}
        by_family: Dict[str, List[Dict[str, Any]]] = {}
        for p in obs_dispatch.programs():
            by_label.setdefault(p["label"], []).append(p)
            by_family.setdefault(p["label"].split(".")[0], []).append(p)
        stages: List[Dict[str, Any]] = []
        seen = set()

        def walk(node):
            m = node["metrics"]
            d = m.get("numDispatches")
            if d is not None:
                batches = m.get("numOutputBatches", 0)
                # join by the exec's own site labels (recorded at
                # profile build) — exact even when a subclass inherits
                # its parent's program labels; fall back to the class-
                # name family for metric-scope-attributed execs
                labels = node.get("dispatch_labels")
                if labels:
                    progs = [p for lb in labels
                             for p in by_label.get(lb, ())]
                else:
                    progs = by_family.get(node["op"], ())
                for lb in labels or (node["op"],):
                    seen.add(lb.split(".")[0])
                stages.append({
                    "op": node["op"], "op_id": node["op_id"],
                    "dispatches": d, "batches": batches,
                    "dispatches_per_batch": (round(d / batches, 4)
                                             if batches else None),
                    "compile_ns": m.get("compileTimeNs", 0),
                    "programs": len(progs),
                })
            for c in node["children"]:
                walk(c)

        walk(self.tree)
        # `engine`: module-level program families with no owning exec
        # instance (closed set by construction; exec-owned families are
        # excluded). These rows are PROCESS-lifetime ledger totals —
        # reference info, not per-query attribution: the query-scoped
        # share of these dispatches already lands in the stage rows
        # above via dispatch.metric_scope (scan claims upload unpack,
        # coalesce claims concat), so do NOT sum stages + engine.
        module_families = {"upload", "transfer", "coalesce",
                           "aggregate", "pallas", "distributed"}
        engine = []
        for fam, progs in sorted(by_family.items()):
            if fam in seen or fam not in module_families:
                continue
            engine.append({
                "scope": "process",
                "family": fam, "programs": len(progs),
                "dispatches": sum(p["dispatches"] for p in progs),
                "compile_ns": sum(p["compile_ns"] for p in progs),
                "cache_hits": sum(p["cache_hits"] for p in progs),
            })
        return {"stages": stages, "engine": engine,
                "counters": obs_dispatch.counters()}

    def top_operators(self, n: int = 5,
                      by: str = "time") -> List[Dict[str, Any]]:
        """Top-N operator rows. by="time" (default) ranks by the sum of
        the node's *Time metrics — operators time their own work in
        per-op metrics (computeAggTime, joinTime, ...), so opTime alone
        under-ranks them; any explicit metric name ranks by that.

        Pipelined stages (ISSUE 3) additionally carry `overlap`: the
        fraction of the stage's lifetime NOT spent stalled waiting on
        its pipelined input, 1 - pipelineWaitNs / pipelineWallNs. 1.0
        means the producer fully hid the input latency; low values mean
        the stage is input-bound (raise pipeline.depth or speed the
        producer). Only meaningful while pipeline.enabled is on — a
        synchronous stage records neither wait nor wall."""
        rows: List[Dict[str, Any]] = []

        def walk(node):
            m = node["metrics"]
            time_ns = sum(v for k, v in m.items() if k.endswith("Time"))
            row = {"op": node["op"], "op_id": node["op_id"],
                   "time_ns": time_ns,
                   "rows": m.get("numOutputRows", 0),
                   "batches": m.get("numOutputBatches", 0),
                   "rank_key": time_ns if by == "time"
                   else m.get(by, 0)}
            if "pipelineWaitNs" in m:
                wait = m["pipelineWaitNs"]
                wall = m.get("pipelineWallNs", 0)
                row["pipeline_wait_ns"] = wait
                if wall > 0:
                    row["overlap"] = round(1.0 - min(wait, wall) / wall, 4)
            rows.append(row)
            for c in node["children"]:
                walk(c)

        walk(self.tree)
        rows.sort(key=lambda r: (-r["rank_key"], r["op"],
                                 r["op_id"] if r["op_id"] is not None
                                 else -1))
        for r in rows:
            r.pop("rank_key", None)
        return rows[:n]


def bench_profile_summary(root, before: Optional[Dict[str, int]] = None,
                          top: int = 5) -> Dict[str, Any]:
    """Compact per-query attribution for a BENCH record: the
    task-metrics summary plus the top-N operators by opTime (ISSUE 2
    satellite: BENCH deltas stop being single scalar GB/s numbers)."""
    from ..exec.task_metrics import query_summary
    summary = query_summary(root, before)
    prof = QueryProfile(root, summary)
    return {
        "query_metrics": {k: v for k, v in summary.items()
                          if not k.startswith("ops.")},
        "top_ops": prof.top_operators(top),
    }
