"""Device regex matching kernel: one uint32 Glushkov state mask per row,
advanced byte-by-byte in a vectorized lax.while_loop — every iteration
moves ALL rows forward one byte with pure bitwise VPU ops; trip count is
the max row byte-length in the batch (a device scalar, so no recompiles
across batches). O(max_len × capacity × n_positions) bit-ops total.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BOOLEAN
from .program import RegexProgram


def regex_find(col: StringColumn, prog: RegexProgram) -> Column:
    """Java Matcher.find()/matches() over every row.

    anchored_start/anchored_end=False (RLike): true iff any substring
    matches. Both anchored (LIKE): true iff the whole row matches.
    """
    from ..ops.strings import string_lengths

    cap = col.capacity
    lens = string_lengths(col)
    valid = col.validity

    # nullable patterns match the empty string; under find() semantics an
    # empty match exists at some position unless BOTH ends are anchored
    if prog.nullable and not (prog.anchored_start and prog.anchored_end):
        return Column(jnp.ones(cap, jnp.bool_), valid, BOOLEAN)
    if prog.n_pos == 0:
        # empty anchored pattern: matches only the empty string
        return Column(lens == 0, valid, BOOLEAN)

    byte_table = jnp.asarray(prog.byte_table)           # (256,) uint32
    follow_rows = jnp.asarray(prog.follow_rows)         # (n,) uint32
    first = jnp.uint32(prog.first_mask)
    last = jnp.uint32(prog.last_mask)
    starts = col.offsets[:-1]
    byte_cap = col.byte_capacity
    max_t = jnp.max(lens)

    def body(carry):
        t, state, matched = carry
        p = jnp.clip(starts + t, 0, byte_cap - 1)
        cmask = byte_table[col.data[p]]
        # follow(state): OR of follow rows of set positions (static unroll
        # over <=32 positions; XLA fuses this into a handful of vector ops)
        fol = jnp.zeros(cap, jnp.uint32)
        for s in range(prog.n_pos):
            bit = (state >> jnp.uint32(s)) & jnp.uint32(1)
            fol = fol | jnp.where(bit != 0, follow_rows[s], jnp.uint32(0))
        inject = first if not prog.anchored_start else \
            jnp.where(t == 0, first, jnp.uint32(0))
        new_state = (fol | inject) & cmask
        active = t < lens
        new_state = jnp.where(active, new_state, state)
        if not prog.anchored_end:
            matched = matched | (active & ((new_state & last) != 0))
        return t + 1, new_state, matched

    def cond(carry):
        t, state, matched = carry
        more = t < max_t
        if not prog.anchored_end and prog.anchored_start:
            # anchored-start find can stop early once every row is decided
            # (state only goes dead after the t=0 injection has happened)
            return more & ((t == 0)
                           | ~jnp.all(matched | (state == 0) | (t >= lens)))
        return more

    state0 = jnp.zeros(cap, jnp.uint32)
    matched0 = jnp.zeros(cap, jnp.bool_)
    _, state, matched = jax.lax.while_loop(
        cond, body, (jnp.int32(0), state0, matched0))

    if prog.anchored_end:
        # accept iff a last position is live exactly at each row's end
        # (state freezes at the final byte); whole-match of the empty row
        # is the nullable case
        matched = (state & last) != 0
        matched = jnp.where(lens == 0, prog.nullable, matched)
    return Column(matched, valid, BOOLEAN)
