"""Match-span kernels for regexp_replace / regexp_extract.

The Glushkov matcher (regex/kernel.py) answers *whether* a row matches;
replace/extract need *where*. Bit-parallel NFA simulation cannot recover
Java's backtracking-preferred match extents in general, so the device
tier handles the two shapes where the preferred extent is derivable
byte-parallel — which together cover most real workloads:

  FIXED      every match has the same byte length L (class sequences,
             equal-length alternations, counted repeats {m}): a match
             starting at byte b is a pure window test, and Java's
             preference plays no role because all extents are equal.
  CLASSPLUS  one character class under + ([0-9]+, \\s+, a+): matches are
             exactly the maximal runs of class bytes — greedy Java
             semantics by construction.

Anything else (variable-length alternations, nested stars, lookaround…)
stays on the host row tier, tagged by the planner exactly like patterns
that blow the 32-position Glushkov budget. Reference analog: the
transpiler rejection tiers of RegexParser.scala:687."""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column, StringColumn
from ..types import STRING
from .parser import (Alt, Empty, Group, Lit, Node, RegexUnsupported, Seq,
                     Star, parse_regex)

# plain Python int, NOT a jnp constant: this module is imported
# lazily, sometimes inside a jit trace, and a traced-time jnp
# constant stored in a module global leaks the tracer into every
# later trace (UnexpectedTracerError). Weak promotion keeps the
# int32 arithmetic identical.
_BIG = 1 << 30


# -- pattern analysis -------------------------------------------------------

def _fixed_len(node: Node) -> Optional[int]:
    if isinstance(node, Empty):
        return 0
    if isinstance(node, Lit):
        return 1
    if isinstance(node, Group):
        return _fixed_len(node.child)
    if isinstance(node, Seq):
        total = 0
        for p in node.parts:
            l = _fixed_len(p)
            if l is None:
                return None
            total += l
        return total
    if isinstance(node, Alt):
        lens = [_fixed_len(o) for o in node.options]
        if any(l is None for l in lens) or len(set(lens)) != 1:
            return None
        return lens[0]
    return None  # Star


def _strip_groups(node: Node) -> Node:
    if isinstance(node, Group):
        return _strip_groups(node.child)
    if isinstance(node, Seq):
        return Seq([_strip_groups(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_strip_groups(o) for o in node.options])
    if isinstance(node, Star):
        return Star(_strip_groups(node.child))
    return node


def _classplus_mask(node: Node) -> Optional[np.ndarray]:
    """X+ (parsed as Seq([X, Star(X)])) for a single byte class X."""
    node = _strip_groups(node)
    if isinstance(node, Seq) and len(node.parts) == 2:
        a, b = node.parts
        a = _strip_groups(a)
        b = _strip_groups(b)
        if isinstance(a, Lit) and isinstance(b, Star):
            inner = _strip_groups(b.child)
            if isinstance(inner, Lit) and np.array_equal(a.mask,
                                                         inner.mask):
                return a.mask
    return None


def _group_window(node: Node, idx: int) -> Optional[Tuple[int, int]]:
    """(byte offset, byte length) of capture group `idx` within a FIXED
    match, when that offset is itself fixed; None otherwise."""

    def walk(n: Node, off: int) -> Tuple[Optional[Tuple[int, int]], int]:
        if isinstance(n, Group):
            l = _fixed_len(n.child)
            if n.idx == idx:
                return ((off, l), off + l) if l is not None else (None,
                                                                  -1)
            return walk(n.child, off)
        if isinstance(n, Seq):
            found = None
            for p in n.parts:
                got, off = walk(p, off)
                if off < 0:
                    return None, -1
                if got is not None:
                    found = got
            return found, off
        if isinstance(n, Alt):
            # group inside an alternation has no fixed offset
            for o in n.options:
                if _contains_group(o, idx):
                    return None, -1
            l = _fixed_len(n)
            return None, off + l if l is not None else -1
        l = _fixed_len(n)
        return None, (off + l) if l is not None else -1

    got, off = walk(node, 0)
    return got if off >= 0 else None


def _contains_group(n: Node, idx: int) -> bool:
    if isinstance(n, Group):
        return n.idx == idx or _contains_group(n.child, idx)
    if isinstance(n, Seq):
        return any(_contains_group(p, idx) for p in n.parts)
    if isinstance(n, Alt):
        return any(_contains_group(o, idx) for o in n.options)
    if isinstance(n, Star):
        return _contains_group(n.child, idx)
    return False


class SpanPlan:
    """Compiled span finder: kind 'fixed' (window tree, length L) or
    'classplus' (byte-class runs)."""

    def __init__(self, kind: str, tree: Node, L: Optional[int],
                 cls: Optional[np.ndarray], anchored_start: bool,
                 anchored_end: bool, n_groups: int):
        self.kind = kind
        self.tree = tree
        self.L = L
        self.cls = cls
        self.anchored_start = anchored_start
        self.anchored_end = anchored_end
        self.n_groups = n_groups


def compile_spans(pattern: str) -> SpanPlan:
    """Raises RegexUnsupported when the pattern fits neither shape."""
    tree, a_start, a_end = parse_regex(pattern)
    n_groups = _count_groups(tree)
    L = _fixed_len(tree)
    if L is not None and L >= 1:
        return SpanPlan("fixed", tree, L, None, a_start, a_end, n_groups)
    cls = _classplus_mask(tree)
    if cls is not None:
        return SpanPlan("classplus", tree, None, cls, a_start, a_end,
                        n_groups)
    raise RegexUnsupported(
        f"pattern {pattern!r}: match spans are only derivable for "
        "fixed-length patterns and single-class X+ on device")


def _count_groups(n: Node) -> int:
    if isinstance(n, Group):
        return max(n.idx, _count_groups(n.child))
    if isinstance(n, Seq):
        return max([_count_groups(p) for p in n.parts], default=0)
    if isinstance(n, Alt):
        return max([_count_groups(o) for o in n.options], default=0)
    if isinstance(n, Star):
        return _count_groups(n.child)
    return 0


# -- device span finding ----------------------------------------------------

def _window_hits(node: Node, col: StringColumn, base, off: int
                 ) -> Tuple[jnp.ndarray, int]:
    """(hits, consumed): hits[b] = subtree matches starting at byte
    base[b]+off. Only fixed-length subtrees reach here."""
    data = col.data
    byte_cap = col.byte_capacity
    if isinstance(node, Empty):
        return jnp.ones(base.shape, jnp.bool_), off
    if isinstance(node, Group):
        return _window_hits(node.child, col, base, off)
    if isinstance(node, Lit):
        table = jnp.asarray(node.mask)
        p = jnp.clip(base + off, 0, byte_cap - 1)
        return table[data[p]], off + 1
    if isinstance(node, Seq):
        ok = jnp.ones(base.shape, jnp.bool_)
        for part in node.parts:
            h, off = _window_hits(part, col, base, off)
            ok = ok & h
        return ok, off
    if isinstance(node, Alt):
        ok = jnp.zeros(base.shape, jnp.bool_)
        out_off = off
        for o in node.options:
            h, out_off = _window_hits(o, col, base, off)
            ok = ok | h
        return ok, out_off
    raise RegexUnsupported("variable-length subtree in fixed plan")


def find_spans(col: StringColumn, plan: SpanPlan):
    """-> (sel starts byte-mask, span_len (byte_cap,) int32 valid at
    starts). Matches are Java's non-overlapping find() sequence."""
    from ..ops.strings import _row_of_byte, select_literal_hits
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    row_start = col.offsets[row]
    row_end = col.offsets[row + 1]
    in_use = pos < col.offsets[-1]

    if plan.kind == "fixed":
        L = plan.L
        hits, _ = _window_hits(plan.tree, col, pos, 0)
        hits = hits & in_use & (pos + L <= row_end)
        if plan.anchored_start:
            hits = hits & (pos == row_start)
        if plan.anchored_end:
            hits = hits & (pos + L == row_end)
        if L > 1:
            hits = _greedy_nonoverlap(col, hits, L)
        return hits, jnp.full((byte_cap,), L, jnp.int32)

    table = jnp.asarray(plan.cls)
    isc = table[col.data] & in_use
    prev = jnp.clip(pos - 1, 0, byte_cap - 1)
    run_start = isc & (~isc[prev] | (pos == row_start))
    # run end: next non-class byte (or row end)
    nxt_non = jnp.flip(jax.lax.associative_scan(
        jnp.minimum, jnp.flip(jnp.where(~isc, pos, _BIG))))
    run_len = jnp.minimum(nxt_non, row_end) - pos
    if plan.anchored_start:
        run_start = run_start & (pos == row_start)
    if plan.anchored_end:
        run_start = run_start & (pos + run_len == row_end)
    return run_start, run_len.astype(jnp.int32)


def _greedy_nonoverlap(col: StringColumn, hits, L: int):
    """Left-to-right non-overlapping selection of fixed-length-L hits
    (same cursor loop as ops/strings.select_literal_hits)."""
    from ..ops.strings import _row_of_byte
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    intra = pos - col.offsets[row]
    big = jnp.int32(1 << 30)

    def body(carry):
        cursor, sel = carry
        cand = jnp.where(hits & (intra >= cursor[row]), intra, big)
        nxt = jax.ops.segment_min(cand, row, num_segments=col.capacity)
        found = nxt < big
        sel_pos = jnp.where(found, col.offsets[:-1] + nxt,
                            jnp.int32(byte_cap))
        sel = sel.at[sel_pos].set(True, mode="drop")
        cursor = jnp.where(found, nxt + L, big)
        return cursor, sel

    def cond(carry):
        return jnp.any(carry[0] < big)

    _, selected = jax.lax.while_loop(
        cond, body, (jnp.zeros(col.capacity, jnp.int32),
                     jnp.zeros(byte_cap, jnp.bool_)))
    return selected & hits


# -- replace / extract ------------------------------------------------------

def regexp_replace_device(col: StringColumn, plan: SpanPlan,
                          replacement: bytes) -> StringColumn:
    from ..columnar.column import bucket_capacity
    from ..ops.strings import _rebuild_offsets, _row_of_byte
    byte_cap = col.byte_capacity
    cap = col.capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    in_use = pos < col.offsets[-1]
    sel, span_len = find_spans(col, plan)
    sel = sel & col.validity[row]
    lr = len(replacement)

    # coverage via difference array (variable span lengths)
    diff = jnp.zeros((byte_cap + 1,), jnp.int32)
    s_idx = jnp.where(sel, pos, byte_cap)
    e_idx = jnp.where(sel, jnp.clip(pos + span_len, 0, byte_cap),
                      byte_cap)
    diff = diff.at[s_idx].add(jnp.where(sel, 1, 0), mode="drop")
    diff = diff.at[e_idx].add(jnp.where(sel, -1, 0), mode="drop")
    covered = jnp.cumsum(diff[:-1]) > 0

    emit = jnp.where(in_use, jnp.int32(1), 0)
    emit = jnp.where(covered, 0, emit)
    emit = jnp.where(sel, jnp.int32(lr), emit)

    out_lens = jax.ops.segment_sum(emit, row, num_segments=cap)
    out_lens = jnp.where(col.validity, out_lens, 0)
    new_offsets = _rebuild_offsets(out_lens)
    # worst case: every byte is a 1-byte match replaced by lr bytes
    out_byte_cap = byte_cap if lr <= 1 else bucket_capacity(byte_cap * lr)

    emit_start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(emit, dtype=jnp.int32)])
    opos = jnp.arange(out_byte_cap, dtype=jnp.int32)
    src = jnp.clip(jnp.searchsorted(emit_start, opos, side="right")
                   .astype(jnp.int32) - 1, 0, byte_cap - 1)
    k = opos - emit_start[src]
    out_in_use = opos < new_offsets[-1]
    repl_arr = jnp.asarray(bytearray(replacement or b"\0"), jnp.uint8)
    byte = jnp.where(sel[src],
                     repl_arr[jnp.clip(k, 0, max(lr - 1, 0))],
                     col.data[src])
    data = jnp.where(out_in_use, byte, jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def regexp_extract_device(col: StringColumn, plan: SpanPlan,
                          idx: int) -> StringColumn:
    """First match's group `idx` per row; "" when the row has no match
    (Java), NULL only for NULL input. Raises RegexUnsupported when the
    group has no fixed window inside the match."""
    from ..ops.strings import _row_of_byte, _substring_gather
    if idx < 0 or idx > plan.n_groups:
        raise RegexUnsupported(f"group {idx} out of range")
    if idx == 0:
        g_off, g_len = 0, None  # whole match
    elif plan.kind == "classplus":
        # supported only when the group wraps the whole X+ ("([0-9]+)");
        # a group under the repeat ("([0-9])+" = last iteration in Java)
        # parses as a Seq and is rejected here
        if not (isinstance(plan.tree, Group) and plan.tree.idx == idx):
            raise RegexUnsupported(
                "classplus extract needs the group around the whole X+")
        g_off, g_len = 0, None
    else:
        win = _group_window(plan.tree, idx)
        if win is None:
            raise RegexUnsupported(
                f"group {idx} has no fixed offset inside the match")
        g_off, g_len = win

    byte_cap = col.byte_capacity
    cap = col.capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    sel, span_len = find_spans(col, plan)
    first = jax.ops.segment_min(jnp.where(sel, pos, _BIG), row,
                                num_segments=cap)
    has = first < _BIG
    firstc = jnp.clip(first, 0, byte_cap - 1)
    mlen = span_len[firstc]
    start = jnp.where(has, firstc + g_off, 0)
    length = jnp.where(has,
                       mlen - g_off if g_len is None else g_len, 0)
    length = jnp.maximum(length, 0)
    return _substring_gather(col, start.astype(jnp.int32),
                             length.astype(jnp.int32))
