r"""Java-regex subset parser (reference RegexParser.scala:687 — the same
approach: parse what the device engine can run, reject the rest loudly).

Grammar (byte semantics, exact for ASCII):
  literal chars and escapes  \\ \. \* \+ \? \( \) \[ \] \{ \} \| \^ \$
                             \t \n \r \f \a \e \0
  .                          any byte except \n
  [abc] [a-z0-9] [^...]      char classes (ranges, escapes, negation)
  \d \D \w \W \s \S          predefined classes (also inside [...])
  X* X+ X? X{m} X{m,} X{m,n} greedy quantifiers (counted repeats expand;
                             m,n <= 16)
  X|Y                        alternation
  (X) (?:X)                  groups (capturing == non-capturing for match)
  ^ $                        anchors at pattern start/end only

Rejected with RegexUnsupported: backreferences, lookaround, lazy/possessive
quantifiers, \b \B boundaries, \p{...} unicode classes, named groups,
inline flags, anchors mid-pattern, {m,} with m>0 beyond expansion budget.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class RegexUnsupported(Exception):
    """Pattern uses a construct the device engine cannot run; the planner
    tags the expression for fallback (reference: transpiler rejection)."""


# -- AST --------------------------------------------------------------------

class Node:
    pass


class Lit(Node):
    """One byte-class position: 256-entry bool mask."""

    def __init__(self, mask: np.ndarray):
        self.mask = mask


class Seq(Node):
    def __init__(self, parts: List[Node]):
        self.parts = parts


class Alt(Node):
    def __init__(self, options: List[Node]):
        self.options = options


class Star(Node):
    """Zero-or-more of child."""

    def __init__(self, child: Node):
        self.child = child


class Empty(Node):
    pass


class Group(Node):
    """Capturing group marker: transparent for matching, consumed by the
    span analyzer (regex/spans.py) for regexp_extract group offsets."""

    def __init__(self, child: Node, idx: int):
        self.child = child
        self.idx = idx


def _mask_of(*bytes_) -> np.ndarray:
    m = np.zeros(256, dtype=bool)
    for b in bytes_:
        m[b] = True
    return m


def _range_mask(lo: int, hi: int) -> np.ndarray:
    m = np.zeros(256, dtype=bool)
    m[lo:hi + 1] = True
    return m


_DIGIT = _range_mask(ord("0"), ord("9"))
_WORD = _range_mask(ord("a"), ord("z")) | _range_mask(ord("A"), ord("Z")) \
    | _DIGIT | _mask_of(ord("_"))
_SPACE = _mask_of(ord(" "), ord("\t"), ord("\n"), ord("\r"),
                  0x0B, 0x0C)
_ANY = np.ones(256, dtype=bool) & ~_mask_of(ord("\n"))

_CLASS_ESCAPES = {
    "d": _DIGIT, "D": ~_DIGIT,
    "w": _WORD, "W": ~_WORD,
    "s": _SPACE, "S": ~_SPACE,
}

_CHAR_ESCAPES = {
    "t": ord("\t"), "n": ord("\n"), "r": ord("\r"), "f": ord("\f"),
    "a": 0x07, "e": 0x1B, "0": 0,
}

_META = set("\\.[]{}()*+?|^$")

MAX_COUNTED_REPEAT = 16


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.anchored_start = False
        self.anchored_end = False
        self.n_groups = 0

    def error(self, msg: str):
        raise RegexUnsupported(
            f"regex {self.p!r} at position {self.i}: {msg}")

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    # -- entry -------------------------------------------------------------
    def parse(self) -> Node:
        if self.peek() == "^":
            self.next()
            self.anchored_start = True
        node = self.alternation()
        if self.i < len(self.p):
            self.error(f"unexpected {self.p[self.i]!r}")
        if (self.anchored_start or self.anchored_end) \
                and isinstance(node, Alt):
            # Java binds anchors tighter than top-level '|' ('a|b$' anchors
            # only the second branch); whole-pattern flags would mis-match
            self.i = 0
            self.error("anchors with top-level alternation not supported")
        return node

    def alternation(self) -> Node:
        opts = [self.sequence()]
        while self.peek() == "|":
            self.next()
            opts.append(self.sequence())
        return opts[0] if len(opts) == 1 else Alt(opts)

    def sequence(self) -> Node:
        parts: List[Node] = []
        while True:
            ch = self.peek()
            if ch is None or ch in ")|":
                break
            if ch == "$":
                # only valid at the very end of the whole pattern
                if self.i == len(self.p) - 1:
                    self.next()
                    self.anchored_end = True
                    break
                self.error("'$' only supported at the end of the pattern")
            if ch == "^":
                self.error("'^' only supported at the start of the pattern")
            parts.append(self.quantified())
        if not parts:
            return Empty()
        return parts[0] if len(parts) == 1 else Seq(parts)

    def quantified(self) -> Node:
        atom = self.atom()
        ch = self.peek()
        if ch not in ("*", "+", "?", "{"):
            return atom
        if ch == "{":
            lo, hi = self.counted()
        else:
            self.next()
            lo, hi = {"*": (0, None), "+": (1, None), "?": (0, 1)}[ch]
        nxt = self.peek()
        if nxt in ("?", "+"):
            self.error("lazy/possessive quantifiers not supported")
        return self._repeat(atom, lo, hi)

    def counted(self) -> Tuple[int, Optional[int]]:
        assert self.next() == "{"
        spec = ""
        while self.peek() is not None and self.peek() != "}":
            spec += self.next()
        if self.peek() != "}":
            self.error("unterminated {...}")
        self.next()
        try:
            if "," not in spec:
                n = int(spec)
                return n, n
            lo_s, hi_s = spec.split(",", 1)
            lo = int(lo_s)
            hi = None if hi_s == "" else int(hi_s)
            return lo, hi
        except ValueError:
            self.error(f"bad counted repeat {{{spec}}}")

    def _repeat(self, atom: Node, lo: int, hi: Optional[int]) -> Node:
        if lo > MAX_COUNTED_REPEAT or (hi is not None
                                       and hi > MAX_COUNTED_REPEAT):
            self.error(f"counted repeat beyond expansion budget "
                       f"{MAX_COUNTED_REPEAT}")
        parts: List[Node] = [_clone(atom) for _ in range(lo)]
        if hi is None:
            parts.append(Star(_clone(atom)))
        else:
            for _ in range(hi - lo):
                parts.append(Alt([_clone(atom), Empty()]))
        if not parts:
            return Empty()
        return parts[0] if len(parts) == 1 else Seq(parts)

    def atom(self) -> Node:
        ch = self.next()
        if ch == "(":
            return self.group()
        if ch == "[":
            return Lit(self.char_class())
        if ch == ".":
            return Lit(_ANY.copy())
        if ch == "\\":
            return Lit(self.escape(in_class=False))
        if ch in "*+?{":
            self.error(f"dangling quantifier {ch!r}")
        b = ch.encode("utf-8")
        if len(b) > 1:
            # multi-byte char -> byte sequence (exact only unquantified)
            return Seq([Lit(_mask_of(x)) for x in b])
        return Lit(_mask_of(b[0]))

    def group(self) -> Node:
        capturing = True
        if self.peek() == "?":
            self.next()
            nxt = self.peek()
            if nxt == ":":
                self.next()
                capturing = False
            else:
                self.error("only (?:...) groups supported "
                           "(no lookaround/named groups/flags)")
        if capturing:
            self.n_groups += 1
            idx = self.n_groups
        node = self.alternation()
        if self.peek() != ")":
            self.error("unterminated group")
        self.next()
        return Group(node, idx) if capturing else node

    def escape(self, in_class: bool) -> np.ndarray:
        ch = self.peek()
        if ch is None:
            self.error("dangling backslash")
        self.next()
        if ch in _CLASS_ESCAPES:
            return _CLASS_ESCAPES[ch].copy()
        if ch in _CHAR_ESCAPES and ch != "0":
            return _mask_of(_CHAR_ESCAPES[ch])
        if ch == "0":
            return _mask_of(0)
        if ch in "123456789":
            self.error("backreferences not supported")
        if ch in ("b", "B", "A", "Z", "z", "G"):
            self.error(f"\\{ch} boundaries not supported")
        if ch in ("p", "P"):
            self.error("unicode classes not supported")
        if ch == "x":
            h = self.p[self.i:self.i + 2]
            if len(h) == 2:
                self.i += 2
                return _mask_of(int(h, 16))
            self.error("bad \\x escape")
        # escaped literal (covers metacharacters and anything else ASCII)
        b = ch.encode("utf-8")
        if len(b) > 1:
            self.error("escaped multi-byte character")
        return _mask_of(b[0])

    def char_class(self) -> np.ndarray:
        neg = False
        if self.peek() == "^":
            self.next()
            neg = True
        mask = np.zeros(256, dtype=bool)
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                self.error("unterminated character class")
            if ch == "]" and not first:
                self.next()
                break
            first = False
            if ch == "\\":
                self.next()
                mask |= self.escape(in_class=True)
                continue
            self.next()
            b = ch.encode("utf-8")
            if len(b) > 1:
                self.error("multi-byte character in class")
            lo = b[0]
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.next()
                hi_ch = self.next()
                if hi_ch == "\\":
                    hi_mask = self.escape(in_class=True)
                    hid = np.nonzero(hi_mask)[0]
                    if len(hid) != 1:
                        self.error("bad range end")
                    hi = int(hid[0])
                else:
                    hb = hi_ch.encode("utf-8")
                    if len(hb) > 1:
                        self.error("multi-byte character in class")
                    hi = hb[0]
                if hi < lo:
                    self.error("reversed range")
                mask |= _range_mask(lo, hi)
            else:
                mask[lo] = True
        if neg:
            mask = ~mask
            mask[ord("\n")] = mask[ord("\n")]  # Java negated classes DO
            # match newline; keep as-is
        return mask


def _clone(node: Node) -> Node:
    if isinstance(node, Lit):
        return Lit(node.mask.copy())
    if isinstance(node, Seq):
        return Seq([_clone(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_clone(o) for o in node.options])
    if isinstance(node, Star):
        return Star(_clone(node.child))
    if isinstance(node, Group):
        # clones from counted-repeat expansion share the group index;
        # span analysis only supports groups outside repeats anyway
        return Group(_clone(node.child), node.idx)
    return Empty()


def parse_regex(pattern: str):
    """-> (ast, anchored_start, anchored_end); raises RegexUnsupported."""
    p = _Parser(pattern)
    ast = p.parse()
    return ast, p.anchored_start, p.anchored_end
