"""Glushkov position-automaton construction: regex AST -> RegexProgram
(first/last/follow bit sets + per-byte position masks).

The Glushkov automaton has one state per literal *position* in the regex —
no epsilon transitions, which is what makes the device step a pure bitwise
operation: next = (follow(state) | inject) & byte_class_mask[byte]. The
reference reaches the same endpoint via cuDF's regex VM; on TPU the
bit-parallel formulation vectorizes across the whole column.
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

import numpy as np

from .parser import (
    Alt, Empty, Lit, Node, RegexUnsupported, Seq, Star, parse_regex,
)

#: state mask is a uint32 — positions beyond this reject at plan time
MAX_POSITIONS = 32


class _Info:
    __slots__ = ("nullable", "first", "last")

    def __init__(self, nullable: bool, first: Set[int], last: Set[int]):
        self.nullable = nullable
        self.first = first
        self.last = last


class RegexProgram:
    """Compiled pattern, ready for the device kernel."""

    def __init__(self, pattern: str, n_pos: int, byte_table: np.ndarray,
                 follow_rows: np.ndarray, first_mask: int, last_mask: int,
                 nullable: bool, anchored_start: bool, anchored_end: bool):
        self.pattern = pattern
        self.n_pos = n_pos
        #: (256,) uint32: positions whose byte class contains each byte
        self.byte_table = byte_table
        #: (n_pos,) uint32: follow set of each position
        self.follow_rows = follow_rows
        self.first_mask = first_mask
        self.last_mask = last_mask
        self.nullable = nullable
        self.anchored_start = anchored_start
        self.anchored_end = anchored_end

    def __repr__(self):
        return (f"RegexProgram({self.pattern!r}, states={self.n_pos}, "
                f"^={self.anchored_start}, $={self.anchored_end})")


def _build(node: Node, positions: List[np.ndarray],
           follow: List[Set[int]]) -> _Info:
    if isinstance(node, Empty):
        return _Info(True, set(), set())
    if isinstance(node, Lit):
        idx = len(positions)
        if idx >= MAX_POSITIONS:
            raise RegexUnsupported(
                f"pattern needs more than {MAX_POSITIONS} positions")
        positions.append(node.mask)
        follow.append(set())
        return _Info(False, {idx}, {idx})
    if isinstance(node, Seq):
        info = _build(node.parts[0], positions, follow)
        for part in node.parts[1:]:
            nxt = _build(part, positions, follow)
            for l in info.last:
                follow[l] |= nxt.first
            first = info.first | nxt.first if info.nullable else info.first
            last = nxt.last | info.last if nxt.nullable else nxt.last
            info = _Info(info.nullable and nxt.nullable, first, last)
        return info
    if isinstance(node, Alt):
        infos = [_build(o, positions, follow) for o in node.options]
        return _Info(any(i.nullable for i in infos),
                     set().union(*(i.first for i in infos)),
                     set().union(*(i.last for i in infos)))
    if isinstance(node, Star):
        inner = _build(node.child, positions, follow)
        for l in inner.last:
            follow[l] |= inner.first
        return _Info(True, inner.first, inner.last)
    from .parser import Group
    if isinstance(node, Group):
        return _build(node.child, positions, follow)
    raise RegexUnsupported(f"unknown node {type(node).__name__}")


def _mask_of_set(s: Set[int]) -> int:
    m = 0
    for i in s:
        m |= 1 << i
    return m


def _compile(ast: Node, pattern: str, anchored_start: bool,
             anchored_end: bool) -> RegexProgram:
    positions: List[np.ndarray] = []
    follow: List[Set[int]] = []
    info = _build(ast, positions, follow)
    n = len(positions)
    byte_table = np.zeros(256, dtype=np.uint32)
    for i, mask in enumerate(positions):
        byte_table[mask] |= np.uint32(1 << i)
    follow_rows = np.array([_mask_of_set(f) for f in follow],
                           dtype=np.uint32) if n else \
        np.zeros(0, dtype=np.uint32)
    return RegexProgram(pattern, n, byte_table, follow_rows,
                        _mask_of_set(info.first), _mask_of_set(info.last),
                        info.nullable, anchored_start, anchored_end)


def compile_regex(pattern: str) -> RegexProgram:
    """Java-regex subset -> device program; RegexUnsupported on rejects
    (the planner turns that into an off-TPU tag, reference behavior)."""
    ast, a_start, a_end = parse_regex(pattern)
    return _compile(ast, pattern, a_start, a_end)


def like_to_program(pattern: str, escape: str = "\\") -> RegexProgram:
    """SQL LIKE -> device program: % = any run, _ = any one byte, escape
    char per Spark's LIKE ... ESCAPE (anchored both ends)."""
    from .parser import Lit as PLit, Seq as PSeq, Star as PStar, Empty as PEmpty
    any_byte = np.ones(256, dtype=bool)
    parts: List[Node] = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape:
            if i + 1 >= len(pattern):
                raise RegexUnsupported(
                    f"LIKE pattern {pattern!r} ends with escape")
            nxt = pattern[i + 1]
            for b in nxt.encode("utf-8"):
                m = np.zeros(256, dtype=bool)
                m[b] = True
                parts.append(PLit(m))
            i += 2
            continue
        if ch == "%":
            parts.append(PStar(PLit(any_byte.copy())))
        elif ch == "_":
            parts.append(PLit(any_byte.copy()))
        else:
            for b in ch.encode("utf-8"):
                m = np.zeros(256, dtype=bool)
                m[b] = True
                parts.append(PLit(m))
        i += 1
    ast: Node = PSeq(parts) if len(parts) > 1 else \
        (parts[0] if parts else PEmpty())
    return _compile(ast, f"LIKE:{pattern}", True, True)
