"""Device regex — the engine's answer to the reference's regex transpiler
(RegexParser.scala:687 + cuDF device regex). SURVEY §2.8 flags this as the
hardest expression family; the TPU design is different from cuDF's
backtracking VM: a Java-regex *subset* parses to a Glushkov position
automaton (≤ 32 positions, one uint32 state mask per row) and matching is
a vectorized device loop — each step advances EVERY row by one byte with
pure bitwise VPU ops, trip count = max row length (device scalar, no
recompile).

Unsupported constructs (backreferences, lookaround, lazy quantifiers,
unbounded counted repeats, char-by-char Unicode classes) raise
RegexUnsupported at PLAN time so the planner can tag the expression off
the TPU — exactly the reference's transpile-or-fallback contract.
"""

from .parser import RegexUnsupported, parse_regex  # noqa: F401
from .program import RegexProgram, compile_regex, like_to_program  # noqa: F401
from .kernel import regex_find  # noqa: F401
