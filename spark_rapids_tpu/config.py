"""Typed config registry — the engine's RapidsConf (reference
RapidsConf.scala: ConfEntry :121, TypedConfBuilder :201, registry :319-333,
212 spark.rapids.* entries, docs generated via help() :149).

Keys keep the `spark.rapids.*` UX (BASELINE.json requires config
compatibility) with TPU-specific entries under `spark.rapids.tpu.*`.
`generate_docs()` renders docs/configs.md the same way the reference does.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional


class ConfEntry:
    def __init__(self, key: str, default, doc: str, conv: Callable[[str], Any],
                 internal: bool = False, startup_only: bool = False,
                 commonly_used: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.conv = conv
        self.internal = internal
        self.startup_only = startup_only
        self.commonly_used = commonly_used

    def get(self, conf: "RapidsConf"):
        raw = conf._settings.get(self.key)
        if raw is None:
            return self.default
        if isinstance(raw, str):
            return self.conv(raw)
        return raw


_REGISTRY: Dict[str, ConfEntry] = {}


def _register(entry: ConfEntry) -> ConfEntry:
    assert entry.key not in _REGISTRY, f"duplicate conf {entry.key}"
    _REGISTRY[entry.key] = entry
    return entry


def _bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes")


def _bytes(s: str) -> int:
    s = s.strip().lower()
    mult = 1
    for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30),
                      ("t", 1 << 40)):
        if s.endswith(suffix + "b"):
            s, mult = s[:-2], m
            break
        if s.endswith(suffix):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


def conf_bool(key, default, doc, **kw):
    return _register(ConfEntry(key, default, doc, _bool, **kw))


def conf_int(key, default, doc, **kw):
    return _register(ConfEntry(key, default, doc, int, **kw))


def conf_float(key, default, doc, **kw):
    return _register(ConfEntry(key, default, doc, float, **kw))


def conf_str(key, default, doc, **kw):
    return _register(ConfEntry(key, default, doc, str, **kw))


def conf_bytes(key, default, doc, **kw):
    return _register(ConfEntry(key, default, doc, _bytes, **kw))


# --- core entries (mirroring the reference's most load-bearing keys) ------

SQL_ENABLED = conf_bool(
    "spark.rapids.sql.enabled", True,
    "Master toggle: when false every operator stays on the CPU path "
    "(reference RapidsConf.scala SQL_ENABLED).", commonly_used=True)

EXPLAIN = conf_str(
    "spark.rapids.sql.explain", "NOT_ON_GPU",
    "Explain mode: NONE, NOT_ON_GPU (log why operators fell back), ALL "
    "(reference sql.explain).", commonly_used=True)

BATCH_SIZE_BYTES = conf_bytes(
    "spark.rapids.sql.batchSizeBytes", 1 << 30,
    "Target output batch size; on TPU this is the target *padded capacity "
    "bucket* footprint (reference RapidsConf.scala:559).", commonly_used=True)

EXCHANGE_ROUND_BYTES = conf_bytes(
    "spark.rapids.sql.exchange.roundBytes", 1 << 28,
    "Per-round input budget for the mesh shuffle exchange: child batches "
    "stream through the ICI collective in fixed-size rounds with "
    "spillable staging instead of materializing the whole stage input "
    "(round-2 verdict item 6; reference bounds the same path with "
    "spillable shuffle buffers).")

MAX_READER_BATCH_SIZE_ROWS = conf_int(
    "spark.rapids.sql.reader.batchSizeRows", 1 << 20,
    "Soft cap on rows per scan batch (reference reader.batchSizeRows).")

CONCURRENT_TPU_TASKS = conf_int(
    "spark.rapids.sql.concurrentGpuTasks", 2,
    "Admission-semaphore width: concurrent tasks allowed to issue device "
    "work (reference RapidsConf.scala:544 concurrentGpuTasks; on TPU this "
    "gates enqueue into the per-chip executor).", commonly_used=True)

HBM_POOL_FRACTION = conf_float(
    "spark.rapids.memory.tpu.allocFraction", 0.9,
    "Fraction of device HBM the engine budget manager may use (reference "
    "rmm allocFraction).", startup_only=True)

HBM_BUDGET_BYTES = conf_bytes(
    "spark.rapids.memory.tpu.budgetBytes", 0,
    "Absolute HBM budget override; 0 = derive from allocFraction and "
    "detected device memory.", startup_only=True)

HOST_SPILL_LIMIT = conf_bytes(
    "spark.rapids.memory.host.spillStorageSize", 4 << 30,
    "Bytes of host memory for spilled buffers before overflowing to disk "
    "(reference host.spillStorageSize).")

SPILL_DIR = conf_str(
    "spark.rapids.memory.spillDirectory", "",
    "Directory for disk-tier spill files; empty = system temp.")

RETRY_MAX_ATTEMPTS = conf_int(
    "spark.rapids.sql.retry.maxAttempts", 10,
    "Upper bound on OOM-retry attempts before surfacing the failure "
    "(guards the withRetry loop, reference RmmRapidsRetryIterator).")

SHUFFLE_MODE = conf_str(
    "spark.rapids.shuffle.mode", "MULTITHREADED",
    "Shuffle mode: MULTITHREADED (host, works everywhere), ICI (resident "
    "mesh all-to-all over interconnect), CACHE_ONLY (reference "
    "RapidsShuffleManagerMode).", commonly_used=True)

BROADCAST_SIZE_THRESHOLD = conf_bytes(
    "spark.rapids.sql.broadcastSizeThreshold", 10 << 20,
    "Max estimated build-side bytes for planning a broadcast hash join "
    "instead of exchanging both sides (Spark's "
    "spark.sql.autoBroadcastJoinThreshold; reference "
    "GpuBroadcastHashJoinExecBase). -1 disables broadcast planning.",
    commonly_used=True)

SHUFFLE_PLAN_EXCHANGE = conf_bool(
    "spark.rapids.tpu.shuffle.planExchange", True,
    "Plan distributed stages when a multi-device mesh is active (session "
    "mesh_devices / parallel.mesh.set_active_mesh): group-bys become "
    "partial → ICI all-to-all exchange → final, equi-joins become "
    "exchange-both-sides → per-partition shuffled hash join (reference "
    "GpuShuffleExchangeExecBase planning).", commonly_used=True)

OPTIMIZER_ENABLED = conf_bool(
    "spark.rapids.sql.optimizer.enabled", False,
    "Cost-based device-vs-host placement: device-eligible Project/Filter "
    "sections whose modeled host cost (row interpreter + transitions) "
    "beats the device cost (program dispatch + bandwidth) run on the "
    "host row engine — tiny inputs, mainly (reference "
    "CostBasedOptimizer.scala, also default-off).")

PALLAS_ENABLED = conf_bool(
    "spark.rapids.tpu.pallas.enabled", True,
    "Use hand-written Pallas TPU kernels for hash hotspots (murmur3 "
    "partition/join/group-by hashing) instead of the fused-XLA path "
    "when running on real TPU hardware (SURVEY §2.9 Pallas tier; "
    "reference analog: spark-rapids-jni hand-tuned CUDA Hash kernels). "
    "Off-TPU backends always use the XLA path; tests drive the kernel "
    "via the Pallas interpreter for bit-exactness.")

PALLAS_FUSED_TIER = conf_str(
    "spark.rapids.tpu.pallas.fusedTier", "auto",
    "Fused Pallas kernel tier for the join-probe and scan-aggregate hot "
    "paths: 'off' keeps the XLA formulations, 'on' forces the fused "
    "kernels (interpret-mode off-TPU — the correctness/test setting), "
    "'auto' (default) consults the per-shape-bucket XLA-vs-Pallas "
    "timings recorded by tools/kern_bench.py and picks the measured "
    "winner; with no recorded measurement for a shape the XLA tier "
    "stays — the tier choice is a measurement, not a guess.",
    commonly_used=True)

PALLAS_FUSED_BENCH_FILE = conf_str(
    "spark.rapids.tpu.pallas.fusedTier.benchFile", "",
    "Path of the kernel-microbenchmark record file driving "
    "fusedTier=auto (written by tools/kern_bench.py). Empty = "
    "tools/kern_bench.json next to the package if present.")

DEBUG_DUMP_PATH = conf_str(
    "spark.rapids.sql.debug.dumpPath", "",
    "When set, operators wrapped in dump_on_error write their input "
    "batches (parquet + metadata) and a repro script there on failure "
    "(reference DumpUtils.scala / spark.rapids.sql.debug dump hooks). "
    "Empty disables dumping.")

UDF_COMPILER_ENABLED = conf_bool(
    "spark.rapids.sql.udfCompiler.enabled", False,
    "Decompile Python UDF bytecode into device expressions when possible "
    "(the reference's udf-compiler module / "
    "spark.rapids.sql.udfCompiler.enabled). Compiled UDFs use SQL null "
    "semantics (NULL propagates) rather than raising on None — opt-in, "
    "like the reference.", commonly_used=True)

CPU_FALLBACK_ENABLED = conf_bool(
    "spark.rapids.sql.cpuFallback.enabled", True,
    "Run Project/Filter nodes whose expressions have no device kernel on "
    "the host row engine (ColumnarToRow → host operator → RowToColumnar), "
    "instead of failing the whole plan — the reference's per-operator "
    "convertToCpu fallback (GpuOverrides.scala:4427). Only expressions "
    "the host interpreter implements fall back; others still fail with "
    "the full explain report.", commonly_used=True)

JOIN_SUBPARTITION_THRESHOLD = conf_bytes(
    "spark.rapids.sql.join.subPartitionThreshold", 1 << 30,
    "When a join BUILD side's estimated size exceeds this, the planner "
    "splits the join into hash sub-partitions via the host shuffle so "
    "each sub-partition's build side fits device memory — the "
    "reference's GpuSubPartitionHashJoin.scala:547 big-build-side "
    "strategy. Requires shuffle mode MULTITHREADED; raises (never "
    "lowers) spark.rapids.sql.shuffle.partitions. -1 disables.",
    commonly_used=True)

SHUFFLE_PARTITIONS = conf_int(
    "spark.rapids.sql.shuffle.partitions", 1,
    "Partition count for host-shuffled stages (Spark's "
    "spark.sql.shuffle.partitions). With no multi-device mesh, a value "
    "> 1 plans group-bys and equi-joins through the MULTITHREADED host "
    "shuffle (partial → host exchange → final), bounding device memory "
    "per partition — the out-of-core repartition path.",
    commonly_used=True)

SHUFFLE_DEVICE_PARTITION = conf_bool(
    "spark.rapids.tpu.shuffle.devicePartition.enabled", True,
    "Device-side shuffle partition split for the MULTITHREADED host "
    "shuffle writer (exec/exchange.py + ops/partition_split.py): the "
    "hash/roundrobin/single lanes compute per-partition counts and a "
    "pid-stable permutation on device, reorder the batch into "
    "partition-major order through the gather engine (ops/gather.py — "
    "tier-aware: the Pallas DMA gather when the `gather` family has a "
    "recorded win, the XLA packed row gather otherwise), land it on the "
    "host as ONE packed D2H copy (columnar/transfer.py) and serialize "
    "each partition directly from a row-range slice "
    "(shuffle/serializer.serialize_slice) — zero host-side row gathers "
    "per written batch (the reference's GpuHashPartitioning + "
    "contiguous_split + JCudfSerialization shape). Range partitioning "
    "keeps the host lane (its sampled split bounds are host objects). "
    "Off restores the host argsort-and-slice partitioner.",
    commonly_used=True)

SHUFFLE_ICI_ENABLED = conf_bool(
    "spark.rapids.tpu.shuffle.ici.enabled", False,
    "ICI-native device-resident shuffle lane for the host shuffle "
    "exchange (exec/exchange.py + parallel/exchange.py, ISSUE 16): when "
    "an active mesh's axis size equals the exchange's partition count, "
    "map output is hash-partitioned, packed into a measured "
    "(partitions, slot_cap) send grid and exchanged device-to-device "
    "with jax.lax.all_to_all over the mesh axis — zero host "
    "serialize/deserialize and zero per-batch D2H/H2D on the hot path "
    "(the reference's UCX/NVLink shuffle transport as an ICI "
    "collective). Received shards stage as spillable catalog entries, "
    "so the spill/quota contracts hold. The host serialize/LZ4 lane "
    "remains the fallback tier: range partitioning, mismatched "
    "partition counts, single-device runs, an open `ici_exchange` "
    "breaker, or a failed collective round degrade per exchange to the "
    "always-works host path. Default off: behavior is byte-identical "
    "to the host lane either way.",
    commonly_used=True)

UPLOAD_PACKED = conf_bool(
    "spark.rapids.tpu.transfer.packedUpload.enabled", True,
    "Packed host->device batch upload (columnar/upload.py — the ingest "
    "mirror of the packed D2H fetch): a decoded batch's row count and "
    "every column buffer are laid into ONE contiguous uint8 staging "
    "buffer drawn from a reusable capacity-bucketed pool, cross the "
    "host->device boundary as ONE transfer, and a jitted device program "
    "slices/bitcasts them back into column arrays — byte-identical to "
    "the per-buffer jnp.asarray lane. Wired at every ingest seam: scan "
    "batch upload, shuffle-read decode promotion, and spill unspill "
    "(the reference's JCudfSerialization / HostConcatResult one-copy "
    "table shape). Off, or for column trees the packer does not "
    "recognize, each buffer uploads individually (2-3 transfers per "
    "column).",
    commonly_used=True)

UPLOAD_POOL_BYTES = conf_bytes(
    "spark.rapids.tpu.transfer.packedUpload.poolBytes", 256 * 1024 * 1024,
    "Total bytes of IDLE staging buffers the packed-upload pool may "
    "retain (the pinned-host-memory analog). Buffers are "
    "capacity-bucketed powers of two, reused LIFO (cache-warm) and "
    "trimmed least-recently-used past this cap; in-flight buffers are "
    "never capped. 0 disables pooling (every upload allocates).")

SHUFFLE_WRITER_THREADS = conf_int(
    "spark.rapids.shuffle.multiThreaded.writer.threads", 8,
    "Writer-side serialization threads (reference "
    "RapidsShuffleInternalManagerBase.scala:238).")

SHUFFLE_READER_THREADS = conf_int(
    "spark.rapids.shuffle.multiThreaded.reader.threads", 8,
    "Reader-side fetch/decode threads (reference :569).")

PARQUET_READER_TYPE = conf_str(
    "spark.rapids.sql.format.parquet.reader.type", "MULTITHREADED",
    "Parquet reader strategy: MULTITHREADED (prefetch pool, one device "
    "upload per row group) or COALESCING (stitch small row groups "
    "host-side into ~batchSize tables before upload; reference "
    "GpuMultiFileReader.scala:830).")

PARQUET_REBASE_MODE_READ = conf_str(
    "spark.rapids.sql.format.parquet.datetimeRebaseModeInRead", "CORRECTED",
    "Datetime rebase for parquet reads: CORRECTED (values are proleptic "
    "Gregorian, pass through) or LEGACY (file was written by Spark < 3.0 "
    "in the hybrid Julian calendar; DATE/TIMESTAMP are rebased on device "
    "— reference datetimeRebaseUtils.scala + JNI DateTimeRebase).")

PARQUET_PUSHDOWN_ENABLED = conf_bool(
    "spark.rapids.sql.format.parquet.filterPushdown.enabled", True,
    "Push simple comparison conjuncts from a Filter into the parquet scan "
    "for footer min/max row-group pruning (reference "
    "GpuParquetScan predicate pushdown).")

SCAN_ENCODED = conf_bool(
    "spark.rapids.tpu.scan.encoded.enabled", True,
    "Dictionary-encoded execution (columnar/encoded.py, ISSUE 18): the "
    "parquet scan requests Arrow dictionary arrays for string columns "
    "and keeps them encoded as a DictionaryColumn — a device-resident "
    "i32 code lane plus the per-batch dictionary payload — instead of "
    "eagerly decoding to full-width strings at scan time. Codes + "
    "dictionary ride the packed H2D upload and spill/unspill as-is "
    "(typically a >=2x byte shrink on string-heavy scans), equality / "
    "IN / null predicates compare i32 codes on device, and hash joins "
    "hash the dictionary once then gather precomputed hashes by code. "
    "Operators that cannot consume encoded input trigger a "
    "materialize-at-boundary decode through the gather engine, so "
    "results are byte-identical with the lane on or off. Off restores "
    "eager decode at StringColumn.from_arrow.",
    commonly_used=True)

MULTITHREADED_READ_NUM_THREADS = conf_int(
    "spark.rapids.sql.multiThreadedRead.numThreads", 8,
    "Threads for the cloud multi-file readers (reference "
    "GpuMultiFileReader.scala:345). Sizes the ONE process-wide decode "
    "pool shared by every scan (io/multifile.py): concurrent scans and "
    "pipeline producer threads draw from it instead of multiplying "
    "thread counts with per-call pools.")

MULTITHREADED_READ_FETCH_AHEAD = conf_int(
    "spark.rapids.sql.multiThreadedRead.fetchAheadWindow", 0,
    "Decode tasks a multi-file reader may have in flight ahead of the "
    "consumer (the fetch-ahead window of the multithreaded cloud "
    "reader). 0 (default) = 2 x the reader's own thread count (its "
    "num_threads argument, not multiThreadedRead.numThreads).")

PIPELINE_ENABLED = conf_bool(
    "spark.rapids.tpu.pipeline.enabled", True,
    "Asynchronous pipelined execution (exec/pipeline.py): bounded "
    "producer threads overlap file decode + host->device transfer, "
    "shuffle-partition deserialization and coalesce accumulation with "
    "downstream device compute — the engine analog of the reference's "
    "multithreaded reader / async shuffle overlap. Results are "
    "bit-identical with pipelining on or off (tier-1 asserted); off "
    "degrades every boundary to the plain synchronous iterator.",
    commonly_used=True)

PIPELINE_DEPTH = conf_int(
    "spark.rapids.tpu.pipeline.depth", 2,
    "Batches a pipeline producer may queue ahead of its consumer at "
    "each pipelined stage boundary (the bounded prefetch window). "
    "Higher overlaps more at the cost of holding more batches live; "
    "0 behaves like pipeline.enabled=false.")

SPILL_ASYNC_WRITE = conf_bool(
    "spark.rapids.tpu.spill.asyncWrite", True,
    "Background spill writeback (memory/catalog.py): a tier hop hands "
    "the buffer to a single writer thread and releases the triggering "
    "operator immediately (device->host copy and host->disk write+fsync "
    "run behind the operator); readers of an in-flight buffer block "
    "until its writeback completes, so results are identical with the "
    "writer on or off. False restores fully synchronous spilling.")

PROFILE_ENABLED = conf_bool(
    "spark.rapids.tpu.profile.enabled", False,
    "Capture jax profiler traces (xprof/TensorBoard) around driven "
    "queries; operator names appear as trace annotations over their XLA "
    "ops (reference spark.rapids.profile.* NVTX integration).")

PROFILE_DIR = conf_str(
    "spark.rapids.tpu.profile.dir", "",
    "Output directory for captured profiler traces; empty = "
    "/tmp/spark_rapids_tpu_trace.")

METRICS_LEVEL = conf_str(
    "spark.rapids.sql.metrics.level", "MODERATE",
    "ESSENTIAL | MODERATE | DEBUG (reference GpuExec.scala:36-47): "
    "metric registries report only entries at or below this level — "
    "TpuExec.all_metrics(), last_query_metrics() and the query profile "
    "all honor it, so DEBUG metrics (per-operator input row/batch "
    "counts) stay out of summaries unless asked for.")

EVENT_LOG_ENABLED = conf_bool(
    "spark.rapids.tpu.eventLog.enabled", False,
    "Write the structured JSONL query event log (obs/events.py): query "
    "begin/end, per-operator open/batch/close spans with wall-ns and "
    "row/byte counts, semaphore waits, spill and OOM-retry events, "
    "Pallas tier decisions, plan fallback reasons, exchange transfer "
    "volumes. Off (default) costs one pointer check per batch — the "
    "analog of the reference's Spark-event/NVTX metric stream.",
    commonly_used=True)

EVENT_LOG_DIR = conf_str(
    "spark.rapids.tpu.eventLog.dir", "",
    "Directory for event-log files (one events-<pid>-<n>.jsonl per "
    "configured bus); empty = /tmp/spark_rapids_tpu_events. Render a "
    "log with tools/profile_report.py.")

EVENT_LOG_LEVEL = conf_str(
    "spark.rapids.tpu.eventLog.level", "MODERATE",
    "ESSENTIAL | MODERATE | DEBUG: event kinds above this level are "
    "dropped at emit time. ESSENTIAL = query begin/end only; MODERATE "
    "adds operator close spans, spills, retries, semaphore waits, tier "
    "and plan decisions, exchange volumes; DEBUG adds per-batch "
    "operator spans and span-API records.")

EVENT_LOG_MAX_BYTES = conf_bytes(
    "spark.rapids.tpu.eventLog.maxBytes", 0,
    "Rotate the JSONL event-log sink once the current file reaches this "
    "many bytes: the file closes and writing continues in "
    "events-<pid>-<n>.<rot>.jsonl (rot = 1, 2, ...), so a long soak or "
    "bench storm never grows one unbounded file. "
    "tools/profile_report.py reads a rotated set in order when given "
    "any member. 0 (default) = unbounded, no rotation.")

DISPATCH_LEDGER_ENABLED = conf_bool(
    "spark.rapids.tpu.dispatch.ledger.enabled", True,
    "Process-wide jit dispatch ledger (obs/dispatch.py): every engine "
    "program dispatch is counted per stable program key (owning "
    "exec/family x arg-shape bucket x platform) with first-trace vs "
    "cache-hit discrimination, trace/compile wall-ns and donated vs "
    "retained argument bytes; wired execs accumulate numDispatches / "
    "compileTimeNs metrics and QueryProfile.dispatch_summary() reads "
    "them as the whole-stage-compilation baseline. On (default) costs "
    "host-side bookkeeping per dispatch (noise against jit dispatch "
    "overhead); explicitly false = one pointer check per dispatch and "
    "no records. Results are byte-identical either way.")

DISPATCH_STORM_TRACES = conf_int(
    "spark.rapids.tpu.dispatch.storm.traces", 8,
    "Recompile-storm threshold: when one program key (see "
    "dispatch.ledger.enabled) is RE-traced this many times inside "
    "dispatch.storm.windowMs, the ledger emits one `recompile_storm` "
    "event (ESSENTIAL) — the shape-bucket-churn failure mode where "
    "every batch arrives with a new exact shape and every dispatch "
    "pays a fresh XLA compile. A program site's FIRST trace of a "
    "bucket is a new program, not churn, and never counts.")

DISPATCH_STORM_WINDOW_MS = conf_int(
    "spark.rapids.tpu.dispatch.storm.windowMs", 10000,
    "Sliding window for the recompile-storm detector. After a storm "
    "fires, the same program key stays quiet for one window (a storm "
    "is one incident, not one event per churning batch).")

TELEMETRY_ENABLED = conf_bool(
    "spark.rapids.tpu.telemetry.enabled", False,
    "Live telemetry registry + sampler (obs/telemetry.py): a "
    "`telemetry-sampler` thread snapshots per-owner HBM attribution, "
    "link bytes (H2D uploads / packed D2H fetches), admission queue "
    "depth, semaphore wait, breaker states and spill volumes every "
    "telemetry.intervalMs into bounded ring-buffer series, and flushes "
    "each snapshot to the event log (when enabled) as a "
    "`telemetry_sample` record — render with tools/telemetry_export.py "
    "(Prometheus text format). Off (default) costs one pointer check "
    "per push-counter site and no sampling thread.",
    commonly_used=True)

TELEMETRY_INTERVAL_MS = conf_int(
    "spark.rapids.tpu.telemetry.intervalMs", 1000,
    "Sampling period of the telemetry registry's exporter thread "
    "(min 10ms). Each tick reads every gauge source once — lock-light "
    "snapshots, no device syncs.")

TELEMETRY_HISTORY_SIZE = conf_int(
    "spark.rapids.tpu.telemetry.historySize", 120,
    "Samples each telemetry series retains in its in-memory ring "
    "buffer (TpuSession.health()['telemetry'] reads the newest; older "
    "samples survive only in the event log).")

PHASES_ENABLED = conf_bool(
    "spark.rapids.tpu.phases.enabled", True,
    "Per-query wall-clock phase attribution (obs/phase.py): every "
    "governed collect() carries a ledger partitioning its total "
    "wall-clock into the closed phase set (admission-wait, compile, "
    "device-compute, host-pack/serialize, shuffle-io, ici-collective, "
    "spill-wait, semaphore-wait, pipeline-stall, retry-backoff, other) "
    "with sum(phases) == wall_ns exactly. Surfaced via "
    "QueryProfile.phases(), the query_phases event (ESSENTIAL) and the "
    "query-history capsule. Explicitly false = one pointer check per "
    "accrual site, no ledger; results are byte-identical either way. "
    "The process-cumulative phase counters bench.py deltas stay on "
    "regardless (the runtime-statistics discipline).")

HISTORY_ENABLED = conf_bool(
    "spark.rapids.tpu.history.enabled", False,
    "Persistent query history (obs/history.py): at the end of every "
    "collect() append ONE self-describing JSONL capsule — plan "
    "fingerprint, phase ledger, essential metrics, statistics skew "
    "summary, dispatch/shuffle/upload deltas, outcome/priority/attempts "
    "— to history-<pid>-<n>.jsonl under history.dir. Capsules from "
    "different sessions and processes in one dir never collide and "
    "survive restarts; aggregate/diff/advise over a dir with "
    "tools/history_report.py. Off (default) costs one pointer check "
    "per collect.", commonly_used=True)

HISTORY_DIR = conf_str(
    "spark.rapids.tpu.history.dir", "",
    "Directory for query-history capsule files (one "
    "history-<pid>-<n>.jsonl per configured store); empty = "
    "/tmp/spark_rapids_tpu_history. Render with "
    "tools/history_report.py (aggregate per plan fingerprint, "
    "--diff BASE for phase-ranked regressions, advisor rules).")

HISTORY_MAX_BYTES = conf_bytes(
    "spark.rapids.tpu.history.maxBytes", 0,
    "Rotate the history capsule file once it reaches this many bytes: "
    "the file closes and writing continues in "
    "history-<pid>-<n>.<rot>.jsonl (the eventLog.maxBytes pattern); "
    "tools/history_report.py reads a rotated set in order. 0 (default) "
    "= unbounded, no rotation.")

SORT_OOC_ENABLED = conf_bool(
    "spark.rapids.sql.sort.outOfCore.enabled", True,
    "Bounded-memory streamed run merge for big sorts: runs stay spilled, "
    "only MERGE_FAN_IN chunks are device-resident at a time, and output "
    "batches emit as soon as they are globally final (reference "
    "GpuOutOfCoreSortIterator, GpuSortExec.scala:281).")

STABLE_SORT = conf_bool(
    "spark.rapids.sql.stableSort.enabled", False,
    "Force fully stable sorts (reference stableSort.enabled).")

IMPROVED_FLOAT_OPS = conf_bool(
    "spark.rapids.sql.improvedFloatOps.enabled", True,
    "Allow float results that differ from Spark in last-ulp ways — on TPU "
    "f64 is double-float emulated so this also gates f64-heavy plans "
    "(reference improvedFloatOps).")

TEST_RETRY_OOM_INJECTION_MODE = conf_str(
    "spark.rapids.sql.test.injectRetryOOM", "",
    "Fault injection: 'retry:N' / 'split:N' throws TpuRetryOOM / "
    "TpuSplitAndRetryOOM on the Nth guarded device call of each task "
    "(reference RmmSpark fault injection, RmmSparkRetrySuiteBase).",
    internal=True)

TEST_FAULTS = conf_str(
    "spark.rapids.tpu.test.faults", "",
    "Seeded chaos injection at the registered fault points (faults.py): "
    "'<point>:prob=P,seed=S,kind=io|device|corrupt|delay[,max=N]"
    "[,ms=N][;...]'. "
    "Decisions are a pure hash of (seed, point, task_id, call_index), "
    "so any chaos failure replays exactly. Empty (default) = injection "
    "off, one pointer check per site.", internal=True)

IO_RETRIES = conf_int(
    "spark.rapids.tpu.io.retries", 3,
    "Bounded retries on transient OSErrors in the multi-file readers "
    "and the shuffle block fetch (io/retrying.py) before the failure "
    "surfaces; each retry sleeps retryBackoffMs * 2^attempt plus "
    "deterministic jitter and emits a structured io_retry event. "
    "0 disables IO retry.")

IO_RETRY_BACKOFF_MS = conf_int(
    "spark.rapids.tpu.io.retryBackoffMs", 50,
    "Base backoff between IO retry attempts (doubled per attempt, "
    "capped at 2000ms, plus up to 25% deterministic jitter).")

TASK_MAX_ATTEMPTS = conf_int(
    "spark.rapids.tpu.task.maxAttempts", 3,
    "Attempts a task (one driven query) gets before a transient "
    "failure — TpuTaskRetryError, an injected device fault, a non-OOM "
    "XLA runtime error, a checksum-quarantined buffer — becomes fatal "
    "(exec/task_retry.py; the engine analog of Spark's "
    "task-attempt re-execution). 1 disables task retry.")

TASK_RETRY_BACKOFF_MS = conf_int(
    "spark.rapids.tpu.task.retryBackoffMs", 100,
    "Base backoff between task attempts (doubled per attempt, capped "
    "at 5000ms, plus deterministic jitter).")

OOM_RETRY_BACKOFF_MS = conf_int(
    "spark.rapids.tpu.retry.backoffMs", 5,
    "Base sleep between OOM-retry attempts in with_retry (doubled per "
    "attempt, capped at 200ms): gives in-flight spill writebacks and "
    "concurrent tasks time to actually free memory instead of "
    "re-spinning through all attempts in microseconds. 0 restores "
    "immediate retry.")

PIPELINE_CLOSE_TIMEOUT_MS = conf_int(
    "spark.rapids.tpu.pipeline.closeTimeoutMs", 10000,
    "Watchdog on pipeline stage close(): how long to wait for a "
    "producer thread to join before giving up, emitting a "
    "pipeline_stuck event and detaching the (daemon) thread instead of "
    "hanging the query teardown / interpreter exit.")

QUERY_TIMEOUT_MS = conf_int(
    "spark.rapids.tpu.query.timeoutMs", 0,
    "Per-query deadline for session-driven collects (exec/lifecycle.py "
    "query lifecycle governor): a query still running after this many "
    "ms is cooperatively cancelled — the cancellation token is checked "
    "at every batch boundary and inside semaphore / pipeline / spill-"
    "writeback waits, and the query unwinds with QueryCancelledError "
    "(a query_cancelled event records the phase that noticed it). The "
    "deadline spans ALL task re-execution attempts, so one query's "
    "wall-clock is bounded even under chaos. 0 (default) disables the "
    "deadline; TpuSession.cancel_query() works either way.",
    commonly_used=True)

QUERY_CANCEL_CHECK_BATCHES = conf_int(
    "spark.rapids.tpu.query.cancelCheckBatches", 8,
    "How many operator batch boundaries pass between cancellation/"
    "deadline checks of a governed query (exec/lifecycle.py). 1 checks "
    "every batch (lowest cancellation latency); higher values shave "
    "the already-tiny per-batch cost. Outside a governed query each "
    "boundary pays exactly one pointer check.")

PARTITION_RECOVERY_ENABLED = conf_bool(
    "spark.rapids.tpu.task.partitionRecovery.enabled", True,
    "Partition-granular recovery for host-shuffle block corruption "
    "(exec/lifecycle.py + shuffle/manager.py): the exchange captures "
    "per-map-output lineage at write time, and a checksum-quarantined "
    "shuffle block re-executes ONLY the producing sub-plan (the "
    "exchange child) to rewrite that one map output, instead of "
    "re-running the whole query through the task-retry lane. Ambiguous "
    "provenance (spill files, missing lineage, repeated corruption of "
    "one map output) still falls back to whole-plan re-execution.")

STALL_TIMEOUT_MS = conf_int(
    "spark.rapids.tpu.stall.timeoutMs", 0,
    "Progress watchdog for governed queries (exec/speculation_shield.py "
    "— distinct from the total-wall query.timeoutMs deadline): a query "
    "whose driving seam advances no root-output batches or rows for "
    "this many ms emits one query_stalled event (ESSENTIAL, with the "
    "ledger phase the time went into and the stalled operator) and "
    "takes stall.action. 0 (default) disables the watchdog — no "
    "monitor thread, one conf read per collect.")

STALL_ACTION = conf_str(
    "spark.rapids.tpu.stall.action", "report",
    "What the progress watchdog does when a governed query stalls past "
    "stall.timeoutMs: 'report' only emits the query_stalled event; "
    "'retry-seam' additionally fails the stalled attempt with a "
    "transient TpuTaskRetryError at its next cancellation checkpoint, "
    "routing it onto the bounded task-retry lane; 'cancel' cancels the "
    "query cooperatively (QueryCancelledError, reason 'stalled').")

SHUFFLE_SPECULATION_ENABLED = conf_bool(
    "spark.rapids.tpu.shuffle.speculation.enabled", False,
    "Speculative shuffle sub-reads (exec/speculation_shield.py + "
    "shuffle/manager.py): when one per-(map,frame) fetch or decode "
    "future exceeds a latency bound derived from the reader's own "
    "measured distribution (Log2Hist p95 x speculation.multiplier, "
    "floored at speculation.minMs), launch ONE duplicate attempt under "
    "a 'spec:' work-item key — first result wins, the loser is "
    "cancelled or discarded. Bounded by speculation.maxInFlight per "
    "query; each resolution emits a speculative_fetch event. Off "
    "(default) keeps the plain unbounded-wait read path, one conf read "
    "per reader.")

SHUFFLE_SPECULATION_MULTIPLIER = conf_float(
    "spark.rapids.tpu.shuffle.speculation.multiplier", 3.0,
    "Latency-bound factor for speculative shuffle sub-reads: a fetch/"
    "decode is considered straggling once it exceeds multiplier x the "
    "reader's measured p95 for that stage (Spark's "
    "spark.speculation.multiplier analog, against measured quantiles "
    "instead of task medians).")

SHUFFLE_SPECULATION_MIN_MS = conf_int(
    "spark.rapids.tpu.shuffle.speculation.minMs", 100,
    "Floor on the speculative-read latency bound: a fetch/decode is "
    "never speculated before this many ms regardless of how fast the "
    "measured p95 says the stage usually is — cold histograms and "
    "microsecond-fast local reads must not trigger duplicate work.")

SHUFFLE_SPECULATION_MAX_INFLIGHT = conf_int(
    "spark.rapids.tpu.shuffle.speculation.maxInFlight", 2,
    "Speculative duplicate attempts one query may have in flight at "
    "once. A straggling future past the bound with no free slot keeps "
    "waiting on its primary (counted speculative_denied) — duplicates "
    "ride the existing bounded reader pool and are never free "
    "admission-path work.")

DISPATCH_TIMEOUT_MS = conf_int(
    "spark.rapids.tpu.dispatch.timeoutMs", 0,
    "Hang bound on guarded device dispatch (obs/dispatch.py chokepoint "
    "and the ICI collective seam): a dispatched program not ready "
    "after this many ms emits dispatch_timeout, records a "
    "device_dispatch (or ici_exchange) breaker failure, and raises a "
    "transient task-lane error — the wedged call is abandoned on its "
    "watchdog thread instead of hanging the process. 0 (default) "
    "disables the bound: dispatch runs inline with no helper thread.")

DEAD_PEER_INVALIDATION_ENABLED = conf_bool(
    "spark.rapids.tpu.shuffle.deadPeerInvalidation.enabled", True,
    "Dead-peer map-output invalidation (parallel/heartbeat.py + "
    "shuffle/manager.py): a peer_dead transition invalidates the map "
    "outputs registered to that peer, so the next read of one routes "
    "through the partition-granular recompute lane (lineage re-executes "
    "only the producing sub-plan) instead of trusting a dead "
    "executor's shards — Spark's fetch-failure map-output invalidation, "
    "single-process edition. The peer's slot stays blacklisted until "
    "it re-registers. Requires an installed heartbeat manager; without "
    "one (the default single-process session) nothing changes.")

ADAPTIVE_ENABLED = conf_bool(
    "spark.rapids.tpu.adaptive.enabled", True,
    "Adaptive runtime replanning (exec/adaptive.py): consult the "
    "MEASURED per-partition map-output sizes the exchange recorder "
    "already captures and replan at exchange-read boundaries — split a "
    "skewed reducer partition into map-granular sub-reads "
    "(adaptive.skewedPartitionFactor), demote a measured-oversized "
    "broadcast/single-build join to the sub-partitioned strategy "
    "before its first OOM retry (adaptive.autoBroadcastMaxBytes and "
    "the workload governor's quota share), convert a shuffle join "
    "whose build side measured small to single-build, coalesce "
    "adjacent tiny reducer partitions (adaptive.coalesceTargetBytes), "
    "and shrink the query's batch target after an OOM split. CPU "
    "results are unchanged: integer paths stay byte-exact; float "
    "deltas are limited to the documented OOM-split reduction-order "
    "class. A misfiring replan lane demotes itself to the static plan "
    "through the `adaptive` circuit-breaker domain.",
    commonly_used=True)

ADAPTIVE_SKEW_FACTOR = conf_float(
    "spark.rapids.tpu.adaptive.skewedPartitionFactor", 4.0,
    "A reducer partition whose measured bytes exceed this factor times "
    "the median partition size (and adaptive.skewedPartitionMinBytes) "
    "is read as map-output-granular sub-reads, each a separate probe "
    "stream against the replicated build side, so no single hash-join "
    "window holds the whole hot key. <= 0 disables skew splitting.")

ADAPTIVE_SKEW_MIN_BYTES = conf_bytes(
    "spark.rapids.tpu.adaptive.skewedPartitionMinBytes", 16 * 1024 * 1024,
    "Floor below which a reducer partition is never treated as skewed "
    "regardless of its ratio to the median — small exchanges are "
    "cheaper to read whole than to split.")

ADAPTIVE_AUTO_BROADCAST_MAX_BYTES = conf_bytes(
    "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes", 64 * 1024 * 1024,
    "Measured build-side cap for adaptive join strategy changes: a "
    "planned broadcast/single-build join whose build side MEASURES "
    "larger than this (or the admitting ticket's quota share) demotes "
    "to the sub-partitioned strategy before the first OOM retry, and a "
    "shuffle join whose build side measures at most this converts to "
    "single-build. -1 disables both conversions.")

ADAPTIVE_COALESCE_TARGET_BYTES = conf_bytes(
    "spark.rapids.tpu.adaptive.coalesceTargetBytes", 1024 * 1024,
    "Adjacent reducer partitions whose measured bytes sum to no more "
    "than this merge into one read on flat (partition-oblivious) "
    "consumers, killing per-partition dispatch overhead on thousand-"
    "partition plans. Partition-aware consumers (shuffled joins, "
    "partition-wise sort) always see the static boundaries. "
    "0 disables coalescing.")

BREAKER_ENABLED = conf_bool(
    "spark.rapids.tpu.breaker.enabled", False,
    "Degradation circuit breakers (exec/lifecycle.py): track classified-"
    "transient failures per fault domain (pallas_fused / pallas_join / "
    "device_dispatch); after breaker.threshold failures inside "
    "breaker.windowMs a domain's breaker opens and the domain is "
    "demoted to its safe path (the XLA kernel tier) for "
    "breaker.cooldownMs, then half-opens for one probe. Off (default): "
    "failure recording is skipped entirely and every tier consult is "
    "one empty-dict check.")

BREAKER_THRESHOLD = conf_int(
    "spark.rapids.tpu.breaker.threshold", 3,
    "Classified-transient failures of one fault domain inside "
    "breaker.windowMs that open its circuit breaker.")

BREAKER_WINDOW_MS = conf_int(
    "spark.rapids.tpu.breaker.windowMs", 60000,
    "Sliding failure-count window per fault domain for the degradation "
    "circuit breakers; failures older than this no longer count toward "
    "breaker.threshold.")

BREAKER_COOLDOWN_MS = conf_int(
    "spark.rapids.tpu.breaker.cooldownMs", 30000,
    "How long an open breaker keeps its domain demoted before "
    "half-opening for one probe (probe success closes the breaker, "
    "probe failure re-opens it for another cooldown).")

WORKLOAD_ENABLED = conf_bool(
    "spark.rapids.tpu.workload.enabled", False,
    "Concurrent workload governor (exec/workload.py): gate query start "
    "through a bounded admission queue (at most "
    "workload.maxConcurrentQueries admitted, workload.queueDepth "
    "queued), carve the device budget into soft per-admitted-query "
    "shares (workload.memoryQuotaFraction), and shed work fast — "
    "QueryAdmissionError with a retry-after hint — when the queue is "
    "full or the device is known-degraded (an open device_dispatch "
    "circuit breaker). Off (default): collect() pays one conf read and "
    "admission is a no-op, exactly the single-tenant behavior.",
    commonly_used=True)

WORKLOAD_MAX_CONCURRENT = conf_int(
    "spark.rapids.tpu.workload.maxConcurrentQueries", 4,
    "Queries allowed to run concurrently under the workload governor; "
    "further arrivals queue (up to workload.queueDepth) in weighted-"
    "fair priority order (exec/workload.py).")

WORKLOAD_QUEUE_DEPTH = conf_int(
    "spark.rapids.tpu.workload.queueDepth", 16,
    "Queries that may wait in the admission queue; an arrival past this "
    "bound is shed immediately with QueryAdmissionError (reason "
    "queue_full) instead of piling onto an already-saturated engine.")

WORKLOAD_ADMISSION_TIMEOUT_MS = conf_int(
    "spark.rapids.tpu.workload.admissionTimeoutMs", 0,
    "Longest a query may wait in the admission queue before it is shed "
    "with QueryAdmissionError (reason timeout). 0 (default) waits "
    "indefinitely — still bounded by the query's own "
    "spark.rapids.tpu.query.timeoutMs deadline, which spans queue wait "
    "(phase admission-wait).")

WORKLOAD_MEMORY_QUOTA_FRACTION = conf_float(
    "spark.rapids.tpu.workload.memoryQuotaFraction", 0.5,
    "Soft per-admitted-query share of the device budget under the "
    "workload governor: a query over max(fraction * budget, budget / "
    "admitted_count) that hits budget pressure spills ITS OWN buffers "
    "first (a quota_spill event) and surfaces pressure on its own "
    "OOM-retry lane, instead of pushing a neighbor's buffers down a "
    "tier. Shares rebalance as queries finish; a lone admitted query "
    "always gets the whole budget.")

WORKLOAD_PRIORITY = conf_str(
    "spark.rapids.tpu.workload.priority", "interactive",
    "Priority class of this session's queries under the workload "
    "governor: 'interactive' is preferred by admission and semaphore "
    "ordering, 'batch' yields to it — but ages: every few grants the "
    "oldest waiter wins regardless of class, so batch never starves "
    "(exec/workload.py PRIORITIES).")

DECIMAL_ENABLED = conf_bool(
    "spark.rapids.sql.decimalType.enabled", True,
    "Enable decimal offload (decimal128 columns stay on CPU until the "
    "two-limb kernels land; reference decimalType.enabled).")

FUSION_ENABLED = conf_bool(
    "spark.rapids.tpu.fusion.enabled", True,
    "Whole-stage fusion: compose chains of narrow operators "
    "(filter/project) into the consuming operator's single XLA program — "
    "the TPU analog of Spark's whole-stage codegen. One program per batch "
    "instead of one per operator; filters become reduction masks instead "
    "of gathers.", commonly_used=True)

STAGE_FUSION_ENABLED = conf_bool(
    "spark.rapids.tpu.stage.fusion.enabled", True,
    "Whole-stage compilation (exec/stage_compiler.py): after plan "
    "conversion a stage planner walks the TpuExec tree and groups "
    "maximal chains of whitelisted operators (filter -> project -> "
    "expand -> inner-join probe -> partial/complete masked aggregate) "
    "into CompiledStageExec nodes whose per-batch body is ONE "
    "dispatch-ledger-routed jitted program with buffer donation "
    "(carried aggregate state reuses HBM in place), per-batch "
    "governance hooks (cancellation, chaos fault points, dispatch "
    "metrics, breaker engagement) at the stage boundary, and program "
    "sites drawn from the plan-fingerprint program cache so a reused "
    "plan's second collect() is all jit cache hits. Non-whitelisted "
    "operators (exchanges, sorts, UDFs, windows) break the stage and "
    "keep their per-operator execs. An open device_dispatch / "
    "pallas_fused circuit breaker demotes a stage back to per-operator "
    "execution. Off: the converted tree runs unchanged and exec "
    "program sites stay per-instance — CPU results are identical "
    "either way (tier-1 asserted).", commonly_used=True)

STAGE_PROGRAM_CACHE_ENTRIES = conf_int(
    "spark.rapids.tpu.stage.programCache.maxSites", 512,
    "Upper bound on program sites the process-wide plan-fingerprint "
    "program cache retains (obs/dispatch.py). Each entry keys one "
    "(site label x canonical plan-subtree fingerprint) to its compiled "
    "program wrapper, so rebuilding the exec tree for an identical "
    "plan — every DataFrame.collect() does — reuses the already-traced "
    "programs instead of recompiling the whole plan. Past the bound "
    "the least recently used site is evicted (its programs recompile "
    "on next use). 0 disables the cache (every exec instance traces "
    "fresh programs, the pre-stage-fusion behavior).")

AGG_SPECULATIVE = conf_bool(
    "spark.rapids.tpu.agg.speculative.enabled", True,
    "Speculative masked-bucket aggregation: emit small partials plus a "
    "device overflow flag; the plan re-runs exactly if the flag ever trips "
    "(checked once at result materialization). Active only inside a "
    "speculation scope (collect / session queries).")

AGG_GROUP_SLOTS = conf_int(
    "spark.rapids.tpu.agg.bucketSlots", 32,
    "Buckets per round of the masked-bucket group-by kernel (max 64). "
    "Fast-path group cardinality is bucketSlots * bucketRounds; higher "
    "cardinality falls back to the exact sort path.")

AGG_ROUNDS = conf_int(
    "spark.rapids.tpu.agg.bucketRounds", 2,
    "Re-hash rounds of the masked-bucket group-by kernel.")


class RapidsConf:
    """Immutable snapshot of settings; construct from a dict of
    spark-style key->string/typed values."""

    #: dynamic per-operator keys (reference registers one conf per rule:
    #: spark.rapids.sql.exec.<Exec> / .expression.<Expr> etc.)
    _DYNAMIC_PREFIXES = ("spark.rapids.sql.exec.",
                         "spark.rapids.sql.expression.",
                         "spark.rapids.sql.input.",
                         "spark.rapids.sql.format.")

    #: retired keys accepted (ignored with a warning) for compatibility
    _DEPRECATED: Dict[str, str] = {}

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})
        for k in list(self._settings):
            if k in self._DEPRECATED:
                import warnings
                warnings.warn(f"config {k!r} is deprecated and ignored: "
                              f"{self._DEPRECATED[k]}")
                del self._settings[k]
                continue
            if (k.startswith("spark.rapids.") and k not in _REGISTRY
                    and not k.startswith(self._DYNAMIC_PREFIXES)):
                raise KeyError(f"unknown config {k!r}; see docs/configs.md")

    def get(self, entry: ConfEntry):
        return entry.get(self)

    def with_overrides(self, **kv) -> "RapidsConf":
        s = dict(self._settings)
        s.update(kv)
        return RapidsConf(s)

    # convenience properties for hot entries
    @property
    def sql_enabled(self):
        return self.get(SQL_ENABLED)

    @property
    def batch_size_bytes(self):
        return self.get(BATCH_SIZE_BYTES)

    @property
    def concurrent_tpu_tasks(self):
        return self.get(CONCURRENT_TPU_TASKS)

    @property
    def retry_max_attempts(self):
        return self.get(RETRY_MAX_ATTEMPTS)


_active = threading.local()


def active_conf() -> RapidsConf:
    conf = getattr(_active, "conf", None)
    if conf is None:
        conf = RapidsConf()
        _active.conf = conf
    return conf


def set_active_conf(conf: RapidsConf):
    _active.conf = conf


def generate_docs() -> str:
    """Render docs/configs.md from the registry (reference RapidsConf.help)."""
    lines = [
        "# spark_rapids_tpu configuration",
        "",
        "Generated from the config registry (`spark_rapids_tpu/config.py`), "
        "mirroring the reference's RapidsConf-generated docs/configs.md.",
        "",
        "| Key | Default | Meaning |",
        "|---|---|---|",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"| `{e.key}` | `{e.default}` | {e.doc} |")
    lines.append("")
    return "\n".join(lines)
