"""ICI all-to-all shuffle exchange — the accelerated data plane (reference
UCX shuffle, SURVEY §2.5: GpuShuffleExchangeExecBase.scala:277 device split
+ shuffle-plugin UCX transport). On TPU the transport IS the compiler:
rows are hash-partitioned on device, packed into fixed (n_parts, slot_cap)
blocks, and exchanged with `jax.lax.all_to_all` over the mesh axis — XLA
lowers that to ICI neighbor exchanges with no host involvement, replacing
the reference's bounce-buffer + RDMA state machines entirely.

Static-shape contract: every device sends exactly `slot_cap` row slots to
every peer (invalid slots carry validity False). slot_cap defaults to the
full local capacity — the true worst case (all local rows hash to one
partition) — so the exchange can never drop rows; production callers
negotiate a smaller cap from measured per-partition load
(`negotiate_slot_cap`, ISSUE 16) and trade memory for speed.

Strings ride as (lengths, fixed-width padded byte matrix) pairs
(ops/strings.py string_to_padded) — the TPU answer to cuDF's varlen
device serialization in JCudfSerialization.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..ops.basic import active_mask, compaction_order, gather_column
from ..ops.hashing import murmur3_batch, pmod

#: hash seed for shuffle partitioning (Spark uses 42 for HashPartitioning)
SHUFFLE_SEED = 42


def negotiate_slot_cap(measured_max: int, capacity: int,
                       hint: int = 0) -> int:
    """Slot capacity for the (n_parts, slot_cap) send grid, negotiated
    from the MEASURED max per-partition load instead of the worst-case
    full-capacity default (ISSUE 16: the review-r1 sizing promoted to a
    shared primitive). `hint` is the caller's running high-water mark
    from earlier rounds' per-partition statistics (ISSUE 11) — flooring
    by it keeps the exchange program shape stable across rounds of one
    stage, so a later smaller round reuses the compiled step instead of
    tracing a fresh one. Bucketed (bucket_capacity) and clamped to the
    local capacity, which is the true worst case."""
    from ..columnar.column import bucket_capacity
    return min(bucket_capacity(max(int(measured_max), int(hint), 1)),
               capacity)


def partition_ids(key_cols: Sequence[Column], num_rows, capacity: int,
                  n_parts: int):
    """Spark HashPartitioning: pmod(murmur3(keys), n). Inactive rows get
    id n_parts so they never land in a real partition."""
    h = murmur3_batch(list(key_cols), seed=SHUFFLE_SEED)
    pid = pmod(h, n_parts)
    act = active_mask(num_rows, capacity)
    return jnp.where(act, pid, n_parts)


def partition_slots(pid, num_rows, capacity: int, n_parts: int,
                    slot_cap: int):
    """Map each active row to a slot in the (n_parts, slot_cap) send grid.

    Returns send_idx (n_parts*slot_cap,) int32: source row for each slot,
    -1 for empty slots. Rows beyond slot_cap per partition are dropped —
    callers must size slot_cap to the worst case (default: capacity).
    """
    iota = jnp.arange(capacity, dtype=jnp.int32)
    # stable sort rows by pid: groups become contiguous
    sorted_pid, perm = jax.lax.sort((pid.astype(jnp.int32), iota), num_keys=1)
    # position within group = index - first index of that pid
    first_of = jax.ops.segment_min(iota, sorted_pid,
                                   num_segments=n_parts + 1)
    pos = iota - first_of[jnp.clip(sorted_pid, 0, n_parts)]
    ok = (sorted_pid < n_parts) & (pos < slot_cap)
    # sentinel slot is out of bounds -> mode="drop" discards those updates
    slot = jnp.where(ok, sorted_pid * slot_cap + pos, n_parts * slot_cap)
    send_idx = jnp.full((n_parts * slot_cap,), -1, jnp.int32)
    return send_idx.at[slot].set(perm, mode="drop")


def _fixed_to_blocks(col: Column, send_idx, n_parts: int, slot_cap: int):
    g = gather_column(col, send_idx)
    return (g.data.reshape((n_parts, slot_cap)),
            g.validity.reshape((n_parts, slot_cap)))


def exchange_columns(columns: Sequence[Column], key_ordinals: Sequence[int],
                     num_rows, capacity: int, axis_name: str, n_parts: int,
                     slot_cap: int | None = None, string_width: int = 64,
                     pid=None) -> Tuple[List[Column], jnp.ndarray]:
    """SPMD body (call inside shard_map): hash-partition local rows and
    all-to-all them so partition p's rows land on device p.

    Partitioning comes from `pid` when given (precomputed partition ids,
    e.g. from expressions over the batch) else from hashing the columns at
    `key_ordinals`. Returns (received columns, received row count);
    received capacity is n_parts*slot_cap with active rows compacted to
    the front.
    """
    from ..ops.strings import string_from_padded, string_to_padded

    slot_cap = slot_cap or capacity
    if pid is None:
        key_cols = [columns[i] for i in key_ordinals]
        pid = partition_ids(key_cols, num_rows, capacity, n_parts)
    send_idx = partition_slots(pid, num_rows, capacity, n_parts, slot_cap)

    out_cols: List[Column] = []
    recv_cap = n_parts * slot_cap

    def xch_one(col: Column) -> Column:
        if isinstance(col, StringColumn):
            g = gather_column(col, send_idx)
            lengths, padded = string_to_padded(g, string_width)
            r_len = jax.lax.all_to_all(
                lengths.reshape((n_parts, slot_cap)), axis_name, 0, 0,
                tiled=False).reshape((recv_cap,))
            r_pad = jax.lax.all_to_all(
                padded.reshape((n_parts, slot_cap, string_width)),
                axis_name, 0, 0,
                tiled=False).reshape((recv_cap, string_width))
            r_val = jax.lax.all_to_all(
                g.validity.reshape((n_parts, slot_cap)), axis_name, 0, 0,
                tiled=False).reshape((recv_cap,))
            return string_from_padded(r_len, r_pad, r_val, col.dtype)
        from ..columnar.column import StructColumn
        if isinstance(col, StructColumn):
            # struct/decimal128: exchange the limbs/fields recursively and
            # carry the struct's own validity as one more lane
            kids = tuple(xch_one(k) for k in col.children)
            g_val = gather_column(
                Column(jnp.zeros((col.capacity,), jnp.int32), col.validity,
                       col.dtype), send_idx).validity
            r_val = jax.lax.all_to_all(
                g_val.reshape((n_parts, slot_cap)), axis_name, 0, 0,
                tiled=False).reshape((recv_cap,))
            return type(col)(kids, r_val, col.dtype)
        data, valid = _fixed_to_blocks(col, send_idx, n_parts, slot_cap)
        r_data = jax.lax.all_to_all(data, axis_name, 0, 0,
                                    tiled=False).reshape((recv_cap,))
        r_val = jax.lax.all_to_all(valid, axis_name, 0, 0,
                                   tiled=False).reshape((recv_cap,))
        return Column(r_data, r_val, col.dtype)

    for col in columns:
        out_cols.append(xch_one(col))

    # occupancy: a slot is occupied iff its send side had a row; validity of
    # a real-but-null row is False, so track occupancy separately
    occ = jax.lax.all_to_all(
        (send_idx >= 0).reshape((n_parts, slot_cap)), axis_name, 0, 0,
        tiled=False).reshape((recv_cap,))
    perm, n_recv = compaction_order(occ, jnp.int32(recv_cap))
    act = active_mask(n_recv, recv_cap)
    out_cols = [gather_column(c, jnp.where(act, perm, -1)) for c in out_cols]
    return out_cols, n_recv
