"""Heartbeat-based peer discovery and liveness — the reference's
RapidsShuffleHeartbeatManager (driver) / RapidsShuffleHeartbeatEndpoint
(executor), which bootstrap UCX peer identity through driver RPC before
any shuffle data moves (Plugin.scala:417-437 registration; SURVEY §2.5).

TPU shape: the accelerated data plane is XLA collectives over ICI, which
need every mesh participant alive before a program launches — exactly the
problem the reference's heartbeats solve for UCX. The manager is the
driver-side registry; each executor runs an endpoint thread that
heartbeats on an interval. A peer missing `timeout` seconds of beats is
declared dead, and `live_peers()` feeds the exchange planner (a dead peer
means: fail fast and let task retry reschedule, the reference's recovery
model — SURVEY §5 'no elastic re-sharding').
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, List, Optional


class PeerInfo:
    __slots__ = ("executor_id", "host", "slot", "registered_at",
                 "last_beat")

    def __init__(self, executor_id: str, host: str, slot: int, now: float):
        self.executor_id = executor_id
        self.host = host
        self.slot = slot
        self.registered_at = now
        self.last_beat = now


class HeartbeatManager:
    """Driver-side registry (reference RapidsShuffleHeartbeatManager).

    Bounded (ISSUE 7 satellite): a peer silent past `purge_timeout_s`
    (default 6x the dead timeout) is PURGED — its registry entry dropped
    and its slot recycled for the next registration — so a long-lived
    driver under executor churn cannot grow the registry without bound.
    A purged executor's next beat registers cleanly (first-beat ==
    registration, per the `_register_locked` contract)."""

    def __init__(self, timeout_s: float = 10.0,
                 purge_timeout_s: Optional[float] = None):
        self.timeout_s = timeout_s
        self.purge_timeout_s = (purge_timeout_s if purge_timeout_s
                                is not None else 6.0 * timeout_s)
        self._lock = threading.Lock()
        self._peers: Dict[str, PeerInfo] = {}
        self._next_slot = 0
        #: slots of purged peers, recycled lowest-first
        self._free_slots: List[int] = []
        #: peers already reported dead (one peer_dead event per
        #: live->dead transition; a returning beat re-arms it)
        self._reported_dead: set = set()

    def _purge_locked(self, now: float,
                      keep: Optional[str] = None) -> List[tuple]:
        """Drop peers silent past purge_timeout_s — caller holds
        self._lock. Their slots go back on the free list. Returns
        (executor_id, silent_s) for purged peers whose death was never
        reported: the caller emits their one peer_dead event OUTSIDE
        the lock, so a peer that crosses the purge threshold between
        dead_peers() polls does not vanish without its transition.
        `keep` is the executor currently beating/registering — it just
        proved alive and is about to be refreshed; purging it here
        would emit a peer_dead for a live peer."""
        unreported = []
        doomed = [e for e, p in self._peers.items()
                  if now - p.last_beat > self.purge_timeout_s
                  and e != keep]
        for executor_id in doomed:
            peer = self._peers.pop(executor_id)
            heapq.heappush(self._free_slots, peer.slot)
            if executor_id not in self._reported_dead:
                unreported.append((executor_id, now - peer.last_beat))
            self._reported_dead.discard(executor_id)
        return unreported

    def _emit_dead(self, fresh) -> None:
        """One peer_dead event per live->dead transition — emitted
        outside the lock."""
        for executor_id, silent_s in fresh:
            from ..obs import events as obs_events
            obs_events.emit("peer_dead", executor_id=executor_id,
                            silent_ms=int(silent_s * 1000),
                            timeout_ms=int(self.timeout_s * 1000))

    def _register_locked(self, executor_id: str,
                         host: str = "local") -> List[PeerInfo]:
        """Registration body — caller holds self._lock. Extracted so
        heartbeat() can register an unknown executor WITHOUT re-taking
        the non-reentrant lock (ISSUE 6 satellite: heartbeat() used to
        call register() while already holding it, so an unregistered
        executor's first beat deadlocked forever)."""
        now = time.monotonic()
        if executor_id not in self._peers:
            if self._free_slots:
                slot = heapq.heappop(self._free_slots)
            else:
                slot = self._next_slot
                self._next_slot += 1
            self._peers[executor_id] = PeerInfo(
                executor_id, host, slot, now)
        else:
            self._peers[executor_id].last_beat = now
        self._reported_dead.discard(executor_id)
        return [p for p in self._peers.values()
                if p.executor_id != executor_id]

    def register(self, executor_id: str, host: str = "local") -> List[PeerInfo]:
        """Executor start: returns all currently-known peers (the
        reference's RegisterExecutor reply carries peer identities so
        clients can connect eagerly)."""
        with self._lock:
            purged = self._purge_locked(time.monotonic(),
                                        keep=executor_id)
            peers = self._register_locked(executor_id, host)
        self._emit_dead(purged)
        return peers

    def heartbeat(self, executor_id: str) -> List[PeerInfo]:
        """Periodic beat: refreshes liveness, returns peers registered
        since this executor last heard (delta updates, like the
        reference's ExecutorHeartbeat reply)."""
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now, keep=executor_id)
            me = self._peers.get(executor_id)
            if me is None:
                peers = self._register_locked(executor_id)
            else:
                prev = me.last_beat
                me.last_beat = now
                self._reported_dead.discard(executor_id)
                peers = [p for p in self._peers.values()
                         if p.executor_id != executor_id
                         and p.registered_at > prev]
        self._emit_dead(purged)
        return peers

    def live_peers(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now)
            live = [p.executor_id for p in self._peers.values()
                    if now - p.last_beat <= self.timeout_s]
        self._emit_dead(purged)
        return live

    def dead_peers(self) -> List[str]:
        """Peers past the dead timeout but not yet purged (a purged
        peer is forgotten entirely — neither live nor dead; its
        transition event, if still unreported, fires on the purge)."""
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now)
            dead = [p.executor_id for p in self._peers.values()
                    if now - p.last_beat > self.timeout_s]
            fresh = [(e, now - self._peers[e].last_beat) for e in dead
                     if e not in self._reported_dead]
            self._reported_dead.update(e for e, _ in fresh)
        # liveness is observable (ISSUE 6 satellite): one peer_dead
        # event per live->dead transition — emitted outside the lock
        self._emit_dead(purged + fresh)
        return dead


class HeartbeatEndpoint:
    """Executor-side beat thread (reference
    RapidsShuffleHeartbeatEndpoint with its scheduled executor)."""

    def __init__(self, manager: HeartbeatManager, executor_id: str,
                 interval_s: float = 1.0,
                 on_new_peer: Optional[Callable[[PeerInfo], None]] = None):
        self.manager = manager
        self.executor_id = executor_id
        self.interval_s = interval_s
        self.on_new_peer = on_new_peer
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        for p in self.manager.register(self.executor_id):
            if self.on_new_peer:
                self.on_new_peer(p)
        # contract: ok thread-adopt — engine-global liveness daemon: it
        # beats the peer table and emits peer_dead transitions, none of
        # which belong to a query; there is no context to adopt
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"heartbeat-{self.executor_id}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            for p in self.manager.heartbeat(self.executor_id):
                if self.on_new_peer:
                    self.on_new_peer(p)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
