"""Heartbeat-based peer discovery and liveness — the reference's
RapidsShuffleHeartbeatManager (driver) / RapidsShuffleHeartbeatEndpoint
(executor), which bootstrap UCX peer identity through driver RPC before
any shuffle data moves (Plugin.scala:417-437 registration; SURVEY §2.5).

TPU shape: the accelerated data plane is XLA collectives over ICI, which
need every mesh participant alive before a program launches — exactly the
problem the reference's heartbeats solve for UCX. The manager is the
driver-side registry; each executor runs an endpoint thread that
heartbeats on an interval. A peer missing `timeout` seconds of beats is
declared dead, and `live_peers()` feeds the exchange planner (a dead peer
means: fail fast and let task retry reschedule, the reference's recovery
model — SURVEY §5 'no elastic re-sharding').
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, List, Optional


class PeerInfo:
    __slots__ = ("executor_id", "host", "slot", "registered_at",
                 "last_beat")

    def __init__(self, executor_id: str, host: str, slot: int, now: float):
        self.executor_id = executor_id
        self.host = host
        self.slot = slot
        self.registered_at = now
        self.last_beat = now


class HeartbeatManager:
    """Driver-side registry (reference RapidsShuffleHeartbeatManager).

    Bounded (ISSUE 7 satellite): a peer silent past `purge_timeout_s`
    (default 6x the dead timeout) is PURGED — its registry entry dropped
    and its slot recycled for the next registration — so a long-lived
    driver under executor churn cannot grow the registry without bound.
    A purged executor's next beat registers cleanly (first-beat ==
    registration, per the `_register_locked` contract)."""

    def __init__(self, timeout_s: float = 10.0,
                 purge_timeout_s: Optional[float] = None):
        self.timeout_s = timeout_s
        self.purge_timeout_s = (purge_timeout_s if purge_timeout_s
                                is not None else 6.0 * timeout_s)
        self._lock = threading.Lock()
        self._peers: Dict[str, PeerInfo] = {}
        self._next_slot = 0
        #: slots of purged peers, recycled lowest-first
        self._free_slots: List[int] = []
        #: peers already reported dead (one peer_dead event per
        #: live->dead transition; a returning beat re-arms it)
        self._reported_dead: set = set()
        #: reported-dead peers' slots (ISSUE 20): withheld from the
        #: exchange/planner surfaces until the peer re-registers (the
        #: returning beat clears the entry) or is purged (the peer is
        #: forgotten entirely and its slot recycles, the ISSUE 7
        #: bounded-registry contract)
        self._blacklist: Dict[str, int] = {}
        #: peers purged over this manager's lifetime (health surface)
        self._purged = 0
        #: dead-peer transition hook (ISSUE 20): called OUTSIDE the
        #: lock, once per live->dead transition, with the executor id —
        #: parallel.heartbeat.install wires it to the speculation
        #: shield's map-output invalidation. None = no glue (the
        #: default for a bare test manager).
        self.on_peer_dead: Optional[Callable[[str], None]] = None

    def _purge_locked(self, now: float,
                      keep: Optional[str] = None) -> List[tuple]:
        """Drop peers silent past purge_timeout_s — caller holds
        self._lock. Their slots go back on the free list. Returns
        (executor_id, silent_s) for purged peers whose death was never
        reported: the caller emits their one peer_dead event OUTSIDE
        the lock, so a peer that crosses the purge threshold between
        dead_peers() polls does not vanish without its transition.
        `keep` is the executor currently beating/registering — it just
        proved alive and is about to be refreshed; purging it here
        would emit a peer_dead for a live peer."""
        unreported = []
        doomed = [e for e, p in self._peers.items()
                  if now - p.last_beat > self.purge_timeout_s
                  and e != keep]
        for executor_id in doomed:
            peer = self._peers.pop(executor_id)
            heapq.heappush(self._free_slots, peer.slot)
            if executor_id not in self._reported_dead:
                unreported.append((executor_id, now - peer.last_beat))
            self._reported_dead.discard(executor_id)
            # the purge forgets the peer entirely: its blacklist entry
            # goes with it (the recycled slot belongs to nobody)
            self._blacklist.pop(executor_id, None)
            self._purged += 1
        return unreported

    def _emit_dead(self, fresh) -> None:
        """One peer_dead event per live->dead transition — emitted
        outside the lock, then the on_peer_dead hook (ISSUE 20: the
        speculation shield invalidates the dead peer's map outputs
        here). A hook failure must not kill the poller that happened
        to notice the transition."""
        for executor_id, silent_s in fresh:
            from ..obs import events as obs_events
            obs_events.emit("peer_dead", executor_id=executor_id,
                            silent_ms=int(silent_s * 1000),
                            timeout_ms=int(self.timeout_s * 1000))
            hook = self.on_peer_dead
            if hook is not None:
                try:
                    hook(executor_id)
                except Exception:  # noqa: BLE001 — see docstring
                    import logging
                    logging.getLogger(
                        "spark_rapids_tpu.parallel").warning(
                        "on_peer_dead hook failed for %s", executor_id,
                        exc_info=True)

    def _register_locked(self, executor_id: str,
                         host: str = "local") -> List[PeerInfo]:
        """Registration body — caller holds self._lock. Extracted so
        heartbeat() can register an unknown executor WITHOUT re-taking
        the non-reentrant lock (ISSUE 6 satellite: heartbeat() used to
        call register() while already holding it, so an unregistered
        executor's first beat deadlocked forever)."""
        now = time.monotonic()
        if executor_id not in self._peers:
            if self._free_slots:
                slot = heapq.heappop(self._free_slots)
            else:
                slot = self._next_slot
                self._next_slot += 1
            self._peers[executor_id] = PeerInfo(
                executor_id, host, slot, now)
        else:
            self._peers[executor_id].last_beat = now
        self._reported_dead.discard(executor_id)
        # the returning peer re-registers: its slot comes off the
        # blacklist (ISSUE 20 — the dead-peer quarantine ends here)
        self._blacklist.pop(executor_id, None)
        return [p for p in self._peers.values()
                if p.executor_id != executor_id]

    def register(self, executor_id: str, host: str = "local") -> List[PeerInfo]:
        """Executor start: returns all currently-known peers (the
        reference's RegisterExecutor reply carries peer identities so
        clients can connect eagerly)."""
        with self._lock:
            purged = self._purge_locked(time.monotonic(),
                                        keep=executor_id)
            peers = self._register_locked(executor_id, host)
        self._emit_dead(purged)
        return peers

    def heartbeat(self, executor_id: str) -> List[PeerInfo]:
        """Periodic beat: refreshes liveness, returns peers registered
        since this executor last heard (delta updates, like the
        reference's ExecutorHeartbeat reply)."""
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now, keep=executor_id)
            me = self._peers.get(executor_id)
            if me is None:
                peers = self._register_locked(executor_id)
            else:
                prev = me.last_beat
                me.last_beat = now
                self._reported_dead.discard(executor_id)
                self._blacklist.pop(executor_id, None)
                peers = [p for p in self._peers.values()
                         if p.executor_id != executor_id
                         and p.registered_at > prev]
        self._emit_dead(purged)
        return peers

    def live_peers(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now)
            live = [p.executor_id for p in self._peers.values()
                    if now - p.last_beat <= self.timeout_s]
        self._emit_dead(purged)
        return live

    def dead_peers(self) -> List[str]:
        """Peers past the dead timeout but not yet purged (a purged
        peer is forgotten entirely — neither live nor dead; its
        transition event, if still unreported, fires on the purge)."""
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now)
            dead = [p.executor_id for p in self._peers.values()
                    if now - p.last_beat > self.timeout_s]
            fresh = [(e, now - self._peers[e].last_beat) for e in dead
                     if e not in self._reported_dead]
            self._reported_dead.update(e for e, _ in fresh)
            # a freshly dead peer's slot is blacklisted: withheld from
            # every planning surface until it re-registers (or the
            # purge forgets the peer and recycles the slot)
            for e, _ in fresh:
                self._blacklist[e] = self._peers[e].slot
        # liveness is observable (ISSUE 6 satellite): one peer_dead
        # event per live->dead transition — emitted outside the lock
        self._emit_dead(purged + fresh)
        return dead

    def blacklisted_slots(self) -> Dict[str, int]:
        """executor_id -> slot for peers currently dead-and-quarantined
        (ISSUE 20): withheld from planning until re-registration."""
        with self._lock:
            return dict(self._blacklist)

    def health_section(self) -> Dict[str, object]:
        """The TpuSession.health()["peers"] payload: live/dead peer
        ids, lifetime purge count and the blacklisted slots. Polls the
        registry (so stale transitions report), like dead_peers()."""
        now = time.monotonic()
        with self._lock:
            purged = self._purge_locked(now)
            live, dead = [], []
            for p in self._peers.values():
                (dead if now - p.last_beat > self.timeout_s
                 else live).append(p.executor_id)
            out = {"enabled": True, "live": sorted(live),
                   "dead": sorted(dead), "purged": self._purged,
                   "blacklisted_slots": dict(self._blacklist)}
        self._emit_dead(purged)
        return out


# ---------------------------------------------------------------------------
# process-wide manager registry (ISSUE 20): the session health surface
# and the dead-peer -> map-output-invalidation glue need ONE nominated
# manager; a bare test manager stays un-wired unless installed.
# ---------------------------------------------------------------------------

_ACTIVE: Optional[HeartbeatManager] = None
_active_lock = threading.Lock()


def install(manager: Optional[HeartbeatManager]) -> None:
    """Nominate `manager` as the process's heartbeat registry (None =
    clear, test isolation). Wires its on_peer_dead hook to the
    speculation shield's map-output invalidation — the conf gate
    (`shuffle.deadPeerInvalidation.enabled`) is consulted inside the
    hook at transition time, so installing is unconditional."""
    global _ACTIVE
    with _active_lock:
        prev, _ACTIVE = _ACTIVE, manager
    if prev is not None and prev is not manager:
        prev.on_peer_dead = None
    if manager is not None:
        from ..exec import speculation_shield
        manager.on_peer_dead = speculation_shield.on_peer_dead


def active_manager() -> Optional[HeartbeatManager]:
    return _ACTIVE


def health_section() -> Dict[str, object]:
    """`TpuSession.health()["peers"]`: the installed manager's liveness
    surface, or the explicit disabled shape when no manager runs (the
    default single-process session)."""
    mgr = _ACTIVE
    if mgr is None:
        return {"enabled": False, "live": [], "dead": [], "purged": 0,
                "blacklisted_slots": {}}
    return mgr.health_section()


class HeartbeatEndpoint:
    """Executor-side beat thread (reference
    RapidsShuffleHeartbeatEndpoint with its scheduled executor)."""

    def __init__(self, manager: HeartbeatManager, executor_id: str,
                 interval_s: float = 1.0,
                 on_new_peer: Optional[Callable[[PeerInfo], None]] = None):
        self.manager = manager
        self.executor_id = executor_id
        self.interval_s = interval_s
        self.on_new_peer = on_new_peer
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        for p in self.manager.register(self.executor_id):
            if self.on_new_peer:
                self.on_new_peer(p)
        # contract: ok thread-adopt — engine-global liveness daemon: it
        # beats the peer table and emits peer_dead transitions, none of
        # which belong to a query; there is no context to adopt
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"heartbeat-{self.executor_id}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            for p in self.manager.heartbeat(self.executor_id):
                if self.on_new_peer:
                    self.on_new_peer(p)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
