"""Distributed query steps: SPMD pipelines compiled once over the whole
mesh (reference analog: the UCX shuffle + partial/final aggregate pattern,
GpuShuffleExchangeExecBase.scala:277 + GpuAggregateExec partial/final).

The biggest architectural departure from the reference (SURVEY §7 risk
register): instead of independent tasks pulling batches through a
transport, a distributed step is ONE resident XLA program over the mesh —
local partial aggregate, ICI all-to-all exchange by key hash, local final
merge — with XLA scheduling compute/communication overlap. Spark tasks
enqueue batches into this program instead of talking to a shuffle service.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn
from ..ops.aggregate import groupby_aggregate
from ..types import DataType, Schema
from .exchange import exchange_columns
from .mesh import DATA_AXIS


def stack_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Stack n same-capacity batches along a new leading device axis; the
    result's leaves have shape (n, ...) ready for shard_map over 'data'.
    Row and string-byte buckets are aligned across batches first."""
    cap = max(b.capacity for b in batches)
    batches = [b.sized_to(cap) for b in batches]
    aligned = []
    byte_caps = {}
    for b in batches:
        for i, c in enumerate(b.columns):
            if isinstance(c, StringColumn):
                byte_caps[i] = max(byte_caps.get(i, 0), c.byte_capacity)
    for b in batches:
        cols = [c.with_byte_capacity(byte_caps[i])
                if isinstance(c, StringColumn) else c
                for i, c in enumerate(b.columns)]
        aligned.append(b.with_columns(cols, b.schema))
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *aligned)


def _squeeze0(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _expand0(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


def required_string_width(batches: Sequence[ColumnarBatch]) -> int:
    """Exact fixed-width byte size for exchanging these batches' string
    columns (host-side, pre-jit): the max string length rounded to 8.
    Pass to make_distributed_groupby/exchange_columns — the fixed-width
    codec TRUNCATES beyond this width."""
    width = 8
    for b in batches:
        for c in b.columns:
            if isinstance(c, StringColumn):
                lengths = c.offsets[1:] - c.offsets[:-1]
                max_len = int(jnp.max(lengths)) if c.capacity else 0
                width = max(width, (max_len + 7) // 8 * 8)
    return width


def make_distributed_groupby(mesh: Mesh, key_count: int,
                             update_inputs: Sequence[Tuple[str, int]],
                             merge_ops: Sequence[str],
                             buffer_types: Sequence[DataType],
                             out_schema: Schema,
                             string_words: int = 4,
                             string_width: int = 64,
                             axis_name: str = DATA_AXIS):
    """Build the jitted SPMD group-by step.

    update_inputs: [(op, input ordinal into the local batch)] per buffer
    (ordinal -1 => count_star). merge_ops/buffer_types: one per buffer.
    string_width: fixed-width byte size for exchanged string columns —
    size it with required_string_width(batches) or longer keys TRUNCATE.
    Input: stacked batch with leaves (n, ...); output: stacked aggregated
    batch, one shard per device holding that device's hash partitions.
    """
    n_parts = mesh.shape[axis_name]
    # sort-lane width must cover the exchanged width for exact key grouping
    string_words = max(string_words, string_width // 8)

    def spmd(stacked: ColumnarBatch) -> ColumnarBatch:
        local = _squeeze0(stacked)
        cap = local.capacity
        keys = list(local.columns[:key_count])
        agg_inputs = [(op, local.columns[ordinal] if ordinal >= 0 else None)
                      for op, ordinal in update_inputs]
        # phase 1: local partial aggregate
        pkeys, presults, pgroups = groupby_aggregate(
            keys, agg_inputs, local.num_rows, cap, string_words)
        partial_cols = list(pkeys)
        for r, bt in zip(presults, buffer_types):
            if r[0] == "col":
                partial_cols.append(r[1])
            else:
                data, valid = r[1]
                partial_cols.append(Column(data.astype(bt.jnp_dtype),
                                           valid, bt))
        # phase 2: all-to-all exchange so equal keys colocate
        recv_cols, n_recv = exchange_columns(
            partial_cols, list(range(key_count)), pgroups, cap,
            axis_name, n_parts, string_width=string_width)
        # phase 3: final merge aggregate on the received partition
        rkeys = recv_cols[:key_count]
        rbufs = recv_cols[key_count:]
        m_inputs = [(op, c) for op, c in zip(merge_ops, rbufs)]
        fkeys, fresults, fgroups = groupby_aggregate(
            rkeys, m_inputs, n_recv, recv_cols[0].capacity, string_words)
        out_cols = list(fkeys)
        for r, bt in zip(fresults, buffer_types):
            if r[0] == "col":
                out_cols.append(r[1])
            else:
                data, valid = r[1]
                out_cols.append(Column(data.astype(bt.jnp_dtype), valid, bt))
        out = ColumnarBatch(out_cols, fgroups, out_schema)
        return _expand0(out)

    from .mesh import shard_map_compat
    mapped = shard_map_compat(spmd, mesh=mesh,
                              in_specs=P(axis_name),
                              out_specs=P(axis_name))
    from ..obs.dispatch import instrument
    jitted = instrument(mapped, label="distributed.agg_exchange_step")

    def checked(stacked: ColumnarBatch) -> ColumnarBatch:
        # the fixed-width exchange codec TRUNCATES beyond string_width;
        # enforce the contract here instead of relying on callers to
        # remember required_string_width (review finding r1). One host
        # sync per step call, outside the compiled program.
        for c in stacked.columns:
            if isinstance(c, StringColumn) and c.offsets.shape[-1] > 1:
                lengths = c.offsets[:, 1:] - c.offsets[:, :-1]
                max_len = int(jnp.max(lengths))
                if max_len > string_width:
                    raise ValueError(
                        f"string key of {max_len} bytes exceeds the "
                        f"exchange width {string_width}; size it with "
                        "required_string_width(batches)")
        return jitted(stacked)

    return checked


def unstack_batches(stacked: ColumnarBatch, n: int) -> List[ColumnarBatch]:
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(n)]
