"""Device-mesh management — the TPU analog of GpuDeviceManager's device
acquisition (reference GpuDeviceManager.scala:115 setGpuDeviceAndAcquire).

Instead of binding one CUDA device per executor, the engine builds a
jax.sharding.Mesh over the chips this host can see. Single-host Spark
executors pin 1 task slice per chip (DP over the 'data' axis); multi-host
pods extend the same mesh over ICI with jax's distributed runtime.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


def shard_map_compat(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: newer jax exposes top-level
    `jax.shard_map` (replication check kwarg `check_vma`); 0.4.x — this
    image — has `jax.experimental.shard_map.shard_map` (`check_rep`).
    The engine disables the replication check either way (exchange
    bodies intentionally produce per-shard-distinct outputs)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def device_mesh(n_devices: Optional[int] = None,
                axis_name: str = DATA_AXIS) -> Mesh:
    """1-D mesh over the first n visible devices (default: all). Shuffle
    exchanges ride this axis as all-to-all collectives."""
    devs = jax.devices()
    if n_devices is not None:
        assert len(devs) >= n_devices, \
            f"need {n_devices} devices, have {len(devs)}"
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def mesh_axis_size(mesh: Mesh, axis_name: str = DATA_AXIS) -> int:
    return mesh.shape[axis_name]


# -- active mesh (planner seam) ---------------------------------------------
# The session installs its mesh here; TpuOverrides reads it to decide
# whether to plan distributed stages (partial → exchange → final, shuffled
# joins). The analog of the reference's "is a shuffle manager configured"
# check (RapidsShuffleInternalManagerBase).

_ACTIVE_MESH: Optional[Mesh] = None


def set_active_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH
