"""Distributed execution over a TPU device mesh (reference layer L5 +
§2.10: shuffle transport + data parallelism). The ICI collective plane
replaces the reference's UCX RDMA path; host-staged exchange replaces the
MULTITHREADED file shuffle."""

from .mesh import device_mesh, mesh_axis_size  # noqa: F401
