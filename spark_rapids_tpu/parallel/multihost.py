"""Multi-host bootstrap + ICI/DCN mesh topology (SURVEY §2.10 mapping:
"ICI intra-slice + host-staged inter-slice"; the reference's analog is the
executor-side distributed init in Plugin.scala plus the UCX/netty split
between fast P2P and the always-works host plane).

Two pieces:

1. `initialize_distributed()` — jax.distributed bootstrap from standard
   cluster env (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, or the
   TPU pod metadata jax discovers natively). Idempotent; a no-op for
   single-process runs so the same engine code runs everywhere.

2. `build_query_mesh(devices)` — a 2-D ("dcn", "ici") Mesh: the inner
   axis spans each host's local devices (ICI — all-to-all shuffle
   exchanges ride it, parallel/exchange.py), the outer axis spans hosts
   (DCN — only partial→final aggregation trees and broadcasts cross it).
   Exchange planning keys on the ICI axis size, so shuffles NEVER cross
   DCN implicitly: inter-host movement goes through the host shuffle
   plane (shuffle/manager.py), mirroring the reference's UCX-fast-path /
   file-shuffle-fallback split.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Sequence, Tuple

log = logging.getLogger("spark_rapids_tpu.multihost")

DCN_AXIS = "dcn"
ICI_AXIS = "data"  # same name the single-host mesh uses (mesh.py)

_initialized = False


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """jax.distributed.initialize from args or environment. Returns True
    when a multi-process runtime was brought up, False for single-process
    (both are valid engine states). Idempotent."""
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("NUM_PROCESSES")
    process_id = process_id if process_id is not None \
        else _int_env("PROCESS_ID")
    import jax
    if coordinator is None:
        # TPU pods: jax discovers the coordinator from metadata; only
        # attempt on a genuinely multi-HOST slice (single-host setups —
        # including tunneled dev chips — export the var with one entry)
        hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES",
                                           "").split(",") if h]
        if len(hosts) > 1:
            jax.distributed.initialize()
            _initialized = True
            return True
        return False  # single-process (no coordinator configured)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    log.info("distributed runtime up: process %s of %s via %s",
             process_id, num_processes, coordinator)
    return True


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


def group_devices_by_host(devices: Sequence) -> List[List]:
    """Stable grouping by process index (each jax process = one host)."""
    hosts: dict = {}
    for d in devices:
        hosts.setdefault(getattr(d, "process_index", 0), []).append(d)
    return [hosts[k] for k in sorted(hosts)]


def topology_shape(devices: Sequence) -> Tuple[int, int]:
    """(n_hosts, devices_per_host); raises on ragged topologies (a host
    down mid-allocation — fail fast, task retry is the recovery model)."""
    groups = group_devices_by_host(devices)
    per_host = {len(g) for g in groups}
    if len(per_host) != 1:
        raise RuntimeError(
            f"ragged device topology: {sorted(len(g) for g in groups)} "
            "devices per host — refusing to build a mesh")
    return len(groups), per_host.pop()


def build_query_mesh(devices: Optional[Sequence] = None):
    """('dcn', 'data') Mesh: inner axis = a host's local chips (ICI),
    outer = hosts (DCN). Single-host collapses to (1, n)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh
    devices = list(devices) if devices is not None else jax.devices()
    n_hosts, per_host = topology_shape(devices)
    grid = np.empty((n_hosts, per_host), dtype=object)
    for hi, group in enumerate(group_devices_by_host(devices)):
        for di, d in enumerate(group):
            grid[hi, di] = d
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def ici_axis_size(mesh) -> int:
    return mesh.shape[ICI_AXIS]


def dcn_axis_size(mesh) -> int:
    return mesh.shape.get(DCN_AXIS, 1) if hasattr(mesh.shape, "get") \
        else mesh.shape[DCN_AXIS]
