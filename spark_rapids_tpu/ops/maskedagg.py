"""Masked-bucket group-by — the engine's primary aggregation kernel.

Reference analog: cuDF's hash group-by under GpuHashAggregateExec
(GpuAggregateExec.scala:1711). The TPU rebuild CANNOT use a hash table:
measured on v5e, XLA scatter/segment ops cost ~15ms per 1M rows (they
serialize), while masked full-array reductions FUSE into a handful of HBM
passes regardless of how many of them read the same input. So grouping is
built entirely from masked reductions:

  round r in [0, R):                              (R static, default 2)
    bucket b = mix_r(keys) mod G                  (G static, <= 64)
    per key column: masked min/max of its order-bits over each bucket
      -> bucket is CLEAN iff every key column is constant across it
         (min == max, and not a null/value mix) — an EXACT uniformity
         proof, no row gathers, no scatters
    clean buckets resolve ALL their rows to slot r*G + b; their key value
      is the min (== max) itself, decoded from order bits
    dirty buckets retry with a different mix next round
  leftover = any row still unresolved after R rounds (cardinality greater
  than the slot table or adversarial collisions)

Aggregates are masked reductions per slot (sum/count/min/max/first/last),
slots compact to a dense prefix with one tiny (R*G)-element pass, and the
whole thing — bucket assignment, uniformity proof, reductions — fuses with
the upstream filter/project into ONE XLA program with ZERO host syncs.

`leftover` handling is the caller's choice: speculate (emit the small
partial + device flag; plan-level retry re-runs exact if it ever trips —
exec/speculation.py) or wrap in lax.cond with the exact sort-based kernel
(masked_groupby_exact).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn, bucket_capacity
from ..types import DataType
from .basic import active_mask, compact_columns
from .sort import _numeric_order_key


def _unorder_bits(u, dtype: DataType):
    """Invert ops/sort._numeric_order_key: order-bits lane -> value."""
    jdt = jnp.dtype(dtype.jnp_dtype)
    if jdt == jnp.bool_:
        return u.astype(jnp.bool_)
    if jnp.issubdtype(jdt, jnp.floating):
        bits_dt = jnp.uint64 if jdt == jnp.float64 else jnp.uint32
        sign = jnp.ones((), bits_dt) << (8 * jnp.dtype(bits_dt).itemsize - 1)
        was_neg = (u & sign) == 0
        bits = jnp.where(was_neg, ~u, u ^ sign)
        val = jax.lax.bitcast_convert_type(
            bits, jnp.float64 if jdt == jnp.float64 else jnp.float32)
        return val.astype(jdt)
    if jnp.issubdtype(jdt, jnp.signedinteger):
        bits = 8 * jnp.dtype(jdt).itemsize
        flipped = u ^ (jnp.ones((), u.dtype) << (bits - 1))
        return jax.lax.bitcast_convert_type(flipped, jdt)
    return u.astype(jdt)


def _mix32(h, salt: int):
    """Cheap murmur3-finalizer mixing (internal bucketing only — Spark-parity
    hashing lives in ops/hashing.py and is ~10x costlier)."""
    h = h ^ jnp.uint32(salt)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _bucket_hash(key_cols: Sequence[Column], salt: int, capacity: int):
    h = jnp.full((capacity,), jnp.uint32(0x9E3779B9))
    for c in key_cols:
        lane = _numeric_order_key(c)
        if lane.dtype in (jnp.uint64, jnp.int64):
            lo = lane.astype(jnp.uint32)
            hi = (lane >> jnp.uint64(32)).astype(jnp.uint32)
            h = _mix32(h ^ lo, salt)
            h = _mix32(h ^ hi, salt + 0x51)
        else:
            h = _mix32(h ^ lane.astype(jnp.uint32), salt)
        h = _mix32(h ^ c.validity.astype(jnp.uint32), salt + 0xA3)
    return h


def masked_group_assignment(key_cols: Sequence[Column], num_rows,
                            capacity: int, row_mask=None,
                            group_slots: int = 32, rounds: int = 2):
    """Scatter-free exact group assignment.

    Returns (seg (capacity,) int32 in [0, R*G) or sentinel R*G;
    slot_occupied (R*G,) bool; slot key values+validity per key column;
    leftover device bool).
    """
    G, R = group_slots, rounds
    assert G <= 64, "bitmask lookup supports at most 64 buckets per round"
    mask_dt = jnp.uint32 if G <= 32 else jnp.uint64
    cap = capacity
    act = active_mask(num_rows, cap)
    if row_mask is not None:
        act = act & row_mask
    unresolved = act
    sentinel = R * G
    seg = jnp.full((cap,), sentinel, jnp.int32)
    slot_occ: List[jnp.ndarray] = []
    slot_keys: List[List[Tuple[jnp.ndarray, jnp.ndarray]]] = []  # per round

    g_iota = jnp.arange(G, dtype=jnp.int32)
    one = jnp.ones((), mask_dt)

    for r in range(R):
        h = _bucket_hash(key_cols, 0x2545F491 + r * 0x9E37, cap)
        b = (h % jnp.uint32(G)).astype(jnp.int32)
        # per-bucket stats as G independent 1-D masked reductions: XLA
        # multi-output fuses same-input reductions into a few HBM passes
        # (a G x cap mask matrix would materialize G*cap bytes instead)
        lanes = [_numeric_order_key(c) for c in key_cols]
        occ_g, clean_g = [], []
        mins_g = [[] for _ in key_cols]
        avail_g = [[] for _ in key_cols]
        for g in range(G):
            m = unresolved & (b == g)
            clean = jnp.bool_(True)
            for ci, (c, lane) in enumerate(zip(key_cols, lanes)):
                neutral_min = jnp.full((), jnp.iinfo(lane.dtype).max,
                                       lane.dtype)
                neutral_max = jnp.zeros((), lane.dtype)
                mv = m & c.validity
                mn = jnp.min(jnp.where(mv, lane, neutral_min))
                mx = jnp.max(jnp.where(mv, lane, neutral_max))
                any_valid = jnp.any(mv)
                any_null = jnp.any(m & ~c.validity)
                clean = clean & ~(any_valid & any_null) & \
                    (~any_valid | (mn == mx))
                mins_g[ci].append(mn)
                avail_g[ci].append(any_valid)
            occ_g.append(jnp.any(m))
            clean_g.append(clean)
        occupied = jnp.stack(occ_g)
        clean = jnp.stack(clean_g)
        keys_r: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (jnp.stack(mins_g[ci]), jnp.stack(avail_g[ci]))
            for ci in range(len(key_cols))]
        resolved_bucket = clean & occupied
        # branchless per-row lookup: clean buckets as a bitmask scalar
        bits = jnp.sum(jnp.where(resolved_bucket,
                                 one << g_iota.astype(mask_dt), 0))
        row_clean = ((bits >> b.astype(mask_dt)) & one) != 0
        resolved = unresolved & row_clean
        seg = jnp.where(resolved, r * G + b, seg)
        unresolved = unresolved & ~resolved
        slot_occ.append(resolved_bucket)
        slot_keys.append(keys_r)

    leftover = jnp.any(unresolved)
    occ = jnp.concatenate(slot_occ)  # (R*G,)
    # per key column: (R*G,) order-bits + validity across rounds
    key_slots = []
    for ci, c in enumerate(key_cols):
        bits = jnp.concatenate([slot_keys[r][ci][0] for r in range(R)])
        valid = jnp.concatenate([slot_keys[r][ci][1] for r in range(R)])
        key_slots.append((bits, valid))
    return seg, occ, key_slots, leftover


def _slot_reduce(op: str, m, col: Optional[Column], positions,
                 capacity: int):
    """One aggregate over one row mask: a masked full-array reduction."""
    if op == "count_star":
        return jnp.sum(m, dtype=jnp.int64), jnp.bool_(True)
    v = col.validity & m
    if op == "count":
        return jnp.sum(v, dtype=jnp.int64), jnp.bool_(True)
    has = jnp.any(v)
    if op in ("sum", "sum_sq"):
        data = col.data
        acc = data.astype(jnp.float64) \
            if jnp.issubdtype(data.dtype, jnp.floating) \
            else data.astype(jnp.int64)
        if op == "sum_sq":
            acc = acc * acc
        return jnp.sum(jnp.where(v, acc, jnp.zeros((), acc.dtype))), has
    if op in ("min", "max"):
        data = col.data
        if jnp.issubdtype(data.dtype, jnp.floating):
            neutral = jnp.full((), jnp.inf if op == "min" else -jnp.inf,
                               data.dtype)
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
            neutral = jnp.int8(1 if op == "min" else 0)
        else:
            info = jnp.iinfo(data.dtype)
            neutral = jnp.full((), info.max if op == "min" else info.min,
                               data.dtype)
        fn = jnp.min if op == "min" else jnp.max
        return fn(jnp.where(v, data, neutral)), has
    if op in ("first", "last", "any_value"):
        if op == "last":
            pick = jnp.max(jnp.where(v, positions, -1))
        else:
            pick = jnp.min(jnp.where(v, positions, capacity))
        ok = (pick >= 0) & (pick < capacity)
        return col.data[jnp.clip(pick, 0, capacity - 1)], ok
    if op in ("first_any", "last_any"):
        # ignoreNulls=False: pick over ACTIVE rows regardless of null
        if op == "last_any":
            pick = jnp.max(jnp.where(m, positions, -1))
        else:
            pick = jnp.min(jnp.where(m, positions, capacity))
        ok = (pick >= 0) & (pick < capacity)
        safe = jnp.clip(pick, 0, capacity - 1)
        return col.data[safe], ok & col.validity[safe]
    raise AssertionError(op)


def masked_groupby(key_columns: Sequence[Column],
                   agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                   num_rows, capacity: int, row_mask=None,
                   group_slots: int = 32, rounds: int = 2):
    """Group-by into a SMALL output bucket (capacity bucket_capacity(R*G)).

    Returns (out_keys, tagged results, num_groups, leftover). When
    `leftover` is True the output is INCOMPLETE (rows of dirty buckets are
    dropped) — the caller must either lax.cond to an exact kernel or run
    under a speculation scope that re-executes the plan exactly.
    No strings (keys or buffers) — callers gate on schema.
    """
    G, R = group_slots, rounds
    n_slots = R * G
    out_cap = bucket_capacity(n_slots)
    seg, occ, key_slots, leftover = masked_group_assignment(
        key_columns, num_rows, capacity, row_mask, G, R)
    act = active_mask(num_rows, capacity)
    if row_mask is not None:
        act = act & row_mask
    positions = jnp.arange(capacity, dtype=jnp.int32)

    # dense ids for occupied slots (tiny arrays)
    dense = jnp.cumsum(occ.astype(jnp.int32)) - 1
    num_groups = jnp.sum(occ, dtype=jnp.int32)
    target = jnp.where(occ, dense, out_cap)  # scatter position per slot

    def _place(vals, valids):
        """(R*G,) slot arrays -> dense-prefix (out_cap,) arrays."""
        d = jnp.zeros((out_cap,), vals.dtype).at[target].set(
            vals, mode="drop")
        v = jnp.zeros((out_cap,), jnp.bool_).at[target].set(
            valids & occ, mode="drop")
        return d, v

    results = []
    for op, col in agg_inputs:
        if isinstance(col, StringColumn):
            raise NotImplementedError(
                "string buffers take the sort/hash tiers")
        svals, svalid = [], []
        for s in range(n_slots):
            val, ok = _slot_reduce(op, seg == s, col, positions, capacity)
            svals.append(val)
            svalid.append(ok)
        data, valid = _place(jnp.stack(svals), jnp.stack(svalid))
        results.append(("raw", (data, valid)))

    out_keys = []
    for (bits, valid), c in zip(key_slots, key_columns):
        vals = _unorder_bits(bits, c.dtype)
        data, v = _place(vals, valid)
        data = jnp.where(v, data, jnp.zeros((), data.dtype))
        out_keys.append(Column(data, v, c.dtype))
    return out_keys, results, num_groups, leftover


def masked_groupby_exact(key_columns: Sequence[Column],
                         agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                         num_rows, capacity: int, row_mask=None,
                         string_words: int = 1,
                         group_slots: int = 32, rounds: int = 2):
    """Exact full-capacity group-by with zero host syncs: masked-bucket fast
    path, lax.cond into the exact sort-based kernel for the (rare) leftover
    case. Output capacity == input capacity so both branches agree."""
    from .aggregate import groupby_aggregate

    seg, occ, key_slots, leftover = masked_group_assignment(
        key_columns, num_rows, capacity, row_mask, group_slots, rounds)
    act = active_mask(num_rows, capacity)
    if row_mask is not None:
        act = act & row_mask
    positions = jnp.arange(capacity, dtype=jnp.int32)
    G, R = group_slots, rounds
    n_slots = R * G

    def fast_branch(_):
        dense = jnp.cumsum(occ.astype(jnp.int32)) - 1
        num_groups = jnp.sum(occ, dtype=jnp.int32)
        target = jnp.where(occ, dense, capacity)

        def place(vals, valids):
            d = jnp.zeros((capacity,), vals.dtype).at[target].set(
                vals, mode="drop")
            v = jnp.zeros((capacity,), jnp.bool_).at[target].set(
                valids & occ, mode="drop")
            return d, v

        res = []
        for op, col in agg_inputs:
            svals, svalid = [], []
            for s in range(n_slots):
                val, ok = _slot_reduce(op, seg == s, col, positions,
                                       capacity)
                svals.append(val)
                svalid.append(ok)
            res.append(place(jnp.stack(svals), jnp.stack(svalid)))
        keys = []
        for (bits, valid), c in zip(key_slots, key_columns):
            vals = _unorder_bits(bits, c.dtype)
            d, v = place(vals, valid)
            keys.append(Column(jnp.where(v, d, jnp.zeros((), d.dtype)),
                               v, c.dtype))
        return tuple(keys), tuple(res), num_groups

    def sort_branch(_):
        if row_mask is None:
            cols = list(key_columns) + [c for _, c in agg_inputs
                                        if c is not None]
            n = num_rows
            kc = key_columns
            ai = agg_inputs
        else:
            # the exact path needs the packed-prefix invariant: compact
            all_cols = list(key_columns) + [c for _, c in agg_inputs
                                            if c is not None]
            packed, n = compact_columns(all_cols, row_mask, num_rows)
            kc = list(packed[: len(key_columns)])
            rest = list(packed[len(key_columns):])
            ai = []
            it = iter(rest)
            for op, c in agg_inputs:
                ai.append((op, next(it) if c is not None else None))
        keys, results, num_groups = groupby_aggregate(
            kc, ai, n, capacity, string_words)
        return (tuple(keys),
                tuple(r[1] for r in results),  # all ("raw", _) by gating
                num_groups)

    keys, plain, num_groups = jax.lax.cond(
        leftover, sort_branch, fast_branch, None)
    tagged = [("raw", p) for p in plain]
    return list(keys), tagged, num_groups


def masked_reduce(agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                  num_rows, row_mask=None, out_capacity: int = 128):
    """Grand aggregate (no GROUP BY), scatter-free: one masked full-array
    reduction per aggregate, one active output row at out_capacity.

    Capacity is derived per input column (a count(*)-only aggregate has NO
    input columns at all — its count is just num_rows/the mask popcount)."""
    act1 = active_mask(jnp.int32(1), out_capacity)
    out = []
    for op, col in agg_inputs:
        if col is None and row_mask is None:
            # count(*) with no filter mask: the row count IS the answer
            val = jnp.asarray(num_rows).astype(jnp.int64)
            ok = jnp.bool_(True)
        else:
            cap = col.capacity if col is not None else row_mask.shape[0]
            act = active_mask(num_rows, cap)
            if row_mask is not None:
                act = act & row_mask
            positions = jnp.arange(cap, dtype=jnp.int32)
            val, ok = _slot_reduce(op, act, col, positions, cap)
        data = jnp.zeros((out_capacity,), val.dtype).at[0].set(val)
        data = jnp.where(act1, data, jnp.zeros((), val.dtype))
        valid = act1 & ok
        out.append((data, valid))
    return out
