"""Masked-bucket group-by — the engine's primary aggregation kernel.

Reference analog: cuDF's hash group-by under GpuHashAggregateExec
(GpuAggregateExec.scala:1711). The TPU rebuild CANNOT use a hash table:
measured on v5e, XLA scatter/segment ops cost ~15ms per 1M rows (they
serialize), while masked full-array reductions FUSE into a handful of HBM
passes regardless of how many of them read the same input. So grouping is
built entirely from masked reductions:

  round r in [0, R):                              (R static, default 2)
    bucket b = mix_r(keys) mod G                  (G static, <= 64)
    per key column: masked min/max of its order-bits over each bucket
      -> bucket is CLEAN iff every key column is constant across it
         (min == max, and not a null/value mix) — an EXACT uniformity
         proof, no row gathers, no scatters
    clean buckets resolve ALL their rows to slot r*G + b; their key value
      is the min (== max) itself, decoded from order bits
    dirty buckets retry with a different mix next round
  leftover = any row still unresolved after R rounds (cardinality greater
  than the slot table or adversarial collisions)

Aggregates are masked reductions per slot (sum/count/min/max/first/last),
slots compact to a dense prefix with one tiny (R*G)-element pass, and the
whole thing — bucket assignment, uniformity proof, reductions — fuses with
the upstream filter/project into ONE XLA program with ZERO host syncs.

`leftover` handling is the caller's choice: speculate (emit the small
partial + device flag; plan-level retry re-runs exact if it ever trips —
exec/speculation.py) or wrap in lax.cond with the exact sort-based kernel
(masked_groupby_exact).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn, bucket_capacity
from ..types import DataType
from .basic import active_mask, compact_columns
from .sort import _numeric_order_key


def _unorder_bits(u, dtype: DataType):
    """Invert ops/sort._numeric_order_key: order-bits lane -> value."""
    jdt = jnp.dtype(dtype.jnp_dtype)
    if jdt == jnp.bool_:
        return u.astype(jnp.bool_)
    if jnp.issubdtype(jdt, jnp.floating):
        bits_dt = jnp.uint64 if jdt == jnp.float64 else jnp.uint32
        sign = jnp.ones((), bits_dt) << (8 * jnp.dtype(bits_dt).itemsize - 1)
        was_neg = (u & sign) == 0
        bits = jnp.where(was_neg, ~u, u ^ sign)
        val = jax.lax.bitcast_convert_type(
            bits, jnp.float64 if jdt == jnp.float64 else jnp.float32)
        return val.astype(jdt)
    if jnp.issubdtype(jdt, jnp.signedinteger):
        bits = 8 * jnp.dtype(jdt).itemsize
        flipped = u ^ (jnp.ones((), u.dtype) << (bits - 1))
        return jax.lax.bitcast_convert_type(flipped, jdt)
    return u.astype(jdt)


def _mix32(h, salt: int):
    """Cheap murmur3-finalizer mixing (internal bucketing only — Spark-parity
    hashing lives in ops/hashing.py and is ~10x costlier)."""
    h = h ^ jnp.uint32(salt)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _bucket_hash(key_cols: Sequence[Column], salt: int, capacity: int):
    h = jnp.full((capacity,), jnp.uint32(0x9E3779B9))
    for c in key_cols:
        lane = _numeric_order_key(c)
        if lane.dtype in (jnp.uint64, jnp.int64):
            lo = lane.astype(jnp.uint32)
            hi = (lane >> jnp.uint64(32)).astype(jnp.uint32)
            h = _mix32(h ^ lo, salt)
            h = _mix32(h ^ hi, salt + 0x51)
        else:
            h = _mix32(h ^ lane.astype(jnp.uint32), salt)
        h = _mix32(h ^ c.validity.astype(jnp.uint32), salt + 0xA3)
    return h


def masked_group_assignment(key_cols: Sequence[Column], num_rows,
                            capacity: int, row_mask=None,
                            group_slots: int = 32, rounds: int = 2):
    """Scatter-free exact group assignment.

    Returns (seg (capacity,) int32 in [0, R*G) or sentinel R*G;
    slot_occupied (R*G,) bool; slot key values+validity per key column;
    leftover device bool).
    """
    G, R = group_slots, rounds
    assert G <= 64, "bitmask lookup supports at most 64 buckets per round"
    mask_dt = jnp.uint32 if G <= 32 else jnp.uint64
    cap = capacity
    act = active_mask(num_rows, cap)
    if row_mask is not None:
        act = act & row_mask
    unresolved = act
    sentinel = R * G
    seg = jnp.full((cap,), sentinel, jnp.int32)
    slot_occ: List[jnp.ndarray] = []
    slot_keys: List[List[Tuple[jnp.ndarray, jnp.ndarray]]] = []  # per round

    g_iota = jnp.arange(G, dtype=jnp.int32)
    one = jnp.ones((), mask_dt)
    lanes = [_numeric_order_key(c) for c in key_cols]

    # one u32 per row packing (valid?2:1) << 2ci for every key column:
    # a single OR-reduction then yields any_valid/any_null per column AND
    # bucket occupancy, replacing 2*n_cols+1 boolean sweep-reductions
    # (the sweeps are VPU-compute-bound, so reduction count is the cost).
    # More than 16 key columns exceed the u32 code word: those queries
    # keep the per-column boolean reductions.
    packed_stats = len(key_cols) <= 16
    if packed_stats:
        base_code = jnp.zeros((cap,), jnp.uint32)
        for ci, c in enumerate(key_cols):
            bits_ci = jnp.where(c.validity, jnp.uint32(2), jnp.uint32(1))
            base_code = base_code | (bits_ci << jnp.uint32(2 * ci))

    def _round(r: int, unresolved):
        """One bucketing round: per-bucket stats as axis-0 reductions over
        an on-the-fly (cap, G) comparison tensor. XLA fuses the broadcast
        compare into the reduce without materializing cap*G elements, and
        one such reduce is dramatically cheaper than G independent masked
        reductions (measured on v5e: 32x4 separate reductions lower to
        serial per-bucket passes; the 2-D form is a single tiled sweep)."""
        h = _bucket_hash(key_cols, 0x2545F491 + r * 0x9E37, cap)
        b = (h % jnp.uint32(G)).astype(jnp.int32)
        bm = b[:, None] == g_iota[None, :]            # (cap, G) on the fly
        un2 = unresolved[:, None] & bm
        if packed_stats:
            code = jax.lax.reduce(
                jnp.where(un2, base_code[:, None], jnp.uint32(0)),
                jnp.uint32(0), jax.lax.bitwise_or, (0,))  # (G,) stats
        clean = jnp.ones((G,), jnp.bool_)
        mins_cols, avail_cols = [], []
        for ci, (c, lane) in enumerate(zip(key_cols, lanes)):
            neutral_min = jnp.full((), jnp.iinfo(lane.dtype).max,
                                   lane.dtype)
            mv = un2 & c.validity[:, None]
            mn = jnp.min(jnp.where(mv, lane[:, None], neutral_min), axis=0)
            mx = jnp.max(jnp.where(mv, lane[:, None],
                                   jnp.zeros((), lane.dtype)), axis=0)
            if packed_stats:
                any_valid = ((code >> jnp.uint32(2 * ci + 1)) & 1) != 0
                any_null = ((code >> jnp.uint32(2 * ci)) & 1) != 0
            else:
                any_valid = jnp.any(mv, axis=0)
                any_null = jnp.any(un2 & ~c.validity[:, None], axis=0)
            clean = clean & ~(any_valid & any_null) & \
                (~any_valid | (mn == mx))
            mins_cols.append(mn)
            avail_cols.append(any_valid)
        occupied = (code != 0) if packed_stats else jnp.any(un2, axis=0)
        resolved_bucket = clean & occupied
        # rows stay unresolved exactly when their bucket is occupied and
        # dirty, so "any row left" is a G-element reduce, not a cap one
        dirty = jnp.any(occupied & ~clean)
        # branchless per-row lookup: clean buckets as a bitmask scalar
        bits = jnp.sum(jnp.where(resolved_bucket,
                                 one << g_iota.astype(mask_dt), 0))
        row_clean = ((bits >> b.astype(mask_dt)) & one) != 0
        resolved = unresolved & row_clean
        return b, resolved_bucket, resolved, dirty, tuple(mins_cols), \
            tuple(avail_cols)

    dirty = None
    for r in range(R):
        if r == 0:
            b, resolved_bucket, resolved, dirty, mins_cols, avail_cols = \
                _round(0, unresolved)
        else:
            # later rounds only matter when earlier rounds left rows
            # unresolved; the common case (low-cardinality keys) resolves
            # everything in round 1, so skip the whole sweep on device
            def _dead(_):
                return (jnp.zeros((cap,), jnp.int32),
                        jnp.zeros((G,), jnp.bool_),
                        jnp.zeros((cap,), jnp.bool_),
                        jnp.bool_(False),
                        tuple(jnp.zeros((G,), ln.dtype) for ln in lanes),
                        tuple(jnp.zeros((G,), jnp.bool_)
                              for _ in key_cols))

            b, resolved_bucket, resolved, dirty, mins_cols, avail_cols = \
                jax.lax.cond(dirty,
                             lambda _, _r=r, _u=unresolved: _round(_r, _u),
                             _dead, None)
        keys_r: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (mins_cols[ci], avail_cols[ci]) for ci in range(len(key_cols))]
        seg = jnp.where(resolved, r * G + b, seg)
        unresolved = unresolved & ~resolved
        slot_occ.append(resolved_bucket)
        slot_keys.append(keys_r)

    # rows left after the final round == final round had a dirty bucket
    leftover = dirty
    occ = jnp.concatenate(slot_occ)  # (R*G,)
    # per key column: (R*G,) order-bits + validity across rounds
    key_slots = []
    for ci, c in enumerate(key_cols):
        bits = jnp.concatenate([slot_keys[r][ci][0] for r in range(R)])
        valid = jnp.concatenate([slot_keys[r][ci][1] for r in range(R)])
        key_slots.append((bits, valid))
    return seg, occ, key_slots, leftover


def _slot_sweep(agg_inputs, seg, positions, capacity: int, n_slots: int,
                G: int, R: int, occ):
    """All aggregates over all slots, skipping the slots past the first G
    on device when no group resolved after round 1 (the common
    low-cardinality case pays for G slots, not R*G)."""

    def sweep(S: int):
        si = jnp.arange(S, dtype=jnp.int32)[None, :]
        m = seg[:, None] == si
        has_map = _packed_has(agg_inputs, m)
        outs = []
        for i, (op, col) in enumerate(agg_inputs):
            svals, svalid = _slot_reduce_all(op, seg, col, positions,
                                             capacity, S, m=m,
                                             has=has_map.get(i))
            if S < n_slots:
                def _pad(a):
                    return jnp.concatenate(
                        [a, jnp.zeros((n_slots - S,), a.dtype)])
                svals = tuple(_pad(x) for x in svals) \
                    if isinstance(svals, tuple) else _pad(svals)
                svalid = jnp.concatenate(
                    [svalid, jnp.zeros((n_slots - S,), jnp.bool_)])
            outs.append((svals, svalid))
        return tuple(outs)

    if R > 1 and agg_inputs:
        return jax.lax.cond(jnp.any(occ[G:]), lambda _: sweep(n_slots),
                            lambda _: sweep(G), None)
    return sweep(n_slots)


def _decimal_limbs(col: Column):
    """(hi, lo) int64 lanes of a decimal column (either tier)."""
    from ..columnar.column import Decimal128Column
    from . import decimal128 as D
    if isinstance(col, Decimal128Column):
        return col.hi.data, col.lo.data
    return D.from_i64(col.data.astype(jnp.int64))


def _packed_has(agg_inputs, m) -> dict:
    """One OR-reduction computing per-slot 'any valid row' for every
    aggregate that needs it (bit i of a packed u32 per row), replacing one
    boolean sweep-reduction per aggregate. Returns {agg_index: (S,) bool}."""
    need = [i for i, (op, c) in enumerate(agg_inputs)
            if op in ("sum", "sum_sq", "min", "max") and c is not None]
    if not need or len(need) > 32:
        return {}
    cap = agg_inputs[need[0]][1].capacity
    base = jnp.zeros((cap,), jnp.uint32)
    for k, i in enumerate(need):
        base = base | (agg_inputs[i][1].validity.astype(jnp.uint32)
                       << jnp.uint32(k))
    packed = jax.lax.reduce(
        jnp.where(m, base[:, None], jnp.uint32(0)),
        jnp.uint32(0), jax.lax.bitwise_or, (0,))
    return {i: ((packed >> jnp.uint32(k)) & 1) != 0
            for k, i in enumerate(need)}


def _slot_reduce_all(op: str, seg, col: Optional[Column], positions,
                     capacity: int, n_slots: int, m=None, has=None):
    """One aggregate over ALL slots at once: an axis-0 reduction over the
    on-the-fly (capacity, n_slots) segment-membership tensor. Returns
    ((n_slots,) values, (n_slots,) valid). Equivalent to n_slots calls of
    _slot_reduce but a single fused sweep on device."""
    if m is None:
        si = jnp.arange(n_slots, dtype=jnp.int32)[None, :]
        m = seg[:, None] == si                  # (cap, S) on the fly
    ones_s = jnp.ones((n_slots,), jnp.bool_)
    if op == "count_star":
        # i32 accumulation (a batch cannot exceed 2^31 rows), widened to
        # Spark's LONG count after the reduce — i64 lanes are emulated
        return (jnp.sum(m, axis=0, dtype=jnp.int32).astype(jnp.int64),
                ones_s)
    v = m & col.validity[:, None]
    if op == "count":
        return (jnp.sum(v, axis=0, dtype=jnp.int32).astype(jnp.int64),
                ones_s)
    if has is None:
        has = jnp.any(v, axis=0)
    if op in ("sum", "sum_sq"):
        from ..types import DecimalType
        if op == "sum" and isinstance(col.dtype, DecimalType):
            # exact 128-bit decimal sum: eight u16-limb lanes summed in
            # int64, recombined mod 2^128 (ops/decimal128.py)
            from . import decimal128 as D
            h, l = _decimal_limbs(col)
            sums = [jnp.sum(jnp.where(v, lane[:, None], jnp.int64(0)),
                            axis=0)
                    for lane in D.limb16_lanes(h, l)]
            negs = jnp.sum(v & (h < 0)[:, None], axis=0,
                           dtype=jnp.int64)
            rh, rl, over = D.combine_limb_sums_checked(sums, negs)
            any_sat = jnp.any(v & D.is_saturated(h, l)[:, None], axis=0)
            rh, rl = D.saturate_sum(rh, rl, over, any_sat)
            return (rh, rl), has
        data = col.data
        acc = data.astype(jnp.float64) \
            if jnp.issubdtype(data.dtype, jnp.floating) \
            else data.astype(jnp.int64)
        if op == "sum_sq":
            acc = acc * acc
        z = jnp.zeros((), acc.dtype)
        return jnp.sum(jnp.where(v, acc[:, None], z), axis=0), has
    if op in ("min", "max"):
        data = col.data
        if jnp.issubdtype(data.dtype, jnp.floating):
            neutral = jnp.full((), jnp.inf if op == "min" else -jnp.inf,
                               data.dtype)
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
            neutral = jnp.int8(1 if op == "min" else 0)
        else:
            info = jnp.iinfo(data.dtype)
            neutral = jnp.full((), info.max if op == "min" else info.min,
                               data.dtype)
        fn = jnp.min if op == "min" else jnp.max
        return fn(jnp.where(v, data[:, None], neutral), axis=0), has
    if op in ("first", "last", "any_value", "first_any", "last_any"):
        pick_mask = m if op in ("first_any", "last_any") else v
        if op in ("last", "last_any"):
            pick = jnp.max(jnp.where(pick_mask, positions[:, None], -1),
                           axis=0)
        else:
            pick = jnp.min(jnp.where(pick_mask, positions[:, None],
                                     capacity), axis=0)
        ok = (pick >= 0) & (pick < capacity)
        safe = jnp.clip(pick, 0, capacity - 1)
        vals = col.data[safe]                    # (S,)-sized gather
        if op in ("first_any", "last_any"):
            ok = ok & col.validity[safe]
        return vals, ok
    raise AssertionError(op)


def _slot_reduce(op: str, m, col: Optional[Column], positions,
                 capacity: int):
    """One aggregate over one row mask: a masked full-array reduction."""
    if op == "count_star":
        return jnp.sum(m, dtype=jnp.int64), jnp.bool_(True)
    v = col.validity & m
    if op == "count":
        return jnp.sum(v, dtype=jnp.int64), jnp.bool_(True)
    has = jnp.any(v)
    if op in ("sum", "sum_sq"):
        from ..types import DecimalType
        if op == "sum" and isinstance(col.dtype, DecimalType):
            from . import decimal128 as D
            h, l = _decimal_limbs(col)
            sums = [jnp.sum(jnp.where(v, lane, jnp.int64(0)))
                    for lane in D.limb16_lanes(h, l)]
            negs = jnp.sum(v & (h < 0), dtype=jnp.int64)[None]
            rh, rl, over = D.combine_limb_sums_checked(
                [s[None] for s in sums], negs)  # (1,)-shaped limb pair
            any_sat = jnp.any(v & D.is_saturated(h, l))[None]
            rh, rl = D.saturate_sum(rh, rl, over, any_sat)
            return (rh, rl), has
        data = col.data
        acc = data.astype(jnp.float64) \
            if jnp.issubdtype(data.dtype, jnp.floating) \
            else data.astype(jnp.int64)
        if op == "sum_sq":
            acc = acc * acc
        return jnp.sum(jnp.where(v, acc, jnp.zeros((), acc.dtype))), has
    if op in ("min", "max"):
        data = col.data
        if jnp.issubdtype(data.dtype, jnp.floating):
            neutral = jnp.full((), jnp.inf if op == "min" else -jnp.inf,
                               data.dtype)
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
            neutral = jnp.int8(1 if op == "min" else 0)
        else:
            info = jnp.iinfo(data.dtype)
            neutral = jnp.full((), info.max if op == "min" else info.min,
                               data.dtype)
        fn = jnp.min if op == "min" else jnp.max
        return fn(jnp.where(v, data, neutral)), has
    if op in ("first", "last", "any_value"):
        if op == "last":
            pick = jnp.max(jnp.where(v, positions, -1))
        else:
            pick = jnp.min(jnp.where(v, positions, capacity))
        ok = (pick >= 0) & (pick < capacity)
        return col.data[jnp.clip(pick, 0, capacity - 1)], ok
    if op in ("first_any", "last_any"):
        # ignoreNulls=False: pick over ACTIVE rows regardless of null
        if op == "last_any":
            pick = jnp.max(jnp.where(m, positions, -1))
        else:
            pick = jnp.min(jnp.where(m, positions, capacity))
        ok = (pick >= 0) & (pick < capacity)
        safe = jnp.clip(pick, 0, capacity - 1)
        return col.data[safe], ok & col.validity[safe]
    raise AssertionError(op)


def masked_groupby(key_columns: Sequence[Column],
                   agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                   num_rows, capacity: int, row_mask=None,
                   group_slots: int = 32, rounds: int = 2):
    """Group-by into a SMALL output bucket (capacity bucket_capacity(R*G)).

    Returns (out_keys, tagged results, num_groups, leftover). When
    `leftover` is True the output is INCOMPLETE (rows of dirty buckets are
    dropped) — the caller must either lax.cond to an exact kernel or run
    under a speculation scope that re-executes the plan exactly.
    No strings (keys or buffers) — callers gate on schema.
    """
    G, R = group_slots, rounds
    n_slots = R * G
    out_cap = bucket_capacity(n_slots)
    seg, occ, key_slots, leftover = masked_group_assignment(
        key_columns, num_rows, capacity, row_mask, G, R)
    act = active_mask(num_rows, capacity)
    if row_mask is not None:
        act = act & row_mask
    positions = jnp.arange(capacity, dtype=jnp.int32)

    # dense ids for occupied slots (tiny arrays)
    dense = jnp.cumsum(occ.astype(jnp.int32)) - 1
    num_groups = jnp.sum(occ, dtype=jnp.int32)
    target = jnp.where(occ, dense, out_cap)  # scatter position per slot

    def _place(vals, valids):
        """(R*G,) slot arrays -> dense-prefix (out_cap,) arrays.
        vals may be a (hi, lo) limb tuple (decimal128 sums)."""
        if isinstance(vals, tuple):
            d = tuple(jnp.zeros((out_cap,), x.dtype).at[target].set(
                x, mode="drop") for x in vals)
        else:
            d = jnp.zeros((out_cap,), vals.dtype).at[target].set(
                vals, mode="drop")
        v = jnp.zeros((out_cap,), jnp.bool_).at[target].set(
            valids & occ, mode="drop")
        return d, v

    for op, col in agg_inputs:
        if isinstance(col, StringColumn):
            raise NotImplementedError(
                "string buffers take the sort/hash tiers")

    sweeps = _slot_sweep(agg_inputs, seg, positions, capacity, n_slots,
                         G, R, occ)

    results = []
    for svals, svalid in sweeps:
        data, valid = _place(svals, svalid)
        results.append(("raw", (data, valid)))

    out_keys = []
    for (bits, valid), c in zip(key_slots, key_columns):
        vals = _unorder_bits(bits, c.dtype)
        data, v = _place(vals, valid)
        data = jnp.where(v, data, jnp.zeros((), data.dtype))
        out_keys.append(Column(data, v, c.dtype))
    return out_keys, results, num_groups, leftover


def masked_groupby_exact(key_columns: Sequence[Column],
                         agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                         num_rows, capacity: int, row_mask=None,
                         string_words: int = 1,
                         group_slots: int = 32, rounds: int = 2):
    """Exact full-capacity group-by with zero host syncs: masked-bucket fast
    path, lax.cond into the exact sort-based kernel for the (rare) leftover
    case. Output capacity == input capacity so both branches agree."""
    from .aggregate import groupby_aggregate

    seg, occ, key_slots, leftover = masked_group_assignment(
        key_columns, num_rows, capacity, row_mask, group_slots, rounds)
    act = active_mask(num_rows, capacity)
    if row_mask is not None:
        act = act & row_mask
    positions = jnp.arange(capacity, dtype=jnp.int32)
    G, R = group_slots, rounds
    n_slots = R * G

    def fast_branch(_):
        dense = jnp.cumsum(occ.astype(jnp.int32)) - 1
        num_groups = jnp.sum(occ, dtype=jnp.int32)
        target = jnp.where(occ, dense, capacity)

        def place(vals, valids):
            if isinstance(vals, tuple):
                d = tuple(jnp.zeros((capacity,), x.dtype).at[target].set(
                    x, mode="drop") for x in vals)
            else:
                d = jnp.zeros((capacity,), vals.dtype).at[target].set(
                    vals, mode="drop")
            v = jnp.zeros((capacity,), jnp.bool_).at[target].set(
                valids & occ, mode="drop")
            return d, v

        sweeps = _slot_sweep(agg_inputs, seg, positions, capacity,
                             n_slots, G, R, occ)
        res = [place(svals, svalid) for svals, svalid in sweeps]
        keys = []
        for (bits, valid), c in zip(key_slots, key_columns):
            vals = _unorder_bits(bits, c.dtype)
            d, v = place(vals, valid)
            keys.append(Column(jnp.where(v, d, jnp.zeros((), d.dtype)),
                               v, c.dtype))
        return tuple(keys), tuple(res), num_groups

    def sort_branch(_):
        if row_mask is None:
            cols = list(key_columns) + [c for _, c in agg_inputs
                                        if c is not None]
            n = num_rows
            kc = key_columns
            ai = agg_inputs
        else:
            # the exact path needs the packed-prefix invariant: compact
            all_cols = list(key_columns) + [c for _, c in agg_inputs
                                            if c is not None]
            packed, n = compact_columns(all_cols, row_mask, num_rows)
            kc = list(packed[: len(key_columns)])
            rest = list(packed[len(key_columns):])
            ai = []
            it = iter(rest)
            for op, c in agg_inputs:
                ai.append((op, next(it) if c is not None else None))
        keys, results, num_groups = groupby_aggregate(
            kc, ai, n, capacity, string_words)
        return (tuple(keys),
                tuple(r[1] for r in results),  # all ("raw", _) by gating
                num_groups)

    keys, plain, num_groups = jax.lax.cond(
        leftover, sort_branch, fast_branch, None)
    tagged = [("raw", p) for p in plain]
    return list(keys), tagged, num_groups


def masked_reduce(agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                  num_rows, row_mask=None, out_capacity: int = 128):
    """Grand aggregate (no GROUP BY), scatter-free: one masked full-array
    reduction per aggregate, one active output row at out_capacity.

    Capacity is derived per input column (a count(*)-only aggregate has NO
    input columns at all — its count is just num_rows/the mask popcount)."""
    act1 = active_mask(jnp.int32(1), out_capacity)
    out = []
    for op, col in agg_inputs:
        if col is None and row_mask is None:
            # count(*) with no filter mask: the row count IS the answer
            val = jnp.asarray(num_rows).astype(jnp.int64)
            ok = jnp.bool_(True)
        else:
            cap = col.capacity if col is not None else row_mask.shape[0]
            act = active_mask(num_rows, cap)
            if row_mask is not None:
                act = act & row_mask
            positions = jnp.arange(cap, dtype=jnp.int32)
            val, ok = _slot_reduce(op, act, col, positions, cap)
        if isinstance(val, tuple):  # decimal128 (hi, lo) limbs
            data = tuple(
                jnp.where(act1, jnp.zeros((out_capacity,), x.dtype)
                          .at[0].set(x.reshape(())), jnp.int64(0))
                for x in val)
        else:
            data = jnp.zeros((out_capacity,), val.dtype).at[0].set(val)
            data = jnp.where(act1, data, jnp.zeros((), val.dtype))
        valid = act1 & ok
        out.append((data, valid))
    return out
