"""DMA-driven packed row gather — the Pallas `gather` kernel family
(ISSUE 8 tentpole; reference analog: cuDF's gather as a first-class
table primitive behind JoinGatherer, not N per-column ops).

XLA's random gather on v5e is loop-bound, not bandwidth-bound
(docs/perf.md: ~330 ms per 2M-row gather on the tunnel chip, ~26 ms per
single i32 column vs ~7.4 ms for an (N, 8) matrix). The engine already
amortizes column count by packing fixed-width columns into one u32
(+ one f64) matrix (ops/rowpack.py); this kernel replaces the XLA row
gather OVER that packed layout with explicit per-row DMA: index tiles
stream through SMEM, the source matrix stays in HBM, and a window of
in-flight async copies moves whole packed rows straight into the VMEM
output tile — one HBM touch per gathered row, no gather loop.

ABI (shared engine contracts):
- the source matrix is ALL u32 lanes: the wrapper bitcasts the f64
  matrix to two u32 lanes per column (TPU kernels avoid 64-bit lanes,
  same discipline as the murmur3/join kernels) and splits it back after
  the gather, so null masks and payload ride ONE pass;
- out-of-range indices (idx < 0 or >= capacity) read row 0 and the
  wrapper zeroes the validity lanes — bit-identical to
  ops/rowpack.gather_rows, which the interpret-mode property tests
  assert elementwise (tests/test_pallas_gather.py);
- index arrays are capacity-bucket padded by callers; padded slots are
  -1 and come back all-invalid (the engine-wide padding contract of
  ops/pallas_kernels.py).

Like the other families the kernel traces under enable_x64(False) on
hardware (mosaic wants i32 grid arithmetic) and under the engine's
global x64 mode in interpret mode. Selection is a measurement: the
`gather` family in tools/kern_bench.py + ops/pallas_tier.py decides
per shape bucket; no record -> the XLA row gather stays.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs.dispatch import instrument as _instrument

#: rows of packed output per grid step (each row is one DMA)
GATHER_TILE_ROWS = 256
#: in-flight row copies per grid step (W distinct DMA semaphores;
#: iteration r starts row r+W-1 before waiting row r, so up to W-1
#: copies overlap — the guide's double-buffer pattern generalized)
DMA_WINDOW = 8

#: host-side count of pallas_call dispatches (trace-time): lets tests
#: and bench attribution assert the measured tier actually routed a
#: gather through the kernel rather than silently falling back
_kernel_traces = 0


def kernel_trace_count() -> int:
    return _kernel_traces


def _gather_kernel_body(window: int, tile_rows: int):
    def kernel(idx_ref, src_ref, out_ref, sems):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def dma(r):
            # interpret mode traces under the engine's global x64, so
            # loop counters arrive as i64 — normalize for the i32 slot
            # arithmetic either way
            r = jnp.asarray(r, jnp.int32)
            i = idx_ref[r, 0]
            return pltpu.make_async_copy(
                src_ref.at[pl.ds(i, 1), :],
                out_ref.at[pl.ds(r, 1), :],
                sems.at[jax.lax.rem(r, jnp.int32(window))])

        def warm(r, c):
            dma(r).start()
            return c

        jax.lax.fori_loop(0, min(window - 1, tile_rows), warm, 0)

        def body(r, c):
            nxt = r + jnp.int32(window - 1)

            @pl.when(nxt < jnp.int32(tile_rows))
            def _():
                dma(nxt).start()

            dma(r).wait()
            return c

        jax.lax.fori_loop(0, tile_rows, body, 0)

    return kernel


@functools.partial(_instrument, label="pallas.gather",
                   static_argnames=("interpret",))
def dma_row_gather(mat: jnp.ndarray, idx: jnp.ndarray,
                   interpret: bool = False) -> jnp.ndarray:
    """out[i] = mat[idx[i]] by per-row DMA; the caller pre-sanitizes idx
    to [0, capacity) (out-of-range handling is the wrapper's job)."""
    import contextlib

    from jax.experimental import enable_x64
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    global _kernel_traces
    _kernel_traces += 1

    n = idx.shape[0]
    lanes = mat.shape[1]
    tr = GATHER_TILE_ROWS
    rows = max(1, -(-n // tr)) * tr
    idx2d = jnp.pad(idx.astype(jnp.int32), (0, rows - n)).reshape(rows, 1)
    grid = rows // tr

    # see ops/pallas_join.py: hardware traces x64-off for i32 grid
    # arithmetic; the interpreter re-canonicalizes under the global mode
    ctx = contextlib.nullcontext() if interpret else enable_x64(False)
    with ctx:
        # contract: ok dispatch-ledger — traced inline into the
        # instrumented dma_row_gather program above
        out = pl.pallas_call(
            _gather_kernel_body(DMA_WINDOW, tr),
            out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.uint32),
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((tr, 1), lambda i: (i, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec((tr, lanes), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((DMA_WINDOW,))],
            interpret=interpret,
        )(idx2d, mat)
    return out[:n]


def pallas_gather_rows(plan, imat, fmat, idx, interpret: bool = False
                       ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Drop-in for ops/rowpack.gather_rows served by the DMA kernel.

    Packs the f64 matrix into u32 lanes beside the int matrix so ONE
    kernel pass moves the whole row (validity bits + data), then splits
    and re-masks exactly like the XLA formulation.
    """
    cap = imat.shape[0]
    ni = imat.shape[1]
    parts = [imat]
    nf = 0
    if fmat is not None:
        nf = fmat.shape[1]
        f_u32 = jax.lax.bitcast_convert_type(fmat, jnp.uint32)
        parts.append(f_u32.reshape(cap, 2 * nf))
    mat = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)

    in_range = (idx >= 0) & (idx < cap)
    safe = jnp.where(in_range, idx, 0).astype(jnp.int32)
    g = dma_row_gather(mat, safe, interpret=interpret)

    nv = plan.n_valid_lanes
    gi = g[:, :ni]
    if nv:
        vmask = jnp.where(in_range, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
        gi = jnp.concatenate([gi[:, :nv] & vmask[:, None], gi[:, nv:]],
                             axis=1)
    gf = None
    if fmat is not None:
        gf = jax.lax.bitcast_convert_type(
            g[:, ni:].reshape(idx.shape[0], nf, 2), fmat.dtype)
    return gi, gf
