"""Varlen (string/binary) kernels over the (offsets, bytes) twin-array layout.

Replaces cuDF's strings column primitives (reference L6). XLA has no ragged
tensors, so every kernel is expressed as dense gathers over the padded byte
buffer. The workhorse is `row_of_byte`: for each output byte position, find
which row it belongs to via searchsorted on the output offsets — this turns
any row-gather of strings into two vectorized gathers (O(B log N) with B =
byte capacity), fully static shapes, MXU-free pure VPU work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn


def string_lengths(col: StringColumn):
    """int32 (capacity,): byte length per row (0 for null/inactive rows)."""
    return col.offsets[1:] - col.offsets[:-1]


def hex_digit_val(b):
    """Value of an ASCII hex digit byte; -1 for non-hex (shared by the
    json/codec/url kernels)."""
    v = jnp.full(b.shape, jnp.int32(-1))
    v = jnp.where((b >= ord("0")) & (b <= ord("9")),
                  b.astype(jnp.int32) - ord("0"), v)
    v = jnp.where((b >= ord("a")) & (b <= ord("f")),
                  b.astype(jnp.int32) - ord("a") + 10, v)
    v = jnp.where((b >= ord("A")) & (b <= ord("F")),
                  b.astype(jnp.int32) - ord("A") + 10, v)
    return v


def seg_incl_cumsum(x, row_start_pos):
    """Per-row inclusive cumsum of int32 x over a flat byte buffer:
    global cumsum minus the exclusive cumsum at each byte's row start."""
    c = jnp.cumsum(x, dtype=jnp.int32)
    return c - (c - x)[row_start_pos]


def _rebuild_offsets(lengths):
    """Exclusive-scan lengths into (capacity+1,) offsets."""
    return jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(lengths, dtype=jnp.int32),
    ])


def gather_string(col: StringColumn, indices, out_valid,
                  out_byte_capacity: int | None = None) -> StringColumn:
    """Gather rows of a string column by pre-clamped int32 `indices`.

    out_byte_capacity: static byte bucket of the result. Defaults to the
    input's byte bucket (sufficient for any permutation/filter; joins that
    duplicate long rows must pass a larger bucket).
    """
    byte_cap = out_byte_capacity or col.byte_capacity
    lengths = string_lengths(col)[indices]
    lengths = jnp.where(out_valid, lengths, 0)
    new_offsets = _rebuild_offsets(lengths)
    src_starts = col.offsets[indices]

    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    # row owning each output byte: last row whose offset <= pos
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, indices.shape[0] - 1)
    intra = pos - new_offsets[row]
    src_pos = src_starts[row] + intra
    in_use = pos < new_offsets[-1]
    src_pos = jnp.where(in_use, jnp.clip(src_pos, 0, col.byte_capacity - 1), 0)
    data = jnp.where(in_use, col.data[src_pos], jnp.uint8(0))
    return StringColumn(data, new_offsets, out_valid, col.dtype)


def concat_string(a: StringColumn, b: StringColumn, a_rows, b_rows,
                  out_capacity: int,
                  out_byte_capacity: int | None = None) -> StringColumn:
    """Concatenate active rows of two string columns."""
    byte_cap = out_byte_capacity or (a.byte_capacity + b.byte_capacity)
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    from_b = idx >= a_rows
    total = a_rows + b_rows
    out_valid_slot = idx < total

    a_len = string_lengths(a)
    b_len = string_lengths(b)
    a_idx = jnp.where(idx < a.capacity, idx, 0)
    b_idx = jnp.clip(idx - a_rows, 0, b.capacity - 1)
    lengths = jnp.where(from_b, b_len[b_idx], a_len[a_idx])
    lengths = jnp.where(out_valid_slot, lengths, 0)
    validity = jnp.where(from_b, b.validity[b_idx], a.validity[a_idx]) & out_valid_slot
    new_offsets = _rebuild_offsets(lengths)
    src_starts = jnp.where(from_b, b.offsets[b_idx], a.offsets[a_idx])

    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, out_capacity - 1)
    intra = pos - new_offsets[row]
    src_pos = src_starts[row] + intra
    row_from_b = from_b[row]
    in_use = pos < new_offsets[-1]
    a_bytes = a.data[jnp.where(in_use & ~row_from_b,
                               jnp.clip(src_pos, 0, a.byte_capacity - 1), 0)]
    b_bytes = b.data[jnp.where(in_use & row_from_b,
                               jnp.clip(src_pos, 0, b.byte_capacity - 1), 0)]
    data = jnp.where(in_use, jnp.where(row_from_b, b_bytes, a_bytes), jnp.uint8(0))
    return StringColumn(data, new_offsets, validity, a.dtype)


# --- elementwise string functions ----------------------------------------

def str_length_bytes(col: StringColumn) -> Column:
    from ..types import INT
    return Column(string_lengths(col), col.validity, INT)


def str_length_chars(col: StringColumn) -> Column:
    """UTF-8 aware character count (Spark `length`): count non-continuation
    bytes ((b & 0xC0) != 0x80) per row via a segmented sum."""
    from ..types import INT
    cap = col.capacity
    is_start = ((col.data & 0xC0) != 0x80).astype(jnp.int32)
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(is_start, dtype=jnp.int32)])
    counts = csum[col.offsets[1:]] - csum[col.offsets[:-1]]
    return Column(counts, col.validity, INT)


def str_upper_ascii(col: StringColumn) -> StringColumn:
    lower = (col.data >= ord("a")) & (col.data <= ord("z"))
    data = jnp.where(lower, col.data - 32, col.data)
    return StringColumn(data, col.offsets, col.validity, col.dtype)


def str_lower_ascii(col: StringColumn) -> StringColumn:
    upper = (col.data >= ord("A")) & (col.data <= ord("Z"))
    data = jnp.where(upper, col.data + 32, col.data)
    return StringColumn(data, col.offsets, col.validity, col.dtype)


def substring(col: StringColumn, start: int, length: int | None) -> StringColumn:
    """Spark substring semantics: 1-based start, negative = from end."""
    lens = string_lengths(col)
    if start > 0:
        begin = jnp.minimum(jnp.int32(start - 1), lens)
    elif start == 0:
        begin = jnp.zeros_like(lens)
    else:
        begin = jnp.maximum(lens + start, 0)
    if length is None:
        sub_len = lens - begin
    else:
        sub_len = jnp.clip(jnp.int32(length), 0, lens - begin)
    starts = col.offsets[:-1] + begin
    return _substring_gather(col, starts, sub_len)


def _substring_gather(col: StringColumn, src_starts, lengths) -> StringColumn:
    lengths = jnp.where(col.validity, lengths, 0)
    new_offsets = _rebuild_offsets(lengths)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, col.capacity - 1)
    intra = pos - new_offsets[row]
    src_pos = src_starts[row] + intra
    in_use = pos < new_offsets[-1]
    src_pos = jnp.where(in_use, jnp.clip(src_pos, 0, byte_cap - 1), 0)
    data = jnp.where(in_use, col.data[src_pos], jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def _match_at(col: StringColumn, needle: bytes, starts):
    """Bool per row: needle matches at byte position `starts` (absolute)."""
    ok = jnp.ones(col.capacity, dtype=jnp.bool_)
    byte_cap = col.byte_capacity
    for j, ch in enumerate(needle):
        p = jnp.clip(starts + j, 0, byte_cap - 1)
        ok = ok & (col.data[p] == jnp.uint8(ch))
    return ok


def str_starts_with(col: StringColumn, prefix: bytes) -> Column:
    from ..types import BOOLEAN
    lens = string_lengths(col)
    ok = (lens >= len(prefix)) & _match_at(col, prefix, col.offsets[:-1])
    return Column(ok, col.validity, BOOLEAN)


def str_ends_with(col: StringColumn, suffix: bytes) -> Column:
    from ..types import BOOLEAN
    lens = string_lengths(col)
    ok = (lens >= len(suffix)) & _match_at(col, suffix,
                                           col.offsets[1:] - len(suffix))
    return Column(ok, col.validity, BOOLEAN)


def str_contains(col: StringColumn, needle: bytes) -> Column:
    """Substring search: needle-length sliding window over the byte buffer,
    segmented to row boundaries. O(bytes * |needle|) VPU work."""
    from ..types import BOOLEAN
    if not needle:
        return Column(jnp.ones(col.capacity, jnp.bool_), col.validity, BOOLEAN)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    hit = jnp.ones(byte_cap, dtype=jnp.bool_)
    for j, ch in enumerate(needle):
        p = jnp.clip(pos + j, 0, byte_cap - 1)
        hit = hit & (col.data[p] == jnp.uint8(ch))
    # a hit at byte p belongs to row r if p..p+len-1 inside row r's span
    row = jnp.searchsorted(col.offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, col.capacity - 1)
    inside = (pos + len(needle)) <= col.offsets[row + 1]
    hit = hit & inside
    # segment-max hit per row
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(hit.astype(jnp.int32))])
    per_row = (csum[jnp.minimum(col.offsets[1:], byte_cap)] -
               csum[jnp.minimum(col.offsets[:-1], byte_cap)]) > 0
    return Column(per_row, col.validity, BOOLEAN)


def _row_of_byte(col: StringColumn, pos):
    """Row owning each byte position of `col`'s buffer."""
    row = jnp.searchsorted(col.offsets, pos, side="right").astype(jnp.int32) - 1
    return jnp.clip(row, 0, col.capacity - 1)


def str_trim(col: StringColumn, side: str = "both",
             trim_chars: bytes = b" \t\n\r\x0b\x0c") -> StringColumn:
    """trim/ltrim/rtrim (reference GpuStringTrim, stringFunctions.scala).
    Default trim set matches Spark's whitespace trimming."""
    assert side in ("both", "left", "right")
    lens = string_lengths(col)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    intra = pos - col.offsets[row]
    in_use = pos < col.offsets[-1]
    is_trim = jnp.zeros(byte_cap, jnp.bool_)
    for ch in trim_chars:
        is_trim = is_trim | (col.data == jnp.uint8(ch))
    non_trim = in_use & ~is_trim
    big = jnp.int32(1 << 30)
    first_non = jax.ops.segment_min(jnp.where(non_trim, intra, big), row,
                                    num_segments=col.capacity)
    last_non = jax.ops.segment_max(jnp.where(non_trim, intra, -1), row,
                                   num_segments=col.capacity)
    lead = jnp.minimum(first_non, lens)
    # segment_max identity is INT_MIN for byte-less rows; clamp to "all
    # trimmed" (end 0) before arithmetic
    end = jnp.clip(last_non + 1, 0, lens)
    if side == "left":
        start, new_len = lead, lens - lead
    elif side == "right":
        start, new_len = jnp.zeros_like(lens), end
    else:
        start, new_len = lead, jnp.maximum(end - lead, 0)
    return _substring_gather(col, col.offsets[:-1] + start, new_len)


def str_pad(col: StringColumn, target: int, pad: bytes,
            side: str) -> StringColumn:
    """lpad/rpad, byte semantics (reference GpuStringLPad/RPad). Rows
    longer than `target` truncate to it; empty pad keeps short rows."""
    from ..columnar.column import bucket_capacity
    assert side in ("left", "right")
    target = max(target, 0)
    lens = string_lengths(col)
    if pad:
        out_lens = jnp.where(col.validity, jnp.int32(target), 0)
    else:
        out_lens = jnp.minimum(lens, target)
    out_lens = jnp.where(col.validity, out_lens, 0)
    new_offsets = _rebuild_offsets(out_lens)
    byte_cap = bucket_capacity(max(col.capacity * max(target, 1), 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, col.capacity - 1)
    intra = pos - new_offsets[row]
    in_use = pos < new_offsets[-1]
    rl = lens[row]
    pad_arr = jnp.asarray(bytearray(pad or b"\0"), jnp.uint8)
    lp = max(len(pad), 1)
    if side == "left":
        pad_n = jnp.maximum(jnp.int32(target) - rl, 0) if pad \
            else jnp.zeros_like(rl)
        from_pad = intra < pad_n
        src_intra = intra - pad_n
        pad_idx = intra % lp
    else:
        from_pad = (intra >= rl) if pad else jnp.zeros_like(intra, jnp.bool_)
        src_intra = intra
        pad_idx = (intra - rl) % lp
    pad_byte = pad_arr[jnp.where(from_pad, pad_idx, 0)]
    src_pos = jnp.clip(col.offsets[row] + jnp.maximum(src_intra, 0), 0,
                       col.byte_capacity - 1)
    data = jnp.where(in_use, jnp.where(from_pad, pad_byte,
                                       col.data[src_pos]), jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def str_repeat(col: StringColumn, n: int) -> StringColumn:
    """repeat(str, n) (reference GpuStringRepeat)."""
    from ..columnar.column import bucket_capacity
    n = max(int(n), 0)
    lens = string_lengths(col)
    out_lens = lens * n
    new_offsets = _rebuild_offsets(out_lens)
    byte_cap = bucket_capacity(max(col.byte_capacity * max(n, 1), 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, col.capacity - 1)
    intra = pos - new_offsets[row]
    in_use = pos < new_offsets[-1]
    rl = jnp.maximum(lens[row], 1)
    src = jnp.clip(col.offsets[row] + intra % rl, 0, col.byte_capacity - 1)
    data = jnp.where(in_use, col.data[src], jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def str_reverse(col: StringColumn) -> StringColumn:
    """reverse(str), byte order (exact for ASCII; multi-byte UTF-8 code
    points are byte-reversed — documented divergence, like the reference's
    early string kernels)."""
    lens = string_lengths(col)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    intra = pos - col.offsets[row]
    in_use = pos < col.offsets[-1]
    src = jnp.clip(col.offsets[row] + lens[row] - 1 - intra, 0, byte_cap - 1)
    data = jnp.where(in_use, col.data[src], jnp.uint8(0))
    return StringColumn(data, col.offsets, col.validity, col.dtype)


def str_initcap(col: StringColumn) -> StringColumn:
    """initcap: first letter of each whitespace-delimited word uppercase,
    rest lowercase (Spark semantics, ASCII letters)."""
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    at_start = pos == col.offsets[row]
    prev = col.data[jnp.clip(pos - 1, 0, byte_cap - 1)]
    prev_is_space = (prev == ord(" ")) | (prev == ord("\t")) | \
        (prev == ord("\n")) | (prev == ord("\r"))
    word_start = at_start | prev_is_space
    b = col.data
    is_lower = (b >= ord("a")) & (b <= ord("z"))
    is_upper = (b >= ord("A")) & (b <= ord("Z"))
    up = jnp.where(is_lower, b - 32, b)
    low = jnp.where(is_upper, b + 32, b)
    data = jnp.where(word_start, up, low)
    return StringColumn(data, col.offsets, col.validity, col.dtype)


def str_locate(col: StringColumn, needle: bytes, start: int = 1) -> Column:
    """locate/instr/position: 1-based byte index of the first occurrence at
    or after `start` (1-based), 0 if absent (Java String.indexOf
    semantics, which Spark delegates to)."""
    from ..types import INT
    lens = string_lengths(col)
    start0 = max(int(start) - 1, 0)
    if not needle:
        # Java indexOf("", from) = min(max(from,0), len)
        res = jnp.minimum(jnp.int32(start0), lens) + 1
        return Column(res.astype(jnp.int32), col.validity, INT)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    hit = jnp.ones(byte_cap, dtype=jnp.bool_)
    for j, ch in enumerate(needle):
        p = jnp.clip(pos + j, 0, byte_cap - 1)
        hit = hit & (col.data[p] == jnp.uint8(ch))
    row = _row_of_byte(col, pos)
    intra = pos - col.offsets[row]
    inside = (pos + len(needle)) <= col.offsets[row + 1]
    ok = hit & inside & (intra >= start0) & (pos < col.offsets[-1])
    big = jnp.int32(1 << 30)
    first = jax.ops.segment_min(jnp.where(ok, intra, big), row,
                                num_segments=col.capacity)
    res = jnp.where(first >= big, 0, first + 1)
    return Column(res.astype(jnp.int32), col.validity, INT)


def _needle_has_border(needle: bytes) -> bool:
    return any(needle[:k] == needle[len(needle) - k:]
               for k in range(1, len(needle)))


def select_literal_hits(col: StringColumn, search: bytes):
    """Byte mask of the greedy non-overlapping left-to-right occurrences
    of literal `search` (Java String.split/replace hit set).

    Fast path: a needle with no proper border cannot overlap itself, so
    every raw hit is automatically part of the greedy non-overlapping set.
    Bordered needles (e.g. "aa") run a device while_loop that advances
    per-row cursors hit by hit — exact Java semantics, vectorized across
    rows."""
    ls = len(search)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    intra = pos - col.offsets[row]
    in_use = pos < col.offsets[-1]
    hit = jnp.ones(byte_cap, dtype=jnp.bool_)
    for j, ch in enumerate(search):
        p = jnp.clip(pos + j, 0, byte_cap - 1)
        hit = hit & (col.data[p] == jnp.uint8(ch))
    hit = hit & in_use & ((pos + ls) <= col.offsets[row + 1])

    if _needle_has_border(search):
        # greedy selection: per-row cursor jumps to the next hit >= cursor
        big = jnp.int32(1 << 30)

        def next_hit(cursor):
            cand = jnp.where(hit & (intra >= cursor[row]), intra, big)
            return jax.ops.segment_min(cand, row,
                                       num_segments=col.capacity)

        def body(carry):
            cursor, sel = carry
            nxt = next_hit(cursor)
            found = nxt < big
            # rows with no further hit scatter out of bounds (dropped) —
            # clipping would collide them onto real byte positions
            sel_pos = jnp.where(found, col.offsets[:-1] + nxt,
                                jnp.int32(byte_cap))
            sel = sel.at[sel_pos].set(True, mode="drop")
            cursor = jnp.where(found, nxt + ls, big)
            return cursor, sel

        def cond(carry):
            cursor, _ = carry
            return jnp.any(cursor < big)

        cursor0 = jnp.zeros(col.capacity, jnp.int32)
        sel0 = jnp.zeros(byte_cap, jnp.bool_)
        _, selected = jax.lax.while_loop(cond, body, (cursor0, sel0))
        return selected & hit
    return hit


def str_replace(col: StringColumn, search: bytes,
                replacement: bytes) -> StringColumn:
    """replace(str, search, replace): non-overlapping left-to-right literal
    replacement (reference GpuStringReplace)."""
    from ..columnar.column import bucket_capacity
    if not search:
        return col
    ls, lr = len(search), len(replacement)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    in_use = pos < col.offsets[-1]
    selected = select_literal_hits(col, search)

    # emit lengths: 1 per plain byte, lr at a match start, 0 inside a match
    sel_csum = jnp.cumsum(selected.astype(jnp.int32))
    lo = jnp.clip(pos - ls, 0, byte_cap - 1)
    covered_cnt = jnp.where(pos >= 1, sel_csum[jnp.clip(pos - 1, 0, byte_cap - 1)], 0) \
        - jnp.where(pos >= ls, sel_csum[lo], 0)
    covered = (covered_cnt > 0) & ~selected
    emit = jnp.where(in_use, 1, 0)
    emit = jnp.where(selected, lr, emit)
    emit = jnp.where(covered, 0, emit)

    out_lens = jax.ops.segment_sum(emit, row, num_segments=col.capacity)
    out_lens = jnp.where(col.validity, out_lens, 0)
    new_offsets = _rebuild_offsets(out_lens)
    emit_start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(emit, dtype=jnp.int32)])
    out_byte_cap = byte_cap if lr <= ls else \
        bucket_capacity(max((byte_cap // ls + 1) * lr, byte_cap))
    opos = jnp.arange(out_byte_cap, dtype=jnp.int32)
    src = jnp.clip(jnp.searchsorted(emit_start, opos, side="right")
                   .astype(jnp.int32) - 1, 0, byte_cap - 1)
    k = opos - emit_start[src]
    out_in_use = opos < new_offsets[-1]
    repl_arr = jnp.asarray(bytearray(replacement or b"\0"), jnp.uint8)
    from_repl = selected[src]
    byte = jnp.where(from_repl, repl_arr[jnp.clip(k, 0, max(lr - 1, 0))],
                     col.data[src])
    data = jnp.where(out_in_use, byte, jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def str_concat_pair(a: StringColumn, b: StringColumn) -> StringColumn:
    """concat(a, b): null-intolerant pairwise concatenation."""
    from ..columnar.column import bucket_capacity
    la, lb = string_lengths(a), string_lengths(b)
    valid = a.validity & b.validity
    out_lens = jnp.where(valid, la + lb, 0)
    new_offsets = _rebuild_offsets(out_lens)
    byte_cap = bucket_capacity(max(a.byte_capacity + b.byte_capacity, 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, a.capacity - 1)
    intra = pos - new_offsets[row]
    in_use = pos < new_offsets[-1]
    from_a = intra < la[row]
    pa = jnp.clip(a.offsets[row] + intra, 0, a.byte_capacity - 1)
    pb = jnp.clip(b.offsets[row] + intra - la[row], 0, b.byte_capacity - 1)
    data = jnp.where(in_use, jnp.where(from_a, a.data[pa], b.data[pb]),
                     jnp.uint8(0))
    return StringColumn(data, new_offsets, valid, a.dtype)


def str_concat_ws(sep: bytes, cols) -> StringColumn:
    """concat_ws(sep, c1..ck): skips NULL children entirely; separator only
    between present children; never null (Spark semantics)."""
    from ..columnar.column import bucket_capacity
    k = len(cols)
    cap = cols[0].capacity
    lsep = len(sep)
    lens = [jnp.where(c.validity, string_lengths(c), 0) for c in cols]
    present = [c.validity for c in cols]
    # segment table per row: [c0, sep, c1, sep, c2, ...] (2k-1 segments)
    seg_lens = [lens[0] * present[0]]
    any_before = present[0]
    for i in range(1, k):
        seg_lens.append(jnp.where(any_before & present[i],
                                  jnp.int32(lsep), 0))
        seg_lens.append(jnp.where(present[i], lens[i], 0))
        any_before = any_before | present[i]
    seg = jnp.stack(seg_lens, axis=1)  # (cap, 2k-1)
    seg_ends = jnp.cumsum(seg, axis=1)
    out_lens = seg_ends[:, -1]
    new_offsets = _rebuild_offsets(out_lens)
    total_in = sum(c.byte_capacity for c in cols) + cap * lsep * (k - 1)
    byte_cap = bucket_capacity(max(total_in, 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, cap - 1)
    intra = pos - new_offsets[row]
    in_use = pos < new_offsets[-1]
    # segment index: count of segment ends <= intra
    seg_idx = jnp.sum(intra[:, None] >= seg_ends[row], axis=1)
    seg_idx = jnp.clip(seg_idx, 0, 2 * k - 2)
    seg_start = seg_ends[row, seg_idx] - seg[row, seg_idx]
    local = intra - seg_start
    sep_arr = jnp.asarray(bytearray(sep or b"\0"), jnp.uint8)
    byte = sep_arr[jnp.clip(local, 0, max(lsep - 1, 0))]
    for i, c in enumerate(cols):
        pi = jnp.clip(c.offsets[row] + local, 0, c.byte_capacity - 1)
        byte = jnp.where(seg_idx == 2 * i, c.data[pi], byte)
    data = jnp.where(in_use, byte, jnp.uint8(0))
    valid = jnp.ones(cap, jnp.bool_)
    return StringColumn(data, new_offsets, valid, cols[0].dtype)


def str_translate(col: StringColumn, from_str: bytes,
                  to_str: bytes) -> StringColumn:
    """translate(str, from, to): per-byte mapping; positions of `from`
    beyond len(to) delete the byte (ASCII semantics; first occurrence in
    `from` wins, like Java)."""
    import numpy as np
    lut = np.arange(256, dtype=np.uint8)
    keep = np.ones(256, dtype=bool)
    seen = set()
    for i, ch in enumerate(from_str):
        if ch in seen:
            continue
        seen.add(ch)
        if i < len(to_str):
            lut[ch] = to_str[i]
        else:
            keep[ch] = False
    lut_d = jnp.asarray(lut)
    keep_d = jnp.asarray(keep)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    in_use = pos < col.offsets[-1]
    emit = jnp.where(in_use & keep_d[col.data], 1, 0)
    out_lens = jax.ops.segment_sum(emit, row, num_segments=col.capacity)
    out_lens = jnp.where(col.validity, out_lens, 0)
    new_offsets = _rebuild_offsets(out_lens)
    emit_start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(emit, dtype=jnp.int32)])
    opos = jnp.arange(byte_cap, dtype=jnp.int32)
    src = jnp.clip(jnp.searchsorted(emit_start, opos, side="right")
                   .astype(jnp.int32) - 1, 0, byte_cap - 1)
    out_in_use = opos < new_offsets[-1]
    data = jnp.where(out_in_use, lut_d[col.data[src]], jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def str_ascii(col: StringColumn) -> Column:
    """ascii(str): code of the first byte, 0 for empty (Spark: first
    character's codepoint; exact for ASCII)."""
    from ..types import INT
    lens = string_lengths(col)
    first = col.data[jnp.clip(col.offsets[:-1], 0, col.byte_capacity - 1)]
    res = jnp.where(lens > 0, first.astype(jnp.int32), 0)
    return Column(res, col.validity, INT)


def str_chr(codes: Column) -> StringColumn:
    """chr(n): 1-byte string from code n % 256; empty for n <= 0
    (Spark/Java Chr semantics for the ASCII range)."""
    from ..columnar.column import bucket_capacity
    from ..types import StringType
    cap = codes.capacity
    n = codes.data.astype(jnp.int64)
    code = (n % 256).astype(jnp.int32)
    out_lens = jnp.where(codes.validity & (n > 0) & (code > 0), 1, 0)
    out_lens = out_lens.astype(jnp.int32)
    new_offsets = _rebuild_offsets(out_lens)
    byte_cap = bucket_capacity(max(cap, 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = pos < new_offsets[-1]
    data = jnp.where(in_use, code[row].astype(jnp.uint8), jnp.uint8(0))
    return StringColumn(data, new_offsets, codes.validity, StringType())


def string_compare_cols(a: StringColumn, b: StringColumn):
    """Row-wise lexicographic byte compare -> int32 sign (-1/0/1).

    Sequential fold per row expressed as a device while_loop over byte
    positions, vectorized across rows; trip count is the max common prefix
    length in the batch (device scalar — no recompile).
    """
    la = string_lengths(a)
    lb = string_lengths(b)
    min_len = jnp.minimum(la, lb)
    max_t = jnp.max(min_len)
    sa, sb = a.offsets[:-1], b.offsets[:-1]

    def body(carry):
        t, res = carry
        pa = jnp.clip(sa + t, 0, a.byte_capacity - 1)
        pb = jnp.clip(sb + t, 0, b.byte_capacity - 1)
        ba = a.data[pa].astype(jnp.int32)
        bb = b.data[pb].astype(jnp.int32)
        active = (res == 0) & (t < min_len)
        diff = jnp.sign(ba - bb)
        return t + 1, jnp.where(active, diff, res)

    res0 = jnp.zeros(a.capacity, jnp.int32)
    _, res = jax.lax.while_loop(lambda c: c[0] < max_t, body,
                                (jnp.int32(0), res0))
    return jnp.where(res == 0, jnp.sign(la - lb), res)


def string_equal(a: StringColumn, b: StringColumn) -> Column:
    """Row-wise string equality via length check + prefix-sum byte compare."""
    from ..types import BOOLEAN
    la = string_lengths(a)
    lb = string_lengths(b)
    same_len = la == lb
    # compare bytes positionally: for each byte of a's row, compare with b's
    pos = jnp.arange(a.byte_capacity, dtype=jnp.int32)
    row = jnp.searchsorted(a.offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, a.capacity - 1)
    intra = pos - a.offsets[row]
    b_pos = jnp.clip(b.offsets[row] + intra, 0, b.byte_capacity - 1)
    in_use = pos < a.offsets[-1]
    neq = in_use & (a.data != b.data[jnp.where(in_use, b_pos, 0)])
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(neq.astype(jnp.int32))])
    any_neq = (csum[jnp.minimum(a.offsets[1:], a.byte_capacity)] -
               csum[jnp.minimum(a.offsets[:-1], a.byte_capacity)]) > 0
    eq = same_len & ~any_neq
    return Column(eq, a.validity & b.validity, BOOLEAN)


def string_to_padded(col: StringColumn, width: int):
    """(lengths (cap,), bytes (cap, width)): fixed-width row-major encoding
    for collective exchange (ICI all-to-all needs rectangular tensors; this
    is the TPU analog of JCudfSerialization's framed host buffers).
    Truncates rows longer than `width` — callers size width from host-known
    max length."""
    cap = col.capacity
    lengths = jnp.minimum(string_lengths(col), width)
    starts = col.offsets[:cap]
    j = jnp.arange(width, dtype=jnp.int32)
    pos = starts[:, None] + j[None, :]
    in_str = j[None, :] < lengths[:, None]
    safe = jnp.where(in_str, jnp.clip(pos, 0, col.byte_capacity - 1), 0)
    padded = jnp.where(in_str, col.data[safe], jnp.uint8(0))
    return lengths, padded


def string_from_padded(lengths, padded, validity,
                       dtype=None) -> StringColumn:
    """Inverse of string_to_padded: rebuild (offsets, bytes) columns.

    Byte capacity is the static worst case cap*width (callers keep width
    small); unused tail stays zero.
    """
    from ..columnar.column import bucket_capacity
    from ..types import StringType
    cap, width = padded.shape
    lengths = jnp.where(validity, lengths, 0)
    offsets = _rebuild_offsets(lengths)
    byte_cap = bucket_capacity(max(cap * width, 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = pos - offsets[row]
    in_use = pos < offsets[-1]
    safe_intra = jnp.clip(intra, 0, width - 1)
    data = jnp.where(in_use, padded[row, safe_intra], jnp.uint8(0))
    return StringColumn(data, offsets, validity, dtype or StringType())
