"""Varlen (string/binary) kernels over the (offsets, bytes) twin-array layout.

Replaces cuDF's strings column primitives (reference L6). XLA has no ragged
tensors, so every kernel is expressed as dense gathers over the padded byte
buffer. The workhorse is `row_of_byte`: for each output byte position, find
which row it belongs to via searchsorted on the output offsets — this turns
any row-gather of strings into two vectorized gathers (O(B log N) with B =
byte capacity), fully static shapes, MXU-free pure VPU work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn


def string_lengths(col: StringColumn):
    """int32 (capacity,): byte length per row (0 for null/inactive rows)."""
    return col.offsets[1:] - col.offsets[:-1]


def _rebuild_offsets(lengths):
    """Exclusive-scan lengths into (capacity+1,) offsets."""
    return jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(lengths, dtype=jnp.int32),
    ])


def gather_string(col: StringColumn, indices, out_valid,
                  out_byte_capacity: int | None = None) -> StringColumn:
    """Gather rows of a string column by pre-clamped int32 `indices`.

    out_byte_capacity: static byte bucket of the result. Defaults to the
    input's byte bucket (sufficient for any permutation/filter; joins that
    duplicate long rows must pass a larger bucket).
    """
    byte_cap = out_byte_capacity or col.byte_capacity
    lengths = string_lengths(col)[indices]
    lengths = jnp.where(out_valid, lengths, 0)
    new_offsets = _rebuild_offsets(lengths)
    src_starts = col.offsets[indices]

    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    # row owning each output byte: last row whose offset <= pos
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, indices.shape[0] - 1)
    intra = pos - new_offsets[row]
    src_pos = src_starts[row] + intra
    in_use = pos < new_offsets[-1]
    src_pos = jnp.where(in_use, jnp.clip(src_pos, 0, col.byte_capacity - 1), 0)
    data = jnp.where(in_use, col.data[src_pos], jnp.uint8(0))
    return StringColumn(data, new_offsets, out_valid, col.dtype)


def concat_string(a: StringColumn, b: StringColumn, a_rows, b_rows,
                  out_capacity: int,
                  out_byte_capacity: int | None = None) -> StringColumn:
    """Concatenate active rows of two string columns."""
    byte_cap = out_byte_capacity or (a.byte_capacity + b.byte_capacity)
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    from_b = idx >= a_rows
    total = a_rows + b_rows
    out_valid_slot = idx < total

    a_len = string_lengths(a)
    b_len = string_lengths(b)
    a_idx = jnp.where(idx < a.capacity, idx, 0)
    b_idx = jnp.clip(idx - a_rows, 0, b.capacity - 1)
    lengths = jnp.where(from_b, b_len[b_idx], a_len[a_idx])
    lengths = jnp.where(out_valid_slot, lengths, 0)
    validity = jnp.where(from_b, b.validity[b_idx], a.validity[a_idx]) & out_valid_slot
    new_offsets = _rebuild_offsets(lengths)
    src_starts = jnp.where(from_b, b.offsets[b_idx], a.offsets[a_idx])

    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, out_capacity - 1)
    intra = pos - new_offsets[row]
    src_pos = src_starts[row] + intra
    row_from_b = from_b[row]
    in_use = pos < new_offsets[-1]
    a_bytes = a.data[jnp.where(in_use & ~row_from_b,
                               jnp.clip(src_pos, 0, a.byte_capacity - 1), 0)]
    b_bytes = b.data[jnp.where(in_use & row_from_b,
                               jnp.clip(src_pos, 0, b.byte_capacity - 1), 0)]
    data = jnp.where(in_use, jnp.where(row_from_b, b_bytes, a_bytes), jnp.uint8(0))
    return StringColumn(data, new_offsets, validity, a.dtype)


# --- elementwise string functions ----------------------------------------

def str_length_bytes(col: StringColumn) -> Column:
    from ..types import INT
    return Column(string_lengths(col), col.validity, INT)


def str_length_chars(col: StringColumn) -> Column:
    """UTF-8 aware character count (Spark `length`): count non-continuation
    bytes ((b & 0xC0) != 0x80) per row via a segmented sum."""
    from ..types import INT
    cap = col.capacity
    is_start = ((col.data & 0xC0) != 0x80).astype(jnp.int32)
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(is_start, dtype=jnp.int32)])
    counts = csum[col.offsets[1:]] - csum[col.offsets[:-1]]
    return Column(counts, col.validity, INT)


def str_upper_ascii(col: StringColumn) -> StringColumn:
    lower = (col.data >= ord("a")) & (col.data <= ord("z"))
    data = jnp.where(lower, col.data - 32, col.data)
    return StringColumn(data, col.offsets, col.validity, col.dtype)


def str_lower_ascii(col: StringColumn) -> StringColumn:
    upper = (col.data >= ord("A")) & (col.data <= ord("Z"))
    data = jnp.where(upper, col.data + 32, col.data)
    return StringColumn(data, col.offsets, col.validity, col.dtype)


def substring(col: StringColumn, start: int, length: int | None) -> StringColumn:
    """Spark substring semantics: 1-based start, negative = from end."""
    lens = string_lengths(col)
    if start > 0:
        begin = jnp.minimum(jnp.int32(start - 1), lens)
    elif start == 0:
        begin = jnp.zeros_like(lens)
    else:
        begin = jnp.maximum(lens + start, 0)
    if length is None:
        sub_len = lens - begin
    else:
        sub_len = jnp.clip(jnp.int32(length), 0, lens - begin)
    starts = col.offsets[:-1] + begin
    return _substring_gather(col, starts, sub_len)


def _substring_gather(col: StringColumn, src_starts, lengths) -> StringColumn:
    lengths = jnp.where(col.validity, lengths, 0)
    new_offsets = _rebuild_offsets(lengths)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, col.capacity - 1)
    intra = pos - new_offsets[row]
    src_pos = src_starts[row] + intra
    in_use = pos < new_offsets[-1]
    src_pos = jnp.where(in_use, jnp.clip(src_pos, 0, byte_cap - 1), 0)
    data = jnp.where(in_use, col.data[src_pos], jnp.uint8(0))
    return StringColumn(data, new_offsets, col.validity, col.dtype)


def _match_at(col: StringColumn, needle: bytes, starts):
    """Bool per row: needle matches at byte position `starts` (absolute)."""
    ok = jnp.ones(col.capacity, dtype=jnp.bool_)
    byte_cap = col.byte_capacity
    for j, ch in enumerate(needle):
        p = jnp.clip(starts + j, 0, byte_cap - 1)
        ok = ok & (col.data[p] == jnp.uint8(ch))
    return ok


def str_starts_with(col: StringColumn, prefix: bytes) -> Column:
    from ..types import BOOLEAN
    lens = string_lengths(col)
    ok = (lens >= len(prefix)) & _match_at(col, prefix, col.offsets[:-1])
    return Column(ok, col.validity, BOOLEAN)


def str_ends_with(col: StringColumn, suffix: bytes) -> Column:
    from ..types import BOOLEAN
    lens = string_lengths(col)
    ok = (lens >= len(suffix)) & _match_at(col, suffix,
                                           col.offsets[1:] - len(suffix))
    return Column(ok, col.validity, BOOLEAN)


def str_contains(col: StringColumn, needle: bytes) -> Column:
    """Substring search: needle-length sliding window over the byte buffer,
    segmented to row boundaries. O(bytes * |needle|) VPU work."""
    from ..types import BOOLEAN
    if not needle:
        return Column(jnp.ones(col.capacity, jnp.bool_), col.validity, BOOLEAN)
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    hit = jnp.ones(byte_cap, dtype=jnp.bool_)
    for j, ch in enumerate(needle):
        p = jnp.clip(pos + j, 0, byte_cap - 1)
        hit = hit & (col.data[p] == jnp.uint8(ch))
    # a hit at byte p belongs to row r if p..p+len-1 inside row r's span
    row = jnp.searchsorted(col.offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, col.capacity - 1)
    inside = (pos + len(needle)) <= col.offsets[row + 1]
    hit = hit & inside
    # segment-max hit per row
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(hit.astype(jnp.int32))])
    per_row = (csum[jnp.minimum(col.offsets[1:], byte_cap)] -
               csum[jnp.minimum(col.offsets[:-1], byte_cap)]) > 0
    return Column(per_row, col.validity, BOOLEAN)


def string_compare_cols(a: StringColumn, b: StringColumn):
    """Row-wise lexicographic byte compare -> int32 sign (-1/0/1).

    Sequential fold per row expressed as a device while_loop over byte
    positions, vectorized across rows; trip count is the max common prefix
    length in the batch (device scalar — no recompile).
    """
    la = string_lengths(a)
    lb = string_lengths(b)
    min_len = jnp.minimum(la, lb)
    max_t = jnp.max(min_len)
    sa, sb = a.offsets[:-1], b.offsets[:-1]

    def body(carry):
        t, res = carry
        pa = jnp.clip(sa + t, 0, a.byte_capacity - 1)
        pb = jnp.clip(sb + t, 0, b.byte_capacity - 1)
        ba = a.data[pa].astype(jnp.int32)
        bb = b.data[pb].astype(jnp.int32)
        active = (res == 0) & (t < min_len)
        diff = jnp.sign(ba - bb)
        return t + 1, jnp.where(active, diff, res)

    res0 = jnp.zeros(a.capacity, jnp.int32)
    _, res = jax.lax.while_loop(lambda c: c[0] < max_t, body,
                                (jnp.int32(0), res0))
    return jnp.where(res == 0, jnp.sign(la - lb), res)


def string_equal(a: StringColumn, b: StringColumn) -> Column:
    """Row-wise string equality via length check + prefix-sum byte compare."""
    from ..types import BOOLEAN
    la = string_lengths(a)
    lb = string_lengths(b)
    same_len = la == lb
    # compare bytes positionally: for each byte of a's row, compare with b's
    pos = jnp.arange(a.byte_capacity, dtype=jnp.int32)
    row = jnp.searchsorted(a.offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, a.capacity - 1)
    intra = pos - a.offsets[row]
    b_pos = jnp.clip(b.offsets[row] + intra, 0, b.byte_capacity - 1)
    in_use = pos < a.offsets[-1]
    neq = in_use & (a.data != b.data[jnp.where(in_use, b_pos, 0)])
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(neq.astype(jnp.int32))])
    any_neq = (csum[jnp.minimum(a.offsets[1:], a.byte_capacity)] -
               csum[jnp.minimum(a.offsets[:-1], a.byte_capacity)]) > 0
    eq = same_len & ~any_neq
    return Column(eq, a.validity & b.validity, BOOLEAN)


def string_to_padded(col: StringColumn, width: int):
    """(lengths (cap,), bytes (cap, width)): fixed-width row-major encoding
    for collective exchange (ICI all-to-all needs rectangular tensors; this
    is the TPU analog of JCudfSerialization's framed host buffers).
    Truncates rows longer than `width` — callers size width from host-known
    max length."""
    cap = col.capacity
    lengths = jnp.minimum(string_lengths(col), width)
    starts = col.offsets[:cap]
    j = jnp.arange(width, dtype=jnp.int32)
    pos = starts[:, None] + j[None, :]
    in_str = j[None, :] < lengths[:, None]
    safe = jnp.where(in_str, jnp.clip(pos, 0, col.byte_capacity - 1), 0)
    padded = jnp.where(in_str, col.data[safe], jnp.uint8(0))
    return lengths, padded


def string_from_padded(lengths, padded, validity,
                       dtype=None) -> StringColumn:
    """Inverse of string_to_padded: rebuild (offsets, bytes) columns.

    Byte capacity is the static worst case cap*width (callers keep width
    small); unused tail stays zero.
    """
    from ..columnar.column import bucket_capacity
    from ..types import StringType
    cap, width = padded.shape
    lengths = jnp.where(validity, lengths, 0)
    offsets = _rebuild_offsets(lengths)
    byte_cap = bucket_capacity(max(cap * width, 1))
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = pos - offsets[row]
    in_use = pos < offsets[-1]
    safe_intra = jnp.clip(intra, 0, width - 1)
    data = jnp.where(in_use, padded[row, safe_intra], jnp.uint8(0))
    return StringColumn(data, offsets, validity, dtype or StringType())
