"""IEEE-754 bit pattern of float64 WITHOUT a 64-bit bitcast.

TPU's X64 legalization pass implements every 64-bit bitcast EXCEPT those
FROM f64 (f64 is emulated; its storage is not a raw u64 lane), so
`lax.bitcast_convert_type(x_f64, uint64)` is a compile-time error on the
real chip. Sort keys and Spark-parity hashing (murmur3/xxhash64 frame
the raw 8 bytes of a double) both need the exact bit pattern, so this
module reconstructs it arithmetically:

  1. range-normalize x by an exact power-of-two scale so it fits the
     f32 exponent range;
  2. split into three f32 limbs (each subtraction exact, 72 mantissa
     bits >= f64's 53: the decomposition is lossless);
  3. decode the limbs' u32 patterns (32-bit bitcasts are supported) into
     one <= 53-bit integer significand + base-2 exponent;
  4. re-assemble sign/exponent/mantissa including subnormals, +-0, inf
     and NaN (canonical quiet NaN, which is all Spark semantics need).

The reverse direction (u64 bits -> f64) IS supported natively and stays
a plain bitcast.

Precision contract: bit-exact on backends with native f64 (CPU/GPU —
asserted by tests). On TPU, f64 arithmetic itself is double-double
emulated (~48-bit precision), so the reconstructed pattern can differ
from the host pattern in the last few mantissa bits — the same
tolerance every f64 comparison/kernel on the chip already has. Sort
order keys remain consistent with the device's own value ordering;
Spark-parity hashing of DOUBLE columns is exact on CPU and best-effort
on TPU (documented divergence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalar: a module-level jnp call captures a tracer when first
# imported inside a jit trace (PR 2 class; contract trace-module-jnp)
_M52 = np.uint64((1 << 52) - 1)


def _decode_f32(b):
    """u32 pattern -> (signed) integer significand scaled by 2^-149 and
    its integer value: val = m * 2^(e) with e relative to 2^-149."""
    e = (b >> jnp.uint32(23)) & jnp.uint32(0xFF)
    m = (b & jnp.uint32(0x7FFFFF)).astype(jnp.uint64)
    is_norm = e > 0
    m = jnp.where(is_norm, m | jnp.uint64(1 << 23), m)
    # exponent of the integer m in units of 2^-149
    shift = jnp.where(is_norm, e.astype(jnp.int64) - 1, jnp.int64(0))
    return m, shift  # value = m * 2^(shift - 149)


def _floor_log2_u64(n):
    """floor(log2(n)) for n >= 1, as int64 (6-step binary search)."""
    t = jnp.zeros(n.shape, jnp.int64)
    cur = n
    for k in (32, 16, 8, 4, 2, 1):
        big = cur >= (jnp.uint64(1) << jnp.uint64(k))
        t = t + jnp.where(big, k, 0)
        cur = jnp.where(big, cur >> jnp.uint64(k), cur)
    return t


def f64_bits(x) -> jnp.ndarray:
    """uint64 IEEE-754 pattern of float64 `x` (NaNs canonicalized to
    0x7FF8...0, matching jnp.nan — Spark collapses NaNs anyway)."""
    if jax.default_backend() in ("cpu", "gpu", "cuda", "rocm"):
        # native f64: one bitcast, bit-exact (incl. subnormals)
        bits = jax.lax.bitcast_convert_type(x, jnp.uint64)
        nanbits = jnp.uint64(0x7FF8000000000000)
        return jnp.where(jnp.isnan(x), nanbits, bits)
    # XLA flushes f64 subnormals to zero in arithmetic (DAZ) on both the
    # CPU backend and the TPU's double-double emulation, so subnormal bit
    # patterns are unrecoverable through any computation — map them to
    # signed zero, matching how every other engine kernel sees them.
    zero = (x == 0.0) | (jnp.abs(x) < jnp.float64(2.0 ** -1022))
    is_nan = jnp.isnan(x)
    is_inf = jnp.isinf(x)
    # jnp.signbit bitcasts f64 internally (unsupported on TPU); the sign
    # of +-0.0 comes from the sign of 1/x instead
    signbit = jnp.where(x == 0.0, 1.0 / jnp.where(x == 0.0, x, 1.0) < 0,
                        x < 0)
    neg_zero = zero & signbit
    sign = signbit & ~is_nan

    a = jnp.abs(x)
    # exact power-of-two range normalization to ~1: the scale factor is
    # BUILT from integer exponent bits (u64 -> f64 bitcast IS supported),
    # applied in two exact multiplies so even 2^-1074 reaches f32 range.
    # log2 only needs +-1 accuracy — the limb split tolerates [2^-3, 2^3]
    def pow2(e):
        return jax.lax.bitcast_convert_type(
            ((e + jnp.int64(1023)).astype(jnp.uint64)) << jnp.uint64(52),
            jnp.float64)

    safe_a = jnp.where((a > 0) & ~is_inf & ~is_nan, a, 1.0)
    # jnp.log2 returns -inf for f64 subnormals: boost them into the
    # normal range first (exact power-of-two multiply)
    boost = safe_a < jnp.float64(2.0 ** -1000)
    a_log = jnp.where(boost, safe_a * jnp.float64(2.0 ** 64), safe_a)
    e_est = jnp.floor(jnp.log2(a_log)).astype(jnp.int64) \
        - jnp.where(boost, jnp.int64(64), 0)
    e1 = jnp.clip(-e_est, -1000, 1000)
    e2 = jnp.clip(-e_est - e1, -1000, 1000)
    y = (safe_a * pow2(e1)) * pow2(e2)
    k_adj = -(e1 + e2)
    y = jnp.where(is_inf | is_nan | zero, 0.0, y)

    h1 = y.astype(jnp.float32)
    r1 = y - h1.astype(jnp.float64)
    h2 = r1.astype(jnp.float32)
    r2 = r1 - h2.astype(jnp.float64)
    h3 = r2.astype(jnp.float32)

    def norm_limb(h):
        b = jax.lax.bitcast_convert_type(h, jnp.uint32)
        m, s = _decode_f32(b)
        # strip trailing zeros so every limb exponent reflects its true
        # lsb: the three limbs then span <= 53 significant bits and the
        # combined integer fits uint64
        nzm = m != 0
        lsb = m & (~m + jnp.uint64(1))
        tz = _floor_log2_u64(jnp.where(nzm, lsb, jnp.uint64(1)))
        m = m >> tz.astype(jnp.uint64)
        s = jnp.where(nzm, s + tz, jnp.int64(1 << 40))  # zero: ignore
        return m, s, (b >> jnp.uint32(31)) == 1

    m1, s1, _n1 = norm_limb(h1)
    m2, s2, neg2 = norm_limb(h2)
    m3, s3, neg3 = norm_limb(h3)

    base = jnp.minimum(jnp.minimum(s1, s2), s3)
    base = jnp.minimum(base, jnp.int64(1 << 40) - 1)

    def term(m, s):
        sh = jnp.clip(s - base, 0, 63).astype(jnp.uint64)
        return (m << sh).astype(jnp.int64)

    n = term(m1, s1) \
        + jnp.where(neg2, -term(m2, s2), term(m2, s2)) \
        + jnp.where(neg3, -term(m3, s3), term(m3, s3))
    n = n.astype(jnp.uint64)          # |y| = n * 2^(base - 149)
    k = base - 149 + k_adj            # |x| = n * 2^k

    nz = n != 0
    t = _floor_log2_u64(jnp.where(nz, n, jnp.uint64(1)))
    e_unb = k + t
    # normal: exponent field e_unb+1023, mantissa = n aligned to bit 52
    lsh = (jnp.int64(52) - t)
    norm_mant = jnp.where(
        lsh >= 0, n << jnp.where(lsh >= 0, lsh, 0).astype(jnp.uint64),
        n >> jnp.where(lsh < 0, -lsh, 0).astype(jnp.uint64)) & _M52
    is_sub = e_unb < -1022
    # subnormal: bits = n * 2^(k + 1074), always an exact integer < 2^52
    sub_sh = k + jnp.int64(1074)
    sub_mant = jnp.where(
        sub_sh >= 0, n << jnp.where(sub_sh >= 0, sub_sh,
                                    0).astype(jnp.uint64),
        n >> jnp.where(sub_sh < 0, -sub_sh, 0).astype(jnp.uint64))
    exp_field = jnp.where(is_sub, jnp.int64(0), e_unb + 1023)
    mant = jnp.where(is_sub, sub_mant, norm_mant)
    bits = (exp_field.astype(jnp.uint64) << jnp.uint64(52)) \
        | (mant & _M52)
    bits = jnp.where(nz, bits, jnp.uint64(0))
    bits = jnp.where(sign, bits | jnp.uint64(1 << 63), bits)
    bits = jnp.where(neg_zero, jnp.uint64(1 << 63), bits)
    bits = jnp.where(is_inf, jnp.uint64(0x7FF0000000000000)
                     | jnp.where(signbit, jnp.uint64(1 << 63),
                                 jnp.uint64(0)), bits)
    bits = jnp.where(is_nan, jnp.uint64(0x7FF8000000000000), bits)
    return bits


def f64_bits_signed(x) -> jnp.ndarray:
    """int64 view of f64_bits (what Spark's Murmur3 frames)."""
    return f64_bits(x).astype(jnp.int64)
