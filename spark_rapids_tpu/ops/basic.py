"""Core row-layout kernels: active masks, compaction, gather, concat, slice.

These replace cuDF's gather/copy_if/concatenate primitives (reference L6,
SURVEY §2.9) with static-shape XLA programs. The universal trick: row counts
live in a device scalar (`num_rows`) while array shapes stay at the capacity
bucket, so filters/joins don't recompile.

Conventions:
  * every kernel is shape-polymorphic only in the capacity bucket;
  * rows with index >= num_rows are "inactive": validity False, data zero;
  * kernels return (columns..., new_num_rows) and always re-normalize the
    inactive region so downstream kernels can rely on it.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import ArrayColumn, Column, StringColumn, StructColumn
from ..columnar.encoded import NULL_CODE, DictionaryColumn
from .strings import gather_string


def active_mask(num_rows, capacity: int):
    """Bool (capacity,): True for logical rows."""
    return jnp.arange(capacity, dtype=jnp.int32) < num_rows


def sanitize(col: Column, num_rows) -> Column:
    """Force the inactive tail to (zero, invalid) so padded slots never leak."""
    act = active_mask(num_rows, col.capacity)
    validity = col.validity & act
    if isinstance(col, DictionaryColumn):
        codes = jnp.where(act, col.codes, jnp.int32(NULL_CODE))
        return DictionaryColumn(codes, col.dict_data, col.dict_offsets,
                                validity, col.dtype)
    if isinstance(col, StringColumn):
        return StringColumn(col.data, col.offsets, validity, col.dtype)
    if isinstance(col, StructColumn):
        kids = tuple(sanitize(k, num_rows) for k in col.children)
        return type(col)(kids, validity, col.dtype)  # incl. Decimal128
    if isinstance(col, ArrayColumn):
        return ArrayColumn(col.child, col.offsets, validity, col.dtype)
    from ..columnar.column import MapColumn
    if isinstance(col, MapColumn):
        return MapColumn(col.keys, col.values, col.offsets, validity,
                         col.dtype)
    data = jnp.where(act, col.data, jnp.zeros((), col.data.dtype))
    return Column(data, validity, col.dtype)


def gather_column(col: Column, indices, out_valid=None,
                  out_byte_capacity: int | None = None) -> Column:
    """Gather rows by int32 indices (the JoinGatherer primitive,
    reference JoinGatherer.scala). indices shape defines output capacity.
    `out_valid` masks output rows (False -> null+inactive slot).
    Out-of-range indices produce invalid rows.
    """
    from .gather import record as _record_gather
    cap = col.capacity
    # structural accounting (ISSUE 8): one materializing per-column
    # gather; no-op unless a wired exec's GatherTracker is observing
    _record_gather(1, nbytes=int(indices.shape[0])
                   * (col.data.dtype.itemsize
                      if type(col) is Column else 4))
    in_range = (indices >= 0) & (indices < cap)
    safe = jnp.where(in_range, indices, 0)
    valid = col.validity[safe] & in_range
    if out_valid is not None:
        valid = valid & out_valid
    if isinstance(col, DictionaryColumn):
        # codes gather fixed-width-style; the dictionary payload rides
        # along untouched (the whole point of staying encoded)
        codes = jnp.where(valid, col.codes[safe], jnp.int32(NULL_CODE))
        return DictionaryColumn(codes, col.dict_data, col.dict_offsets,
                                valid, col.dtype)
    if isinstance(col, StringColumn):
        return gather_string(col, safe, valid, out_byte_capacity)
    if isinstance(col, StructColumn):
        kids = tuple(gather_column(k, indices, out_valid, out_byte_capacity)
                     for k in col.children)
        return type(col)(kids, valid, col.dtype)  # incl. Decimal128
    if isinstance(col, ArrayColumn):
        from .collection import gather_array
        return gather_array(col, safe, valid,
                            out_child_capacity=out_byte_capacity)
    from ..columnar.column import MapColumn
    if isinstance(col, MapColumn):
        from .collection import gather_array
        from .maps import map_keys, map_values
        # duplicating gathers pass (entries, key_bytes, value_bytes)
        if isinstance(out_byte_capacity, tuple):
            elems, kb, vb = out_byte_capacity
            kcap = (elems, kb) if kb is not None else elems
            vcap = (elems, vb) if vb is not None else elems
        else:
            kcap = vcap = out_byte_capacity
        gk = gather_array(map_keys(col), safe, valid,
                          out_child_capacity=kcap)
        gv = gather_array(map_values(col), safe, valid,
                          out_child_capacity=vcap)
        return MapColumn(gk.child, gv.child, gk.offsets, valid, col.dtype)
    data = jnp.where(valid, col.data[safe], jnp.zeros((), col.data.dtype))
    return Column(data, valid, col.dtype)


def compaction_order(keep, num_rows):
    """Stable permutation moving kept active rows to the front.

    Returns (perm, new_num_rows). This is the engine's copy_if.

    HAZARD: slots at positions >= new_num_rows hold the DROPPED rows'
    indices (it is a full permutation) — an unmasked gather silently
    resurrects dropped rows as plausible-looking data. Every caller MUST
    mask the tail (gather with an active_mask(new_num_rows) out_valid, or
    wrap tail indices to -1). Use masked_compaction_order for the
    fail-safe variant that pre-wraps tail slots to -1.
    """
    cap = keep.shape[0]
    act = active_mask(num_rows, cap)
    k = keep & act
    iota = jnp.arange(cap, dtype=jnp.int32)
    # stable sort on the drop flag: kept rows first in original order.
    # Measured ~2x the scatter formulation on v5e (round 4): lax.sort is
    # the chip's cheapest reordering primitive.
    _, perm = jax.lax.sort(((~k).astype(jnp.uint32), iota), num_keys=1,
                           is_stable=True)
    new_rows = jnp.sum(k, dtype=jnp.int32)
    return perm, new_rows


def masked_compaction_order(keep, num_rows):
    """Fail-safe compaction_order: tail slots (>= new_num_rows) are -1, so
    an unmasked gather yields invalid rows instead of resurrecting dropped
    ones."""
    perm, new_rows = compaction_order(keep, num_rows)
    out_valid = active_mask(new_rows, keep.shape[0])
    return jnp.where(out_valid, perm, -1), new_rows


def compact_columns(columns: Sequence[Column], keep, num_rows
                    ) -> Tuple[Tuple[Column, ...], jnp.ndarray]:
    """Filter: keep rows where `keep` is True (null predicate rows dropped
    by the caller having already AND-ed validity into keep).

    Fixed-width columns compact through ONE packed row gather (XLA's
    gather cost on v5e is per-row loop overhead, not bytes — see
    ops/rowpack), routed through the gather engine (ops/gather) so the
    measured Pallas tier and the structural numGathers accounting cover
    every compaction in the engine; varlen/nested columns keep the
    per-column path."""
    from .gather import gather_batch_columns
    perm, new_rows = compaction_order(keep, num_rows)
    cap = keep.shape[0]
    out_valid = active_mask(new_rows, cap)
    out = gather_batch_columns(columns, perm, out_valid=out_valid)
    return tuple(out), new_rows


def concat_columns(a: Column, b: Column, a_rows, b_rows, out_capacity: int
                   ) -> Column:
    """Concatenate two columns' active rows (the coalesce primitive).

    out_capacity must be >= a_rows+b_rows worst case (callers size it to the
    bucket of a.capacity+b.capacity).
    """
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    from_b = idx >= a_rows
    b_idx = idx - a_rows
    total = a_rows + b_rows
    out_valid = idx < total
    if isinstance(a, DictionaryColumn):
        # coalesce inputs are materialized at the operator boundary
        # (exec/base.py), so this only fires for two views of the SAME
        # dictionary (e.g. slices of one scan batch) — concat the code
        # lanes fixed-width-style. Distinct dictionaries cannot be
        # merged shape-stably here; crash loudly rather than misread.
        assert isinstance(b, DictionaryColumn) \
            and a.dict_data is b.dict_data \
            and a.dict_offsets is b.dict_offsets, \
            "concat of distinct dictionaries — materialize first"
        codes = _concat_fixed(a.codes, b.codes, from_b, b_idx, idx)
        codes = jnp.where(out_valid, codes, jnp.int32(NULL_CODE))
        valid = _concat_fixed(a.validity, b.validity, from_b, b_idx, idx) \
            & out_valid
        return DictionaryColumn(codes, a.dict_data, a.dict_offsets,
                                valid, a.dtype)
    if isinstance(a, StringColumn):
        from .strings import concat_string
        return concat_string(a, b, a_rows, b_rows, out_capacity)
    if isinstance(a, StructColumn):
        kids = tuple(concat_columns(ka, kb, a_rows, b_rows, out_capacity)
                     for ka, kb in zip(a.children, b.children))
        valid = _concat_fixed(a.validity, b.validity, from_b, b_idx, idx) & out_valid
        return type(a)(kids, valid, a.dtype)  # incl. Decimal128
    if isinstance(a, ArrayColumn):
        # gather both sides' rows into the output slot order; gather_array
        # rebuilds offsets and compacts the child elements
        from .collection import concat_arrays
        return concat_arrays(a, b, a_rows, b_rows, out_capacity)
    data = _concat_fixed(a.data, b.data, from_b, b_idx, idx)
    valid = _concat_fixed(a.validity, b.validity, from_b, b_idx, idx) & out_valid
    data = jnp.where(out_valid, data, jnp.zeros((), data.dtype))
    return Column(data, valid, a.dtype)


def _concat_fixed(a, b, from_b, b_idx, idx):
    a_safe = jnp.where(idx < a.shape[0], idx, 0)
    b_safe = jnp.clip(b_idx, 0, b.shape[0] - 1)
    return jnp.where(from_b, b[b_safe], a[a_safe])


def slice_rows(col: Column, start, length, out_capacity: int) -> Column:
    """Rows [start, start+length) moved to the front of a fresh column."""
    idx = jnp.arange(out_capacity, dtype=jnp.int32) + start
    out_valid = jnp.arange(out_capacity, dtype=jnp.int32) < length
    return gather_column(col, idx, out_valid)
