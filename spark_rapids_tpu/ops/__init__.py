"""Columnar kernel substrate — the engine's replacement for libcudf (L6)."""

from .basic import (
    active_mask, compact_columns, compaction_order, concat_columns,
    gather_column, masked_compaction_order, sanitize, slice_rows,
)
from .hashing import murmur3_batch, pmod, xxhash64_batch
