"""Group-by aggregation kernels — device core of GpuHashAggregateExec
(reference GpuAggregateExec.scala:1711 over cuDF groupby).

TPU-first: no device hash table. XLA's native sort is fast and static-shaped,
so group-by is sort-based end to end: order-key lanes (ops/sort.py) -> stable
sort -> segment boundaries -> `jax.ops.segment_*` reductions. This is the
same shape the reference falls back to when hash-merge can't fit
(buildSortFallbackIterator, GpuAggregateExec.scala:909) — on TPU it is the
primary path because segment reductions vectorize perfectly and never
collide. num_groups rides as a device scalar; the output keeps the input
capacity bucket (num_groups <= num_rows), so merge passes re-run the SAME
compiled kernel.

Null semantics follow Spark: nulls are excluded from sum/min/max/avg/count
(sum of an all-null group is null); count(*) counts rows; GROUP BY treats
nulls as equal.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import DataType, DoubleType, LongType
from .basic import active_mask, gather_column, sanitize
from .sort import (
    SortOrder, group_segment_ids, sort_permutation, string_words_for,
)

#: aggregate op names understood by the kernel. first/last skip nulls
#: (ignoreNulls=True); first_any/last_any take the first/last row
#: regardless of null (Spark's default ignoreNulls=False). collect/
#: collect_set build list results (collect_merge flattens partials).
AGG_OPS = ("sum", "count", "count_star", "min", "max", "first", "last",
           "first_any", "last_any", "any_value", "sum_sq", "collect",
           "collect_set", "collect_merge")


def collect_all(op: str, col: Column, num_rows, capacity: int) -> "Column":
    """Grand-aggregate (no group keys) collect_list/collect_set: ONE row
    holding every valid value (deduped for sets)."""
    from ..columnar.column import ArrayColumn
    from ..types import ArrayType
    from .basic import compaction_order
    from .strings import _rebuild_offsets

    act = active_mask(num_rows, capacity)
    if op.startswith("psketch"):
        # grand approx_percentile: one segment covering every active row
        seg = jnp.where(act, 0, jnp.int32(capacity))
        positions = jnp.arange(capacity, dtype=jnp.int32)
        group_act = jnp.zeros(capacity, jnp.bool_).at[0].set(True)
        return _collect_group(op, col, seg, act, capacity, positions,
                              group_act)
    if op == "collect_merge":
        assert isinstance(col, ArrayColumn)
        from .collection import array_lengths
        lens = jnp.where(act & col.validity, array_lengths(col), 0)
        total = jnp.sum(lens)
        counts = jnp.zeros(capacity, jnp.int32).at[0].set(
            total.astype(jnp.int32))
        offsets = _rebuild_offsets(counts)
        valid = jnp.zeros(capacity, jnp.bool_).at[0].set(True)
        return ArrayColumn(col.child, offsets, valid, col.dtype)
    keep = act & col.validity
    if op == "collect_set":
        keep = keep & _first_occurrence(
            col, jnp.where(keep, 0, 1).astype(jnp.int32), keep, capacity)
    total = jnp.sum(keep.astype(jnp.int32))
    counts = jnp.zeros(capacity, jnp.int32).at[0].set(
            total.astype(jnp.int32))
    offsets = _rebuild_offsets(counts)
    perm, n_kept = compaction_order(keep, jnp.int32(capacity))
    child = gather_column(col, perm, active_mask(n_kept, capacity))
    valid = jnp.zeros(capacity, jnp.bool_).at[0].set(True)
    return ArrayColumn(child, offsets, valid, ArrayType(col.dtype))


def _dedup_value_lanes(col: Column):
    """Fixed-width dedup sort lanes with Spark equality semantics: -0.0
    equals 0.0 and NaN equals NaN. Floats go through the arithmetic bit
    reconstruction — bitcasts FROM f64 do not compile on TPU."""
    data = col.data
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    if jnp.issubdtype(data.dtype, jnp.floating):
        from .f64bits import f64_bits
        d = data.astype(jnp.float64)
        d = jnp.where(d == 0.0, 0.0, d)           # -0.0 -> 0.0
        d = jnp.where(jnp.isnan(d), jnp.float64(jnp.nan), d)  # one NaN
        return [f64_bits(d)]
    return [data]


def _first_occurrence(col: Column, group_key, keep, capacity: int):
    """Mask of the first kept row of each (group_key, value) pair —
    the dedup primitive behind collect_set. The dropped-row sentinel is
    far above any group id (group ids may exceed `capacity` when the
    group domain is the parent batch of a child buffer)."""
    from .sort import _split_u64_lanes
    lanes = _split_u64_lanes(_dedup_value_lanes(col))
    iota = jnp.arange(capacity, dtype=jnp.int32)
    big = jnp.int32(1 << 30)
    gk = jnp.where(keep, group_key, big).astype(jnp.int32)
    sorted_out = jax.lax.sort(tuple([gk] + lanes + [iota]),
                              num_keys=1 + len(lanes))
    sgk, sperm = sorted_out[0], sorted_out[-1]
    slanes = sorted_out[1:-1]
    diff = sgk[1:] != sgk[:-1]
    for sl in slanes:
        diff = diff | (sl[1:] != sl[:-1])
    first = jnp.concatenate([jnp.ones(1, jnp.bool_), diff])
    return jnp.zeros(capacity, jnp.bool_).at[sperm].set(
        first & (sgk < big))


@dataclass(frozen=True)
class AggSpec:
    """One physical aggregate: op over an input ordinal (-1 for count_star)."""
    op: str
    ordinal: int = -1

    def __post_init__(self):
        assert self.op in AGG_OPS, self.op


def _segment_reduce(op: str, values, validity, seg, capacity: int, positions):
    """One aggregate over presorted segments. Returns (data, validity)."""
    num_segments = capacity
    valid_i = validity.astype(jnp.int32)
    counts = jax.ops.segment_sum(valid_i, seg, num_segments=num_segments)
    has_any = counts > 0
    if op == "count":
        return counts.astype(jnp.int64), jnp.ones((capacity,), jnp.bool_)
    if op == "count_star":
        ones = jnp.ones_like(seg, jnp.int32)
        c = jax.ops.segment_sum(ones, seg, num_segments=num_segments)
        return c.astype(jnp.int64), jnp.ones((capacity,), jnp.bool_)
    if op in ("sum", "sum_sq"):
        v = values.astype(jnp.float64) if jnp.issubdtype(values.dtype, jnp.floating) \
            else values.astype(jnp.int64)
        if op == "sum_sq":
            v = v * v
        v = jnp.where(validity, v, jnp.zeros((), v.dtype))
        s = jax.ops.segment_sum(v, seg, num_segments=num_segments)
        return s, has_any
    if op in ("min", "max"):
        fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        if jnp.issubdtype(values.dtype, jnp.floating):
            sub = jnp.inf if op == "min" else -jnp.inf
            neutral = jnp.full((), sub, values.dtype)
        elif values.dtype == jnp.bool_:
            values = values.astype(jnp.int8)
            neutral = jnp.int8(1 if op == "min" else 0)
        else:
            info = jnp.iinfo(values.dtype)
            neutral = jnp.full((), info.max if op == "min" else info.min,
                               values.dtype)
        v = jnp.where(validity, values, neutral)
        r = fn(v, seg, num_segments=num_segments)
        return r, has_any
    if op in ("first", "last", "any_value"):
        # ignoreNulls=True: value at the smallest (first) / largest (last)
        # position holding a VALID row
        big = jnp.int32(capacity)
        if op == "last":
            p = jnp.where(validity, positions, -1)
            pick = jax.ops.segment_max(p, seg, num_segments=num_segments)
        else:
            p = jnp.where(validity, positions, big)
            pick = jax.ops.segment_min(p, seg, num_segments=num_segments)
        ok = (pick >= 0) & (pick < capacity)
        safe = jnp.clip(pick, 0, capacity - 1)
        return values[safe], ok & has_any
    if op in ("first_any", "last_any"):
        # ignoreNulls=False (Spark default): first/last row regardless of
        # null; the result is null when that row's value is null
        if op == "last_any":
            pick = jax.ops.segment_max(positions, seg,
                                       num_segments=num_segments)
        else:
            pick = jax.ops.segment_min(positions, seg,
                                       num_segments=num_segments)
        ok = (pick >= 0) & (pick < capacity)
        safe = jnp.clip(pick, 0, capacity - 1)
        return values[safe], ok & validity[safe]
    raise AssertionError(op)


def _collect_group(op: str, g: Column, seg, act, capacity: int, positions,
                   group_act) -> Column:
    """collect_list/collect_set update + merge over key-sorted rows
    (reference GpuCollectList/GpuCollectSet, aggregate functions over
    cuDF lists; here the sorted layout makes the list column literally
    the compacted values with group-boundary offsets).

    'collect': values of each group in row order, nulls dropped.
    'collect_set': additionally dedup within the group (element order
    unspecified, as in Spark). 'collect_merge': flatten the per-row
    lists of each group (the merge of partial collect buffers)."""
    from ..columnar.column import ArrayColumn
    from ..types import ArrayType
    from .strings import _rebuild_offsets

    if op == "collect_merge":
        assert isinstance(g, ArrayColumn), g
        # g is key-sorted: each group's row lists are contiguous, so the
        # gathered child IS the flattened result; offsets accumulate the
        # per-group totals
        from .collection import array_lengths
        lens = jnp.where(act & g.validity, array_lengths(g), 0)
        counts = jax.ops.segment_sum(lens, seg, num_segments=capacity)
        offsets = _rebuild_offsets(jnp.where(group_act, counts, 0))
        return ArrayColumn(g.child, offsets, group_act, g.dtype)

    if op.startswith("psketch_merge"):
        # bounded approx_percentile: merge partial sketches
        # ([values..., n] rows) of each group — decode per-element
        # weights from the PRE-flatten row structure, flatten like
        # collect_merge, then resample to K (ops/percentile.sketch_merge)
        k = int(op.split(":")[1])
        assert isinstance(g, ArrayColumn), g
        from .collection import array_lengths
        from .percentile import sketch_merge
        cap = capacity
        rowlen = array_lengths(g)
        ccap = g.child.capacity
        epos = jnp.arange(ccap, dtype=jnp.int32)
        prow = jnp.clip(jnp.searchsorted(g.offsets, epos, side="right")
                        .astype(jnp.int32) - 1, 0, cap - 1)
        last_idx = jnp.clip(g.offsets[1:] - 1, 0, ccap - 1)
        counts_row = jnp.where(rowlen > 0, g.child.data[last_idx], 0.0)
        lens_row = jnp.maximum(rowlen - 1, 0)
        pos_in_row = epos - g.offsets[prow]
        is_count_elem = pos_in_row == (rowlen[prow] - 1)
        row_lens_e = jnp.where(is_count_elem, 0.0,
                               lens_row[prow].astype(jnp.float64))
        row_counts_e = counts_row[prow].astype(jnp.float64)
        lens = jnp.where(act & g.validity, rowlen, 0)
        counts = jax.ops.segment_sum(lens, seg, num_segments=capacity)
        offsets = _rebuild_offsets(jnp.where(group_act, counts, 0))
        flat = ArrayColumn(g.child, offsets, group_act, g.dtype)
        return sketch_merge(flat, row_lens_e, row_counts_e, k)

    if op.startswith("psketch"):
        # bounded approx_percentile update: collect the group's raw
        # values then compress to the K-point sketch encoding
        k = int(op.split(":")[1])
        collected = _collect_group("collect", g, seg, act, capacity,
                                   positions, group_act)
        from .percentile import sketch_compress
        return sketch_compress(collected, k)

    keep = act & g.validity  # Spark: collect_* drop nulls
    if op == "collect_set":
        # dedup: first kept occurrence of each (segment, value)
        keep = keep & _first_occurrence(g, seg, keep, capacity)
    counts = jax.ops.segment_sum(keep.astype(jnp.int32), seg,
                                 num_segments=capacity)
    offsets = _rebuild_offsets(jnp.where(group_act, counts, 0))
    from .basic import compaction_order as _co
    perm2, n_kept = _co(keep, jnp.int32(capacity))
    child = gather_column(g, perm2, active_mask(n_kept, capacity))
    return ArrayColumn(child, offsets, group_act, ArrayType(g.dtype))


def groupby_aggregate(key_columns: Sequence[Column],
                      agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                      num_rows, capacity: int,
                      string_words: int,
                      pre_grouped: bool = False,
                      ) -> Tuple[List[Column], List[Tuple[jnp.ndarray, jnp.ndarray]], jnp.ndarray]:
    """Sort-based group-by over one batch.

    agg_inputs: list of (op, input Column or None for count_star).
    Returns (grouped key columns, [(agg data, agg validity)], num_groups).
    All outputs have the input capacity; rows >= num_groups are inactive.

    pre_grouped: the caller guarantees equal keys are already CONTIGUOUS
    (e.g. the inner join's key-grouped emission, exec/joins.py) — the
    batch sort is skipped entirely; segment detection works on adjacency
    and never needed a total order.
    """
    all_cols = list(key_columns) + [c for _, c in agg_inputs
                                    if c is not None]
    if pre_grouped:
        sorted_all = list(all_cols)
    else:
        orders = [SortOrder(i) for i in range(len(key_columns))]
        # ONE sort carries keys AND agg inputs as packed lanes (round 4):
        # the old per-column gather-by-permutation cost ~26 ms per column
        from .sort import sort_batch_columns
        sorted_all, _ = sort_batch_columns(all_cols, orders, num_rows,
                                           capacity, string_words)
    sorted_keys = sorted_all[: len(key_columns)]
    sorted_in = sorted_all[len(key_columns):]
    seg, num_groups = group_segment_ids(sorted_keys, num_rows, capacity,
                                        string_words)
    act = active_mask(num_rows, capacity)
    positions = jnp.arange(capacity, dtype=jnp.int32)
    group_act = active_mask(num_groups, capacity)

    # -- prefix-difference tier (round 4, reworked round 5) ---------------
    # Over SORTED segments, sum/count collapse to SEGMENT-LOCAL inclusive
    # cumsums read at each group's LAST row. jax.ops.segment_sum is a
    # scatter-add (~163 ms for 2M f64 on v5e); this path has no scatters.
    # Segment-local scans (associative_scan with a segment-reset combine)
    # keep float sums numerically sound — a global cumsum difference
    # loses tiny groups sorted after large-magnitude ones to catastrophic
    # cancellation (ADVICE r4) — and the group totals come back via ONE
    # packed row gather at group-last positions instead of carrying every
    # prefix lane through the boundary-compaction sort.
    from ..types import DecimalType

    def prefixable(op, g):
        if op in ("count", "count_star"):
            return True
        if op in ("sum", "sum_sq"):
            return g is not None and not isinstance(g, StringColumn) \
                and not isinstance(g.dtype, DecimalType)
        return False

    in_it = iter(sorted_in)
    per_agg_inputs: List[Optional[Column]] = []
    for op, col in agg_inputs:
        per_agg_inputs.append(next(in_it) if col is not None else None)

    first_flag = ((seg != jnp.roll(seg, 1)) | (positions == 0)) & act
    scan_lanes: List[jnp.ndarray] = []
    agg_lane: dict = {}
    for i, (op, _) in enumerate(agg_inputs):
        g = per_agg_inputs[i]
        if not prefixable(op, g):
            continue
        if op == "count_star":
            # active rows sort first, so group size falls out of the
            # first-row positions alone
            agg_lane[i] = ("pos", None, None)
            continue
        valid_c = (g.validity & act).astype(jnp.int32)
        vlane = len(scan_lanes)
        scan_lanes.append(valid_c)
        if op == "count":
            agg_lane[i] = ("count", vlane, None)
            continue
        v = g.data.astype(jnp.float64) \
            if jnp.issubdtype(g.data.dtype, jnp.floating) \
            else g.data.astype(jnp.int64)
        if op == "sum_sq":
            v = v * v
        v = jnp.where(g.validity & act, v, jnp.zeros((), v.dtype))
        slane = len(scan_lanes)
        scan_lanes.append(v)
        agg_lane[i] = ("sum", vlane, slane)

    # ONE fused segment-reset scan over every lane: incl[j] = sum of the
    # lane within j's segment up to and including j
    if scan_lanes:
        def _comb(a, b):
            af, bf = a[-1], b[-1]
            out = tuple(jnp.where(bf, bv, av + bv)
                        for av, bv in zip(a[:-1], b[:-1]))
            return out + (af | bf,)

        scanned = jax.lax.associative_scan(
            _comb, tuple(scan_lanes) + (first_flag,))[:-1]
    else:
        scanned = ()

    # boundary compaction: one stable sort carrying the first-row
    # positions and the packed key lanes (prefix lanes no longer ride it)
    from .rowpack import pack_rows, split_packable, unpack_rows
    kp_idx, ko_idx = split_packable(sorted_keys)
    if kp_idx:
        kplan, kimat, kfmat = pack_rows([sorted_keys[i] for i in kp_idx])
        key_lanes = [kimat[:, j] for j in range(kimat.shape[1])]
        key_flanes = [kfmat[:, j] for j in range(kfmat.shape[1])] \
            if kfmat is not None else []
    else:
        key_lanes, key_flanes = [], []
    operands = ((~first_flag).astype(jnp.uint32), positions,
                *key_lanes, *key_flanes)
    comp = jax.lax.sort(operands, num_keys=1, is_stable=True)
    first_pos = jnp.where(group_act, comp[1], capacity)
    comp_keys_i = comp[2: 2 + len(key_lanes)]
    comp_keys_f = comp[2 + len(key_lanes):]

    last_group = positions == (num_groups - 1)

    # per-group LAST row: ONE stacked-matrix gather per dtype class reads
    # every group total (per-lane gathers cost ~26 ms each on v5e; an
    # (N, L) matrix gather is ~13 ms total)
    if scan_lanes:
        last_pos = jnp.where(last_group, num_rows - 1,
                             jnp.roll(first_pos, -1) - 1)
        last_safe = jnp.clip(jnp.where(group_act, last_pos, 0), 0,
                             capacity - 1)
        ilanes: List[jnp.ndarray] = []
        flanes: List[jnp.ndarray] = []
        lane_slot = []
        for lane in scanned:
            if lane.dtype == jnp.float64:
                lane_slot.append(("f", len(flanes)))
                flanes.append(lane)
            elif lane.dtype == jnp.int64:
                pair = jax.lax.bitcast_convert_type(lane, jnp.uint32)
                lane_slot.append(("w2", len(ilanes)))
                ilanes.append(pair[:, 0])
                ilanes.append(pair[:, 1])
            else:
                lane_slot.append(("w1", len(ilanes)))
                ilanes.append(jax.lax.bitcast_convert_type(
                    lane.astype(jnp.int32), jnp.uint32))
        gi = jnp.stack(ilanes, axis=1)[last_safe] if ilanes else None
        gf = jnp.stack(flanes, axis=1)[last_safe] if flanes else None
        lane_vals = []
        for kind, j in lane_slot:
            if kind == "f":
                lane_vals.append(gf[:, j])
            elif kind == "w2":
                pair = jnp.stack([gi[:, j], gi[:, j + 1]], axis=1)
                lane_vals.append(
                    jax.lax.bitcast_convert_type(pair, jnp.int64))
            else:
                lane_vals.append(jax.lax.bitcast_convert_type(
                    gi[:, j], jnp.int32))
    else:
        lane_vals = []

    results = []
    for i, (op, col) in enumerate(agg_inputs):
        if i in agg_lane:
            kind, vlane, slane = agg_lane[i]
            if kind == "pos":
                nxt = jnp.where(last_group, num_rows, jnp.roll(first_pos, -1))
                data = jnp.where(group_act, (nxt - first_pos), 0) \
                    .astype(jnp.int64)
                valid = group_act
            elif kind == "count":
                data = jnp.where(group_act, lane_vals[vlane], 0) \
                    .astype(jnp.int64)
                valid = group_act
            else:
                data = jnp.where(group_act, lane_vals[slane],
                                 jnp.zeros((), lane_vals[slane].dtype))
                valid = (lane_vals[vlane] > 0) & group_act
            results.append(("raw", (data, valid)))
            continue
        if col is None:
            data, valid = _segment_reduce("count_star", positions,
                                          act, seg, capacity, positions)
        else:
            g = per_agg_inputs[i]
            if op in ("collect", "collect_set", "collect_merge") \
                    or op.startswith("psketch"):
                results.append(("col", _collect_group(
                    op, g, seg, act, capacity, positions, group_act)))
                continue
            if isinstance(g, StringColumn):
                if op in ("min", "max", "first", "last", "first_any",
                          "last_any", "any_value"):
                    # order strings via their sort lanes; pick the row index
                    # then gather the string (exact given string_words).
                    from .sort import string_prefix_lanes
                    lanes = string_prefix_lanes(g, string_words)
                    valid = g.validity
                    pickpos = _pick_string_pos(op, lanes, valid, seg,
                                               capacity, positions)
                    ok = (pickpos >= 0) & (pickpos < capacity)
                    safe = jnp.clip(pickpos, 0, capacity - 1)
                    out = gather_column(g, safe, out_valid=ok & group_act)
                    results.append(("col", out))
                    continue
                raise NotImplementedError(f"string agg {op}")
            if op == "sum" and isinstance(g.dtype, DecimalType):
                from .decimal128 import decimal_segment_sum
                (rh, rl), has = decimal_segment_sum(g, g.validity, seg,
                                                    capacity)
                valid = has & group_act
                data = (jnp.where(group_act, rh, 0),
                        jnp.where(group_act, rl, 0))
                results.append(("raw", (data, valid)))
                continue
            data, valid = _segment_reduce(op, g.data, g.validity, seg,
                                          capacity, positions)
        valid = valid & group_act
        data = jnp.where(group_act, data, jnp.zeros((), data.dtype))
        results.append(("raw", (data, valid)))

    # representative key per group: first row of each segment, taken from
    # the compaction's carried key lanes (packable) or gathered (varlen)
    out_keys: List[Optional[Column]] = [None] * len(key_columns)
    if kp_idx:
        s_imat = jnp.stack(comp_keys_i, axis=1)
        s_fmat = jnp.stack(comp_keys_f, axis=1) if key_flanes else None
        for j, c in zip(kp_idx, unpack_rows(kplan, s_imat, s_fmat)):
            from ..columnar.column import Column as _C
            out_keys[j] = _C(jnp.where(group_act, c.data,
                                       jnp.zeros((), c.data.dtype)),
                             c.validity & group_act, c.dtype)
    if ko_idx:
        safe = jnp.clip(first_pos, 0, capacity - 1)
        for j in ko_idx:
            c = sorted_keys[j]
            out_keys[j] = gather_column(
                c, safe, out_valid=c.validity[safe] & group_act)
    return list(out_keys), results, num_groups


def _pick_string_pos(op, lanes, valid, seg, capacity, positions):
    """Position of the min/max/first/last string per segment using its
    uint64 prefix lanes + position as the final tiebreaker."""
    if op in ("first", "any_value"):
        p = jnp.where(valid, positions, capacity)
        return jax.ops.segment_min(p, seg, num_segments=capacity)
    if op == "last":
        p = jnp.where(valid, positions, -1)
        return jax.ops.segment_max(p, seg, num_segments=capacity)
    if op == "first_any":  # ignoreNulls=False: position regardless of null
        return jax.ops.segment_min(positions, seg, num_segments=capacity)
    if op == "last_any":
        return jax.ops.segment_max(positions, seg, num_segments=capacity)
    # min/max over lexicographic lanes: sort rows by (seg, lanes) and take
    # the first/last row of each segment — reuse lax.sort for exactness.
    key_lanes = [seg.astype(jnp.uint32)]
    for lane in lanes:
        lane = jnp.where(valid, lane, jnp.zeros((), lane.dtype))
        if op == "max":
            lane = ~lane
        # invalid rows must lose: push them after all valid rows
        key_lanes.append(lane)
    # nulls excluded: make invalid rows sort last inside the segment
    key_lanes.insert(1, (~valid).astype(jnp.uint32))
    out = jax.lax.sort(tuple(key_lanes) + (positions,),
                       num_keys=len(key_lanes))
    sorted_pos = out[-1]
    sorted_seg = seg[sorted_pos]
    # index (in this ordering) of each segment's first VALID row, then map
    # back to the original row position; capacity => "no valid row".
    first_idx = jax.ops.segment_min(
        jnp.where(valid[sorted_pos],
                  jnp.arange(capacity, dtype=jnp.int32),
                  jnp.int32(capacity)),
        sorted_seg, num_segments=capacity)
    ok = first_idx < capacity
    safe = jnp.clip(first_idx, 0, capacity - 1)
    return jnp.where(ok, sorted_pos[safe], jnp.int32(capacity))


def groupby_aggregate_hash(key_columns: Sequence[Column],
                           agg_inputs: Sequence[Tuple[str, Optional[Column]]],
                           num_rows, capacity: int, rounds: int = 2,
                           ):
    """Hash-path group-by (ops/hashagg.py): no sort; returns the same
    (keys, results, num_groups) plus a `leftover` device flag the exec
    must host-check — True means unresolved collisions and the caller
    must re-run the exact sort-based kernel instead.

    Not supported here: min/max over string inputs (they need ordering
    lanes; the exec routes those plans to the sort path statically).
    """
    from .hashagg import hash_group_assignment

    seg_slots, rep_row, leftover = hash_group_assignment(
        key_columns, num_rows, capacity, rounds)
    keys, results, num_groups = _aggregate_with_assignment(
        key_columns, agg_inputs, num_rows, capacity, rounds,
        seg_slots, rep_row)
    return keys, results, num_groups, leftover


def _aggregate_with_assignment(key_columns, agg_inputs, num_rows,
                               capacity: int, rounds: int,
                               seg_slots, rep_row):
    """Aggregate over a precomputed hash group assignment."""
    from .hashagg import dense_group_ids

    seg, group_rep, num_groups = dense_group_ids(seg_slots, rep_row,
                                                 capacity, rounds)
    act = active_mask(num_rows, capacity)
    positions = jnp.arange(capacity, dtype=jnp.int32)
    group_act = active_mask(num_groups, capacity)

    results = []
    for op, col in agg_inputs:
        if col is None:
            data, valid = _segment_reduce("count_star", positions, act, seg,
                                          capacity, positions)
        else:
            if isinstance(col, StringColumn):
                if op in ("first", "last", "first_any", "last_any",
                          "any_value"):
                    valid = col.validity
                    if op == "last":
                        p = jnp.where(valid, positions, -1)
                        pick = jax.ops.segment_max(p, seg,
                                                   num_segments=capacity)
                    elif op == "last_any":
                        pick = jax.ops.segment_max(positions, seg,
                                                   num_segments=capacity)
                    elif op == "first_any":
                        pick = jax.ops.segment_min(positions, seg,
                                                   num_segments=capacity)
                    else:
                        p = jnp.where(valid, positions, capacity)
                        pick = jax.ops.segment_min(p, seg,
                                                   num_segments=capacity)
                    ok = (pick >= 0) & (pick < capacity)
                    safe = jnp.clip(pick, 0, capacity - 1)
                    out_valid = ok & group_act
                    if op in ("first_any", "last_any"):
                        out_valid = out_valid & valid[safe]
                    out = gather_column(col, safe, out_valid=out_valid)
                    results.append(("col", out))
                    continue
                raise NotImplementedError(
                    f"string agg {op} requires the sort path")
            from ..types import DecimalType
            if op == "sum" and isinstance(col.dtype, DecimalType):
                from .decimal128 import decimal_segment_sum
                (rh, rl), has = decimal_segment_sum(
                    col, col.validity & act, seg, capacity)
                valid = has & group_act
                data = (jnp.where(group_act, rh, 0),
                        jnp.where(group_act, rl, 0))
                results.append(("raw", (data, valid)))
                continue
            data, valid = _segment_reduce(op, col.data, col.validity & act,
                                          seg, capacity, positions)
        valid = valid & group_act
        data = jnp.where(group_act, data, jnp.zeros((), data.dtype))
        results.append(("raw", (data, valid)))

    out_keys = [gather_column(c, jnp.clip(group_rep, 0, capacity - 1),
                              out_valid=(group_rep < capacity)
                              & c.validity[jnp.clip(group_rep, 0,
                                                    capacity - 1)])
                for c in key_columns]
    return out_keys, results, num_groups
