"""Percentile kernels over array-of-values aggregation buffers.

Reference analog: GpuPercentile / GpuApproximatePercentile
(aggregate/GpuApproximatePercentile.scala over the JNI Histogram /
cuDF t-digest). The TPU build computes both EXACTLY: percentile
aggregates buffer their group's values as a list column (the collect
machinery), and evaluation segment-sorts the flat child once and picks
rank positions — approx_percentile therefore returns exact quantiles,
which satisfies (and beats) its accuracy contract. The reference needs
the sketch because cuDF merges per-batch; here the merge pass already
concatenates each group's values."""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ..columnar.column import ArrayColumn, Column
from ..types import DOUBLE
from .sort import _numeric_order_key


def _sorted_child(arr: ArrayColumn):
    """Stable sort of the child within each row's segment; returns the
    sorted child data (same offsets)."""
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.searchsorted(arr.offsets, epos,
                            side="right").astype(jnp.int32) - 1
    erow = jnp.clip(erow, 0, arr.capacity - 1)
    in_use = (epos < arr.offsets[arr.capacity]) & arr.child.validity
    row_key = jnp.where(in_use, erow, jnp.int32(1 << 30))
    from .sort import _split_u64_lanes
    lanes = _split_u64_lanes([_numeric_order_key(arr.child)])
    out = jax.lax.sort(tuple([row_key] + lanes + [epos]),
                       num_keys=1 + len(lanes))
    return arr.child.data[out[-1]]


def percentile_of_arrays(arr: ArrayColumn,
                         percentages: Union[float, Sequence[float]],
                         interpolate: bool) -> Column:
    """Per row (group): the percentile(s) of its array values.

    interpolate=True  -> Spark `percentile` (DOUBLE, linear interpolation
                         at rank p*(n-1));
    interpolate=False -> Spark `approx_percentile` (input type, element
                         at rank ceil(p*n)-1).
    Scalar `percentages` yields a scalar column; a list yields an array
    column (one element per percentage)."""
    scalar = not isinstance(percentages, (list, tuple))
    ps = [float(percentages)] if scalar else [float(p) for p in percentages]
    cap = arr.capacity
    sorted_vals = _sorted_child(arr)
    starts = arr.offsets[:-1]
    lens = (arr.offsets[1:] - starts)
    # valid element count per row (nulls sorted to the tail by the
    # validity-aware in_use mask above... nulls are excluded from
    # percentile entirely, so count only valid elements)
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.clip(jnp.searchsorted(arr.offsets, epos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = (epos < arr.offsets[cap]) & arr.child.validity
    nvalid = jax.ops.segment_sum(in_use.astype(jnp.int32), erow,
                                 num_segments=cap)
    # valid elements of row i occupy sorted positions
    # [valid_start[i], valid_start[i] + nvalid[i]) where valid_start is
    # the exclusive cumsum of nvalid (the segment sort moves invalid
    # elements to the global tail, compacting valid ones to a prefix)
    valid_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(nvalid, dtype=jnp.int32)])[:-1]

    outs = []
    valids = []
    for p in ps:
        has = nvalid > 0
        n = jnp.maximum(nvalid, 1)
        if interpolate:
            rank = p * (n - 1).astype(jnp.float64)
            lo_k = jnp.floor(rank).astype(jnp.int32)
            hi_k = jnp.ceil(rank).astype(jnp.int32)
            frac = rank - lo_k.astype(jnp.float64)
            lo_i = jnp.clip(valid_start + lo_k, 0, ccap - 1)
            hi_i = jnp.clip(valid_start + hi_k, 0, ccap - 1)
            lo_v = sorted_vals[lo_i].astype(jnp.float64)
            hi_v = sorted_vals[hi_i].astype(jnp.float64)
            outs.append(lo_v + frac * (hi_v - lo_v))
        else:
            k = jnp.ceil(p * n.astype(jnp.float64)).astype(jnp.int32) - 1
            k = jnp.clip(k, 0, n - 1)
            idx = jnp.clip(valid_start + k, 0, ccap - 1)
            outs.append(sorted_vals[idx])
        valids.append(arr.validity & has)

    out_t = DOUBLE if interpolate else arr.dtype.element_type
    if scalar:
        data = jnp.where(valids[0], outs[0],
                         jnp.zeros((), outs[0].dtype))
        return Column(data, valids[0], out_t)
    from ..types import ArrayType
    from .maps import interleave_columns
    cols = [Column(jnp.where(v, o, jnp.zeros((), o.dtype)), v, out_t)
            for o, v in zip(outs, valids)]
    child = interleave_columns(cols)
    off = jnp.arange(cap + 1, dtype=jnp.int32) * len(ps)
    # a group with no valid values yields a NULL array, not [NULL, ...]
    row_valid = arr.validity & (nvalid > 0)
    return ArrayColumn(child, off, row_valid, ArrayType(out_t))


# -- round-5 bounded sketch (approx_percentile) ---------------------------
# Reference GpuApproximatePercentile.scala:41-76 merges cuDF t-digests so
# per-group state stays O(accuracy). The TPU analog is a uniform-weight
# quantile sketch: a group keeps at most K value points (K = 2*accuracy),
# each merge/compress resamples to K evenly-spaced weighted quantiles, and
# groups with <= K values stay EXACT. Rank error per compress level is
# <= n/(2K) = n/(4*accuracy); the merge tree is MERGE_FAN_IN-ary, so a
# few levels stay comfortably inside Spark's n/accuracy contract.
#
# Buffer encoding (one ArrayColumn of DOUBLE per group, the same layout
# Spark's sketch serializes to a binary buffer): [v_0..v_{L-1}, n] — the
# TRUE value count rides as the trailing element, so element weights
# (n/L) survive merges without a second buffer column.


def _group_sorted_elements(arr: ArrayColumn, weights=None):
    """Per-group ascending value sort of the child. Returns (sorted
    values f64, sorted weights f64, erow, in_use-sorted mask,
    valid_start, nvalid_weights?) pieces used by the resamplers."""
    cap = arr.capacity
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.clip(jnp.searchsorted(arr.offsets, epos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = (epos < arr.offsets[cap]) & arr.child.validity
    row_key = jnp.where(in_use, erow, jnp.int32(1 << 30))
    from .sort import _split_u64_lanes
    lanes = _split_u64_lanes([_numeric_order_key(arr.child)])
    w = weights if weights is not None else jnp.ones((ccap,), jnp.float64)
    out = jax.lax.sort(tuple([row_key] + lanes
                             + [epos, w.astype(jnp.float64)]),
                       num_keys=1 + len(lanes))
    sorted_vals = arr.child.data[out[-2]].astype(jnp.float64)
    sorted_w = out[-1]
    sorted_row = out[0]
    return sorted_vals, sorted_w, sorted_row


def _merge_rank_1d(key_a, val_a, key_b, val_b, nb: int):
    """rank of each (key_b, val_b) probe among the (key_a, val_a)
    entries (count sorting strictly before), via one stable sort — both
    sequences must already be sorted by (key, val)."""
    na = key_a.shape[0]
    keys = jnp.concatenate([key_a, key_b])
    vals = jnp.concatenate([val_a, val_b])
    flag = jnp.concatenate([jnp.ones((na,), jnp.int32),
                            jnp.zeros((nb,), jnp.int32)])
    payload = jnp.arange(na + nb, dtype=jnp.int32)
    out = jax.lax.sort((keys, vals, flag, payload), num_keys=3,
                       is_stable=True)
    pos_of = jnp.zeros((na + nb,), jnp.int32).at[out[-1]].set(payload)
    return pos_of[na:] - jnp.arange(nb, dtype=jnp.int32)


def sketch_compress(arr: ArrayColumn, k: int) -> ArrayColumn:
    """Compress per-group RAW value lists (weights 1) into the sketch
    encoding; merging already-encoded partial sketches is sketch_merge's
    job (it decodes per-row weights before resampling)."""
    from ..types import ArrayType
    cap = arr.capacity
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)

    # raw values, weights 1
    sorted_vals, sorted_w, sorted_row = _group_sorted_elements(arr)
    in_use = (epos < arr.offsets[cap]) & arr.child.validity
    nvalid = jax.ops.segment_sum(
        in_use.astype(jnp.int32),
        jnp.clip(jnp.searchsorted(arr.offsets, epos, side="right")
                 .astype(jnp.int32) - 1, 0, cap - 1),
        num_segments=cap)
    valid_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(nvalid, dtype=jnp.int32)])[:-1]

    out_len = jnp.minimum(nvalid, k) + 1  # +1 for the trailing count
    out_len = jnp.where(arr.validity, out_len, 0)
    from .strings import _rebuild_offsets
    offsets = _rebuild_offsets(out_len)
    out_cap = ccap + cap  # worst case: every group exact + count slot
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    orow = jnp.clip(jnp.searchsorted(offsets, opos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    o_use = opos < offsets[cap]
    j = opos - offsets[orow]
    m = nvalid[orow]
    L = jnp.minimum(m, k)
    is_count = j == L
    exact = m <= k
    # exact: element j; compressed: element floor((j+0.5)*m/L)
    idx_exact = j
    idx_comp = jnp.floor((j.astype(jnp.float64) + 0.5)
                         * m.astype(jnp.float64)
                         / jnp.maximum(L, 1).astype(jnp.float64)
                         ).astype(jnp.int32)
    idx = jnp.clip(jnp.where(exact, idx_exact, idx_comp), 0,
                   jnp.maximum(m - 1, 0))
    src = jnp.clip(valid_start[orow] + idx, 0, ccap - 1)
    val = jnp.where(is_count, m.astype(jnp.float64), sorted_vals[src])
    data = jnp.where(o_use, val, 0.0)
    child = Column(data, o_use, DOUBLE)
    return ArrayColumn(child, offsets, arr.validity, ArrayType(DOUBLE))


def sketch_merge(flat: ArrayColumn, row_lens, row_counts,
                 k: int) -> ArrayColumn:
    """Merge partial sketches already flattened per group.

    flat: per GROUP, the concatenation of its partial sketch rows'
    elements (counts still embedded); row_lens/row_counts: per ELEMENT of
    flat.child, the source sketch row's value-length and true count
    (decoded by the caller, which knows the pre-flatten row structure).
    Resamples every group to min(total_values, k) uniform-weight points
    and re-appends the merged count."""
    from ..types import ArrayType
    cap = flat.capacity
    ccap = flat.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.clip(jnp.searchsorted(flat.offsets, epos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = (epos < flat.offsets[cap]) & flat.child.validity
    is_val = in_use & (row_lens > 0)
    w = jnp.where(is_val,
                  row_counts / jnp.maximum(row_lens, 1.0), 0.0)

    # group totals
    m = jax.ops.segment_sum(is_val.astype(jnp.int32), erow,
                            num_segments=cap)          # value points
    n_total = jax.ops.segment_sum(w, erow, num_segments=cap)

    # per-group value sort carrying weights; dead elements (count slots)
    # sort to the tail of their group via a +inf value key
    masked = ArrayColumn(
        Column(flat.child.data,
               flat.child.validity & (row_lens > 0), flat.dtype.element_type
               if hasattr(flat.dtype, "element_type") else DOUBLE),
        flat.offsets, flat.validity, flat.dtype)
    sorted_vals, sorted_w, sorted_row = _group_sorted_elements(masked, w)
    # cumulative weight WITHIN each group (segment-reset scan)
    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf
    is_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_row[1:] != sorted_row[:-1]])
    cumw, _ = jax.lax.associative_scan(comb, (sorted_w, is_start))

    valid_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(m, dtype=jnp.int32)])[:-1]

    out_len = jnp.minimum(m, k) + 1
    out_len = jnp.where(flat.validity, out_len, 0)
    from .strings import _rebuild_offsets
    offsets = _rebuild_offsets(out_len)
    out_cap = ccap + cap
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    orow = jnp.clip(jnp.searchsorted(offsets, opos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    o_use = opos < offsets[cap]
    j = opos - offsets[orow]
    L = jnp.minimum(m, k)[orow]
    is_count = j == L
    # weighted resample target for slot j of its group
    t = (j.astype(jnp.float64) + 0.5) * n_total[orow] \
        / jnp.maximum(L, 1).astype(jnp.float64)
    # rank among the group's cumweights: first element with cumw > t
    # (probe ranks via ONE merge sort; both sides sorted by (group, w))
    probe_key = jnp.where(o_use & ~is_count, orow, jnp.int32(1 << 30))
    # entries: (group, cumw) — dead/count elements already carry the
    # BIG row key from the group sort; probes: (group, t). probe-first
    # (strictly-before count = #cumw < t) picks the first cumw >= t
    rank = _merge_rank_1d(sorted_row, cumw, probe_key, t, out_cap)
    idx = jnp.clip(rank - valid_start[orow], 0,
                   jnp.maximum(m[orow] - 1, 0))
    src = jnp.clip(valid_start[orow] + idx, 0, ccap - 1)
    val = jnp.where(is_count, n_total[orow], sorted_vals[src])
    data = jnp.where(o_use, val, 0.0)
    child = Column(data, o_use, DOUBLE)
    return ArrayColumn(child, offsets, flat.validity, ArrayType(DOUBLE))


def approx_percentile_of_sketches(arr: ArrayColumn, percentages,
                                  result_type) -> Column:
    """Final evaluation over sketch buffers ([values..., n] per group):
    element at weighted rank ceil(p*n) (Spark approx_percentile pick)."""
    scalar = not isinstance(percentages, (list, tuple))
    ps = [float(percentages)] if scalar \
        else [float(p) for p in percentages]
    cap = arr.capacity
    ccap = arr.child.capacity
    lens = arr.offsets[1:] - arr.offsets[:-1]
    L = jnp.maximum(lens - 1, 0)         # value points per group
    last = jnp.clip(arr.offsets[1:] - 1, 0, ccap - 1)
    n = jnp.where(L > 0, arr.child.data[last], 0.0)  # true counts
    has = (L > 0) & (n > 0)
    outs, valids = [], []
    for p in ps:
        # rank r = ceil(p*n) of n uniform-weight points spread over L
        # centroids: centroid ceil(r*L/n) - 1
        r = jnp.ceil(p * n)
        ci = jnp.ceil(r * L.astype(jnp.float64)
                      / jnp.maximum(n, 1.0)) - 1
        ci = jnp.clip(ci.astype(jnp.int32), 0, jnp.maximum(L - 1, 0))
        idx = jnp.clip(arr.offsets[:-1] + ci, 0, ccap - 1)
        v = arr.child.data[idx]
        outs.append(v.astype(result_type.jnp_dtype))
        valids.append(arr.validity & has)
    out_t = result_type
    if scalar:
        data = jnp.where(valids[0], outs[0], jnp.zeros((), outs[0].dtype))
        return Column(data, valids[0], out_t)
    from ..types import ArrayType
    from .maps import interleave_columns
    cols = [Column(jnp.where(v, o, jnp.zeros((), o.dtype)), v, out_t)
            for o, v in zip(outs, valids)]
    child = interleave_columns(cols)
    off = jnp.arange(cap + 1, dtype=jnp.int32) * len(ps)
    row_valid = arr.validity & has
    return ArrayColumn(child, off, row_valid, ArrayType(out_t))
