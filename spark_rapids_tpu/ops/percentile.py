"""Percentile kernels over array-of-values aggregation buffers.

Reference analog: GpuPercentile / GpuApproximatePercentile
(aggregate/GpuApproximatePercentile.scala over the JNI Histogram /
cuDF t-digest). The TPU build computes both EXACTLY: percentile
aggregates buffer their group's values as a list column (the collect
machinery), and evaluation segment-sorts the flat child once and picks
rank positions — approx_percentile therefore returns exact quantiles,
which satisfies (and beats) its accuracy contract. The reference needs
the sketch because cuDF merges per-batch; here the merge pass already
concatenates each group's values."""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ..columnar.column import ArrayColumn, Column
from ..types import DOUBLE
from .sort import _numeric_order_key


def _sorted_child(arr: ArrayColumn):
    """Stable sort of the child within each row's segment; returns the
    sorted child data (same offsets)."""
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.searchsorted(arr.offsets, epos,
                            side="right").astype(jnp.int32) - 1
    erow = jnp.clip(erow, 0, arr.capacity - 1)
    in_use = (epos < arr.offsets[arr.capacity]) & arr.child.validity
    row_key = jnp.where(in_use, erow, jnp.int32(1 << 30))
    from .sort import _split_u64_lanes
    lanes = _split_u64_lanes([_numeric_order_key(arr.child)])
    out = jax.lax.sort(tuple([row_key] + lanes + [epos]),
                       num_keys=1 + len(lanes))
    return arr.child.data[out[-1]]


def percentile_of_arrays(arr: ArrayColumn,
                         percentages: Union[float, Sequence[float]],
                         interpolate: bool) -> Column:
    """Per row (group): the percentile(s) of its array values.

    interpolate=True  -> Spark `percentile` (DOUBLE, linear interpolation
                         at rank p*(n-1));
    interpolate=False -> Spark `approx_percentile` (input type, element
                         at rank ceil(p*n)-1).
    Scalar `percentages` yields a scalar column; a list yields an array
    column (one element per percentage)."""
    scalar = not isinstance(percentages, (list, tuple))
    ps = [float(percentages)] if scalar else [float(p) for p in percentages]
    cap = arr.capacity
    sorted_vals = _sorted_child(arr)
    starts = arr.offsets[:-1]
    lens = (arr.offsets[1:] - starts)
    # valid element count per row (nulls sorted to the tail by the
    # validity-aware in_use mask above... nulls are excluded from
    # percentile entirely, so count only valid elements)
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.clip(jnp.searchsorted(arr.offsets, epos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = (epos < arr.offsets[cap]) & arr.child.validity
    nvalid = jax.ops.segment_sum(in_use.astype(jnp.int32), erow,
                                 num_segments=cap)
    # valid elements of row i occupy sorted positions
    # [valid_start[i], valid_start[i] + nvalid[i]) where valid_start is
    # the exclusive cumsum of nvalid (the segment sort moves invalid
    # elements to the global tail, compacting valid ones to a prefix)
    valid_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(nvalid, dtype=jnp.int32)])[:-1]

    outs = []
    valids = []
    for p in ps:
        has = nvalid > 0
        n = jnp.maximum(nvalid, 1)
        if interpolate:
            rank = p * (n - 1).astype(jnp.float64)
            lo_k = jnp.floor(rank).astype(jnp.int32)
            hi_k = jnp.ceil(rank).astype(jnp.int32)
            frac = rank - lo_k.astype(jnp.float64)
            lo_i = jnp.clip(valid_start + lo_k, 0, ccap - 1)
            hi_i = jnp.clip(valid_start + hi_k, 0, ccap - 1)
            lo_v = sorted_vals[lo_i].astype(jnp.float64)
            hi_v = sorted_vals[hi_i].astype(jnp.float64)
            outs.append(lo_v + frac * (hi_v - lo_v))
        else:
            k = jnp.ceil(p * n.astype(jnp.float64)).astype(jnp.int32) - 1
            k = jnp.clip(k, 0, n - 1)
            idx = jnp.clip(valid_start + k, 0, ccap - 1)
            outs.append(sorted_vals[idx])
        valids.append(arr.validity & has)

    out_t = DOUBLE if interpolate else arr.dtype.element_type
    if scalar:
        data = jnp.where(valids[0], outs[0],
                         jnp.zeros((), outs[0].dtype))
        return Column(data, valids[0], out_t)
    from ..types import ArrayType
    from .maps import interleave_columns
    cols = [Column(jnp.where(v, o, jnp.zeros((), o.dtype)), v, out_t)
            for o, v in zip(outs, valids)]
    child = interleave_columns(cols)
    off = jnp.arange(cap + 1, dtype=jnp.int32) * len(ps)
    # a group with no valid values yields a NULL array, not [NULL, ...]
    row_valid = arr.validity & (nvalid > 0)
    return ArrayColumn(child, off, row_valid, ArrayType(out_t))
