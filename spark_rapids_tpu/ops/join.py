"""Equi-join gather-map kernels — device core of GpuHashJoin / JoinGatherer
(reference org/apache/spark/sql/rapids/execution/GpuHashJoin.scala:994,
JoinGatherer.scala).

TPU-first: no device hash table with collision chains. The build side is
sorted by a 64-bit key hash (xxhash64, already Spark-exact in ops/hashing);
each stream row finds its hash-equal candidate range with two searchsorteds;
candidates expand into (stream, build) index pairs; a vectorized *verify*
pass compares the real key columns (so hash collisions cost a false
candidate, never a wrong row); compaction drops mismatches. All steps are
static-shape XLA; the only host sync is choosing the candidate-capacity
bucket from the total match count — the analog of the reference sizing its
gather maps from cuDF's join row count.

Join-type semantics (Spark):
  * equi-keys never match null keys (IS NOT DISTINCT FROM is handled by the
    planner rewriting to a null-safe wrapper before reaching here);
  * left outer emits unmatched stream rows with build side null (build_idx
    == -1 -> gather_column yields invalid rows);
  * semi/anti/existence reduce to the per-stream-row matched flag.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import (
    ArrayColumn, Column, StringColumn, bucket_capacity,
)
from .basic import active_mask, compaction_order, gather_column
from .hashing import murmur3_batch
# row gathers in this module route through ops.gather (tier selection,
# breaker demotion, numGathers accounting) — do NOT import the raw
# rowpack.gather_rows here
from .rowpack import pack_rows, split_packable
from .strings import string_equal

JOIN_HASH_SEED = 0x5370_6172  # arbitrary fixed seed, 'Spar'
JOIN_HASH_SEED2 = 0x85EB_CA6B


def join_hash_pair(key_cols: Sequence[Column], lo_too: bool = True):
    """Internal join bucket hash: two independent murmur3 passes (u32 VPU
    ops only). xxhash64's emulated 64-bit arithmetic measured ~120 ms per
    2M i64 keys on v5e vs ~10 ms for murmur3 lanes (round 4); the join
    never needs Spark-exact hashing here — collisions only cost a false
    candidate that the exact key-verify pass drops."""
    h_hi = jax.lax.bitcast_convert_type(
        murmur3_batch(list(key_cols), seed=JOIN_HASH_SEED), jnp.uint32)
    if not lo_too:
        return h_hi, None
    h_lo = jax.lax.bitcast_convert_type(
        murmur3_batch(list(key_cols), seed=JOIN_HASH_SEED2), jnp.uint32)
    return h_hi, h_lo


def _keys_valid(key_cols: Sequence[Column], num_rows, capacity: int):
    v = active_mask(num_rows, capacity)
    for c in key_cols:
        v = v & c.validity
    return v


def _bucket_bits(capacity: int) -> int:
    """Static bucket-count exponent: ~2 slots per build row, capped so
    the offsets table stays small."""
    return min(21, max(10, (capacity - 1).bit_length() + 1))


def int_key_lanes(key_cols: Sequence[Column]):
    """Key columns as u32 equality lanes + a combined validity lane, or
    None when any key is not integer-like (strings/floats/decimals keep
    the XLA verify: float IEEE `==` and varlen compares are not
    bit-equality). 32-bit-or-narrower types widen to one i32 lane
    (injective, so lane equality == value equality); 64-bit types split
    into (lo, hi) u32 lanes. Shared by the XLA BuildTable and the fused
    Pallas probe so both compare identical bit patterns."""
    lanes = []
    valid = None
    for c in key_cols:
        if type(c) is not Column:
            return None
        dt = c.data.dtype
        if dt == jnp.bool_:
            lanes.append(jax.lax.bitcast_convert_type(
                c.data.astype(jnp.int32), jnp.uint32))
        elif jnp.issubdtype(dt, jnp.integer):
            if jnp.dtype(dt).itemsize <= 4:
                lanes.append(jax.lax.bitcast_convert_type(
                    c.data.astype(jnp.int32), jnp.uint32))
            else:
                pair = jax.lax.bitcast_convert_type(
                    c.data.astype(jnp.int64), jnp.uint32)  # (n, 2) lo, hi
                lanes.append(pair[:, 0])
                lanes.append(pair[:, 1])
        else:
            return None
        v = c.validity
        valid = v if valid is None else (valid & v)
    if valid is None:
        return None
    return tuple(lanes), valid.astype(jnp.int32)


def candidate_fill_inputs(lo, counts, out_capacity: int):
    """Shared candidate-expansion inputs for the i32 fast path: the
    scattered owner-row-index array `seg` (range starts carry their row,
    disjoint by construction) and the (lo, start) 2-lane matrix. Both the
    XLA `expand_candidates` and the fused Pallas probe walk these, so the
    two tiers produce bit-identical (stream_idx, build_pos) layouts."""
    n_rows = counts.shape[0]
    cum32 = jnp.cumsum(counts)          # inclusive, i32
    start = cum32 - counts              # exclusive prefix
    nonempty = counts > 0
    pos = jnp.where(nonempty, jnp.minimum(start, out_capacity),
                    out_capacity)
    j = jnp.arange(n_rows, dtype=jnp.int32)
    seg = jnp.zeros((out_capacity,), jnp.int32).at[pos].max(
        j, mode="drop")
    ls = jnp.stack([lo, start], axis=1)
    return seg, ls


class BuildTable:
    """Hash-bucketed build side: the TPU analog of the cuDF hash table
    the reference builds once and probes per stream batch. Rows sort by
    the u32 hash pair (u32 sort keys are ~5x cheaper than emulated u64 on
    v5e) and a top-B-bits bucket offsets table replaces binary search:
    probing is two tiny table gathers instead of 2 x 19 emulated-u64
    searchsorted rounds (measured: ~1.05 s per 2M probes). Bucket-mates
    with unequal keys are filtered by the existing exact key-verify pass,
    so correctness never depends on hash-range tightness. A registered
    pytree so the whole build phase jits and the probe phase takes it as
    a traced argument."""

    def __init__(self, bucket_table, perm, valid_count, num_rows,
                 key_cols: Sequence[Column], payload: Sequence[Column],
                 capacity: int, payload_prefix: Sequence = (),
                 pair_table=None, pack=None, key_lanes=None):
        self.bucket_table = bucket_table  # (2^B + 1,) int32 offsets
        self.perm = perm  # sorted position -> original build row
        self.valid_count = valid_count
        self.num_rows = num_rows
        self.key_cols = list(key_cols)
        self.payload = list(payload)
        self.capacity = capacity
        # per STRING payload column (payload order): (capacity+1,) int64
        # prefix sum of row byte lengths in sorted order — sizes the join's
        # string output buckets without per-stream-batch recomputation
        self.payload_prefix = tuple(payload_prefix)
        # (2^B, 2) int32 [lo, hi) per bucket: ONE row gather per probe
        # instead of two offset-table gathers (round 4)
        self.pair_table = pair_table
        # (plan_k, kmat_sorted, kfmat_sorted, plan_p, pmat_sorted,
        #  pfmat_sorted, key_pack_idx, payload_pack_idx,
        #  payload_other_idx): fixed-width KEYS and PAYLOAD packed into
        #  SEPARATE u32 (+ f64) matrices in SORTED hash order (round 8:
        #  the probe's verify gathers only the key pack at candidate
        #  level; the payload pack is gathered ONCE, at output level,
        #  after compaction — the gather-elimination contract asserted
        #  by the structural numGathers tests)
        self.pack = pack
        # (u32 lane arrays..., i32 combined-validity lane) in SORTED hash
        # order, or None for non-integer keys: the fused Pallas probe
        # keeps these VMEM-resident and verifies candidates in-register
        # (ops/pallas_join.fused_probe_verify)
        self.key_lanes = key_lanes

    @staticmethod
    def build(key_cols: Sequence[Column], payload: Sequence[Column],
              num_rows, capacity: int,
              with_key_lanes: bool = True) -> "BuildTable":
        """with_key_lanes: prepare the fused Pallas probe's u32 key-lane
        tables (1-2 extra permuted lanes per key). Callers on the default
        XLA path pass the tier selector's family_may_engage so the
        common case pays nothing for a kernel it will never run."""
        from .strings import string_lengths
        valid = _keys_valid(key_cols, num_rows, capacity)
        # invalid/inactive rows: push to the end with the max hash AND keep
        # them out of every candidate range via the valid-count boundary.
        h_hi, h_lo = join_hash_pair(key_cols)
        big32 = jnp.uint32(0xFFFF_FFFF)
        k_hi = jnp.where(valid, h_hi, big32)
        k_lo = jnp.where(valid, h_lo, big32)
        iota = jnp.arange(capacity, dtype=jnp.int32)
        sorted_hi, _, _, perm = jax.lax.sort(
            (k_hi, k_lo, (~valid).astype(jnp.int8), iota), num_keys=3)
        valid_count = jnp.sum(valid, dtype=jnp.int32)
        # top-B-bits bucket offsets over the sorted order
        B = _bucket_bits(capacity)
        n_buckets = 1 << B
        sorted_bucket = (sorted_hi >> jnp.uint32(32 - B)).astype(jnp.int32)
        in_valid = iota < valid_count
        seg = jnp.where(in_valid, sorted_bucket, n_buckets)
        counts = jax.ops.segment_sum(
            jnp.ones((capacity,), jnp.int32), seg,
            num_segments=n_buckets + 1)[:n_buckets]
        bucket_table = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(counts, dtype=jnp.int32)])
        pair_table = jnp.stack([bucket_table[:-1], bucket_table[1:]], axis=1)
        prefixes = []
        for c in payload:
            if isinstance(c, (StringColumn, ArrayColumn)):
                if isinstance(c, ArrayColumn):
                    lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int64)
                else:
                    lens = string_lengths(c).astype(jnp.int64)
                sorted_lens = jnp.where(iota < valid_count, lens[perm], 0)
                prefixes.append(jnp.concatenate(
                    [jnp.zeros((1,), jnp.int64), jnp.cumsum(sorted_lens)]))
        # pack fixed-width keys and payload into SEPARATE sorted-order
        # matrices (round 8): the key pack serves the candidate-level
        # verify, the payload pack is gathered once at output level.
        # The permutes route through the gather engine so the measured
        # Pallas tier can serve the build reorder too.
        from .gather import gather_rows as routed_gather_rows
        key_pack_idx, _ = split_packable(key_cols)
        payload_pack_idx, payload_other_idx = split_packable(payload)
        plan_k, kmat, kfmat = pack_rows([key_cols[i]
                                         for i in key_pack_idx])
        plan_p, pmat, pfmat = pack_rows([payload[i]
                                         for i in payload_pack_idx])
        kmat_s, kfmat_s = routed_gather_rows(plan_k, kmat, kfmat, perm) \
            if key_pack_idx else (kmat, kfmat)
        pmat_s, pfmat_s = routed_gather_rows(plan_p, pmat, pfmat, perm) \
            if payload_pack_idx else (pmat, pfmat)
        pack = (plan_k, kmat_s, kfmat_s, plan_p, pmat_s, pfmat_s,
                tuple(key_pack_idx), tuple(payload_pack_idx),
                tuple(payload_other_idx))
        key_lanes = None
        kl = int_key_lanes(key_cols) if with_key_lanes else None
        if kl is not None:
            lanes, kvalid = kl
            key_lanes = (tuple(ln[perm] for ln in lanes), kvalid[perm])
        return BuildTable(bucket_table, perm, valid_count,
                          num_rows, key_cols, payload, capacity, prefixes,
                          pair_table, pack, key_lanes)


def _bt_flatten(bt: BuildTable):
    (plan_k, kmat_s, kfmat_s, plan_p, pmat_s, pfmat_s,
     kpi, ppi, poi) = bt.pack
    return ((bt.bucket_table, bt.perm, bt.valid_count, bt.num_rows,
             tuple(bt.key_cols), tuple(bt.payload), bt.payload_prefix,
             bt.pair_table, kmat_s, kfmat_s, pmat_s, pfmat_s,
             bt.key_lanes),
            (bt.capacity, plan_k, plan_p, kpi, ppi, poi))


def _bt_unflatten(aux, children):
    capacity, plan_k, plan_p, kpi, ppi, poi = aux
    (bucket_table, perm, valid_count, num_rows, key_cols, payload,
     payload_prefix, pair_table, kmat_s, kfmat_s, pmat_s, pfmat_s,
     key_lanes) = children
    return BuildTable(bucket_table, perm, valid_count, num_rows,
                      list(key_cols), list(payload), capacity,
                      payload_prefix, pair_table,
                      (plan_k, kmat_s, kfmat_s, plan_p, pmat_s, pfmat_s,
                       kpi, ppi, poi), key_lanes)


jax.tree_util.register_pytree_node(BuildTable, _bt_flatten, _bt_unflatten)


def probe_counts(build: BuildTable, stream_keys: Sequence[Column],
                 stream_rows, stream_cap: int):
    """Per-stream-row candidate range (lo, hi) in the bucketed build
    table: two offset-table gathers; bucket-mates with different keys
    are dropped by the key-verify pass downstream."""
    valid = _keys_valid(stream_keys, stream_rows, stream_cap)
    h_hi, _ = join_hash_pair(stream_keys, lo_too=False)
    B = _bucket_bits(build.capacity)
    b = (h_hi >> jnp.uint32(32 - B)).astype(jnp.int32)
    if build.pair_table is not None:
        # ONE row gather for [lo, hi) (round 4; two offset gathers before)
        pair = build.pair_table[b]
        lo = pair[:, 0]
        hi = jnp.minimum(pair[:, 1], build.valid_count)
    else:
        lo = build.bucket_table[b]
        hi = jnp.minimum(build.bucket_table[b + 1], build.valid_count)
    lo = jnp.minimum(lo, hi)
    counts = jnp.where(valid, hi - lo, 0)
    return lo, counts, valid


def expand_candidates(lo, counts, out_capacity: int):
    """Flatten candidate ranges into (stream_idx, build_pos) pairs.

    out_capacity >= total candidates (host-chosen bucket). Pair i belongs to
    the stream row whose cumulative count interval contains i.

    Formulation (round 4): interval starts scatter their OWNER ROW INDEX
    (disjoint targets by construction), a cummax forward-fills (row index
    is monotone along the flat order), and one 2-lane row gather fetches
    (lo, start) to turn flat positions into build positions. The old i32
    searchsorted was ~21 binary-search rounds, each a full-width gather —
    ~10x this formulation's cost on v5e (tools/exp_join_parts.py).

    Overflow discipline: the i32 prefix sums are exact whenever the true
    candidate total < 2^31; the total itself is accumulated in int64 (a
    cheap reduce), so skew past 2^31 is still detected by the caller's
    sizing/overflow checks (review finding r1) and served by the int64
    searchsorted fallback.
    """
    total = jnp.sum(counts.astype(jnp.int64)) if counts.shape[0] \
        else jnp.int64(0)
    if counts.shape[0] and out_capacity < (1 << 31):
        seg, ls = candidate_fill_inputs(lo, counts, out_capacity)
        row_f = jax.lax.cummax(seg)
        g = ls[row_f]                       # one 2-lane row gather
        i = jnp.arange(out_capacity, dtype=jnp.int32)
        in_range = i.astype(jnp.int64) < total
        stream_idx = jnp.where(in_range, row_f, -1)
        build_pos = g[:, 0] + (i - g[:, 1])
        return stream_idx, build_pos, total
    cum = jnp.cumsum(counts.astype(jnp.int64))  # inclusive
    i = jnp.arange(out_capacity, dtype=jnp.int64)
    stream_idx = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    in_range = i < total
    safe_stream = jnp.clip(stream_idx, 0, max(counts.shape[0] - 1, 0))
    before = cum[safe_stream] - counts[safe_stream]
    # (i - before) < per-row count <= capacity, so the int64->int32 narrowing
    # is safe after the subtraction
    build_pos = lo[safe_stream] + (i - before).astype(jnp.int32)
    return jnp.where(in_range, safe_stream, -1), build_pos, total


def verify_pairs(build: BuildTable, stream_keys: Sequence[Column],
                 stream_idx, build_pos, pair_valid):
    """Exact key equality per candidate pair (null-safe: nulls never match,
    but null STREAM rows never produce candidates, so only hash collisions
    are filtered here)."""
    from ..columnar.encoded import DictionaryColumn, bytes_equal_at
    build_row = gather_column_indices(build.perm, build_pos)
    ok = pair_valid
    for bk, sk in zip(build.key_cols, stream_keys):
        if isinstance(bk, DictionaryColumn) or \
                isinstance(sk, DictionaryColumn):
            # encoded key (ISSUE 18): byte-compare through spans into
            # the ORIGINAL buffers (the sides carry DIFFERENT
            # dictionaries, so code equality means nothing across them;
            # a materialized candidate gather would overflow the base
            # byte bucket under join fan-out)
            ok = ok & bytes_equal_at(bk, build_row, sk, stream_idx)
            continue
        b = gather_column(bk, build_row)
        s = gather_column(sk, stream_idx)
        if isinstance(bk, StringColumn):
            eq = string_equal(b, s)
            ok = ok & eq.data & eq.validity
        else:
            ok = ok & (b.data == s.data) & b.validity & s.validity
    return ok, build_row


def gather_column_indices(arr, idx):
    safe = jnp.clip(idx, 0, arr.shape[0] - 1)
    return jnp.where((idx >= 0) & (idx < arr.shape[0]), arr[safe], -1)


def inner_gather_maps(verified, stream_idx, build_row, total):
    """Compact verified pairs to the front: (stream_map, build_map, rows)."""
    cap = verified.shape[0]
    perm, n = compaction_order(verified, total)
    s = jnp.where(active_mask(n, cap), stream_idx[perm], -1)
    b = jnp.where(active_mask(n, cap), build_row[perm], -1)
    return s, b, n


def matched_flags(verified, idx, capacity: int):
    """Per-row matched flag via scatter-or (idx may repeat)."""
    flags = jnp.zeros((capacity,), jnp.int32)
    safe = jnp.clip(idx, 0, capacity - 1)
    contrib = (verified & (idx >= 0)).astype(jnp.int32)
    return flags.at[safe].max(contrib) > 0


def outer_extend_maps(s_map, b_map, n_pairs, unmatched_idx, n_unmatched,
                      null_on: str, out_capacity: int):
    """Append unmatched rows (other side -1 => null) after the matched pairs.

    null_on: which side of the appended rows is null ('build' for left outer,
    'stream' for right outer).
    """
    i = jnp.arange(out_capacity, dtype=jnp.int32)
    total = n_pairs + n_unmatched
    from_un = (i >= n_pairs) & (i < total)
    un_i = jnp.clip(i - n_pairs, 0, unmatched_idx.shape[0] - 1)
    pair_i = jnp.clip(i, 0, s_map.shape[0] - 1)
    if null_on == "build":
        s = jnp.where(from_un, unmatched_idx[un_i], jnp.where(i < n_pairs, s_map[pair_i], -1))
        b = jnp.where(from_un, -1, jnp.where(i < n_pairs, b_map[pair_i], -1))
    else:
        s = jnp.where(from_un, -1, jnp.where(i < n_pairs, s_map[pair_i], -1))
        b = jnp.where(from_un, unmatched_idx[un_i], jnp.where(i < n_pairs, b_map[pair_i], -1))
    return s, b, total


def unmatched_indices(matched, num_rows, capacity: int):
    """Indices of active rows whose matched flag is False, compacted."""
    act = active_mask(num_rows, capacity)
    keep = act & (~matched)
    perm, n = compaction_order(keep, num_rows)
    idx = jnp.where(active_mask(n, capacity), perm, -1)
    return idx, n


def cross_pairs(stream_rows, build_rows, chunk_start, out_capacity: int):
    """Nested-loop candidates: all (stream, build) pairs with flat pair index
    in [chunk_start, chunk_start+out_capacity). The exec layer loops chunks
    (reference GpuBroadcastNestedLoopJoinExecBase / GpuCartesianProductExec).

    Pair indices are int64: stream_rows*build_rows overflows int32 well
    inside practical cartesian-product sizes."""
    i = jnp.arange(out_capacity, dtype=jnp.int64) + jnp.int64(chunk_start)
    total = jnp.int64(stream_rows) * jnp.int64(build_rows)
    ok = i < total
    safe_build = jnp.maximum(jnp.int64(build_rows), 1)
    s = jnp.where(ok, i // safe_build, -1).astype(jnp.int32)
    b = jnp.where(ok, i % safe_build, -1).astype(jnp.int32)
    remaining = jnp.maximum(total - jnp.int64(chunk_start), 0)
    n = jnp.minimum(remaining, out_capacity).astype(jnp.int32)
    return s, b, n
