"""Fused probe-verify-emit Pallas kernel for the hash join (ISSUE 1
tentpole; reference analog: the cuDF mixed-join probe kernels that
spark-rapids treats as the entire point of the accelerator).

The XLA probe path runs bucket-range lookup, candidate-pair expansion,
key verification and packed-row gathers as SEPARATE programs with
full-width candidate-level intermediates round-tripping HBM between them
(ops/join.py, exec/joins.py:_probe_kernel). This kernel streams candidate
tiles through VMEM once: it forward-fills the owner-row index (the
cummax formulation of `expand_candidates`, carried across sequential
grid steps in SMEM), derives (stream_idx, build_pos) in-register, walks
the sorted-bucket `BuildTable` key lanes to verify exact key equality,
and emits (verified, stream_idx, build_pos, build_row) in one pass — no
expanded-index or gathered-key intermediate ever materializes in HBM.

Layout contract: candidates are walked in exactly the flat order of
`expand_candidates` (position start_i + k for stream row i's k-th
candidate), built from the SAME `candidate_fill_inputs` arrays, so the
two tiers are bit-identical — the interpret-mode property tests in
tier-1 assert elementwise equality (tests/test_pallas_fused.py).

Eligibility (gated by the caller / exec tier selector):
- every join key integer-like on both sides (ops/join.int_key_lanes):
  float keys keep IEEE `==` semantics the bit-equality lanes cannot
  express, strings/decimals are varlen/two-limb;
- candidate capacity < 2^31 (the i32 fast path's own bound);
- key-lane + permutation tables VMEM-resident on hardware — the
  measured tier (tools/kern_bench.py) only turns the kernel on where it
  actually wins, so oversize shapes simply keep the XLA tier.

All lanes are 32-bit, so like the murmur3 kernels the pallas_call traces
under jax.enable_x64(False) (mosaic wants i32 grid arithmetic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.dispatch import instrument as _instrument

from .pallas_kernels import TILE_ROWS, pad_to_tiles, tile_spec, whole_spec

# candidate tiles are smaller than the murmur3 tiles: the kernel keeps
# several whole side tables VMEM-resident next to the streamed tile
PROBE_TILE_ROWS = 64


def _probe_kernel_body(n_lanes: int):
    """Kernel factory: the number of u32 key lanes is static per shape."""

    def kernel(total_ref, seg_ref, lo_ref, start_ref, *refs):
        from jax.experimental import pallas as pl
        bk_refs = refs[:n_lanes]
        sk_refs = refs[n_lanes:2 * n_lanes]
        (bvalid_ref, svalid_ref, perm_ref, ver_ref, sidx_ref, bpos_ref,
         brow_ref, carry_ref) = refs[2 * n_lanes:]
        t = pl.program_id(0)

        # --- owner-row forward fill: flat cummax over the (TR, 128) tile
        # with the running maximum carried across sequential grid steps
        # (full-slice scratch stores only: indexed/conditional stores
        # discharge through dtype-fragile selects in interpret mode) ---
        seg = seg_ref[:]                                  # (TR, 128) i32
        row_incl = jax.lax.cummax(seg, axis=1)
        last = row_incl[:, 127:128]                       # (TR, 1)
        incl = jax.lax.cummax(last, axis=0)               # (TR, 1)
        carry = jnp.where(t == jnp.int32(0), jnp.int32(0),
                          carry_ref[:][0])
        prev = jnp.concatenate(
            [jnp.zeros((1, 1), jnp.int32), incl[:-1]], axis=0)
        prev = jnp.maximum(prev, carry)
        row_f = jnp.maximum(row_incl, prev)               # (TR, 128)
        carry_ref[:] = jnp.maximum(carry, incl[-1, 0]).reshape(1)

        # --- expand in-register: (stream_idx, build_pos) per candidate ---
        tr = seg.shape[0]
        i_flat = (jnp.int32(t) * jnp.int32(tr * 128)
                  + jax.lax.broadcasted_iota(jnp.int32, (tr, 128), 0)
                  * jnp.int32(128)
                  + jax.lax.broadcasted_iota(jnp.int32, (tr, 128), 1))
        total = total_ref[0, 0]
        in_range = i_flat < total
        lo_arr = lo_ref[:]
        start_arr = start_ref[:]
        neg1 = jnp.int32(-1)
        b_pos = lo_arr[row_f] + (i_flat - start_arr[row_f])
        s_idx = jnp.where(in_range, row_f, neg1)

        # --- verify: exact key equality over the u32 lanes ---
        build_cap = perm_ref.shape[0]
        safe_b = jnp.clip(b_pos, jnp.int32(0), jnp.int32(build_cap - 1))
        ok = in_range
        for bk_ref, sk_ref in zip(bk_refs, sk_refs):
            ok = ok & (bk_ref[:][safe_b] == sk_ref[:][row_f])
        ok = ok & (bvalid_ref[:][safe_b] != jnp.int32(0)) \
            & (svalid_ref[:][row_f] != jnp.int32(0))

        # --- emit ---
        b_pos_m = jnp.where(in_range, b_pos, neg1)
        pos_ok = (b_pos_m >= jnp.int32(0)) & (b_pos_m < jnp.int32(build_cap))
        b_row = jnp.where(pos_ok, perm_ref[:][safe_b], neg1)
        ver_ref[:] = ok.astype(jnp.int32)
        sidx_ref[:] = s_idx
        bpos_ref[:] = b_pos
        brow_ref[:] = b_row

    return kernel


@functools.partial(_instrument, label="pallas.join_probe",
                   static_argnames=("out_capacity", "interpret"))
def fused_probe_verify(lo, counts, bk_lanes, bvalid, sk_lanes, svalid,
                       perm, out_capacity: int, interpret: bool = False):
    """One-pass probe of a bucketed build side.

    lo/counts: per-stream-row candidate range (ops/join.probe_counts);
    bk_lanes/sk_lanes: u32 equality lanes (build side in SORTED order —
    BuildTable.key_lanes); bvalid/svalid: i32 combined key-validity
    lanes; perm: sorted position -> original build row.

    Returns (verified bool, stream_idx i32, build_pos i32, build_row i32)
    over the flat candidate layout of `expand_candidates` — bit-identical
    to the XLA expand+verify pipeline for integer keys.
    """
    from jax.experimental import enable_x64
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .join import candidate_fill_inputs

    assert len(bk_lanes) == len(sk_lanes)
    n_lanes = len(bk_lanes)
    seg, ls = candidate_fill_inputs(lo, counts, out_capacity)
    total = jnp.sum(counts.astype(jnp.int64)) if counts.shape[0] \
        else jnp.int64(0)
    total32 = jnp.minimum(total, out_capacity).astype(jnp.int32)

    seg2d, _ = pad_to_tiles(seg, PROBE_TILE_ROWS)
    rows = seg2d.shape[0]
    grid = rows // PROBE_TILE_ROWS
    tspec = tile_spec(PROBE_TILE_ROWS)
    out_struct = jax.ShapeDtypeStruct((rows, 128), jnp.int32)

    import contextlib

    # mosaic wants i32 grid/index arithmetic, so the hardware path traces
    # under x64-off like the murmur3 kernels; the interpreter must trace
    # under the engine's global x64 mode instead — its state-discharge
    # replay re-canonicalizes jaxpr consts, and a jaxpr traced x64-off
    # then replayed x64-on trips dtype checks (every kernel value is
    # explicitly 32-bit typed either way)
    ctx = contextlib.nullcontext() if interpret else enable_x64(False)
    with ctx:
        smem_spec = pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
        whole = whole_spec()
        # contract: ok dispatch-ledger — this pallas_call is traced
        # inline into the instrumented fused_probe_verify program above
        ver, s_idx, b_pos, b_row = pl.pallas_call(
            _probe_kernel_body(n_lanes),
            out_shape=(out_struct,) * 4,
            grid=(grid,),
            in_specs=[smem_spec, tspec]
            + [whole] * (2 + 2 * n_lanes + 3),
            out_specs=(tspec,) * 4,
            scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
            interpret=interpret,
        )(total32.reshape(1, 1), seg2d, ls[:, 0], ls[:, 1],
          *[ln.astype(jnp.uint32) for ln in bk_lanes],
          *[ln.astype(jnp.uint32) for ln in sk_lanes],
          bvalid.astype(jnp.int32), svalid.astype(jnp.int32),
          perm.astype(jnp.int32))
    flat = slice(None, out_capacity)
    return (ver.reshape(-1)[flat] != 0, s_idx.reshape(-1)[flat],
            b_pos.reshape(-1)[flat], b_row.reshape(-1)[flat])
