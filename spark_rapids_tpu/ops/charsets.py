"""Charset encode/decode kernels (reference GpuEncode/GpuDecode under
stringFunctions.scala — Java String.getBytes / new String(bytes, charset)
semantics, '?' for unmappable on encode, U+FFFD on decode).

Engine strings are UTF-8 bytes, so:
  encode(s, 'UTF-8')        -> byte-identical BINARY
  encode(s, 'US-ASCII')     -> one byte per code point; >0x7F -> '?'
  encode(s, 'ISO-8859-1')   -> code points <=0xFF collapse to one byte
  decode(b, 'UTF-8')        -> byte-identical STRING (malformed input is
                               passed through, a documented deviation —
                               Java substitutes U+FFFD per bad byte)
  decode(b, 'ISO-8859-1')   -> bytes >=0x80 expand to two UTF-8 bytes
  decode(b, 'US-ASCII')     -> bytes >=0x80 expand to U+FFFD (3 bytes)
UTF-16 variants keep the host tier (surrogates + BOM state machine).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BINARY, STRING
from .basic import active_mask, compaction_order


def _rebuild_offsets(lengths):
    return jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(lengths)]).astype(jnp.int32)


def _row_of_byte(col: StringColumn, pos):
    row = jnp.searchsorted(col.offsets[: col.capacity + 1], pos,
                           side="right").astype(jnp.int32) - 1
    return jnp.clip(row, 0, col.capacity - 1)


def encode_single_byte(col: StringColumn, charset: str) -> StringColumn:
    """UTF-8 -> US-ASCII / ISO-8859-1 (one output byte per code point)."""
    cap = col.capacity
    byte_cap = col.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    in_use = pos < col.offsets[-1]
    b = col.data
    is_start = (b & jnp.uint8(0xC0)) != jnp.uint8(0x80)
    keep = in_use & is_start
    # output char per code-point start
    nxt = jnp.concatenate([b[1:], jnp.zeros((1,), jnp.uint8)])
    if charset == "US-ASCII":
        ch = jnp.where(b < 0x80, b, jnp.uint8(ord("?")))
    else:  # ISO-8859-1
        ch = jnp.where(
            b < 0x80, b,
            jnp.where(b == 0xC2, nxt,
                      jnp.where(b == 0xC3, nxt + jnp.uint8(0x40),
                                jnp.uint8(ord("?")))))
    counts = jax.ops.segment_sum(keep.astype(jnp.int32), row,
                                 num_segments=cap)
    lengths = jnp.where(col.validity, counts, 0)
    offsets = _rebuild_offsets(lengths)
    perm, total = compaction_order(keep, col.offsets[-1])
    out_use = active_mask(total, byte_cap)
    data = jnp.where(out_use, ch[jnp.clip(perm, 0, byte_cap - 1)],
                     jnp.uint8(0))
    return StringColumn(data, offsets, col.validity, BINARY)


def decode_single_byte(col: StringColumn, charset: str) -> StringColumn:
    """US-ASCII / ISO-8859-1 bytes -> UTF-8 string."""
    cap = col.capacity
    byte_cap = col.byte_capacity
    b = col.data
    hi = b >= 0x80
    per_len = jnp.where(hi, 3 if charset == "US-ASCII" else 2, 1) \
        .astype(jnp.int32)
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    in_use = pos < col.offsets[-1]
    per_len = jnp.where(in_use, per_len, 0)
    out_counts = jax.ops.segment_sum(per_len, row, num_segments=cap)
    lengths = jnp.where(col.validity, out_counts, 0)
    offsets = _rebuild_offsets(lengths)

    # source start position of each input byte within the OUTPUT stream
    out_start = jnp.cumsum(per_len) - per_len
    out_total = offsets[-1]
    mult = 3 if charset == "US-ASCII" else 2
    out_cap = byte_cap * mult
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    # map output byte -> source input byte: searchsorted over out_start
    src = jnp.clip(jnp.searchsorted(out_start, opos, side="right")
                   .astype(jnp.int32) - 1, 0, byte_cap - 1)
    k = opos - out_start[src]  # 0..2 within the expansion
    sb = b[src]
    if charset == "US-ASCII":
        # U+FFFD = EF BF BD
        rep = jnp.asarray([0xEF, 0xBF, 0xBD], jnp.uint8)
        ch = jnp.where(sb < 0x80, sb, rep[jnp.clip(k, 0, 2)])
    else:
        ch = jnp.where(
            sb < 0x80, sb,
            jnp.where(k == 0,
                      jnp.uint8(0xC0) | (sb >> jnp.uint8(6)),
                      jnp.uint8(0x80) | (sb & jnp.uint8(0x3F))))
    out_use = opos < out_total
    data = jnp.where(out_use, ch, jnp.uint8(0))
    return StringColumn(data, offsets, col.validity, STRING)


def recast_bytes(col: StringColumn, dtype) -> StringColumn:
    """UTF-8 passthrough: same bytes, new logical type."""
    return StringColumn(col.data, col.offsets, col.validity, dtype)
