"""Device get_json_object: byte-parallel JSONPath extraction over the
(offsets, bytes) string layout.

Reference analog: GpuGetJsonObject.scala over the spark-rapids-jni CUDA
JSON scanner (reference sql-plugin/.../GpuGetJsonObject.scala). The TPU
formulation is scanner-free: instead of a per-row state machine it builds
whole-buffer structural masks with segment scans —

  1. escape parity (run length of backslashes) → which quotes are real;
  2. quote-count parity per row → in-string mask;
  3. segment cumsum of bracket deltas outside strings → nesting depth;

then walks the (static, literal) path by narrowing a per-row [lo, hi)
byte span: a `.field` step finds the first direct-child key at the right
depth whose text matches; an `[n]` step finds the n-th comma at element
depth. Every step is O(bytes) vectorized work, no data-dependent Python.

Semantics follow the host tier (expr/jsonexprs.py), with two documented
divergences on inputs the host's full parser treats differently:
  * scalar numbers return their RAW text (host re-renders via Python
    json: '1.00' → '1.0');
  * malformed documents are detected structurally (unbalanced brackets,
    unterminated strings); host rejects every non-RFC document.
Nested object/array results are compacted (whitespace outside strings
stripped) like the host's compact json.dumps rendering. Quoted string
results are unescaped, including \\uXXXX (with surrogate pairs).
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn, bucket_capacity
from .strings import (_rebuild_offsets, _row_of_byte, seg_incl_cumsum,
                      string_lengths)

# plain Python int, NOT a jnp constant: this module is imported
# lazily, sometimes inside a jit trace, and a traced-time jnp
# constant stored in a module global leaks the tracer into every
# later trace (UnexpectedTracerError). Weak promotion keeps the
# int32 arithmetic identical.
_BIG = 1 << 30
_WS = (0x20, 0x09, 0x0A, 0x0D)


def _u8(ch: str):
    return jnp.uint8(ord(ch))


def _is_ws(data):
    m = data == jnp.uint8(_WS[0])
    for w in _WS[1:]:
        m = m | (data == jnp.uint8(w))
    return m


def _cummax(x):
    return jax.lax.associative_scan(jnp.maximum, x)


_seg_incl_cumsum = seg_incl_cumsum


def _next_pos(mask, pos, byte_cap):
    """For each byte i: smallest j > i with mask[j] (BIG if none).
    A reverse inclusive min-scan, shifted to be exclusive."""
    cand = jnp.where(mask, pos, _BIG)
    rev_min = jnp.flip(jax.lax.associative_scan(jnp.minimum,
                                                jnp.flip(cand)))
    nxt = jnp.concatenate([rev_min[1:], jnp.full((1,), _BIG, jnp.int32)])
    return nxt


class JsonStructure:
    """Shared structural masks for one string column of JSON documents."""

    def __init__(self, col: StringColumn):
        self.col = col
        data = col.data
        byte_cap = col.byte_capacity
        pos = jnp.arange(byte_cap, dtype=jnp.int32)
        row = _row_of_byte(col, pos)
        row_start = col.offsets[row]
        in_use = pos < col.offsets[-1]

        # -- escape parity: a char is escaped iff preceded by an odd run
        # of backslashes (runs cannot cross row boundaries)
        bs = (data == _u8("\\")) & in_use
        stop = jnp.where(~bs, pos, jnp.int32(-1))
        last_stop = jnp.concatenate(
            [jnp.full((1,), -1, jnp.int32), _cummax(stop)[:-1]])
        last_stop = jnp.maximum(last_stop, row_start - 1)
        n_bs_before = (pos - 1) - last_stop  # length of backslash run
        escaped = (n_bs_before % 2) == 1

        quote_real = (data == _u8('"')) & ~escaped & in_use
        nq_before = _seg_incl_cumsum(quote_real.astype(jnp.int32),
                                     row_start) \
            - quote_real.astype(jnp.int32)
        in_string = (nq_before % 2) == 1     # excludes the opening quote
        # structural byte: outside strings entirely (quotes excluded too)
        structural = ~in_string & ~quote_real & in_use

        opens = ((data == _u8("{")) | (data == _u8("["))) & structural
        closes = ((data == _u8("}")) | (data == _u8("]"))) & structural
        delta = opens.astype(jnp.int32) - closes.astype(jnp.int32)
        depth_after = _seg_incl_cumsum(delta, row_start)
        depth_before = depth_after - delta

        ws = _is_ws(data)
        nonws = in_use & ~ws

        self.pos = pos
        self.row = row
        self.row_start = row_start
        self.in_use = in_use
        self.escaped = escaped
        self.quote_real = quote_real
        self.in_string = in_string
        self.structural = structural
        self.depth_after = depth_after
        self.depth_before = depth_before
        self.nonws = nonws
        self.next_quote = _next_pos(quote_real, pos, byte_cap)
        self.next_nonws = _next_pos(nonws, pos, byte_cap)

        cap = col.capacity
        lens = string_lengths(col)
        row_end = col.offsets[:-1] + lens  # (cap,) exclusive end
        # structural validity: depth never negative, ends at 0, strings
        # terminated (even quote count per row)
        neg = jax.ops.segment_min(
            jnp.where(in_use, depth_after, jnp.int32(0)), row,
            num_segments=cap)
        tot_delta = jax.ops.segment_sum(delta, row, num_segments=cap)
        tot_quotes = jax.ops.segment_sum(
            quote_real.astype(jnp.int32), row, num_segments=cap)
        self.doc_ok = col.validity & (lens > 0) & (neg >= 0) \
            & (tot_delta == 0) & ((tot_quotes % 2) == 0)
        self.row_end = row_end

    # -- per-row helpers ---------------------------------------------------
    def first_nonws_in(self, lo, hi):
        """(cap,) position of first non-ws byte in [lo, hi); BIG if none."""
        cand = jnp.where(self.nonws, self.pos, _BIG)
        # next_nonws at lo-1 == first nonws >= lo; handle lo==row_start
        start = jnp.clip(lo - 1, 0, self.col.byte_capacity - 1)
        at_lo = jnp.where(
            self.nonws[jnp.clip(lo, 0, self.col.byte_capacity - 1)] &
            (lo < hi), lo, self.next_nonws[start])
        return jnp.where(at_lo < hi, at_lo, _BIG)

    def last_nonws_in(self, lo, hi):
        """(cap,) position of last non-ws byte in [lo, hi); -1 if none."""
        cap = self.col.capacity
        m = self.nonws & (self.pos >= lo[self.row]) \
            & (self.pos < hi[self.row])
        return jax.ops.segment_max(
            jnp.where(m, self.pos, jnp.int32(-1)), self.row,
            num_segments=cap)


def json_extract(col: StringColumn,
                 steps: List[Union[str, int]]) -> StringColumn:
    """get_json_object for a literal non-wildcard path ('$' + steps)."""
    st = JsonStructure(col)
    cap = col.capacity
    byte_cap = col.byte_capacity
    data = col.data
    pos, row = st.pos, st.row

    # root span: whole document, ws-trimmed
    lo = st.first_nonws_in(col.offsets[:-1], st.row_end)
    last = st.last_nonws_in(col.offsets[:-1], st.row_end)
    hi = jnp.where(last >= 0, last + 1, jnp.int32(0))
    ok = st.doc_ok & (lo < _BIG)
    lo = jnp.clip(lo, 0, byte_cap - 1)

    for step in steps:
        at_lo = data[lo]
        d_elem = st.depth_before[lo] + 1
        # commas separating the container's direct children
        comma_m = (data == _u8(",")) & st.structural \
            & (st.depth_after == d_elem[row]) \
            & (pos > lo[row]) & (pos < hi[row])
        if isinstance(step, int):
            ok = ok & (at_lo == _u8("["))
            if step == 0:
                start = st.first_nonws_in(lo + 1, hi - 1)
                exists = start < _BIG
            else:
                # position of the step-th comma (1-based ranking)
                rank = _seg_incl_cumsum(comma_m.astype(jnp.int32),
                                        st.row_start)
                nth = jax.ops.segment_min(
                    jnp.where(comma_m & (rank == step), pos, _BIG), row,
                    num_segments=cap)
                start = st.first_nonws_in(jnp.clip(nth + 1, 0, byte_cap),
                                          hi - 1)
                exists = (nth < _BIG) & (start < _BIG)
            nxt = jax.ops.segment_min(
                jnp.where(comma_m & (pos >= start[row]), pos, _BIG), row,
                num_segments=cap)
            v_hi_raw = jnp.minimum(nxt, hi - 1)
            ok = ok & exists
            # an element must actually exist ([] has none): start byte may
            # not be the closing bracket
            ok = ok & (data[jnp.clip(start, 0, byte_cap - 1)] != _u8("]"))
        else:
            key = step.encode("utf-8")
            ok = ok & (at_lo == _u8("{"))
            # direct-child keys: real opening quotes at depth d_elem whose
            # string is followed (next nonws after closing quote) by ':'
            opening = st.quote_real & ~st.in_string \
                & (st.depth_after == d_elem[row]) \
                & (pos > lo[row]) & (pos < hi[row])
            closing = st.next_quote  # for an opening quote: its closer
            after = st.next_nonws[jnp.clip(closing, 0, byte_cap - 1)]
            is_key = opening & (closing < _BIG) \
                & (data[jnp.clip(after, 0, byte_cap - 1)] == _u8(":"))
            klen = closing - pos - 1
            match = is_key & (klen == len(key))
            for j, ch in enumerate(key):
                pj = jnp.clip(pos + 1 + j, 0, byte_cap - 1)
                match = match & (data[pj] == jnp.uint8(ch))
            q = jax.ops.segment_min(jnp.where(match, pos, _BIG), row,
                                    num_segments=cap)
            ok = ok & (q < _BIG)
            q = jnp.clip(q, 0, byte_cap - 1)
            colon = st.next_nonws[jnp.clip(st.next_quote[q], 0,
                                           byte_cap - 1)]
            start = st.first_nonws_in(jnp.clip(colon + 1, 0, byte_cap),
                                      hi - 1)
            ok = ok & (start < _BIG)
            nxt = jax.ops.segment_min(
                jnp.where(comma_m & (pos >= start[row]), pos, _BIG), row,
                num_segments=cap)
            v_hi_raw = jnp.minimum(nxt, hi - 1)
        # the value's span, ws-trimmed; containers end at their matching
        # close which is exactly the last nonws before the next separator
        start = jnp.clip(start, 0, byte_cap - 1)
        last = st.last_nonws_in(start, v_hi_raw)
        lo = start
        hi = jnp.where(last >= 0, last + 1, start)
        ok = ok & (last >= 0)

    return _render_spans(st, lo, hi, ok)


def _render_spans(st: JsonStructure, lo, hi, ok) -> StringColumn:
    """Emit the extracted spans: strings unquoted+unescaped, containers
    compacted (ws outside strings dropped), 'null' → NULL, scalars raw."""
    col = st.col
    cap = col.capacity
    byte_cap = col.byte_capacity
    data = col.data
    pos, row = st.pos, st.row

    first = data[jnp.clip(lo, 0, byte_cap - 1)]
    is_str = ok & (first == _u8('"'))
    is_container = ok & ((first == _u8("{")) | (first == _u8("[")))
    # null scalar → NULL (host: json null renders as SQL NULL)
    span_len = hi - lo
    is_null_lit = ok & (span_len == 4)
    for j, ch in enumerate(b"null"):
        pj = jnp.clip(lo + j, 0, byte_cap - 1)
        is_null_lit = is_null_lit & (data[pj] == jnp.uint8(ch))
    # 'null' inside a string value ("null") is a real string — first
    # byte is a quote there, so the literal test above cannot collide
    valid = ok & ~is_null_lit

    # effective span: strings drop the quotes
    eff_lo = jnp.where(is_str, lo + 1, lo)
    eff_hi = jnp.where(is_str, hi - 1, hi)

    in_span = (pos >= eff_lo[row]) & (pos < eff_hi[row]) & valid[row]

    # per-byte emit lengths
    emit = jnp.where(in_span, jnp.int32(1), jnp.int32(0))
    # containers: drop whitespace outside strings (compact rendering)
    ws_struct = _is_ws(data) & ~st.in_string & ~st.quote_real
    emit = jnp.where(in_span & is_container[row] & ws_struct, 0, emit)

    # strings: decode escapes. escape-start = backslash NOT itself escaped
    esc_start = in_span & is_str[row] & (data == _u8("\\")) & ~st.escaped
    nxt = jnp.clip(pos + 1, 0, byte_cap - 1)
    esc_ch = data[nxt]
    is_u = esc_ch == _u8("u")
    # \uXXXX: decode 4 hex digits
    cp = jnp.zeros((byte_cap,), jnp.int32)
    for j in range(4):
        pj = jnp.clip(pos + 2 + j, 0, byte_cap - 1)
        cp = cp * 16 + _hex_val(data[pj])
    is_hi_sur = is_u & (cp >= 0xD800) & (cp <= 0xDBFF)
    is_lo_sur = is_u & (cp >= 0xDC00) & (cp <= 0xDFFF)
    # a \uXXXX high surrogate immediately followed by a \uXXXX low
    # surrogate forms one astral codepoint (12 source bytes, 4 out)
    nxt_cp = _cp_at(data, jnp.clip(pos + 8, 0, byte_cap - 1), byte_cap)
    next_is_lo_esc = esc_start[jnp.clip(pos + 6, 0, byte_cap - 1)] \
        & (data[jnp.clip(pos + 7, 0, byte_cap - 1)] == _u8("u")) \
        & (nxt_cp >= 0xDC00) & (nxt_cp <= 0xDFFF)
    paired = is_hi_sur & next_is_lo_esc
    prev6 = jnp.clip(pos - 6, 0, byte_cap - 1)
    consumed_by_pair = esc_start & is_lo_sur & paired[prev6] \
        & esc_start[prev6]

    # emitted utf8 length per escape start; unpaired surrogates emit '?'
    u_len = jnp.where(cp < 0x80, 1, jnp.where(cp < 0x800, 2, 3))
    u_len = jnp.where(is_hi_sur | is_lo_sur, jnp.int32(1), u_len)
    u_len = jnp.where(paired, jnp.int32(4), u_len)
    esc_len = jnp.where(is_u, u_len, 1)

    esc_span = jnp.where(is_u, jnp.where(paired, 12, 6), 2)
    # zero out the bytes covered by an escape, then write the decoded
    # length at the escape start; coverage via a difference array
    # (+1 at start, -1 at start+span)
    starts = jnp.where(esc_start & ~consumed_by_pair & in_span,
                       esc_span, 0)
    diff = jnp.zeros((byte_cap + 1,), jnp.int32)
    s_idx = jnp.where(starts > 0, pos, byte_cap)
    e_idx = jnp.where(starts > 0,
                      jnp.clip(pos + starts, 0, byte_cap), byte_cap)
    diff = diff.at[s_idx].add(jnp.where(starts > 0, 1, 0), mode="drop")
    diff = diff.at[e_idx].add(jnp.where(starts > 0, -1, 0), mode="drop")
    covered = jnp.cumsum(diff[:-1]) > 0
    emit = jnp.where(covered & in_span & is_str[row], 0, emit)
    emit = jnp.where(esc_start & ~consumed_by_pair & in_span,
                     esc_len, emit)

    out_lens = jax.ops.segment_sum(emit, row, num_segments=cap)
    out_lens = jnp.where(valid, out_lens, 0)
    new_offsets = _rebuild_offsets(out_lens)
    out_byte_cap = byte_cap

    emit_start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(emit, dtype=jnp.int32)])
    opos = jnp.arange(out_byte_cap, dtype=jnp.int32)
    src = jnp.clip(jnp.searchsorted(emit_start, opos, side="right")
                   .astype(jnp.int32) - 1, 0, byte_cap - 1)
    k = opos - emit_start[src]
    out_in_use = opos < new_offsets[-1]

    # decoded bytes for escape positions
    plain = data[src]
    e_ch = data[jnp.clip(src + 1, 0, byte_cap - 1)]
    simple = _simple_escape_byte(e_ch)
    src_cp = cp[src]
    # low surrogate's codepoint lives 8 bytes after the high's start
    lo_cp = _cp_at(data, jnp.clip(src + 8, 0, byte_cap - 1), byte_cap)
    full_cp = jnp.where(paired[src],
                        0x10000 + ((src_cp - 0xD800) << 10)
                        + (lo_cp - 0xDC00),
                        src_cp)
    # unpaired surrogates render as '?'
    full_cp = jnp.where((is_hi_sur[src] | is_lo_sur[src]) & ~paired[src],
                        jnp.int32(ord("?")), full_cp)
    ub = _utf8_byte(full_cp, k)
    esc_out = jnp.where(e_ch == _u8("u"), ub, simple)
    byte = jnp.where(esc_start[src], esc_out, plain)
    out_data = jnp.where(out_in_use, byte, jnp.uint8(0))
    return StringColumn(out_data, new_offsets, valid, col.dtype)


def _hex_val(b):
    v = jnp.where((b >= _u8("0")) & (b <= _u8("9")),
                  b.astype(jnp.int32) - ord("0"), jnp.int32(0))
    v = jnp.where((b >= _u8("a")) & (b <= _u8("f")),
                  b.astype(jnp.int32) - ord("a") + 10, v)
    v = jnp.where((b >= _u8("A")) & (b <= _u8("F")),
                  b.astype(jnp.int32) - ord("A") + 10, v)
    return v


def _cp_at(data, at, byte_cap):
    cp = jnp.zeros(at.shape, jnp.int32)
    for j in range(4):
        pj = jnp.clip(at + j, 0, byte_cap - 1)
        cp = cp * 16 + _hex_val(data[pj])
    return cp


def _simple_escape_byte(e):
    out = e  # \" \\ \/ and any unknown escape: the char itself
    for c, r in ((b"b", 8), (b"f", 12), (b"n", 10), (b"r", 13), (b"t", 9)):
        out = jnp.where(e == jnp.uint8(c[0]), jnp.uint8(r), out)
    return out


def _utf8_byte(cp, k):
    """k-th UTF-8 byte of codepoint cp (cp < 0x110000)."""
    b1_1 = cp
    b2_1, b2_2 = 0xC0 | (cp >> 6), 0x80 | (cp & 0x3F)
    b3_1, b3_2, b3_3 = (0xE0 | (cp >> 12), 0x80 | ((cp >> 6) & 0x3F),
                        0x80 | (cp & 0x3F))
    b4 = (0xF0 | (cp >> 18), 0x80 | ((cp >> 12) & 0x3F),
          0x80 | ((cp >> 6) & 0x3F), 0x80 | (cp & 0x3F))
    is1 = cp < 0x80
    is2 = (cp >= 0x80) & (cp < 0x800)
    is3 = (cp >= 0x800) & (cp < 0x10000)
    b = jnp.where(is1, b1_1, 0)
    b = jnp.where(is2, jnp.where(k == 0, b2_1, b2_2), b)
    b = jnp.where(is3, jnp.select([k == 0, k == 1], [b3_1, b3_2], b3_3), b)
    b = jnp.where(~is1 & ~is2 & ~is3,
                  jnp.select([k == 0, k == 1, k == 2],
                             [b4[0], b4[1], b4[2]], b4[3]), b)
    return b.astype(jnp.uint8)
