"""Device base64 / hex codecs over the (offsets, bytes) layout.

Reference analog: GpuBase64/GpuUnBase64/GpuHex/GpuUnhex over cuDF string
kernels. Byte-parallel emit: every OUTPUT byte computes its source group
arithmetically — no per-row loops, one gather per output byte lane."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn, bucket_capacity
from ..types import BINARY, STRING
from .strings import _rebuild_offsets, _row_of_byte, string_lengths

_B64 = b"ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/"
_HEXU = b"0123456789ABCDEF"


def base64_encode(col: StringColumn) -> StringColumn:
    """base64(bin): 3 source bytes -> 4 output chars, '=' padded."""
    lens = string_lengths(col)
    out_lens = ((lens + 2) // 3) * 4
    new_off = _rebuild_offsets(jnp.where(col.validity, out_lens, 0))
    # worst case: ceil(len/3)*4 <= 4*len/3 + 4 per row
    out_cap = bucket_capacity(
        max((int(col.byte_capacity) * 4) // 3 + 4 * col.capacity, 1))
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, opos, side="right")
                   .astype(jnp.int32) - 1, 0, col.capacity - 1)
    j = opos - new_off[row]              # output position within row
    g, k = j // 4, j % 4                 # 4-char group, char index
    src0 = col.offsets[row] + 3 * g
    bcap = col.byte_capacity

    def byte_at(off):
        p = src0 + off
        ok = (3 * g + off) < lens[row]
        return jnp.where(ok, col.data[jnp.clip(p, 0, bcap - 1)],
                         jnp.uint8(0)), ok

    b0, ok0 = byte_at(0)
    b1, ok1 = byte_at(1)
    b2, ok2 = byte_at(2)
    b0i = b0.astype(jnp.int32)
    b1i = b1.astype(jnp.int32)
    b2i = b2.astype(jnp.int32)
    sextet = jnp.select(
        [k == 0, k == 1, k == 2],
        [b0i >> 2,
         ((b0i & 3) << 4) | (b1i >> 4),
         ((b1i & 15) << 2) | (b2i >> 6)],
        b2i & 63)
    table = jnp.asarray(bytearray(_B64), jnp.uint8)
    ch = table[jnp.clip(sextet, 0, 63)]
    # '=' padding: char 2 pads when byte1 absent; char 3 when byte2 absent
    pad = ((k == 2) & ~ok1) | ((k == 3) & ~ok2)
    ch = jnp.where(pad, jnp.uint8(ord("=")), ch)
    in_use = opos < new_off[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), new_off,
                        col.validity, STRING)


def _b64_val(b):
    v = jnp.full(b.shape, jnp.int32(-1))
    v = jnp.where((b >= ord("A")) & (b <= ord("Z")),
                  b.astype(jnp.int32) - ord("A"), v)
    v = jnp.where((b >= ord("a")) & (b <= ord("z")),
                  b.astype(jnp.int32) - ord("a") + 26, v)
    v = jnp.where((b >= ord("0")) & (b <= ord("9")),
                  b.astype(jnp.int32) - ord("0") + 52, v)
    v = jnp.where(b == ord("+"), jnp.int32(62), v)
    v = jnp.where(b == ord("/"), jnp.int32(63), v)
    return v


def base64_decode(col: StringColumn) -> StringColumn:
    """unbase64(str) -> BINARY; NULL on malformed input (non-alphabet
    chars, bad length, '=' anywhere but the tail — java.util.Base64
    semantics, matching the host tier)."""
    cap = col.capacity
    bcap = col.byte_capacity
    lens = string_lengths(col)
    data = col.data
    pos = jnp.arange(bcap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    intra = pos - col.offsets[row]
    in_use = pos < col.offsets[-1]

    is_pad = (data == jnp.uint8(ord("="))) & in_use
    val = _b64_val(data)
    # count trailing '=' (only 1 or 2 allowed, only at the very end)
    pad_cnt = jax.ops.segment_sum(is_pad.astype(jnp.int32), row,
                                  num_segments=cap)
    last_non_pad = jnp.maximum(jax.ops.segment_max(
        jnp.where(in_use & ~is_pad, intra, -1), row, num_segments=cap),
        -1)  # empty rows: segment_max identity is INT32_MIN
    pads_at_tail = last_non_pad + 1 + pad_cnt == lens
    bad_char = jax.ops.segment_max(
        (in_use & ~is_pad & (val < 0)).astype(jnp.int32), row,
        num_segments=cap) > 0
    # lenient tail (Spark UnBase64 / the host tier, which pads up before
    # decoding): a final group of 2 or 3 data chars decodes with ANY
    # number of trailing '=' (0..2); 1 leftover data char is malformed
    rem = (lens - pad_cnt) % 4
    ok = col.validity & (rem != 1) & (pad_cnt <= 2) & pads_at_tail \
        & ~bad_char
    n_data = lens - pad_cnt
    out_lens = jnp.where(ok, (n_data * 3) // 4, 0)
    # 4 chars -> 3 bytes exactly when unpadded; padding drops 1-2 bytes
    new_off = _rebuild_offsets(out_lens)
    out_cap = bucket_capacity(max(int(bcap), 1))
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    orow = jnp.clip(jnp.searchsorted(new_off, opos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    j = opos - new_off[orow]
    g, k = j // 3, j % 3
    src0 = col.offsets[orow] + 4 * g

    def v_at(off):
        p = jnp.clip(src0 + off, 0, bcap - 1)
        return jnp.clip(_b64_val(data[p]), 0, 63)

    v0, v1, v2, v3 = v_at(0), v_at(1), v_at(2), v_at(3)
    byte = jnp.select(
        [k == 0, k == 1],
        [(v0 << 2) | (v1 >> 4),
         ((v1 & 15) << 4) | (v2 >> 2)],
        ((v2 & 3) << 6) | v3)
    in_use_o = opos < new_off[-1]
    return StringColumn(
        jnp.where(in_use_o, byte.astype(jnp.uint8), jnp.uint8(0)),
        new_off, ok, BINARY)


def hex_encode(col: StringColumn) -> StringColumn:
    """hex(str/bin): two uppercase hex chars per byte."""
    lens = string_lengths(col)
    new_off = _rebuild_offsets(jnp.where(col.validity, lens * 2, 0))
    out_cap = bucket_capacity(max(int(col.byte_capacity) * 2, 1))
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, opos, side="right")
                   .astype(jnp.int32) - 1, 0, col.capacity - 1)
    j = opos - new_off[row]
    src = jnp.clip(col.offsets[row] + j // 2, 0, col.byte_capacity - 1)
    b = col.data[src].astype(jnp.int32)
    nib = jnp.where(j % 2 == 0, b >> 4, b & 15)
    table = jnp.asarray(bytearray(_HEXU), jnp.uint8)
    ch = table[jnp.clip(nib, 0, 15)]
    in_use = opos < new_off[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), new_off,
                        col.validity, STRING)


def hex_encode_long(col: Column) -> StringColumn:
    """hex(long): minimal-width uppercase hex of the UNSIGNED 64-bit
    pattern (Spark: hex(-1) = 'FFFFFFFFFFFFFFFF')."""
    cap = col.capacity
    u = col.data.astype(jnp.uint64)
    # number of hex digits: 16 - leading_zero_nibbles, min 1
    ndig = jnp.ones((cap,), jnp.int32)
    for d in range(2, 17):
        ndig = jnp.where(u >= (jnp.uint64(1) << jnp.uint64(4 * (d - 1))),
                         d, ndig)
    new_off = _rebuild_offsets(jnp.where(col.validity, ndig, 0))
    out_cap = bucket_capacity(max(cap * 16, 1))
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, opos, side="right")
                   .astype(jnp.int32) - 1, 0, cap - 1)
    j = opos - new_off[row]
    shift = (ndig[row] - 1 - j) * 4
    nib = (u[row] >> jnp.clip(shift, 0, 63).astype(jnp.uint64)) \
        & jnp.uint64(15)
    table = jnp.asarray(bytearray(_HEXU), jnp.uint8)
    ch = table[nib.astype(jnp.int32)]
    in_use = opos < new_off[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), new_off,
                        col.validity, STRING)


from .strings import hex_digit_val as _hex_val  # noqa: E402


def hex_decode(col: StringColumn) -> StringColumn:
    """unhex(str) -> BINARY; odd length gets an implicit leading 0;
    NULL on any non-hex character (Spark semantics)."""
    cap = col.capacity
    bcap = col.byte_capacity
    lens = string_lengths(col)
    data = col.data
    pos = jnp.arange(bcap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    in_use = pos < col.offsets[-1]
    bad = jax.ops.segment_max(
        (in_use & (_hex_val(data) < 0)).astype(jnp.int32), row,
        num_segments=cap) > 0
    ok = col.validity & ~bad
    out_lens = jnp.where(ok, (lens + 1) // 2, 0)
    new_off = _rebuild_offsets(out_lens)
    out_cap = bucket_capacity(max(int(bcap), 1))
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    orow = jnp.clip(jnp.searchsorted(new_off, opos, side="right")
                    .astype(jnp.int32) - 1, 0, cap - 1)
    j = opos - new_off[orow]
    odd = (lens[orow] % 2) == 1
    # source char indices for output byte j: (2j-1, 2j) when odd (char -1
    # is the implicit leading 0), else (2j, 2j+1)
    i_hi = jnp.where(odd, 2 * j - 1, 2 * j)
    i_lo = jnp.where(odd, 2 * j, 2 * j + 1)
    base = col.offsets[orow]
    hi = jnp.where(i_hi >= 0,
                   jnp.clip(_hex_val(
                       data[jnp.clip(base + i_hi, 0, bcap - 1)]), 0, 15),
                   0)
    lo = jnp.clip(_hex_val(
        data[jnp.clip(base + i_lo, 0, bcap - 1)]), 0, 15)
    byte = ((hi << 4) | lo).astype(jnp.uint8)
    in_use_o = opos < new_off[-1]
    return StringColumn(jnp.where(in_use_o, byte, jnp.uint8(0)),
                        new_off, ok, BINARY)
