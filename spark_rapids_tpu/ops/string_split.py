"""Device kernels for the delimiter-driven string family:
find_in_set, substring_index, split (literal patterns).

Reference analogs: GpuSubstringIndex / GpuStringSplit / find_in_set in
stringFunctions.scala over cuDF string kernels. The TPU formulation is
byte-parallel over the (offsets, bytes) layout: delimiter occurrences are
a byte mask (greedy non-overlapping via ops/strings.select_literal_hits),
per-row ordinal ranks come from segment cumsums, and outputs are emitted
with the same searchsorted-gather used by every other varlen kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import (ArrayColumn, Column, StringColumn,
                               bucket_capacity)
from ..types import INT, STRING, ArrayType
from .strings import (_rebuild_offsets, _row_of_byte, _substring_gather,
                      seg_incl_cumsum as _seg_incl_cumsum,
                      select_literal_hits, string_lengths)

# plain Python int, NOT a jnp constant: this module is imported
# lazily, sometimes inside a jit trace, and a traced-time jnp
# constant stored in a module global leaks the tracer into every
# later trace (UnexpectedTracerError). Weak promotion keeps the
# int32 arithmetic identical.
_BIG = 1 << 30


def find_in_set(needle: StringColumn, sets: StringColumn) -> Column:
    """1-based index of `needle` among the comma-separated elements of
    `sets`; 0 when absent or when the needle contains a comma."""
    cap = sets.capacity
    byte_cap = sets.byte_capacity
    data = sets.data
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(sets, pos)
    row_start = sets.offsets[row]
    row_end = sets.offsets[row + 1]
    in_use = pos < sets.offsets[-1]

    nlen = string_lengths(needle)
    nstart = needle.offsets[:-1]
    set_len = string_lengths(sets)

    comma = (data == jnp.uint8(ord(","))) & in_use
    # element index of each byte = #commas before it in the row
    n_comma_incl = _seg_incl_cumsum(comma.astype(jnp.int32), row_start)
    elem_idx = n_comma_incl - comma.astype(jnp.int32)
    # start of the element owning each byte
    last_comma = jax.lax.associative_scan(
        jnp.maximum, jnp.where(comma, pos, jnp.int32(-1)))
    last_comma = jnp.concatenate(
        [jnp.full((1,), -1, jnp.int32), last_comma[:-1]])
    elem_start = jnp.maximum(row_start, last_comma + 1)
    off = pos - elem_start

    # per-byte compare against the row's needle at the same offset
    np_idx = jnp.clip(nstart[row] + off, 0, needle.byte_capacity - 1)
    nb = needle.data[np_idx]
    in_nlen = off < nlen[row]
    bad = in_use & ~comma & (~in_nlen | (data != nb))
    bad_csum = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(bad.astype(jnp.int32))])

    # element starts: first byte of row, or the byte after a comma (which
    # for an empty element is the next comma itself)
    prev = jnp.clip(pos - 1, 0, byte_cap - 1)
    es = in_use & ((pos == row_start) | (comma[prev] & (pos > row_start)))
    next_comma = jnp.flip(jax.lax.associative_scan(
        jnp.minimum, jnp.flip(jnp.where(comma, pos, _BIG))))
    elem_end = jnp.minimum(next_comma, row_end)
    elen = elem_end - pos
    ok = es & (elen == nlen[row]) \
        & (bad_csum[jnp.clip(elem_end, 0, byte_cap)]
           - bad_csum[jnp.clip(pos, 0, byte_cap)] == 0)
    best = jax.ops.segment_min(jnp.where(ok, elem_idx, _BIG), row,
                               num_segments=cap)

    # trailing empty element ("a," has elements a and ''): exists when the
    # row ends with a comma; its index is the row's comma count
    lastb = jnp.clip(sets.offsets[1:] - 1, 0, byte_cap - 1)
    ends_comma = (set_len > 0) & (sets.data[lastb] == jnp.uint8(ord(",")))
    commas_per_row = jax.ops.segment_sum(comma.astype(jnp.int32),
                                         row, num_segments=cap)
    best = jnp.where((nlen == 0) & ends_comma,
                     jnp.minimum(best, commas_per_row), best)
    # empty set string holds exactly one empty element
    best = jnp.where((set_len == 0) & (nlen == 0), jnp.int32(0), best)

    res = jnp.where(best < _BIG, best + 1, jnp.int32(0)).astype(jnp.int32)
    valid = needle.validity & sets.validity
    return Column(jnp.where(valid, res, 0), valid, INT)


def substring_index(col: StringColumn, delim: bytes,
                    count: int) -> StringColumn:
    """substring_index(str, delim, count): prefix before the count-th
    delimiter (count > 0) / suffix after the |count|-th-from-last
    (count < 0); the whole string when there are not enough delimiters."""
    cap = col.capacity
    byte_cap = col.byte_capacity
    if not delim or count == 0:
        lens = jnp.zeros((cap,), jnp.int32)
        return _substring_gather(col, col.offsets[:-1], lens)
    ld = len(delim)
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    row_start = col.offsets[row]
    sel = select_literal_hits(col, delim)
    rank = _seg_incl_cumsum(sel.astype(jnp.int32), row_start)  # 1-based
    m = jax.ops.segment_sum(sel.astype(jnp.int32), row, num_segments=cap)
    starts = col.offsets[:-1]
    lens = string_lengths(col)
    if count > 0:
        cut = jax.ops.segment_min(
            jnp.where(sel & (rank == count), pos, _BIG), row,
            num_segments=cap)
        out_start = starts
        out_len = jnp.where(m >= count, cut - starts, lens)
    else:
        want = m + count + 1  # 1-based rank of the delimiter to cut AFTER
        cut = jax.ops.segment_min(
            jnp.where(sel & (rank == want[row]), pos, _BIG), row,
            num_segments=cap)
        enough = m >= -count
        out_start = jnp.where(enough, jnp.clip(cut + ld, 0, byte_cap),
                              starts)
        out_len = jnp.where(enough, col.offsets[1:] - out_start, lens)
    return _substring_gather(col, out_start.astype(jnp.int32),
                             out_len.astype(jnp.int32))


def split_literal(col: StringColumn, delim: bytes,
                  limit: int) -> ArrayColumn:
    """split(str, delim, limit) for a literal delimiter — Java semantics:
    limit > 0 caps the part count; limit == 0 strips trailing empty parts;
    negative limits keep everything."""
    cap = col.capacity
    byte_cap = col.byte_capacity
    out_t = ArrayType(STRING)
    lens = string_lengths(col)

    if not delim or limit == 1:
        # no splitting: every row becomes the 1-element array [s]; child
        # row i IS source row i so offsets are the identity ramp
        arr_off = jnp.arange(cap + 1, dtype=jnp.int32)
        child = StringColumn(col.data, col.offsets,
                             col.validity, col.dtype)
        return ArrayColumn(child, arr_off, col.validity, out_t)

    ld = len(delim)
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = _row_of_byte(col, pos)
    row_start = col.offsets[row]
    sel = select_literal_hits(col, delim) & col.validity[row]
    rank = _seg_incl_cumsum(sel.astype(jnp.int32), row_start)  # 1-based
    if limit > 0:
        sel = sel & (rank <= limit - 1)
        rank = jnp.minimum(rank, limit - 1)
    m = jax.ops.segment_sum(sel.astype(jnp.int32), row, num_segments=cap)
    n_parts = jnp.where(col.validity, m + 1, 0).astype(jnp.int32)

    # provisional array offsets (before trailing-empty stripping)
    arr_off = _rebuild_offsets(n_parts)
    total_parts = arr_off[cap]
    part_cap = byte_cap + cap  # m+1 parts per row, m <= bytes in row

    # per-part start/end byte positions (absolute), scattered by part id
    p_start = jnp.zeros((part_cap,), jnp.int32)
    p_end = jnp.zeros((part_cap,), jnp.int32)
    # part k (k >= 1) starts after the k-th delimiter
    gid_for_hit = jnp.where(sel, arr_off[row] + rank, part_cap)
    p_start = p_start.at[gid_for_hit].set(pos + ld, mode="drop")
    # part k-1 ends at the k-th delimiter start
    gid_prev = jnp.where(sel, arr_off[row] + rank - 1, part_cap)
    p_end = p_end.at[gid_prev].set(pos, mode="drop")
    # part 0 starts at row start; last part ends at row end
    first_gid = jnp.where(col.validity, arr_off[:-1], part_cap)
    p_start = p_start.at[first_gid].set(col.offsets[:-1], mode="drop")
    last_gid = jnp.where(col.validity, arr_off[:-1] + m, part_cap)
    p_end = p_end.at[last_gid].set(col.offsets[1:], mode="drop")

    p_len = jnp.maximum(p_end - p_start, 0)

    if limit == 0:
        # strip trailing empty parts per row
        pids = jnp.arange(part_cap, dtype=jnp.int32)
        prow = jnp.searchsorted(arr_off, pids,
                                side="right").astype(jnp.int32) - 1
        prow = jnp.clip(prow, 0, cap - 1)
        pidx = pids - arr_off[prow]
        in_parts = pids < total_parts
        nonempty = in_parts & (p_len > 0)
        last_ne = jax.ops.segment_max(
            jnp.where(nonempty, pidx, jnp.int32(-1)), prow,
            num_segments=cap)
        n_parts = jnp.where(col.validity, last_ne + 1, 0).astype(jnp.int32)
        # re-pack: parts keep their gid ordering, rows just shorten, so
        # rebuild offsets and gather part info through old gids
        new_off = _rebuild_offsets(n_parts)
        newp = jnp.arange(part_cap, dtype=jnp.int32)
        nrow = jnp.searchsorted(new_off, newp,
                                side="right").astype(jnp.int32) - 1
        nrow = jnp.clip(nrow, 0, cap - 1)
        old_gid = jnp.clip(arr_off[nrow] + (newp - new_off[nrow]), 0,
                           part_cap - 1)
        p_start = p_start[old_gid]
        p_len = p_len[old_gid]
        arr_off = new_off
        total_parts = arr_off[cap]

    # child string column: emit part bytes in gid order
    child_off = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(p_len, dtype=jnp.int32)])[: part_cap + 1]
    opos = jnp.arange(byte_cap, dtype=jnp.int32)
    src_part = jnp.clip(jnp.searchsorted(child_off, opos, side="right")
                        .astype(jnp.int32) - 1, 0, part_cap - 1)
    intra = opos - child_off[src_part]
    src = jnp.clip(p_start[src_part] + intra, 0, byte_cap - 1)
    child_in_use = opos < child_off[jnp.clip(total_parts, 0, part_cap)]
    cdata = jnp.where(child_in_use, col.data[src], jnp.uint8(0))

    # child columns are sized by bucket: part_cap entries of offsets
    ccap = bucket_capacity(max(part_cap, 1))
    c_off = jnp.zeros((ccap + 1,), jnp.int32)
    c_off = c_off.at[: part_cap + 1].set(child_off)
    total_bytes = child_off[jnp.clip(total_parts, 0, part_cap)]
    c_off = jnp.where(jnp.arange(ccap + 1, dtype=jnp.int32) > total_parts,
                      total_bytes, c_off)
    c_valid = jnp.arange(ccap, dtype=jnp.int32) < total_parts
    child = StringColumn(cdata, c_off, c_valid, STRING)
    return ArrayColumn(child, arr_off, col.validity, out_t)
