"""Device parse_url: byte-parallel URL component spans.

Reference analog: GpuParseUrl.scala over the spark-rapids-jni ParseURI
CUDA kernel. The host tier (expr/urlexprs.py) delegates to Python's
urlparse; this kernel reproduces that behavior byte-parallel for
well-formed URLs: per-row delimiter positions come from segment-min
reductions, components are span arithmetic over those positions, and
extraction is the usual emit/gather. Exotic inputs (scheme-less strings
with stray delimiters, %-encoded QUERY KEYS) may diverge from urlparse's
full grammar; the differential test pins the realistic corpus.

Part semantics (matching the host tier exactly where supported):
  PROTOCOL  scheme, lowercased, None when absent
  AUTHORITY raw netloc, None when absent/empty
  USERINFO  netloc before the last '@', None when no '@'
  HOST      hostname: after last '@', port stripped, brackets stripped,
            lowercased, None when empty
  PATH      path ('' when empty — never None)
  QUERY     raw query, None when absent/empty; with a key: the FIRST
            matching key's value, %XX and '+' decoded
  REF       fragment, None when absent/empty
  FILE      path + '?' + query (raw)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import StringColumn, bucket_capacity
from ..types import STRING
from .strings import _rebuild_offsets, _row_of_byte, string_lengths

# plain Python int, NOT a jnp constant: this module is imported
# lazily, sometimes inside a jit trace, and a traced-time jnp
# constant stored in a module global leaks the tracer into every
# later trace (UnexpectedTracerError). Weak promotion keeps the
# int32 arithmetic identical.
_BIG = 1 << 30


def _u8(ch):
    return jnp.uint8(ord(ch))


class _UrlSpans:
    """Per-row component spans for one URL column."""

    def __init__(self, col: StringColumn):
        cap = col.capacity
        bcap = col.byte_capacity
        data = col.data
        pos = jnp.arange(bcap, dtype=jnp.int32)
        row = _row_of_byte(col, pos)
        start = col.offsets[:-1]
        end = col.offsets[1:]
        in_use = pos < col.offsets[-1]

        def first_of(mask, lo=None, hi=None):
            m = mask & in_use
            if lo is not None:
                m = m & (pos >= lo[row])
            if hi is not None:
                m = m & (pos < hi[row])
            return jax.ops.segment_min(jnp.where(m, pos, _BIG), row,
                                       num_segments=cap)

        def last_of(mask, lo=None, hi=None):
            m = mask & in_use
            if lo is not None:
                m = m & (pos >= lo[row])
            if hi is not None:
                m = m & (pos < hi[row])
            return jax.ops.segment_max(jnp.where(m, pos, jnp.int32(-1)),
                                       row, num_segments=cap)

        is_hash = data == _u8("#")
        hash_pos = first_of(is_hash)
        pre_frag_end = jnp.minimum(hash_pos, end)

        is_q = data == _u8("?")
        q_pos = first_of(is_q, hi=pre_frag_end)

        # scheme: first ':' strictly before any '/', '?', '#', with a
        # leading alpha and only scheme chars before it
        is_colon = data == _u8(":")
        is_slash = data == _u8("/")
        colon = first_of(is_colon)
        slash = first_of(is_slash)
        b = data
        alpha = ((b >= _u8("a")) & (b <= _u8("z"))) | \
            ((b >= _u8("A")) & (b <= _u8("Z")))
        digit = (b >= _u8("0")) & (b <= _u8("9"))
        scheme_char = alpha | digit | (b == _u8("+")) | (b == _u8("-")) \
            | (b == _u8("."))
        bad = in_use & ~scheme_char
        bad_csum = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(bad.astype(jnp.int32))])
        first_b = b[jnp.clip(start, 0, bcap - 1)]
        first_alpha = ((first_b >= _u8("a")) & (first_b <= _u8("z"))) | \
            ((first_b >= _u8("A")) & (first_b <= _u8("Z")))
        ccl = jnp.clip(colon, 0, bcap)
        scheme_clean = (bad_csum[ccl] - bad_csum[jnp.clip(start, 0, bcap)]
                        ) == 0
        has_scheme = (colon < jnp.minimum(jnp.minimum(slash, q_pos),
                                          hash_pos)) \
            & (colon > start) & first_alpha & scheme_clean \
            & (string_lengths(col) > 0)
        after_scheme = jnp.where(has_scheme, colon + 1, start)

        # netloc: '//' immediately after the scheme (or at the start)
        a0 = b[jnp.clip(after_scheme, 0, bcap - 1)]
        a1 = b[jnp.clip(after_scheme + 1, 0, bcap - 1)]
        has_netloc = (a0 == _u8("/")) & (a1 == _u8("/")) \
            & (after_scheme + 1 < pre_frag_end)
        net_start = jnp.where(has_netloc, after_scheme + 2, after_scheme)
        net_end_cand = first_of(is_slash, lo=net_start, hi=pre_frag_end)
        net_end = jnp.minimum(jnp.minimum(net_end_cand, q_pos),
                              pre_frag_end)
        net_end = jnp.where(has_netloc, net_end, net_start)

        path_start = jnp.where(has_netloc, net_end, after_scheme)
        path_end = jnp.minimum(q_pos, pre_frag_end)

        at = last_of(b == _u8("@"), lo=net_start, hi=net_end)
        has_at = at >= 0
        host_start = jnp.where(has_at, at + 1, net_start)
        hb = b[jnp.clip(host_start, 0, bcap - 1)]
        bracketed = hb == _u8("[")
        rb = first_of(b == _u8("]"), lo=host_start, hi=net_end)
        # port: last ':' after the host part (and after any ']')
        port_colon = last_of(is_colon,
                             lo=jnp.where(bracketed, rb, host_start),
                             hi=net_end)
        host_end = jnp.where(port_colon >= 0, port_colon, net_end)
        # brackets stripped from the reported hostname
        h_lo = jnp.where(bracketed, host_start + 1, host_start)
        h_hi = jnp.where(bracketed & (rb < _BIG), rb, host_end)

        self.col = col
        self.row = row
        self.pos = pos
        self.in_use = in_use
        self.start, self.end = start, end
        self.has_scheme = has_scheme
        self.scheme_span = (start, jnp.where(has_scheme, colon, start))
        self.has_netloc = has_netloc
        self.netloc_span = (net_start, net_end)
        self.has_at = has_at
        self.userinfo_span = (net_start,
                              jnp.where(has_at, at, net_start))
        self.host_span = (h_lo, h_hi)
        self.path_span = (path_start, jnp.maximum(path_end, path_start))
        self.has_q = q_pos < _BIG
        self.query_span = (jnp.where(self.has_q, q_pos + 1, start),
                           jnp.where(self.has_q, pre_frag_end, start))
        self.has_frag = hash_pos < _BIG
        self.ref_span = (jnp.where(self.has_frag, hash_pos + 1, start),
                         jnp.where(self.has_frag, end, start))
        # FILE drops a trailing '?' when the query is empty (urlparse:
        # path + ('?' + query if query else ''))
        q_empty = self.has_q & (pre_frag_end == q_pos + 1)
        file_end = jnp.where(q_empty, q_pos, pre_frag_end)
        self.file_span = (path_start, jnp.maximum(file_end, path_start))


def _extract(col: StringColumn, lo, hi, valid, lowercase=False
             ) -> StringColumn:
    from .strings import _substring_gather
    lens = jnp.where(valid, jnp.maximum(hi - lo, 0), 0)
    out = _substring_gather(col, lo.astype(jnp.int32),
                            lens.astype(jnp.int32))
    data = out.data
    if lowercase:
        up = (data >= _u8("A")) & (data <= _u8("Z"))
        data = jnp.where(up, data + jnp.uint8(32), data)
    return StringColumn(data, out.offsets, valid & col.validity, STRING)


def parse_url(col: StringColumn, part: str, key=None) -> StringColumn:
    s = _UrlSpans(col)
    v = col.validity
    if part == "PROTOCOL":
        return _extract(col, *s.scheme_span, v & s.has_scheme,
                        lowercase=True)
    if part == "AUTHORITY":
        lo, hi = s.netloc_span
        return _extract(col, lo, hi, v & s.has_netloc & (hi > lo))
    if part == "USERINFO":
        return _extract(col, *s.userinfo_span, v & s.has_at)
    if part == "HOST":
        lo, hi = s.host_span
        return _extract(col, lo, hi, v & s.has_netloc & (hi > lo),
                        lowercase=True)
    if part == "PATH":
        return _extract(col, *s.path_span, v)
    if part == "REF":
        lo, hi = s.ref_span
        return _extract(col, lo, hi, v & s.has_frag & (hi > lo))
    if part == "FILE":
        return _extract(col, *s.file_span, v)
    if part == "QUERY" and key is None:
        lo, hi = s.query_span
        return _extract(col, lo, hi, v & s.has_q & (hi > lo))
    if part == "QUERY":
        return _query_value(col, s, key)
    # unknown part name: all NULL (Spark is case-sensitive here);
    # keep the standard capacity buckets so downstream programs reuse
    # their compiled shapes
    zero = jnp.zeros((col.capacity,), jnp.bool_)
    return StringColumn(jnp.zeros(bucket_capacity(1), jnp.uint8),
                        jnp.zeros((col.capacity + 1,), jnp.int32),
                        zero, STRING)


def _query_value(col: StringColumn, s: _UrlSpans, key: str
                 ) -> StringColumn:
    """First value whose key matches `key` exactly (raw bytes), with
    %XX and '+' decoding applied to the VALUE (parse_qs semantics)."""
    cap = col.capacity
    bcap = col.byte_capacity
    data = col.data
    pos, row = s.pos, s.row
    q_lo, q_hi = s.query_span
    in_q = s.in_use & (pos >= q_lo[row]) & (pos < q_hi[row])
    amp = (data == _u8("&")) & in_q
    # pair starts: query start or the byte after '&'
    prev = jnp.clip(pos - 1, 0, bcap - 1)
    ps = in_q & ((pos == q_lo[row]) | amp[prev])
    next_amp = jnp.flip(jax.lax.associative_scan(
        jnp.minimum, jnp.flip(jnp.where(amp, pos, _BIG))))
    pair_end = jnp.minimum(next_amp, q_hi[row])
    eq = (data == _u8("=")) & in_q
    next_eq = jnp.flip(jax.lax.associative_scan(
        jnp.minimum, jnp.flip(jnp.where(eq, pos, _BIG))))
    # '=' belonging to this pair (parse_qs splits once on the first '=')
    key_end = jnp.minimum(next_eq, pair_end)

    kb = key.encode("utf-8")
    klen_ok = (key_end - pos) == len(kb)
    match = ps & klen_ok
    for j, ch in enumerate(kb):
        pj = jnp.clip(pos + j, 0, bcap - 1)
        match = match & (data[pj] == jnp.uint8(ch))
    first = jax.ops.segment_min(jnp.where(match, pos, _BIG), row,
                                num_segments=cap)
    has = (first < _BIG) & s.has_q & col.validity
    firstc = jnp.clip(first, 0, bcap - 1)
    # value span: after '=' when present, else empty ('a' -> '')
    ke = key_end[firstc]
    pe = pair_end[firstc]
    v_lo = jnp.where(ke < pe, ke + 1, pe)
    v_hi = pe

    # emit with %XX / '+' decoding
    in_val = s.in_use & (pos >= v_lo[row]) & (pos < v_hi[row]) & has[row]
    is_pct = in_val & (data == _u8("%"))
    h1 = _hexv(data[jnp.clip(pos + 1, 0, bcap - 1)])
    h2 = _hexv(data[jnp.clip(pos + 2, 0, bcap - 1)])
    pct_ok = is_pct & (h1 >= 0) & (h2 >= 0) & (pos + 2 < v_hi[row])
    # bytes covered by a valid escape emit 0; the '%' emits the byte
    covered = jnp.zeros((bcap,), jnp.bool_)
    for back in (1, 2):
        pb = jnp.clip(pos - back, 0, bcap - 1)
        covered = covered | (pct_ok[pb] & in_val)
    emit = jnp.where(in_val & ~covered, jnp.int32(1), 0)
    out_lens = jax.ops.segment_sum(emit, row, num_segments=cap)
    out_lens = jnp.where(has, out_lens, 0)
    new_off = _rebuild_offsets(out_lens)
    out_cap = bucket_capacity(max(int(bcap), 1))
    emit_start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(emit, dtype=jnp.int32)])
    opos = jnp.arange(out_cap, dtype=jnp.int32)
    src = jnp.clip(jnp.searchsorted(emit_start, opos, side="right")
                   .astype(jnp.int32) - 1, 0, bcap - 1)
    plus = data[src] == _u8("+")
    dec = (_hexv(data[jnp.clip(src + 1, 0, bcap - 1)]) * 16
           + _hexv(data[jnp.clip(src + 2, 0, bcap - 1)]))
    byte = jnp.where(pct_ok[src], jnp.clip(dec, 0, 255).astype(jnp.uint8),
                     jnp.where(plus, _u8(" "), data[src]))
    in_use_o = opos < new_off[-1]
    return StringColumn(jnp.where(in_use_o, byte, jnp.uint8(0)),
                        new_off, has, STRING)


from .strings import hex_digit_val as _hexv  # noqa: E402
