"""Device higher-order functions over array columns.

Reference analog: higherOrderFunctions.scala (GpuArrayTransform etc.)
over cuDF segmented kernels. The TPU formulation exploits the
offsets+child layout directly: a lambda over elements is just the body
expression evaluated on the CHILD column (one flat vectorized pass over
all elements of all rows), and per-row reductions (exists/forall) are
segment reductions keyed by each element's owning row.

Scope: lambda bodies whose leaves are the lambda variable and literals
(no outer-row column references — those need per-element row broadcast
and stay on the host tier; the planner tags them via device_supported).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import ArrayColumn, Column, StringColumn
from ..types import BOOLEAN, ArrayType

# plain Python int, NOT a jnp constant: this module is imported
# lazily, sometimes inside a jit trace, and a traced-time jnp
# constant stored in a module global leaks the tracer into every
# later trace (UnexpectedTracerError). Weak promotion keeps the
# int32 arithmetic identical.
_BIG = 1 << 30


class _ElemBatch:
    """Minimal batch facade for evaluating a lambda body over the child
    column: expressions only touch num_rows/capacity here."""

    def __init__(self, num_rows, capacity: int):
        self.num_rows = num_rows
        self.capacity = capacity


def _elem_row_map(arr: ArrayColumn):
    """(child_capacity,) int32: owning ROW of each child element, and the
    in-use mask of child elements."""
    ccap = arr.child.capacity
    epos = jnp.arange(ccap, dtype=jnp.int32)
    erow = jnp.searchsorted(arr.offsets, epos,
                            side="right").astype(jnp.int32) - 1
    erow = jnp.clip(erow, 0, arr.capacity - 1)
    in_use = epos < arr.offsets[arr.capacity]
    return erow, in_use


def eval_lambda(body, var: str, arr: ArrayColumn) -> Column:
    """Evaluate `body` (over LambdaVar `var`) elementwise on the child."""
    from ..expr.collectionexprs import LambdaVar

    child = arr.child
    bound_holder = _BoundElem(child)

    def fn(node):
        if isinstance(node, LambdaVar) and node.name == var:
            return bound_holder
        return node

    bound = body.transform_up(fn)
    n_elems = arr.offsets[arr.capacity]
    return bound.columnar_eval(_ElemBatch(n_elems, child.capacity))


class _BoundElem:
    """Expression leaf yielding the child column (the bound lambda var)."""

    children = ()

    def __init__(self, col: Column):
        self._col = col

    def with_children(self, cs):
        return self

    def transform_up(self, fn):
        return fn(self)

    @property
    def data_type(self):
        return self._col.dtype

    def columnar_eval(self, batch):
        return self._col

    def semantic_key(self):
        return ("_BoundElem", id(self._col))


def array_transform(arr: ArrayColumn, body, var: str) -> ArrayColumn:
    out = eval_lambda(body, var, arr)
    return ArrayColumn(out, arr.offsets, arr.validity,
                       ArrayType(out.dtype))


def array_filter(arr: ArrayColumn, body, var: str) -> ArrayColumn:
    pred = eval_lambda(body, var, arr)
    erow, in_use = _elem_row_map(arr)
    keep = pred.data & pred.validity & in_use  # Spark: only TRUE keeps
    ccap = arr.child.capacity

    # new element counts per row -> new offsets
    counts = jax.ops.segment_sum(keep.astype(jnp.int32), erow,
                                 num_segments=arr.capacity)
    counts = jnp.where(arr.validity, counts, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(counts, dtype=jnp.int32)])
    # compaction gather: kept element k (in order) -> its source index
    kpos = jnp.cumsum(keep.astype(jnp.int32)) - 1   # target idx per kept
    src = jnp.zeros((ccap,), jnp.int32)
    tgt = jnp.where(keep, kpos, ccap)
    src = src.at[tgt].set(jnp.arange(ccap, dtype=jnp.int32), mode="drop")
    total = new_off[arr.capacity]
    out_valid = jnp.arange(ccap, dtype=jnp.int32) < total
    child = _gather_child(arr.child, src, out_valid)
    return ArrayColumn(child, new_off, arr.validity, arr.dtype)


def _gather_child(child: Column, idx, out_in_use) -> Column:
    from ..ops.strings import gather_string
    if isinstance(child, StringColumn):
        valid = jnp.where(out_in_use, child.validity[idx], False)
        return gather_string(child, idx, valid)
    data = jnp.where(out_in_use, child.data[idx],
                     jnp.zeros((), child.data.dtype))
    valid = jnp.where(out_in_use, child.validity[idx], False)
    return Column(data, valid, child.dtype)


def _exists_forall(arr: ArrayColumn, body, var: str, forall: bool
                   ) -> Column:
    pred = eval_lambda(body, var, arr)
    erow, in_use = _elem_row_map(arr)
    t = pred.data & pred.validity & in_use
    nul = ~pred.validity & in_use
    any_true = jax.ops.segment_max(t.astype(jnp.int32), erow,
                                   num_segments=arr.capacity) > 0
    any_false = jax.ops.segment_max(
        ((~pred.data) & pred.validity & in_use).astype(jnp.int32), erow,
        num_segments=arr.capacity) > 0
    any_null = jax.ops.segment_max(nul.astype(jnp.int32), erow,
                                   num_segments=arr.capacity) > 0
    if forall:
        # false if any false; else null if any null; else true
        result = ~any_false
        known = any_false | ~any_null
    else:
        # true if any true; else null if any null; else false
        result = any_true
        known = any_true | ~any_null
    valid = arr.validity & known
    return Column(jnp.where(valid, result, False), valid, BOOLEAN)


def array_exists(arr: ArrayColumn, body, var: str) -> Column:
    return _exists_forall(arr, body, var, forall=False)


def array_forall(arr: ArrayColumn, body, var: str) -> Column:
    return _exists_forall(arr, body, var, forall=True)
