"""Date/time kernels (reference datetimeExpressions.scala + JNI DateTimeRebase
/ GpuTimeZoneDB). Dates are int32 days since epoch, timestamps int64 micros
UTC; all in the proleptic Gregorian calendar (Spark >= 3.0 semantics, so no
julian rebase needed except for legacy parquet, handled at the IO layer).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column, StringColumn
from ..types import DATE, INT, LONG, TIMESTAMP

_DAY_US = 86_400_000_000


def days_from_civil(y, m, d):
    """Howard Hinnant days_from_civil: (y,m,d) -> days since 1970-01-01."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def civil_from_days(days):
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _is_leap(y):
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


# numpy, NOT jnp: a module-level jnp constant created while a jit trace
# is active (lazy import inside a traced function) would store a tracer
# in this global and poison every later trace (UnexpectedTracerError)
_DAYS_IN_MONTH = np.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                            np.int32)


def days_in_month(y, m):
    base = jnp.asarray(_DAYS_IN_MONTH)[jnp.clip(m - 1, 0, 11)]
    return jnp.where((m == 2) & _is_leap(y), 29, base)


def string_to_date(col: StringColumn) -> Column:
    """Spark cast(string as date): accepts 'yyyy', 'yyyy-mm', 'yyyy-mm-dd'
    (plus trailing 'T...' / time suffix ignored); invalid -> NULL."""
    from .cast_strings import _trimmed_span
    s, e = _trimmed_span(col)
    data = col.data
    byte_cap = col.byte_capacity
    cap = col.capacity

    def byte_at(p):
        return data[jnp.clip(p, 0, byte_cap - 1)]

    def digit_at(p, active):
        b = byte_at(p)
        is_d = (b >= ord("0")) & (b <= ord("9"))
        return (b - ord("0")).astype(jnp.int32), is_d | ~active

    # parse segments split by '-': year (1-6 digits incl sign? Spark: 4ish),
    # month, day. Implement the common fixed layouts: y{1,6}[-m{1,2}[-d{1,2}]]
    # via a vectorized scan over characters.
    max_t = jnp.max(jnp.maximum(e - s, 0))

    def body(carry):
        (t, seg, vals0, vals1, vals2, seg_len, ok, done) = carry
        p = s + t
        b = byte_at(p)
        active = (p < e) & ~done
        is_digit = (b >= ord("0")) & (b <= ord("9"))
        is_dash = b == ord("-")
        is_t = (b == ord("T")) | (b == ord(" "))
        d = (b - ord("0")).astype(jnp.int32)
        v0 = jnp.where(active & is_digit & (seg == 0), vals0 * 10 + d, vals0)
        v1 = jnp.where(active & is_digit & (seg == 1), vals1 * 10 + d, vals1)
        v2 = jnp.where(active & is_digit & (seg == 2), vals2 * 10 + d, vals2)
        seg_len_n = jnp.where(active & is_digit, seg_len + 1, seg_len)
        advance = active & is_dash & (seg < 2) & (seg_len > 0)
        seg_n = jnp.where(advance, seg + 1, seg)
        seg_len_n = jnp.where(advance, 0, seg_len_n)
        # 'T' or ' ' after day segment terminates parse (time part ignored
        # only when a full y-m-d was seen, like Spark)
        done_n = done | (active & is_t & (seg == 2) & (seg_len > 0))
        bad = active & ~(is_digit | advance | (is_t & (seg == 2) & (seg_len > 0)))
        ok = ok & ~bad
        return (t + 1, seg_n, v0, v1, v2, seg_len_n, ok, done_n)

    z = jnp.zeros(cap, jnp.int32)
    ob = jnp.ones(cap, jnp.bool_)
    zb = jnp.zeros(cap, jnp.bool_)
    (_, seg, y, m, d, seg_len, ok, _done) = jax.lax.while_loop(
        lambda c: c[0] < max_t, body, (jnp.int32(0), z, z, z, z, z, ob, zb))

    m = jnp.where(seg >= 1, m, 1)
    d = jnp.where(seg >= 2, d, 1)
    ok = ok & (e > s)
    ok = ok & (m >= 1) & (m <= 12) & (d >= 1) & (d <= days_in_month(y, m))
    days = days_from_civil(y, m, d).astype(jnp.int32)
    valid = col.validity & ok
    return Column(jnp.where(valid, days, 0), valid, DATE)


# --- field extraction -----------------------------------------------------

def extract_year(days) -> jnp.ndarray:
    y, _, _ = civil_from_days(days)
    return y


def extract_month(days) -> jnp.ndarray:
    _, m, _ = civil_from_days(days)
    return m


def extract_day(days) -> jnp.ndarray:
    _, _, d = civil_from_days(days)
    return d


def extract_dayofweek(days):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday. 1970-01-01 = Thursday."""
    return ((days.astype(jnp.int64) + 4) % 7 + 1).astype(jnp.int32)


def extract_dayofyear(days):
    y, _, _ = civil_from_days(days)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (days.astype(jnp.int64) - jan1 + 1).astype(jnp.int32)


def extract_quarter(days):
    _, m, _ = civil_from_days(days)
    return (m - 1) // 3 + 1


def timestamp_to_date_days(micros):
    return jnp.floor_divide(micros, _DAY_US).astype(jnp.int32)


def extract_hour(micros):
    day_us = jnp.mod(micros, _DAY_US)
    return (day_us // 3_600_000_000).astype(jnp.int32)


def extract_minute(micros):
    day_us = jnp.mod(micros, _DAY_US)
    return ((day_us // 60_000_000) % 60).astype(jnp.int32)


def extract_second(micros):
    day_us = jnp.mod(micros, _DAY_US)
    return ((day_us // 1_000_000) % 60).astype(jnp.int32)


def date_add(days, n):
    return (days.astype(jnp.int64) + n.astype(jnp.int64)).astype(jnp.int32)


def date_diff(end, start):
    return (end.astype(jnp.int64) - start.astype(jnp.int64)).astype(jnp.int32)


def last_day(days):
    y, m, _ = civil_from_days(days)
    return days_from_civil(y, m, days_in_month(y, m)).astype(jnp.int32)


def add_months(days, n):
    y, m, d = civil_from_days(days)
    total = y * 12 + (m - 1) + n
    ny = jnp.floor_divide(total, 12)
    nm = jnp.mod(total, 12) + 1
    nd = jnp.minimum(d, days_in_month(ny, nm))
    return days_from_civil(ny, nm, nd).astype(jnp.int32)


def trunc_date(days, unit: str):
    y, m, _d = civil_from_days(days)
    if unit in ("year", "yyyy", "yy"):
        return days_from_civil(y, jnp.ones_like(m), jnp.ones_like(m)).astype(jnp.int32)
    if unit in ("quarter",):
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(y, qm, jnp.ones_like(m)).astype(jnp.int32)
    if unit in ("month", "mon", "mm"):
        return days_from_civil(y, m, jnp.ones_like(m)).astype(jnp.int32)
    if unit in ("week",):
        # Monday-aligned: 1970-01-01 is Thursday (dow 4 with Mon=1)
        dow = jnp.mod(days.astype(jnp.int64) + 3, 7)  # 0 = Monday
        return (days.astype(jnp.int64) - dow).astype(jnp.int32)
    raise ValueError(f"unsupported trunc unit {unit}")
