"""Fused scan-filter-project-partial-aggregate Pallas kernel (ISSUE 1
tentpole; the q1 shape): ONE kernel reads source column tiles, applies
the inlined Filter predicates, computes the derived projections, and
accumulates masked-bucket partial aggregates — no intermediate column
(filtered, projected, or pre-projected) ever materializes in HBM. The
reference analog is Flare-style operator-pipeline fusion: one compiled
kernel per pipeline instead of one device program per relational
primitive (PAPERS.md).

Structure:
- an expression COMPILER (`compile_scan_agg_spec`) walks the operator
  chain the aggregate already absorbs for whole-stage fusion
  (AggregateExec._fused_steps + its pre-projection) and accepts it when
  every expression is a whitelisted pure-elementwise form. The kernel
  body then simply calls the expressions' own `columnar_eval` on
  tile-shaped Columns — the engine's null semantics hold inside the
  kernel by construction because it is the same code;
- the KERNEL runs a (2, n_tiles) grid. TPU grids iterate sequentially,
  so outputs with constant index maps act as cross-tile accumulators:
  phase 0 accumulates per-bucket key statistics (lane-wise min/max of
  the order bits + any-valid/any-null), phase 1 derives the clean-bucket
  bitmask from those statistics and accumulates the masked aggregates
  for rows in clean buckets. Dirty buckets (min != max, or a null/value
  mix) leave their rows out and raise the caller's speculation flag —
  the same contract as ops/maskedagg.masked_groupby, whose round-0
  bucket hash this kernel reuses verbatim;
- a thin XLA WRAPPER reduces the (G, 128) lane-wise accumulators,
  recovers key values from the order bits, and dense-places slots,
  returning masked_groupby's exact (out_keys, results, num_groups,
  leftover) contract so AggregateExec._streaming_step folds the partial
  with zero special cases.

Bit-exactness: integer aggregates (count/min/max/integer sums) are
order-independent and match the XLA tier bitwise; float sums accumulate
lane-wise then reduce, so they agree with the XLA formulation to
reduction-order rounding (the property tests assert ulp-level closeness
for floats and bitwise equality for everything else).

Off-TPU the kernel runs under the Pallas interpreter (tier-1 gating);
on hardware the measured tier selector decides whether it replaces the
XLA formulation per shape bucket (ops/pallas_tier.py). 64-bit lanes
(i64/f64 accumulators) rely on Mosaic's emulation on the chip — if a
shape fails to legalize, the measurement simply never records a Pallas
win and `auto` keeps the XLA tier.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column
from .pallas_kernels import pad_to_tiles

AGG_TILE_ROWS = 64

#: round-0 salt of ops/maskedagg.masked_group_assignment — identical
#: bucketization keeps the two tiers' resolved-group sets comparable
_ROUND0_SALT = 0x2545F491

_SUPPORTED_EXPRS = {
    "BoundReference", "Literal", "Alias",
    "Add", "Subtract", "Multiply", "Divide", "UnaryMinus", "Abs",
    "EqualTo", "EqualNullSafe", "LessThan", "LessThanOrEqual",
    "GreaterThan", "GreaterThanOrEqual",
    "And", "Or", "Not", "IsNull", "IsNotNull",
}

_SUPPORTED_OPS = ("sum", "sum_sq", "count", "count_star", "min", "max")


class _TileBatch:
    """Minimal batch shim for columnar_eval inside the kernel: bound
    expressions only touch .columns and .capacity."""

    def __init__(self, columns: List[Column], capacity: int):
        self.columns = columns
        self.capacity = capacity


class ScanAggSpec(NamedTuple):
    steps: Tuple            # (("filter", bound) | ("project", bound, schema))*
    pre_bound: Tuple        # pre-projection expressions (keys + agg inputs)
    key_count: int
    agg_ops: Tuple          # ((op, pre-slot index | None), ...)
    key_dtypes: Tuple       # engine DataType per key
    agg_dtypes: Tuple       # input DataType per agg op (None for count_star)


def _expr_supported(expr) -> bool:
    from ..types import DecimalType, StringType
    name = type(expr).__name__
    if name not in _SUPPORTED_EXPRS:
        return False
    try:
        dt = expr.data_type
    except Exception:  # noqa: BLE001 — unresolved/odd expressions
        return False
    if isinstance(dt, DecimalType) or isinstance(dt, StringType):
        return False
    if name == "Literal" and expr.value is None:
        return False
    return all(_expr_supported(c) for c in getattr(expr, "children", ()))


def compile_scan_agg_spec(fused_steps, pre_bound, pre_schema, key_count: int,
                          agg_ops, source_schema) -> Optional[ScanAggSpec]:
    """Validate the absorbed operator chain for the fused kernel; None
    when any piece falls outside the whitelisted elementwise subset."""
    from ..types import DecimalType
    if key_count == 0 or not agg_ops:
        return None
    # EVERY source column rides the kernel as (data, validity) row tiles
    # (BoundReference ordinals index the full column list), so varlen /
    # decimal128 source columns — whose .data is a byte buffer or absent
    # — make the whole shape ineligible even when no expression
    # references them
    for f in source_schema.fields:
        if not f.data_type.is_fixed_width or \
                isinstance(f.data_type, DecimalType):
            return None
    for step in fused_steps:
        exprs = [step[1]] if step[0] == "filter" else list(step[1])
        if not all(_expr_supported(e) for e in exprs):
            return None
    if not all(_expr_supported(e) for e in pre_bound):
        return None
    key_dtypes = []
    for f in pre_schema.fields[:key_count]:
        if not f.data_type.is_fixed_width or \
                isinstance(f.data_type, DecimalType):
            return None
        # sub-32-bit keys are excluded: their native order lanes
        # (u8/u16) would be widened to the u32 accumulator and
        # _unorder_bits' bitcast back to int8/int16 splits the lane into
        # extra trailing dims (confirmed trace-time crash); BYTE/SHORT
        # group keys simply keep the XLA tier
        jdt = jnp.dtype(f.data_type.jnp_dtype)
        if jdt == jnp.bool_ or jdt.itemsize < 4:
            return None
        key_dtypes.append(f.data_type)
    agg_dtypes = []
    for op, slot in agg_ops:
        if op not in _SUPPORTED_OPS:
            return None
        if slot is None:
            if op != "count_star":
                return None
            agg_dtypes.append(None)
            continue
        dt = pre_schema.fields[slot].data_type
        if not dt.is_fixed_width or isinstance(dt, DecimalType):
            return None
        jdt = jnp.dtype(dt.jnp_dtype)
        if op in ("sum", "sum_sq") and not (
                jnp.issubdtype(jdt, jnp.integer)
                or jnp.issubdtype(jdt, jnp.floating)):
            return None
        agg_dtypes.append(dt)
    return ScanAggSpec(tuple(fused_steps), tuple(pre_bound), key_count,
                       tuple(agg_ops), tuple(key_dtypes), tuple(agg_dtypes))


def _eval_pipeline(spec: ScanAggSpec, cols: List[Column], capacity: int):
    """Run the absorbed filter/project chain + pre-projection on (tile or
    full-width) columns. Returns (mask | None, key columns, agg input
    columns aligned with spec.agg_ops). Padding rows are NOT sanitized
    here — the kernel's active mask keeps them out of every bucket and
    reduction, the same discipline as the masked XLA tier."""
    cur = list(cols)
    mask = None
    for step in spec.steps:
        batch = _TileBatch(cur, capacity)
        if step[0] == "filter":
            pred = step[1].columnar_eval(batch)
            m = pred.data & pred.validity
            mask = m if mask is None else (mask & m)
        else:
            cur = [e.columnar_eval(batch) for e in step[1]]
    batch = _TileBatch(cur, capacity)
    pre = [e.columnar_eval(batch) for e in spec.pre_bound]
    keys = pre[: spec.key_count]
    agg_cols = [None if slot is None else pre[slot]
                for _, slot in spec.agg_ops]
    return mask, keys, agg_cols


def _acc_dtype(op: str, dt) -> jnp.dtype:
    if op in ("count", "count_star"):
        return jnp.dtype(jnp.int32)
    jdt = jnp.dtype(dt.jnp_dtype)
    if op in ("sum", "sum_sq"):
        return jnp.dtype(jnp.float64) if jnp.issubdtype(jdt, jnp.floating) \
            else jnp.dtype(jnp.int64)
    # min/max: bool rides an int8 lane (ops/maskedagg._slot_reduce_all)
    return jnp.dtype(jnp.int8) if jdt == jnp.bool_ else jdt


def _minmax_neutral(op: str, jdt):
    if jnp.issubdtype(jdt, jnp.floating):
        return jnp.full((), jnp.inf if op == "min" else -jnp.inf, jdt)
    info = jnp.iinfo(jdt)
    return jnp.full((), info.max if op == "min" else info.min, jdt)


def _order_lane_dtype(dt) -> jnp.dtype:
    jdt = jnp.dtype(dt.jnp_dtype)
    return jnp.dtype(jnp.uint64) if jdt.itemsize == 8 \
        else jnp.dtype(jnp.uint32)


def _scan_agg_kernel_body(spec: ScanAggSpec, src_dtypes, n_cols: int,
                          G: int, tile_rows: int):
    """Kernel factory: phases/columns/aggregates are static structure.

    Discharge discipline (learned on the fused probe): full-slice stores
    only, no @pl.when around stores, every constant explicitly dtyped.
    """
    from .maskedagg import _bucket_hash

    def kernel(nrows_ref, *refs):
        from jax.experimental import pallas as pl
        data_refs = refs[:n_cols]
        valid_refs = refs[n_cols:2 * n_cols]
        out_refs = refs[2 * n_cols:]
        p = pl.program_id(0)
        t = pl.program_id(1)
        init = (p == jnp.int32(0)) & (t == jnp.int32(0))
        phase1 = p == jnp.int32(1)

        tr = tile_rows
        flat = tr * 128
        # global row index of each tile element (padding rows inactive)
        i_flat = (jnp.int32(t) * jnp.int32(flat)
                  + jax.lax.broadcasted_iota(jnp.int32, (tr, 128), 0)
                  * jnp.int32(128)
                  + jax.lax.broadcasted_iota(jnp.int32, (tr, 128), 1))
        act2 = i_flat < nrows_ref[0, 0]

        # --- the fused operator chain on flattened tile columns ---
        cols = [Column(d[:].reshape(flat), (v[:] != jnp.int32(0))
                       .reshape(flat), dt)
                for d, v, dt in zip(data_refs, valid_refs, src_dtypes)]
        mask, keys, agg_cols = _eval_pipeline(spec, cols, flat)
        act = act2.reshape(flat)
        if mask is not None:
            act = act & mask

        h = _bucket_hash(keys, _ROUND0_SALT, flat)
        b = (h % jnp.uint32(G)).astype(jnp.int32)
        b2 = b.reshape(tr, 128)
        act2d = act.reshape(tr, 128)

        ri = 0  # output-ref cursor

        def nxt():
            nonlocal ri
            r = out_refs[ri]
            ri += 1
            return r

        # --- phase 0: per-bucket key statistics (always computed; the
        # stores pass through unchanged during phase 1) ---
        kstat_refs = []
        for kc in keys:
            from .sort import _numeric_order_key
            lane = _numeric_order_key(kc).reshape(tr, 128)
            v2 = kc.validity.reshape(tr, 128)
            mv = act2d & v2
            mn_n = jnp.full((), jnp.iinfo(lane.dtype).max, lane.dtype)
            zero_l = jnp.zeros((), lane.dtype)
            mn_t, mx_t, av_t, an_t = [], [], [], []
            for g in range(G):
                mg = mv & (b2 == jnp.int32(g))
                mn_t.append(jnp.min(jnp.where(mg, lane, mn_n), axis=0))
                mx_t.append(jnp.max(jnp.where(mg, lane, zero_l), axis=0))
                av_t.append(jnp.any(mg, axis=0))
                an_t.append(jnp.any(
                    act2d & ~v2 & (b2 == jnp.int32(g)), axis=0))
            mn_c = jnp.stack(mn_t)
            mx_c = jnp.stack(mx_t)
            av_c = jnp.stack(av_t).astype(jnp.int32)
            an_c = jnp.stack(an_t).astype(jnp.int32)
            r_mn, r_mx, r_av, r_an = nxt(), nxt(), nxt(), nxt()
            kstat_refs.append((r_mn, r_mx, r_av, r_an))
            old = jnp.where(init, mn_n, r_mn[:])
            r_mn[:] = jnp.where(phase1, old, jnp.minimum(old, mn_c))
            old = jnp.where(init, zero_l, r_mx[:])
            r_mx[:] = jnp.where(phase1, old, jnp.maximum(old, mx_c))
            old = jnp.where(init, jnp.int32(0), r_av[:])
            r_av[:] = jnp.where(phase1, old, old | av_c)
            old = jnp.where(init, jnp.int32(0), r_an[:])
            r_an[:] = jnp.where(phase1, old, old | an_c)

        # --- phase 1: clean-bucket bitmask from the finished statistics
        # (phase 0 wrote them across ALL tiles before any phase-1 step
        # runs — the grid's minor dimension iterates fastest) ---
        clean = jnp.ones((G,), jnp.bool_)
        occupied = jnp.zeros((G,), jnp.bool_)
        for r_mn, r_mx, r_av, r_an in kstat_refs:
            mn_g = jnp.min(r_mn[:], axis=1)
            mx_g = jnp.max(r_mx[:], axis=1)
            av_g = jnp.any(r_av[:] != jnp.int32(0), axis=1)
            an_g = jnp.any(r_an[:] != jnp.int32(0), axis=1)
            clean = clean & ~(av_g & an_g) & (~av_g | (mn_g == mx_g))
            occupied = occupied | av_g | an_g
        bits = jnp.sum(jnp.where(clean & occupied,
                                 jnp.uint32(1) << jnp.arange(
                                     G, dtype=jnp.uint32),
                                 jnp.uint32(0)))
        row_clean = ((bits >> b2.astype(jnp.uint32)) & jnp.uint32(1)) \
            != jnp.uint32(0)
        m1 = act2d & row_clean

        # --- phase 1: masked aggregate accumulation over clean buckets ---
        for (op, _), col, dt in zip(spec.agg_ops, agg_cols,
                                    spec.agg_dtypes):
            adt = _acc_dtype(op, dt)
            r_acc = nxt()
            if op == "count_star":
                contrib = jnp.stack([
                    jnp.sum(m1 & (b2 == jnp.int32(g)),
                            axis=0, dtype=jnp.int32) for g in range(G)])
                old = jnp.where(init, jnp.int32(0), r_acc[:])
                r_acc[:] = jnp.where(phase1, old + contrib, old)
                continue
            v2 = col.validity.reshape(tr, 128)
            d2 = col.data.reshape(tr, 128)
            mv1 = m1 & v2
            r_has = None
            if op in ("sum", "sum_sq", "min", "max"):
                r_has = nxt()
                has_c = jnp.stack([
                    jnp.any(mv1 & (b2 == jnp.int32(g)), axis=0)
                    for g in range(G)]).astype(jnp.int32)
                old_h = jnp.where(init, jnp.int32(0), r_has[:])
                r_has[:] = jnp.where(phase1, old_h | has_c, old_h)
            if op == "count":
                contrib = jnp.stack([
                    jnp.sum(mv1 & (b2 == jnp.int32(g)),
                            axis=0, dtype=jnp.int32)
                    for g in range(G)])
                old = jnp.where(init, jnp.int32(0), r_acc[:])
                r_acc[:] = jnp.where(phase1, old + contrib, old)
            elif op in ("sum", "sum_sq"):
                accv = d2.astype(adt)
                if op == "sum_sq":
                    accv = accv * accv
                zero = jnp.zeros((), adt)
                contrib = jnp.stack([
                    jnp.sum(jnp.where(mv1 & (b2 == jnp.int32(g)),
                                      accv, zero), axis=0)
                    for g in range(G)])
                old = jnp.where(init, zero, r_acc[:])
                r_acc[:] = jnp.where(phase1, old + contrib, old)
            else:  # min / max
                dv = d2.astype(jnp.int8) \
                    if d2.dtype == jnp.bool_ else d2
                neutral = _minmax_neutral(op, jnp.dtype(adt))
                fn = jnp.minimum if op == "min" else jnp.maximum
                red = jnp.min if op == "min" else jnp.max
                contrib = jnp.stack([
                    red(jnp.where(mv1 & (b2 == jnp.int32(g)), dv,
                                  neutral), axis=0)
                    for g in range(G)])
                old = jnp.where(init, jnp.full((), neutral, adt),
                                r_acc[:])
                r_acc[:] = jnp.where(phase1, fn(old, contrib), old)

    return kernel


def fused_scan_agg_update(spec: ScanAggSpec, batch, G: int, out_cap: int,
                          interpret: bool = False):
    """ONE kernel pass over a source batch -> masked-bucket partial.

    Returns (out_keys, tagged results, num_groups, leftover) — exactly
    ops/maskedagg.masked_groupby's contract, dense-placed into an
    `out_cap` bucket.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .maskedagg import _unorder_bits

    assert G <= 32, "clean-bucket bitmask is u32"
    cols = list(batch.columns)
    tr = AGG_TILE_ROWS
    tiles = []
    for c in cols:
        d2, _ = pad_to_tiles(c.data, tr)
        v2, _ = pad_to_tiles(c.validity.astype(jnp.int32), tr)
        tiles.append((d2, v2))
    rows2d = tiles[0][0].shape[0]
    n_tiles = rows2d // tr

    kernel = _scan_agg_kernel_body(spec, [c.dtype for c in cols],
                                   len(cols), G, tr)

    tspec = pl.BlockSpec((tr, 128), lambda p, t: (t, 0),
                         memory_space=pltpu.VMEM)
    const = pl.BlockSpec((G, 128), lambda p, t: (0, 0),
                         memory_space=pltpu.VMEM)
    smem = pl.BlockSpec((1, 1), lambda p, t: (0, 0),
                        memory_space=pltpu.SMEM)

    out_shapes = []
    for dt in spec.key_dtypes:
        ldt = _order_lane_dtype(dt)
        out_shapes += [jax.ShapeDtypeStruct((G, 128), ldt),
                       jax.ShapeDtypeStruct((G, 128), ldt),
                       jax.ShapeDtypeStruct((G, 128), jnp.int32),
                       jax.ShapeDtypeStruct((G, 128), jnp.int32)]
    for op, dt in zip((o for o, _ in spec.agg_ops), spec.agg_dtypes):
        out_shapes.append(jax.ShapeDtypeStruct((G, 128),
                                               _acc_dtype(op, dt)))
        if op in ("sum", "sum_sq", "min", "max"):
            out_shapes.append(jax.ShapeDtypeStruct((G, 128), jnp.int32))

    nrows = jnp.asarray(batch.num_rows).astype(jnp.int32).reshape(1, 1)
    # contract: ok dispatch-ledger — traced inline into the owning
    # AggregateExec's instrumented streaming-step program (this function
    # is only ever called inside an exec's jit trace)
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shapes),
        grid=(2, n_tiles),
        in_specs=[smem] + [tspec] * (2 * len(cols)),
        out_specs=tuple(const for _ in out_shapes),
        interpret=interpret,
    )(nrows, *[d for d, _ in tiles], *[v for _, v in tiles])

    # --- XLA epilogue: reduce lanes, prove cleanliness, place dense ---
    outs = list(outs)

    def take():
        return outs.pop(0)

    g_iota = jnp.arange(G, dtype=jnp.int32)
    clean = jnp.ones((G,), jnp.bool_)
    occupied = jnp.zeros((G,), jnp.bool_)
    key_info = []
    for dt in spec.key_dtypes:
        mn = jnp.min(take(), axis=1)
        mx = jnp.max(take(), axis=1)
        av = jnp.any(take() != 0, axis=1)
        an = jnp.any(take() != 0, axis=1)
        clean = clean & ~(av & an) & (~av | (mn == mx))
        occupied = occupied | av | an
        key_info.append((mn, av, dt))
    resolved = clean & occupied
    leftover = jnp.any(occupied & ~clean)
    num_groups = jnp.sum(resolved, dtype=jnp.int32)
    dense = jnp.cumsum(resolved.astype(jnp.int32)) - 1
    target = jnp.where(resolved, dense, out_cap)

    def place(vals, valids):
        d = jnp.zeros((out_cap,), vals.dtype).at[target].set(
            vals, mode="drop")
        v = jnp.zeros((out_cap,), jnp.bool_).at[target].set(
            valids & resolved, mode="drop")
        return d, v

    out_keys = []
    for mn, av, dt in key_info:
        vals = _unorder_bits(mn, dt)
        d, v = place(vals, av)
        d = jnp.where(v, d, jnp.zeros((), d.dtype))
        out_keys.append(Column(d, v, dt))

    results = []
    for (op, _), dt in zip(spec.agg_ops, spec.agg_dtypes):
        acc = take()
        if op in ("count", "count_star"):
            vals = jnp.sum(acc, axis=1, dtype=jnp.int32).astype(jnp.int64)
            valid = jnp.ones((G,), jnp.bool_)
        elif op in ("sum", "sum_sq"):
            has = jnp.any(take() != 0, axis=1)
            vals = jnp.sum(acc, axis=1)
            valid = has
        else:
            has = jnp.any(take() != 0, axis=1)
            red = jnp.min if op == "min" else jnp.max
            vals = red(acc, axis=1)
            valid = has
        d, v = place(vals, valid)
        results.append(("raw", (d, v)))
    return out_keys, results, num_groups, leftover
