"""The gather engine (ISSUE 8): one routing + accounting chokepoint for
every materializing row gather in the engine.

Three jobs:

1. **Routing** — `gather_rows` is the tier-aware packed row gather: it
   serves the call from the Pallas DMA kernel (ops/pallas_gather.py)
   when the measured tier selector says the kernel wins for this
   (rows, capacity) shape bucket (`gather` family, ops/pallas_tier.py),
   else from the XLA formulation (ops/rowpack.py). No record -> XLA, so
   default CPU behavior is byte-identical to the pre-gather-engine tree.
   The decision is made on the host at trace time (the established
   pallas_tier contract); an open `pallas_gather` circuit breaker
   (exec/lifecycle.FAMILY_DOMAINS) demotes NEW traces to XLA.

2. **Structural accounting** — every routed gather records (count,
   packed, bytes-moved estimate) into a thread-local recorder while a
   wired exec's `GatherTracker.observe` scope is active. Recording
   happens at TRACE time (the calls live inside jit programs); the
   tracker memoizes the structural counts per static program key and
   replays them on cache hits, so the per-iteration `numGathers` /
   `gatherTimeNs` metrics stay exact under jit caching. This is what
   the gather-count regression test asserts (counts, not timing —
   CPU-runnable).

3. **Batch-level helper** — `gather_batch_columns` is the ONE
   implementation of "gather a batch of columns by an index map":
   fixed-width columns ride a single packed row gather, varlen/nested
   columns keep the per-column path. The join emit, the filter/output
   compaction (ops/basic.compact_columns) and the window sort
   permutation all route through it, so the gather-count drop is
   engine-wide.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import List, Optional, Sequence

import jax.numpy as jnp

__all__ = [
    "GatherStats", "GatherTracker", "gather_rows", "gather_lane_matrix",
    "gather_batch_columns", "record", "recording", "counters",
]

_tls = threading.local()


class GatherStats:
    """Structural gather totals: number of materializing gathers, how
    many rode a packed (multi-column) row gather, how many were served
    by the Pallas DMA kernel, and the estimated bytes moved."""

    __slots__ = ("count", "packed_count", "pallas_count", "bytes")

    def __init__(self, count: int = 0, packed_count: int = 0,
                 pallas_count: int = 0, nbytes: int = 0):
        self.count = count
        self.packed_count = packed_count
        self.pallas_count = pallas_count
        self.bytes = nbytes

    def add(self, other: "GatherStats") -> None:
        self.count += other.count
        self.packed_count += other.packed_count
        self.pallas_count += other.pallas_count
        self.bytes += other.bytes

    def copy(self) -> "GatherStats":
        return GatherStats(self.count, self.packed_count,
                           self.pallas_count, self.bytes)

    def delta(self, since: "GatherStats") -> "GatherStats":
        return GatherStats(self.count - since.count,
                           self.packed_count - since.packed_count,
                           self.pallas_count - since.pallas_count,
                           self.bytes - since.bytes)


#: process-cumulative totals (bench.py embeds per-record deltas)
_proc = GatherStats()
_proc_lock = threading.Lock()


def counters() -> dict:
    with _proc_lock:
        return {"count": _proc.count, "packed_count": _proc.packed_count,
                "pallas_count": _proc.pallas_count, "bytes": _proc.bytes}


def record(n: int = 1, packed: bool = False, pallas: bool = False,
           nbytes: int = 0) -> None:
    """Note a routed gather on the active recorder (one pointer check
    when no wired exec is observing)."""
    rec = getattr(_tls, "rec", None)
    if rec is None:
        return
    rec.count += n
    if packed:
        rec.packed_count += n
    if pallas:
        rec.pallas_count += n
    rec.bytes += nbytes


@contextmanager
def recording():
    """Collect structural gather counts for the enclosed region (the
    tracker's trace-time capture; also used directly by tests)."""
    prev = getattr(_tls, "rec", None)
    rec = GatherStats()
    _tls.rec = rec
    try:
        yield rec
    finally:
        _tls.rec = prev


class GatherTracker:
    """Per-exec gather accounting: wraps the exec's gather-bearing
    kernel dispatches, memoizing trace-time structural counts per
    static program key so jit cache hits replay the same counts.

    `numGathers` adds the structural count per dispatch; `gatherTimeNs`
    adds the dispatch wall-ns (the gather-bearing region, inclusive of
    the program's non-gather work — counts are the structural signal,
    time is the profile hint). `emit_event` writes one `gather_stats`
    event per exec execution with the totals since the last emission.
    """

    def __init__(self, num_metric=None, time_metric=None):
        self._num = num_metric
        self._time = time_metric
        self._memo = {}
        self._total = GatherStats()
        self._emitted = GatherStats()

    @contextmanager
    def observe(self, key):
        prev = getattr(_tls, "rec", None)
        rec = GatherStats()
        _tls.rec = rec
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            _tls.rec = prev
            dt = time.perf_counter_ns() - t0
            if rec.count:
                # a trace happened inside: refresh the structural memo
                self._memo[key] = rec.copy()
            stats = self._memo.get(key)
            if stats is not None and stats.count:
                self._total.add(stats)
                with _proc_lock:
                    _proc.add(stats)
                if self._num is not None:
                    self._num.add(stats.count)
                if self._time is not None:
                    self._time.add(dt)

    def emit_event(self, op: str, op_id) -> None:
        """One `gather_stats` event with this exec's totals since the
        last emission (called at operator-iterator close, the
        pipeline-event convention)."""
        delta = self._total.delta(self._emitted)
        if not delta.count:
            return
        self._emitted = self._total.copy()
        from ..obs import events as obs_events
        if obs_events.active_bus() is None:
            return
        obs_events.emit("gather_stats", op=op, op_id=op_id,
                        count=delta.count, packed=delta.packed_count,
                        pallas=delta.pallas_count, bytes=delta.bytes)


# ---------------------------------------------------------------------------
# routed primitives
# ---------------------------------------------------------------------------


def _pallas_tier_on(rows: int, cap: int) -> bool:
    if rows == 0 or cap == 0:
        return False
    from .pallas_tier import fused_tier_enabled
    return fused_tier_enabled("gather", (rows, cap))


def gather_rows(plan, imat, fmat, idx):
    """Tier-aware packed row gather (drop-in for rowpack.gather_rows)."""
    rows = int(idx.shape[0])
    cap = int(imat.shape[0])
    lanes = int(imat.shape[1]) + (2 * int(fmat.shape[1])
                                  if fmat is not None else 0)
    use_pallas = bool(lanes) and _pallas_tier_on(rows, cap)
    record(1, packed=True, pallas=use_pallas, nbytes=rows * lanes * 4)
    if use_pallas:
        from .pallas_gather import pallas_gather_rows
        from .pallas_kernels import on_tpu
        return pallas_gather_rows(plan, imat, fmat, idx,
                                  interpret=not on_tpu())
    from .rowpack import gather_rows as _xla_gather_rows
    return _xla_gather_rows(plan, imat, fmat, idx)


def gather_lane_matrix(mat, idx):
    """Row gather of a small index-lane matrix (the join emit's ONE
    index materialization): rows out of range read row 0 — callers mask
    by their own selection predicate."""
    cap = mat.shape[0]
    record(1, packed=True,
           nbytes=int(idx.shape[0]) * int(mat.shape[1]) * 4)
    in_range = (idx >= 0) & (idx < cap)
    safe = jnp.where(in_range, idx, 0)
    return mat[safe]


def gather_batch_columns(columns: Sequence, idx, num_rows=None,
                         byte_caps: Optional[Sequence] = None,
                         out_valid=None) -> List:
    """Gather a batch's columns by an int32 index map: fixed-width
    columns via ONE packed row gather, varlen/nested via the per-column
    path. `num_rows` masks output slots >= num_rows; `out_valid` masks
    by predicate; indices already -1-masked pass neither."""
    from .basic import active_mask, gather_column
    from .rowpack import pack_rows, split_packable, unpack_rows
    caps = byte_caps or (None,) * len(columns)
    midx = idx
    if num_rows is not None:
        midx = jnp.where(active_mask(num_rows, idx.shape[0]), idx, -1)
    elif out_valid is not None:
        midx = jnp.where(out_valid, idx, -1)
    out: List = [None] * len(columns)
    p_idx, o_idx = split_packable(columns)
    if len(p_idx) > 1:
        plan, imat, fmat = pack_rows([columns[i] for i in p_idx])
        gi, gf = gather_rows(plan, imat, fmat, midx)
        for j, c in zip(p_idx, unpack_rows(plan, gi, gf)):
            out[j] = c
    else:
        o_idx = sorted(p_idx + o_idx)
    for j in o_idx:
        out[j] = gather_column(columns[j], midx, out_byte_capacity=caps[j])
    return out
