"""String <-> numeric cast kernels with Spark semantics.

The reference delegates these to the spark-rapids-jni `CastStrings` CUDA
kernels (imported by GpuCast.scala). Here they are dense XLA programs:

  * int -> string: fixed 20-iteration digit extraction (max int64 digits),
    right-aligned into a per-row 20-byte scratch then compacted.
  * string -> int: device parse with Spark's whitespace trim, sign, overflow
    -> NULL, trailing-garbage -> NULL (non-ANSI returns NULL, never throws).
  * string -> float/double: mantissa/exponent parse; 'NaN'/'Infinity'
    accepted like Spark.
  * bool/date renderings match Spark's Cast.scala output formats.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import (
    BOOLEAN, BooleanType, ByteType, DataType, DateType, DoubleType, FloatType,
    IntegerType, IntegralType, LongType, ShortType, STRING, TimestampType,
)
from .strings import _rebuild_offsets, string_lengths

_INT_BOUNDS = {
    ByteType: (-128, 127),
    ShortType: (-32768, 32767),
    IntegerType: (-(2**31), 2**31 - 1),
    LongType: (-(2**63), 2**63 - 1),
}


def _digits_fixed(vals_i64):
    """(n,) int64 -> (n, 20) uint8 right-aligned decimal digits + lengths.

    20 = sign + max 19 digits of int64.
    """
    n = vals_i64.shape[0]
    neg = vals_i64 < 0
    # abs of int64 min overflows; go through uint64
    mag = jnp.where(neg, (-(vals_i64.astype(jnp.int64))).astype(jnp.uint64),
                    vals_i64.astype(jnp.uint64))
    mag = jnp.where(vals_i64 == jnp.int64(-(2**63)),
                    jnp.uint64(2**63), mag)
    digits = []
    x = mag
    for _ in range(19):
        digits.append((x % 10).astype(jnp.uint8))
        x = x // 10
    # digits[0] is least significant
    digit_mat = jnp.stack(digits[::-1], axis=1)  # (n, 19) most-significant first
    ndig = jnp.maximum(
        19 - jnp.argmax(digit_mat != 0, axis=1), 1)
    all_zero = jnp.all(digit_mat == 0, axis=1)
    ndig = jnp.where(all_zero, 1, ndig)
    return digit_mat, ndig, neg


def int_to_string(col: Column) -> StringColumn:
    vals = col.data.astype(jnp.int64)
    digit_mat, ndig, neg = _digits_fixed(vals)
    lengths = (ndig + neg.astype(jnp.int32)).astype(jnp.int32)
    lengths = jnp.where(col.validity, lengths, 0)
    offsets = _rebuild_offsets(lengths)
    cap = col.capacity
    byte_cap = 20 * cap
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = pos - offsets[row]
    is_sign = neg[row] & (intra == 0)
    digit_idx = intra - neg[row].astype(jnp.int32)  # 0-based into the number
    # digit d of row r lives at digit_mat[r, 19 - ndig[r] + d]
    mat_col = jnp.clip(19 - ndig[row] + digit_idx, 0, 18)
    ch = digit_mat[row, mat_col] + jnp.uint8(ord("0"))
    ch = jnp.where(is_sign, jnp.uint8(ord("-")), ch)
    in_use = pos < offsets[-1]
    data = jnp.where(in_use, ch, jnp.uint8(0))
    return StringColumn(data, offsets, col.validity, STRING)


def bool_to_string(col: Column) -> StringColumn:
    lengths = jnp.where(col.data, 4, 5).astype(jnp.int32)
    lengths = jnp.where(col.validity, lengths, 0)
    offsets = _rebuild_offsets(lengths)
    cap = col.capacity
    byte_cap = 5 * cap
    t = jnp.asarray(list(b"true\x00"), jnp.uint8)
    f = jnp.asarray(list(b"false"), jnp.uint8)
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = jnp.clip(pos - offsets[row], 0, 4)
    ch = jnp.where(col.data[row], t[intra], f[intra])
    in_use = pos < offsets[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), offsets,
                        col.validity, STRING)


def _civil_from_days(days):
    """Proleptic Gregorian (y, m, d) from days since 1970-01-01.
    Howard Hinnant's algorithm, branch-free."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def date_to_string(col: Column) -> StringColumn:
    """DATE -> 'YYYY-MM-DD' (years padded to 4; negative years unsupported
    on device — the planner tags pre-epoch-extreme dates for host fallback)."""
    y, m, d = _civil_from_days(col.data)
    cap = col.capacity
    lengths = jnp.where(col.validity, 10, 0).astype(jnp.int32)
    offsets = _rebuild_offsets(lengths)
    byte_cap = 10 * cap
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = pos - offsets[row]
    yr, mr, dr = y[row], m[row], d[row]
    digits = jnp.stack([
        yr // 1000 % 10, yr // 100 % 10, yr // 10 % 10, yr % 10,
        jnp.full_like(yr, -3),  # '-'
        mr // 10 % 10, mr % 10,
        jnp.full_like(yr, -3),
        dr // 10 % 10, dr % 10,
    ], axis=1)
    i = jnp.clip(intra, 0, 9)
    val = digits[jnp.arange(byte_cap), i]
    ch = jnp.where(val == -3, jnp.uint8(ord("-")),
                   val.astype(jnp.uint8) + jnp.uint8(ord("0")))
    in_use = pos < offsets[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), offsets,
                        col.validity, STRING)


def cast_to_string(col: Column) -> StringColumn:
    dt = col.dtype
    if isinstance(dt, BooleanType):
        return bool_to_string(col)
    if isinstance(dt, IntegralType):
        return int_to_string(col)
    if isinstance(dt, DateType):
        return date_to_string(col)
    from ..types import DecimalType
    if isinstance(dt, DecimalType):
        return decimal_to_string(col)
    raise TypeError(f"cast {dt} -> string not yet on device")


def decimal_to_string(col: Column) -> StringColumn:
    """decimal64 -> string with exactly `scale` fraction digits (Spark)."""
    dt = col.dtype
    if dt.scale == 0:
        return int_to_string(Column(col.data, col.validity, LongType()))
    # render unscaled padded, then splice the point — simplest correct form:
    # integer part and fraction part rendered separately
    m = 10 ** dt.scale
    neg = col.data < 0
    mag = jnp.abs(col.data)
    int_part = mag // m
    frac_part = mag % m
    int_str = int_to_string(Column(jnp.where(neg, -int_part, int_part),
                                   col.validity, LongType()))
    # fraction digits, fixed width = scale
    digits = []
    x = frac_part
    for _ in range(dt.scale):
        digits.append((x % 10).astype(jnp.uint8))
        x = x // 10
    frac_mat = jnp.stack(digits[::-1], axis=1)  # (n, scale)
    int_len = string_lengths(int_str)
    # handle "-0.xx": int part of -0 renders "0"; need explicit minus
    needs_minus = neg & (int_part == 0)
    lengths = int_len + needs_minus.astype(jnp.int32) + 1 + dt.scale
    lengths = jnp.where(col.validity, lengths, 0)
    offsets = _rebuild_offsets(lengths)
    cap = col.capacity
    byte_cap = int(int_str.byte_capacity) + (dt.scale + 2) * cap
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = pos - offsets[row]
    nm = needs_minus[row]
    ilen = int_len[row] + nm.astype(jnp.int32)
    is_minus = nm & (intra == 0)
    in_int = (intra < ilen) & ~is_minus
    is_dot = intra == ilen
    int_pos = jnp.clip(int_str.offsets[row] + intra - nm.astype(jnp.int32),
                       0, int_str.byte_capacity - 1)
    frac_idx = jnp.clip(intra - ilen - 1, 0, dt.scale - 1)
    ch = jnp.where(is_minus, jnp.uint8(ord("-")),
          jnp.where(in_int, int_str.data[int_pos],
           jnp.where(is_dot, jnp.uint8(ord(".")),
                     frac_mat[row, frac_idx] + jnp.uint8(ord("0")))))
    in_use = pos < offsets[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), offsets,
                        col.validity, STRING)


# --- parsing --------------------------------------------------------------

_SPACE = ord(" ")


def _trimmed_span(col: StringColumn):
    """Spark trims ASCII whitespace (<= 0x20) before parsing."""
    starts = col.offsets[:-1]
    ends = col.offsets[1:]
    byte_cap = col.byte_capacity
    data = col.data

    def trim_front(carry):
        s, e = carry
        b = data[jnp.clip(s, 0, byte_cap - 1)]
        can = (s < e) & (b <= 0x20)
        return jnp.where(can, s + 1, s), e

    def front_cond(carry):
        s, e = carry
        b = data[jnp.clip(s, 0, byte_cap - 1)]
        return jnp.any((s < e) & (b <= 0x20))

    s, e = jax.lax.while_loop(front_cond, trim_front, (starts, ends))

    def trim_back(carry):
        s2, e2 = carry
        b = data[jnp.clip(e2 - 1, 0, byte_cap - 1)]
        can = (s2 < e2) & (b <= 0x20)
        return s2, jnp.where(can, e2 - 1, e2)

    def back_cond(carry):
        s2, e2 = carry
        b = data[jnp.clip(e2 - 1, 0, byte_cap - 1)]
        return jnp.any((s2 < e2) & (b <= 0x20))

    s, e = jax.lax.while_loop(back_cond, trim_back, (s, e))
    return s, e


def string_to_integral(col: StringColumn, dst) -> Column:
    """Spark string->int: optional sign, digits only, overflow/garbage->NULL."""
    s, e = _trimmed_span(col)
    data = col.data
    byte_cap = col.byte_capacity
    first = data[jnp.clip(s, 0, byte_cap - 1)]
    neg = first == ord("-")
    has_sign = neg | (first == ord("+"))
    ds = s + has_sign.astype(jnp.int32)
    n_digits = e - ds
    max_t = jnp.max(jnp.maximum(n_digits, 0))

    def body(carry):
        t, acc, ok, ovf = carry
        p = jnp.clip(ds + t, 0, byte_cap - 1)
        b = data[p]
        active = t < n_digits
        is_digit = (b >= ord("0")) & (b <= ord("9"))
        d = (b - ord("0")).astype(jnp.uint64)
        # magnitude accumulates in uint64 so Long.MIN_VALUE (2^63) fits
        new_ovf = ovf | (acc > (jnp.uint64(2**64 - 1) - d) // 10)
        new_acc = acc * 10 + d
        acc = jnp.where(active & is_digit, new_acc, acc)
        ok = ok & (~active | is_digit)
        ovf = jnp.where(active & is_digit, new_ovf, ovf)
        return t + 1, acc, ok, ovf

    acc0 = jnp.zeros(col.capacity, jnp.uint64)
    ok0 = jnp.ones(col.capacity, jnp.bool_)
    ovf0 = jnp.zeros(col.capacity, jnp.bool_)
    _, acc, ok, ovf = jax.lax.while_loop(
        lambda c: c[0] < max_t, body, (jnp.int32(0), acc0, ok0, ovf0))
    ok = ok & (n_digits > 0) & ~ovf
    max_mag = jnp.where(neg, jnp.uint64(2**63), jnp.uint64(2**63 - 1))
    ok = ok & (acc <= max_mag)
    val = jnp.where(neg, -(acc.astype(jnp.int64)), acc.astype(jnp.int64))
    lo, hi = _INT_BOUNDS[type(dst)]
    in_range = (val >= lo) & (val <= hi) | (neg & (acc == jnp.uint64(2**63))
                                           & (lo == -(2**63)))
    valid = col.validity & ok & in_range
    out = jnp.where(valid, val, 0).astype(dst.jnp_dtype)
    return Column(out, valid, dst)


def string_to_fractional(col: StringColumn, dst) -> Column:
    """string -> float/double: sign, digits, optional '.', optional e-exp,
    plus 'NaN'/'[+-]Infinity' like Spark; malformed -> NULL."""
    s, e = _trimmed_span(col)
    data = col.data
    byte_cap = col.byte_capacity
    cap = col.capacity

    def byte_at(p):
        return data[jnp.clip(p, 0, byte_cap - 1)]

    first = byte_at(s)
    neg = first == ord("-")
    has_sign = neg | (first == ord("+"))
    p0 = s + has_sign.astype(jnp.int32)

    # special literals
    def match_lit(lit: bytes, start):
        ok = (e - start) == len(lit)
        for j, chx in enumerate(lit):
            bl = byte_at(start + j)
            # case-insensitive ascii match
            ok = ok & ((bl == chx) | (bl == (chx ^ 0x20) if chr(chx).isalpha() else bl == chx))
        return ok

    is_nan = match_lit(b"NaN", p0) | match_lit(b"nan", p0)
    is_inf = match_lit(b"Infinity", p0) | match_lit(b"Inf", p0) | \
        match_lit(b"infinity", p0) | match_lit(b"inf", p0)

    max_t = jnp.max(jnp.maximum(e - p0, 0))

    def body(carry):
        (t, mant, frac_digits, seen_dot, seen_digit, exp_val, exp_neg,
         in_exp, seen_exp_digit, ok) = carry
        p = p0 + t
        b = byte_at(p)
        active = p < e
        is_digit = (b >= ord("0")) & (b <= ord("9"))
        d = (b - ord("0")).astype(jnp.float64)
        is_dot = b == ord(".")
        is_e = (b == ord("e")) | (b == ord("E"))
        is_exp_sign = ((b == ord("+")) | (b == ord("-"))) & in_exp & ~seen_exp_digit

        mant_new = jnp.where(is_digit & ~in_exp, mant * 10 + d, mant)
        frac_new = jnp.where(is_digit & ~in_exp & seen_dot,
                             frac_digits + 1, frac_digits)
        exp_new = jnp.where(is_digit & in_exp,
                            exp_val * 10 + (b - ord("0")).astype(jnp.int32),
                            exp_val)
        bad = ~(is_digit | (is_dot & ~seen_dot & ~in_exp) |
                (is_e & ~in_exp & seen_digit) | is_exp_sign)
        ok = ok & (~active | ~bad)
        seen_dot_n = seen_dot | (is_dot & active)
        seen_digit_n = seen_digit | (is_digit & active & ~in_exp)
        in_exp_n = in_exp | (is_e & active)
        exp_neg_n = exp_neg | (is_exp_sign & (b == ord("-")) & active)
        seen_exp_digit_n = seen_exp_digit | (is_digit & in_exp & active)
        return (t + 1,
                jnp.where(active, mant_new, mant),
                jnp.where(active, frac_new, frac_digits),
                seen_dot_n, seen_digit_n,
                jnp.where(active, exp_new, exp_val),
                exp_neg_n, in_exp_n, seen_exp_digit_n, ok)

    z_f = jnp.zeros(cap, jnp.float64)
    z_i = jnp.zeros(cap, jnp.int32)
    z_b = jnp.zeros(cap, jnp.bool_)
    o_b = jnp.ones(cap, jnp.bool_)
    (_, mant, frac_digits, seen_dot, seen_digit, exp_val, exp_neg,
     in_exp, seen_exp_digit, ok) = jax.lax.while_loop(
        lambda c: c[0] < max_t, body,
        (jnp.int32(0), z_f, z_i, z_b, z_b, z_i, z_b, z_b, z_b, o_b))

    ok = ok & seen_digit & (~in_exp | seen_exp_digit)
    exp = jnp.where(exp_neg, -exp_val, exp_val) - frac_digits
    val = mant * jnp.power(10.0, exp.astype(jnp.float64))
    val = jnp.where(neg, -val, val)
    val = jnp.where(is_nan, jnp.float64(jnp.nan), val)
    val = jnp.where(is_inf, jnp.where(neg, -jnp.inf, jnp.inf), val)
    ok = ok | is_nan | is_inf
    valid = col.validity & ok
    out = jnp.where(valid, val, 0.0).astype(dst.jnp_dtype)
    return Column(out, valid, dst)


def string_to_boolean(col: StringColumn) -> Column:
    """Spark accepts t/true/y/yes/1 and f/false/n/no/0 (case-insensitive)."""
    from .strings import str_lower_ascii
    low = str_lower_ascii(col)
    s, e = _trimmed_span(low)
    length = e - s
    data = low.data
    byte_cap = low.byte_capacity

    def eq_lit(lit: bytes):
        ok = length == len(lit)
        for j, chx in enumerate(lit):
            ok = ok & (data[jnp.clip(s + j, 0, byte_cap - 1)] == chx)
        return ok

    truthy = eq_lit(b"t") | eq_lit(b"true") | eq_lit(b"y") | eq_lit(b"yes") | eq_lit(b"1")
    falsy = eq_lit(b"f") | eq_lit(b"false") | eq_lit(b"n") | eq_lit(b"no") | eq_lit(b"0")
    valid = col.validity & (truthy | falsy)
    return Column(truthy & valid, valid, BOOLEAN)


def cast_string_to(col: StringColumn, dst: DataType) -> Column:
    if isinstance(dst, BooleanType):
        return string_to_boolean(col)
    if isinstance(dst, IntegralType):
        return string_to_integral(col, dst)
    if isinstance(dst, (FloatType, DoubleType)):
        return string_to_fractional(col, dst)
    if isinstance(dst, DateType):
        from .datetime_ops import string_to_date
        return string_to_date(col)
    raise TypeError(f"cast string -> {dst} not yet on device")


def format_number_string(col: Column, decimals: int) -> StringColumn:
    """format_number(x, d): HALF_EVEN rounding to d places, thousands
    separators (reference GpuFormatNumber / Java DecimalFormat
    '#,##0.00'). Device path: the scaled value rides an int64, so inputs
    with |x|*10^d >= 2^63 saturate (documented deviation — Spark prints
    full digits via arbitrary-precision DecimalFormat)."""
    assert 0 <= decimals <= 18  # 10^d must fit an int64 (gated upstream)
    cap = col.capacity
    x = col.data.astype(jnp.float64)
    neg = x < 0
    scale = float(10 ** decimals)
    scaled = jnp.rint(jnp.abs(x) * scale)  # rint = HALF_EVEN
    scaled = jnp.clip(scaled, 0.0, 9.2e18).astype(jnp.int64)
    if jnp.issubdtype(col.data.dtype, jnp.integer):
        # exact for integral inputs: no float round trip on the int part;
        # |x|*10^d past int64 saturates like the float path (documented)
        mag = jnp.where(neg, -(col.data.astype(jnp.int64)),
                        col.data.astype(jnp.int64))
        limit = jnp.int64((2 ** 63 - 1) // 10 ** decimals)
        scaled = jnp.where(mag > limit, jnp.int64(2 ** 63 - 1),
                           mag * jnp.int64(10 ** decimals))
    int_part = scaled // jnp.int64(10 ** decimals)
    frac = (scaled % jnp.int64(10 ** decimals)).astype(jnp.int64)

    digit_mat, ndig, _ = _digits_fixed(int_part)
    n_commas = (ndig - 1) // 3
    int_chars = ndig + n_commas
    frac_chars = (1 + decimals) if decimals > 0 else 0
    lengths = (neg.astype(jnp.int32) + int_chars + frac_chars)
    lengths = jnp.where(col.validity, lengths, 0).astype(jnp.int32)
    offsets = _rebuild_offsets(lengths)

    byte_cap = int(27 + 1 + decimals + 1) * cap
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    intra = pos - offsets[row]
    r_neg = neg[row]
    j = intra - r_neg.astype(jnp.int32)       # 0-based in int section
    m = int_chars[row]
    is_sign = r_neg & (intra == 0)
    in_int = (j >= 0) & (j < m)
    r0 = m - 1 - j                            # 0-based from the right
    is_comma = in_int & ((r0 + 1) % 4 == 0)
    dig_from_right = r0 - (r0 + 1) // 4
    mat_col = jnp.clip(18 - dig_from_right, 0, 18)
    int_ch = digit_mat[row, mat_col] + jnp.uint8(ord("0"))
    fpos = j - m                              # 0 is the '.', 1.. digits
    is_dot = fpos == 0
    fd = jnp.clip(fpos - 1, 0, max(decimals - 1, 0))
    if decimals > 0:
        pow10 = jnp.asarray([10 ** (decimals - 1 - k)
                             for k in range(decimals)], jnp.int64)
        frac_ch = ((frac[row] // pow10[fd]) % 10).astype(jnp.uint8) \
            + jnp.uint8(ord("0"))
    else:
        frac_ch = jnp.zeros((byte_cap,), jnp.uint8)
    ch = jnp.where(is_sign, jnp.uint8(ord("-")),
                   jnp.where(is_comma, jnp.uint8(ord(",")),
                             jnp.where(in_int, int_ch,
                                       jnp.where(is_dot,
                                                 jnp.uint8(ord(".")),
                                                 frac_ch))))
    in_use = pos < offsets[-1]
    return StringColumn(jnp.where(in_use, ch, jnp.uint8(0)), offsets,
                        col.validity, STRING)
