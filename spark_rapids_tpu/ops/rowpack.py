"""Packed-row gather: the TPU JoinGatherer fast path (round 4).

XLA's random gather on v5e is loop-bound, not bandwidth-bound: a 2M-row
gather of ONE i32 column costs ~26 ms while a 2M-row gather of an
(N, 8) i32 matrix costs ~7.4 ms (tools/exp_gather.py). The engine's old
join probe did 2 gathers per column (data + validity); packing every
fixed-width column of a batch into one u32 matrix (plus one f64 matrix —
TPU forbids bitcasts from f64) turns a whole-batch row gather into 1-2
XLA gathers regardless of column count.

Reference analog: cuDF's JoinGatherer gathers a table in one pass per
column because GPU gathers are bandwidth-bound; on TPU the same
architectural slot is filled by this row-packing (SURVEY §2.9,
reference JoinGatherer.scala).

Layout of the u32 matrix (capacity, n_lanes):
  lane 0..nv-1   validity bits, column c -> bit (c % 32) of lane (c // 32)
  data lanes     per column: 1 lane (<=32-bit, bitcast), 2 lanes
                 (64-bit ints, little-endian bitcast), or none (f64 data
                 goes to the f64 matrix; validity still in the u32 bits)

Only plain fixed-width Columns pack; strings/arrays/structs/maps keep the
per-column gather path (ops/basic.gather_column).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column

__all__ = [
    "is_packable", "split_packable", "pack_rows", "gather_rows",
    "unpack_rows", "PackPlan",
]


class PackPlan(NamedTuple):
    """Static description of a pack: per-column (kind, lane) and engine
    dtypes, derived at trace time from the concrete Columns. A NamedTuple
    so jit static/aux comparisons use value equality (a BuildTable carries
    its plan as pytree aux data — identity equality would retrace every
    probe)."""

    kinds: Tuple                  # ('w1'|'w2'|'f64', lane_index)
    np_dtypes: Tuple              # numpy dtype per column
    dtypes: Tuple                 # engine DataType per column
    n_valid_lanes: int
    n_data_lanes: int
    n_f_lanes: int

    @property
    def n_ilanes(self) -> int:
        return self.n_valid_lanes + self.n_data_lanes


def is_packable(col: Column) -> bool:
    if type(col) is not Column:
        return False
    k = col.data.dtype.kind
    if k == "f":
        return col.data.dtype.itemsize in (4, 8)
    return k in ("i", "u", "b") and col.data.dtype.itemsize <= 8


def split_packable(cols: Sequence[Column]):
    """Partition columns into (packable_idx, other_idx), order-preserving."""
    p, o = [], []
    for i, c in enumerate(cols):
        (p if is_packable(c) else o).append(i)
    return p, o


def _plan(cols: Sequence[Column]) -> PackPlan:
    kinds: List = []
    n_data = 0
    n_f = 0
    for c in cols:
        dt = c.data.dtype
        if dt.kind == "f" and dt.itemsize == 8:
            kinds.append(("f64", n_f))
            n_f += 1
        elif dt.itemsize == 8:
            kinds.append(("w2", n_data))
            n_data += 2
        else:
            kinds.append(("w1", n_data))
            n_data += 1
    nv = max(1, -(-len(cols) // 32)) if cols else 0
    return PackPlan(tuple(kinds), tuple(c.data.dtype for c in cols),
                    tuple(c.dtype for c in cols), nv, n_data, n_f)


def pack_rows(cols: Sequence[Column]) -> Tuple[PackPlan, jnp.ndarray,
                                               Optional[jnp.ndarray]]:
    """Pack columns into (plan, u32 matrix, f64 matrix|None)."""
    plan = _plan(cols)
    cap = cols[0].capacity if cols else 0
    vlanes = [jnp.zeros((cap,), jnp.uint32)
              for _ in range(plan.n_valid_lanes)]
    dlanes: List[Optional[jnp.ndarray]] = [None] * plan.n_data_lanes
    flanes: List[Optional[jnp.ndarray]] = [None] * plan.n_f_lanes
    for ci, (c, (kind, lane)) in enumerate(zip(cols, plan.kinds)):
        vlanes[ci // 32] = vlanes[ci // 32] | (
            c.validity.astype(jnp.uint32) << np.uint32(ci % 32))
        d = c.data
        if kind == "f64":
            flanes[lane] = d
        elif kind == "w2":
            pair = jax.lax.bitcast_convert_type(d, jnp.uint32)  # (cap, 2)
            dlanes[lane] = pair[:, 0]
            dlanes[lane + 1] = pair[:, 1]
        else:
            if d.dtype.kind == "b":
                dlanes[lane] = d.astype(jnp.uint32)
            else:
                if d.dtype.itemsize < 4:
                    d = d.astype(jnp.int32)
                dlanes[lane] = jax.lax.bitcast_convert_type(d, jnp.uint32)
    imat = jnp.stack(vlanes + [x for x in dlanes], axis=1) \
        if (vlanes or dlanes) else jnp.zeros((cap, 0), jnp.uint32)
    fmat = jnp.stack([x for x in flanes], axis=1) if flanes else None
    return plan, imat, fmat


def gather_rows(plan: PackPlan, imat, fmat, idx):
    """Row gather with out-of-range masking: idx < 0 or >= capacity yields
    an all-invalid row (validity lanes zeroed; data lanes left as row 0)."""
    cap = imat.shape[0]
    in_range = (idx >= 0) & (idx < cap)
    safe = jnp.where(in_range, idx, 0)
    g = imat[safe]
    nv = plan.n_valid_lanes
    if nv:
        vmask = jnp.where(in_range, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
        g = jnp.concatenate([g[:, :nv] & vmask[:, None], g[:, nv:]], axis=1)
    gf = fmat[safe] if fmat is not None else None
    return g, gf


def unpack_rows(plan: PackPlan, imat, fmat,
                only: Optional[Sequence[int]] = None) -> List[Column]:
    """Rebuild Columns from packed matrices (inverse of pack_rows).
    `only` restricts to a subset of column indices (plan order)."""
    out: List[Column] = []
    nv = plan.n_valid_lanes
    cols = range(len(plan.kinds)) if only is None else only
    for ci in cols:
        (kind, lane), npdt, edt = (plan.kinds[ci], plan.np_dtypes[ci],
                                   plan.dtypes[ci])
        valid = ((imat[:, ci // 32] >> np.uint32(ci % 32))
                 & np.uint32(1)) != 0
        if kind == "f64":
            d = fmat[:, lane]
        elif kind == "w2":
            pair = jnp.stack([imat[:, nv + lane], imat[:, nv + lane + 1]],
                             axis=1)
            d = jax.lax.bitcast_convert_type(pair, npdt)
        else:
            u = imat[:, nv + lane]
            if npdt == np.bool_:
                d = u != 0
            elif np.dtype(npdt).itemsize < 4:
                d = jax.lax.bitcast_convert_type(u, jnp.int32).astype(npdt)
            else:
                d = jax.lax.bitcast_convert_type(u, npdt)
        d = jnp.where(valid, d, jnp.zeros((), d.dtype))
        out.append(Column(d, valid, edt))
    return out
