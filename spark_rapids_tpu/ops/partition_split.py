"""Device-side shuffle partition split (ISSUE 9): given device partition
ids, produce the per-partition count table and a pid-stable permutation
so the whole batch can be emitted as ONE partition-ordered reorder —
the engine analog of the reference's GpuHashPartitioning pid kernel +
`contiguous_split` (one device pass, packed per-partition buffers).

The host shuffle writer used to split every batch with
O(n_partitions x n_columns) serial numpy gathers (`partition_batch_host`
-> `host_gather_column` per partition per column), squarely inside
`shuffleWriteTime`. This module moves the split onto the device:

  1. `partition_table` — per-partition counts (segment_sum) and a
     stable sort-by-pid permutation in one traced program; the offset
     table is the only value the host ever syncs on.
  2. `reorder_columns` — the partition-major reorder, routed through
     the gather engine (`ops/gather.gather_batch_columns`), so the
     fixed-width lanes ride ONE packed row gather served by the
     measured tier (Pallas DMA kernel where the `gather` family has a
     recorded win, XLA floor otherwise) and the structural
     numGathers/gatherTimeNs accounting covers the shuffle write path.

The reordered batch then lands on the host as a single packed D2H copy
(`columnar/transfer.fetch_split_host`) and each partition serializes
straight from a row-range slice (`shuffle/serializer.serialize_slice`)
— zero host-side row gathers per written batch.

`tools/kern_bench.py`'s `partition_split` family benches this exact
pipeline shape (counts + permutation + packed gather) XLA-vs-Pallas;
the runtime tier consult rides the `gather` family records because the
gather IS the tiered step.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["partition_table", "reorder_columns"]


def partition_table(pid, num_rows, capacity: int, n_partitions: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-partition counts + pid-stable permutation, one traced pass.

    `pid` is the per-row partition id (any int dtype; values >=
    n_partitions and rows >= num_rows count as inactive). Returns
    (counts (n_partitions,) int32, order (capacity,) int32) where
    `order` lists source rows in partition-major order, original row
    order preserved within a partition (stable), inactive rows last.
    """
    from .basic import active_mask
    act = active_mask(num_rows, capacity)
    key = jnp.where(act, pid.astype(jnp.int32), jnp.int32(n_partitions))
    key = jnp.clip(key, 0, n_partitions)
    ones = jnp.where(key < n_partitions, jnp.int32(1), jnp.int32(0))
    counts = jax.ops.segment_sum(ones, key,
                                 num_segments=n_partitions + 1)
    iota = jnp.arange(capacity, dtype=jnp.int32)
    # stable sort by pid: partitions become contiguous, row order within
    # a partition is the input order (lax.sort is the chip's cheapest
    # reordering primitive — same formulation as compaction_order)
    _, order = jax.lax.sort((key.astype(jnp.uint32), iota), num_keys=1,
                            is_stable=True)
    return counts[:n_partitions], order


def reorder_columns(columns: Sequence, order, num_rows) -> List:
    """Partition-major reorder of a batch's columns by the
    `partition_table` permutation, through the gather engine (ONE
    packed row gather for the fixed-width lanes, tier-aware; varlen
    keeps the per-column device path). Output slots >= num_rows are
    masked invalid."""
    from .gather import gather_batch_columns
    return gather_batch_columns(columns, order, num_rows=num_rows)
