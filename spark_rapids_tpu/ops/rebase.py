"""Julian ↔ proleptic-Gregorian datetime rebase — the reference's
datetimeRebaseUtils.scala + JNI DateTimeRebase: files written by legacy
Spark (< 3.0) or Hive store dates/timestamps in the hybrid
Julian-Gregorian calendar; modern Spark (and this engine) is proleptic
Gregorian. Rebase re-interprets the same Y-M-D wall date across
calendars, a piecewise-constant day shift with breakpoints at Julian
century leap days and the 1582-10-15 cutover.

The breakpoint table is generated once from the standard JDN formulas
(no data files) and uploaded; the device kernel is searchsorted + add,
mirroring the JNI kernel's device-resident rebase table.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax.numpy as jnp
import numpy as np

_EPOCH_JDN = 2440588          # 1970-01-01 proleptic Gregorian
_CUTOVER_DAYS = -141427       # 1582-10-15, first Gregorian day of the hybrid
MICROS_PER_DAY = 86_400_000_000


def _julian_ymd_to_jdn(y: int, m: int, d: int) -> int:
    a = (14 - m) // 12
    y2 = y + 4800 - a
    m2 = m + 12 * a - 3
    return d + (153 * m2 + 2) // 5 + 365 * y2 + y2 // 4 - 32083


def _greg_ymd_to_jdn(y: int, m: int, d: int) -> int:
    a = (14 - m) // 12
    y2 = y + 4800 - a
    m2 = m + 12 * a - 3
    return (d + (153 * m2 + 2) // 5 + 365 * y2 + y2 // 4 - y2 // 100
            + y2 // 400 - 32045)


def _jdn_to_julian_ymd(jdn: int) -> Tuple[int, int, int]:
    c = jdn + 32082
    d = (4 * c + 3) // 1461
    e = c - (1461 * d) // 4
    m = (5 * e + 2) // 153
    day = e - (153 * m + 2) // 5 + 1
    month = m + 3 - 12 * (m // 10)
    year = d - 4800 + m // 10
    return year, month, day


def _jdn_to_greg_ymd(jdn: int) -> Tuple[int, int, int]:
    a = jdn + 32044
    b = (4 * a + 3) // 146097
    c = a - (146097 * b) // 4
    d = (4 * c + 3) // 1461
    e = c - (1461 * d) // 4
    m = (5 * e + 2) // 153
    day = e - (153 * m + 2) // 5 + 1
    month = m + 3 - 12 * (m // 10)
    year = 100 * b + d - 4800 + m // 10
    return year, month, day


def _hybrid_to_proleptic(days: int) -> int:
    """One hybrid-calendar day number → proleptic-Gregorian day number."""
    if days >= _CUTOVER_DAYS:
        return days
    y, m, d = _jdn_to_julian_ymd(days + _EPOCH_JDN)
    return _greg_ymd_to_jdn(y, m, d) - _EPOCH_JDN


def _proleptic_to_hybrid(days: int) -> int:
    if days >= _CUTOVER_DAYS:
        return days
    y, m, d = _jdn_to_greg_ymd(days + _EPOCH_JDN)
    # dates that existed only in the Gregorian gap (none before 1582)
    return _julian_ymd_to_jdn(y, m, d) - _EPOCH_JDN


@functools.lru_cache(maxsize=2)
def _switch_table(direction: str) -> Tuple[np.ndarray, np.ndarray]:
    """(switch_days, diffs) in the SOURCE calendar's day numbers. The
    shift changes only at Julian century leap days (Feb 29 Julian /
    absent Gregorian) and the cutover, so probing around each century's
    March 1 (both calendars) finds every breakpoint."""
    conv = _hybrid_to_proleptic if direction == "j2g" else \
        _proleptic_to_hybrid
    probes = []
    for y in range(-4800, 1601, 100):
        for to_jdn in (_julian_ymd_to_jdn, _greg_ymd_to_jdn):
            base = to_jdn(y, 3, 1) - _EPOCH_JDN
            probes.extend(range(base - 3, base + 3))
    probes.extend(range(_CUTOVER_DAYS - 15, _CUTOVER_DAYS + 2))
    probes = sorted(set(probes))
    switch, diffs = [probes[0]], [conv(probes[0]) - probes[0]]
    prev = diffs[0]
    for p in probes[1:]:
        diff = conv(p) - p
        if diff != prev:
            # walk back to the first day carrying the new shift (probes
            # bracket every breakpoint within a few days)
            q = p
            while conv(q - 1) - (q - 1) == diff:
                q -= 1
            switch.append(q)
            diffs.append(diff)
            prev = diff
    return (np.array(switch, np.int64), np.array(diffs, np.int64))


def _apply(days, direction: str):
    switch, diffs = _switch_table(direction)
    sw = jnp.asarray(switch)
    df = jnp.asarray(diffs)
    i = jnp.clip(jnp.searchsorted(sw, days, side="right") - 1, 0,
                 sw.shape[0] - 1)
    shift = jnp.where(days < _CUTOVER_DAYS, df[i], 0)
    return days + shift


def rebase_julian_to_gregorian_days(days):
    """LEGACY-written DATE (hybrid calendar) → proleptic Gregorian."""
    return _apply(days, "j2g")


def rebase_gregorian_to_julian_days(days):
    """proleptic Gregorian DATE → LEGACY hybrid calendar (write path)."""
    return _apply(days, "g2j")


def _floordiv(a, b):
    return jnp.floor_divide(a, b)


def rebase_julian_to_gregorian_micros(micros):
    """LEGACY TIMESTAMP rebase: shift the day component, keep the time of
    day (the reference's JNI rebase is also day-granular for the calendar
    component; sub-day zone shifts are the timezone DB's job)."""
    days = _floordiv(micros, MICROS_PER_DAY)
    tod = micros - days * MICROS_PER_DAY
    return rebase_julian_to_gregorian_days(days) * MICROS_PER_DAY + tod


def rebase_gregorian_to_julian_micros(micros):
    days = _floordiv(micros, MICROS_PER_DAY)
    tod = micros - days * MICROS_PER_DAY
    return rebase_gregorian_to_julian_days(days) * MICROS_PER_DAY + tod
