"""Window kernels — device core of the reference's window stack
(window/GpuWindowExec.scala:146, GpuRunningWindowExec.scala:220,
GpuUnboundedToUnboundedAggWindowExec.scala, BasicWindowCalc.scala).

TPU-first: all frames lower to *segmented scans and prefix differences*
over partition-sorted data:
  * running frames (UNBOUNDED PRECEDING..CURRENT ROW) -> segmented
    cumulative ops (cumsum / associative_scan with a segment-reset carry);
  * whole-partition frames -> segment reduce + gather-back;
  * ROWS bounded frames (sum/count/avg) -> prefix[i+b] - prefix[i-a-1];
  * rank family -> positions relative to segment starts and order-key
    boundaries;
  * lag/lead -> shifted gather guarded by segment membership.
The reference implements these as four separate exec strategies over cuDF
window kernels; on TPU one segmented-prefix formulation covers them all
and XLA fuses the scans with the surrounding arithmetic.

All kernels assume rows are already sorted by (partition, order) with
segment ids precomputed (ops/sort.py group_segment_ids) and inactive rows
at the tail.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column
from .basic import active_mask


def segment_starts(seg, capacity: int):
    """first row index of each row's segment: gather of segment-min pos."""
    positions = jnp.arange(capacity, dtype=jnp.int32)
    first = jax.ops.segment_min(positions, seg, num_segments=capacity)
    safe = jnp.clip(seg, 0, capacity - 1)
    return jnp.clip(first[safe], 0, capacity - 1)


def segment_ends(seg, capacity: int):
    """last row index of each row's segment."""
    positions = jnp.arange(capacity, dtype=jnp.int32)
    last = jax.ops.segment_max(positions, seg, num_segments=capacity)
    safe = jnp.clip(seg, 0, capacity - 1)
    return jnp.clip(last[safe], 0, capacity - 1)


def _prefix_sum_exclusive(values):
    """exclusive prefix sum along the row axis."""
    return jnp.concatenate([jnp.zeros((1,), values.dtype),
                            jnp.cumsum(values)[:-1]])


def _segmented_cumsum(v, seg):
    """Inclusive per-segment cumsum (associative_scan with segment-reset
    combine). Frames never cross partitions, so differencing THIS prefix
    instead of a global cumsum keeps float windowed sums segment-local —
    a tiny partition sorted after 1e12-scale partitions no longer loses
    its sums to catastrophic cancellation (the same failure ADVICE r4
    flagged in the group-by prefix-difference tier)."""
    is_start = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                seg[1:] != seg[:-1]])

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf

    out, _ = jax.lax.associative_scan(combine, (v, is_start))
    return out


def windowed_sum_count(values, validity, seg, num_rows, capacity: int,
                       preceding: Optional[int], following: Optional[int]):
    """sum+count over a ROWS frame [i-preceding, i+following] clipped to the
    segment; None means unbounded on that side. Returns (sum f64/i64,
    count i32) per row. This one kernel backs sum/count/avg for every
    frame shape via prefix differences."""
    act = active_mask(num_rows, capacity)
    v = jnp.where(validity & act, values, jnp.zeros((), values.dtype))
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = v.astype(jnp.float64)
    else:
        v = v.astype(jnp.int64)
    c = (validity & act).astype(jnp.int32)
    # SEGMENT-LOCAL inclusive prefix (float-cancellation-safe); counts are
    # int-exact so the global prefix is fine
    incl = _segmented_cumsum(v, seg)
    excl = incl - v
    pc_full = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(c, dtype=jnp.int32)])
    i = jnp.arange(capacity, dtype=jnp.int32)
    start_seg = segment_starts(seg, capacity)
    end_seg = segment_ends(seg, capacity)
    lo = start_seg if preceding is None else jnp.maximum(
        start_seg, i - preceding)
    hi = end_seg if following is None else jnp.minimum(
        end_seg, i + following)
    nonempty = hi >= lo
    # inclusive window [lo, hi] within one segment:
    # incl[hi] - (incl[lo] - v[lo])
    s = incl[jnp.clip(hi, 0, capacity - 1)] - \
        excl[jnp.clip(lo, 0, capacity - 1)]
    s = jnp.where(nonempty, s, jnp.zeros((), s.dtype))
    hi = jnp.maximum(hi, lo - 1)
    n = pc_full[jnp.clip(hi + 1, 0, capacity)] - \
        pc_full[jnp.clip(lo, 0, capacity)]
    return s, n.astype(jnp.int32)


def _saturating_shift(data, delta):
    """data + delta with saturation instead of wraparound (int) — the
    probe value for a RANGE bound; floats saturate to +-inf naturally."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        return data + jnp.asarray(delta, data.dtype)
    d = jnp.asarray(delta, data.dtype)
    res = data + d
    info = jnp.iinfo(data.dtype)
    over = (d > 0) & (res < data)
    under = (d < 0) & (res > data)
    return jnp.where(over, info.max, jnp.where(under, info.min, res))


def _merge_rank(key_lanes, probe_lanes, capacity: int, probe_first: bool):
    """Count of key-entries sorting strictly before (probe_first) or
    at-or-before (not probe_first) each probe, via ONE stable sort of the
    2*cap concatenated entries. Both entry sets must already be sorted by
    the same lane order (true here: keys are the sorted rows, probes are
    monotone shifts of them), which makes the classic merge identity
    hold: rank_of_probe_i_among_keys = merged_pos(probe_i) - i."""
    kf, pf = (1, 0) if probe_first else (0, 1)
    merged = [jnp.concatenate([k, p]) for k, p in
              zip(key_lanes, probe_lanes)]
    flags = jnp.concatenate([
        jnp.full((capacity,), kf, jnp.uint32),
        jnp.full((capacity,), pf, jnp.uint32)])
    payload = jnp.arange(2 * capacity, dtype=jnp.int32)
    out = jax.lax.sort(tuple(merged) + (flags, payload),
                       num_keys=len(merged) + 1, is_stable=True)
    pos_of = jnp.zeros((2 * capacity,), jnp.int32).at[out[-1]].set(payload)
    return pos_of[capacity:] - jnp.arange(capacity, dtype=jnp.int32)


def range_frame_bounds(order_col: Column, seg, num_rows, capacity: int,
                       preceding, following, ascending: bool,
                       nulls_first: bool):
    """Per-row [lo, hi) global row-index bounds of a RANGE frame over ONE
    numeric order key (Spark requires a single numeric order expression
    for bounded RANGE frames; reference
    window/GpuWindowExpression.scala:111-179 GpuSpecifiedWindowFrame
    range case).

    preceding/following are VALUE offsets (None = unbounded); the frame
    of row i is every row j in i's partition whose key lies in
    [key_i - preceding, key_i + following] (direction-adjusted for
    descending order). Rows with a NULL key frame exactly the partition's
    null run, matching Spark's null-ordering semantics.

    TPU formulation: no searchsorted (u64 searchsorted measured ~1s/2M on
    v5e). Both bounds come from one stable lax.sort each over the 2*cap
    concatenated (row-keys ++ shifted-probe-keys) lane stacks — the
    merge-rank identity turns the sort positions into per-row row-index
    bounds, and the partition/null lanes confine every probe to its own
    partition and null class."""
    from .sort import _numeric_order_key, _split_u64_lanes

    act = active_mask(num_rows, capacity)
    valid = order_col.validity & act
    i = jnp.arange(capacity, dtype=jnp.int32)

    lo = segment_starts(seg, capacity)
    hi = segment_ends(seg, capacity) + 1

    if preceding is None and following is None:
        return lo, jnp.where(act, hi, 0)

    null_rank = (jnp.where(valid, 1, 0) if nulls_first
                 else jnp.where(valid, 0, 1)).astype(jnp.uint32)

    def lanes_for(data) -> list:
        vlane = _numeric_order_key(Column(data, valid, order_col.dtype))
        if not ascending:
            vlane = ~vlane
        vlane = jnp.where(valid, vlane, jnp.zeros((), vlane.dtype))
        return _split_u64_lanes([
            (~act).astype(jnp.uint32), seg.astype(jnp.uint32),
            null_rank, vlane])

    key_lanes = lanes_for(order_col.data)
    # direction-adjusted probe values: for DESC order the "preceding"
    # side holds LARGER keys, so the shift sign flips
    sgn = 1 if ascending else -1
    if preceding is not None:
        p_lo = _saturating_shift(order_col.data, -sgn * preceding)
        lo = _merge_rank(key_lanes, lanes_for(p_lo), capacity,
                         probe_first=True)
    if following is not None:
        p_hi = _saturating_shift(order_col.data, sgn * following)
        hi = _merge_rank(key_lanes, lanes_for(p_hi), capacity,
                         probe_first=False)
    return lo, jnp.where(act, hi, 0)


def range_sum_count(values, validity, seg, num_rows, capacity: int, lo, hi):
    """sum+count over per-row [lo, hi) row-index frames (from
    range_frame_bounds) via prefix differences; the float prefix is
    segment-local (frames never cross partitions) to avoid global-cumsum
    cancellation."""
    act = active_mask(num_rows, capacity)
    v = jnp.where(validity & act, values, jnp.zeros((), values.dtype))
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = v.astype(jnp.float64)
    else:
        v = v.astype(jnp.int64)
    c = (validity & act).astype(jnp.int32)
    incl = _segmented_cumsum(v, seg)
    excl = incl - v
    pc = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                          jnp.cumsum(c, dtype=jnp.int32)])
    hi_c = jnp.clip(hi, 0, capacity)
    lo_c = jnp.clip(lo, 0, capacity)
    nonempty = hi_c > lo_c
    s = incl[jnp.clip(hi_c - 1, 0, capacity - 1)] - \
        excl[jnp.clip(lo_c, 0, capacity - 1)]
    s = jnp.where(nonempty, s, jnp.zeros((), v.dtype))
    n = jnp.where(nonempty, pc[hi_c] - pc[lo_c], 0)
    return s, n.astype(jnp.int32)


def _extrema_over_ranges(values, validity, act, a, b, capacity: int,
                         is_max: bool):
    """min/max over per-row inclusive row-index ranges [a, b] via a
    sparse (doubling) range-extrema table: log2(cap) levels, two gathers
    per row (classic O(1) RMQ), fully vectorized."""
    valid = validity & act
    vals = values
    if vals.dtype == jnp.bool_:
        vals = vals.astype(jnp.int8)
    if jnp.issubdtype(vals.dtype, jnp.floating):
        neutral = jnp.full((), -jnp.inf if is_max else jnp.inf, vals.dtype)
    else:
        info = jnp.iinfo(vals.dtype)
        neutral = jnp.full((), info.min if is_max else info.max, vals.dtype)
    v = jnp.where(valid, vals, neutral)
    op = jnp.maximum if is_max else jnp.minimum

    empty = b < a

    levels = [v]
    span = 1
    while span < capacity:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[span:], jnp.full((span,), neutral, prev.dtype)])
        levels.append(op(prev, shifted))
        span *= 2
    tbl = jnp.stack(levels)  # (L+1, capacity)

    length = jnp.maximum(b - a + 1, 1)
    k = 31 - jax.lax.clz(length.astype(jnp.uint32)).astype(jnp.int32)
    k = jnp.clip(k, 0, len(levels) - 1)
    right = jnp.clip(b + 1 - (jnp.int32(1) << k), 0, capacity - 1)
    res = op(tbl[k, jnp.clip(a, 0, capacity - 1)], tbl[k, right])

    # validity: any non-null value inside the window (prefix-count diff)
    cnt = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                           jnp.cumsum(valid.astype(jnp.int32))])
    has_val = (cnt[jnp.clip(b + 1, 0, capacity)]
               - cnt[jnp.clip(a, 0, capacity)]) > 0
    out_valid = act & has_val & ~empty
    res = jnp.where(out_valid, res, jnp.zeros((), res.dtype))
    if values.dtype == jnp.bool_:
        res = res.astype(jnp.bool_)
    return res, out_valid


def range_min_max(values, validity, num_rows, capacity: int, lo, hi,
                  is_max: bool):
    """min/max over per-row [lo, hi) frames from range_frame_bounds."""
    act = active_mask(num_rows, capacity)
    return _extrema_over_ranges(values, validity, act, lo, hi - 1,
                                capacity, is_max)


def bounded_min_max(values, validity, seg, num_rows, capacity: int,
                    preceding: "Optional[int]", following: "Optional[int]",
                    is_max: bool):
    """min/max over a ROWS frame [i-preceding, i+following] clipped to the
    segment, nulls skipped (reference GpuBatchedBoundedWindowExec.scala:220
    sliding-frame strategy).

    TPU formulation: a sparse (doubling) range-extrema table — log2(cap)
    levels, level l holding the extremum of [i, i+2^l) — answers every
    row's clamped window with TWO gathers (the classic O(1) RMQ query),
    instead of a per-row sequential deque. O(n log n) build, fully
    vectorized."""
    act = active_mask(num_rows, capacity)
    # window bounds per row, clamped to the row's segment
    i = jnp.arange(capacity, dtype=jnp.int32)
    seg_a = segment_starts(seg, capacity)
    seg_b = segment_ends(seg, capacity)
    a = seg_a if preceding is None else jnp.maximum(i - preceding, seg_a)
    b = seg_b if following is None else jnp.minimum(i + following, seg_b)
    return _extrema_over_ranges(values, validity, act, a, b, capacity,
                                is_max)


def running_min_max(values, validity, seg, num_rows, capacity: int,
                    is_max: bool):
    """segmented running min/max (UNBOUNDED PRECEDING..CURRENT ROW) via
    associative_scan with a segment-reset combine."""
    act = active_mask(num_rows, capacity)
    valid = validity & act
    if jnp.issubdtype(values.dtype, jnp.floating):
        neutral = jnp.full((), -jnp.inf if is_max else jnp.inf, values.dtype)
    elif values.dtype == jnp.bool_:
        values = values.astype(jnp.int8)
        neutral = jnp.int8(0 if is_max else 1)
    else:
        info = jnp.iinfo(values.dtype)
        neutral = jnp.full((), info.min if is_max else info.max, values.dtype)
    v = jnp.where(valid, values, neutral)
    is_start = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                seg[1:] != seg[:-1]])

    def combine(a, b):
        av, aflag, acnt = a
        bv, bflag, bcnt = b
        op = jnp.maximum if is_max else jnp.minimum
        nv = jnp.where(bflag, bv, op(av, bv))
        ncnt = jnp.where(bflag, bcnt, acnt + bcnt)
        return nv, aflag | bflag, ncnt
    cnt = valid.astype(jnp.int32)
    out_v, _, out_c = jax.lax.associative_scan(
        combine, (v, is_start, cnt))
    return out_v, out_c > 0


def row_number(seg, num_rows, capacity: int):
    i = jnp.arange(capacity, dtype=jnp.int32)
    return i - segment_starts(seg, capacity) + 1


def rank_dense_rank(order_boundary, seg, num_rows, capacity: int):
    """(rank, dense_rank) from the order-key boundary mask (True at the
    first row of each distinct order key within its segment, which the
    caller builds from sort lanes)."""
    i = jnp.arange(capacity, dtype=jnp.int32)
    start = segment_starts(seg, capacity)
    # rank: index (within segment) of the first row of my order group + 1
    seg_start_flag = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                      seg[1:] != seg[:-1]])
    boundary = order_boundary | seg_start_flag

    def combine(a, b):
        apos, aflag = a
        bpos, bflag = b
        return jnp.where(bflag, bpos, apos), aflag | bflag
    group_first, _ = jax.lax.associative_scan(
        combine, (i, boundary))
    rank = group_first - start + 1
    # dense rank: boundaries in my segment up to & including me
    pb = jnp.cumsum(boundary.astype(jnp.int32))  # inclusive
    dense = pb - (pb[start] - boundary[start].astype(jnp.int32))
    return rank, dense


def lag_lead(col: Column, seg, num_rows, capacity: int, offset: int,
             default_value=None):
    """lag (offset>0 looks back) / lead (offset<0) within the segment.

    Returns (gathered column, same_seg mask). The mask distinguishes
    "offset row does not exist" (default applies, Spark semantics) from
    "offset row exists but is NULL" (result stays NULL even with a
    default) — collapsing both into validity would substitute the default
    for real nulls."""
    i = jnp.arange(capacity, dtype=jnp.int32)
    src = i - offset
    in_range = (src >= 0) & (src < capacity)
    safe = jnp.clip(src, 0, capacity - 1)
    same_seg = in_range & (seg[safe] == seg)
    from .basic import gather_column
    out = gather_column(col, jnp.where(same_seg, safe, -1))
    return out, same_seg


def whole_partition_broadcast(reduced, seg, capacity: int):
    """gather a per-segment reduction back to every row of the segment."""
    safe = jnp.clip(seg, 0, capacity - 1)
    return reduced[safe]
