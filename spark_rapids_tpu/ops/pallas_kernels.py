"""Pallas TPU kernels for the hash hotspot (SURVEY §2.9 #40: the
blueprint's Pallas tier over the XLA substrate; reference analog: the
hand-tuned CUDA hash kernels in spark-rapids-jni `Hash`).

Murmur3 is the engine's hottest scalar kernel — every shuffle partition
id, hash-join bucket and group-by probe hashes its keys with Spark-exact
murmur3_x86_32 (ops/hashing.py). The XLA path is ~20 elementwise HLOs per
key column; this kernel runs the whole mixing pipeline on the VPU inside
one VMEM tile, one HBM read + one write per block.

TPU constraints shape the ABI:
- the VPU has no 64-bit lanes → a LONG key is bitcast OUTSIDE the kernel
  to two int32 planes (low, high), which is exactly how murmur3 consumes
  an 8-byte value anyway (two 32-bit mix rounds);
- tiles are (sublane, 128): rows pad to TILE_ROWS×128 and view 2-D.
  Padding rows hash to garbage and are masked by callers (validity
  discipline is the engine-wide contract for padded capacity buckets);
- the running hash (seed) is a PER-ROW vector, because Spark chains
  columns by feeding column i's hash in as column i+1's seed.

Off-TPU the same kernel runs under the Pallas interpreter, so the CPU
test suite validates bit-exactness against the XLA path and the host
oracle. Enable on device via spark.rapids.tpu.pallas.enabled.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.dispatch import instrument as _instrument

TILE_ROWS = 256  # (256, 128) int32 tile = 128 KiB VMEM per operand


def _rotl(x, r):
    return jnp.bitwise_or(
        jax.lax.shift_left(x, np.uint32(r)),
        jax.lax.shift_right_logical(x, np.uint32(32 - r)))


C1 = np.uint32(0xCC9E2D51)
C2 = np.uint32(0x1B873593)
M5 = np.uint32(0xE6546B64)
F1 = np.uint32(0x85EBCA6B)
F2 = np.uint32(0xC2B2AE35)


def _mix_k1(k1):
    return _rotl(k1 * C1, 15) * C2


def _mix_h1(h1, k1):
    h1 = jnp.bitwise_xor(h1, k1)
    return _rotl(h1, 13) * np.uint32(5) + M5


def _fmix(h1, length):
    h1 = jnp.bitwise_xor(h1, np.uint32(length))
    h1 = jnp.bitwise_xor(h1, jax.lax.shift_right_logical(h1, np.uint32(16)))
    h1 = h1 * F1
    h1 = jnp.bitwise_xor(h1, jax.lax.shift_right_logical(h1, np.uint32(13)))
    h1 = h1 * F2
    return jnp.bitwise_xor(h1, jax.lax.shift_right_logical(h1, np.uint32(16)))


def _two_word_kernel(lo_ref, hi_ref, seed_ref, out_ref):
    """Spark murmur3 of an 8-byte value from two uint32 planes, per-row
    running-hash seeds (LONG/TIMESTAMP/DOUBLE lanes)."""
    h1 = _mix_h1(seed_ref[:], _mix_k1(lo_ref[:]))
    h1 = _mix_h1(h1, _mix_k1(hi_ref[:]))
    out_ref[:] = _fmix(h1, 8)


def _one_word_kernel(w_ref, seed_ref, out_ref):
    """4-byte value lanes (INT/FLOAT/DATE/BOOLEAN)."""
    out_ref[:] = _fmix(_mix_h1(seed_ref[:], _mix_k1(w_ref[:])), 4)


def pad_to_tiles(x: jnp.ndarray, tile_rows: int = TILE_ROWS):
    """Pad a 1-D lane to a whole number of (tile_rows, 128) VMEM tiles and
    view it 2-D. Returns (tiled view, original length). Shared by every
    Pallas family (murmur3, fused join probe, fused scan-aggregate) so
    padding discipline — garbage rows masked by callers — is uniform."""
    n = x.shape[0]
    per_tile = tile_rows * 128
    tiles = max(1, -(-n // per_tile))
    padded = tiles * per_tile
    if padded != n:
        x = jnp.pad(x, (0, padded - n))
    return x.reshape(tiles * tile_rows, 128), n


def tile_spec(tile_rows: int = TILE_ROWS):
    """BlockSpec for one (tile_rows, 128) VMEM tile of a grid-tiled lane."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec((tile_rows, 128), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def whole_spec():
    """BlockSpec for an operand resident in full across the whole grid
    (bucket tables, key lanes, permutations): every grid step sees the
    same block. Sized by the caller; the fused-tier selector gates shapes
    so these fit the VMEM budget on hardware."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec(memory_space=pltpu.VMEM)


# back-compat private aliases (murmur3 kernels below predate the shared
# helpers going public)
_pad_to_tiles = pad_to_tiles
_tile_spec = tile_spec


@functools.partial(_instrument, label="pallas.murmur3_long",
                   static_argnames=("interpret",))
def murmur3_long_lanes(data_i64, seeds_u32, interpret: bool = False):
    """Per-row murmur3 update over int64 lanes; seeds/result uint32."""
    from jax.experimental import enable_x64
    from jax.experimental import pallas as pl

    pair = jax.lax.bitcast_convert_type(
        data_i64.astype(jnp.int64), jnp.uint32)  # (n, 2): [low, high]
    lo, n = _pad_to_tiles(pair[:, 0])
    hi, _ = _pad_to_tiles(pair[:, 1])
    seeds, _ = _pad_to_tiles(seeds_u32.astype(jnp.uint32))
    rows = lo.shape[0]
    # mosaic wants i32 grid/index arithmetic; the engine's global x64
    # mode would trace the index maps as i64 and fail legalization
    with enable_x64(False):
        # contract: ok dispatch-ledger — traced inline into the
        # instrumented murmur3_long_lanes program above
        out = pl.pallas_call(
            _two_word_kernel,
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.uint32),
            grid=(rows // TILE_ROWS,),
            in_specs=[_tile_spec(), _tile_spec(), _tile_spec()],
            out_specs=_tile_spec(),
            interpret=interpret,
        )(lo, hi, seeds)
    return out.reshape(-1)[:n]


@functools.partial(_instrument, label="pallas.murmur3_int",
                   static_argnames=("interpret",))
def murmur3_int_lanes(data_i32, seeds_u32, interpret: bool = False):
    from jax.experimental import enable_x64
    from jax.experimental import pallas as pl

    w, n = _pad_to_tiles(jax.lax.bitcast_convert_type(
        data_i32.astype(jnp.int32), jnp.uint32))
    seeds, _ = _pad_to_tiles(seeds_u32.astype(jnp.uint32))
    rows = w.shape[0]
    with enable_x64(False):
        # contract: ok dispatch-ledger — traced inline into the
        # instrumented murmur3_int_lanes program above
        out = pl.pallas_call(
            _one_word_kernel,
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.uint32),
            grid=(rows // TILE_ROWS,),
            in_specs=[_tile_spec(), _tile_spec()],
            out_specs=_tile_spec(),
            interpret=interpret,
        )(w, seeds)
    return out.reshape(-1)[:n]


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — backend probe only
        return False
