"""Array (list) kernels over the (child, offsets) layout — the engine's
first slice of the reference's collectionOperations.scala / cuDF lists
column support.

Same dense-gather design as strings: `searchsorted` maps each child
element to its owning row, turning per-row operations into segment
reductions and row-gathers into two vectorized gathers. Fixed-width and
string element types supported; deeper nesting is tagged off."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import ArrayColumn, Column, StringColumn
from ..types import BOOLEAN, INT, BooleanType


def array_lengths(col: ArrayColumn):
    return col.offsets[1:] - col.offsets[:-1]


def _row_of_child(col: ArrayColumn, idx):
    row = jnp.searchsorted(col.offsets, idx, side="right").astype(jnp.int32) - 1
    return jnp.clip(row, 0, col.capacity - 1)


def gather_array(col: ArrayColumn, safe_indices, out_valid,
                 out_child_capacity=None) -> ArrayColumn:
    """Row gather (filter/join/sort reordering) for list columns with
    fixed-width or string children.

    out_child_capacity: static element bucket of the result — an int, or
    an (elements, child_bytes) pair for string-element arrays. Defaults
    to the input's buckets (sufficient for permutations/filters);
    row-DUPLICATING gathers (join probe sides, explode payloads) must
    pass measured needs, like gather_string's out_byte_capacity. A
    duplicating gather of a string-element array WITHOUT a byte
    measurement is guarded by assertion."""
    from .strings import _rebuild_offsets
    in_child_cap = col.child_capacity
    child_byte_cap = None
    if isinstance(out_child_capacity, tuple):
        child_cap, child_byte_cap = out_child_capacity
    else:
        child_cap = out_child_capacity or in_child_cap
    assert child_byte_cap is not None or child_cap <= in_child_cap \
        or not isinstance(col.child, StringColumn), \
        "duplicating gather of array<string> needs child byte measurement"
    lens = array_lengths(col)[safe_indices]
    lens = jnp.where(out_valid, lens, 0)
    new_offsets = _rebuild_offsets(lens)
    src_starts = col.offsets[safe_indices]
    pos = jnp.arange(child_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, safe_indices.shape[0] - 1)
    intra = pos - new_offsets[row]
    in_use = pos < new_offsets[-1]
    src = jnp.where(in_use, jnp.clip(src_starts[row] + intra, 0,
                                     in_child_cap - 1), 0)
    from .basic import gather_column
    child = gather_column(col.child, jnp.where(in_use, src, -1),
                          out_byte_capacity=child_byte_cap)
    return ArrayColumn(child, new_offsets, out_valid, col.dtype)


def concat_arrays(a: ArrayColumn, b: ArrayColumn, a_rows, b_rows,
                  out_capacity: int) -> ArrayColumn:
    """Concatenate two array columns' active rows (coalesce primitive):
    row lengths concatenate, and each side's kept elements gather into the
    combined child buffer."""
    from .strings import _rebuild_offsets
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    from_b = idx >= a_rows
    b_idx = idx - a_rows
    total = a_rows + b_rows
    out_valid = idx < total

    def side_lens(col, rows):
        lens = array_lengths(col)
        act = jnp.arange(col.capacity, dtype=jnp.int32) < rows
        return jnp.where(act, lens, 0), act

    la, act_a = side_lens(a, a_rows)
    lb, act_b = side_lens(b, b_rows)
    a_safe = jnp.where(idx < a.capacity, idx, 0)
    b_safe = jnp.clip(b_idx, 0, b.capacity - 1)
    out_lens = jnp.where(out_valid,
                         jnp.where(from_b, lb[b_safe], la[a_safe]), 0)
    new_offsets = _rebuild_offsets(out_lens)
    valid = jnp.where(from_b, b.validity[b_safe], a.validity[a_safe]) \
        & out_valid

    from ..columnar.column import bucket_capacity
    child_cap = bucket_capacity(max(a.child_capacity + b.child_capacity, 1))
    pos = jnp.arange(child_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, pos, side="right")
                   .astype(jnp.int32) - 1, 0, out_capacity - 1)
    intra = pos - new_offsets[row]
    in_use = pos < new_offsets[-1]
    elem_from_b = from_b[jnp.clip(row, 0, out_capacity - 1)]
    src_a = a.offsets[jnp.clip(row, 0, a.capacity - 1)] + intra
    src_b = b.offsets[jnp.clip(row - a_rows, 0, b.capacity - 1)] + intra
    from .basic import gather_column
    child_a = gather_column(
        a.child, jnp.where(in_use & ~elem_from_b,
                           jnp.clip(src_a, 0, a.child_capacity - 1), -1))
    child_b = gather_column(
        b.child, jnp.where(in_use & elem_from_b,
                           jnp.clip(src_b, 0, b.child_capacity - 1), -1))
    # merge the two gathers (disjoint slots)
    if isinstance(a.child, StringColumn):
        from .strings import string_lengths as _sl
        # string children: pick per-slot from whichever side owns it
        lens_c = jnp.where(elem_from_b, _sl(child_b), _sl(child_a))
        lens_c = jnp.where(in_use, lens_c, 0)
        off_c = _rebuild_offsets(lens_c)
        byte_cap = bucket_capacity(child_a.byte_capacity
                                   + child_b.byte_capacity)
        bpos = jnp.arange(byte_cap, dtype=jnp.int32)
        brow = jnp.clip(jnp.searchsorted(off_c, bpos, side="right")
                        .astype(jnp.int32) - 1, 0, child_cap - 1)
        bintra = bpos - off_c[brow]
        b_use = bpos < off_c[-1]
        eb = elem_from_b[brow]
        pa = jnp.clip(child_a.offsets[brow] + bintra, 0,
                      child_a.byte_capacity - 1)
        pb = jnp.clip(child_b.offsets[brow] + bintra, 0,
                      child_b.byte_capacity - 1)
        data = jnp.where(b_use, jnp.where(eb, child_b.data[pb],
                                          child_a.data[pa]), jnp.uint8(0))
        cvalid = jnp.where(elem_from_b, child_b.validity, child_a.validity)
        child = StringColumn(data, off_c, cvalid, a.child.dtype)
    else:
        cdata = jnp.where(elem_from_b, child_b.data, child_a.data)
        cvalid = jnp.where(elem_from_b, child_b.validity, child_a.validity)
        child = Column(cdata, cvalid, a.child.dtype)
    return ArrayColumn(child, new_offsets, valid, a.dtype)


def array_size(col: ArrayColumn) -> Column:
    """size(arr) (spark.sql.legacy.sizeOfNull=false: null for null)."""
    return Column(array_lengths(col).astype(jnp.int32), col.validity, INT)


def array_contains(col: ArrayColumn, value) -> Column:
    """array_contains(arr, lit): Spark 3-valued result — true if present,
    null if absent but the array has null elements, false otherwise."""
    child = col.child
    cap = col.capacity
    idx = jnp.arange(child.capacity, dtype=jnp.int32)
    row = _row_of_child(col, idx)
    in_use = idx < col.offsets[-1]
    if isinstance(child, StringColumn):
        from .strings import str_starts_with, string_lengths
        needle = value.encode("utf-8") if isinstance(value, str) else value
        eq_data = str_starts_with(child, needle).data & \
            (string_lengths(child) == len(needle))
        match = eq_data & child.validity & in_use
    else:
        match = (child.data == value) & child.validity & in_use
    has_match = jax.ops.segment_max(match.astype(jnp.int32), row,
                                    num_segments=cap) > 0
    has_null = jax.ops.segment_max(
        ((~child.validity) & in_use).astype(jnp.int32), row,
        num_segments=cap) > 0
    valid = col.validity & (has_match | ~has_null)
    return Column(has_match, valid, BOOLEAN)


def element_at(col: ArrayColumn, index: int) -> Column:
    """element_at(arr, i): 1-based; negative from the end; null when out
    of bounds (non-ANSI Spark)."""
    lens = array_lengths(col)
    if index >= 0:
        pos0 = jnp.int32(index - 1)
        pos = jnp.broadcast_to(pos0, lens.shape)
    else:
        pos = lens + index
    ok = (pos >= 0) & (pos < lens) & col.validity
    src = jnp.where(ok, col.offsets[:-1] + pos, -1)
    from .basic import gather_column
    return gather_column(col.child, src)


def element_at_col(col: ArrayColumn, idx: Column) -> Column:
    """element_at(arr, expr): per-row 1-based index, negative from the
    end, null when out of bounds or index null (non-ANSI Spark;
    reference collectionOperations.scala GpuElementAt).

    DEVIATION: Spark raises 'SQL array indices start at 1' for a row whose
    index evaluates to 0 even in non-ANSI mode; this kernel returns NULL
    for such rows. Raising would require a per-batch host sync on a
    data-dependent predicate. The scalar/literal path (ElementAt with a
    static index) does raise, matching Spark."""
    lens = array_lengths(col)
    i = idx.data.astype(jnp.int32)
    pos = jnp.where(i >= 0, i - 1, lens + i)
    ok = (pos >= 0) & (pos < lens) & col.validity & idx.validity
    src = jnp.where(ok, col.offsets[:-1].astype(jnp.int32) + pos, -1)
    from .basic import gather_column
    return gather_column(col.child, src)


def get_array_item(col: ArrayColumn, index: int) -> Column:
    """arr[i]: 0-based, null out of bounds (GetArrayItem non-ANSI)."""
    lens = array_lengths(col)
    pos = jnp.broadcast_to(jnp.int32(index), lens.shape)
    ok = (pos >= 0) & (pos < lens) & col.validity
    src = jnp.where(ok, col.offsets[:-1] + pos, -1)
    from .basic import gather_column
    return gather_column(col.child, src)


def sort_array(col: ArrayColumn, ascending: bool = True) -> ArrayColumn:
    """sort_array: sort elements within each row (fixed-width children).
    Spark: asc => nulls first, desc => nulls last."""
    child = col.child
    assert not isinstance(child, StringColumn), \
        "sort_array over string elements requires sort lanes (planned)"
    idx = jnp.arange(child.capacity, dtype=jnp.int32)
    row = _row_of_child(col, idx)
    in_use = idx < col.offsets[-1]
    data = child.data
    if isinstance(child.dtype, BooleanType):
        data = data.astype(jnp.int8)
    if jnp.issubdtype(data.dtype, jnp.floating):
        # total order incl NaN: flip sign bit trick (f64 bitcasts don't
        # compile on TPU; go through the arithmetic bit reconstruction)
        if data.dtype == jnp.float64:
            from .f64bits import f64_bits_signed
            bits = f64_bits_signed(data)
        else:
            bits = jax.lax.bitcast_convert_type(data, jnp.int32)
        data = jnp.where(bits < 0, ~bits, bits | (jnp.ones((), bits.dtype)
                                                  << (bits.dtype.itemsize * 8 - 1)))
        data = data ^ (jnp.ones((), data.dtype)
                       << (data.dtype.itemsize * 8 - 1))
    # bitwise-not reverses total order with no INT_MIN negation overflow
    key = data if ascending else ~data
    # nulls first (asc) / last (desc): validity as leading key
    null_key = jnp.where(child.validity, 1, 0).astype(jnp.int8)
    if not ascending:
        null_key = -null_key
    # inactive slots stay put at the end of their row span: sort within
    # (row, active) groups by sorting on (row, inactive, null_key, key)
    inactive = (~in_use).astype(jnp.int8)
    _, _, _, _, perm = jax.lax.sort(
        (row, inactive, null_key.astype(jnp.int32),
         key.astype(jnp.int64) if key.dtype != jnp.int64 else key, idx),
        num_keys=4)
    from .basic import gather_column
    new_child = gather_column(child, perm)
    return ArrayColumn(new_child, col.offsets, col.validity, col.dtype)


def array_min_max(col: ArrayColumn, op: str) -> Column:
    """array_min/array_max over fixed-width elements (nulls skipped; null
    when every element is null or the array is empty/null)."""
    child = col.child
    cap = col.capacity
    idx = jnp.arange(child.capacity, dtype=jnp.int32)
    row = _row_of_child(col, idx)
    ok = (idx < col.offsets[-1]) & child.validity
    if op == "min":
        big = jnp.asarray(jnp.inf if jnp.issubdtype(child.data.dtype,
                                                    jnp.floating)
                          else jnp.iinfo(child.data.dtype).max,
                          child.data.dtype)
        vals = jnp.where(ok, child.data, big)
        res = jax.ops.segment_min(vals, row, num_segments=cap)
    else:
        small = jnp.asarray(-jnp.inf if jnp.issubdtype(child.data.dtype,
                                                       jnp.floating)
                            else jnp.iinfo(child.data.dtype).min,
                            child.data.dtype)
        vals = jnp.where(ok, child.data, small)
        res = jax.ops.segment_max(vals, row, num_segments=cap)
    any_ok = jax.ops.segment_max(ok.astype(jnp.int32), row,
                                 num_segments=cap) > 0
    valid = col.validity & any_ok
    return Column(jnp.where(valid, res, jnp.zeros((), res.dtype)), valid,
                  col.dtype.element_type)


def create_array(cols, dtype) -> ArrayColumn:
    """array(c1..ck): k elements per row (fixed-width inputs)."""
    k = len(cols)
    cap = cols[0].capacity
    data = jnp.stack([c.data for c in cols], axis=1).reshape(cap * k)
    valid = jnp.stack([c.validity for c in cols], axis=1).reshape(cap * k)
    child = Column(data, valid, dtype.element_type)
    offsets = jnp.arange(cap + 1, dtype=jnp.int32) * k
    return ArrayColumn(child, offsets, jnp.ones(cap, jnp.bool_), dtype)


# -- round-5 device kernels for the former host-tier family ---------------
# (reference collectionOperations.scala: GpuArrayPosition, GpuArrayRemove,
# GpuArrayDistinct, GpuSlice, GpuFlatten, GpuArraysOverlap, GpuArrayRepeat,
# GpuSequence)

def _elem_grid(col: ArrayColumn):
    """(idx, row, in_use, pos0): child element index, owning row, active
    flag and 0-based position within its row."""
    idx = jnp.arange(col.child_capacity, dtype=jnp.int32)
    row = _row_of_child(col, idx)
    in_use = idx < col.offsets[-1]
    pos0 = idx - col.offsets[row]
    return idx, row, in_use, pos0


def _value_order_key(child: Column):
    """Total-order integer key over fixed-width element values (floats via
    the sign-flip bit trick; TPU forbids f64 bitcasts so f64 goes through
    the arithmetic reconstruction)."""
    data = child.data
    if isinstance(child.dtype, BooleanType):
        return data.astype(jnp.int32)
    if jnp.issubdtype(data.dtype, jnp.floating):
        if data.dtype == jnp.float64:
            from .f64bits import f64_bits_signed
            bits = f64_bits_signed(data)
        else:
            bits = jax.lax.bitcast_convert_type(
                data.astype(jnp.float32), jnp.int32)
        return jnp.where(bits < 0, ~bits,
                         bits | (jnp.ones((), bits.dtype)
                                 << (bits.dtype.itemsize * 8 - 1)))
    return data


def _rebuild_with_keep(col: ArrayColumn, keep) -> ArrayColumn:
    """New ArrayColumn keeping only flagged ACTIVE elements, preserving
    per-row element order (stable global compaction keeps rows
    contiguous)."""
    from .basic import active_mask, compaction_order, gather_column
    idx, row, in_use, _ = _elem_grid(col)
    k = keep & in_use
    counts = jax.ops.segment_sum(k.astype(jnp.int32), row,
                                 num_segments=col.capacity)
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)])
    perm, total = compaction_order(k, col.offsets[-1])
    new_child = gather_column(
        col.child, jnp.where(active_mask(total, col.child_capacity),
                             perm, -1))
    return ArrayColumn(new_child, new_offsets, col.validity, col.dtype)


def _spark_value_eq(a, b):
    """Spark ordering equality (interpreted ordering / Double.compare):
    NaN == NaN, but -0.0 != 0.0 — IEEE == gets both wrong. Floats
    compare by exact bit pattern with NaN canonicalized (f64_bits is the
    TPU-safe arithmetic reconstruction)."""
    if jnp.issubdtype(a.dtype, jnp.floating):
        from .f64bits import f64_bits

        def bits(x):
            d = x.astype(jnp.float64)
            d = jnp.where(jnp.isnan(d), jnp.float64(jnp.nan), d)
            return f64_bits(d)
        return bits(a) == bits(b)
    return a == b


def array_position(col: ArrayColumn, value: Column) -> Column:
    """array_position(arr, v): 1-based first index of v (per-row value),
    0 when absent, NULL when the array or value is NULL. Null elements
    never match; equality is Spark's ordering equality (NaN matches NaN,
    -0.0 does not match 0.0 — Spark GpuArrayPosition)."""
    from ..types import LONG
    idx, row, in_use, pos0 = _elem_grid(col)
    child = col.child
    v_data = value.data[row]
    v_ok = value.validity[row]
    match = in_use & child.validity & v_ok \
        & _spark_value_eq(child.data, v_data)
    big = jnp.int32(2 ** 31 - 1)
    first = jax.ops.segment_min(jnp.where(match, pos0 + 1, big), row,
                                num_segments=col.capacity)
    data = jnp.where(first == big, 0, first).astype(jnp.int64)
    valid = col.validity & value.validity
    return Column(jnp.where(valid, data, 0), valid, LONG)


def array_remove(col: ArrayColumn, value: Column) -> ArrayColumn:
    """array_remove(arr, v): drop elements equal to v (nulls kept,
    Spark ordering equality — see _spark_value_eq); NULL array or NULL v
    gives NULL (Spark GpuArrayRemove)."""
    idx, row, in_use, _ = _elem_grid(col)
    child = col.child
    v_data = value.data[row]
    v_ok = value.validity[row]
    drop = child.validity & v_ok & _spark_value_eq(child.data, v_data)
    out = _rebuild_with_keep(col, ~drop)
    return ArrayColumn(out.child, out.offsets,
                       out.validity & value.validity, out.dtype)


def array_distinct(col: ArrayColumn) -> ArrayColumn:
    """array_distinct: first occurrence of each value kept in original
    order; one NULL element survives (Spark GpuArrayDistinct)."""
    idx, row, in_use, _ = _elem_grid(col)
    child = col.child
    nullflag = (~child.validity).astype(jnp.int32)
    key = _value_order_key(child)
    key = jnp.where(child.validity, key, jnp.zeros((), key.dtype))
    inactive = (~in_use).astype(jnp.int32)
    key64 = key.astype(jnp.int64) if key.dtype != jnp.int64 else key
    srow, sinact, snull, skey, sidx = jax.lax.sort(
        (row, inactive, nullflag, key64, idx), num_keys=4, is_stable=True)
    dup = (srow == jnp.roll(srow, 1)) & (snull == jnp.roll(snull, 1)) \
        & (skey == jnp.roll(skey, 1))
    dup = dup.at[0].set(False)
    first_sorted = ~dup
    keep = jnp.zeros((col.child_capacity,), jnp.bool_) \
        .at[sidx].set(first_sorted)
    return _rebuild_with_keep(col, keep)


def array_slice(col: ArrayColumn, start: Column, length: Column
                ) -> ArrayColumn:
    """slice(arr, start, length): 1-based start, negative from the end
    (Spark GpuSlice). A negative start reaching past the front yields [].

    DEVIATION: rows where start == 0 or length < 0 yield NULL; Spark
    raises at runtime, and a device-side raise on a data-dependent
    predicate would cost a per-batch host sync (the host tier and
    literal-argument paths keep the raise)."""
    idx, row, in_use, pos0 = _elem_grid(col)
    lens = array_lengths(col)
    s = start.data.astype(jnp.int32)
    ln = length.data.astype(jnp.int32)
    s0 = jnp.where(s > 0, s - 1, lens + s)
    s0e = s0[row]
    # negative start past the array front: empty result (host: i<0 -> [])
    keep = (pos0 >= s0e) & (pos0 < s0e + ln[row]) & (s0e >= 0)
    out = _rebuild_with_keep(col, keep)
    bad = ((s == 0) & start.validity) | ((ln < 0) & length.validity)
    valid = out.validity & start.validity & length.validity & ~bad
    return ArrayColumn(out.child, out.offsets, valid, out.dtype)


def flatten_array(col: ArrayColumn) -> ArrayColumn:
    """flatten(arr<arr<T>>) -> arr<T>: pure offset composition (the
    nested layout is row-major contiguous); NULL when the outer row or
    ANY inner array in it is NULL (Spark GpuFlatten)."""
    inner = col.child
    assert isinstance(inner, ArrayColumn), "flatten needs nested arrays"
    o = jnp.clip(col.offsets, 0, inner.capacity)
    new_offsets = inner.offsets[o]
    idx, row, in_use, _ = _elem_grid(col)  # over INNER ROWS as elements
    inner_ok = jnp.where(in_use, inner.validity[
        jnp.clip(idx, 0, inner.capacity - 1)], True)
    all_ok = jax.ops.segment_min(inner_ok.astype(jnp.int32), row,
                                 num_segments=col.capacity) > 0
    valid = col.validity & all_ok
    from ..types import ArrayType
    return ArrayColumn(inner.child, new_offsets, valid,
                       ArrayType(inner.dtype.element_type))


def arrays_overlap(a: ArrayColumn, b: ArrayColumn) -> Column:
    """arrays_overlap(a, b): TRUE when a non-null element is shared;
    NULL when no match but either side holds a NULL element (and both
    are non-empty); FALSE otherwise; NULL when either array is NULL
    (Spark GpuArraysOverlap). Sort-merge: one stable sort of both
    element sets keyed (row, value, side) — any shared value puts an
    a-entry adjacent to a b-entry."""
    ca, cb = a.child, b.child
    na, nb = ca.capacity, cb.capacity
    idx_a, row_a, use_a, _ = _elem_grid(a)
    idx_b, row_b, use_b, _ = _elem_grid(b)
    rows = jnp.concatenate([row_a, row_b])
    use = jnp.concatenate([use_a, use_b])
    validc = jnp.concatenate([ca.validity, cb.validity])
    key = jnp.concatenate([
        _value_order_key(ca).astype(jnp.int64),
        _value_order_key(cb).astype(jnp.int64)])
    key = jnp.where(validc, key, 0)
    side = jnp.concatenate([jnp.zeros((na,), jnp.int32),
                            jnp.ones((nb,), jnp.int32)])
    ok = use & validc
    srow, sbad, skey, sside = jax.lax.sort(
        (rows, (~ok).astype(jnp.int32), key, side), num_keys=4)
    adj = (srow == jnp.roll(srow, 1)) & (sbad == 0) \
        & (jnp.roll(sbad, 1) == 0) & (skey == jnp.roll(skey, 1)) \
        & (sside != jnp.roll(sside, 1))
    adj = adj.at[0].set(False)
    hit = jax.ops.segment_max(adj.astype(jnp.int32), srow,
                              num_segments=a.capacity) > 0
    has_null_a = jax.ops.segment_max(
        (use_a & ~ca.validity).astype(jnp.int32), row_a,
        num_segments=a.capacity) > 0
    has_null_b = jax.ops.segment_max(
        (use_b & ~cb.validity).astype(jnp.int32), row_b,
        num_segments=b.capacity) > 0
    len_a = array_lengths(a)
    len_b = array_lengths(b)
    null_out = ~hit & (has_null_a | has_null_b) & (len_a > 0) & (len_b > 0)
    valid = a.validity & b.validity & ~null_out
    return Column(hit & valid, valid, BOOLEAN)


def array_repeat(elem: Column, count: Column, child_capacity: int
                 ) -> ArrayColumn:
    """array_repeat(e, n): n copies of e per row; negative n gives an
    empty array; NULL n gives NULL (Spark GpuArrayRepeat). The caller
    sizes child_capacity (one measured sync at the expression layer)."""
    from ..types import ArrayType
    cap = elem.capacity
    cnt = jnp.where(count.validity, count.data.astype(jnp.int32), 0)
    cnt = jnp.maximum(cnt, 0)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(cnt)]).astype(jnp.int32)
    idx = jnp.arange(child_capacity, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(offsets, idx, side="right")
                   .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = idx < offsets[-1]
    data = jnp.where(in_use, elem.data[row], jnp.zeros((), elem.data.dtype))
    cvalid = in_use & elem.validity[row]
    child = Column(data, cvalid, elem.dtype)
    return ArrayColumn(child, offsets, count.validity,
                       ArrayType(elem.dtype))


def sequence_array(start: Column, stop: Column, step: Column,
                   child_capacity: int) -> ArrayColumn:
    """sequence(start, stop, step) over integers (Spark GpuSequence);
    rows where the step is zero or points away from stop yield NULL (the
    reference raises — documented deviation, a device-side raise would
    need a host sync). The caller sizes child_capacity."""
    from ..types import ArrayType
    cap = start.capacity
    s = start.data.astype(jnp.int64)
    e = stop.data.astype(jnp.int64)
    st = step.data.astype(jnp.int64)
    in_valid = start.validity & stop.validity & step.validity
    ok_dir = (st != 0) & jnp.where(st > 0, e >= s, e <= s)
    # Spark also allows start==stop with any nonzero step -> [start]
    ok = in_valid & (ok_dir | (s == e))
    n = jnp.where(ok, jnp.abs(
        jnp.where(st != 0, (e - s) // jnp.where(st == 0, 1, st), 0)) + 1, 0)
    n = n.astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(n)]).astype(jnp.int32)
    idx = jnp.arange(child_capacity, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(offsets, idx, side="right")
                   .astype(jnp.int32) - 1, 0, cap - 1)
    in_use = idx < offsets[-1]
    pos = idx - offsets[row]
    data = s[row] + pos.astype(jnp.int64) * st[row]
    child = Column(jnp.where(in_use, data, 0), in_use,
                   start.dtype)
    return ArrayColumn(child, offsets, ok,
                       ArrayType(start.dtype))
