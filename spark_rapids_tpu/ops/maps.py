"""Map-column kernels: build, lookup, keys/values views.

Reference analog: GpuCreateMap / GpuGetMapValue / GpuMapKeys /
GpuMapValues over cuDF LIST<STRUCT> + MapUtils JNI
(collectionOperations.scala). Here maps are (offsets, keys, values)
triplets (columnar/column.MapColumn); a lookup is a flat compare over
the keys child plus one segment-min per row — no per-row loops.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..columnar.column import (ArrayColumn, Column, MapColumn,
                               StringColumn, bucket_capacity)
from ..types import ArrayType, MapType

# plain Python int, NOT a jnp constant: this module is imported
# lazily, sometimes inside a jit trace, and a traced-time jnp
# constant stored in a module global leaks the tracer into every
# later trace (UnexpectedTracerError). Weak promotion keeps the
# int32 arithmetic identical.
_BIG = 1 << 30


def _entry_rows(m: MapColumn):
    ecap = m.entry_capacity
    epos = jnp.arange(ecap, dtype=jnp.int32)
    erow = jnp.searchsorted(m.offsets, epos,
                            side="right").astype(jnp.int32) - 1
    erow = jnp.clip(erow, 0, m.capacity - 1)
    in_use = epos < m.offsets[m.capacity]
    return epos, erow, in_use


def map_keys(m: MapColumn) -> ArrayColumn:
    return ArrayColumn(m.keys, m.offsets, m.validity,
                       ArrayType(m.dtype.key_type, False))


def map_values(m: MapColumn) -> ArrayColumn:
    return ArrayColumn(m.values, m.offsets, m.validity,
                       ArrayType(m.dtype.value_type,
                                 m.dtype.value_contains_null))


def _key_match(m: MapColumn, key) -> jnp.ndarray:
    """(entry_capacity,) bool: entry key == lookup key (per entry row).

    `key` is a host literal or a per-row Column of the key type."""
    keys = m.keys
    epos, erow, in_use = _entry_rows(m)
    if isinstance(keys, StringColumn):
        from .strings import string_lengths
        klens = string_lengths(keys)
        if isinstance(key, Column):
            from .strings import seg_incl_cumsum
            tgt: StringColumn = key  # per-row key strings
            tlens = string_lengths(tgt)[erow]
            # byte-level compare: each byte of the keys child against the
            # same offset of its row's target key
            bcap = keys.byte_capacity
            bpos = jnp.arange(bcap, dtype=jnp.int32)
            bent = jnp.searchsorted(keys.offsets, bpos,
                                    side="right").astype(jnp.int32) - 1
            bent = jnp.clip(bent, 0, keys.capacity - 1)
            boff = bpos - keys.offsets[bent]
            brow = erow[bent]
            tpos = jnp.clip(tgt.offsets[brow] + boff, 0,
                            tgt.byte_capacity - 1)
            bad = (bpos < keys.offsets[-1]) & \
                (keys.data[bpos] != tgt.data[tpos])
            bad_csum = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(bad.astype(jnp.int32))])
            lo = jnp.clip(keys.offsets[:-1], 0, bcap)
            hi = jnp.clip(keys.offsets[1:], 0, bcap)
            match = (klens == tlens) & (bad_csum[hi] - bad_csum[lo] == 0)
            match = match & tgt.validity[erow]
        else:
            kb = key.encode("utf-8") if isinstance(key, str) \
                else bytes(key)
            from .strings import _match_at
            match = (klens == len(kb)) & _match_at(keys, kb,
                                                   keys.offsets[:-1])
    else:
        if isinstance(key, Column):
            match = (keys.data == key.data[erow]) & key.validity[erow]
        else:
            match = keys.data == jnp.asarray(key, keys.data.dtype)
    return match & keys.validity & in_use


def map_get(m: MapColumn, key) -> Column:
    """element_at(map, key) / map[key]: the value of the FIRST entry whose
    key equals `key`; NULL when absent or the key is NULL (non-ANSI)."""
    if key is None:
        vals = m.values
        if isinstance(vals, StringColumn):
            from .strings import gather_string
            idx = jnp.zeros((m.capacity,), jnp.int32)
            return gather_string(vals, idx,
                                 jnp.zeros((m.capacity,), jnp.bool_))
        return Column(jnp.zeros((m.capacity,), vals.data.dtype),
                      jnp.zeros((m.capacity,), jnp.bool_), vals.dtype)
    epos, erow, in_use = _entry_rows(m)
    match = _key_match(m, key)
    first = jax.ops.segment_min(jnp.where(match, epos, _BIG), erow,
                                num_segments=m.capacity)
    has = (first < _BIG) & m.validity
    if isinstance(key, Column):
        has = has & key.validity
    idx = jnp.clip(first, 0, m.entry_capacity - 1)
    vals = m.values
    if isinstance(vals, StringColumn):
        from .strings import gather_string
        valid = has & vals.validity[idx]
        return gather_string(vals, idx, valid)
    data = jnp.where(has, vals.data[idx], jnp.zeros((), vals.data.dtype))
    return Column(data, has & vals.validity[idx], vals.dtype)


def map_contains_key(m: MapColumn, key) -> Column:
    from ..types import BOOLEAN
    epos, erow, _ = _entry_rows(m)
    match = _key_match(m, key)
    any_m = jax.ops.segment_max(match.astype(jnp.int32), erow,
                                num_segments=m.capacity) > 0
    valid = m.validity
    if isinstance(key, Column):
        valid = valid & key.validity
    return Column(jnp.where(valid, any_m, False), valid, BOOLEAN)


def interleave_columns(cols: Sequence[Column]) -> Column:
    """Row-major interleave of k same-type columns into one column of
    k*cap rows: output row r*k + j = cols[j][r]. The CreateMap entry
    builder."""
    k = len(cols)
    cap = cols[0].capacity
    out_cap = bucket_capacity(cap * k)
    if isinstance(cols[0], StringColumn):
        from .strings import _rebuild_offsets, string_lengths
        lens = [string_lengths(c) for c in cols]
        inter_lens = jnp.stack(lens, axis=1).reshape(-1)  # (cap*k,)
        inter_lens = jnp.concatenate(
            [inter_lens, jnp.zeros((out_cap - cap * k,), jnp.int32)])
        new_off = _rebuild_offsets(inter_lens)
        byte_cap = bucket_capacity(
            max(sum(int(c.byte_capacity) for c in cols), 1))
        bpos = jnp.arange(byte_cap, dtype=jnp.int32)
        orow = jnp.searchsorted(new_off, bpos,
                                side="right").astype(jnp.int32) - 1
        orow = jnp.clip(orow, 0, out_cap - 1)
        src_row = orow // k
        src_col = orow % k
        intra = bpos - new_off[orow]
        in_use = bpos < new_off[-1]
        byte = jnp.zeros((byte_cap,), jnp.uint8)
        for j, c in enumerate(cols):
            sp = jnp.clip(c.offsets[jnp.clip(src_row, 0, cap - 1)] + intra,
                          0, c.byte_capacity - 1)
            byte = jnp.where(src_col == j, c.data[sp], byte)
        data = jnp.where(in_use, byte, jnp.uint8(0))
        valid = jnp.stack([c.validity for c in cols], axis=1).reshape(-1)
        valid = jnp.concatenate(
            [valid, jnp.zeros((out_cap - cap * k,), jnp.bool_)])
        return StringColumn(data, new_off, valid, cols[0].dtype)
    data = jnp.stack([c.data for c in cols], axis=1).reshape(-1)
    valid = jnp.stack([c.validity for c in cols], axis=1).reshape(-1)
    pad = out_cap - cap * k
    data = jnp.concatenate([data, jnp.zeros((pad,), data.dtype)])
    valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
    return Column(data, valid, cols[0].dtype)


def create_map(key_cols: Sequence[Column], val_cols: Sequence[Column],
               num_rows, dtype: MapType) -> MapColumn:
    """map(k1, v1, k2, v2, ...): k static pairs per row. Duplicate keys
    are kept in entry order and every consumer (map_get, to_pylist, host
    rows) resolves them FIRST-wins — a documented divergence from
    Spark's default EXCEPTION dedup policy (which errors) chosen so the
    engine never has to raise from inside a compiled kernel."""
    k = len(key_cols)
    cap = key_cols[0].capacity
    keys = interleave_columns(key_cols)
    vals = interleave_columns(val_cols)
    from .basic import active_mask
    act = active_mask(num_rows, cap)
    # every row slot owns exactly k interleaved entries (offsets must
    # stay aligned with the row-major entry layout even for padded rows;
    # padded rows are invalid so their entries are never read)
    off = jnp.arange(cap + 1, dtype=jnp.int32) * k
    return MapColumn(keys, vals, off, act, dtype)
