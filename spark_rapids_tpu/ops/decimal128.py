"""Two-limb (hi int64 / lo uint64-in-int64) decimal128 kernels.

Reference analog: cuDF decimal128 + DecimalUtil.scala /
decimalExpressions.scala. The TPU build stores the 128-bit unscaled
value as TWO int64 lanes (lo carries the low 64 bits reinterpreted as
signed; hi carries the high 64 including the sign). All arithmetic is
built from u32 half-limbs so every multiply stays within the emulated
64-bit lanes XLA already supports.

Layout invariant: value = hi * 2^64 + (lo as unsigned).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

# numpy scalar: a module-level jnp call captures a tracer when first
# imported inside a jit trace (PR 2 class; contract trace-module-jnp)
_U32 = np.uint64(0xFFFFFFFF)


def _u(x):
    return x.astype(jnp.uint64)


def _s(x):
    return x.astype(jnp.int64)


def from_i64(v) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sign-extend an int64 unscaled value to (hi, lo)."""
    return v >> jnp.int64(63), v


def add128(h1, l1, h2, l2):
    lo = _s(_u(l1) + _u(l2))
    carry = _u(lo) < _u(l1)
    hi = h1 + h2 + carry.astype(jnp.int64)
    return hi, lo


def neg128(h, l):
    lo = _s(~_u(l) + jnp.uint64(1))
    hi = ~h + (lo == 0).astype(jnp.int64)
    return hi, lo


def sub128(h1, l1, h2, l2):
    nh, nl = neg128(h2, l2)
    return add128(h1, l1, nh, nl)


def is_neg(h):
    return h < 0


def abs128(h, l):
    nh, nl = neg128(h, l)
    neg = is_neg(h)
    return jnp.where(neg, nh, h), jnp.where(neg, nl, l)


def cmp128(h1, l1, h2, l2):
    """-1 / 0 / +1 as int32 (signed 128-bit compare)."""
    lt = (h1 < h2) | ((h1 == h2) & (_u(l1) < _u(l2)))
    gt = (h1 > h2) | ((h1 == h2) & (_u(l1) > _u(l2)))
    return gt.astype(jnp.int32) - lt.astype(jnp.int32)


def _mul_u64(a, b):
    """u64 x u64 -> (hi u64, lo u64) via u32 half-limbs."""
    a, b = _u(a), _u(b)
    a0, a1 = a & _U32, a >> jnp.uint64(32)
    b0, b1 = b & _U32, b >> jnp.uint64(32)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> jnp.uint64(32)) + (p01 & _U32) + (p10 & _U32)
    lo = (p00 & _U32) | (mid << jnp.uint64(32))
    hi = p11 + (p01 >> jnp.uint64(32)) + (p10 >> jnp.uint64(32)) \
        + (mid >> jnp.uint64(32))
    return hi, lo


def mul_i64_i64(a, b):
    """Signed 64 x 64 -> exact signed 128 (hi, lo)."""
    sign = (a < 0) ^ (b < 0)
    ua = _u(jnp.where(a < 0, -a, a))
    ub = _u(jnp.where(b < 0, -b, b))
    hi, lo = _mul_u64(ua, ub)
    hi, lo = _s(hi), _s(lo)
    nh, nl = neg128(hi, lo)
    return jnp.where(sign, nh, hi), jnp.where(sign, nl, lo)


def mul128_u64(h, l, m) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(h,l) * unsigned 64-bit m -> (hi, lo, overflowed). Sign-aware:
    operates on |x| then restores the sign."""
    neg = is_neg(h)
    ah, al = abs128(h, l)
    hi_lo, lo = _mul_u64(al, m)            # low limb product
    hi2_hi, hi2_lo = _mul_u64(_u(ah), m)   # high limb product
    hi = _u(hi_lo) + hi2_lo
    carry_over = (hi2_hi != 0) | (hi < hi2_lo)
    # result |x| must fit 127 bits
    over = carry_over | (_s(hi) < 0)
    rh, rl = _s(hi), _s(lo)
    nh, nl = neg128(rh, rl)
    return jnp.where(neg, nh, rh), jnp.where(neg, nl, rl), over


def _divmod_u32(h, l, d32: int):
    """Unsigned (h,l) divided by a host u32 constant-or-array divisor
    d32 < 2^31: schoolbook long division over four u32 digits."""
    d = jnp.uint64(d32) if isinstance(d32, int) else _u(d32)
    digits = [
        _u(h) >> jnp.uint64(32), _u(h) & _U32,
        _u(l) >> jnp.uint64(32), _u(l) & _U32,
    ]
    r = jnp.zeros_like(_u(h))
    q = []
    for dig in digits:
        cur = (r << jnp.uint64(32)) | dig   # < d*2^32 <= 2^63: fits u64
        q.append(cur // d)
        r = cur % d
    qh = _s((q[0] << jnp.uint64(32)) | q[1])
    ql = _s((q[2] << jnp.uint64(32)) | q[3])
    return qh, ql, r


def divmod_pow10(h, l, k: int):
    """Signed (h,l) // 10^k, k in [0, 38]. Returns
    (qh, ql, last_rem_u64, last_half_u64): the staged division's FINAL
    remainder decides HALF_UP exactly — rem_total >= 10^k/2 iff the most
    significant stage's remainder >= its own half (the earlier stages'
    remainders only add < one final-stage unit), so no 128-bit remainder
    tracking is needed and k > 19 cannot overflow any u64 constant."""
    assert 0 <= k <= 38
    if k == 0:
        return h, l, jnp.zeros_like(_u(h)), jnp.uint64(1)
    neg = is_neg(h)
    ah, al = abs128(h, l)
    r = jnp.zeros_like(_u(h))
    last = 1
    for step in _pow10_steps(k):
        d = 10 ** step
        ah, al, r = _divmod_u32(ah, al, d)
        last = d
    nh, nl = neg128(ah, al)
    qh = jnp.where(neg, nh, ah)
    ql = jnp.where(neg, nl, al)
    half = jnp.uint64(last // 2)
    return qh, ql, r, half


def _pow10_steps(k: int):
    """Split 10^k into factors < 2^31 (each <= 10^9)."""
    out = []
    while k > 0:
        s = min(k, 9)
        out.append(s)
        k -= s
    return out


def rescale(h, l, from_scale: int, to_scale: int):
    """Unscaled rescale with Spark HALF_UP rounding on scale reduction.
    Returns (hi, lo, overflowed)."""
    if to_scale == from_scale:
        return h, l, jnp.zeros(h.shape, jnp.bool_)
    if to_scale > from_scale:
        k = to_scale - from_scale
        over = jnp.zeros(h.shape, jnp.bool_)
        for step in _pow10_steps(k):
            h, l, o = mul128_u64(h, l, jnp.uint64(10 ** step))
            over = over | o
        return h, l, over
    k = from_scale - to_scale
    qh, ql, rem, half = divmod_pow10(h, l, k)
    # HALF_UP: round away from zero when |rem| >= half
    bump = rem >= half
    neg = is_neg(h)
    bh, bl = add128(qh, ql, jnp.where(neg & bump, -1, 0),
                    jnp.where(bump, jnp.where(neg, -1, 1), 0))
    return bh, bl, jnp.zeros(h.shape, jnp.bool_)


def pow10_128(k: int) -> Tuple[int, int]:
    """(hi, lo) host ints of 10^k for overflow bounds."""
    v = 10 ** k
    return (v >> 64), v & ((1 << 64) - 1)


def fits_precision(h, l, precision: int):
    """|value| < 10^precision (the non-ANSI overflow -> NULL check)."""
    ah, al = abs128(h, l)
    bh, bl = pow10_128(precision)
    bhj = jnp.int64(bh if bh < (1 << 63) else bh - (1 << 64))
    blj = jnp.int64(bl if bl < (1 << 63) else bl - (1 << 64))
    return cmp128(ah, al, bhj, blj) < 0


def divmod128_u64(h, l, d):
    """Unsigned (h,l) // d for a VARIABLE u64 divisor d < 2^63.
    Returns (qh, ql, rem). Schoolbook: the high limb divides natively;
    the (rem, lo) double-word divides by 64 unrolled binary steps
    (rem stays < d < 2^63 so the shifted partial fits u64)."""
    uh, ul, ud = _u(h), _u(l), _u(d)
    qh = uh // ud
    r = uh % ud
    ql = jnp.zeros_like(ul)
    for i in range(63, -1, -1):
        bit = (ul >> jnp.uint64(i)) & jnp.uint64(1)
        r = (r << jnp.uint64(1)) | bit
        ge = r >= ud
        r = jnp.where(ge, r - ud, r)
        ql = ql | jnp.where(ge, jnp.uint64(1) << jnp.uint64(i),
                            jnp.uint64(0))
    return _s(qh), _s(ql), r


def div128_round_half_up(h, l, d):
    """Signed (h,l) / signed i64 d (nonzero), HALF_UP rounding."""
    neg = is_neg(h) ^ (d < 0)
    ah, al = abs128(h, l)
    ad = _u(jnp.where(d < 0, -d, d))
    qh, ql, r = divmod128_u64(ah, al, ad)
    bump = (r * jnp.uint64(2)) >= ad
    qh, ql = add128(qh, ql, jnp.zeros_like(qh),
                    bump.astype(jnp.int64))
    nh, nl = neg128(qh, ql)
    return jnp.where(neg, nh, qh), jnp.where(neg, nl, ql)


def shl128(h, l, k: int):
    """Logical left shift of (h,l) by k in [0, 63]."""
    if k == 0:
        return h, l
    uh, ul = _u(h), _u(l)
    nh = (uh << jnp.uint64(k)) | (ul >> jnp.uint64(64 - k))
    nl = ul << jnp.uint64(k)
    return _s(nh), _s(nl)


def limb16_lanes(h, l):
    """Eight u16 limbs (as int64 lanes, low first) of the UNSIGNED
    128-bit representation. Summing each lane exactly in int64 (bounded
    by 2^16 * rows) and recombining mod 2^128 gives the exact two's
    complement 128-bit sum with ordinary masked/segment sums — no custom
    reduction combiner needed."""
    mask = jnp.uint64(0xFFFF)
    out = []
    for src in (l, h):
        u = _u(src)
        for k in range(4):
            out.append(_s((u >> jnp.uint64(16 * k)) & mask))
    return out


#: saturation sentinel for overflowed decimal sums: i128 max, magnitude
#: ~1.7e38 — beyond every legal decimal(38) value, so it can only arise
#: from saturation and the any-input-saturated check keeps it sticky
SAT_HI = (1 << 63) - 1
SAT_LO = -1


def _add192(a2, a1, a0, b2, b1, b0):
    lo = _s(_u(a0) + _u(b0))
    c0 = (_u(lo) < _u(a0)).astype(jnp.int64)
    mid = _s(_u(a1) + _u(b1) + _u(c0))
    c1 = ((_u(mid) < _u(a1)) | ((c0 == 1) & (_u(mid) == _u(a1)))
          ).astype(jnp.int64)
    hi = a2 + b2 + c1
    return hi, mid, lo


def combine_limb_sums(sums):
    """Recombine eight per-limb int64 sums into (hi, lo) mod 2^128.
    Use combine_limb_sums_checked when overflow past signed 128 bits
    must surface (decimal sum accumulation)."""
    rh, rl = combine_limb_sums_checked(sums)[:2]
    return rh, rl


def combine_limb_sums_checked(sums, neg_count=None):
    """(hi, lo, overflowed): exact 192-bit accumulation of the shifted
    limb sums, so a true sum past +-2^127 is DETECTED instead of
    aliasing back into range mod 2^128.

    The limbs decompose each value's UNSIGNED two's-complement pattern,
    so every NEGATIVE input inflates the 192-bit total by exactly 2^128;
    `neg_count` (per-slot count of negative summed values) corrects the
    top limb before the fits-signed-128 test. None disables the check
    (overflowed is returned as all-False)."""
    t2 = jnp.zeros_like(sums[0])
    t1 = jnp.zeros_like(sums[0])
    t0 = jnp.zeros_like(sums[0])
    for k, s in enumerate(sums):
        bits = 16 * k
        # sign-extend s to 3 limbs, then shift left by `bits` (< 128)
        s2, s1, s0 = s >> jnp.int64(63), s >> jnp.int64(63), s
        if bits:
            if bits < 64:
                nb = jnp.uint64(bits)
                inv = jnp.uint64(64 - bits)
                n0 = _s(_u(s0) << nb)
                n1 = _s((_u(s1) << nb) | (_u(s0) >> inv))
                n2 = _s((_u(s2) << nb) | (_u(s1) >> inv))
                s2, s1, s0 = n2, n1, n0
            else:
                nb = jnp.uint64(bits - 64)
                inv = jnp.uint64(64 - (bits - 64)) if bits > 64 else None
                if bits == 64:
                    s2, s1, s0 = s1, s0, jnp.zeros_like(s0)
                else:
                    n1 = _s(_u(s0) << nb)
                    n2 = _s((_u(s1) << nb) | (_u(s0) >> inv))
                    s2, s1, s0 = n2, n1, jnp.zeros_like(s0)
        t2, t1, t0 = _add192(t2, t1, t0, s2, s1, s0)
    if neg_count is None:
        return t1, t0, jnp.zeros(t1.shape, jnp.bool_)
    # fits signed 128 iff (after removing the unsigned-representation
    # inflation) the top limb is the sign extension of the mid limb
    over = (t2 - neg_count) != (t1 >> jnp.int64(63))
    return t1, t0, over


def saturate_sum(rh, rl, over, any_sat):
    """Apply decimal-sum overflow semantics: past signed-128 (or fed by
    an already-saturated partial) the slot pins to the SAT sentinel,
    which fails fits_precision at evaluate -> NULL (Spark saturates
    decimal sums at the buffer precision the same way)."""
    bad = over | any_sat
    rh = jnp.where(bad, jnp.int64(SAT_HI), rh)
    rl = jnp.where(bad, jnp.int64(SAT_LO), rl)
    return rh, rl


def is_saturated(h, l):
    return (h == jnp.int64(SAT_HI)) & (l == jnp.int64(SAT_LO))


def decimal_segment_sum(col, valid_mask, seg, capacity: int):
    """Exact 128-bit segment sum of a decimal column (either tier):
    eight u16-limb int64 segment sums recombined with 192-bit overflow
    detection and sticky saturation.
    Returns ((hi, lo) (capacity,) limb arrays, has_any bool array)."""
    import jax

    from .maskedagg import _decimal_limbs
    h, l = _decimal_limbs(col)
    sums = [jax.ops.segment_sum(
        jnp.where(valid_mask, lane, jnp.int64(0)), seg,
        num_segments=capacity) for lane in limb16_lanes(h, l)]
    negs = jax.ops.segment_sum(
        ((h < 0) & valid_mask).astype(jnp.int64), seg,
        num_segments=capacity)
    rh, rl, over = combine_limb_sums_checked(sums, negs)
    any_sat = jax.ops.segment_max(
        (is_saturated(h, l) & valid_mask).astype(jnp.int32), seg,
        num_segments=capacity) > 0
    rh, rl = saturate_sum(rh, rl, over, any_sat)
    counts = jax.ops.segment_sum(valid_mask.astype(jnp.int32), seg,
                                 num_segments=capacity)
    return (rh, rl), counts > 0


def to_f64(h, l):
    return h.astype(jnp.float64) * jnp.float64(2.0 ** 64) \
        + _u(l).astype(jnp.float64)


def fits_i64(h, l):
    """value representable in one int64 limb?"""
    return h == (l >> jnp.int64(63))
