"""Spark-compatible hash kernels: Murmur3_x86_32 (seed 42) and XxHash64.

Replaces the reference's JNI Hash kernels (spark-rapids-jni `Hash`, used by
HashFunctions.scala and GpuHashPartitioningBase.scala). Bit-for-bit parity
with Spark's Murmur3Hash / XxHash64 expressions is required because hash
partitioning decides shuffle placement: a CPU-partial / TPU-final aggregate
must agree on row placement.

All lanes vectorized on the VPU; uint32/uint64 wrap-around arithmetic is
native in XLA. Variable-length (string) hashing uses a device-side
while_loop over 4-byte words with per-row masking — trip count is the max
byte length in the batch, known only on device, which XLA handles fine in a
while loop (no recompile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column, StringColumn, StructColumn
from ..columnar.encoded import DictionaryColumn, row_byte_lanes
from ..types import (
    BooleanType, ByteType, DateType, DecimalType, DoubleType, FloatType,
    IntegerType, LongType, ShortType, StringType, TimestampType,
)

# --- Murmur3_x86_32 -------------------------------------------------------

# numpy (not jnp) scalars: a module-level jnp call builds a jax array at
# IMPORT time, and a first import inside a jit trace captures a tracer —
# the PR 2 order-dependent leak class (contract rule trace-module-jnp).
# Every use site has a jax operand, so dtype semantics are unchanged.
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    return _rotl32(k1 * _C1, 15) * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> 16)
    return h1


def _use_pallas() -> bool:
    """Static (trace-time) tier choice: the Pallas kernel on real TPU
    when spark.rapids.tpu.pallas.enabled, else the fused-XLA path. An
    open `pallas_hash` circuit breaker (exec/lifecycle.FAMILY_DOMAINS
    entry for the `murmur3` family, ISSUE 8) demotes NEW traces to the
    XLA formulation like the fused-tier families."""
    from ..config import PALLAS_ENABLED, active_conf
    from .pallas_kernels import on_tpu
    try:
        if not (on_tpu() and active_conf().get(PALLAS_ENABLED)):
            return False
        # one implementation of breaker-consult + engagement noting
        # (shared with the fused-tier families)
        from .pallas_tier import _breaker_allows, _note_engaged
        if not _breaker_allows("murmur3"):
            return False
        _note_engaged("murmur3")
        return True
    except Exception:  # noqa: BLE001 — conf unavailable during early init
        return False


def murmur3_int(v, seed):
    """v: int32 lanes; seed: uint32 lanes. Spark Murmur3_x86_32.hashInt."""
    if _use_pallas():
        from .pallas_kernels import murmur3_int_lanes
        return murmur3_int_lanes(v, seed)
    k1 = _mix_k1(v.astype(jnp.uint32))
    return _fmix(_mix_h1(seed, k1), 4)


def murmur3_long(v, seed):
    if _use_pallas():
        from .pallas_kernels import murmur3_long_lanes
        return murmur3_long_lanes(v, seed)
    v = v.astype(jnp.uint64)
    low = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (v >> 32).astype(jnp.uint32)
    h1 = _mix_h1(seed, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8)


def _normalize_float(data, dtype):
    """Spark normalizes -0.0 to 0.0 before hashing."""
    zero = jnp.zeros((), data.dtype)
    return jnp.where(data == zero, zero, data)


def murmur3_bytes(lengths, starts, data, byte_cap, seed):
    """Spark Murmur3_x86_32.hashUnsafeBytes over per-row (start, length)
    byte spans of a flat buffer: little-endian 4-byte words, then
    trailing bytes one at a time (sign-extended). The span form (ISSUE
    18) lets dictionary columns hash through code-indirected starts
    without materializing."""
    def word_at(t):
        # little-endian 4-byte word at starts + 4t per row
        base = starts + 4 * t
        b = [data[jnp.clip(base + j, 0, byte_cap - 1)].astype(jnp.uint32)
             for j in range(4)]
        return b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)

    max_words = jnp.max(lengths) // 4

    def body(carry):
        t, h1 = carry
        active = (4 * (t + 1)) <= lengths
        h1_new = _mix_h1(h1, _mix_k1(word_at(t)))
        return t + 1, jnp.where(active, h1_new, h1)

    def cond(carry):
        t, _ = carry
        return t < max_words

    h0 = jnp.broadcast_to(seed, lengths.shape).astype(jnp.uint32)
    _, h1 = jax.lax.while_loop(cond, body, (jnp.int32(0), h0))

    # trailing 0..3 bytes, one at a time, sign-extended to int32
    aligned = (lengths // 4) * 4
    for j in range(3):
        p = jnp.clip(starts + aligned + j, 0, byte_cap - 1)
        byte = data[p].astype(jnp.int8).astype(jnp.int32)  # sign extension
        active = (aligned + j) < lengths
        h1 = jnp.where(active, _mix_h1(h1, _mix_k1(byte.astype(jnp.uint32))), h1)
    return _fmix(h1, lengths.astype(jnp.uint32))


def murmur3_string(col: StringColumn, seed):
    """Spark Murmur3_x86_32.hashUnsafeBytes over a string column."""
    lengths = (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)
    return murmur3_bytes(lengths, col.offsets[:-1], col.data,
                         col.byte_capacity, seed)


def murmur3_column(col: Column, seed) -> jnp.ndarray:
    """Per-row murmur3 update: null rows leave the running hash unchanged
    (Spark semantics). seed is uint32 lanes (running hash)."""
    dt = col.dtype
    if isinstance(col, DictionaryColumn):
        # non-uniform running hash: hash each row's dictionary bytes
        # through code-indirected (start, length) spans — no decode.
        # (murmur3_batch owns the uniform-seed precompute fast path.)
        lengths, starts, data, byte_cap = row_byte_lanes(col)
        h = murmur3_bytes(lengths.astype(jnp.int32), starts, data,
                          byte_cap, seed)
    elif isinstance(col, StringColumn):
        h = murmur3_string(col, seed)
    elif isinstance(col, StructColumn):
        h = seed
        for kid in col.children:
            h = murmur3_column(kid, h)
        return jnp.where(col.validity, h, seed)
    elif isinstance(dt, BooleanType):
        h = murmur3_int(col.data.astype(jnp.int32), seed)
    elif isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
        h = murmur3_int(col.data.astype(jnp.int32), seed)
    elif isinstance(dt, (LongType, TimestampType)):
        h = murmur3_long(col.data, seed)
    elif isinstance(dt, FloatType):
        bits = jax.lax.bitcast_convert_type(
            _normalize_float(col.data, dt), jnp.int32)
        h = murmur3_int(bits, seed)
    elif isinstance(dt, DoubleType):
        from .f64bits import f64_bits_signed
        bits = f64_bits_signed(_normalize_float(col.data, dt))
        h = murmur3_long(bits, seed)
    elif isinstance(dt, DecimalType) and not dt.is_decimal128:
        h = murmur3_long(col.data, seed)
    else:
        raise TypeError(f"murmur3 unsupported for {dt}")
    return jnp.where(col.validity, h, seed)


def murmur3_batch(columns, seed: int = 42) -> jnp.ndarray:
    """Spark Murmur3Hash(cols..., 42) -> int32 lanes."""
    cap = columns[0].capacity
    h = jnp.full((cap,), jnp.uint32(seed))
    for i, col in enumerate(columns):
        if i == 0 and isinstance(col, DictionaryColumn):
            # ISSUE 18: the running hash is still the uniform scalar
            # seed, so hash the dictionary ONCE and serve per-row
            # hashes as a code-indexed gather of the precomputed table
            # (not a re-hash per row). Later fold positions carry
            # per-row hashes and take murmur3_column's span path.
            from ..columnar.encoded import dict_take, dictionary_hashes
            table = dictionary_hashes(col, seed)
            h = jnp.where(col.validity, dict_take(table, col.codes), h)
        else:
            h = murmur3_column(col, h)
    return h.astype(jnp.int32)


# --- XxHash64 -------------------------------------------------------------

# numpy scalars for the same reason as _C1/_C2 above (every use site
# folds into a jax uint64 expression: seeds are always jax lanes)
_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(x, r):
    return (x << r) | (x >> (64 - r))


def _xx_fmix(h):
    h = h ^ (h >> 33)
    h = h * _P2
    h = h ^ (h >> 29)
    h = h * _P3
    h = h ^ (h >> 32)
    return h


def xxhash64_int(v, seed):
    """Spark XXH64.hashInt: the int's 4 bytes, zero-extended."""
    h = seed + _P5 + jnp.uint64(4)
    k = (v.astype(jnp.uint32).astype(jnp.uint64)) * _P1
    h = _rotl64(h ^ k, 23) * _P2 + _P3
    return _xx_fmix(h)


def xxhash64_long(v, seed):
    h = seed + _P5 + jnp.uint64(8)
    k = _rotl64(v.astype(jnp.uint64) * _P2, 31) * _P1
    h = h ^ k
    h = _rotl64(h, 27) * _P1 + _P4
    return _xx_fmix(h)


def xxhash64_string(col: StringColumn, seed):
    """XXH64 over utf-8 bytes per row (Spark XXH64.hashUnsafeBytesBlock)."""
    lengths = (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)
    starts = col.offsets[:-1]
    byte_cap = col.byte_capacity
    data = col.data

    def word64_at(base):
        b = [data[jnp.clip(base + j, 0, byte_cap - 1)].astype(jnp.uint64)
             for j in range(8)]
        out = b[0]
        for j in range(1, 8):
            out = out | (b[j] << (8 * j))
        return out

    def word32_at(base):
        b = [data[jnp.clip(base + j, 0, byte_cap - 1)].astype(jnp.uint32)
             for j in range(4)]
        return b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)

    n = lengths.shape[0]
    seed_l = jnp.broadcast_to(seed, (n,)).astype(jnp.uint64)
    long_input = lengths >= 32

    # 32-byte stripe accumulators (only for rows with >= 32 bytes)
    v1 = seed_l + _P1 + _P2
    v2 = seed_l + _P2
    v3 = seed_l
    v4 = seed_l - _P1
    stripes = lengths // 32
    max_stripes = jnp.max(stripes)

    def stripe_body(carry):
        s, v1, v2, v3, v4 = carry
        base = starts + 32 * s
        act = s < stripes

        def upd(v, off):
            nv = _rotl64(v + word64_at(base + off) * _P2, 31) * _P1
            return jnp.where(act, nv, v)

        return s + 1, upd(v1, 0), upd(v2, 8), upd(v3, 16), upd(v4, 24)

    _, v1, v2, v3, v4 = jax.lax.while_loop(
        lambda c: c[0] < max_stripes, stripe_body,
        (jnp.int32(0), v1, v2, v3, v4))

    hash_big = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) +
                _rotl64(v4, 18))

    def merge(h, v):
        h = h ^ (_rotl64(v * _P2, 31) * _P1)
        return h * _P1 + _P4

    hash_big = merge(merge(merge(merge(hash_big, v1), v2), v3), v4)
    h = jnp.where(long_input, hash_big, seed_l + _P5)
    h = h + lengths.astype(jnp.uint64)

    # remaining 8-byte words
    consumed = stripes * 32
    rem8 = (lengths - consumed) // 8
    max8 = jnp.max(rem8)

    def rem8_body(carry):
        t, h, consumed_t = carry
        act = t < rem8
        k = _rotl64(word64_at(starts + consumed_t) * _P2, 31) * _P1
        nh = _rotl64(h ^ k, 27) * _P1 + _P4
        return (t + 1, jnp.where(act, nh, h),
                jnp.where(act, consumed_t + 8, consumed_t))

    _, h, consumed = jax.lax.while_loop(
        lambda c: c[0] < max8, rem8_body, (jnp.int32(0), h, consumed))

    # one 4-byte word
    has4 = (lengths - consumed) >= 4
    k4 = word32_at(starts + consumed).astype(jnp.uint64) * _P1
    nh = _rotl64(h ^ k4, 23) * _P2 + _P3
    h = jnp.where(has4, nh, h)
    consumed = jnp.where(has4, consumed + 4, consumed)

    # trailing bytes
    for j in range(3):
        p = jnp.clip(starts + consumed + j, 0, byte_cap - 1)
        act = (consumed + j) < lengths
        k1 = data[p].astype(jnp.uint64) * _P5
        nh = _rotl64(h ^ k1, 11) * _P1
        h = jnp.where(act, nh, h)
    return _xx_fmix(h)


def xxhash64_column(col: Column, seed) -> jnp.ndarray:
    dt = col.dtype
    if isinstance(col, StringColumn):
        h = xxhash64_string(col, seed)
    elif isinstance(dt, BooleanType):
        h = xxhash64_int(col.data.astype(jnp.int32), seed)
    elif isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
        h = xxhash64_int(col.data.astype(jnp.int32), seed)
    elif isinstance(dt, (LongType, TimestampType)):
        h = xxhash64_long(col.data, seed)
    elif isinstance(dt, FloatType):
        bits = jax.lax.bitcast_convert_type(
            _normalize_float(col.data, dt), jnp.int32)
        h = xxhash64_int(bits, seed)
    elif isinstance(dt, DoubleType):
        from .f64bits import f64_bits_signed
        bits = f64_bits_signed(_normalize_float(col.data, dt))
        h = xxhash64_long(bits, seed)
    elif isinstance(dt, DecimalType) and not dt.is_decimal128:
        h = xxhash64_long(col.data, seed)
    elif isinstance(col, StructColumn):
        # decimal128/struct: fold the children (limbs) — engine-internal
        # consistency (bucketing/grouping); cross-system partition parity
        # for >18-digit decimals is not claimed
        h = seed
        for kid in col.children:
            h = xxhash64_column(kid, h)
        return jnp.where(col.validity, h, seed)
    else:
        raise TypeError(f"xxhash64 unsupported for {dt}")
    return jnp.where(col.validity, h, seed)


def xxhash64_batch(columns, seed: int = 42) -> jnp.ndarray:
    """Spark XxHash64(cols..., 42) -> int64 lanes; null columns pass seed on."""
    cap = columns[0].capacity
    h = jnp.full((cap,), jnp.uint64(seed))
    for col in columns:
        h = xxhash64_column(col, h)
    return h.astype(jnp.int64)


def pmod(h, n: int):
    """Spark's positive-mod used by hash partitioning."""
    r = h % n
    return jnp.where(r < 0, r + n, r)
