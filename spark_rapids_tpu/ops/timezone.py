"""Device timezone database — the reference's GpuTimeZoneDB
(spark-rapids-jni) + TimeZoneDB.scala:61: timezone transition tables are
loaded ONCE onto the device and non-UTC datetime expressions become
searchsorted + add over those tables, so from_utc_timestamp /
to_utc_timestamp stay fully columnar (no host round trip per row).

The tables come straight from the system tzdata (IANA TZif files under
/usr/share/zoneinfo), parsed here — the TPU build's equivalent of the
JNI library shipping a compiled tzdb. Fixed-offset zones (UTC+HH:MM) are
synthesized without a file.

Semantics: wall-clock conversions use fold=0 (earlier offset) for
ambiguous local times during DST overlaps, matching Java's
ZonedDateTime.of / Spark's zoneId rules for the overlap case.
"""

from __future__ import annotations

import os
import re
import struct
import threading
from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

MICROS = 1_000_000
_TZDIR = os.environ.get("TZDIR", "/usr/share/zoneinfo")

# sentinel transition far before any real data so searchsorted never
# lands at -1 (covers the pre-first-transition LMT era)
_NEG_INF = -(1 << 62)


def _parse_tzif(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """TZif v1/v2/v3 → (transition instants [utc seconds], utc offsets
    [seconds]) with a leading era entry. RFC 8536."""
    with open(path, "rb") as f:
        data = f.read()

    def parse_block(buf, off, time_size, time_fmt):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt
         ) = struct.unpack_from(">6I", buf, off + 20)
        p = off + 44
        trans = np.frombuffer(buf, dtype=time_fmt, count=timecnt, offset=p
                              ).astype(np.int64)
        p += timecnt * time_size
        idx = np.frombuffer(buf, dtype=np.uint8, count=timecnt, offset=p)
        p += timecnt
        ttinfo = []
        isdst_flags = []
        for i in range(typecnt):
            utoff, isdst, abbrind = struct.unpack_from(">iBB", buf, p)
            ttinfo.append(utoff)
            isdst_flags.append(bool(isdst))
            p += 6
        p += charcnt + leapcnt * (time_size + 4) + isstdcnt + isutcnt
        return trans, idx, np.array(ttinfo, np.int64), isdst_flags, p

    assert data[:4] == b"TZif", path
    version = data[4:5]
    trans, idx, ttinfo, isdst, end = parse_block(data, 0, 4, ">i4")
    if version in (b"2", b"3"):
        # v2+ block follows with 64-bit times; prefer it
        assert data[end:end + 4] == b"TZif"
        trans, idx, ttinfo, isdst, _ = parse_block(data, end, 8, ">i8")

    if len(ttinfo) == 0:
        return (np.array([_NEG_INF], np.int64), np.array([0], np.int64))
    # era entry (pre-first-transition): RFC 8536 §3.2 — the first
    # STANDARD-time type (usually LMT), not the first transition's target
    first = next((off for off, dst in zip(ttinfo, isdst) if not dst),
                 int(ttinfo[0]))
    instants = np.concatenate([[_NEG_INF], trans])
    offsets = np.concatenate([[first],
                              ttinfo[idx] if len(idx) else []]).astype(
        np.int64)
    return instants, offsets


_FIXED = re.compile(r"^(?:UTC|GMT)?([+-])(\d{1,2})(?::?(\d{2}))?$")


class TimeZoneDB:
    """Process-wide cache of device-resident transition tables."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: Dict[str, tuple] = {}

    def _load(self, tz: str):
        m = _FIXED.match(tz)
        if tz.upper() in ("UTC", "GMT", "Z") or tz == "+00:00":
            inst = np.array([_NEG_INF], np.int64)
            offs = np.array([0], np.int64)
        elif m:
            sign = 1 if m.group(1) == "+" else -1
            secs = sign * (int(m.group(2)) * 3600 + int(m.group(3) or 0) * 60)
            inst = np.array([_NEG_INF], np.int64)
            offs = np.array([secs], np.int64)
        else:
            path = os.path.join(_TZDIR, tz)
            if not os.path.isfile(path) or ".." in tz:
                raise ValueError(f"unknown timezone {tz!r}")
            inst, offs = _parse_tzif(path)
        # micros-domain tables; clamp sentinel to stay in int64 micros
        inst_us = np.where(inst <= _NEG_INF, np.int64(-(1 << 62)),
                           inst * MICROS)
        # wall-time interval ENDS under each interval's own offset —
        # first-containing-interval search = fold=0 (earlier offset wins
        # in overlaps)
        ends = np.empty_like(inst_us)
        ends[:-1] = inst_us[1:] + offs[:-1] * MICROS
        ends[-1] = (1 << 62)
        return (jnp.asarray(inst_us), jnp.asarray(offs * MICROS),
                jnp.asarray(ends))

    def tables(self, tz: str):
        key = tz
        got = self._cache.get(key)
        if got is None:
            with self._lock:
                got = self._cache.get(key)
                if got is None:
                    got = self._load(tz)
                    self._cache[key] = got
        return got


_DB = TimeZoneDB()


def timezone_db() -> TimeZoneDB:
    return _DB


def utc_to_local(ts_micros, tz: str):
    """from_utc_timestamp kernel: shift UTC instants to wall clock in
    `tz` (stays TIMESTAMP_NTZ-like micros)."""
    inst, offs, _ = _DB.tables(tz)
    i = jnp.searchsorted(inst, ts_micros, side="right") - 1
    i = jnp.clip(i, 0, inst.shape[0] - 1)
    return ts_micros + offs[i]


def local_to_utc(ts_micros, tz: str):
    """to_utc_timestamp kernel: wall clock in `tz` → UTC instants
    (fold=0: the earlier offset for ambiguous overlap times; nonexistent
    gap times follow Java's ZonedDateTime rule — shift forward by the
    gap, i.e. resolve with the PRE-transition offset)."""
    inst, offs, ends = _DB.tables(tz)
    i = jnp.searchsorted(ends, ts_micros, side="right")
    i = jnp.clip(i, 0, offs.shape[0] - 1)
    # A wall time earlier than the matched interval's own wall start is in
    # a DST gap: no interval contains it. Java resolves it with the offset
    # BEFORE the transition (local − offsetBefore shifts forward by the gap).
    in_gap = ts_micros < inst[i] + offs[i]
    prev = jnp.clip(i - 1, 0, offs.shape[0] - 1)
    off = jnp.where(in_gap, offs[prev], offs[i])
    return ts_micros - off
