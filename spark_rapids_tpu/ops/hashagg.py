"""Hash-based group assignment — the TPU answer to cuDF's hash group-by
(the reference's primary aggregation path; sort-based is its fallback,
GpuAggregateExec.scala:909 — same duality here).

No open addressing / probing loops (serial, XLA-hostile). Instead,
*collision-verified scatter*: R static rounds, each round r
  1. bucket b = xxhash64(keys, seed=r) mod capacity
  2. representative per bucket = min row index (one scatter-min)
  3. rows whose keys EQUAL their bucket's representative key resolve to
     that bucket (vectorized gather + compare; hash collisions between
     distinct keys simply fail the compare)
  4. unresolved rows go to round r+1 with a different seed
All equal keys share a bucket every round, so each distinct key resolves
as a whole group the first round its bucket isn't contested. After R
rounds a `leftover` flag reports unresolved rows; the exec checks it on
the host (one sync) and falls back to the exact sort-based kernel — rare
in practice for cardinality << capacity, and for cardinality ~ capacity
the sort path is the right algorithm anyway.

Cost: O(R·n) scatters/gathers/compares, no O(n log n) sort, no
data-dependent shapes. This is the hot kernel for TPC-style low-to-mid
cardinality aggregations.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from .basic import active_mask, compaction_order, gather_column
from .hashing import xxhash64_batch
from .strings import string_equal

#: static number of re-hash rounds before the sort fallback
DEFAULT_ROUNDS = 2


def _keys_equal_rows(key_cols: Sequence[Column], idx_a, idx_b):
    """Null-aware GROUP BY equality between row idx_a[i] and idx_b[i]:
    null == null, values compare exactly."""
    eq = None
    for col in key_cols:
        a = gather_column(col, idx_a)
        b = gather_column(col, idx_b)
        if isinstance(col, StringColumn):
            s = string_equal(a, b)
            val_eq = s.data & s.validity
        else:
            val_eq = a.data == b.data
        both_null = (~a.validity) & (~b.validity)
        both_valid = a.validity & b.validity
        this_eq = both_null | (both_valid & val_eq)
        eq = this_eq if eq is None else (eq & this_eq)
    return eq if eq is not None else jnp.ones_like(idx_a, jnp.bool_)


def hash_group_assignment(key_cols: Sequence[Column], num_rows,
                          capacity: int, rounds: int = DEFAULT_ROUNDS):
    """Assign group slots without sorting.

    Returns (seg (capacity,) int32 in [0, rounds*capacity) or the sentinel
    rounds*capacity for unresolved/inactive rows,
    rep_row (rounds*capacity,) int32: representative source row per slot
    (or capacity when the slot is empty),
    leftover: device bool scalar — True iff some active row stayed
    unresolved and the caller must use the sort fallback).
    """
    cap = capacity
    iota = jnp.arange(cap, dtype=jnp.int32)
    act = active_mask(num_rows, cap)
    # include validity in the hash so null keys get their own bucket chain
    remaining = act
    seg = jnp.full((cap,), rounds * cap, jnp.int32)
    rep_rows: List[jnp.ndarray] = []
    for r in range(rounds):
        h = xxhash64_batch(list(key_cols), seed=0x9E3779B9 + r)
        h_u = jax.lax.bitcast_convert_type(h, jnp.uint64)
        bucket = (h_u % jnp.uint64(cap)).astype(jnp.int32)
        # scatter-min row index into contested buckets (only remaining rows)
        rep = jnp.full((cap,), cap, jnp.int32)
        rep = rep.at[jnp.where(remaining, bucket, cap)].min(iota, mode="drop")
        my_rep = rep[bucket]
        same = _keys_equal_rows(key_cols, iota,
                                jnp.clip(my_rep, 0, cap - 1))
        resolved = remaining & (my_rep < cap) & same
        seg = jnp.where(resolved, r * cap + bucket, seg)
        # a slot's representative is only real if the rep row resolved INTO
        # this slot (rep row always matches itself, so rep<cap => resolved)
        rep_rows.append(rep)
        remaining = remaining & ~resolved
    leftover = jnp.any(remaining)
    # a slot's rep row always resolves into that slot (it compares equal to
    # itself), so rep < cap is exactly "slot occupied"
    rep_row = jnp.concatenate(rep_rows)
    return seg, rep_row, leftover


def dense_group_ids(seg, rep_row, capacity: int, rounds: int):
    """Compact occupied slots into dense ids [0, num_groups).

    Returns (dense_seg (capacity,) int32 with sentinel capacity for
    unresolved rows, group_rep (capacity,) int32 source row per dense
    group, num_groups)."""
    n_slots = rounds * capacity
    occupied = rep_row < capacity
    # dense id per slot: prefix count of occupied slots
    pos = jnp.cumsum(occupied.astype(jnp.int32)) - 1
    num_groups = jnp.sum(occupied, dtype=jnp.int32)
    slot_to_dense = jnp.where(occupied, pos, capacity)
    safe_seg = jnp.clip(seg, 0, n_slots - 1)
    dense_seg = jnp.where(seg < n_slots, slot_to_dense[safe_seg], capacity)
    # group_rep in dense order: scatter rep rows to their dense position
    group_rep = jnp.full((capacity,), capacity, jnp.int32)
    group_rep = group_rep.at[jnp.where(occupied, pos, capacity)].set(
        rep_row, mode="drop")
    return dense_seg, group_rep, num_groups
