"""Measured fused-tier selection (ISSUE 1: "turn the projection into a
measurement").

`spark.rapids.tpu.pallas.fusedTier` = off | on | auto decides whether the
fused Pallas kernel families (ops/pallas_join.py, ops/pallas_fused.py)
replace their XLA formulations. `auto` — the default — is driven by the
per-kernel microbenchmark harness `tools/kern_bench.py`, which records
XLA-vs-Pallas wall-clock per (family, backend platform, shape bucket);
a family only engages for a shape bucket where a recorded measurement
shows the Pallas kernel winning. No record -> XLA stays, so a fresh
checkout behaves exactly like the pre-fused engine until someone runs
the harness on the actual hardware.

Shape buckets are log2 sizes — the same power-of-two discipline as the
engine's capacity buckets — so one measurement covers every batch that
compiles to the same program shape.
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from pathlib import Path
from typing import Dict, Optional, Tuple

#: the closed registry of Pallas kernel families. Every family must
#: have (a) an exec/lifecycle.FAMILY_DOMAINS entry so the degradation
#: circuit breakers can demote it, (b) a tools/kern_bench.py bench so
#: `auto` selection is a measurement, and (c) a row in the docs/perf.md
#: tier table — tests/test_docs_lint.py lints all three (the registries
#: drifted silently before measurement-gating existed).
#: `h2d_upload` is the odd one out: its two bench lanes are the packed
#: one-copy upload vs the per-buffer jnp.asarray lane (no Pallas kernel
#: — the gate is spark.rapids.tpu.transfer.packedUpload.enabled, not a
#: tier consult), registered here so the kern_bench/docs/breaker-domain
#: lints cover it like every other measured family.
#: `ici_all_to_all` follows the same lanes-not-kernels pattern: its two
#: bench lanes are the host serialize/LZ4 shuffle exchange vs the
#: device-resident packed all_to_all step (parallel/exchange.py); the
#: gate is spark.rapids.tpu.shuffle.ici.enabled, not a tier consult.
#: `dict_gather` (ISSUE 18) is the encoded lane's code-indexed take
#: (columnar/encoded.dict_take: per-row dictionary lookups for hashes,
#: literal hit masks and late materialization): its two lanes are the
#: XLA take and the Pallas DMA row gather over the lookup table.
PALLAS_FAMILIES = ("murmur3", "join_probe", "scan_agg", "gather",
                   "partition_split", "h2d_upload", "ici_all_to_all",
                   "dict_gather")

#: kern_bench.json layout version. The records file is rewritten by
#: tools/kern_bench.py with this stamp; a file from an older layout
#: (missing or mismatched stamp) is IGNORED LOUDLY instead of silently
#: mis-selecting tiers against measurements of code that no longer
#: exists. Bump when a family's bench formulation or the record shape
#: changes incompatibly.
KERN_BENCH_SCHEMA = 2

_lock = threading.Lock()
#: path -> (mtime, {(family, platform, bucket): record}) cache
_cache: Dict[str, Tuple[float, Dict]] = {}


def normalize_mode(raw: str) -> str:
    s = str(raw).strip().lower()
    if s in ("on", "true", "1", "yes"):
        return "on"
    if s in ("off", "false", "0", "no"):
        return "off"
    return "auto"


def shape_bucket(shape) -> Tuple[int, ...]:
    """log2-ceiling bucket per dimension (engine capacities are already
    powers of two, so this is usually exact)."""
    out = []
    for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
        out.append(max(int(s), 1).bit_length() - (1 if
                   max(int(s), 1) & (max(int(s), 1) - 1) == 0 else 0))
    return tuple(out)


def default_bench_file() -> str:
    return str(Path(__file__).resolve().parents[2]
               / "tools" / "kern_bench.json")


def _load_records(path: str) -> Dict:
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}
    with _lock:
        hit = _cache.get(path)
        if hit is not None and hit[0] == mtime:
            return hit[1]
    try:
        with open(path) as f:
            doc = json.load(f)
        index = {}
        if doc.get("schema") != KERN_BENCH_SCHEMA:
            # stale layout: refuse the whole file, loudly — a record
            # measured against an older kernel/bench formulation must
            # not flip tiers (ISSUE 8 satellite)
            warnings.warn(
                f"ignoring kern_bench records at {path}: schema "
                f"{doc.get('schema')!r} != {KERN_BENCH_SCHEMA} — "
                "re-run tools/kern_bench.py to refresh the file",
                stacklevel=2)
        else:
            for r in doc.get("records", ()):
                key = (r["family"], r["platform"],
                       tuple(r["shape_bucket"]))
                index[key] = r
    except (OSError, ValueError, KeyError, TypeError):
        index = {}
    with _lock:
        _cache[path] = (mtime, index)
    return index


def bench_record(family: str, shape) -> Optional[Dict]:
    """The recorded measurement for (family, current platform, bucket),
    or None."""
    import jax

    from ..config import PALLAS_FUSED_BENCH_FILE, active_conf
    path = active_conf().get(PALLAS_FUSED_BENCH_FILE) \
        or default_bench_file()
    records = _load_records(path)
    return records.get((family, jax.default_backend(),
                        shape_bucket(shape)))


def _breaker_allows(family: str) -> bool:
    """Degradation circuit breaker consult (ISSUE 6): an open breaker
    on this family's fault domain demotes it to the XLA safe path.
    With no breaker ever tripped this is one empty-dict check."""
    from ..exec import lifecycle
    return lifecycle.breaker_allows(
        lifecycle.FAMILY_DOMAINS.get(family, family))


def _note_engaged(family: str) -> None:
    """Record the engagement on the current task attempt so a
    classified-transient failure attributes to this family's fault
    domain (and a half-open breaker's probe can close on success)."""
    from ..exec import lifecycle
    lifecycle.note_engagement(family)


def family_may_engage(family: str) -> bool:
    """Could `family`'s fused kernel engage for ANY shape under the
    current config? Used to skip preparing kernel-only inputs (e.g. the
    BuildTable's permuted key lanes) on paths where the tier can never
    turn on: off -> never; on -> yes; auto -> only if some recorded
    measurement for this family+platform shows a Pallas win. An open
    circuit breaker on the family's domain answers no in every mode."""
    import jax

    from ..config import (PALLAS_FUSED_BENCH_FILE, PALLAS_FUSED_TIER,
                          active_conf)
    mode = normalize_mode(active_conf().get(PALLAS_FUSED_TIER))
    if mode == "off":
        return False
    if not _breaker_allows(family):
        return False
    if mode == "on":
        return True
    path = active_conf().get(PALLAS_FUSED_BENCH_FILE) \
        or default_bench_file()
    platform = jax.default_backend()
    for (fam, plat, _), rec in _load_records(path).items():
        try:
            if fam == family and plat == platform and \
                    float(rec["pallas_ms"]) < float(rec["xla_ms"]):
                return True
        except (KeyError, TypeError, ValueError):
            continue
    return False


def _emit_decision(family: str, shape, mode: str, engaged: bool,
                   reason: str) -> None:
    """pallas_tier event (obs/events.py): trace-time decisions land in
    the query event log so a BENCH delta can be attributed to a tier
    flip, not guessed at. One pointer check when logging is off."""
    from ..obs import events as obs_events
    if obs_events.active_bus() is None:
        return
    obs_events.emit("pallas_tier", family=family,
                    bucket=list(shape_bucket(shape)), mode=mode,
                    engaged=engaged, reason=reason)


def fused_tier_enabled(family: str, shape) -> bool:
    """Should `family` use its fused Pallas kernel for `shape`?

    Called on the host at trace time (the answer is static per compiled
    program shape). off -> never; on -> always (callers still fall back
    when a shape is structurally ineligible, e.g. non-integer join
    keys); auto -> only where a recorded measurement says Pallas wins.
    """
    from ..config import PALLAS_FUSED_TIER, active_conf
    mode = normalize_mode(active_conf().get(PALLAS_FUSED_TIER))
    if mode == "off":
        _emit_decision(family, shape, mode, False, "forced off")
        return False
    if not _breaker_allows(family):
        # demotion (ISSUE 6): the domain's breaker is open — the XLA
        # formulation is the safe path until the cooldown's half-open
        # probe closes it again
        _emit_decision(family, shape, mode, False,
                       "circuit breaker open")
        return False
    if mode == "on":
        _note_engaged(family)
        _emit_decision(family, shape, mode, True, "forced on")
        return True
    rec = bench_record(family, shape)
    if not rec:
        _emit_decision(family, shape, mode, False,
                       "no recorded measurement")
        return False
    try:
        engaged = float(rec["pallas_ms"]) < float(rec["xla_ms"])
        if engaged:
            _note_engaged(family)
        _emit_decision(family, shape, mode, engaged,
                       f"measured pallas_ms={rec['pallas_ms']} vs "
                       f"xla_ms={rec['xla_ms']}")
        return engaged
    except (KeyError, TypeError, ValueError):
        _emit_decision(family, shape, mode, False, "unreadable record")
        return False
