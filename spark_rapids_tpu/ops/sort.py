"""Multi-column sort kernels — the device core behind GpuSortExec,
out-of-core merge sort, sort-based aggregation fallback and range
partitioning (reference GpuSortExec.scala:86, SortUtils.scala).

TPU-first design: instead of cuDF's comparator-based radix sort we lower
every ORDER BY to *order-key lanes* — unsigned integer arrays whose plain
ascending lexicographic order equals the requested Spark ordering (asc/desc,
nulls first/last, NaN-greatest, UTF-8 binary string order). The lanes feed
`jax.lax.sort(num_keys=k)`, which XLA compiles to its native tiled sort on
the MXU-adjacent vector units. One extra iota lane makes the sort stable and
doubles as the permutation used to gather the payload columns.

Inactive rows (index >= num_rows) always sort last via a leading
activity lane, so sorted batches keep the packed-prefix invariant.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BooleanType, DataType
from .basic import active_mask, gather_column


@dataclass(frozen=True)
class SortOrder:
    """One ORDER BY term: column ordinal + direction + null placement.

    Spark defaults: ascending => nulls first, descending => nulls last.
    """
    ordinal: int
    ascending: bool = True
    nulls_first: bool = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.nulls_first is None:
            object.__setattr__(self, "nulls_first", self.ascending)


#: default number of 8-byte words of string prefix used as sort lanes.
#: 32 bytes covers TPC-DS/TPC-H key domains; raise via SortSpec for longer.
DEFAULT_STRING_WORDS = 4


def _float_order_bits(data, bits_dtype, sign_bit):
    """IEEE-754 total order as unsigned ints, with Spark semantics:
    all NaNs collapse to one value greater than +inf; -0.0 == 0.0."""
    data = jnp.where(jnp.isnan(data), jnp.full((), jnp.nan, data.dtype), data)
    # -0.0 -> +0.0 via select: `x + 0.0` is NOT value-preserving for -0.0
    # and XLA's algebraic simplifier folds it away under jit
    data = jnp.where(data == jnp.zeros((), data.dtype),
                     jnp.zeros((), data.dtype), data)
    if jnp.dtype(bits_dtype).itemsize == 8:
        # direct f64 bitcasts don't compile on TPU (X64 pass limitation);
        # reconstruct the pattern arithmetically
        from .f64bits import f64_bits
        bits = f64_bits(data)
    else:
        bits = jax.lax.bitcast_convert_type(data, bits_dtype)
    neg = (bits >> (sign_bit)) & 1
    flipped = jnp.where(neg == 1, ~bits, bits | (jnp.ones((), bits_dtype) << sign_bit))
    return flipped


def _numeric_order_key(col: Column):
    """Map one fixed-width column to a single unsigned lane that sorts
    ascending in value order."""
    data = col.data
    dt = data.dtype
    if dt == jnp.bool_:
        return data.astype(jnp.uint32)
    if jnp.issubdtype(dt, jnp.floating):
        if dt == jnp.float64:
            return _float_order_bits(data, jnp.uint64, 63)
        return _float_order_bits(data.astype(jnp.float32), jnp.uint32, 31)
    if jnp.issubdtype(dt, jnp.signedinteger):
        bits = 8 * dt.itemsize
        udt = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32, 64: jnp.uint64}[bits]
        unsigned = jax.lax.bitcast_convert_type(data, udt)
        return unsigned ^ (jnp.ones((), udt) << (bits - 1))
    return data  # already unsigned


def numeric_order_lanes(col: Column):
    """Order-consistent unsigned lane LIST for one fixed-width column:
    one lane for plain columns, two u64 limb lanes for decimal128
    (round 5: decimal keys)."""
    from ..columnar.column import Decimal128Column
    if isinstance(col, Decimal128Column):
        sign = jnp.uint64(1) << jnp.uint64(63)
        return [jax.lax.bitcast_convert_type(col.hi.data, jnp.uint64)
                ^ sign,
                jax.lax.bitcast_convert_type(col.lo.data, jnp.uint64)]
    return [_numeric_order_key(col)]


def string_prefix_lanes(col: StringColumn, num_words: int) -> List[jnp.ndarray]:
    """First `num_words`*8 bytes of each string as big-endian uint64 lanes;
    plain ascending uint64 order == UTF-8 binary order (zero-padded, so
    shorter strings sort before their extensions, matching Spark)."""
    cap = col.capacity
    starts = col.offsets[:cap]
    lengths = col.offsets[1:] - starts
    byte_cap = col.byte_capacity
    lanes = []
    for w in range(num_words):
        word = jnp.zeros((cap,), jnp.uint64)
        for b in range(8):
            j = w * 8 + b
            pos = starts + j
            in_str = j < lengths
            safe = jnp.clip(pos, 0, byte_cap - 1)
            byte = jnp.where(in_str, col.data[safe], 0).astype(jnp.uint64)
            word = (word << jnp.uint64(8)) | byte
        lanes.append(word)
    return lanes


def string_words_for(columns: Sequence[Column], ordinals: Sequence[int],
                     num_rows=None) -> int:
    """Lane count making string ordering EXACT for these batches: measures
    the max string length on device (one host sync, outside jit) and rounds
    to a power-of-two word count so lane shapes bucket like capacities do."""
    words = DEFAULT_STRING_WORDS
    for i in ordinals:
        col = columns[i]
        if isinstance(col, StringColumn):
            lengths = col.offsets[1:] - col.offsets[:-1]
            max_len = int(jnp.max(lengths))
            need = max(1, -(-max_len // 8))
            while words < need:
                words *= 2
    return words


def order_key_lanes(columns: Sequence[Column], orders: Sequence[SortOrder],
                    num_rows, capacity: int,
                    string_words: int = DEFAULT_STRING_WORDS,
                    ) -> List[jnp.ndarray]:
    """Build the full lane stack: [activity, (nulls, value-lanes)*]."""
    act = active_mask(num_rows, capacity)
    lanes: List[jnp.ndarray] = [(~act).astype(jnp.uint32)]
    for o in orders:
        col = columns[o.ordinal]
        valid = col.validity & act
        # null lane: 0 sorts first. nulls_first => null rank 0, else rank 1
        # (then inverted for descending along with everything else).
        null_rank = jnp.where(valid, 1, 0) if o.nulls_first else \
            jnp.where(valid, 0, 1)
        lanes.append(null_rank.astype(jnp.uint32))
        if isinstance(col, StringColumn):
            vlanes = string_prefix_lanes(col, string_words)
        else:
            # one lane for plain columns, two limb lanes for
            # decimal128 (round 5: decimal keys; i64 bitcasts are fine
            # on TPU — only f64 sources are broken)
            vlanes = numeric_order_lanes(col)
        for v in vlanes:
            v = jnp.where(valid, v, jnp.zeros((), v.dtype))
            if not o.ascending:
                v = ~v
            lanes.append(v)
    return lanes


def _split_u64_lanes(lanes):
    """Split uint64 sort lanes into (hi, lo) uint32 pairs: emulated-u64
    compares make XLA's TPU sort ~5x slower than the u32 equivalent
    (measured v5e; order is identical lexicographically)."""
    out = []
    for lane in lanes:
        if lane.dtype == jnp.uint64:
            out.append((lane >> jnp.uint64(32)).astype(jnp.uint32))
            out.append(lane.astype(jnp.uint32))
        else:
            out.append(lane)
    return out


def sort_permutation(columns: Sequence[Column], orders: Sequence[SortOrder],
                     num_rows, capacity: int,
                     string_words: int = DEFAULT_STRING_WORDS):
    """Stable sort permutation: int32 (capacity,) such that gathering by it
    yields rows in the requested order, inactive rows last."""
    lanes = _split_u64_lanes(
        order_key_lanes(columns, orders, num_rows, capacity, string_words))
    iota = jnp.arange(capacity, dtype=jnp.int32)
    out = jax.lax.sort(tuple(lanes) + (iota,), num_keys=len(lanes))
    return out[-1]


def sort_batch_columns(columns: Sequence[Column], orders: Sequence[SortOrder],
                       num_rows, capacity: int,
                       string_words: int = DEFAULT_STRING_WORDS,
                       ) -> Tuple[List[Column], jnp.ndarray]:
    """Sort all columns of a batch; returns (sorted columns, permutation).

    Round 4: fixed-width payload columns ride INSIDE lax.sort as packed
    u32/f64 lanes (ops/rowpack) instead of being gathered by the
    permutation afterwards — on v5e a multi-operand sort costs a few ms
    while each per-column random gather costs ~26 ms. The iota lane stays
    a KEY so the sort is stable and varlen columns still gather by it.
    """
    from .rowpack import pack_rows, split_packable, unpack_rows
    lanes = _split_u64_lanes(
        order_key_lanes(columns, orders, num_rows, capacity, string_words))
    iota = jnp.arange(capacity, dtype=jnp.int32)
    p_idx, o_idx = split_packable(columns)
    out: List = [None] * len(columns)
    if len(p_idx) > 0:
        plan, imat, fmat = pack_rows([columns[i] for i in p_idx])
        ilanes = [imat[:, j] for j in range(imat.shape[1])]
        flanes = [fmat[:, j] for j in range(fmat.shape[1])] \
            if fmat is not None else []
        res = jax.lax.sort(
            tuple(lanes) + (iota,) + tuple(ilanes) + tuple(flanes),
            num_keys=len(lanes) + 1)
        perm = res[len(lanes)]
        s_il = res[len(lanes) + 1: len(lanes) + 1 + len(ilanes)]
        s_fl = res[len(lanes) + 1 + len(ilanes):]
        s_imat = jnp.stack(s_il, axis=1)
        s_fmat = jnp.stack(s_fl, axis=1) if flanes else None
        for j, c in zip(p_idx, unpack_rows(plan, s_imat, s_fmat)):
            out[j] = c
    else:
        res = jax.lax.sort(tuple(lanes) + (iota,), num_keys=len(lanes) + 1)
        perm = res[len(lanes)]
    for j in o_idx:
        # gather marks rows valid per source validity; the inactive tail
        # is handled by perm pointing at rows whose validity is False
        out[j] = gather_column(columns[j], perm, out_valid=None)
    return list(out), perm


def group_segment_ids(key_columns: Sequence[Column], num_rows, capacity: int,
                      string_words: int = DEFAULT_STRING_WORDS):
    """For KEY-SORTED columns: (segment_ids int32 (capacity,), num_groups).

    Rows with equal keys (nulls equal, Spark GROUP BY semantics) share an id;
    ids are dense 0..num_groups-1 in sorted order; inactive rows get id ==
    capacity (dropped by jax segment reductions with num_segments=capacity).
    """
    act = active_mask(num_rows, capacity)
    orders = [SortOrder(i) for i in range(len(key_columns))]
    lanes = order_key_lanes(key_columns, orders, num_rows, capacity,
                            string_words)[1:]  # drop activity lane
    boundary = jnp.zeros((capacity,), jnp.bool_)
    for lane in lanes:
        boundary = boundary | (lane != jnp.roll(lane, 1))
    boundary = boundary.at[0].set(True)
    boundary = boundary & act
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num_groups = jnp.where(num_rows > 0, jnp.max(jnp.where(act, seg, -1)) + 1, 0)
    seg = jnp.where(act, seg, capacity)
    return seg, num_groups.astype(jnp.int32)
