"""Hash expressions: Murmur3Hash (Spark `hash`), XxHash64 (reference
HashFunctions.scala over JNI Hash kernels)."""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column
from ..types import INT, LONG
from .core import Expression
from ..ops.hashing import murmur3_batch, xxhash64_batch


class Murmur3Hash(Expression):
    def __init__(self, *children: Expression, seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    def with_children(self, children):
        return Murmur3Hash(*children, seed=self.seed)

    def _semantic_args(self):
        return (self.seed,)

    @property
    def data_type(self):
        return INT

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        cols = [c.columnar_eval(batch) for c in self.children]
        h = murmur3_batch(cols, self.seed)
        return Column(h, jnp.ones((h.shape[0],), jnp.bool_), INT)


class XxHash64(Expression):
    def __init__(self, *children: Expression, seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    def with_children(self, children):
        return XxHash64(*children, seed=self.seed)

    def _semantic_args(self):
        return (self.seed,)

    @property
    def data_type(self):
        return LONG

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        cols = [c.columnar_eval(batch) for c in self.children]
        h = xxhash64_batch(cols, self.seed)
        return Column(h, jnp.ones((h.shape[0],), jnp.bool_), LONG)
