"""Window expressions (reference GpuWindowExpression.scala + the window
function zoo in window/). A WindowExpression pairs a window function with
a WindowSpec; WindowExec lowers them onto the segmented-scan kernels in
ops/window.py."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..types import DataType, DoubleType, IntegerType, LongType
from .core import Expression


#: frame bound: None = UNBOUNDED, 0 = CURRENT ROW, n>0 = n rows
@dataclass(frozen=True)
class WindowFrame:
    kind: str = "default"  # 'default' | 'rows' | 'range'
    preceding: Optional[int] = None
    following: Optional[int] = 0

    @staticmethod
    def rows(preceding: Optional[int], following: Optional[int]
             ) -> "WindowFrame":
        return WindowFrame("rows", preceding, following)

    @staticmethod
    def range(preceding, following) -> "WindowFrame":
        """RANGE frame with VALUE offsets over the single numeric order
        key (None = unbounded; 0 = CURRENT ROW incl. ties). Reference
        window/GpuWindowExpression.scala:111-179."""
        return WindowFrame("range", preceding, following)

    @staticmethod
    def unbounded() -> "WindowFrame":
        return WindowFrame("rows", None, None)


@dataclass
class WindowSpec:
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple = ()  # (Expression, ascending, nulls_first?) tuples
    frame: WindowFrame = field(default_factory=WindowFrame)

    def with_frame(self, frame: WindowFrame) -> "WindowSpec":
        return WindowSpec(self.partition_by, self.order_by, frame)


def window(partition_by: Sequence = (), order_by: Sequence = (),
           frame: Optional[WindowFrame] = None) -> WindowSpec:
    from .core import col
    pb = tuple(col(p) if isinstance(p, str) else p for p in partition_by)
    ob = []
    for o in order_by:
        if isinstance(o, tuple):
            e = col(o[0]) if isinstance(o[0], str) else o[0]
            ob.append((e,) + tuple(o[1:]))
        else:
            ob.append((col(o) if isinstance(o, str) else o, True))
    return WindowSpec(pb, tuple(ob), frame or WindowFrame())


class WindowFunction:
    """Marker base; `inputs` are expressions evaluated pre-sort."""
    inputs: Tuple[Expression, ...] = ()
    needs_order = False
    name = "window_fn"

    def result_type(self, input_types) -> DataType:
        raise NotImplementedError

    def over(self, spec: WindowSpec) -> "WindowExpression":
        return WindowExpression(self, spec)

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.inputs))})"


class RowNumber(WindowFunction):
    name, needs_order = "row_number", True

    def result_type(self, input_types):
        return IntegerType()


class Rank(WindowFunction):
    name, needs_order = "rank", True

    def result_type(self, input_types):
        return IntegerType()


class DenseRank(Rank):
    name = "dense_rank"


class Lag(WindowFunction):
    name, needs_order = "lag", True

    def __init__(self, child: Expression, offset: int = 1, default=None):
        self.inputs = (child,)
        self.offset = offset
        self.default = default

    def result_type(self, input_types):
        return input_types[0]


class Lead(Lag):
    name = "lead"

    def __init__(self, child: Expression, offset: int = 1, default=None):
        super().__init__(child, -offset, default)


class WindowAgg(WindowFunction):
    """sum/min/max/count/avg over a frame."""

    def __init__(self, op: str, child: Optional[Expression]):
        assert op in ("sum", "min", "max", "count", "avg")
        self.op = op
        self.name = op
        self.inputs = (child,) if child is not None else ()

    def result_type(self, input_types):
        if self.op == "count":
            return LongType()
        if self.op == "avg":
            return DoubleType()
        dt = input_types[0]
        if self.op == "sum":
            from ..expr.aggexprs import _sum_buffer_type
            return _sum_buffer_type(dt)
        return dt


class FirstValue(WindowFunction):
    name = "first_value"

    def __init__(self, child: Expression):
        self.inputs = (child,)

    def result_type(self, input_types):
        return input_types[0]


class LastValue(FirstValue):
    name = "last_value"


class WindowExpression:
    def __init__(self, fn: WindowFunction, spec: WindowSpec):
        self.fn = fn
        self.spec = spec

    def __repr__(self):
        return f"{self.fn!r} OVER {self.spec!r}"
