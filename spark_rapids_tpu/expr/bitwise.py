"""Bitwise + shift expressions (reference: the bitwise rules in
GpuOverrides.scala:919 over cuDF bitwise kernels; Spark's
BitwiseAnd/Or/Xor/Not and ShiftLeft/ShiftRight/ShiftRightUnsigned).

Spark semantics carried over exactly:
- bitwise ops promote to the wider integral type (Add's promotion);
- shifts take an INT shift amount, keep the VALUE's type, and mask the
  distance to the type width (Java << / >> / >>>: `x << (n & 31|63)`);
- >>> is logical (zero-fill), >> arithmetic (sign-fill).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column
from ..types import DataType, IntegerType, LongType
from .arithmetic import _promote, numeric_promote
from .core import Expression


class _BitwiseBinary(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, cs):
        return type(self)(cs[0], cs[1])

    @property
    def data_type(self) -> DataType:
        lt = self.children[0].data_type
        rt = self.children[1].data_type
        return lt if lt == rt else numeric_promote(lt, rt)

    def columnar_eval(self, batch) -> Column:
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        out_t = self.data_type
        ld, rd = _promote(l, r, out_t)
        valid = l.validity & r.validity
        data = self._op(ld, rd)
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
        return Column(data, valid, out_t)


class BitwiseAnd(_BitwiseBinary):
    @staticmethod
    def _op(a, b):
        return jnp.bitwise_and(a, b)


class BitwiseOr(_BitwiseBinary):
    @staticmethod
    def _op(a, b):
        return jnp.bitwise_or(a, b)


class BitwiseXor(_BitwiseBinary):
    @staticmethod
    def _op(a, b):
        return jnp.bitwise_xor(a, b)


class BitwiseNot(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return type(self)(cs[0])

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch) -> Column:
        c = self.children[0].columnar_eval(batch)
        data = jnp.where(c.validity, jnp.invert(c.data),
                         jnp.zeros((), c.data.dtype))
        return Column(data, c.validity, self.data_type)


class _ShiftBase(Expression):
    """value SHIFT amount: result keeps the value's type; the distance is
    masked to the type width like Java (x << 65 == x << 1 for int64)."""

    def __init__(self, value: Expression, amount: Expression):
        self.children = (value, amount)

    def with_children(self, cs):
        return type(self)(cs[0], cs[1])

    @property
    def data_type(self):
        dt = self.children[0].data_type
        # Spark: byte/short promote to int for shifts
        return dt if isinstance(dt, LongType) else IntegerType()

    def columnar_eval(self, batch) -> Column:
        v = self.children[0].columnar_eval(batch)
        n = self.children[1].columnar_eval(batch)
        out_t = self.data_type
        bits = 64 if isinstance(out_t, LongType) else 32
        data = v.data.astype(out_t.jnp_dtype)
        dist = jnp.bitwise_and(n.data.astype(jnp.int32),
                               jnp.int32(bits - 1))
        valid = v.validity & n.validity
        out = self._op(data, dist.astype(data.dtype))
        out = jnp.where(valid, out, jnp.zeros((), out.dtype))
        return Column(out, valid, out_t)


class ShiftLeft(_ShiftBase):
    @staticmethod
    def _op(x, d):
        return jax.lax.shift_left(x, d)


class ShiftRight(_ShiftBase):
    @staticmethod
    def _op(x, d):
        return jax.lax.shift_right_arithmetic(x, d)


class ShiftRightUnsigned(_ShiftBase):
    @staticmethod
    def _op(x, d):
        return jax.lax.shift_right_logical(x, d)
