"""JSON expressions (reference GpuGetJsonObject.scala + JNI JSONUtils,
GpuJsonTuple.scala; SURVEY §2.3 expression families). These run on the
HOST row-engine tier: the reference offloads them through a dedicated
CUDA JSON parser; this engine routes them through the CPU fallback
transitions (exec/fallback.py) until a device JSON kernel exists — the
rules tag them host-tier so plans stay runnable and explain output says
where they execute.

JSONPath subset (same as Spark's get_json_object): `$` root, `.field`,
`['field']`, `[n]` array index, `[*]` wildcard over arrays.
"""

from __future__ import annotations

import json
import re
from typing import List, Optional

from ..types import STRING
from .core import Expression, Literal

_TOKEN = re.compile(r"""
    \.(?P<field>[A-Za-z_][A-Za-z0-9_\- ]*)   |
    \[\s*'(?P<qfield>[^']*)'\s*\]            |
    \[\s*(?P<index>\d+)\s*\]                 |
    \[\s*\*\s*\](?P<star>)
""", re.X)


def parse_json_path(path: str) -> Optional[List]:
    """'$.a.b[0]' → ['a', 'b', 0]; None for malformed paths (Spark
    returns NULL for them)."""
    if not path or path[0] != "$":
        return None
    out: List = []
    pos = 1
    while pos < len(path):
        m = _TOKEN.match(path, pos)
        if m is None:
            return None
        if m.group("field") is not None:
            out.append(m.group("field"))
        elif m.group("qfield") is not None:
            out.append(m.group("qfield"))
        elif m.group("index") is not None:
            out.append(int(m.group("index")))
        else:
            out.append("*")
        pos = m.end()
    return out


def _first_key_wins(pairs):
    d = {}
    for k, v in pairs:
        if k not in d:
            d[k] = v
    return d


def _walk(node, steps, i):
    if i == len(steps):
        yield node
        return
    step = steps[i]
    if step == "*":
        if isinstance(node, list):
            for item in node:
                yield from _walk(item, steps, i + 1)
        return
    if isinstance(step, int):
        if isinstance(node, list) and 0 <= step < len(node):
            yield from _walk(node[step], steps, i + 1)
        return
    if isinstance(node, dict) and step in node:
        yield from _walk(node[step], steps, i + 1)


def _render(v) -> Optional[str]:
    """Spark's scalar rendering: strings bare, others as JSON text."""
    if v is None:
        return None
    if isinstance(v, str):
        return v
    return json.dumps(v, separators=(",", ":"))


class GetJsonObject(Expression):
    """get_json_object(json, path) — host tier (reference
    GpuGetJsonObject over the JNI JSON parser)."""

    def __init__(self, child: Expression, path):
        self.children = (child,)
        self.path = path.value if isinstance(path, Literal) else path
        # parse the constant path ONCE, not per row in the hot loop
        self._steps = parse_json_path(self.path) \
            if isinstance(self.path, str) else None

    def with_children(self, cs):
        return GetJsonObject(cs[0], self.path)

    def _semantic_args(self):
        return (self.path,)

    @property
    def data_type(self):
        return STRING

    def host_eval_row(self, s):
        steps = self._steps
        if s is None or steps is None:
            return None
        try:
            # first duplicate key wins, matching Jackson's streaming
            # get_json_object (and the device scanner); plain json.loads
            # would keep the LAST duplicate
            doc = json.loads(
                s, object_pairs_hook=_first_key_wins)
        except ValueError:
            return None
        hits = [h for h in _walk(doc, steps, 0)]
        if not hits:
            return None
        if len(hits) == 1:
            return _render(hits[0])
        # wildcard with multiple matches renders as a JSON array
        return json.dumps(hits, separators=(",", ":"))

    @property
    def device_supported(self) -> bool:
        """Literal wildcard-free paths run the byte-parallel device
        scanner (ops/json_device.py); '[*]' paths stay on the host tier."""
        return self._steps is None or "*" not in [
            s for s in self._steps if isinstance(s, str)]

    def columnar_eval(self, batch):
        from ..columnar.column import StringColumn
        from ..ops.json_device import json_extract
        import jax.numpy as jnp
        c = self.children[0].columnar_eval(batch)
        if self._steps is None:
            # malformed/non-literal path: NULL for every row
            valid = jnp.zeros((c.capacity,), jnp.bool_)
            return StringColumn(
                jnp.zeros((1,), jnp.uint8),
                jnp.zeros((c.capacity + 1,), jnp.int32), valid, STRING)
        if not self.device_supported:
            raise NotImplementedError(
                "wildcard JSON paths run on the host tier")
        return json_extract(c, self._steps)


class JsonToStructsField(Expression):
    """from_json limited to extracting ONE typed field (the common
    `from_json(col, schema).field` shape; reference GpuJsonToStructs is
    the full version). Host tier."""

    HOST_ONLY = True

    def __init__(self, child: Expression, field: str, dtype):
        self.children = (child,)
        self.field = field
        self._dtype = dtype

    def with_children(self, cs):
        return JsonToStructsField(cs[0], self.field, self._dtype)

    def _semantic_args(self):
        return (self.field, repr(self._dtype))

    @property
    def data_type(self):
        return self._dtype

    def host_eval_row(self, s):
        if s is None:
            return None
        try:
            doc = json.loads(s)
        except ValueError:
            return None
        if not isinstance(doc, dict) or self.field not in doc:
            return None
        v = doc[self.field]
        from ..types import (BooleanType, DoubleType, FloatType,
                             IntegerType, LongType, StringType)
        try:
            if isinstance(self._dtype, (LongType, IntegerType)):
                return int(v)
            if isinstance(self._dtype, (DoubleType, FloatType)):
                return float(v)
            if isinstance(self._dtype, BooleanType):
                return bool(v)
            if isinstance(self._dtype, StringType):
                return v if isinstance(v, str) else json.dumps(v)
        except (TypeError, ValueError):
            return None
        return None

    def columnar_eval(self, batch):
        raise NotImplementedError(
            "from_json runs on the host tier (CPU fallback)")
