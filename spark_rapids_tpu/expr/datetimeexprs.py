"""Datetime expressions (reference datetimeExpressions.scala; kernels in
ops/datetime_ops.py use the Howard Hinnant civil-calendar algorithms the
reference gets from cuDF). Dates are int32 days since epoch; timestamps
int64 microseconds UTC (Spark's physical encodings)."""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column
from ..ops import datetime_ops as dt
from ..types import DateType, IntegerType, TimestampType
from .core import Expression, lit


class _UnaryDatetime(Expression):
    out_type = IntegerType()

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def data_type(self):
        return self.out_type

    def with_children(self, cs):
        return type(self)(cs[0])

    def _days(self, batch):
        c = self.children[0].columnar_eval(batch)
        if isinstance(c.dtype, TimestampType):
            return dt.timestamp_to_date_days(c.data), c.validity
        return c.data, c.validity

    def columnar_eval(self, batch: ColumnarBatch) -> Column:
        days, valid = self._days(batch)
        return Column(self.kernel(days).astype(jnp.int32), valid,
                      self.out_type)

    kernel = None


class Year(_UnaryDatetime):
    kernel = staticmethod(dt.extract_year)


class Month(_UnaryDatetime):
    kernel = staticmethod(dt.extract_month)


class DayOfMonth(_UnaryDatetime):
    kernel = staticmethod(dt.extract_day)


class DayOfWeek(_UnaryDatetime):
    kernel = staticmethod(dt.extract_dayofweek)


class DayOfYear(_UnaryDatetime):
    kernel = staticmethod(dt.extract_dayofyear)


class Quarter(_UnaryDatetime):
    kernel = staticmethod(dt.extract_quarter)


class _TimePart(Expression):
    """hour/minute/second need the raw microseconds, not days."""

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def data_type(self):
        return IntegerType()

    def with_children(self, cs):
        return type(self)(cs[0])

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        return Column(self.kernel(c.data).astype(jnp.int32), c.validity,
                      IntegerType())

    kernel = None


class Hour(_TimePart):
    kernel = staticmethod(dt.extract_hour)


class Minute(_TimePart):
    kernel = staticmethod(dt.extract_minute)


class Second(_TimePart):
    kernel = staticmethod(dt.extract_second)


class LastDay(_UnaryDatetime):
    out_type = DateType()
    kernel = staticmethod(dt.last_day)


class DateAdd(Expression):
    """date_add(date, n) / date_sub via negated n."""

    def __init__(self, date: Expression, n: Expression, negate: bool = False):
        self.children = (date, n)
        self.negate = negate

    @property
    def data_type(self):
        return DateType()

    def with_children(self, cs):
        return DateAdd(cs[0], cs[1], self.negate)

    def columnar_eval(self, batch):
        d = self.children[0].columnar_eval(batch)
        n = self.children[1].columnar_eval(batch)
        delta = -n.data if self.negate else n.data
        return Column(dt.date_add(d.data, delta).astype(jnp.int32),
                      d.validity & n.validity, DateType())

    def _semantic_args(self):
        return (self.negate,)


class DateDiff(Expression):
    def __init__(self, end: Expression, start: Expression):
        self.children = (end, start)

    @property
    def data_type(self):
        return IntegerType()

    def with_children(self, cs):
        return DateDiff(cs[0], cs[1])

    def columnar_eval(self, batch):
        e = self.children[0].columnar_eval(batch)
        s = self.children[1].columnar_eval(batch)
        return Column(dt.date_diff(e.data, s.data).astype(jnp.int32),
                      e.validity & s.validity, IntegerType())


class AddMonths(Expression):
    def __init__(self, date: Expression, n: Expression):
        self.children = (date, n)

    @property
    def data_type(self):
        return DateType()

    def with_children(self, cs):
        return AddMonths(cs[0], cs[1])

    def columnar_eval(self, batch):
        d = self.children[0].columnar_eval(batch)
        n = self.children[1].columnar_eval(batch)
        return Column(dt.add_months(d.data, n.data).astype(jnp.int32),
                      d.validity & n.validity, DateType())


class TruncDate(Expression):
    def __init__(self, date: Expression, unit: str):
        self.children = (date,)
        self.unit = unit.lower()

    @property
    def data_type(self):
        return DateType()

    def with_children(self, cs):
        return TruncDate(cs[0], self.unit)

    def columnar_eval(self, batch):
        d = self.children[0].columnar_eval(batch)
        return Column(dt.trunc_date(d.data, self.unit).astype(jnp.int32),
                      d.validity, DateType())

    def _semantic_args(self):
        return (self.unit,)


class FromUTCTimestamp(Expression):
    """from_utc_timestamp(ts, tz): UTC instant → wall clock in tz
    (reference GpuFromUTCTimestamp + GpuTimeZoneDB device transition
    tables; ops/timezone.py)."""

    def __init__(self, ts: Expression, tz):
        self.children = (ts,)
        self.tz = tz.value if hasattr(tz, "value") else tz

    @property
    def data_type(self):
        return TimestampType()

    def with_children(self, cs):
        return type(self)(cs[0], self.tz)

    def _semantic_args(self):
        return (self.tz,)

    def columnar_eval(self, batch):
        from ..ops.timezone import utc_to_local
        c = self.children[0].columnar_eval(batch)
        return Column(utc_to_local(c.data, self.tz), c.validity,
                      TimestampType())


class ToUTCTimestamp(FromUTCTimestamp):
    """to_utc_timestamp(ts, tz): wall clock in tz → UTC instant (fold=0
    for ambiguous DST-overlap times, matching Java's zone rules)."""

    def columnar_eval(self, batch):
        from ..ops.timezone import local_to_utc
        c = self.children[0].columnar_eval(batch)
        return Column(local_to_utc(c.data, self.tz), c.validity,
                      TimestampType())
