"""Math expressions (reference mathExpressions.scala): unary transcendentals,
rounding with Spark HALF_UP/HALF_EVEN semantics, log family with Spark's
null-on-nonpositive behavior.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column
from ..types import DOUBLE, LONG, DataType, DoubleType, FractionalType, IntegralType
from .core import Expression


class UnaryMath(Expression):
    """double -> double elementwise; input implicitly cast to double."""

    fn = None
    #: when True, non-positive inputs produce NULL (Spark log/sqrt family)
    null_on_nonpositive = False
    null_on_negative = False
    #: lower bound (exclusive) below which the result is NULL (log1p: -1)
    null_below = None

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return type(self)(children[0])

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        x = c.data.astype(jnp.float64)
        valid = c.validity
        if self.null_on_nonpositive:
            ok = x > 0
            valid = valid & ok
            x = jnp.where(ok, x, jnp.float64(1.0))
        if self.null_on_negative:
            ok = x >= 0
            valid = valid & ok
            x = jnp.where(ok, x, jnp.float64(0.0))
        if self.null_below is not None:
            ok = x > self.null_below
            valid = valid & ok
            x = jnp.where(ok, x, jnp.float64(0.0))
        data = type(self).fn(x)
        data = jnp.where(valid, data, jnp.float64(0.0))
        return Column(data, valid, DOUBLE)


def _mk(name, fn, **attrs):
    cls = type(name, (UnaryMath,), {"fn": staticmethod(fn), **attrs})
    return cls


Sqrt = _mk("Sqrt", jnp.sqrt)  # Spark sqrt(-x) -> NaN (not null)
Exp = _mk("Exp", jnp.exp)
Expm1 = _mk("Expm1", jnp.expm1)
Log = _mk("Log", jnp.log, null_on_nonpositive=True)
Log2 = _mk("Log2", jnp.log2, null_on_nonpositive=True)
Log10 = _mk("Log10", jnp.log10, null_on_nonpositive=True)
Log1p = _mk("Log1p", jnp.log1p, null_below=-1.0)
Sin = _mk("Sin", jnp.sin)
Cos = _mk("Cos", jnp.cos)
Tan = _mk("Tan", jnp.tan)
Asin = _mk("Asin", jnp.arcsin)
Acos = _mk("Acos", jnp.arccos)
Atan = _mk("Atan", jnp.arctan)
Sinh = _mk("Sinh", jnp.sinh)
Cosh = _mk("Cosh", jnp.cosh)
Tanh = _mk("Tanh", jnp.tanh)
Asinh = _mk("Asinh", jnp.arcsinh)
Acosh = _mk("Acosh", jnp.arccosh)
Atanh = _mk("Atanh", jnp.arctanh)
Cbrt = _mk("Cbrt", jnp.cbrt)
ToDegrees = _mk("ToDegrees", jnp.degrees)
ToRadians = _mk("ToRadians", jnp.radians)
Signum = _mk("Signum", jnp.sign)
Rint = _mk("Rint", jnp.rint)


class Pow(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return Pow(*children)

    @property
    def data_type(self):
        return DOUBLE

    def columnar_eval(self, batch):
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        valid = l.validity & r.validity
        data = jnp.power(l.data.astype(jnp.float64), r.data.astype(jnp.float64))
        return Column(jnp.where(valid, data, 0.0), valid, DOUBLE)


class Atan2(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return Atan2(*children)

    @property
    def data_type(self):
        return DOUBLE

    def columnar_eval(self, batch):
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        valid = l.validity & r.validity
        data = jnp.arctan2(l.data.astype(jnp.float64), r.data.astype(jnp.float64))
        return Column(jnp.where(valid, data, 0.0), valid, DOUBLE)


class Floor(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Floor(children[0])

    @property
    def data_type(self):
        dt = self.children[0].data_type
        return dt if isinstance(dt, IntegralType) else LONG

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        if isinstance(c.dtype, IntegralType):
            return c
        data = jnp.floor(c.data).astype(jnp.int64)
        return Column(jnp.where(c.validity, data, 0), c.validity, LONG)


class Ceil(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Ceil(children[0])

    @property
    def data_type(self):
        dt = self.children[0].data_type
        return dt if isinstance(dt, IntegralType) else LONG

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        if isinstance(c.dtype, IntegralType):
            return c
        data = jnp.ceil(c.data).astype(jnp.int64)
        return Column(jnp.where(c.validity, data, 0), c.validity, LONG)


def _round_half_up(x, scale: int):
    m = 10.0 ** scale
    scaled = x * m
    # HALF_UP: away from zero at .5 (Java BigDecimal ROUND_HALF_UP)
    return jnp.where(scaled >= 0,
                     jnp.floor(scaled + 0.5),
                     jnp.ceil(scaled - 0.5)) / m


def _round_half_even(x, scale: int):
    m = 10.0 ** scale
    return jnp.round(x * m) / m  # rint = banker's rounding


class Round(Expression):
    """Spark round(col, scale): HALF_UP."""

    def __init__(self, child: Expression, scale: int = 0):
        self.children = (child,)
        self.scale = scale

    def with_children(self, children):
        return Round(children[0], self.scale)

    def _semantic_args(self):
        return (self.scale,)

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        dt = c.dtype
        if isinstance(dt, IntegralType):
            if self.scale >= 0:
                return c
            from .arithmetic import _round_div_half_up
            m = jnp.asarray(10 ** (-self.scale), c.data.dtype)
            data = _round_div_half_up(c.data, m) * m
            return Column(jnp.where(c.validity, data, 0), c.validity, dt)
        data = _round_half_up(c.data.astype(jnp.float64), self.scale)
        data = data.astype(dt.jnp_dtype)
        return Column(jnp.where(c.validity, data, jnp.zeros((), data.dtype)),
                      c.validity, dt)


class BRound(Round):
    """Spark bround: HALF_EVEN."""

    def with_children(self, children):
        return BRound(children[0], self.scale)

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        dt = c.dtype
        if isinstance(dt, IntegralType) and self.scale >= 0:
            return c
        data = _round_half_even(c.data.astype(jnp.float64), self.scale)
        data = data.astype(dt.jnp_dtype)
        return Column(jnp.where(c.validity, data, jnp.zeros((), data.dtype)),
                      c.validity, dt)
