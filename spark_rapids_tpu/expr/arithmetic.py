"""Arithmetic expressions with Spark semantics (non-ANSI mode).

Mirrors reference sql-plugin org/apache/spark/sql/rapids/arithmetic.scala:
  * integral overflow wraps (Java semantics; XLA integer ops wrap natively);
  * Divide / IntegralDivide / Remainder / Pmod return NULL when the divisor
    is 0 (Spark's non-ANSI behavior — unlike IEEE);
  * binary op type coercion promotes to the wider numeric type
    (Spark's BinaryArithmetic with implicit casts).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column
from ..types import (
    DOUBLE, DataType, DecimalType, DoubleType, FloatType, FractionalType,
    IntegralType, LONG, LongType, numeric_promote,
)
from .core import Expression


def _promote(l: Column, r: Column, target: DataType):
    ld = l.data.astype(target.jnp_dtype) if l.dtype != target else l.data
    rd = r.data.astype(target.jnp_dtype) if r.dtype != target else r.data
    return ld, rd


def _trunc_div(a, b):
    q = a // b
    rem = a - q * b
    # floor division rounds toward -inf; adjust when signs differ and rem != 0
    adjust = (rem != 0) & ((a < 0) != (b < 0))
    return q + adjust.astype(q.dtype)


def _trunc_mod(a, b):
    return a - _trunc_div(a, b) * b


def _round_div_half_up(a, m):
    """(a / m) rounded HALF_UP on int lanes (m positive int scalar)."""
    half = m // 2
    adj = jnp.where(a >= 0, a + half, a - half)
    return _trunc_div(adj, m)


def _round_div_half_up_signed(a, b):
    """(a / b) rounded HALF_UP where b may be negative (lanes)."""
    sign = jnp.where((a >= 0) == (b >= 0), jnp.int64(1), jnp.int64(-1))
    mag = _round_div_half_up(jnp.abs(a), jnp.abs(b))
    return sign * mag


def _decimal_scale_of(dt: DataType) -> int:
    if isinstance(dt, DecimalType):
        return dt.scale
    return 0  # integral coerced to decimal(p, 0)


def _rescale_unscaled(data, from_scale: int, to_scale: int):
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * jnp.int64(10 ** (to_scale - from_scale))
    return _round_div_half_up(data, jnp.int64(10 ** (from_scale - to_scale)))


class BinaryArithmetic(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])

    @property
    def data_type(self) -> DataType:
        lt, rt = self.left.data_type, self.right.data_type
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            return self._decimal_type(lt, rt)
        if lt == rt:
            return lt
        return numeric_promote(lt, rt)

    def _decimal_type(self, lt, rt) -> DataType:
        from .decimal_rules import binary_result_type
        return binary_result_type(type(self).__name__, lt, rt)

    def columnar_eval(self, batch) -> Column:
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        out_t = self.data_type
        if isinstance(out_t, DecimalType):
            return self._decimal_eval(l, r, out_t)
        ld, rd = _promote(l, r, out_t)
        valid = l.validity & r.validity
        data = self._op(ld, rd)
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
        return Column(data, valid, out_t)

    def _decimal_eval(self, l: Column, r: Column, out_t: DecimalType) -> Column:
        """Decimal arithmetic on unscaled lanes: rescale to a common
        working scale, operate, rescale HALF_UP to the result scale
        (Spark's Decimal math; overflow past the result precision ->
        NULL, non-ANSI). Results or inputs past 18 digits take the
        two-limb decimal128 path (ops/decimal128.py)."""
        from ..columnar.column import Decimal128Column
        name = type(self).__name__
        needs_128 = out_t.precision > 18 \
            or isinstance(l, Decimal128Column) \
            or isinstance(r, Decimal128Column)
        if needs_128:
            return self._decimal128_eval(l, r, out_t)
        s1 = _decimal_scale_of(l.dtype)
        s2 = _decimal_scale_of(r.dtype)
        valid = l.validity & r.validity
        ld = l.data.astype(jnp.int64)
        rd = r.data.astype(jnp.int64)
        if name in ("Add", "Subtract"):
            ws = max(s1, s2)
            a = _rescale_unscaled(ld, s1, ws)
            b = _rescale_unscaled(rd, s2, ws)
            res = a + b if name == "Add" else a - b
            res = _rescale_unscaled(res, ws, out_t.scale)
        elif name == "Multiply":
            res = _rescale_unscaled(ld * rd, s1 + s2, out_t.scale)
        elif name == "Divide":
            # l/r at result scale rs: unscaled = l*10^(rs - s1 + s2) / r
            shift = out_t.scale - s1 + s2
            num = ld * jnp.int64(10 ** max(shift, 0))
            if shift < 0:
                num = _round_div_half_up(num, jnp.int64(10 ** (-shift)))
            div_ok = rd != 0
            safe_r = jnp.where(div_ok, rd, jnp.int64(1))
            res = _round_div_half_up_signed(num, safe_r)
            valid = valid & div_ok
        elif name in ("Remainder", "Pmod"):
            ws = max(s1, s2)
            a = _rescale_unscaled(ld, s1, ws)
            b = _rescale_unscaled(rd, s2, ws)
            div_ok = b != 0
            safe_b = jnp.where(div_ok, b, jnp.int64(1))
            res = _trunc_mod(a, safe_b)
            if name == "Pmod":
                res = jnp.where(res < 0, res + jnp.abs(safe_b), res)
            res = _rescale_unscaled(res, ws, out_t.scale)
            valid = valid & div_ok
        else:
            raise TypeError(f"no decimal eval for {name}")
        bound = 10 ** min(out_t.precision, 18)
        ok = (res < bound) & (res > -bound)
        valid = valid & ok
        return Column(jnp.where(valid, res, 0), valid, out_t)

    def _decimal128_eval(self, l: Column, r: Column,
                         out_t: DecimalType) -> Column:
        """Two-limb path for results (or inputs) past 18 digits."""
        from ..columnar.column import Decimal128Column
        from ..ops import decimal128 as D
        name = type(self).__name__
        s1 = _decimal_scale_of(l.dtype)
        s2 = _decimal_scale_of(r.dtype)
        valid = l.validity & r.validity

        def limbs(c: Column):
            if isinstance(c, Decimal128Column):
                return c.hi.data, c.lo.data
            return D.from_i64(c.data.astype(jnp.int64))

        over = jnp.zeros(l.validity.shape, jnp.bool_)
        if name in ("Add", "Subtract"):
            ws = max(s1, s2)
            h1, l1 = limbs(l)
            h2, l2 = limbs(r)
            h1, l1, o1 = D.rescale(h1, l1, s1, ws)
            h2, l2, o2 = D.rescale(h2, l2, s2, ws)
            fn = D.add128 if name == "Add" else D.sub128
            rh, rl = fn(h1, l1, h2, l2)
            rh, rl, o3 = D.rescale(rh, rl, ws, out_t.scale)
            over = o1 | o2 | o3
        elif name == "Multiply":
            if isinstance(l, Decimal128Column) \
                    or isinstance(r, Decimal128Column):
                raise NotImplementedError(
                    "decimal multiply with >18-digit inputs needs a "
                    "256-bit intermediate (tagged off at plan time)")
            rh, rl = D.mul_i64_i64(l.data.astype(jnp.int64),
                                   r.data.astype(jnp.int64))
            rh, rl, over = D.rescale(rh, rl, s1 + s2, out_t.scale)
        elif name == "Divide":
            if isinstance(l, Decimal128Column) \
                    or isinstance(r, Decimal128Column):
                raise NotImplementedError(
                    "decimal divide with >18-digit inputs is tagged off "
                    "at plan time")
            # unscaled = l * 10^(rs - s1 + s2) / r, HALF_UP
            shift = out_t.scale - s1 + s2
            nh, nl = D.from_i64(l.data.astype(jnp.int64))
            nh, nl, over = D.rescale(nh, nl, 0, max(shift, 0))
            if shift < 0:
                nh, nl, _ = D.rescale(nh, nl, -shift, 0)
            rd = r.data.astype(jnp.int64)
            div_ok = rd != 0
            safe_r = jnp.where(div_ok, rd, jnp.int64(1))
            rh, rl = D.div128_round_half_up(nh, nl, safe_r)
            valid = valid & div_ok
        else:
            raise NotImplementedError(
                f"decimal128 {name} runs on the host row tier")
        ok = D.fits_precision(rh, rl, out_t.precision) & ~over
        valid = valid & ok
        rh = jnp.where(valid, rh, 0)
        rl = jnp.where(valid, rl, 0)
        if out_t.precision <= 18:
            return Column(rl, valid, out_t)  # fits one limb by the check
        return Decimal128Column.from_limbs(rh, rl, valid, out_t)

    def _op(self, l, r):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.children[0]!r} {self.symbol} {self.children[1]!r})"


class Add(BinaryArithmetic):
    symbol = "+"

    def _op(self, l, r):
        return l + r


class Subtract(BinaryArithmetic):
    symbol = "-"

    def _op(self, l, r):
        return l - r


class Multiply(BinaryArithmetic):
    symbol = "*"

    def _op(self, l, r):
        return l * r


class Divide(BinaryArithmetic):
    """Spark `/`: fractional result; NULL on divide-by-zero."""
    symbol = "/"

    @property
    def data_type(self):
        lt, rt = self.left.data_type, self.right.data_type
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            return self._decimal_type(lt, rt)
        return DOUBLE

    def columnar_eval(self, batch):
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        out_t = self.data_type
        if isinstance(out_t, DecimalType):
            return self._decimal_eval(l, r, out_t)
        ld, rd = _promote(l, r, out_t)
        zero = jnp.zeros((), rd.dtype)
        div_ok = rd != zero
        valid = l.validity & r.validity & div_ok
        data = ld / jnp.where(div_ok, rd, jnp.ones((), rd.dtype))
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
        return Column(data, valid, out_t)


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: long result, truncated toward zero; NULL on zero divisor."""
    symbol = "div"

    @property
    def data_type(self):
        return LONG

    def columnar_eval(self, batch):
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        s1 = _decimal_scale_of(l.dtype)
        s2 = _decimal_scale_of(r.dtype)
        ws = max(s1, s2)
        ld = _rescale_unscaled(l.data.astype(jnp.int64), s1, ws)
        rd = _rescale_unscaled(r.data.astype(jnp.int64), s2, ws)
        div_ok = rd != 0
        valid = l.validity & r.validity & div_ok
        safe_r = jnp.where(div_ok, rd, jnp.int64(1))
        q = _trunc_div(ld, safe_r)
        q = jnp.where(valid, q, jnp.int64(0))
        return Column(q, valid, LONG)


class Remainder(BinaryArithmetic):
    """Spark `%`: sign of dividend (Java); NULL on zero divisor."""
    symbol = "%"

    def columnar_eval(self, batch):
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        out_t = self.data_type
        if isinstance(out_t, DecimalType):
            return self._decimal_eval(l, r, out_t)
        ld, rd = _promote(l, r, out_t)
        if isinstance(out_t, FractionalType):
            div_ok = rd != 0
            safe_r = jnp.where(div_ok, rd, jnp.ones((), rd.dtype))
            data = ld - jnp.trunc(ld / safe_r) * safe_r
        else:
            div_ok = rd != 0
            safe_r = jnp.where(div_ok, rd, jnp.ones((), rd.dtype))
            data = _trunc_mod(ld, safe_r)
        valid = l.validity & r.validity & div_ok
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
        return Column(data, valid, out_t)


class Pmod(BinaryArithmetic):
    """Spark pmod (`r = a % n; r < 0 ? (r + n) % n : r`, Java remainder):
    non-negative for positive divisors, but NEGATIVE results for n < 0
    (pmod(-7, -2) = -1 in Spark). NULL on zero divisor."""
    symbol = "pmod"

    def columnar_eval(self, batch):
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        out_t = self.data_type
        if isinstance(out_t, DecimalType):
            return self._decimal_eval(l, r, out_t)
        ld, rd = _promote(l, r, out_t)
        div_ok = rd != 0
        safe_r = jnp.where(div_ok, rd, jnp.ones((), rd.dtype))
        # Spark Pmod (arithmetic.scala): r = a % n; r < 0 ? (r + n) % n : r
        # — with Java remainder. For n < 0 the result stays negative
        # (Spark returns -1 for pmod(-7, -6)); do NOT normalize by |n|.
        if isinstance(out_t, FractionalType):
            def rem(x):
                return x - jnp.trunc(x / safe_r) * safe_r
        else:
            def rem(x):
                return _trunc_mod(x, safe_r)
        r0 = rem(ld)
        m = jnp.where(r0 < 0, rem(r0 + safe_r), r0)
        valid = l.validity & r.validity & div_ok
        m = jnp.where(valid, m, jnp.zeros((), m.dtype))
        return Column(m, valid, out_t)


class UnaryMinus(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return UnaryMinus(children[0])

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        return Column(-c.data, c.validity, c.dtype)


class Abs(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return Abs(children[0])

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        return Column(jnp.abs(c.data), c.validity, c.dtype)


class Least(Expression):
    """Spark least(): null-skipping minimum across children."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return Least(*children)

    def columnar_eval(self, batch):
        return _least_greatest(self, batch, want_smaller=True)


class Greatest(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return Greatest(*children)

    def columnar_eval(self, batch):
        return _least_greatest(self, batch, want_smaller=False)


def _least_greatest(node, batch, want_smaller: bool):
    """Null-skipping min/max across children with Java float ordering
    (NaN greatest) — Spark least()/greatest()."""
    from .predicates import _float_compare_sign
    cols = [c.columnar_eval(batch) for c in node.children]
    out_t = node.data_type
    is_float = jnp.issubdtype(out_t.jnp_dtype, jnp.floating) \
        if out_t.jnp_dtype is not None else False
    data, valid = None, None
    for c in cols:
        d = c.data.astype(out_t.jnp_dtype)
        if data is None:
            data, valid = d, c.validity
            continue
        if is_float:
            sign = _float_compare_sign(d, data)
            better = (sign < 0) if want_smaller else (sign > 0)
        else:
            better = (d < data) if want_smaller else (d > data)
        take_new = c.validity & (~valid | better)
        data = jnp.where(take_new, d, data)
        valid = valid | c.validity
    return Column(jnp.where(valid, data, jnp.zeros((), data.dtype)),
                  valid, out_t)

