"""Conditional & null expressions (reference conditionalExpressions.scala,
nullExpressions.scala): If, CaseWhen, Coalesce, IsNaN, NaNvl, Nvl-family.

All are lazy in Spark only for side effects; columnar eval computes all
branches and blends with jnp.where — the XLA-idiomatic form (no divergence
cost on a vector machine; fusion collapses the blends).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BOOLEAN, DOUBLE, DataType, DoubleType, FloatType
from .core import Expression


def _blend(pred_data, pred_valid, t: Column, f: Column) -> Column:
    """Select t where predicate is TRUE (valid & data), else f; a NULL
    predicate selects the else branch value semantics-wise? No — Spark: NULL
    predicate yields the else branch in CaseWhen chains and NULL-selects
    `else` in If. Spark If: if(cond, a, b) with NULL cond -> b."""
    take_t = pred_data & pred_valid
    if isinstance(t, StringColumn) or isinstance(f, StringColumn):
        return _blend_strings(take_t, t, f)
    data = jnp.where(take_t, t.data, f.data)
    valid = jnp.where(take_t, t.validity, f.validity)
    return Column(jnp.where(valid, data, jnp.zeros((), data.dtype)),
                  valid, t.dtype)


def _blend_strings(take_t, t: StringColumn, f: StringColumn) -> StringColumn:
    """Row-wise select between two string columns: rebuild offsets+bytes."""
    from ..ops.strings import string_lengths, _rebuild_offsets
    lt = string_lengths(t)
    lf = string_lengths(f)
    valid = jnp.where(take_t, t.validity, f.validity)
    lengths = jnp.where(valid, jnp.where(take_t, lt, lf), 0)
    new_offsets = _rebuild_offsets(lengths)
    # worst case the selection keeps every byte of both inputs' used regions
    byte_cap = t.byte_capacity + f.byte_capacity
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets, pos, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, t.capacity - 1)
    intra = pos - new_offsets[row]
    from_t = take_t[row]
    t_pos = jnp.clip(t.offsets[row] + intra, 0, t.byte_capacity - 1)
    f_pos = jnp.clip(f.offsets[row] + intra, 0, f.byte_capacity - 1)
    in_use = pos < new_offsets[-1]
    data = jnp.where(in_use,
                     jnp.where(from_t, t.data[t_pos], f.data[f_pos]),
                     jnp.uint8(0))
    return StringColumn(data, new_offsets, valid, t.dtype)


class If(Expression):
    def __init__(self, pred: Expression, t: Expression, f: Expression):
        self.children = (pred, t, f)

    def with_children(self, children):
        return If(*children)

    @property
    def data_type(self):
        return self.children[1].data_type

    def columnar_eval(self, batch):
        p = self.children[0].columnar_eval(batch)
        t = self.children[1].columnar_eval(batch)
        f = self.children[2].columnar_eval(batch)
        return _blend(p.data, p.validity, t, f)


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 ... ELSE e END — right-fold of If blends."""

    def __init__(self, branches, else_value: Expression | None = None):
        flat = []
        for c, v in branches:
            flat += [c, v]
        if else_value is not None:
            flat.append(else_value)
        self.children = tuple(flat)
        self.n_branches = len(branches)
        self.has_else = else_value is not None

    def with_children(self, children):
        n = self.n_branches
        branches = [(children[2 * i], children[2 * i + 1]) for i in range(n)]
        else_v = children[-1] if self.has_else else None
        return CaseWhen(branches, else_v)

    def _semantic_args(self):
        return (self.n_branches, self.has_else)

    @property
    def data_type(self):
        return self.children[1].data_type

    def columnar_eval(self, batch):
        n = self.n_branches
        if self.has_else:
            result = self.children[-1].columnar_eval(batch)
        else:
            from .core import Literal
            result = Literal(None, self.data_type).columnar_eval(batch)
        # fold from the last branch backwards so earlier branches win
        for i in reversed(range(n)):
            p = self.children[2 * i].columnar_eval(batch)
            v = self.children[2 * i + 1].columnar_eval(batch)
            result = _blend(p.data, p.validity, v, result)
        return result


class Coalesce(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return Coalesce(*children)

    @property
    def data_type(self):
        for c in self.children:
            from ..types import NullType
            if not isinstance(c.data_type, NullType):
                return c.data_type
        return self.children[0].data_type

    def columnar_eval(self, batch):
        cols = [c.columnar_eval(batch) for c in self.children]
        result = cols[-1]
        for c in reversed(cols[:-1]):
            result = _blend(c.validity, jnp.ones_like(c.validity), c, result)
        return result


class Nvl(Coalesce):
    """nvl/ifnull(a, b) == coalesce(a, b) (reference GpuNvl)."""

    def __init__(self, a: Expression, b: Expression):
        super().__init__(a, b)

    def with_children(self, children):
        return Nvl(*children)


class Nvl2(Expression):
    """nvl2(a, b, c): b when a is not null, else c (reference GpuNvl2 —
    NOT an If(IsNotNull(a)) rewrite because b/c eval unconditionally)."""

    def __init__(self, a: Expression, b: Expression, c: Expression):
        self.children = (a, b, c)

    def with_children(self, children):
        return Nvl2(*children)

    @property
    def data_type(self):
        return self.children[1].data_type

    def columnar_eval(self, batch):
        a = self.children[0].columnar_eval(batch)
        b = self.children[1].columnar_eval(batch)
        c = self.children[2].columnar_eval(batch)
        return _blend(a.validity, jnp.ones_like(a.validity), b, c)


class NullIf(Expression):
    """nullif(a, b): null when a == b else a (reference GpuNullIf)."""

    def __init__(self, a: Expression, b: Expression):
        self.children = (a, b)

    def with_children(self, children):
        return NullIf(*children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        from ..columnar.column import StringColumn
        a = self.children[0].columnar_eval(batch)
        b = self.children[1].columnar_eval(batch)
        if isinstance(a, StringColumn):
            from ..ops.strings import string_equal
            eq_col = string_equal(a, b)
            eq = eq_col.data & eq_col.validity
            return StringColumn(a.data, a.offsets, a.validity & ~eq,
                                a.dtype)
        eq = (a.data == b.data) & a.validity & b.validity
        valid = a.validity & ~eq
        return Column(jnp.where(valid, a.data, jnp.zeros((), a.data.dtype)),
                      valid, a.dtype)


class IsNaN(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return IsNaN(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        data = jnp.isnan(c.data) & c.validity
        return Column(data, jnp.ones_like(c.validity), BOOLEAN)


class NaNvl(Expression):
    """nanvl(a, b): a unless a is NaN, then b (nulls propagate from chosen)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return NaNvl(*children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        a = self.children[0].columnar_eval(batch)
        b = self.children[1].columnar_eval(batch)
        use_b = jnp.isnan(a.data) & a.validity
        data = jnp.where(use_b, b.data.astype(a.data.dtype), a.data)
        valid = jnp.where(use_b, b.validity, a.validity)
        return Column(jnp.where(valid, data, jnp.zeros((), data.dtype)),
                      valid, a.dtype)
