"""Collection (array) expressions — the engine's first slice of the
reference's collectionOperations.scala operator family (GpuSize,
GpuArrayContains, GpuElementAt, GpuGetArrayItem, GpuSortArray,
GpuArrayMin/Max, GpuCreateArray)."""

from __future__ import annotations

from ..columnar.column import ArrayColumn
from ..ops import collection as C
from ..types import BOOLEAN, INT, ArrayType
from .core import Expression, Literal


class Size(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Size(children[0])

    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return C.array_size(self.children[0].columnar_eval(batch))


class ArrayContains(Expression):
    def __init__(self, child: Expression, value):
        self.children = (child,)
        self.value = value.value if isinstance(value, Literal) else value

    def with_children(self, children):
        return ArrayContains(children[0], self.value)

    def _semantic_args(self):
        return (self.value,)

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        return C.array_contains(self.children[0].columnar_eval(batch),
                                self.value)


class ElementAt(Expression):
    """element_at(arr, i): 1-based, negative from end, null out of bounds
    (non-ANSI)."""

    def __init__(self, child: Expression, index):
        self.children = (child,)
        self.index = index.value if isinstance(index, Literal) else index

    def with_children(self, children):
        return type(self)(children[0], self.index)

    def _semantic_args(self):
        return (self.index,)

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def columnar_eval(self, batch):
        return C.element_at(self.children[0].columnar_eval(batch),
                            self.index)


class GetArrayItem(ElementAt):
    """arr[i]: 0-based, null out of bounds (non-ANSI)."""

    def columnar_eval(self, batch):
        return C.get_array_item(self.children[0].columnar_eval(batch),
                                self.index)


class SortArray(Expression):
    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending.value if isinstance(ascending, Literal) \
            else ascending

    def with_children(self, children):
        return SortArray(children[0], self.ascending)

    def _semantic_args(self):
        return (self.ascending,)

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        return C.sort_array(self.children[0].columnar_eval(batch),
                            self.ascending)


class ArrayMin(Expression):
    OP = "min"

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return type(self)(children[0])

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def columnar_eval(self, batch):
        return C.array_min_max(self.children[0].columnar_eval(batch),
                               self.OP)


class ArrayMax(ArrayMin):
    OP = "max"


class CreateArray(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return CreateArray(*children)

    @property
    def data_type(self):
        return ArrayType(self.children[0].data_type)

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        cols = [c.columnar_eval(batch) for c in self.children]
        return C.create_array(cols, self.data_type)
