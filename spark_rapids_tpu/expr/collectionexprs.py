"""Collection (array) expressions — the engine's first slice of the
reference's collectionOperations.scala operator family (GpuSize,
GpuArrayContains, GpuElementAt, GpuGetArrayItem, GpuSortArray,
GpuArrayMin/Max, GpuCreateArray)."""

from __future__ import annotations

from ..columnar.column import ArrayColumn
from ..ops import collection as C
from ..types import BOOLEAN, INT, ArrayType
from .core import Expression, Literal


class Size(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Size(children[0])

    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return C.array_size(self.children[0].columnar_eval(batch))


class ArrayContains(Expression):
    def __init__(self, child: Expression, value):
        self.children = (child,)
        self.value = value.value if isinstance(value, Literal) else value

    def with_children(self, children):
        return ArrayContains(children[0], self.value)

    def _semantic_args(self):
        return (self.value,)

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        return C.array_contains(self.children[0].columnar_eval(batch),
                                self.value)


class ElementAt(Expression):
    """element_at(arr, i) — 1-based, negative from end, null out of
    bounds (non-ANSI) — or element_at(map, key)."""

    def __init__(self, child: Expression, index):
        if isinstance(index, Expression) and not isinstance(index, Literal):
            # expression index: dispatches on the CHILD's resolved type at
            # eval (map lookup vs per-row array index — ADVICE r3 #1)
            self.children = (child, index)
            self.index = None
        else:
            self.children = (child,)
            self.index = index.value if isinstance(index, Literal) else index

    def with_children(self, children):
        return type(self)(children[0],
                          children[1] if len(children) == 2 else self.index)

    def _semantic_args(self):
        return (self.index,)

    @property
    def data_type(self):
        from ..types import MapType
        ct = self.children[0].data_type
        if isinstance(ct, MapType):
            return ct.value_type
        return ct.element_type

    def columnar_eval(self, batch):
        from ..columnar.column import MapColumn
        c = self.children[0].columnar_eval(batch)
        if len(self.children) == 2:
            k = self.children[1].columnar_eval(batch)
            if isinstance(c, MapColumn):
                from ..ops.maps import map_get
                return map_get(c, k)
            return C.element_at_col(c, k)
        if isinstance(c, MapColumn):
            from ..ops.maps import map_get
            return map_get(c, self.index)
        if self.index == 0:
            # Spark raises even in non-ANSI mode (GpuElementAt); the
            # per-row expression-index path (element_at_col) deviates and
            # returns NULL — the index is data, and a device-side raise
            # would force a host sync per batch (documented in
            # ops/collection.element_at_col).
            raise ValueError("SQL array indices start at 1")
        return C.element_at(c, self.index)

    def host_eval_row(self, *vals):
        v = vals[0]
        i = vals[1] if len(self.children) == 2 else self.index
        if len(self.children) == 1 and i == 0:
            from ..types import MapType
            if not isinstance(self.children[0].data_type, MapType):
                # static literal 0: raise before the null check so host and
                # device tiers agree (Spark raises regardless of the row)
                raise ValueError("SQL array indices start at 1")
        if v is None or i is None:
            return None
        if isinstance(v, dict):
            return v.get(i)
        if i == 0:
            # per-row index 0 -> NULL, matching the device kernel's
            # documented deviation (ops/collection.element_at_col)
            return None
        if abs(i) > len(v):
            return None
        return v[i - 1] if i > 0 else v[i]


class GetArrayItem(ElementAt):
    """arr[i]: 0-based, null out of bounds (non-ANSI)."""

    def columnar_eval(self, batch):
        return C.get_array_item(self.children[0].columnar_eval(batch),
                                self.index)

    def host_eval_row(self, *vals):
        v = vals[0]
        if v is None or self.index is None:
            return None
        if 0 <= self.index < len(v):
            return v[self.index]
        return None


class SortArray(Expression):
    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending.value if isinstance(ascending, Literal) \
            else ascending

    def with_children(self, children):
        return SortArray(children[0], self.ascending)

    def _semantic_args(self):
        return (self.ascending,)

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        return C.sort_array(self.children[0].columnar_eval(batch),
                            self.ascending)


class ArrayMin(Expression):
    OP = "min"

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return type(self)(children[0])

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def columnar_eval(self, batch):
        return C.array_min_max(self.children[0].columnar_eval(batch),
                               self.OP)


class ArrayMax(ArrayMin):
    OP = "max"


class CreateArray(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return CreateArray(*children)

    @property
    def data_type(self):
        return ArrayType(self.children[0].data_type)

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        cols = [c.columnar_eval(batch) for c in self.children]
        return C.create_array(cols, self.data_type)


# ---------------------------------------------------------------------------
# higher-order functions + collection long tail (reference
# higherOrderFunctions.scala / collectionOperations.scala). Host-tier:
# these evaluate through the CPU fallback transitions (exec/fallback.py)
# — ragged per-element lambdas have no static-shape device kernel yet.
# Lambdas are expression trees over LambdaVar placeholders, mirroring
# Catalyst's LambdaFunction/NamedLambdaVariable.
# ---------------------------------------------------------------------------

class LambdaVar(Expression):
    """Catalyst NamedLambdaVariable analog: a placeholder the HOF binds
    per element at evaluation time."""

    children = ()

    def __init__(self, name: str = "x"):
        self.name = name

    def with_children(self, cs):
        return self

    def _semantic_args(self):
        return (self.name,)

    @property
    def data_type(self):
        raise TypeError(f"unbound lambda variable {self.name!r}")

    def __repr__(self):
        return f"λ{self.name}"


def _subst(body: Expression, mapping):
    from .core import lit

    def fn(node):
        if isinstance(node, LambdaVar) and node.name in mapping:
            return lit(mapping[node.name])
        return node
    return body.transform_up(fn)


class _HostHOF(Expression):
    """Base: children = (array,); `body` is the lambda expression over
    LambdaVar(var) [and optionally LambdaVar(idx_var)]."""

    def __init__(self, child: Expression, body: Expression,
                 var: str = "x"):
        self.children = (child,)
        self.body = body
        self.var = var

    def with_children(self, cs):
        return type(self)(cs[0], self.body, self.var)

    def transform_up(self, fn):
        # the lambda body must see tree rewrites too (column resolution
        # binds outer references inside the body; LambdaVars pass
        # through untouched)
        child = self.children[0].transform_up(fn)
        body = self.body.transform_up(fn)
        return fn(type(self)(child, body, self.var))

    def _semantic_args(self):
        return (self.body.semantic_key(), self.var)

    #: device kernel per subclass (ops/array_hof.py); None = host only
    _device_kernel = None

    @property
    def device_supported(self) -> bool:
        """Lambda bodies whose leaves are just the lambda variable and
        literals — and whose every interior operator has a device kernel
        — run on the device as one flat pass over the child column;
        everything else stays host tier."""
        cached = getattr(self, "_dev_ok", None)
        if cached is None:
            cached = self._compute_device_supported()
            self._dev_ok = cached
        return cached

    def _compute_device_supported(self) -> bool:
        if self._device_kernel is None:
            return False
        from ..types import ArrayType
        from .core import Literal

        def node_ok(node) -> bool:
            if isinstance(node, LambdaVar):
                return node.name == self.var
            if isinstance(node, Literal):
                return True
            # interior operators must be device-evaluable themselves:
            # a partial device kernel exposes device_supported; pure
            # host-tier classes carry the HOST_ONLY marker
            ds = getattr(node, "device_supported", None)
            if ds is not None and not ds:
                return False
            if ds is None and getattr(node, "HOST_ONLY", False):
                return False
            kids = getattr(node, "children", ())
            if not kids:
                return False  # column refs / unknown leaves
            return all(node_ok(c) for c in kids)

        try:
            arr_t = self.children[0].data_type
        except (TypeError, NotImplementedError):
            return False
        if not isinstance(arr_t, ArrayType):
            return False
        if isinstance(arr_t.element_type, ArrayType):
            return False  # nested arrays await the nested-column work
        return node_ok(self.body)

    def columnar_eval(self, batch):
        from ..ops import array_hof
        if not self.device_supported:
            raise NotImplementedError(
                f"{type(self).__name__} lambda runs on the host tier")
        arr = self.children[0].columnar_eval(batch)
        return getattr(array_hof, self._device_kernel)(arr, self.body,
                                                       self.var)

    def _elem(self, row, eval_fn, v):
        return eval_fn(_subst(self.body, {self.var: v}), row)

    def _body_type(self):
        """Body type with the lambda var bound to the element type (the
        Catalyst bind step that gives NamedLambdaVariable its type)."""
        from ..types import ArrayType
        from .core import Literal
        arr_t = self.children[0].data_type
        elem = arr_t.element_type if isinstance(arr_t, ArrayType) else arr_t

        def fn(node):
            if isinstance(node, LambdaVar) and node.name == self.var:
                return Literal(None, elem)
            return node
        return self.body.transform_up(fn).data_type


class ArrayTransform(_HostHOF):
    """transform(arr, x -> expr)"""

    _device_kernel = "array_transform"

    @property
    def data_type(self):
        from ..types import NULL, ArrayType
        try:
            return ArrayType(self._body_type())
        except TypeError:
            return ArrayType(NULL)

    def host_eval_with_row(self, row, eval_fn):
        arr = eval_fn(self.children[0], row)
        if arr is None:
            return None
        return [self._elem(row, eval_fn, v) for v in arr]


class ArrayFilter(_HostHOF):
    """filter(arr, x -> predicate)"""

    _device_kernel = "array_filter"

    @property
    def data_type(self):
        return self.children[0].data_type

    def host_eval_with_row(self, row, eval_fn):
        arr = eval_fn(self.children[0], row)
        if arr is None:
            return None
        return [v for v in arr if self._elem(row, eval_fn, v) is True]


class ArrayExists(_HostHOF):
    """exists(arr, x -> predicate): Spark 3-valued semantics."""

    _device_kernel = "array_exists"

    @property
    def data_type(self):
        from ..types import BOOLEAN
        return BOOLEAN

    def host_eval_with_row(self, row, eval_fn):
        arr = eval_fn(self.children[0], row)
        if arr is None:
            return None
        saw_null = False
        for v in arr:
            r = self._elem(row, eval_fn, v)
            if r is True:
                return True
            if r is None:
                saw_null = True
        return None if saw_null else False


class ArrayForAll(_HostHOF):
    """forall(arr, x -> predicate)"""

    _device_kernel = "array_forall"

    @property
    def data_type(self):
        from ..types import BOOLEAN
        return BOOLEAN

    def host_eval_with_row(self, row, eval_fn):
        arr = eval_fn(self.children[0], row)
        if arr is None:
            return None
        saw_null = False
        for v in arr:
            r = self._elem(row, eval_fn, v)
            if r is False:
                return False
            if r is None:
                saw_null = True
        return None if saw_null else True


class ArrayAggregate(Expression):
    """aggregate(arr, zero, (acc, x) -> merge[, acc -> finish])"""

    def __init__(self, child: Expression, zero: Expression,
                 merge: Expression, finish: Expression = None,
                 acc_var: str = "acc", var: str = "x"):
        self.children = (child, zero)
        self.merge = merge
        self.finish = finish
        self.acc_var = acc_var
        self.var = var

    def with_children(self, cs):
        return ArrayAggregate(cs[0], cs[1], self.merge, self.finish,
                              self.acc_var, self.var)

    def transform_up(self, fn):
        cs = [c.transform_up(fn) for c in self.children]
        merge = self.merge.transform_up(fn)
        finish = self.finish.transform_up(fn) \
            if self.finish is not None else None
        return fn(ArrayAggregate(cs[0], cs[1], merge, finish,
                                 self.acc_var, self.var))

    def _semantic_args(self):
        return (self.merge.semantic_key(),
                self.finish.semantic_key() if self.finish else None,
                self.acc_var, self.var)

    @property
    def data_type(self):
        from ..types import ArrayType
        from .core import Literal
        try:
            zero_t = self.children[1].data_type
            arr_t = self.children[0].data_type
            elem = arr_t.element_type if isinstance(arr_t, ArrayType) \
                else arr_t

            def bind(node):
                if isinstance(node, LambdaVar):
                    if node.name == self.acc_var:
                        return Literal(None, zero_t)
                    if node.name == self.var:
                        return Literal(None, elem)
                return node
            merged_t = self.merge.transform_up(bind).data_type
            if self.finish is None:
                return merged_t

            def bind_f(node):
                if isinstance(node, LambdaVar) \
                        and node.name == self.acc_var:
                    return Literal(None, merged_t)
                return node
            return self.finish.transform_up(bind_f).data_type
        except TypeError:
            return self.children[1].data_type

    def columnar_eval(self, batch):
        raise NotImplementedError(
            "aggregate() runs on the host tier (CPU fallback)")

    def host_eval_with_row(self, row, eval_fn):
        arr = eval_fn(self.children[0], row)
        if arr is None:
            return None
        acc = eval_fn(self.children[1], row)
        for v in arr:
            acc = eval_fn(_subst(self.merge,
                                 {self.acc_var: acc, self.var: v}), row)
        if self.finish is not None:
            acc = eval_fn(_subst(self.finish, {self.acc_var: acc}), row)
        return acc


class _HostCollection(Expression):
    HOST_ONLY = True
    def columnar_eval(self, batch):
        raise NotImplementedError(
            f"{type(self).__name__} runs on the host tier (CPU fallback)")


def _host_spark_eq(a, b) -> bool:
    """Spark ordering equality on the host tier: NaN == NaN,
    -0.0 != 0.0 (java.lang.Double.compare semantics)."""
    import math
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        if a == 0.0 and b == 0.0:
            return math.copysign(1.0, a) == math.copysign(1.0, b)
    return a == b


def _fixed_width_elems(expr) -> bool:
    """Device gate: array child with fixed-width (non-nested, non-string)
    elements."""
    from ..types import ArrayType
    try:
        dt = expr.data_type
    except TypeError:
        return False
    return isinstance(dt, ArrayType) and dt.element_type.is_fixed_width


class ArrayPosition(_HostCollection):
    """array_position(arr, v): 1-based first index, 0 if absent.
    Device kernel for fixed-width elements (ops/collection.array_position,
    reference GpuArrayPosition); string elements take the host tier."""

    def __init__(self, child: Expression, value: Expression):
        self.children = (child, value)

    def with_children(self, cs):
        return ArrayPosition(cs[0], cs[1])

    @property
    def device_supported(self):
        return _fixed_width_elems(self.children[0])

    @property
    def data_type(self):
        from ..types import LONG
        return LONG

    def columnar_eval(self, batch):
        from ..ops.collection import array_position
        return array_position(self.children[0].columnar_eval(batch),
                              self.children[1].columnar_eval(batch))

    def host_eval_row(self, arr, v):
        if arr is None or v is None:
            return None
        for i, item in enumerate(arr):
            if item is not None and _host_spark_eq(item, v):
                return i + 1
        return 0


class ArrayRemove(_HostCollection):
    """Device kernel for fixed-width elements (reference
    GpuArrayRemove)."""

    def __init__(self, child: Expression, value: Expression):
        self.children = (child, value)

    def with_children(self, cs):
        return ArrayRemove(cs[0], cs[1])

    @property
    def device_supported(self):
        return _fixed_width_elems(self.children[0])

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        from ..ops.collection import array_remove
        return array_remove(self.children[0].columnar_eval(batch),
                            self.children[1].columnar_eval(batch))

    def host_eval_row(self, arr, v):
        if arr is None or v is None:
            return None
        return [x for x in arr
                if x is None or not _host_spark_eq(x, v)]


class ArrayDistinct(_HostCollection):
    """Device kernel for fixed-width elements (reference
    GpuArrayDistinct)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return ArrayDistinct(cs[0])

    @property
    def device_supported(self):
        return _fixed_width_elems(self.children[0])

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        from ..ops.collection import array_distinct
        return array_distinct(self.children[0].columnar_eval(batch))

    def host_eval_row(self, arr):
        if arr is None:
            return None
        out = []
        saw_null = False
        for x in arr:
            if x is None:
                if not saw_null:
                    out.append(None)
                    saw_null = True
            elif x not in out:
                out.append(x)
        return out


class Slice(_HostCollection):
    """slice(arr, start, length): 1-based; negative start from end.
    Device kernel (reference GpuSlice); a data-dependent start of 0 or
    negative length yields NULL on device (Spark raises — the host tier
    keeps the raise for literal args)."""

    def __init__(self, child: Expression, start: Expression,
                 length: Expression):
        self.children = (child, start, length)

    def with_children(self, cs):
        return Slice(cs[0], cs[1], cs[2])

    @property
    def device_supported(self):
        return _fixed_width_elems(self.children[0])

    @property
    def data_type(self):
        return self.children[0].data_type

    def columnar_eval(self, batch):
        from ..ops.collection import array_slice
        return array_slice(self.children[0].columnar_eval(batch),
                           self.children[1].columnar_eval(batch),
                           self.children[2].columnar_eval(batch))

    def host_eval_row(self, arr, start, length):
        if arr is None or start is None or length is None:
            return None
        if start == 0:
            raise ValueError("slice(): start must not be 0")
        if length < 0:
            raise ValueError("slice(): length must be >= 0")
        i = start - 1 if start > 0 else len(arr) + start
        if i < 0:
            return []
        return arr[i: i + length]


class Flatten(_HostCollection):
    """flatten(arr<arr<T>>): pure offset composition on device for ANY
    inner element type (reference GpuFlatten)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return Flatten(cs[0])

    @property
    def device_supported(self):
        from ..types import ArrayType
        try:
            dt = self.children[0].data_type
        except TypeError:
            return False
        return isinstance(dt, ArrayType) \
            and isinstance(dt.element_type, ArrayType)

    @property
    def data_type(self):
        from ..types import ArrayType
        dt = self.children[0].data_type
        return dt.element_type if isinstance(dt, ArrayType) else dt

    def columnar_eval(self, batch):
        from ..ops.collection import flatten_array
        return flatten_array(self.children[0].columnar_eval(batch))

    def host_eval_row(self, arr):
        if arr is None:
            return None
        out = []
        for sub in arr:
            if sub is None:
                return None  # Spark: null inner array -> null result
            out.extend(sub)
        return out


class ArraysOverlap(_HostCollection):
    """Device sort-merge kernel for fixed-width elements (reference
    GpuArraysOverlap)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, cs):
        return ArraysOverlap(cs[0], cs[1])

    @property
    def device_supported(self):
        return _fixed_width_elems(self.children[0]) \
            and _fixed_width_elems(self.children[1])

    @property
    def data_type(self):
        from ..types import BOOLEAN
        return BOOLEAN

    def columnar_eval(self, batch):
        from ..ops.collection import arrays_overlap
        return arrays_overlap(self.children[0].columnar_eval(batch),
                              self.children[1].columnar_eval(batch))

    def host_eval_row(self, a, b):
        if a is None or b is None:
            return None
        bs = {x for x in b if x is not None}
        if any(x in bs for x in a if x is not None):
            return True
        # Spark: NULL only when BOTH arrays are non-empty and either has
        # a null element; an empty side always gives false
        if a and b and (None in a or None in b):
            return None
        return False


class ArrayJoin(_HostCollection):
    def __init__(self, child: Expression, delim, null_replacement=None):
        from .core import Literal
        self.children = (child,)
        self.delim = delim.value if isinstance(delim, Literal) else delim
        self.null_replacement = null_replacement.value \
            if isinstance(null_replacement, Literal) else null_replacement

    def with_children(self, cs):
        return ArrayJoin(cs[0], self.delim, self.null_replacement)

    def _semantic_args(self):
        return (self.delim, self.null_replacement)

    @property
    def data_type(self):
        from ..types import STRING
        return STRING

    def host_eval_row(self, arr):
        if arr is None:
            return None
        parts = []
        for x in arr:
            if x is None:
                if self.null_replacement is not None:
                    parts.append(self.null_replacement)
            else:
                parts.append(str(x))
        return self.delim.join(parts)


class Sequence(_HostCollection):
    """sequence(start, stop[, step]) -> array<long>.

    Device kernel (ops/collection.sequence_array, reference GpuSequence)
    when every bound is a LITERAL — the output child capacity is then
    static under XLA; data-dependent bounds keep the host tier (dynamic
    output shapes cannot trace)."""

    def __init__(self, start: Expression, stop: Expression,
                 step: Expression = None):
        self.children = (start, stop) + ((step,) if step is not None
                                         else ())

    def with_children(self, cs):
        return Sequence(*cs)

    @property
    def device_supported(self):
        from .core import Literal
        from ..types import IntegerType, LongType, ShortType, ByteType
        if not all(isinstance(c, Literal) and c.value is not None
                   for c in self.children):
            return False
        try:
            return all(isinstance(c.data_type, (ByteType, ShortType,
                                                IntegerType, LongType))
                       for c in self.children)
        except TypeError:
            return False

    def columnar_eval(self, batch):
        from ..columnar.column import Column, bucket_capacity
        from ..ops.collection import sequence_array
        import jax.numpy as jnp
        start = self.children[0].value
        stop = self.children[1].value
        step = self.children[2].value if len(self.children) > 2 \
            else (1 if stop >= start else -1)
        if step == 0:
            raise ValueError("sequence(): step must not be 0")
        n = max((stop - start) // step + 1, 0) \
            if (stop - start) * step >= 0 else 0
        cols = [c.columnar_eval(batch) for c in self.children]
        cap = cols[0].capacity
        if len(cols) < 3:
            cols.append(Column(jnp.full((cap,), step, cols[0].data.dtype),
                               jnp.ones((cap,), jnp.bool_),
                               cols[0].dtype))
        ccap = bucket_capacity(max(int(n) * cap, 1))
        return sequence_array(cols[0], cols[1], cols[2], ccap)

    @property
    def data_type(self):
        from ..types import ArrayType
        return ArrayType(self.children[0].data_type)

    def host_eval_row(self, start, stop, step=None):
        if start is None or stop is None:
            return None
        if step is None:
            step = 1 if stop >= start else -1
        if step == 0:
            raise ValueError("sequence(): step must not be 0")
        out = []
        v = start
        if step > 0:
            while v <= stop:
                out.append(v)
                v += step
        else:
            while v >= stop:
                out.append(v)
                v += step
        return out


class ArrayRepeat(_HostCollection):
    """array_repeat(e, n) (reference GpuArrayRepeat). Device kernel when
    the count is a LITERAL (static child capacity under XLA); per-row
    counts keep the host tier."""

    def __init__(self, elem: Expression, count: Expression):
        self.children = (elem, count)

    def with_children(self, cs):
        return ArrayRepeat(cs[0], cs[1])

    @property
    def device_supported(self):
        from .core import Literal
        c = self.children[1]
        if not (isinstance(c, Literal) and c.value is not None):
            return False
        try:
            return self.children[0].data_type.is_fixed_width
        except TypeError:
            return False

    @property
    def data_type(self):
        from ..types import ArrayType
        return ArrayType(self.children[0].data_type)

    def columnar_eval(self, batch):
        from ..columnar.column import bucket_capacity
        from ..ops.collection import array_repeat
        elem = self.children[0].columnar_eval(batch)
        count = self.children[1].columnar_eval(batch)
        n = max(int(self.children[1].value), 0)
        ccap = bucket_capacity(max(n * elem.capacity, 1))
        return array_repeat(elem, count, ccap)

    def host_eval_row(self, v, n):
        if n is None:
            return None
        return [v] * max(n, 0)
