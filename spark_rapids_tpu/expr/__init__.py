"""Expression layer — the engine's analog of the reference's ~218 Gpu
expression implementations (GpuOverrides.scala:919 rule table)."""

from .core import (
    Alias, BoundReference, Expression, Literal, UnresolvedAttribute, col, lit,
    output_name, resolve,
)
from .arithmetic import (
    Abs, Add, Divide, Greatest, IntegralDivide, Least, Multiply, Pmod,
    Remainder, Subtract, UnaryMinus,
)
from .predicates import (
    And, EqualNullSafe, EqualTo, GreaterThan, GreaterThanOrEqual, In, IsNotNull,
    IsNull, LessThan, LessThanOrEqual, Not, Or,
)
from .conditional import CaseWhen, Coalesce, If, IsNaN, NaNvl
from .math import (
    Acos, Asin, Atan, Atan2, BRound, Cbrt, Ceil, Cos, Cosh, Exp, Expm1, Floor,
    Log, Log10, Log1p, Log2, Pow, Rint, Round, Signum, Sin, Sinh, Sqrt, Tan,
    Tanh, ToDegrees, ToRadians,
)
from .cast import Cast
from .stringexprs import (
    Contains, EndsWith, Length, Lower, StartsWith, Substring, Upper,
)
from .hashexprs import Murmur3Hash, XxHash64
