"""Z-order expressions (reference org/.../rapids/zorder/: ZOrderRules,
GpuInterleaveBits + JNI ZOrder — used by Delta OPTIMIZE ZORDER BY).

Device kernel: normalize each INT/LONG key to an unsigned rank (flip the
sign bit, so ordering is preserved across negatives), then interleave the
keys' bits MSB-first into one LONG morton code. Sorting by the code
clusters rows that are close in ALL keys — the data-skipping win Delta's
OPTIMIZE chases. Pure bitwise XLA; no host round trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import Column
from ..types import LONG, LongType
from .core import Expression


class InterleaveBits(Expression):
    """interleave_bits(k1, k2, ...) -> LONG morton code (MSB-first over
    the top bits of each key; 64 // n_keys bits per key)."""

    def __init__(self, *children: Expression):
        assert children, "interleave_bits needs at least one key"
        assert len(children) <= 8, "at most 8 z-order keys"
        self.children = tuple(children)

    def with_children(self, cs):
        return InterleaveBits(*cs)

    @property
    def data_type(self):
        return LONG

    def columnar_eval(self, batch) -> Column:
        cols = [c.columnar_eval(batch) for c in self.children]
        n = len(cols)
        bits_per = 64 // n
        valid = cols[0].validity
        for c in cols[1:]:
            valid = valid & c.validity
        # order-preserving unsigned rank: flip the sign bit of the i64
        ranks = []
        for c in cols:
            v = c.data.astype(jnp.int64).astype(jnp.uint64)
            ranks.append(jnp.bitwise_xor(v, jnp.uint64(1 << 63)))
        out = jnp.zeros_like(ranks[0])
        # MSB-first round-robin: bit b of the code takes bit
        # (63 - b // n) of key (b % n)
        for b in range(n * bits_per):
            key = ranks[b % n]
            src_bit = 63 - (b // n)
            dst_bit = n * bits_per - 1 - b
            bit = jnp.bitwise_and(
                jax.lax.shift_right_logical(key, jnp.uint64(src_bit)),
                jnp.uint64(1))
            out = jnp.bitwise_or(
                out, jax.lax.shift_left(bit, jnp.uint64(dst_bit)))
        # the code is an UNSIGNED rank; flip its top bit so the stored
        # signed LONG sorts in the same order (mirror of the per-key
        # normalization above)
        out = jnp.bitwise_xor(out, jnp.uint64(1 << 63))
        data = out.astype(jnp.int64)
        data = jnp.where(valid, data, jnp.zeros((), jnp.int64))
        return Column(data, valid, LongType())
