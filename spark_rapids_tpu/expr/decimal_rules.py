"""Spark DecimalPrecision result-type rules (allowPrecisionLoss=true default).

Mirrors the semantics the reference gets from Spark's DecimalPrecision +
its own DecimalUtil.scala / decimalExpressions.scala checks.
"""

from __future__ import annotations

from ..types import DecimalType, DataType, IntegralType, ByteType, ShortType, IntegerType, LongType

MAX_PRECISION = 38
MINIMUM_ADJUSTED_SCALE = 6


def _adjust(precision: int, scale: int) -> DecimalType:
    if precision <= MAX_PRECISION:
        return DecimalType(precision, scale)
    int_digits = precision - scale
    min_scale = min(scale, MINIMUM_ADJUSTED_SCALE)
    adjusted_scale = max(MAX_PRECISION - int_digits, min_scale)
    return DecimalType(MAX_PRECISION, adjusted_scale)


def integral_as_decimal(dt: DataType) -> DecimalType:
    if isinstance(dt, ByteType):
        return DecimalType(3, 0)
    if isinstance(dt, ShortType):
        return DecimalType(5, 0)
    if isinstance(dt, IntegerType):
        return DecimalType(10, 0)
    if isinstance(dt, LongType):
        return DecimalType(20, 0)
    raise TypeError(dt)


def _coerce(dt: DataType) -> DecimalType:
    if isinstance(dt, DecimalType):
        return dt
    if isinstance(dt, IntegralType):
        return integral_as_decimal(dt)
    raise TypeError(f"cannot coerce {dt} to decimal")


def binary_result_type(op: str, lt: DataType, rt: DataType) -> DecimalType:
    l = _coerce(lt)
    r = _coerce(rt)
    p1, s1, p2, s2 = l.precision, l.scale, r.precision, r.scale
    if op in ("Add", "Subtract"):
        scale = max(s1, s2)
        return _adjust(max(p1 - s1, p2 - s2) + scale + 1, scale)
    if op == "Multiply":
        return _adjust(p1 + p2 + 1, s1 + s2)
    if op == "Divide":
        scale = max(MINIMUM_ADJUSTED_SCALE, s1 + p2 + 1)
        return _adjust(p1 - s1 + s2 + scale, scale)
    if op in ("Remainder", "Pmod"):
        scale = max(s1, s2)
        return _adjust(min(p1 - s1, p2 - s2) + scale, scale)
    raise TypeError(f"no decimal rule for {op}")
