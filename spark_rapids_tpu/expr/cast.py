"""Cast expression — Spark cast matrix (reference GpuCast.scala:1823 plus
JNI CastStrings). This module starts with the numeric/temporal core; the
string-cast long tail (string->number parsing with Spark's trim/overflow
rules, number->string formatting) lives in ops/cast_strings.py and grows
under phase 7.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import (
    BOOLEAN, BooleanType, ByteType, DataType, DateType, DecimalType,
    DoubleType, FloatType, FractionalType, IntegerType, IntegralType,
    LongType, ShortType, StringType, TimestampType,
)
from .core import Expression

_INT_BOUNDS = {
    ByteType: (-128, 127),
    ShortType: (-32768, 32767),
    IntegerType: (-(2**31), 2**31 - 1),
    LongType: (-(2**63), 2**63 - 1),
}


class Cast(Expression):
    def __init__(self, child: Expression, dtype: DataType, ansi: bool = False):
        self.children = (child,)
        self._dtype = dtype
        self.ansi = ansi

    def with_children(self, children):
        return Cast(children[0], self._dtype, self.ansi)

    def _semantic_args(self):
        return (repr(self._dtype), self.ansi)

    @property
    def data_type(self):
        return self._dtype

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        src, dst = c.dtype, self._dtype
        if src == dst:
            return c
        if isinstance(dst, StringType):
            from ..ops.cast_strings import cast_to_string
            return cast_to_string(c)
        if isinstance(src, StringType):
            from ..ops.cast_strings import cast_string_to
            return cast_string_to(c, dst)
        if isinstance(dst, BooleanType):
            data = c.data != jnp.zeros((), c.data.dtype)
            return Column(data & c.validity, c.validity, dst)
        if isinstance(src, BooleanType):
            data = c.data.astype(dst.jnp_dtype)
            return Column(data, c.validity, dst)
        if isinstance(dst, IntegralType) and isinstance(src, FractionalType) \
                and not isinstance(src, DecimalType):
            # Spark float->int: truncate; NaN -> 0; out of range saturates
            lo, hi = _INT_BOUNDS[type(dst)]
            x = jnp.nan_to_num(c.data, nan=0.0, posinf=float(hi), neginf=float(lo))
            x = jnp.clip(jnp.trunc(x), float(lo), float(hi))
            # convert via int64 then clamp in the integer domain: XLA's
            # float->int conversion clamping is not exact at the boundary
            data = jnp.clip(x.astype(jnp.int64), lo, hi).astype(dst.jnp_dtype)
            return Column(jnp.where(c.validity, data, 0), c.validity, dst)
        if isinstance(dst, DecimalType):
            return self._cast_to_decimal(c, src, dst)
        if isinstance(src, DecimalType):
            return self._cast_from_decimal(c, src, dst)
        if isinstance(src, DateType) and isinstance(dst, TimestampType):
            data = c.data.astype(jnp.int64) * 86_400_000_000
            return Column(jnp.where(c.validity, data, 0), c.validity, dst)
        if isinstance(src, TimestampType) and isinstance(dst, DateType):
            days = jnp.floor_divide(c.data, 86_400_000_000).astype(jnp.int32)
            return Column(jnp.where(c.validity, days, 0), c.validity, dst)
        if isinstance(src, TimestampType) and isinstance(dst, LongType):
            data = jnp.floor_divide(c.data, 1_000_000)
            return Column(jnp.where(c.validity, data, 0), c.validity, dst)
        if isinstance(src, (IntegralType,)) and isinstance(dst, TimestampType):
            data = c.data.astype(jnp.int64) * 1_000_000
            return Column(jnp.where(c.validity, data, 0), c.validity, dst)
        # numeric widening/narrowing: Java-style wrap on narrowing
        data = c.data.astype(dst.jnp_dtype)
        data = jnp.where(c.validity, data, jnp.zeros((), data.dtype))
        return Column(data, c.validity, dst)

    def _cast_to_decimal(self, c, src, dst: DecimalType):
        scale_m = 10 ** dst.scale
        if isinstance(src, DecimalType):
            shift = dst.scale - src.scale
            if shift >= 0:
                unscaled = c.data * (10 ** shift)
            else:
                unscaled = _round_div_half_up(c.data, 10 ** (-shift))
        elif isinstance(src, IntegralType):
            unscaled = c.data.astype(jnp.int64) * scale_m
        else:  # float/double -> decimal, HALF_UP at target scale
            x = c.data.astype(jnp.float64) * scale_m
            unscaled = jnp.where(x >= 0, jnp.floor(x + 0.5),
                                 jnp.ceil(x - 0.5)).astype(jnp.int64)
        # overflow -> null (non-ANSI)
        bound = 10 ** dst.precision
        ok = (unscaled < bound) & (unscaled > -bound)
        valid = c.validity & ok
        return Column(jnp.where(valid, unscaled, 0), valid, dst)

    def _cast_from_decimal(self, c, src: DecimalType, dst):
        m = 10 ** src.scale
        if isinstance(dst, FractionalType) and not isinstance(dst, DecimalType):
            data = c.data.astype(jnp.float64) / m
            data = data.astype(dst.jnp_dtype)
            return Column(jnp.where(c.validity, data, jnp.zeros((), data.dtype)),
                          c.validity, dst)
        if isinstance(dst, IntegralType):
            q = _trunc_div64(c.data, jnp.int64(m))
            lo, hi = _INT_BOUNDS[type(dst)]
            ok = (q >= lo) & (q <= hi)
            valid = c.validity & ok
            return Column(jnp.where(valid, q.astype(dst.jnp_dtype), 0), valid, dst)
        raise TypeError(f"cast decimal -> {dst} unsupported")


def _trunc_div64(a, b):
    q = a // b
    rem = a - q * b
    adjust = (rem != 0) & ((a < 0) != (b < 0))
    return q + adjust.astype(q.dtype)


def _round_div_half_up(a, m: int):
    half = m // 2
    adj = jnp.where(a >= 0, a + half, a - half)
    return _trunc_div64(adj, jnp.int64(m))
