"""Predicates & comparisons with Spark's 3-valued logic.

Mirrors reference predicates (sql-plugin GpuOverrides rules for And/Or/Not,
EqualTo, comparisons) — validity lanes implement SQL ternary logic directly:
  AND: F && anything = F ;  T && NULL = NULL
  OR : T || anything = T ;  F || NULL = NULL
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BOOLEAN, DataType, numeric_promote
from .core import Expression, Literal
from ..ops.strings import string_compare_cols, string_equal


def _float_compare_sign(l, r):
    """Spark/Java float ordering as a sign lane: NaN equals NaN and sorts
    greater than any other value (Double.compare semantics)."""
    ln = jnp.isnan(l)
    rn = jnp.isnan(r)
    lt = (~ln & rn) | (~ln & ~rn & (l < r))
    gt = (ln & ~rn) | (~ln & ~rn & (l > r))
    return jnp.where(lt, jnp.int32(-1), jnp.where(gt, jnp.int32(1), jnp.int32(0)))


class BinaryComparison(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])

    @property
    def data_type(self) -> DataType:
        return BOOLEAN

    def columnar_eval(self, batch) -> Column:
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        valid = l.validity & r.validity
        if isinstance(l, StringColumn) or isinstance(r, StringColumn):
            cmp = string_compare_cols(l, r)
            data = self._cmp_from_sign(cmp)
        else:
            lt, rt = l.dtype, r.dtype
            common = lt if lt == rt else numeric_promote(lt, rt)
            ld = l.data.astype(common.jnp_dtype)
            rd = r.data.astype(common.jnp_dtype)
            if jnp.issubdtype(ld.dtype, jnp.floating):
                # Spark's float total order: NaN == NaN, NaN > everything
                data = self._cmp_from_sign(_float_compare_sign(ld, rd))
            else:
                data = self._op(ld, rd)
        data = data & valid
        return Column(data, valid, BOOLEAN)

    def _op(self, l, r):
        raise NotImplementedError

    def _cmp_from_sign(self, cmp):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.children[0]!r} {self.symbol} {self.children[1]!r})"


class EqualTo(BinaryComparison):
    symbol = "="

    def _op(self, l, r):
        return l == r

    def _cmp_from_sign(self, cmp):
        return cmp == 0


class LessThan(BinaryComparison):
    symbol = "<"

    def _op(self, l, r):
        return l < r

    def _cmp_from_sign(self, cmp):
        return cmp < 0


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def _op(self, l, r):
        return l <= r

    def _cmp_from_sign(self, cmp):
        return cmp <= 0


class GreaterThan(BinaryComparison):
    symbol = ">"

    def _op(self, l, r):
        return l > r

    def _cmp_from_sign(self, cmp):
        return cmp > 0


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def _op(self, l, r):
        return l >= r

    def _cmp_from_sign(self, cmp):
        return cmp >= 0


class EqualNullSafe(BinaryComparison):
    """<=> : null-safe equality, never returns null."""
    symbol = "<=>"

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        if isinstance(l, StringColumn) or isinstance(r, StringColumn):
            eq_vals = string_equal(l, r).data
        elif jnp.issubdtype(l.data.dtype, jnp.floating) or \
                jnp.issubdtype(r.data.dtype, jnp.floating):
            eq_vals = _float_compare_sign(l.data.astype(jnp.float64),
                                          r.data.astype(jnp.float64)) == 0
        else:
            eq_vals = l.data == r.data
        both_valid = l.validity & r.validity
        both_null = ~l.validity & ~r.validity
        data = (both_valid & eq_vals) | both_null
        cap = data.shape[0]
        return Column(data, jnp.ones((cap,), jnp.bool_), BOOLEAN)


class And(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return And(children[0], children[1])

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        lv, rv = l.validity, r.validity
        ld = l.data & lv  # null treated as "unknown", data lane meaningless
        rd = r.data & rv
        false_l = lv & ~l.data
        false_r = rv & ~r.data
        data = ld & rd
        valid = (lv & rv) | false_l | false_r
        return Column(data & valid, valid, BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} AND {self.children[1]!r})"


class Or(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return Or(children[0], children[1])

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        lv, rv = l.validity, r.validity
        true_l = lv & l.data
        true_r = rv & r.data
        data = true_l | true_r
        valid = (lv & rv) | true_l | true_r
        return Column(data & valid, valid, BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} OR {self.children[1]!r})"


class Not(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Not(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        return Column(~c.data & c.validity, c.validity, BOOLEAN)

    def __repr__(self):
        return f"NOT {self.children[0]!r}"


class IsNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return IsNull(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        cap = c.capacity
        return Column(~c.validity, jnp.ones((cap,), jnp.bool_), BOOLEAN)


class IsNotNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return IsNotNull(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        cap = c.capacity
        return Column(c.validity, jnp.ones((cap,), jnp.bool_), BOOLEAN)


class In(Expression):
    """Spark IN over a literal list: null list elements give NULL when no
    positive match exists (3-valued membership)."""

    def __init__(self, value: Expression, items):
        self.children = (value,)
        self.items = tuple(items)

    def with_children(self, children):
        return In(children[0], self.items)

    @property
    def data_type(self):
        return BOOLEAN

    def _semantic_args(self):
        return (self.items,)

    def columnar_eval(self, batch):
        from .core import lit
        c = self.children[0]
        has_null = any(i is None for i in self.items)
        hit = None
        for item in self.items:
            if item is None:
                continue
            e = EqualTo(c, lit(item)).columnar_eval(batch)
            hit = e.data if hit is None else (hit | e.data)
        v = c.columnar_eval(batch)
        cap = v.capacity
        if hit is None:
            hit = jnp.zeros((cap,), jnp.bool_)
        valid = v.validity & (hit | ~jnp.asarray(has_null))
        return Column(hit & valid, valid, BOOLEAN)
