"""Predicates & comparisons with Spark's 3-valued logic.

Mirrors reference predicates (sql-plugin GpuOverrides rules for And/Or/Not,
EqualTo, comparisons) — validity lanes implement SQL ternary logic directly:
  AND: F && anything = F ;  T && NULL = NULL
  OR : T || anything = T ;  F || NULL = NULL
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BOOLEAN, DataType, numeric_promote
from .core import Expression, Literal
from ..ops.strings import string_compare_cols, string_equal


def _float_compare_sign(l, r):
    """Spark/Java float ordering as a sign lane: NaN equals NaN and sorts
    greater than any other value (Double.compare semantics)."""
    ln = jnp.isnan(l)
    rn = jnp.isnan(r)
    lt = (~ln & rn) | (~ln & ~rn & (l < r))
    gt = (ln & ~rn) | (~ln & ~rn & (l > r))
    return jnp.where(lt, jnp.int32(-1), jnp.where(gt, jnp.int32(1), jnp.int32(0)))


class BinaryComparison(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])

    @property
    def data_type(self) -> DataType:
        return BOOLEAN

    def columnar_eval(self, batch) -> Column:
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        return self._compare_cols(l, r)

    def _compare_cols(self, l: Column, r: Column) -> Column:
        from ..columnar.encoded import DictionaryColumn
        if isinstance(l, DictionaryColumn) or isinstance(r, DictionaryColumn):
            # only EqualTo-vs-literal has a code-space lane (handled in
            # EqualTo.columnar_eval before evaluation reaches here);
            # crash loudly instead of misreading the encoded layout —
            # the exec-layer eligibility walk (encoded_safe_predicate)
            # materializes upstream so this is unreachable in planned
            # queries
            raise TypeError(
                "dictionary-encoded column reached a non-code-space "
                "comparison — materialize first (columnar/encoded.py)")
        valid = l.validity & r.validity
        if isinstance(l, StringColumn) or isinstance(r, StringColumn):
            cmp = string_compare_cols(l, r)
            data = self._cmp_from_sign(cmp)
        else:
            lt, rt = l.dtype, r.dtype
            common = lt if lt == rt else numeric_promote(lt, rt)
            ld = l.data.astype(common.jnp_dtype)
            rd = r.data.astype(common.jnp_dtype)
            if jnp.issubdtype(ld.dtype, jnp.floating):
                # Spark's float total order: NaN == NaN, NaN > everything
                data = self._cmp_from_sign(_float_compare_sign(ld, rd))
            else:
                data = self._op(ld, rd)
        data = data & valid
        return Column(data, valid, BOOLEAN)

    def _op(self, l, r):
        raise NotImplementedError

    def _cmp_from_sign(self, cmp):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.children[0]!r} {self.symbol} {self.children[1]!r})"


class EqualTo(BinaryComparison):
    symbol = "="

    def columnar_eval(self, batch) -> Column:
        """Code-space lane (ISSUE 18): `encoded_col == literal` compares
        i32 dictionary codes on device — the literal is matched against
        the dictionary ONCE (dict_capacity byte compares) and the row
        answer is a code-indexed gather of the per-entry hit lane, never
        a row-level decode. Everything else falls to the generic path."""
        from ..columnar.encoded import DictionaryColumn, encoded_equal_literal
        lit_l = isinstance(self.left, Literal)
        lit_r = isinstance(self.right, Literal)
        if lit_r and not lit_l:
            l = self.left.columnar_eval(batch)
            if isinstance(l, DictionaryColumn):
                return encoded_equal_literal(l, self.right.value)
            return self._compare_cols(l, self.right.columnar_eval(batch))
        if lit_l and not lit_r:
            r = self.right.columnar_eval(batch)
            if isinstance(r, DictionaryColumn):
                return encoded_equal_literal(r, self.left.value)
            return self._compare_cols(self.left.columnar_eval(batch), r)
        return super().columnar_eval(batch)

    def _op(self, l, r):
        return l == r

    def _cmp_from_sign(self, cmp):
        return cmp == 0


class LessThan(BinaryComparison):
    symbol = "<"

    def _op(self, l, r):
        return l < r

    def _cmp_from_sign(self, cmp):
        return cmp < 0


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def _op(self, l, r):
        return l <= r

    def _cmp_from_sign(self, cmp):
        return cmp <= 0


class GreaterThan(BinaryComparison):
    symbol = ">"

    def _op(self, l, r):
        return l > r

    def _cmp_from_sign(self, cmp):
        return cmp > 0


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def _op(self, l, r):
        return l >= r

    def _cmp_from_sign(self, cmp):
        return cmp >= 0


class EqualNullSafe(BinaryComparison):
    """<=> : null-safe equality, never returns null."""
    symbol = "<=>"

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        l = self.left.columnar_eval(batch)
        r = self.right.columnar_eval(batch)
        if isinstance(l, StringColumn) or isinstance(r, StringColumn):
            eq_vals = string_equal(l, r).data
        elif jnp.issubdtype(l.data.dtype, jnp.floating) or \
                jnp.issubdtype(r.data.dtype, jnp.floating):
            eq_vals = _float_compare_sign(l.data.astype(jnp.float64),
                                          r.data.astype(jnp.float64)) == 0
        else:
            eq_vals = l.data == r.data
        both_valid = l.validity & r.validity
        both_null = ~l.validity & ~r.validity
        data = (both_valid & eq_vals) | both_null
        cap = data.shape[0]
        return Column(data, jnp.ones((cap,), jnp.bool_), BOOLEAN)


class And(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return And(children[0], children[1])

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        lv, rv = l.validity, r.validity
        ld = l.data & lv  # null treated as "unknown", data lane meaningless
        rd = r.data & rv
        false_l = lv & ~l.data
        false_r = rv & ~r.data
        data = ld & rd
        valid = (lv & rv) | false_l | false_r
        return Column(data & valid, valid, BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} AND {self.children[1]!r})"


class Or(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, children):
        return Or(children[0], children[1])

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        l = self.children[0].columnar_eval(batch)
        r = self.children[1].columnar_eval(batch)
        lv, rv = l.validity, r.validity
        true_l = lv & l.data
        true_r = rv & r.data
        data = true_l | true_r
        valid = (lv & rv) | true_l | true_r
        return Column(data & valid, valid, BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} OR {self.children[1]!r})"


class Not(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Not(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        return Column(~c.data & c.validity, c.validity, BOOLEAN)

    def __repr__(self):
        return f"NOT {self.children[0]!r}"


class IsNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return IsNull(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        cap = c.capacity
        return Column(~c.validity, jnp.ones((cap,), jnp.bool_), BOOLEAN)


class IsNotNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return IsNotNull(children[0])

    @property
    def data_type(self):
        return BOOLEAN

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        c = self.children[0].columnar_eval(batch)
        cap = c.capacity
        return Column(c.validity, jnp.ones((cap,), jnp.bool_), BOOLEAN)


class In(Expression):
    """Spark IN over a literal list: null list elements give NULL when no
    positive match exists (3-valued membership)."""

    def __init__(self, value: Expression, items):
        self.children = (value,)
        self.items = tuple(items)

    def with_children(self, children):
        return In(children[0], self.items)

    @property
    def data_type(self):
        return BOOLEAN

    def _semantic_args(self):
        return (self.items,)

    def columnar_eval(self, batch):
        from .core import lit
        c = self.children[0]
        has_null = any(i is None for i in self.items)
        hit = None
        for item in self.items:
            if item is None:
                continue
            e = EqualTo(c, lit(item)).columnar_eval(batch)
            hit = e.data if hit is None else (hit | e.data)
        v = c.columnar_eval(batch)
        cap = v.capacity
        if hit is None:
            hit = jnp.zeros((cap,), jnp.bool_)
        valid = v.validity & (hit | ~jnp.asarray(has_null))
        return Column(hit & valid, valid, BOOLEAN)


# -- encoded-execution eligibility walk (ISSUE 18) --------------------------
# Structural answer to "can this expression evaluate correctly when its
# string-typed inputs arrive as DictionaryColumns?". The positions with a
# code-space lane: equality/IN against a literal, null checks, bare
# pass-through references, and And/Or/Not compositions of those. Everything
# else must see full values, so the exec layer materializes its input
# (columnar/encoded.materialize_batch) before evaluating. The walk is
# intentionally conservative: an unrecognized node is safe only when no
# string/binary-typed reference occurs anywhere below it.

def _string_free_subtree(e: Expression) -> bool:
    """True when no string/binary-typed column reference occurs in the
    subtree — such an expression never receives an encoded column, so it
    is trivially safe. Unresolved attributes (no type available) count as
    potentially-string: conservative False."""
    from ..types import BinaryType, StringType
    from .core import BoundReference, UnresolvedAttribute
    if isinstance(e, UnresolvedAttribute):
        return False
    if isinstance(e, BoundReference):
        return not isinstance(e.data_type, (StringType, BinaryType))
    return all(_string_free_subtree(c) for c in e.children)


def _encoded_operand(e: Expression) -> bool:
    """A position whose evaluation tolerates an encoded column directly
    (bare reference) or never produces one (string-free subtree)."""
    from .core import Alias, BoundReference, UnresolvedAttribute
    if isinstance(e, Alias):
        return _encoded_operand(e.children[0])
    if isinstance(e, (BoundReference, UnresolvedAttribute)):
        return True
    return _string_free_subtree(e)


def encoded_safe_predicate(e: Expression) -> bool:
    """True when the predicate evaluates correctly over a batch whose
    string columns are dictionary-encoded (code-space equality/IN/null
    checks and their boolean compositions)."""
    if isinstance(e, (And, Or)):
        return all(encoded_safe_predicate(c) for c in e.children)
    if isinstance(e, Not):
        return encoded_safe_predicate(e.children[0])
    if isinstance(e, (IsNull, IsNotNull)):
        # validity-lane-only: works on any column class
        return True
    if isinstance(e, EqualTo):
        l, r = e.children
        if isinstance(r, Literal):
            return _encoded_operand(l)
        if isinstance(l, Literal):
            return _encoded_operand(r)
        return _string_free_subtree(e)
    if isinstance(e, In):
        return _encoded_operand(e.children[0])
    return _string_free_subtree(e)


def encoded_safe_projection(e: Expression) -> bool:
    """True when a projection expression evaluates correctly over encoded
    input: bare (aliased) pass-through references carry the encoded
    column forward untouched; predicates reduce to the walk above;
    anything else is safe only when string-reference-free."""
    from .core import Alias
    if isinstance(e, Alias):
        return encoded_safe_projection(e.children[0])
    return _encoded_operand(e) or encoded_safe_predicate(e)
