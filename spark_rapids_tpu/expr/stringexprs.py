"""String expressions (reference stringFunctions.scala subset, growing)."""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import BOOLEAN, INT, STRING, DataType
from .core import Expression, Literal
from ..ops import strings as S


class _UnaryString(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return type(self)(children[0])


class Length(_UnaryString):
    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return S.str_length_chars(self.children[0].columnar_eval(batch))


class Upper(_UnaryString):
    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_upper_ascii(self.children[0].columnar_eval(batch))


class Lower(_UnaryString):
    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_lower_ascii(self.children[0].columnar_eval(batch))


class Substring(Expression):
    """Spark substring(str, pos, len): 1-based, negative pos from end."""

    def __init__(self, child: Expression, pos: int, length: int | None = None):
        self.children = (child,)
        self.pos = pos
        self.length = length

    def with_children(self, children):
        return Substring(children[0], self.pos, self.length)

    def _semantic_args(self):
        return (self.pos, self.length)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.substring(self.children[0].columnar_eval(batch),
                           self.pos, self.length)


class _LiteralNeedle(Expression):
    def __init__(self, child: Expression, needle):
        self.children = (child,)
        if isinstance(needle, Literal):
            needle = needle.value
        self.needle = needle.encode("utf-8") if isinstance(needle, str) else bytes(needle)

    def with_children(self, children):
        return type(self)(children[0], self.needle)

    def _semantic_args(self):
        return (self.needle,)

    @property
    def data_type(self):
        return BOOLEAN


class StartsWith(_LiteralNeedle):
    def columnar_eval(self, batch):
        return S.str_starts_with(self.children[0].columnar_eval(batch), self.needle)


class EndsWith(_LiteralNeedle):
    def columnar_eval(self, batch):
        return S.str_ends_with(self.children[0].columnar_eval(batch), self.needle)


class Contains(_LiteralNeedle):
    def columnar_eval(self, batch):
        return S.str_contains(self.children[0].columnar_eval(batch), self.needle)
