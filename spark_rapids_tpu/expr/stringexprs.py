"""String expressions (reference stringFunctions.scala subset, growing)."""

from __future__ import annotations

import jax.numpy as jnp

import re as _re_mod

from ..columnar.column import Column, StringColumn
from ..types import BOOLEAN, INT, STRING, DataType
from .core import Expression, Literal
from ..ops import strings as S

# a split/replace pattern with none of these is a literal string
_REGEX_META = _re_mod.compile(r"[\\.\[\]{}()*+?^$|]")


class _UnaryString(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return type(self)(children[0])


class Length(_UnaryString):
    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return S.str_length_chars(self.children[0].columnar_eval(batch))


class Upper(_UnaryString):
    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_upper_ascii(self.children[0].columnar_eval(batch))


class Lower(_UnaryString):
    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_lower_ascii(self.children[0].columnar_eval(batch))


class Substring(Expression):
    """Spark substring(str, pos, len): 1-based, negative pos from end."""

    def __init__(self, child: Expression, pos: int, length: int | None = None):
        self.children = (child,)
        self.pos = pos
        self.length = length

    def with_children(self, children):
        return Substring(children[0], self.pos, self.length)

    def _semantic_args(self):
        return (self.pos, self.length)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.substring(self.children[0].columnar_eval(batch),
                           self.pos, self.length)


class _LiteralNeedle(Expression):
    def __init__(self, child: Expression, needle):
        self.children = (child,)
        if isinstance(needle, Literal):
            needle = needle.value
        self.needle = needle.encode("utf-8") if isinstance(needle, str) else bytes(needle)

    def with_children(self, children):
        return type(self)(children[0], self.needle)

    def _semantic_args(self):
        return (self.needle,)

    @property
    def data_type(self):
        return BOOLEAN


class StartsWith(_LiteralNeedle):
    def columnar_eval(self, batch):
        return S.str_starts_with(self.children[0].columnar_eval(batch), self.needle)


class EndsWith(_LiteralNeedle):
    def columnar_eval(self, batch):
        return S.str_ends_with(self.children[0].columnar_eval(batch), self.needle)


class Contains(_LiteralNeedle):
    def columnar_eval(self, batch):
        return S.str_contains(self.children[0].columnar_eval(batch), self.needle)


def _as_bytes(v) -> bytes:
    if isinstance(v, Literal):
        v = v.value
    return v.encode("utf-8") if isinstance(v, str) else bytes(v)


class StringTrim(_UnaryString):
    """trim/ltrim/rtrim with an optional literal trim set (reference
    GpuStringTrim/TrimLeft/TrimRight, stringFunctions.scala)."""

    SIDE = "both"

    def __init__(self, child: Expression, trim_str=None):
        super().__init__(child)
        self.trim_str = None if trim_str is None else _as_bytes(trim_str)

    def with_children(self, children):
        return type(self)(children[0], self.trim_str)

    def _semantic_args(self):
        return (self.SIDE, self.trim_str)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        chars = self.trim_str if self.trim_str is not None else b" "
        return S.str_trim(self.children[0].columnar_eval(batch),
                          self.SIDE, chars)


class StringTrimLeft(StringTrim):
    SIDE = "left"


class StringTrimRight(StringTrim):
    SIDE = "right"


class _PadBase(Expression):
    SIDE = "left"

    def __init__(self, child: Expression, length, pad=" "):
        self.children = (child,)
        self.length = length.value if isinstance(length, Literal) else length
        self.pad = _as_bytes(pad)

    def with_children(self, children):
        return type(self)(children[0], self.length, self.pad)

    def _semantic_args(self):
        return (self.SIDE, self.length, self.pad)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_pad(self.children[0].columnar_eval(batch),
                         self.length, self.pad, self.SIDE)


class StringLPad(_PadBase):
    SIDE = "left"


class StringRPad(_PadBase):
    SIDE = "right"


class StringRepeat(Expression):
    def __init__(self, child: Expression, n):
        self.children = (child,)
        self.n = n.value if isinstance(n, Literal) else n

    def with_children(self, children):
        return StringRepeat(children[0], self.n)

    def _semantic_args(self):
        return (self.n,)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_repeat(self.children[0].columnar_eval(batch), self.n)


class Reverse(_UnaryString):
    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_reverse(self.children[0].columnar_eval(batch))


class InitCap(_UnaryString):
    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_initcap(self.children[0].columnar_eval(batch))


class StringLocate(Expression):
    """locate(substr, str, start) / instr (reference GpuStringLocate)."""

    def __init__(self, substr, child: Expression, start=1):
        self.children = (child,)
        self.needle = _as_bytes(substr)
        self.start = start.value if isinstance(start, Literal) else start

    def with_children(self, children):
        return StringLocate(self.needle, children[0], self.start)

    def _semantic_args(self):
        return (self.needle, self.start)

    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return S.str_locate(self.children[0].columnar_eval(batch),
                            self.needle, self.start)


class StringReplace(Expression):
    def __init__(self, child: Expression, search, replacement):
        self.children = (child,)
        self.search = _as_bytes(search)
        self.replacement = _as_bytes(replacement)

    def with_children(self, children):
        return StringReplace(children[0], self.search, self.replacement)

    def _semantic_args(self):
        return (self.search, self.replacement)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_replace(self.children[0].columnar_eval(batch),
                             self.search, self.replacement)


class Concat(Expression):
    """concat(...): null-intolerant string concatenation (reference
    GpuConcat, collectionOperations.scala for the string overload).

    One k-ary kernel pass (the concat_ws segment-table machinery with an
    empty separator), not a pairwise fold — a fold re-copies earlier
    columns' bytes O(k) times. Rows with any null child are invalid, so
    the skip-null byte layout under them is irrelevant."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return Concat(*children)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        import jax.numpy as jnp
        cols = [c.columnar_eval(batch) for c in self.children]
        if len(cols) == 1:
            return cols[0]
        if len(cols) == 2:
            return S.str_concat_pair(cols[0], cols[1])
        joined = S.str_concat_ws(b"", cols)
        valid = cols[0].validity
        for c in cols[1:]:
            valid = valid & c.validity
        return StringColumn(joined.data, joined.offsets, valid,
                            cols[0].dtype)


class ConcatWs(Expression):
    """concat_ws(sep, ...): skips nulls, never returns null (reference
    GpuConcatWs)."""

    def __init__(self, sep, *children: Expression):
        self.children = tuple(children)
        self.sep = _as_bytes(sep)

    def with_children(self, children):
        return ConcatWs(self.sep, *children)

    def _semantic_args(self):
        return (self.sep,)

    @property
    def data_type(self):
        return STRING

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        cols = [c.columnar_eval(batch) for c in self.children]
        return S.str_concat_ws(self.sep, cols)


class StringTranslate(Expression):
    def __init__(self, child: Expression, from_str, to_str):
        self.children = (child,)
        self.from_str = _as_bytes(from_str)
        self.to_str = _as_bytes(to_str)

    def with_children(self, children):
        return StringTranslate(children[0], self.from_str, self.to_str)

    def _semantic_args(self):
        return (self.from_str, self.to_str)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_translate(self.children[0].columnar_eval(batch),
                               self.from_str, self.to_str)


class Ascii(_UnaryString):
    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return S.str_ascii(self.children[0].columnar_eval(batch))


class Chr(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return Chr(children[0])

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.str_chr(self.children[0].columnar_eval(batch))


class OctetLength(_UnaryString):
    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        return S.str_length_bytes(self.children[0].columnar_eval(batch))


class BitLength(_UnaryString):
    @property
    def data_type(self):
        return INT

    def columnar_eval(self, batch):
        c = S.str_length_bytes(self.children[0].columnar_eval(batch))
        return Column(c.data * 8, c.validity, INT)


class Left(Expression):
    def __init__(self, child: Expression, n):
        self.children = (child,)
        self.n = n.value if isinstance(n, Literal) else n

    def with_children(self, children):
        return type(self)(children[0], self.n)

    def _semantic_args(self):
        return (self.n,)

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        return S.substring(self.children[0].columnar_eval(batch), 1,
                           max(self.n, 0))


class Right(Left):
    def columnar_eval(self, batch):
        if self.n <= 0:
            return S.substring(self.children[0].columnar_eval(batch), 1, 0)
        return S.substring(self.children[0].columnar_eval(batch), -self.n,
                           None)


class RLike(Expression):
    """rlike/regexp (reference GpuRLike + RegexParser.scala transpiler):
    the literal pattern compiles lazily to a device Glushkov program;
    unsupported constructs raise RegexUnsupported, which the rule table's
    tag_fn turns into an off-TPU tag at PLAN time (constructing the
    expression itself never throws, matching Spark's analyze-then-tag
    order)."""

    def __init__(self, child: Expression, pattern):
        self.children = (child,)
        self.pattern = pattern.value if isinstance(pattern, Literal) \
            else pattern
        self._program = None

    @property
    def program(self):
        if self._program is None:
            from ..regex import RegexUnsupported, compile_regex
            if not isinstance(self.pattern, str):
                raise RegexUnsupported(
                    "only literal regex patterns are supported "
                    f"(got {type(self.pattern).__name__})")
            self._program = compile_regex(self.pattern)
        return self._program

    def with_children(self, children):
        return RLike(children[0], self.pattern)

    def _semantic_args(self):
        return (self.pattern,)

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        from ..regex import regex_find
        return regex_find(self.children[0].columnar_eval(batch),
                          self.program)


class Like(Expression):
    """SQL LIKE ... ESCAPE (reference GpuLike): translated lazily to an
    anchored device regex program (tagging mirrors RLike)."""

    def __init__(self, child: Expression, pattern, escape_char="\\"):
        self.children = (child,)
        self.pattern = pattern.value if isinstance(pattern, Literal) \
            else pattern
        self.escape_char = escape_char
        self._program = None

    @property
    def program(self):
        if self._program is None:
            from ..regex import RegexUnsupported, like_to_program
            if not isinstance(self.pattern, str):
                raise RegexUnsupported(
                    "only literal LIKE patterns are supported "
                    f"(got {type(self.pattern).__name__})")
            self._program = like_to_program(self.pattern, self.escape_char)
        return self._program

    def with_children(self, children):
        return Like(children[0], self.pattern, self.escape_char)

    def _semantic_args(self):
        return (self.pattern, self.escape_char)

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        from ..regex import regex_find
        return regex_find(self.children[0].columnar_eval(batch),
                          self.program)


# ---------------------------------------------------------------------------
# host-tier string long tail (reference stringFunctions.scala families the
# engine has no device kernel for yet; they run through the CPU fallback
# transitions, exec/fallback.py, and are tagged host-tier at plan time)
# ---------------------------------------------------------------------------

class _HostString(Expression):
    """Base for host-tier string expressions: scalar semantics in
    host_eval_row; no columnar kernel (the rule tags them off-device).
    Subclasses that grow a device kernel override `device_supported`,
    which takes precedence over this marker."""

    HOST_ONLY = True

    def columnar_eval(self, batch):
        raise NotImplementedError(
            f"{type(self).__name__} runs on the host tier (CPU fallback)")

    def with_children(self, cs):
        raise NotImplementedError  # overridden per class


class StringSplit(_HostString):
    """split(str, regex[, limit]) -> array<string> (reference
    GpuStringSplit; Java split semantics incl. trailing-empty removal
    when limit == 0 and the literal fast path)."""

    def __init__(self, child: Expression, pattern, limit=-1):
        self.children = (child,)
        self.pattern = pattern.value if isinstance(pattern, Literal) \
            else pattern
        self.limit = limit.value if isinstance(limit, Literal) else limit

    def with_children(self, cs):
        return StringSplit(cs[0], self.pattern, self.limit)

    def _semantic_args(self):
        return (self.pattern, self.limit)

    @property
    def data_type(self):
        from ..types import ArrayType
        return ArrayType(STRING)

    def host_eval_row(self, s):
        import re as _re
        if s is None or not isinstance(self.pattern, str):
            return None
        limit = self.limit if isinstance(self.limit, int) else -1
        if limit == 1:
            return [s]  # Java: at most 1 element = no split at all
        parts = _re.split(self.pattern, s, maxsplit=limit - 1
                          if limit > 0 else 0)
        # Java split: ONLY limit == 0 strips trailing empties; negative
        # limits keep them (Spark's default limit is -1)
        if limit == 0:
            while parts and parts[-1] == "":
                parts.pop()
        return parts

    @property
    def device_supported(self) -> bool:
        """Metacharacter-free literal patterns take the device kernel
        (the reference's GpuStringSplit literal fast path); regex
        patterns stay on the host tier until the Glushkov matcher grows
        split support."""
        return (isinstance(self.pattern, str) and len(self.pattern) > 0
                and not _REGEX_META.search(self.pattern)
                and isinstance(self.limit, int))

    def columnar_eval(self, batch):
        from ..ops.string_split import split_literal
        if not self.device_supported:
            raise NotImplementedError(
                "regex split runs on the host tier (CPU fallback)")
        c = self.children[0].columnar_eval(batch)
        return split_literal(c, self.pattern.encode("utf-8"), self.limit)


class SubstringIndex(_HostString):
    """substring_index(str, delim, count) (reference
    GpuSubstringIndex)."""

    def __init__(self, child: Expression, delim, count):
        self.children = (child,)
        self.delim = delim.value if isinstance(delim, Literal) else delim
        self.count = count.value if isinstance(count, Literal) else count

    def with_children(self, cs):
        return SubstringIndex(cs[0], self.delim, self.count)

    def _semantic_args(self):
        return (self.delim, self.count)

    @property
    def data_type(self):
        return STRING

    def host_eval_row(self, s):
        if s is None:
            return None
        d, c = self.delim, self.count
        if not d or c == 0:
            return ""
        if c > 0:
            parts = s.split(d)
            return d.join(parts[:c]) if len(parts) > c else s
        parts = s.split(d)
        return d.join(parts[c:]) if len(parts) > -c else s

    @property
    def device_supported(self) -> bool:
        return isinstance(self.delim, str) and isinstance(self.count, int)

    def columnar_eval(self, batch):
        from ..ops.string_split import substring_index
        if not self.device_supported:
            raise NotImplementedError(
                "non-literal substring_index runs on the host tier")
        c = self.children[0].columnar_eval(batch)
        return substring_index(c, self.delim.encode("utf-8"), self.count)


class FindInSet(_HostString):
    """find_in_set(str, comma_list) -> 1-based index or 0."""

    def __init__(self, needle: Expression, set_col: Expression):
        self.children = (needle, set_col)

    def with_children(self, cs):
        return FindInSet(cs[0], cs[1])

    @property
    def data_type(self):
        from ..types import INT
        return INT

    def host_eval_row(self, needle, s):
        if needle is None or s is None:
            return None
        if "," in needle:
            return 0
        items = s.split(",")
        return items.index(needle) + 1 if needle in items else 0

    def columnar_eval(self, batch):
        from ..ops.string_split import find_in_set
        n = self.children[0].columnar_eval(batch)
        s = self.children[1].columnar_eval(batch)
        return find_in_set(n, s)


class RegExpExtract(_HostString):
    """regexp_extract(str, pattern, idx) (reference GpuRegExpExtract over
    the transpiled device regex; host tier here — Python re)."""

    def __init__(self, child: Expression, pattern, idx=1):
        self.children = (child,)
        self.pattern = pattern.value if isinstance(pattern, Literal) \
            else pattern
        self.idx = idx.value if isinstance(idx, Literal) else idx

    def with_children(self, cs):
        return RegExpExtract(cs[0], self.pattern, self.idx)

    def _semantic_args(self):
        return (self.pattern, self.idx)

    @property
    def data_type(self):
        return STRING

    def host_eval_row(self, s):
        import re as _re
        if s is None or not isinstance(self.pattern, str):
            return None
        m = _re.search(self.pattern, s)
        if m is None:
            return ""
        try:
            g = m.group(self.idx)
        except (IndexError, _re.error):
            # Spark raises for an out-of-range group index — a typo must
            # fail the query, not silently yield an all-null column
            raise ValueError(
                f"regexp_extract: group {self.idx} out of range for "
                f"pattern {self.pattern!r} "
                f"({_re.compile(self.pattern).groups} groups)")
        return g if g is not None else ""

    def _device_plan(self):
        # the (pattern, idx) pair is constant: compile and probe ONCE
        got = getattr(self, "_span_plan", False)
        if got is not False:
            return got
        from ..regex import RegexUnsupported
        from ..regex.spans import compile_spans, regexp_extract_device
        plan = None
        if isinstance(self.pattern, str) and isinstance(self.idx, int):
            try:
                p = compile_spans(self.pattern)
                if 0 <= self.idx <= p.n_groups:
                    # probe group-window support on an empty column
                    from ..columnar.column import StringColumn
                    regexp_extract_device(StringColumn.from_pylist([]), p,
                                          self.idx)
                    plan = p
            except RegexUnsupported:
                plan = None
        self._span_plan = plan
        return plan

    @property
    def device_supported(self) -> bool:
        return self._device_plan() is not None

    def columnar_eval(self, batch):
        from ..regex.spans import regexp_extract_device
        plan = self._device_plan()
        if plan is None:
            raise NotImplementedError(
                "regexp_extract pattern runs on the host tier")
        c = self.children[0].columnar_eval(batch)
        return regexp_extract_device(c, plan, self.idx)


class RegExpReplace(_HostString):
    """regexp_replace(str, pattern, replacement) (reference
    GpuRegExpReplace; host tier — Python re with Java-style $n rewritten
    to \\n backrefs)."""

    def __init__(self, child: Expression, pattern, replacement):
        self.children = (child,)
        self.pattern = pattern.value if isinstance(pattern, Literal) \
            else pattern
        self.replacement = replacement.value \
            if isinstance(replacement, Literal) else replacement

    def with_children(self, cs):
        return RegExpReplace(cs[0], self.pattern, self.replacement)

    def _semantic_args(self):
        return (self.pattern, self.replacement)

    @property
    def data_type(self):
        return STRING

    def host_eval_row(self, s):
        import re as _re
        if s is None or not isinstance(self.pattern, str):
            return None
        # Java replacement dialect: $1 group refs, \$ literal dollar.
        # \g<1> (not \1) so a digit FOLLOWING the reference stays literal
        # ('<$10>' with one group = group 1 then '0', like Java)
        rep = _re.sub(r"(?<!\\)\$(\d)", r"\\g<\1>", self.replacement)
        rep = rep.replace(r"\$", "$")
        return _re.sub(self.pattern, rep, s)

    def _device_plan(self):
        # the (pattern, replacement) pair is constant: compile ONCE
        got = getattr(self, "_span_plan", False)
        if got is not False:
            return got
        from ..regex import RegexUnsupported
        from ..regex.spans import compile_spans
        plan = None
        if isinstance(self.pattern, str) \
                and isinstance(self.replacement, str) \
                and "$" not in self.replacement \
                and "\\" not in self.replacement:
            try:
                plan = compile_spans(self.pattern)
            except RegexUnsupported:
                plan = None
        self._span_plan = plan
        return plan

    @property
    def device_supported(self) -> bool:
        return self._device_plan() is not None

    def columnar_eval(self, batch):
        from ..regex.spans import regexp_replace_device
        plan = self._device_plan()
        if plan is None:
            raise NotImplementedError(
                "regexp_replace pattern runs on the host tier")
        c = self.children[0].columnar_eval(batch)
        return regexp_replace_device(c, plan,
                                     self.replacement.encode("utf-8"))


class FormatNumber(_HostString):
    """format_number(x, d): thousands separators + d decimals. Device
    digit-emission kernel (ops/cast_strings.format_number_string,
    reference GpuFormatNumber); decimal inputs keep the host tier."""

    def __init__(self, child: Expression, decimals):
        self.children = (child,)
        self.decimals = decimals.value if isinstance(decimals, Literal) \
            else decimals

    def with_children(self, cs):
        return FormatNumber(cs[0], self.decimals)

    def _semantic_args(self):
        return (self.decimals,)

    @property
    def device_supported(self):
        from ..types import DecimalType
        # d <= 18 keeps 10^d in int64 (the device kernel's scaled lane);
        # larger d takes the host tier like decimal inputs
        if not isinstance(self.decimals, int) \
                or not 0 <= self.decimals <= 18:
            return False
        try:
            return not isinstance(self.children[0].data_type, DecimalType)
        except TypeError:
            return False

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        from ..ops.cast_strings import format_number_string
        return format_number_string(self.children[0].columnar_eval(batch),
                                    int(self.decimals))

    def host_eval_row(self, v):
        if v is None or self.decimals is None or self.decimals < 0:
            return None
        return f"{v:,.{int(self.decimals)}f}"


class Levenshtein(_HostString):
    """levenshtein(a, b) edit distance (reference GpuLevenshtein)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def with_children(self, cs):
        return Levenshtein(cs[0], cs[1])

    @property
    def data_type(self):
        from ..types import INT
        return INT

    def host_eval_row(self, a, b):
        if a is None or b is None:
            return None
        if len(a) < len(b):
            a, b = b, a
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[-1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]


class Base64Encode(_HostString):
    """base64(bin) (reference GpuBase64): input str is encoded utf-8."""

    HOST_ONLY = False  # device codec kernels

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return Base64Encode(cs[0])

    @property
    def data_type(self):
        return STRING

    def host_eval_row(self, v):
        import base64 as _b
        if v is None:
            return None
        raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        return _b.b64encode(raw).decode("ascii")

    def columnar_eval(self, batch):
        from ..ops.codecs import base64_encode
        return base64_encode(self.children[0].columnar_eval(batch))


class UnBase64(_HostString):
    """unbase64(str) -> binary (reference GpuUnBase64)."""

    HOST_ONLY = False  # device codec kernels

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return UnBase64(cs[0])

    @property
    def data_type(self):
        from ..types import BINARY
        return BINARY

    def columnar_eval(self, batch):
        from ..ops.codecs import base64_decode
        return base64_decode(self.children[0].columnar_eval(batch))

    def host_eval_row(self, v):
        import base64 as _b
        import binascii
        if v is None:
            return None
        if isinstance(v, (bytes, bytearray)):
            v = bytes(v).decode("ascii", errors="ignore")
        # java.util.Base64 is lenient about missing padding; Python is
        # not — pad up before decoding
        v = v + "=" * (-len(v) % 4)
        try:
            return _b.b64decode(v, validate=False)
        except (ValueError, binascii.Error):
            return None


class Hex(_HostString):
    """hex(long | str): uppercase hex, Spark's minimal-width long form."""

    HOST_ONLY = False  # device codec kernels

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return Hex(cs[0])

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        from ..columnar.column import StringColumn
        from ..ops.codecs import hex_encode, hex_encode_long
        c = self.children[0].columnar_eval(batch)
        if isinstance(c, StringColumn):
            return hex_encode(c)
        return hex_encode_long(c)

    def host_eval_row(self, v):
        if v is None:
            return None
        if isinstance(v, str):
            return v.encode("utf-8").hex().upper()
        if isinstance(v, (bytes, bytearray)):
            return bytes(v).hex().upper()
        return format(v & ((1 << 64) - 1), "X")


class Unhex(_HostString):
    """unhex(str) -> binary; NULL on malformed input (odd-length input
    gets a leading 0, like Spark)."""

    HOST_ONLY = False  # device codec kernels

    def __init__(self, child: Expression):
        self.children = (child,)

    def columnar_eval(self, batch):
        from ..ops.codecs import hex_decode
        return hex_decode(self.children[0].columnar_eval(batch))

    def with_children(self, cs):
        return Unhex(cs[0])

    @property
    def data_type(self):
        from ..types import BINARY
        return BINARY

    def host_eval_row(self, v):
        import re as _re
        if v is None:
            return None
        if isinstance(v, (bytes, bytearray)):
            v = bytes(v).decode("ascii", errors="ignore")
        # Spark rejects ANY non-hex character incl. whitespace (Python's
        # bytes.fromhex would silently skip spaces)
        if not _re.fullmatch(r"[0-9A-Fa-f]*", v):
            return None
        if len(v) % 2:
            v = "0" + v
        try:
            return bytes.fromhex(v)
        except ValueError:
            return None


class Encode(_HostString):
    """encode(str, charset) -> binary."""

    _CHARSETS = ("US-ASCII", "ISO-8859-1", "UTF-8", "UTF-16BE",
                 "UTF-16LE", "UTF-16")

    def __init__(self, child: Expression, charset):
        self.children = (child,)
        self.charset = charset.value if isinstance(charset, Literal) \
            else charset
        # Spark raises for an unknown charset at analysis time — a typo
        # must not silently NULL the whole column
        if isinstance(self.charset, str) \
                and self.charset.upper() not in self._CHARSETS:
            raise ValueError(f"unsupported charset {self.charset!r}")

    def with_children(self, cs):
        return Encode(cs[0], self.charset)

    def _semantic_args(self):
        return (self.charset,)

    @property
    def device_supported(self):
        # byte-map kernels (ops/charsets.py); UTF-16 needs the host's
        # surrogate/BOM state machine
        return isinstance(self.charset, str) and self.charset.upper() in (
            "UTF-8", "US-ASCII", "ISO-8859-1")

    @property
    def data_type(self):
        from ..types import BINARY
        return BINARY

    def columnar_eval(self, batch):
        from ..ops.charsets import encode_single_byte, recast_bytes
        from ..types import BINARY
        c = self.children[0].columnar_eval(batch)
        cs = self.charset.upper()
        if cs == "UTF-8":
            return recast_bytes(c, BINARY)
        return encode_single_byte(c, cs)

    def host_eval_row(self, v):
        if v is None:
            return None
        # Java String.getBytes replaces unmappable chars with '?'
        return v.encode(self.charset.replace("-", "_"), errors="replace")


class Decode(_HostString):
    """decode(bin, charset) -> string."""

    def __init__(self, child: Expression, charset):
        self.children = (child,)
        self.charset = charset.value if isinstance(charset, Literal) \
            else charset
        if isinstance(self.charset, str) \
                and self.charset.upper() not in Encode._CHARSETS:
            raise ValueError(f"unsupported charset {self.charset!r}")

    def with_children(self, cs):
        return Decode(cs[0], self.charset)

    def _semantic_args(self):
        return (self.charset,)

    @property
    def device_supported(self):
        # UTF-8 decode is a passthrough that does NOT substitute U+FFFD
        # for malformed bytes (documented deviation, ops/charsets.py)
        return isinstance(self.charset, str) and self.charset.upper() in (
            "UTF-8", "US-ASCII", "ISO-8859-1")

    @property
    def data_type(self):
        return STRING

    def columnar_eval(self, batch):
        from ..ops.charsets import decode_single_byte, recast_bytes
        c = self.children[0].columnar_eval(batch)
        cs = self.charset.upper()
        if cs == "UTF-8":
            return recast_bytes(c, STRING)
        return decode_single_byte(c, cs)

    def host_eval_row(self, v):
        if v is None:
            return None
        raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        # Java new String(bytes, cs) substitutes U+FFFD for bad bytes
        return raw.decode(self.charset.replace("-", "_"),
                          errors="replace")
