"""Map expressions (reference collectionOperations.scala GpuCreateMap /
GpuGetMapValue / GpuMapKeys / GpuMapValues / GpuElementAt for maps)."""

from __future__ import annotations

from ..columnar.column import MapColumn
from ..types import BOOLEAN, ArrayType, MapType
from .core import Expression, Literal


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...)"""

    def __init__(self, *children: Expression):
        assert children and len(children) % 2 == 0, \
            "map() takes key/value pairs"
        self.children = tuple(children)

    def with_children(self, cs):
        return CreateMap(*cs)

    @property
    def data_type(self):
        return MapType(self.children[0].data_type,
                       self.children[1].data_type)

    @property
    def nullable(self):
        return False

    def columnar_eval(self, batch):
        from ..ops.maps import create_map
        cols = [c.columnar_eval(batch) for c in self.children]
        return create_map(cols[0::2], cols[1::2], batch.num_rows,
                          self.data_type)

    def host_eval_with_row(self, row, eval_fn):
        vals = [eval_fn(c, row) for c in self.children]
        d = {}
        for k, v in zip(vals[0::2], vals[1::2]):
            if k not in d:  # FIRST duplicate wins, matching the device
                d[k] = v
        return d


class GetMapValue(Expression):
    """map[key] / element_at(map, key): NULL when absent (non-ANSI)."""

    def __init__(self, child: Expression, key):
        if isinstance(key, Literal):
            key = key.value
        if isinstance(key, Expression):
            self.children = (child, key)
            self.key = None
        else:
            self.children = (child,)
            self.key = key

    def with_children(self, cs):
        if len(cs) == 1:
            return GetMapValue(cs[0], self.key)
        return GetMapValue(cs[0], cs[1])

    def _semantic_args(self):
        return (self.key,)

    @property
    def data_type(self):
        return self.children[0].data_type.value_type

    def columnar_eval(self, batch):
        from ..ops.maps import map_get
        m = self.children[0].columnar_eval(batch)
        key = self.key if len(self.children) == 1 \
            else self.children[1].columnar_eval(batch)
        out = map_get(m, key)
        return out

    def host_eval_row(self, *vals):
        m = vals[0]
        k = self.key if len(self.children) == 1 else vals[1]
        if m is None or k is None:
            return None
        return m.get(k)


class MapKeys(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, cs):
        return type(self)(cs[0])

    @property
    def data_type(self):
        return ArrayType(self.children[0].data_type.key_type, False)

    def columnar_eval(self, batch):
        from ..ops.maps import map_keys
        return map_keys(self.children[0].columnar_eval(batch))

    def host_eval_row(self, m):
        return None if m is None else list(m.keys())


class MapValues(MapKeys):
    @property
    def data_type(self):
        mt = self.children[0].data_type
        return ArrayType(mt.value_type, mt.value_contains_null)

    def columnar_eval(self, batch):
        from ..ops.maps import map_values
        return map_values(self.children[0].columnar_eval(batch))

    def host_eval_row(self, m):
        return None if m is None else list(m.values())


class MapContainsKey(Expression):
    """map_contains_key(map, key)"""

    def __init__(self, child: Expression, key):
        if isinstance(key, Literal):
            key = key.value
        if isinstance(key, Expression):
            self.children = (child, key)
            self.key = None
        else:
            self.children = (child,)
            self.key = key

    def with_children(self, cs):
        if len(cs) == 1:
            return MapContainsKey(cs[0], self.key)
        return MapContainsKey(cs[0], cs[1])

    def _semantic_args(self):
        return (self.key,)

    @property
    def data_type(self):
        return BOOLEAN

    def columnar_eval(self, batch):
        import jax.numpy as jnp

        from ..columnar.column import Column
        from ..ops.maps import map_contains_key
        m = self.children[0].columnar_eval(batch)
        key = self.key if len(self.children) == 1 \
            else self.children[1].columnar_eval(batch)
        if key is None:  # NULL key literal -> NULL result
            z = jnp.zeros((m.capacity,), jnp.bool_)
            return Column(z, z, BOOLEAN)
        return map_contains_key(m, key)

    def host_eval_row(self, *vals):
        m = vals[0]
        k = self.key if len(self.children) == 1 else vals[1]
        if m is None or k is None:
            return None
        return k in m
