"""Python UDFs — the engine's analog of Spark's Arrow-batched Python UDF
path (reference: rapids accelerates *around* Python UDFs by keeping data
columnar across the worker boundary, GpuPythonUDF/GpuArrowEvalPythonExec;
SURVEY §2.7).

TPU shape: `jax.pure_callback` splices a host round trip INTO the
compiled program — the XLA runtime ships the batch's device buffers to
the host, the Python function runs row-wise over numpy views, and the
result re-enters the program as a device array. That is architecturally
the same thing Spark does with its Arrow socket to a Python worker, with
XLA as the transport. Fixed-width inputs and outputs (plus string
INPUTS, decoded host-side); string outputs would need dynamic byte
buckets and stay unsupported (tagged off)."""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column, StringColumn
from ..types import DataType
from .core import Expression


class PythonUDF(Expression):
    def __init__(self, fn: Callable, return_type: DataType,
                 *children: Expression, name: str = None):
        assert return_type.is_fixed_width, \
            "Python UDFs return fixed-width types (string outputs need " \
            "dynamic byte buckets)"
        self.fn = fn
        self.return_type = return_type
        self.children = tuple(children)
        self.fn_name = name or getattr(fn, "__name__", "udf")

    def with_children(self, children):
        return PythonUDF(self.fn, self.return_type, *children,
                         name=self.fn_name)

    def _semantic_args(self):
        # per-INSTANCE identity: an opaque host function may be
        # non-deterministic, so distinct call sites must never CSE into
        # one evaluation (only the literally-same expression object is)
        return (id(self),)

    @property
    def data_type(self):
        return self.return_type

    @property
    def deterministic(self):
        # opaque host function; _semantic_args is per-instance so the
        # projection CSE cache can never merge distinct call sites
        return False

    def columnar_eval(self, batch) -> Column:
        cap = batch.capacity
        cols = [c.columnar_eval(batch) for c in self.children]
        out_dtype = self.return_type.jnp_dtype

        host_args = []
        specs = []  # decode recipe per child
        for c in cols:
            if isinstance(c, StringColumn):
                host_args += [c.data, c.offsets, c.validity]
                specs.append("str")
            else:
                host_args += [c.data, c.validity]
                specs.append("fixed")

        fn = self.fn

        def host(num_rows, *bufs):
            n = int(num_rows)
            vals_per_child = []
            i = 0
            for spec in specs:
                if spec == "str":
                    data, offsets, validity = bufs[i:i + 3]
                    i += 3
                    vals = [None if not validity[r] else
                            bytes(data[offsets[r]:offsets[r + 1]])
                            .decode("utf-8") for r in range(n)]
                else:
                    data, validity = bufs[i:i + 2]
                    i += 2
                    vals = [data[r].item() if validity[r] else None
                            for r in range(n)]
                vals_per_child.append(vals)
            out = np.zeros(cap, dtype=out_dtype)
            ok = np.zeros(cap, dtype=np.bool_)
            for r in range(n):
                res = fn(*(v[r] for v in vals_per_child))
                if res is not None:
                    out[r] = res
                    ok[r] = True
            return out, ok

        result_shape = (jax.ShapeDtypeStruct((cap,), out_dtype),
                        jax.ShapeDtypeStruct((cap,), np.bool_))
        data, valid = jax.pure_callback(host, result_shape,
                                        batch.num_rows, *host_args)
        return Column(data, valid, self.return_type)

    def __repr__(self):
        return f"udf:{self.fn_name}({', '.join(map(repr, self.children))})"


def udf(fn: Callable = None, *, return_type: DataType = None):
    """Spark's F.udf surface: `udf(lambda x: ..., return_type=LONG)` or
    `@udf(return_type=LONG)`. Returns a builder producing PythonUDF
    expressions over its column arguments."""
    from .core import col, lit

    if return_type is None:
        raise TypeError(
            "udf(...) requires return_type= (a fixed-width DataType); "
            "e.g. F.udf(lambda x: x + 1, return_type=LONG)")

    def wrap(f):
        def build(*args):
            # PySpark surface: a str argument is a COLUMN name
            exprs = [a if isinstance(a, Expression)
                     else col(a) if isinstance(a, str) else lit(a)
                     for a in args]
            return PythonUDF(f, return_type, *exprs)
        build.__name__ = getattr(f, "__name__", "udf")
        return build

    if fn is not None:
        return wrap(fn)
    return wrap
