"""Declarative aggregate functions — the engine's analog of the reference's
GpuAggregateFunction hierarchy (org/apache/spark/sql/rapids/aggregate/
aggregateFunctions.scala): each function declares its input expressions,
update/merge buffer ops (executed by the sort-based group-by kernel,
ops/aggregate.py) and a final `evaluate` over merged buffers.

Spark semantics:
  * sum(int*) -> long, sum(float|double) -> double; all-null group -> null
  * count(x) counts non-null, count(*) counts rows; never null
  * avg -> double; null when count == 0
  * min/max ignore nulls; null for all-null groups
  * stddev/variance via (n, sum, sum_sq) buffers; sample variants NaN at n=1
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..columnar.column import Column, StringColumn
from ..types import (
    BooleanType, DataType, DecimalType, DoubleType, FloatType, IntegralType,
    LongType, StringType,
)
from .core import Expression


class AggregateFunction:
    """Base: subclasses define inputs, buffer ops and final evaluation."""

    #: expressions evaluated against the input batch (pre-projection)
    inputs: Tuple[Expression, ...] = ()
    name = "agg"

    def __init__(self, *inputs: Expression):
        self.inputs = tuple(inputs)

    def _semantic_args(self):
        """Per-class parameters beyond the input expressions (the
        Expression._semantic_args contract): everything that changes
        the aggregate's computation MUST appear here — semantic_key()
        feeds the plan-fingerprint program cache (ISSUE 14), and a
        lossy key hands one aggregate another's compiled programs."""
        return ()

    def semantic_key(self):
        """Value-complete structural identity (the Expression
        semantic_key contract, extended to aggregate functions)."""
        return (type(self).__name__, self._semantic_args(),
                tuple(e.semantic_key() for e in self.inputs))

    def result_type_from_buffer(self, buffer_types):
        """Result type in FINAL mode, where only buffer types are known
        (the default treats them as the input types, which most
        aggregates' result_type handles identically)."""
        return self.result_type(buffer_types)

    @property
    def child(self) -> Expression:
        return self.inputs[0]

    # -- contract ----------------------------------------------------------
    def update_ops(self) -> List[Tuple[str, Optional[int]]]:
        """[(kernel op, input index or None for count_star)] — one per buffer."""
        raise NotImplementedError

    def merge_ops(self) -> List[str]:
        """Kernel op per buffer when re-aggregating partial buffers."""
        raise NotImplementedError

    def buffer_types(self, input_types: Sequence[DataType]) -> List[DataType]:
        raise NotImplementedError

    def result_type(self, input_types: Sequence[DataType]) -> DataType:
        raise NotImplementedError

    def evaluate(self, buffers: List[Column],
                 input_types: Sequence[DataType]) -> Column:
        """Final projection from merged buffer columns to the result."""
        raise NotImplementedError

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.inputs))})"


def _sum_buffer_type(dt: DataType) -> DataType:
    if isinstance(dt, (DoubleType, FloatType)):
        return DoubleType()
    if isinstance(dt, DecimalType):
        # buffers are ALWAYS two-limb (precision > 18): a single-limb
        # partial could overflow int64 across merges and a nulled partial
        # would be silently skipped by the next sum-merge — overflow must
        # only surface at evaluate (Spark CheckOverflow)
        return DecimalType(min(max(dt.precision + 10, 19), 38), dt.scale)
    return LongType()


def _sum_result_type(dt: DataType) -> DataType:
    if isinstance(dt, DecimalType):
        return DecimalType(min(dt.precision + 10, 38), dt.scale)
    return _sum_buffer_type(dt)


class Sum(AggregateFunction):
    name = "sum"

    def update_ops(self):
        return [("sum", 0)]

    def merge_ops(self):
        return ["sum"]

    def buffer_types(self, input_types):
        return [_sum_buffer_type(input_types[0])]

    def result_type(self, input_types):
        return _sum_result_type(input_types[0])

    def result_type_from_buffer(self, buffer_types):
        # final mode cannot recover the pre-widening input precision from
        # the (always two-limb) decimal buffer; the buffer type IS the
        # distributed result type (overflow checks use its precision)
        return buffer_types[0]

    def evaluate(self, buffers, input_types):
        b = buffers[0]
        from ..types import DecimalType
        if isinstance(b.dtype, DecimalType):
            # Spark CheckOverflow at evaluation: sums past the RESULT
            # precision become NULL (non-ANSI). The buffer is always
            # two-limb; fold to one limb when the result type fits 18.
            from ..columnar.column import Decimal128Column
            from ..ops import decimal128 as D
            in_t = input_types[0] if input_types else b.dtype
            rt = b.dtype if in_t == b.dtype else _sum_result_type(in_t)
            if isinstance(b, Decimal128Column):
                hi, lo = b.hi.data, b.lo.data
            else:
                hi, lo = D.from_i64(b.data)
            ok = D.fits_precision(hi, lo, rt.precision)
            v = b.validity & ok
            if rt.precision > 18:
                return Decimal128Column.from_limbs(
                    jnp.where(v, hi, 0), jnp.where(v, lo, 0), v, rt)
            return Column(jnp.where(v, lo, 0), v, rt)
        return b


class Count(AggregateFunction):
    """count(expr); Count() with no input is count(*)."""
    name = "count"

    def update_ops(self):
        return [("count", 0) if self.inputs else ("count_star", None)]

    def merge_ops(self):
        return ["sum"]

    def buffer_types(self, input_types):
        return [LongType()]

    def result_type(self, input_types):
        return LongType()

    def evaluate(self, buffers, input_types):
        b = buffers[0]
        # count is never null: all-null/empty groups are 0
        data = jnp.where(b.validity, b.data, 0)
        return Column(data, jnp.ones_like(b.validity) | b.validity, LongType())


class Min(AggregateFunction):
    name = "min"

    def update_ops(self):
        return [("min", 0)]

    def merge_ops(self):
        return ["min"]

    def buffer_types(self, input_types):
        return [input_types[0]]

    def result_type(self, input_types):
        return input_types[0]

    def evaluate(self, buffers, input_types):
        return buffers[0]


class Max(Min):
    name = "max"

    def update_ops(self):
        return [("max", 0)]

    def merge_ops(self):
        return ["max"]


class First(AggregateFunction):
    """first(expr[, ignoreNulls]) — Spark defaults ignoreNulls=False (the
    first row's value even when null); deterministic only after sort."""
    name = "first"
    _OPS = ("first", "first_any")

    def __init__(self, *inputs, ignore_nulls: bool = False):
        super().__init__(*inputs)
        self.ignore_nulls = ignore_nulls

    def _semantic_args(self):
        return (self.ignore_nulls,)

    def _op(self):
        return self._OPS[0] if self.ignore_nulls else self._OPS[1]

    def update_ops(self):
        return [(self._op(), 0)]

    def merge_ops(self):
        return [self._op()]

    def buffer_types(self, input_types):
        return [input_types[0]]

    def result_type(self, input_types):
        return input_types[0]

    def evaluate(self, buffers, input_types):
        return buffers[0]


class Last(First):
    name = "last"
    _OPS = ("last", "last_any")


class CollectList(AggregateFunction):
    """collect_list(expr): values of the group as an array, nulls dropped
    (reference GpuCollectList; array buffers force the sort tier)."""
    name = "collect_list"
    _UPDATE = "collect"

    def update_ops(self):
        return [(self._UPDATE, 0)]

    def merge_ops(self):
        return ["collect_merge"]

    def buffer_types(self, input_types):
        from ..types import ArrayType
        return [ArrayType(input_types[0])]

    def result_type(self, input_types):
        from ..types import ArrayType
        return ArrayType(input_types[0])

    def result_type_from_buffer(self, buffer_types):
        # final mode: the buffer already IS the list type (distinguished
        # explicitly — inferring from the input type would collapse
        # collect_list over array inputs to array<T>)
        return buffer_types[0]

    def evaluate(self, buffers, input_types):
        return buffers[0]


class Percentile(AggregateFunction):
    """percentile(col, p [, ...]) — exact interpolated percentile
    (reference GpuPercentile). Values buffer as a list column (collect
    machinery, exact tier); evaluation segment-sorts once and picks
    interpolated ranks (ops/percentile.py)."""
    name = "percentile"
    _INTERPOLATE = True

    def __init__(self, child, percentage):
        super().__init__(child)
        from .core import Literal
        if isinstance(percentage, Literal):
            percentage = percentage.value
        self.percentage = percentage

    def _semantic_args(self):
        p = self.percentage
        return (tuple(p) if isinstance(p, (list, tuple)) else p,)

    def update_ops(self):
        return [("collect", 0)]

    def merge_ops(self):
        return ["collect_merge"]

    def buffer_types(self, input_types):
        from ..types import ArrayType
        return [ArrayType(input_types[0])]

    def _scalar_result(self, elem_t):
        from ..types import DOUBLE
        return DOUBLE if self._INTERPOLATE else elem_t

    def result_type(self, input_types):
        from ..types import ArrayType
        rt = self._scalar_result(input_types[0])
        return rt if not isinstance(self.percentage, (list, tuple)) \
            else ArrayType(rt)

    def result_type_from_buffer(self, buffer_types):
        return self.result_type([buffer_types[0].element_type])

    def evaluate(self, buffers, input_types):
        from ..ops.percentile import percentile_of_arrays
        return percentile_of_arrays(buffers[0], self.percentage,
                                    self._INTERPOLATE)


class ApproxPercentile(Percentile):
    """approx_percentile(col, p [, accuracy]) — BOUNDED-memory sketch
    (round 5; reference GpuApproximatePercentile.scala:41-76 merges cuDF
    t-digests). Per-group state is at most K = 2*accuracy value points
    ([values..., n] DOUBLE buffer rows); groups with <= K values stay
    EXACT; each compress/merge level adds rank error <= n/(2K) =
    n/(4*accuracy), inside Spark's n/accuracy contract for shallow merge
    trees. Values ride f64 centroids, so integral inputs beyond 2^53
    lose low bits (the reference's double-based t-digest shares this)."""
    name = "approx_percentile"
    _INTERPOLATE = False
    DEFAULT_ACCURACY = 10000

    def __init__(self, child, percentage, accuracy=None):
        super().__init__(child, percentage)
        from .core import Literal
        if isinstance(accuracy, Literal):
            accuracy = accuracy.value
        self.accuracy = int(accuracy) if accuracy else \
            self.DEFAULT_ACCURACY

    def _semantic_args(self):
        return super()._semantic_args() + (self.accuracy,)

    @property
    def _k(self) -> int:
        return 2 * self.accuracy

    def update_ops(self):
        return [(f"psketch:{self._k}", 0)]

    def merge_ops(self):
        return [f"psketch_merge:{self._k}"]

    def buffer_types(self, input_types):
        from ..types import ArrayType, DOUBLE
        return [ArrayType(DOUBLE)]

    def result_type_from_buffer(self, buffer_types):
        from ..types import DOUBLE
        return self.result_type([DOUBLE])

    def evaluate(self, buffers, input_types):
        from ..ops.percentile import approx_percentile_of_sketches
        rt = self._scalar_result(input_types[0])
        return approx_percentile_of_sketches(buffers[0], self.percentage,
                                             rt)


class CollectSet(CollectList):
    """collect_set(expr): deduped values (reference GpuCollectSet). The
    merge pass flattens partial sets; cross-partial duplicates only arise
    across batches, where the final merge re-dedups via collect_set."""
    name = "collect_set"
    _UPDATE = "collect_set"

    def merge_ops(self):
        # flatten partials, then the evaluate-side dedup is unnecessary
        # because the exact tier merges ALL rows of a group in one batch
        # and re-runs collect_set over the flattened elements... which
        # needs explode; instead merge via collect_merge and rely on the
        # single-merge-pass layout: each group's partials concat, then a
        # final dedup happens in evaluate().
        return ["collect_merge"]

    def evaluate(self, buffers, input_types):
        from ..columnar.column import ArrayColumn
        buf = buffers[0]
        assert isinstance(buf, ArrayColumn)
        return _dedup_array(buf)


def _dedup_array(col):
    """Remove duplicate elements within each list (fixed-width child)."""
    import jax
    import jax.numpy as jnp

    from ..columnar.column import ArrayColumn
    from ..ops.aggregate import _first_occurrence
    from ..ops.basic import compaction_order, gather_column
    from ..ops.collection import _row_of_child
    from ..ops.strings import _rebuild_offsets
    child = col.child
    cap = child.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    row = _row_of_child(col, idx)
    in_use = idx < col.offsets[-1]
    keep = in_use & child.validity
    keep = keep & _first_occurrence(child, row, keep, cap)
    counts = jax.ops.segment_sum(keep.astype(jnp.int32), row,
                                 num_segments=col.capacity)
    counts = jnp.where(col.validity, counts, 0)
    offsets = _rebuild_offsets(counts)
    perm, n_kept = compaction_order(keep, jnp.int32(cap))
    from ..ops.basic import active_mask
    new_child = gather_column(child, perm, active_mask(n_kept, cap))
    return ArrayColumn(new_child, offsets, col.validity, col.dtype)


class Average(AggregateFunction):
    name = "avg"

    def update_ops(self):
        return [("sum", 0), ("count", 0)]

    def merge_ops(self):
        return ["sum", "sum"]

    def buffer_types(self, input_types):
        return [DoubleType(), LongType()]

    def result_type(self, input_types):
        return DoubleType()

    def evaluate(self, buffers, input_types):
        s, c = buffers
        cnt = jnp.where(c.validity, c.data, 0)
        ok = (cnt > 0) & s.validity
        denom = jnp.where(cnt > 0, cnt, 1).astype(jnp.float64)
        data = s.data.astype(jnp.float64) / denom
        return Column(jnp.where(ok, data, 0.0), ok, DoubleType())


class _CentralMoment(AggregateFunction):
    """Shared (count, sum, sum_sq) machinery for variance/stddev."""

    sample = True
    sqrt = False

    def update_ops(self):
        return [("count", 0), ("sum", 0), ("sum_sq", 0)]

    def merge_ops(self):
        return ["sum", "sum", "sum"]

    def buffer_types(self, input_types):
        return [LongType(), DoubleType(), DoubleType()]

    def result_type(self, input_types):
        return DoubleType()

    def evaluate(self, buffers, input_types):
        c, s, sq = buffers
        n = jnp.where(c.validity, c.data, 0).astype(jnp.float64)
        has = n > 0
        safe_n = jnp.where(has, n, 1.0)
        mean = s.data.astype(jnp.float64) / safe_n
        m2 = sq.data.astype(jnp.float64) - n * mean * mean
        m2 = jnp.maximum(m2, 0.0)  # clamp catastrophic cancellation
        if self.sample:
            denom = n - 1.0
            var = jnp.where(denom > 0, m2 / jnp.where(denom > 0, denom, 1.0),
                            jnp.nan)  # n==1 -> NaN (Spark)
        else:
            var = m2 / safe_n
        out = jnp.sqrt(var) if self.sqrt else var
        return Column(jnp.where(has, out, 0.0), has, DoubleType())


class VarianceSamp(_CentralMoment):
    name = "var_samp"
    sample = True


class VariancePop(_CentralMoment):
    name = "var_pop"
    sample = False


class StddevSamp(_CentralMoment):
    name = "stddev_samp"
    sample, sqrt = True, True


class StddevPop(_CentralMoment):
    name = "stddev_pop"
    sample, sqrt = False, True
