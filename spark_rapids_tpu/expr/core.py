"""Expression tree core: the engine's analog of Catalyst expressions plus
their columnar TPU evaluation (the reference's Gpu* expression hierarchy,
e.g. arithmetic.scala / predicates / conditionalExpressions across
sql-plugin; ~218 expr rules in GpuOverrides.scala:919).

Every expression evaluates columnar: `columnar_eval(batch) -> Column`, a pure
traced-jax function of the batch, so whole projections jit into one XLA
program and fuse (the TPU-side advantage over per-kernel cuDF dispatch).

Null semantics follow Spark exactly: null-intolerant operators AND child
validities; special forms (And/Or/If/Coalesce) implement Spark's 3-valued
logic explicitly on validity lanes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn
from ..types import (
    BOOLEAN, BooleanType, DataType, DoubleType, NullType, StringType,
)


class Expression:
    """Base expression node. Immutable; children in `children`."""

    children: Sequence["Expression"] = ()

    @property
    def data_type(self) -> DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    def columnar_eval(self, batch: ColumnarBatch) -> Column:
        raise NotImplementedError(type(self).__name__)

    # -- traversal helpers -------------------------------------------------
    def transform_up(self, fn):
        new_children = [c.transform_up(fn) for c in self.children]
        node = self.with_children(new_children) if new_children else self
        return fn(node)

    def with_children(self, children: List["Expression"]) -> "Expression":
        if not self.children:
            return self
        raise NotImplementedError(type(self).__name__)

    def collect(self, pred) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    @property
    def deterministic(self) -> bool:
        return all(c.deterministic for c in self.children)

    def __repr__(self):
        args = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__}({args})"

    # convenience operator sugar (DataFrame API uses these)
    def _bin(self, other, cls):
        from . import arithmetic, predicates  # noqa
        return cls(self, lit(other) if not isinstance(other, Expression) else other)

    def __add__(self, other):
        from .arithmetic import Add
        return self._bin(other, Add)

    def __sub__(self, other):
        from .arithmetic import Subtract
        return self._bin(other, Subtract)

    def __mul__(self, other):
        from .arithmetic import Multiply
        return self._bin(other, Multiply)

    def __truediv__(self, other):
        from .arithmetic import Divide
        return self._bin(other, Divide)

    def __mod__(self, other):
        from .arithmetic import Remainder
        return self._bin(other, Remainder)

    def __neg__(self):
        from .arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __eq__(self, other):  # type: ignore[override]
        from .predicates import EqualTo
        return self._bin(other, EqualTo)

    def __ne__(self, other):  # type: ignore[override]
        from .predicates import Not, EqualTo
        return Not(self._bin(other, EqualTo))

    def __lt__(self, other):
        from .predicates import LessThan
        return self._bin(other, LessThan)

    def __le__(self, other):
        from .predicates import LessThanOrEqual
        return self._bin(other, LessThanOrEqual)

    def __gt__(self, other):
        from .predicates import GreaterThan
        return self._bin(other, GreaterThan)

    def __ge__(self, other):
        from .predicates import GreaterThanOrEqual
        return self._bin(other, GreaterThanOrEqual)

    def __and__(self, other):
        from .predicates import And
        return self._bin(other, And)

    def __or__(self, other):
        from .predicates import Or
        return self._bin(other, Or)

    def __invert__(self):
        from .predicates import Not
        return Not(self)

    def __hash__(self):
        return id(self)

    def semantic_key(self):
        """Structural identity for CSE (the tiered-project dedupe,
        reference GpuTieredProject basicPhysicalOperators.scala:507)."""
        return (type(self).__name__, self._semantic_args(),
                tuple(c.semantic_key() for c in self.children))

    def _semantic_args(self):
        return ()

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, dt: DataType) -> "Expression":
        from .cast import Cast
        return Cast(self, dt)


class LeafExpression(Expression):
    children = ()

    def with_children(self, children):
        assert not children
        return self


class Literal(LeafExpression):
    def __init__(self, value, dtype: Optional[DataType] = None):
        self.value = value
        self._dtype = dtype or _infer_literal_type(value)

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    def columnar_eval(self, batch: ColumnarBatch) -> Column:
        cap = batch.capacity
        dt = self._dtype
        if isinstance(dt, StringType):
            b = (self.value or "").encode("utf-8") if isinstance(self.value, str) \
                else (self.value or b"")
            n_bytes = max(len(b), 1)
            from ..columnar.column import bucket_capacity
            byte_cap = bucket_capacity(n_bytes * cap)
            lengths = jnp.full((cap,), len(b), jnp.int32)
            offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                       jnp.cumsum(lengths, dtype=jnp.int32)])
            pattern = np.frombuffer(b, dtype=np.uint8) if b else np.zeros(0, np.uint8)
            reps = int(np.ceil(byte_cap / max(len(b), 1)))
            data = np.tile(pattern, reps)[:byte_cap] if len(b) else np.zeros(byte_cap, np.uint8)
            valid = jnp.full((cap,), self.value is not None)
            return StringColumn(jnp.asarray(data), offsets, valid, dt)
        if self.value is None:
            zero = jnp.zeros((cap,), dt.jnp_dtype if dt.jnp_dtype else jnp.int8)
            return Column(zero, jnp.zeros((cap,), jnp.bool_), dt)
        data = jnp.full((cap,), self.value, dt.jnp_dtype)
        return Column(data, jnp.ones((cap,), jnp.bool_), dt)

    def _semantic_args(self):
        return (self.value, repr(self._dtype))

    def __repr__(self):
        return f"lit({self.value!r})"


def _infer_literal_type(value) -> DataType:
    from ..types import (BOOLEAN, DOUBLE, INT, LONG, NULL, STRING)
    if value is None:
        return NULL
    if isinstance(value, bool):
        return BOOLEAN
    if isinstance(value, int):
        return INT if -(2**31) <= value < 2**31 else LONG
    if isinstance(value, float):
        return DOUBLE
    if isinstance(value, (str, bytes)):
        return STRING
    import datetime
    if isinstance(value, datetime.date) and not isinstance(value, datetime.datetime):
        from ..types import DATE
        return DATE
    raise TypeError(f"cannot infer literal type for {value!r}")


def lit(value) -> Expression:
    return value if isinstance(value, Expression) else Literal(value)


class BoundReference(LeafExpression):
    """Resolved column reference by ordinal (Catalyst BoundReference)."""

    def __init__(self, ordinal: int, dtype: DataType, name: str = ""):
        self.ordinal = ordinal
        self._dtype = dtype
        self.name = name

    @property
    def data_type(self):
        return self._dtype

    def columnar_eval(self, batch: ColumnarBatch) -> Column:
        return batch.columns[self.ordinal]

    def _semantic_args(self):
        return (self.ordinal,)

    def __repr__(self):
        return f"#{self.ordinal}:{self.name}"


class UnresolvedAttribute(LeafExpression):
    """Named column reference; resolved against a schema during planning."""

    def __init__(self, name: str):
        self.name = name

    @property
    def data_type(self):
        raise TypeError(f"unresolved attribute {self.name!r}")

    def columnar_eval(self, batch: ColumnarBatch) -> Column:
        return batch.column(self.name)

    def _semantic_args(self):
        return (self.name,)

    def __repr__(self):
        return f"col({self.name!r})"


def col(name: str) -> UnresolvedAttribute:
    return UnresolvedAttribute(name)


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.name = name

    @property
    def child(self):
        return self.children[0]

    @property
    def data_type(self):
        return self.child.data_type

    @property
    def nullable(self):
        return self.child.nullable

    def columnar_eval(self, batch):
        return self.child.columnar_eval(batch)

    def with_children(self, children):
        return Alias(children[0], self.name)

    def _semantic_args(self):
        return ()  # alias is transparent for CSE

    def semantic_key(self):
        return self.children[0].semantic_key()

    def __repr__(self):
        return f"{self.children[0]!r} AS {self.name}"


def resolve(expr: Expression, schema) -> Expression:
    """Bind UnresolvedAttribute -> BoundReference against `schema`."""
    def fn(node):
        if isinstance(node, UnresolvedAttribute):
            idx = schema.index_of(node.name)
            return BoundReference(idx, schema.fields[idx].data_type, node.name)
        return node
    return expr.transform_up(fn)


def output_name(expr: Expression, default: str) -> str:
    if isinstance(expr, Alias):
        return expr.name
    if isinstance(expr, (UnresolvedAttribute, BoundReference)):
        return expr.name
    return default
