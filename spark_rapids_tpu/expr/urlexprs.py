"""URL expressions (reference GpuParseUrl.scala + JNI ParseURI).
Literal part/key run the byte-parallel device kernel (ops/url.py);
non-literal parts keep the host row tier."""

from __future__ import annotations

from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..types import STRING
from .core import Expression, Literal

_PARTS = ("HOST", "PATH", "QUERY", "REF", "PROTOCOL", "FILE",
          "AUTHORITY", "USERINFO")


class ParseUrl(Expression):
    """parse_url(url, part[, key]) with Spark's part names."""

    HOST_ONLY = False  # device kernel for literal part/key

    def __init__(self, child: Expression, part, key=None):
        self.children = (child,)
        # Spark's parse_url is CASE-SENSITIVE: 'host' is an unknown part
        # and yields NULL, only 'HOST' extracts
        self.part = part.value if isinstance(part, Literal) else part
        self.key = key.value if isinstance(key, Literal) else key

    def with_children(self, cs):
        return ParseUrl(cs[0], self.part, self.key)

    def _semantic_args(self):
        return (self.part, self.key)

    @property
    def data_type(self):
        return STRING

    def host_eval_row(self, url) -> Optional[str]:
        if url is None or self.part not in _PARTS:
            return None
        try:
            p = urlparse(url)
        except ValueError:
            return None
        if self.part == "HOST":
            return p.hostname
        if self.part == "PROTOCOL":
            return p.scheme or None
        if self.part == "PATH":
            return p.path
        if self.part == "QUERY":
            if self.key is not None:
                vals = parse_qs(p.query, keep_blank_values=True
                                ).get(self.key)
                return vals[0] if vals else None
            return p.query or None
        if self.part == "REF":
            return p.fragment or None
        if self.part == "FILE":
            return p.path + ("?" + p.query if p.query else "")
        if self.part == "AUTHORITY":
            return p.netloc or None
        if self.part == "USERINFO":
            if p.username is None:
                return None
            return p.username + (f":{p.password}"
                                 if p.password is not None else "")
        return None

    @property
    def device_supported(self) -> bool:
        """Literal part/key run the byte-parallel device kernel
        (ops/url.py)."""
        return isinstance(self.part, str) and (
            self.key is None or isinstance(self.key, str))

    def columnar_eval(self, batch):
        from ..ops.url import parse_url
        if not self.device_supported:
            raise NotImplementedError(
                "parse_url with non-literal part runs on the host tier")
        c = self.children[0].columnar_eval(batch)
        return parse_url(c, self.part, self.key)
