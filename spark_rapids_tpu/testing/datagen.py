"""Composable random data generators — the engine's data_gen.py (reference
integration_tests/src/main/python/data_gen.py: DataGen hierarchy with
special-value weighting, nullability, and seeded reproducibility).

Every generator deliberately over-samples the values that break columnar
kernels: type min/max, 0/-0.0/NaN/±inf for floats, empty and
max-length strings, epoch boundaries for dates/timestamps. Nulls are mixed
in at a configurable probability.
"""

from __future__ import annotations

import datetime
import math
import string as _string
from decimal import Decimal
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..types import (
    BooleanType, ByteType, DataType, DateType, DecimalType, DoubleType,
    FloatType, IntegerType, LongType, Schema, ShortType, StringType,
    StructField, TimestampType,
)

#: probability of drawing from the special-value pool instead of random
SPECIAL_PROB = 0.05


class DataGen:
    """Base generator: produces python values of `data_type`."""

    def __init__(self, data_type: DataType, nullable: bool = True,
                 null_prob: float = 0.08):
        self.data_type = data_type
        self.nullable = nullable
        self.null_prob = null_prob if nullable else 0.0
        self._specials: List[Any] = []

    def with_special_case(self, value, weight: float = 1.0) -> "DataGen":
        self._specials.append(value)
        return self

    # -- subclass surface --------------------------------------------------
    def gen_value(self, rng: np.random.Generator):
        raise NotImplementedError(type(self).__name__)

    # -- drive -------------------------------------------------------------
    def gen_list(self, rng: np.random.Generator, n: int) -> List:
        out = []
        for _ in range(n):
            if self.nullable and rng.random() < self.null_prob:
                out.append(None)
            elif self._specials and rng.random() < SPECIAL_PROB:
                out.append(self._specials[int(rng.integers(
                    0, len(self._specials)))])
            else:
                out.append(self.gen_value(rng))
        return out


class _IntGen(DataGen):
    BITS = 64

    def __init__(self, data_type, nullable=True, null_prob=0.08,
                 min_val: Optional[int] = None,
                 max_val: Optional[int] = None):
        super().__init__(data_type, nullable, null_prob)
        lo = -(1 << (self.BITS - 1))
        hi = (1 << (self.BITS - 1)) - 1
        self.min_val = lo if min_val is None else min_val
        self.max_val = hi if max_val is None else max_val
        for s in (0, 1, -1, self.min_val, self.max_val):
            if self.min_val <= s <= self.max_val:
                self.with_special_case(s)

    def gen_value(self, rng):
        return int(rng.integers(self.min_val, self.max_val, endpoint=True))


class ByteGen(_IntGen):
    BITS = 8

    def __init__(self, **kw):
        super().__init__(ByteType(), **kw)


class ShortGen(_IntGen):
    BITS = 16

    def __init__(self, **kw):
        super().__init__(ShortType(), **kw)


class IntegerGen(_IntGen):
    BITS = 32

    def __init__(self, **kw):
        super().__init__(IntegerType(), **kw)


class LongGen(_IntGen):
    BITS = 64

    def __init__(self, **kw):
        super().__init__(LongType(), **kw)


class _FpGen(DataGen):
    def __init__(self, data_type, nullable=True, null_prob=0.08,
                 no_nans: bool = False, special_cases: Optional[Sequence] = None):
        super().__init__(data_type, nullable, null_prob)
        if special_cases is None:
            special_cases = [0.0, -0.0, 1.0, -1.0,
                             float("inf"), float("-inf")]
            if not no_nans:
                special_cases.append(float("nan"))
        for s in special_cases:
            self.with_special_case(s)

    def gen_value(self, rng):
        # mix magnitudes: uniform small, exponential large
        scale = 10.0 ** rng.integers(-3, 12)
        return float(rng.normal(0, 1) * scale)


class DoubleGen(_FpGen):
    def __init__(self, **kw):
        super().__init__(DoubleType(), **kw)


class FloatGen(_FpGen):
    def __init__(self, **kw):
        super().__init__(FloatType(), **kw)

    def gen_value(self, rng):
        return float(np.float32(super().gen_value(rng)))


class BooleanGen(DataGen):
    def __init__(self, nullable=True, null_prob=0.08):
        super().__init__(BooleanType(), nullable, null_prob)

    def gen_value(self, rng):
        return bool(rng.random() < 0.5)


class StringGen(DataGen):
    """Random strings over a charset with length-edge special cases. The
    default charset includes multi-byte UTF-8 so offset kernels see
    non-ASCII byte lengths."""

    def __init__(self, nullable=True, null_prob=0.08, min_length=0,
                 max_length=20, charset: Optional[str] = None,
                 ascii_only: bool = False):
        super().__init__(StringType(), nullable, null_prob)
        base = _string.ascii_letters + _string.digits + " _-."
        if not ascii_only:
            base += "é中ß"
        self.charset = charset or base
        self.min_length = min_length
        self.max_length = max_length
        self.with_special_case("")
        self.with_special_case("A" * max_length)
        self.with_special_case(" leading")
        self.with_special_case("trailing ")

    def gen_value(self, rng):
        n = int(rng.integers(self.min_length, self.max_length, endpoint=True))
        idx = rng.integers(0, len(self.charset), n)
        return "".join(self.charset[int(i)] for i in idx)


class DateGen(DataGen):
    """Days since epoch as datetime.date (civil-calendar edge cases)."""

    def __init__(self, nullable=True, null_prob=0.08,
                 start=datetime.date(1900, 1, 1),
                 end=datetime.date(2100, 12, 31)):
        super().__init__(DateType(), nullable, null_prob)
        self.start_days = start.toordinal()
        self.end_days = end.toordinal()
        for s in (datetime.date(1970, 1, 1), datetime.date(2000, 2, 29),
                  datetime.date(1999, 12, 31), start, end):
            if start <= s <= end:
                self.with_special_case(s)

    def gen_value(self, rng):
        return datetime.date.fromordinal(
            int(rng.integers(self.start_days, self.end_days, endpoint=True)))


class TimestampGen(DataGen):
    """Microseconds since epoch as tz-naive datetime (engine is UTC-only,
    like the reference defaults with spark.sql.session.timeZone=UTC)."""

    def __init__(self, nullable=True, null_prob=0.08,
                 start=datetime.datetime(1970, 1, 1),
                 end=datetime.datetime(2100, 1, 1)):
        super().__init__(TimestampType(), nullable, null_prob)
        self.start_us = int(start.timestamp() * 0) + \
            (start - datetime.datetime(1970, 1, 1)) // datetime.timedelta(
                microseconds=1)
        self.end_us = (end - datetime.datetime(1970, 1, 1)) // \
            datetime.timedelta(microseconds=1)
        self.with_special_case(datetime.datetime(1970, 1, 1))
        self.with_special_case(end)

    def gen_value(self, rng):
        us = int(rng.integers(self.start_us, self.end_us, endpoint=True))
        return datetime.datetime(1970, 1, 1) + datetime.timedelta(
            microseconds=us)


class DecimalGen(DataGen):
    def __init__(self, precision=10, scale=2, nullable=True, null_prob=0.08):
        super().__init__(DecimalType(precision, scale), nullable, null_prob)
        self.precision = precision
        self.scale = scale
        unscaled_max = 10 ** precision - 1
        for s in (0, 1, -1, unscaled_max, -unscaled_max):
            self.with_special_case(Decimal(s).scaleb(-scale))

    def gen_value(self, rng):
        unscaled_max = 10 ** self.precision - 1
        u = int(rng.integers(-unscaled_max, unscaled_max, endpoint=True))
        return Decimal(u).scaleb(-self.scale)


class SetValuesGen(DataGen):
    """Draw uniformly from a fixed pool (low-cardinality keys)."""

    def __init__(self, data_type, values: Sequence, nullable=True,
                 null_prob=0.08):
        super().__init__(data_type, nullable and None in values,
                         null_prob if None in values else 0.0)
        self.values = [v for v in values if v is not None]
        self._has_null = None in values

    def gen_value(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]


class RepeatSeqGen(DataGen):
    """Cycle a fixed sequence deterministically (stable group keys)."""

    def __init__(self, data_type, values: Sequence):
        super().__init__(data_type, nullable=False, null_prob=0.0)
        self.values = list(values)
        self._i = 0

    def gen_list(self, rng, n):
        out = [self.values[(self._i + i) % len(self.values)]
               for i in range(n)]
        self._i = (self._i + n) % len(self.values)
        return out


def gen_pydict(gens: Sequence[Tuple[str, DataGen]], n: int,
               seed: int = 0) -> Tuple[dict, Schema]:
    """Generate a column dict + matching Schema from (name, gen) pairs."""
    rng = np.random.default_rng(seed)
    data = {}
    fields = []
    for name, g in gens:
        data[name] = g.gen_list(rng, n)
        fields.append(StructField(name, g.data_type, g.nullable))
    return data, Schema(tuple(fields))


def gen_df(session, gens: Sequence[Tuple[str, DataGen]], n: int = 256,
           seed: int = 0, batch_rows: Optional[int] = None):
    """Generate a DataFrame in `session` (reference gen_df, data_gen.py)."""
    data, schema = gen_pydict(gens, n, seed)
    return session.from_pydict(data, schema, batch_rows=batch_rows)
