"""Equality harness — the engine's asserts.py (reference
integration_tests/src/main/python/asserts.py:579
assert_gpu_and_cpu_are_equal_collect and friends).

The reference's oracle is CPU Spark executing the same query. Standalone,
correctness is established two ways:

  * `assert_rows_equal(got, expected)` against an explicit Python-semantics
    oracle (NaN==NaN, -0.0==0.0 per Spark group semantics is NOT applied
    here — exact row values, with float tolerance for accumulation-order
    differences);
  * `assert_consistent_across_configs(build)` runs the same logical query
    on independent engine tiers — speculative vs exact, fused vs unfused,
    single-partition vs mesh-distributed — and requires them all to agree.
    A bug must hit every tier identically to slip through.
"""

from __future__ import annotations

import math
from decimal import Decimal
from typing import Callable, Dict, List, Optional, Sequence

#: engine tiers that must agree on every query (cross-oracle matrix)
CONFIG_TIERS: List[Dict] = [
    {},  # default: speculative masked-bucket + whole-stage fusion
    {"spark.rapids.tpu.agg.speculative.enabled": False},
    {"spark.rapids.tpu.agg.speculative.enabled": False,
     "spark.rapids.tpu.fusion.enabled": False},
]


def collect_with_conf(build: Callable, conf: Optional[Dict] = None,
                      mesh_devices: Optional[int] = None) -> List[tuple]:
    """Run `build(session) -> DataFrame` under a fresh session and collect."""
    from ..api.session import TpuSession
    sess = TpuSession(dict(conf or {}), mesh_devices=mesh_devices)
    return build(sess).collect()


def _value_equal(a, b, rel_tol: float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if math.isinf(fa) or math.isinf(fb):
            return fa == fb
        return math.isclose(fa, fb, rel_tol=rel_tol, abs_tol=rel_tol)
    if isinstance(a, Decimal) or isinstance(b, Decimal):
        return Decimal(a) == Decimal(b)
    return a == b


def _sort_key(row: tuple):
    out = []
    for v in row:
        if v is None:
            out.append((2, ""))
        elif isinstance(v, float) and math.isnan(v):
            out.append((1, ""))
        else:
            out.append((0, str(v)))
    return out


def assert_rows_equal(got: Sequence[tuple], expected: Sequence[tuple],
                      rel_tol: float = 1e-9, ordered: bool = False):
    """Row-set equality with Spark-style float handling (NaN equals NaN;
    tolerance absorbs accumulation-order float differences)."""
    assert len(got) == len(expected), \
        f"row count {len(got)} != {len(expected)}\n" \
        f"got={list(got)[:10]}\nexpected={list(expected)[:10]}"
    g = list(got) if ordered else sorted(got, key=_sort_key)
    e = list(expected) if ordered else sorted(expected, key=_sort_key)
    for i, (rg, re_) in enumerate(zip(g, e)):
        assert len(rg) == len(re_), f"row {i} arity {rg} vs {re_}"
        for j, (a, b) in enumerate(zip(rg, re_)):
            assert _value_equal(a, b, rel_tol), \
                f"row {i} col {j}: {a!r} != {b!r}\n got: {rg}\n exp: {re_}"


def assert_equal_with_tolerance(got, expected, rel_tol: float = 1e-9):
    assert_rows_equal(got, expected, rel_tol=rel_tol)


def assert_consistent_across_configs(build: Callable,
                                     mesh_devices: Optional[int] = 8,
                                     rel_tol: float = 1e-6,
                                     expected: Optional[Sequence] = None):
    """Run `build(session) -> DataFrame` on every engine tier (and the
    mesh-distributed plan when >= mesh_devices devices exist) and assert
    all results agree; optionally also against an explicit oracle."""
    import jax
    results = [(repr(conf), collect_with_conf(build, conf))
               for conf in CONFIG_TIERS]
    if mesh_devices and len(jax.devices()) >= mesh_devices:
        results.append((f"mesh[{mesh_devices}]",
                        collect_with_conf(build,
                                          mesh_devices=mesh_devices)))
        results.append(
            (f"mesh[{mesh_devices}]+exact",
             collect_with_conf(
                 build, {"spark.rapids.tpu.agg.speculative.enabled": False},
                 mesh_devices=mesh_devices)))
    base_name, base = results[0]
    for name, rows in results[1:]:
        try:
            assert_rows_equal(rows, base, rel_tol=rel_tol)
        except AssertionError as ex:
            raise AssertionError(
                f"tier {name} disagrees with {base_name}: {ex}") from ex
    if expected is not None:
        assert_rows_equal(base, list(expected), rel_tol=rel_tol)
    return base
