"""Test harness the engine ships with — the analog of the reference's
integration-test toolkit (integration_tests/src/main/python/data_gen.py
composable generators and asserts.py:579 assert_gpu_and_cpu_are_equal*).

The reference's oracle is CPU Spark running the same query; standalone,
the oracle is (a) an explicit Python-semantics evaluation where provided
and (b) cross-config consistency: the same query run on independent engine
tiers (speculative vs exact, fused vs unfused, single-partition vs
mesh-distributed) must agree bit-for-bit / within float tolerance.
"""

from .asserts import (  # noqa: F401
    assert_consistent_across_configs, assert_equal_with_tolerance,
    assert_rows_equal, collect_with_conf,
)
from .datagen import (  # noqa: F401
    BooleanGen, ByteGen, DataGen, DateGen, DecimalGen, DoubleGen, FloatGen,
    IntegerGen, LongGen, RepeatSeqGen, SetValuesGen, ShortGen, StringGen,
    TimestampGen, gen_df, gen_pydict,
)
