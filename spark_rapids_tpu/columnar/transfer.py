"""Packed device->host batch transfer.

Host materialization of a result batch used to fetch every column (and the
row count) as its own d2h transfer. Each transfer pays a full round-trip
latency — on remote-attached TPUs that latency dwarfs the kernels, and even
locally it serializes the pipeline once per column. The analog in the
reference is JCudfSerialization packing a table into ONE host buffer
(SURVEY §2.5); here a tiny jitted packer bit-casts every buffer of the
batch into one contiguous uint8 vector so materialization is exactly one
transfer, then numpy views slice it back apart on the host.

Layout (all little-endian, matching XLA bitcasts on every supported host):
  [int32 num_rows][per column: blocks in schema order]
    fixed-width col : data bytes (cap*itemsize)  + validity (cap bytes)
    string/binary   : offsets ((cap+1)*4) + data (byte_cap) + validity
    struct          : validity + child blocks
    array           : offsets ((cap+1)*4) + validity + child blocks
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .column import (ArrayColumn, Column, MapColumn, StringColumn,
                     StructColumn)

# process-cumulative packed-D2H counters (ISSUE 11: the device->host
# half of the telemetry plane's link-byte attribution — the H2D mirror
# lives in columnar/upload.py)
_COUNTER_LOCK = threading.Lock()
_COUNTERS = {"d2h_copies": 0, "d2h_bytes": 0}


def note_d2h(nbytes: int) -> None:
    """One packed device->host copy landed (`exec/exchange.py` calls
    this for the fused split+pack buffer it fetches itself)."""
    with _COUNTER_LOCK:
        _COUNTERS["d2h_copies"] += 1
        _COUNTERS["d2h_bytes"] += int(nbytes)


def counters() -> Dict[str, int]:
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def _dd_split() -> bool:
    """True when f64 must travel as (hi, lo) float32 pairs: TPU emulates
    f64 as double-double, its compiler has no f64 bitcast, and the dd pair
    IS the exact device value (reconstruction is lossless by construction).
    CPU/GPU keep the direct IEEE-754 bitcast."""
    return jax.default_backend() not in ("cpu", "gpu", "cuda", "rocm")


def _bytes_of(arr) -> jnp.ndarray:
    """Flatten any device array into a uint8 vector via bitcast.

    64-bit integer lanes are staged through uint32: TPU's X64 rewriting
    pass stores 64-bit values as u32 pairs and implements 64->32 bitcasts,
    but not a direct 64->8 bitcast. The u32 pair order matches the
    little-endian byte order numpy `.view()` expects on the host
    (asserted by tests).
    """
    if arr.dtype == jnp.bool_:
        return arr.astype(jnp.uint8).ravel()
    if arr.dtype == jnp.uint8:
        return arr.ravel()
    if arr.dtype == jnp.float64 and _dd_split():
        hi = arr.astype(jnp.float32)
        lo = (arr - hi.astype(jnp.float64)).astype(jnp.float32)
        arr = jnp.stack([hi, lo], axis=-1).ravel()
    elif np.dtype(arr.dtype).itemsize == 8:
        # ravel between the two bitcasts: XLA's simplifier mis-folds a
        # chained 64->32->8 bitcast into one op with the wrong shape
        arr = jax.lax.bitcast_convert_type(arr, jnp.uint32).ravel()
    return jax.lax.bitcast_convert_type(arr, jnp.uint8).ravel()


def _pack_column(col: Column, out: List[jnp.ndarray]) -> None:
    if isinstance(col, StringColumn):
        out.append(_bytes_of(col.offsets))
        out.append(_bytes_of(col.data))
        out.append(_bytes_of(col.validity))
        return
    if isinstance(col, StructColumn):
        out.append(_bytes_of(col.validity))
        for k in col.children:
            _pack_column(k, out)
        return
    if isinstance(col, ArrayColumn):
        out.append(_bytes_of(col.offsets))
        out.append(_bytes_of(col.validity))
        _pack_column(col.child, out)
        return
    if isinstance(col, MapColumn):
        out.append(_bytes_of(col.offsets))
        out.append(_bytes_of(col.validity))
        _pack_column(col.keys, out)
        _pack_column(col.values, out)
        return
    out.append(_bytes_of(col.data))
    out.append(_bytes_of(col.validity))


def _pack_impl(batch) -> jnp.ndarray:
    pieces: List[jnp.ndarray] = [
        _bytes_of(jnp.asarray(batch.num_rows, jnp.int32).reshape(1))]
    for col in batch.columns:
        _pack_column(col, pieces)
    return jnp.concatenate(pieces)


from ..obs.dispatch import instrument as _instrument

_pack_jit = _instrument(_pack_impl, label="transfer.pack_batch")


def _take(buf: np.ndarray, pos: int, n: int) -> Tuple[np.ndarray, int]:
    return buf[pos: pos + n], pos + n


def _unpack_column(col: Column, buf: np.ndarray, pos: int
                   ) -> Tuple[Column, int]:
    cap = col.capacity
    if isinstance(col, StringColumn):
        raw, pos = _take(buf, pos, (cap + 1) * 4)
        offsets = raw.view(np.int32)
        data, pos = _take(buf, pos, col.byte_capacity)
        v, pos = _take(buf, pos, cap)
        return StringColumn(data, offsets, v.astype(np.bool_), col.dtype), pos
    if isinstance(col, StructColumn):
        v, pos = _take(buf, pos, cap)
        kids = []
        for k in col.children:
            kid, pos = _unpack_column(k, buf, pos)
            kids.append(kid)
        # type(col) keeps Decimal128Column limbs as decimal, not struct
        return type(col)(tuple(kids), v.astype(np.bool_), col.dtype), pos
    if isinstance(col, ArrayColumn):
        raw, pos = _take(buf, pos, (cap + 1) * 4)
        offsets = raw.view(np.int32)
        v, pos = _take(buf, pos, cap)
        kid, pos = _unpack_column(col.child, buf, pos)
        return ArrayColumn(kid, offsets, v.astype(np.bool_), col.dtype), pos
    if isinstance(col, MapColumn):
        raw, pos = _take(buf, pos, (cap + 1) * 4)
        offsets = raw.view(np.int32)
        v, pos = _take(buf, pos, cap)
        keys, pos = _unpack_column(col.keys, buf, pos)
        vals, pos = _unpack_column(col.values, buf, pos)
        return MapColumn(keys, vals, offsets, v.astype(np.bool_),
                         col.dtype), pos
    np_dtype = np.dtype(col.data.dtype)
    if np_dtype == np.bool_:
        raw, pos = _take(buf, pos, cap)
        data = raw.astype(np.bool_)
    elif np_dtype == np.float64 and _dd_split():
        raw, pos = _take(buf, pos, cap * 8)
        pair = raw.view(np.float32).reshape(cap, 2)
        data = pair[:, 0].astype(np.float64) + pair[:, 1].astype(np.float64)
    else:
        raw, pos = _take(buf, pos, cap * np_dtype.itemsize)
        data = raw.view(np_dtype)
    v, pos = _take(buf, pos, cap)
    return Column(data, v.astype(np.bool_), col.dtype), pos


def _pack_split_impl(counts, columns) -> jnp.ndarray:
    pieces: List[jnp.ndarray] = [_bytes_of(counts.astype(jnp.int32))]
    for col in columns:
        _pack_column(col, pieces)
    return jnp.concatenate(pieces)


_pack_split_jit = _instrument(_pack_split_impl,
                              label="transfer.pack_split")


def pack_split(counts, columns) -> jnp.ndarray:
    """Traceable split packer: (count table, partition-ordered columns)
    -> one uint8 buffer. Exposed so the exchange can fuse it INTO the
    partition-split traced program (ISSUE 10 satellite — shuffle write
    is ONE dispatch, split + reorder + pack)."""
    return _pack_split_impl(counts, list(columns))


def unpack_split_host(buf: np.ndarray, template_columns,
                      n_parts: int) -> Tuple[np.ndarray, List[Column]]:
    """Host-side unpack of a pack_split buffer. `template_columns` only
    provides the layout (class / capacity / dtype per column) — column
    objects or `jax.eval_shape` results both work, so the fused
    split+pack program never has to materialize per-column device
    arrays. Returns (counts int64 numpy, numpy-backed columns)."""
    host_counts = buf[: 4 * n_parts].view(np.int32).astype(np.int64)
    pos = 4 * n_parts
    out: List[Column] = []
    for col in template_columns:
        host_col, pos = _unpack_column(col, buf, pos)
        out.append(host_col)
    assert pos == buf.shape[0], (pos, buf.shape)
    return host_counts, out


def fetch_split_host(counts, columns) -> Tuple[np.ndarray, List[Column]]:
    """Packed D2H lane for the device shuffle partition split (ISSUE 9):
    land the per-partition count table AND the partition-ordered columns
    in ONE host copy. The count table is the only host-synced control
    value of the split; the column payload rides the same buffer instead
    of per-column pulls.

    Returns (counts int64 numpy, numpy-backed columns).
    """
    n_parts = int(counts.shape[0])
    buf = np.asarray(_pack_split_jit(counts, list(columns)))  # ONE d2h
    note_d2h(buf.nbytes)
    return unpack_split_host(buf, columns, n_parts)


def fetch_batch_host(batch) -> Tuple[List[Column], int]:
    """Materialize a device batch with ONE d2h transfer.

    Returns (numpy-backed columns, host row count). Already-host batches
    (numpy leaves) pass through untouched.
    """
    # late-materialization output seam (ISSUE 18): a batch fetched for
    # host consumption genuinely needs full values — decode encoded
    # columns through the gather engine before the packed d2h
    from .encoded import materialize_batch
    batch = materialize_batch(batch, seam="output")
    leaves = jax.tree_util.tree_leaves(batch.columns)
    if batch._host_rows is not None and all(
            isinstance(x, np.ndarray) for x in leaves):
        return list(batch.columns), batch._host_rows
    packed = _pack_jit(batch)
    buf = np.asarray(packed)  # the single transfer
    note_d2h(buf.nbytes)
    n = int(buf[:4].view(np.int32)[0])
    pos = 4
    cols: List[Column] = []
    for col in batch.columns:
        host_col, pos = _unpack_column(col, buf, pos)
        cols.append(host_col)
    assert pos == buf.shape[0], (pos, buf.shape)
    return cols, n
