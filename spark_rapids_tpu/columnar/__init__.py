from .column import (
    ArrayColumn, Column, StringColumn, StructColumn, bucket_capacity,
    column_from_arrow, column_to_arrow,
)
from .batch import ColumnarBatch, empty_batch
