from .column import (
    ArrayColumn, Column, MapColumn, StringColumn, StructColumn,
    bucket_capacity, build_column, column_from_arrow, column_to_arrow,
)
from .batch import ColumnarBatch, empty_batch
