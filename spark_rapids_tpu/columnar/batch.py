"""ColumnarBatch — the unit of work flowing between operators.

TPU analog of Spark's ColumnarBatch of GpuColumnVector (reference
GpuColumnVector.java:40). Differences driven by XLA:

  * `num_rows` is carried as a *device* int32 scalar so that row-count-changing
    ops (filter, join) stay inside one compiled program. A host-side cached int
    is kept when statically known; reading `num_rows_host` on a traced batch
    forces a device sync (the analog of a cudaStreamSynchronize — use sparingly,
    operators should stay on device).
  * all columns share one capacity bucket; `sized_to` grows buckets so two
    batches can be processed by one compiled kernel shape.

The batch is a pytree: entire operator pipelines jit end-to-end.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..types import DataType, Schema, StringType, StructField
from .column import (
    Column, StringColumn, bucket_capacity, column_from_arrow, column_to_arrow,
)


class ColumnarBatch:
    __slots__ = ("columns", "num_rows", "schema", "_host_rows")

    def __init__(self, columns: Sequence[Column], num_rows, schema: Schema,
                 host_rows: Optional[int] = None):
        self.columns = tuple(columns)
        if isinstance(num_rows, (int, np.integer)):
            host_rows = int(num_rows)
            num_rows = jnp.asarray(num_rows, jnp.int32)
        self.num_rows = num_rows
        self.schema = schema
        self._host_rows = host_rows

    # -- accessors ---------------------------------------------------------
    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def num_rows_host(self) -> int:
        """Logical row count as a host int; syncs if produced on device."""
        if self._host_rows is None:
            self._host_rows = int(self.num_rows)
        return self._host_rows

    def column(self, name_or_idx) -> Column:
        if isinstance(name_or_idx, str):
            return self.columns[self.schema.index_of(name_or_idx)]
        return self.columns[name_or_idx]

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_pydict(data: dict, schema: Schema,
                    capacity: Optional[int] = None) -> "ColumnarBatch":
        lengths = {len(v) for v in data.values()} or {0}
        assert len(lengths) == 1, "ragged input columns"
        n = lengths.pop()
        cap = capacity or bucket_capacity(n)
        from .column import build_column
        cols = [build_column(data[f.name], f.data_type, cap)
                for f in schema.fields]
        return ColumnarBatch(cols, n, schema)

    @staticmethod
    def from_arrow(table, fault_key=None) -> "ColumnarBatch":
        """pyarrow Table/RecordBatch -> device batch (one capacity
        bucket). The scan ingest seam (ISSUE 10): columns are built
        host-resident and the whole batch crosses the host->device
        boundary through the packed upload engine — ONE transfer per
        batch when `spark.rapids.tpu.transfer.packedUpload.enabled`
        (default), one per buffer otherwise. `fault_key` is the batch's
        chaos work-item key (the scan chunk offset)."""
        from ..types import from_arrow as type_from_arrow
        from .column import host_build
        from .upload import to_device_batch
        n = table.num_rows
        cap = bucket_capacity(n)
        fields, cols = [], []
        with host_build():
            for name in table.column_names:
                arr = table.column(name)
                col = column_from_arrow(arr)
                if col.capacity < cap:
                    col = col.with_capacity(cap)
                cols.append(col)
                fields.append(StructField(name, col.dtype))
        # ISSUE 18: account encoded vs decoded scan lanes (encoded_scan
        # event + advisor evidence) while buffers are still host numpy
        from .encoded import note_scan_batch
        note_scan_batch(cols)
        return to_device_batch(cols, n, Schema(tuple(fields)),
                               fault_key=fault_key, seam="scan")

    # -- host materialization ---------------------------------------------
    # All three fetch the whole batch as ONE packed d2h transfer
    # (columnar/transfer.py) — per-column fetches each pay a full device
    # round trip, which dominates everything else on remote-attached TPUs.
    def to_arrow(self):
        import pyarrow as pa
        from .transfer import fetch_batch_host
        cols, n = fetch_batch_host(self)
        self._host_rows = n
        arrays = [column_to_arrow(c, n) for c in cols]
        return pa.table(arrays, names=self.schema.names)

    def to_pydict(self) -> dict:
        from .transfer import fetch_batch_host
        cols, n = fetch_batch_host(self)
        self._host_rows = n
        return {f.name: c.to_pylist(n)
                for f, c in zip(self.schema.fields, cols)}

    def to_pylist(self) -> List[tuple]:
        d = self.to_pydict()
        names = self.schema.names
        n = self.num_rows_host
        return [tuple(d[name][i] for name in names) for i in range(n)]

    # -- shape management --------------------------------------------------
    def sized_to(self, capacity: int) -> "ColumnarBatch":
        if capacity == self.capacity:
            return self
        return ColumnarBatch([c.with_capacity(capacity) for c in self.columns],
                             self.num_rows if self._host_rows is None
                             else self._host_rows,
                             self.schema, self._host_rows)

    def with_columns(self, columns: Sequence[Column],
                     schema: Schema) -> "ColumnarBatch":
        return ColumnarBatch(columns, self.num_rows if self._host_rows is None
                             else self._host_rows, schema, self._host_rows)

    def device_size_bytes(self) -> int:
        """Padded physical footprint (capacity-based, like cuDF deviceMemorySize)."""
        total = 0
        for c in jax.tree_util.tree_leaves(self):
            total += int(np.prod(c.shape)) * c.dtype.itemsize if hasattr(c, "dtype") else 0
        return total

    def __repr__(self):
        rows = self._host_rows if self._host_rows is not None else "<traced>"
        return f"ColumnarBatch(rows={rows}, cap={self.capacity}, schema={self.schema.names})"


def _batch_flatten(b: ColumnarBatch):
    return (b.columns, b.num_rows), b.schema


def _batch_unflatten(schema, children):
    cols, num_rows = children
    return ColumnarBatch(cols, num_rows, schema)


jax.tree_util.register_pytree_node(ColumnarBatch, _batch_flatten, _batch_unflatten)


def empty_batch(schema: Schema, capacity: int = 128) -> ColumnarBatch:
    from .column import build_column
    cols = [build_column([], f.data_type, capacity)
            for f in schema.fields]
    return ColumnarBatch(cols, 0, schema)
